"""Probe: segmented-f32 MXU Gram vs the emulated-f64 VPU Gram (tnt_d).

The exact b-draw's cost is dominated by the f64-accumulated TNT einsum
(VERDICT r3: b_draw 148.7 ms at C=32, ~40% of the steady sweep after
EXACT_EVERY amortization).  The inputs are f32 *entries* already — the f64
buys only exact accumulation over the Nmax~720 TOA axis.  This probe
measures, on the real device and the real 45-pulsar bench model at a
warmed-up state:

  - wall time of the current f64 Gram vs segmented f32 einsums (f32 MXU
    accumulate within segments of m TOAs, f64 sum over segments) at
    several segment counts, at C=32 and C=64;
  - accuracy: max Gram error relative to the Jacobi scale sqrt(Gbb*Gcc);
  - lambda_min of the preconditioned conditional precision A = D Sigma D
    (the margin that decides whether a straight Gibbs swap risks an
    indefinite Cholesky);
  - the b-draw conditional-mean error in posterior-sigma units;
  - the Metropolis log-ratio if the segmented draw is used as a proposal
    with the exact accept (predicted acceptance).

Usage: python tools/gram_probe.py [--nchains 32] [--warm 200]
       [--kernel pallas|xla]   # extra rows: kernel-tier Gram paths
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def tnt_d_nseg(cm, Nvec, nseg):
    """Segmented Gram with an explicit segment COUNT (the production
    jax_backend.tnt_d_seg takes a segment LENGTH instead — keep the
    names distinct so the probe sweep over nseg is unambiguous)."""
    import jax.numpy as jnp

    Ta = jnp.concatenate([jnp.asarray(cm.T, cm.dtype),
                          jnp.asarray(cm.y, cm.dtype)[:, :, None]], axis=2)
    TNa = Ta / Nvec.astype(cm.dtype)[:, :, None]
    P, N, B1 = Ta.shape
    m = N // nseg
    assert m * nseg == N, (N, nseg)
    G32 = jnp.einsum("psnb,psnc->spbc", TNa.reshape(P, nseg, m, B1),
                     Ta.reshape(P, nseg, m, B1), precision="highest")
    G = jnp.sum(G32.astype(cm.cdtype), axis=0)
    return G[:, :cm.Bmax, :cm.Bmax], G[:, :cm.Bmax, cm.Bmax]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=32)
    ap.add_argument("--warm", type=int, default=200)
    ap.add_argument("--adapt", type=int, default=300)
    ap.add_argument("--kernel", choices=("pallas", "xla"), default=None,
                    help="also time the production kernel-tier Gram "
                         "paths (tnt_d_seg32 / tnt_d_seg / tnt_d) at "
                         "this tier — extra rows in the timing table "
                         "(off-TPU 'pallas' interprets)")
    args = ap.parse_args()

    import bench
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.ops.linalg import (
        _batched_diag, blocked_chol_inv, mvn_conditional_draw)
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    print(f"# devices: {jax.devices()}", file=sys.stderr)
    pta = bench.build_pta(45)
    x0 = pta.initial_sample(np.random.default_rng(0))
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=args.adapt, chunk_size=50,
                         nchains=args.nchains)
    C = drv.C
    cm = drv.cm
    cshape, bshape = drv.chain_shapes(args.warm)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    t0 = time.time()
    for _ in drv.run(x0, chain, bchain, 0, args.warm):
        pass
    print(f"# warmup {args.warm} iters done in {time.time()-t0:.1f}s",
          file=sys.stderr)

    x = jnp.asarray(np.asarray(drv.x_cur, np.float64), cm.cdtype)  # (C, nx)
    b = jnp.asarray(drv.b)                                         # (C,P,B)
    if x.ndim == 1:
        x = jnp.tile(x, (C, 1))

    # ---- timing ---------------------------------------------------------
    def time_gram(fn, label):
        def single(x1, b1, k1):
            N = cm.ndiag_fast(x1)
            TNT, d = fn(cm, N)
            return x1, b1 + 1e-30 * d[:, :] + 1e-30 * TNT[:, :, 0]

        def body(xx, bb, k):
            return jax.vmap(single)(xx, bb, jr.split(k, C))

        t = profiling._scan_time(body, x, b, 20, 3)
        print(f"{label:28s} {t*1e3:9.3f} ms  (C={C})")

    time_gram(jb.tnt_d, "tnt_d f64 (current)")
    for nseg in (4, 8, 16):
        time_gram(lambda cm_, N, n=nseg: tnt_d_nseg(cm_, N, n),
                  f"tnt_d_seg f32 nseg={nseg}")

    if args.kernel:
        # per-kernel column: the production ops/kernels Gram paths at
        # the requested tier (dispatch + per-segment shapes included,
        # unlike the tnt_d_nseg sweep above)
        from pulsar_timing_gibbsspec_tpu.config import settings

        settings.kernel_tier = args.kernel
        k = args.kernel
        time_gram(jb.tnt_d_seg32, f"tnt_d_seg32 [kernel={k}]")
        time_gram(jb.tnt_d_seg, f"tnt_d_seg   [kernel={k}]")
        time_gram(jb.tnt_d, f"tnt_d exact [kernel={k}]")

    # full exact draw vs segmented draw
    def time_draw(fn, label):
        def body(xx, bb, k):
            return jax.vmap(lambda x1, b1, k1: (x1, fn(x1, k1)))(
                xx, bb, jr.split(k, C))

        t = profiling._scan_time(body, x, b, 20, 3)
        print(f"{label:28s} {t*1e3:9.3f} ms  (C={C})")

    def draw_exact(x1, k1):
        return jb.draw_b_fn(cm, x1, k1)

    def draw_seg(x1, k1, nseg=8):
        N = cm.ndiag_fast(x1)
        TNT, d = tnt_d_nseg(cm, N, nseg)
        phi = cm.phi(x1)
        z = jr.normal(k1, (cm.P, cm.Bmax), cm.cdtype)
        bb, _ = mvn_conditional_draw(TNT, 1.0 / phi, d, z)
        return bb

    time_draw(draw_exact, "draw_b exact f64 (current)")
    time_draw(draw_seg, "draw_b segmented nseg=8")

    # ---- accuracy at the warmed state (chain 0..3) ----------------------
    @jax.jit
    def grams(x1):
        N = cm.ndiag_fast(x1)
        TNT0, d0 = jb.tnt_d(cm, N)
        outs = {"f64": (TNT0, d0)}
        for nseg in (4, 8, 16):
            outs[f"seg{nseg}"] = tnt_d_nseg(cm, N, nseg)
        phi = cm.phi(x1)
        return outs, phi

    for ci in range(min(4, C)):
        outs, phi = grams(x[ci])
        TNT0, d0 = outs["f64"]
        TNT0 = np.asarray(TNT0, np.float64)
        d0 = np.asarray(d0, np.float64)
        phi = np.asarray(phi, np.float64)
        Sig0 = TNT0 + np.stack([np.diag(1.0 / p) for p in phi])
        dg = np.sqrt(np.einsum("pb,pc->pbc",
                               np.diagonal(Sig0, axis1=1, axis2=2),
                               np.diagonal(Sig0, axis1=1, axis2=2)))
        lam = []
        for p in range(cm.P):
            dj = 1.0 / np.sqrt(np.diag(Sig0[p]))
            A = Sig0[p] * dj[:, None] * dj[None, :]
            lam.append(np.linalg.eigvalsh(A)[0])
        lam = np.array(lam)
        line = (f"chain {ci}: lam_min(precond A) min={lam.min():.3e} "
                f"p5={np.percentile(lam, 5):.3e}")
        for nseg in (4, 8, 16):
            T1, _ = outs[f"seg{nseg}"]
            err = np.max(np.abs(np.asarray(T1, np.float64) - TNT0) / dg)
            line += f"  err_seg{nseg}={err:.2e}"
        print(line)

    # ---- draw-mean error in sigma units + MH log-ratio ------------------
    @jax.jit
    def mean_err(x1, k1):
        N = cm.ndiag_fast(x1)
        TNT0, d0 = jb.tnt_d(cm, N)
        TNT1, d1 = tnt_d_nseg(cm, N, 8)
        phi = cm.phi(x1)
        z = jr.normal(k1, (cm.P, cm.Bmax), cm.cdtype)
        b0, m0 = mvn_conditional_draw(TNT0, 1.0 / phi, d0, z)
        b1, m1 = mvn_conditional_draw(TNT1, 1.0 / phi, d1, z)
        # posterior sigma: diag of Sigma^-1 via the factor
        Sig = TNT0 + _batched_diag(1.0 / phi)
        diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        A = Sig * dj[..., :, None] * dj[..., None, :]
        _, Li = blocked_chol_inv(A)
        # Sigma^-1 = D Li^T Li D  ->  var_i = dj_i^2 sum_k Li[k, i]^2
        var = dj * dj * jnp.sum(Li * Li, axis=-2)
        sig = jnp.sqrt(var)
        return jnp.max(jnp.abs(m1 - m0) / sig), jnp.max(
            jnp.abs(b1 - b0) / sig)

    for ci in range(min(4, C)):
        me, be = mean_err(x[ci], jr.PRNGKey(ci))
        print(f"chain {ci}: mean_err={float(me):.3e} sigma, "
              f"draw_err={float(be):.3e} sigma")

    # MH log-ratio of the segmented draw as proposal vs exact target
    @jax.jit
    def mh_logr(x1, b1, k1):
        N = cm.ndiag_fast(x1)
        TNT1, d1 = tnt_d_nseg(cm, N, 8)
        phi = cm.phi(x1)
        Sig = TNT1 + _batched_diag(1.0 / phi)
        diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        A = Sig * dj[..., :, None] * dj[..., None, :]
        L, Li = blocked_chol_inv(A)
        u = jnp.einsum("...ij,...j->...i", Li, dj * d1)
        mean = dj * jnp.einsum("...ji,...j->...i", Li, u)
        z = jr.normal(k1, (cm.P, cm.Bmax), cm.cdtype)
        bp = mean + dj * jnp.einsum("...ji,...j->...i", Li, z)
        up = jb.b_matvec(cm, bp)
        u_old = jb.b_matvec(cm, b1)
        lpi_new = jb._logpi_b_per(cm, x1, bp, up)
        lpi_old = jb._logpi_b_per(cm, x1, b1, u_old)
        w_old = jnp.einsum("pji,pj->pi", L, (b1 - mean) / dj)
        logq_old = -0.5 * jnp.sum(w_old * w_old, axis=1)
        logq_new = -0.5 * jnp.sum(z * z, axis=1)
        return (lpi_new - lpi_old) + (logq_old - logq_new)

    accs = []
    for ci in range(min(8, C)):
        lr = np.asarray(mh_logr(x[ci], b[ci], jr.PRNGKey(100 + ci)),
                        np.float64)
        accs.append(np.minimum(1.0, np.exp(lr)))
    accs = np.concatenate(accs)
    print(f"MH-accept of segmented proposal: mean={accs.mean():.6f} "
          f"min={accs.min():.6f} p1={np.percentile(accs, 1):.6f}")


if __name__ == "__main__":
    main()
