"""Probe 3: accuracy + speed of tf_chol_factor (two-float MXU factor) on
the warmed 45-pulsar bench state.

Reports, per chain: max ||Li A Li^T - I||_max over pulsars (the proposal
covariance error that prices MH acceptance), plus acceptance stats of a
b-draw proposal factored by tf_chol_factor, and timing vs the f64
blocked_chol_inv.

Usage: python tools/tf_probe.py [--nchains 32] [--warm 200]
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=32)
    ap.add_argument("--warm", type=int, default=200)
    ap.add_argument("--adapt", type=int, default=300)
    args = ap.parse_args()

    import bench
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.ops.linalg import (
        _batched_diag, blocked_chol_inv, tf_chol_factor)
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta = bench.build_pta(45)
    x0 = pta.initial_sample(np.random.default_rng(0))
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=args.adapt, chunk_size=50,
                         nchains=args.nchains)
    C = drv.C
    cm = drv.cm
    cshape, bshape = drv.chain_shapes(args.warm)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    t0 = time.time()
    for _ in drv.run(x0, chain, bchain, 0, args.warm):
        pass
    print(f"# warmup {args.warm} iters in {time.time()-t0:.1f}s",
          file=sys.stderr)
    x = jnp.asarray(np.asarray(drv.x_cur, np.float64), cm.cdtype)
    b = jnp.asarray(drv.b)

    @jax.jit
    def build_A(x1):
        N = cm.ndiag_fast(x1)
        TNT, d = jb.tnt_d_seg(cm, N)
        phi = cm.phi(x1)
        Sig = TNT + _batched_diag(1.0 / phi)
        diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        A = Sig * dj[:, :, None] * dj[:, None, :]
        return A, dj, d

    @jax.jit
    def tf_err(x1):
        A, dj, d = build_A(x1)
        L, Li = tf_chol_factor(A)
        R = jnp.einsum("...ij,...jk,...lk->...il", Li, A, Li)
        E = R - jnp.eye(cm.Bmax, dtype=A.dtype)
        # also check L Li ~ I (logq consistency)
        S = jnp.einsum("...ij,...jk->...ik", L, Li) - jnp.eye(
            cm.Bmax, dtype=A.dtype)
        return (jnp.max(jnp.abs(E), axis=(-2, -1)),
                jnp.max(jnp.abs(S), axis=(-2, -1)))

    for ci in range(min(4, C)):
        e, s = tf_err(x[ci])
        e = np.asarray(e)
        print(f"chain {ci}: ||Li A Li^T - I||_max: max={e.max():.3e} "
              f"median={np.median(e):.3e}   ||L Li - I||_max: "
              f"{float(np.asarray(s).max()):.3e}")

    # ---- MH acceptance with tf-factored proposal ------------------------
    @jax.jit
    def mh_logr(x1, b1, k1):
        A, dj, d = build_A(x1)
        L, Li = tf_chol_factor(A)
        u = jnp.einsum("...ij,...j->...i", Li, dj * d)
        mean = dj * jnp.einsum("...ji,...j->...i", Li, u)
        z = jr.normal(k1, (cm.P, cm.Bmax), cm.cdtype)
        bp = mean + dj * jnp.einsum("...ji,...j->...i", Li, z)
        up = jb.b_matvec(cm, bp)
        u_old = jb.b_matvec(cm, b1)
        lpi_new = jb._logpi_b_per(cm, x1, bp, up)
        lpi_old = jb._logpi_b_per(cm, x1, b1, u_old)
        w_old = jnp.einsum("pji,pj->pi", L, (b1 - mean) / dj)
        logq_old = -0.5 * jnp.sum(w_old * w_old, axis=1)
        logq_new = -0.5 * jnp.sum(z * z, axis=1)
        return (lpi_new - lpi_old) + (logq_old - logq_new)

    accs = []
    for ci in range(C):
        lr = np.asarray(mh_logr(x[ci], b[ci], jr.PRNGKey(500 + ci)),
                        np.float64)
        accs.append(np.minimum(1.0, np.exp(lr)))
    accs = np.concatenate(accs)
    print(f"tf-proposal MH accept: mean={accs.mean():.6f} "
          f"min={accs.min():.6f} p1={np.percentile(accs, 1):.6f}")

    # ---- timing ---------------------------------------------------------
    def t_body(single, label):
        def body(xx, bb, k):
            return jax.vmap(single)(xx, bb, jr.split(k, C))

        t = profiling._scan_time(body, x, b, 20, 3)
        print(f"{label:36s} {t*1e3:9.3f} ms  (C={C})")

    def ps(b1, *arrs):
        s = sum(jnp.sum(a).astype(b1.dtype) for a in arrs)
        return b1 + 1e-30 * s

    def factor_tf(x1, b1, k1):
        A, dj, d = build_A(x1)
        L, Li = tf_chol_factor(A)
        return x1, ps(b1, Li, L)

    def factor_f64(x1, b1, k1):
        A, dj, d = build_A(x1)
        L, Li = blocked_chol_inv(A)
        return x1, ps(b1, Li, L)

    t_body(factor_tf, "gram_seg + tf_chol_factor")
    t_body(factor_f64, "gram_seg + blocked_chol_inv f64")

    def full_tf_draw(x1, b1, k1):
        k1, ku = jr.split(k1)
        u1 = jb.b_matvec(cm, b1)
        A, dj, d = build_A(x1)
        L, Li = tf_chol_factor(A)
        w = jnp.einsum("...ij,...j->...i", Li, dj * d)
        mean = dj * jnp.einsum("...ji,...j->...i", Li, w)
        z = jr.normal(k1, (cm.P, cm.Bmax), cm.cdtype)
        bp = mean + dj * jnp.einsum("...ji,...j->...i", Li, z)
        up = jb.b_matvec(cm, bp)
        lpi_new = jb._logpi_b_per(cm, x1, bp, up)
        lpi_old = jb._logpi_b_per(cm, x1, b1, u1)
        w_old = jnp.einsum("pji,pj->pi", L, (b1 - mean) / dj)
        logq_old = -0.5 * jnp.sum(w_old * w_old, axis=1)
        logq_new = -0.5 * jnp.sum(z * z, axis=1)
        logr = (lpi_new - lpi_old) + (logq_old - logq_new)
        ok = jnp.all(jnp.isfinite(bp), axis=1) & jnp.isfinite(logr)
        logu = jnp.log(jr.uniform(ku, (cm.P,), cm.cdtype))
        acc = ok & (logr > logu)
        return x1, jnp.where(acc[:, None], bp, b1)

    t_body(full_tf_draw, "full tf-factored MH draw")

    def cur_mh(x1, b1, k1):
        u1 = jb.b_matvec(cm, b1)
        bn, un, acc = jb.draw_b_mh(cm, x1, b1, u1, k1)
        return x1, bn

    t_body(cur_mh, "current draw_b_mh (f32)")


if __name__ == "__main__":
    main()
