"""Generate the committed enterprise-Pulsar attribute-surface snapshot.

The reference's canonical demo loads a *real* NANOGrav 9-yr pulsar through
``enterprise.Pulsar`` (tempo2 timing solution; ``clean_demo.ipynb`` cells
3-5) and the sampler consumes only the resulting attribute surface: full
design matrix ``Mmat``, post-fit ``residuals``, per-TOA flag arrays,
``pos``.  enterprise (and real NANOGrav data) are not present in this
environment, so this script *records* that attribute surface at full
structural fidelity from the shipped simulated corpus:

- dual-frequency observing (1440/820 MHz receiver pair) so dispersion
  columns are identifiable, as in any real NANOGrav dataset;
- ``Mmat`` widened from the leading-order partials to a NANOGrav-style
  tempo2 solution: DM + DMX piecewise-constant dispersion windows (one
  ``1/nu^2`` indicator column per ~60-day epoch window), per-backend
  JUMP offset columns, alongside spin/astrometry/parallax partials —
  the column structure enterprise hands the sampler for a 9-yr pulsar;
- ``residuals`` are *post-fit*: the injected realization minus its
  projection onto Mmat's column space (what tempo2's fit leaves);
- per-TOA flag arrays (``pta``, ``f``, ``fe``, ``be``) in the enterprise
  convention, exercising the adapter's array-flag handling.

The snapshot keeps ``from_enterprise`` testable hermetically:
``tests/test_enterprise_snapshot.py`` drives it through the adapter, the
model factory and both sampler backends with no enterprise install.

Usage: python tools/make_enterprise_snapshot.py [--psr J1713+0747]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DAY = 86400.0
REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--psr", default="J1713+0747")
    ap.add_argument("--out", default="tests/data/enterprise_J1713+0747.npz")
    ap.add_argument("--dmx-days", type=float, default=60.0)
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu.data import load_pulsar
    from pulsar_timing_gibbsspec_tpu.data.design import design_matrix
    from pulsar_timing_gibbsspec_tpu.data.fourier import fourier_basis
    from pulsar_timing_gibbsspec_tpu.data.partim import parse_par, parse_tim
    from pulsar_timing_gibbsspec_tpu.data.simulate import inject_residuals

    par = parse_par(f"{REFDATA}/{args.psr}.par")
    tim = parse_tim(f"{REFDATA}/{args.psr}.tim")
    n = len(tim.mjds)
    mjd = tim.mjds

    # dual-frequency observing (DMX needs the frequency lever arm, as any
    # real NANOGrav dataset provides) with the backend split DECOUPLED
    # from frequency — each backend observes both bands, so the JUMP
    # column is not a linear combination of offset + DMX (it would be if
    # frequency were a function of backend: within each window,
    # a + b_j/nu^2 can reproduce any backend indicator that is)
    freq_ix = np.arange(n) % 2
    freqs = np.where(freq_ix == 0, 1440.0, 820.0)
    tim.freqs[:] = freqs
    sys_ix = (np.floor(mjd / 30.0).astype(int) % 2)
    fe = np.where(freq_ix == 0, "L-wide", "Rcvr_800").astype(object)
    be = np.where(sys_ix == 0, "PUPPI", "GUPPI").astype(object)
    f_flag = np.array([f"{a}_{b}" for a, b in zip(fe, be)], dtype=object)

    # Write the NANOGrav-style par/tim TEXT and ingest it through the
    # standard parser: parse_par/design_matrix understand DMX_/DMXR/JUMP
    # lines (r5), so the snapshot's Mmat is the parser's own output on a
    # real-format file — by construction the same column structure any
    # real NANOGrav par now ingests at (previously these columns were
    # hand-built here, r4 VERDICT missing #1).
    import tempfile
    from pathlib import Path

    extra = []
    edges = np.arange(mjd.min(), mjd.max() + args.dmx_days, args.dmx_days)
    nwin = 0
    for j in range(len(edges) - 1):
        in_win = (mjd >= edges[j]) & (mjd < edges[j + 1])
        if in_win.sum() == 0:
            continue
        nwin += 1
        extra.append(f"DMX_{nwin:04d}   0.0 1 1e-6")
        extra.append(f"DMXR1_{nwin:04d} {edges[j]:.6f}")
        # half-open [R1, R2): keep the next window's left edge out
        extra.append(f"DMXR2_{nwin:04d} {edges[j + 1] - 1e-6:.6f}")
    extra.append("JUMP -be GUPPI 0.0 1 1e-8")   # trailing uncertainty,
    # as tempo2 writes it — the parser must read the positional fit flag
    with tempfile.TemporaryDirectory() as tmps:
        tmpd = Path(tmps)
        par2_path = tmpd / f"{args.psr}.par"
        par2_path.write_text(
            Path(f"{REFDATA}/{args.psr}.par").read_text().rstrip() + "\n"
            + "\n".join(extra) + "\n")
        tim_lines = ["FORMAT 1"]
        for i in range(n):
            tim_lines.append(
                f"{args.psr} {freqs[i]:.3f} {mjd[i]:.12f} "
                f"{tim.errs[i] * 1e6:.6f} ao -fe {fe[i]} -be {be[i]} "
                f"-f {f_flag[i]} -pta NANOGrav")
        tim2_path = tmpd / f"{args.psr}.tim"
        tim2_path.write_text("\n".join(tim_lines) + "\n")
        par2 = parse_par(par2_path)
        tim2 = parse_tim(tim2_path)
        Mmat, fitpars = design_matrix(par2, tim2, return_labels=True)

    # injected realization -> post-fit residuals against the FULL Mmat
    Tspan = float(np.ptp(mjd) * DAY)
    F, f = fourier_basis(mjd, 30, Tspan)
    resid_post, _ = inject_residuals(
        par.name, F, f, Tspan, tim.errs, Mmat,
        log10_A=np.log10(2e-15), gamma=13.0 / 3.0)

    # column-normalized rank check: the raw partials span ~18 decades, so
    # an unnormalized matrix_rank reads deceptively low
    Mn = Mmat / np.linalg.norm(Mmat, axis=0)
    rank = np.linalg.matrix_rank(Mn)
    if rank < Mmat.shape[1]:
        raise SystemExit(
            f"Mmat rank {rank} < {Mmat.shape[1]} columns — snapshot would "
            "carry a degenerate timing solution")

    host = load_pulsar(f"{REFDATA}/{args.psr}.par",
                       f"{REFDATA}/{args.psr}.tim")
    out = dict(
        name=np.str_(par.name),
        toas=mjd * DAY,
        toaerrs=tim.errs,
        residuals=resid_post,
        freqs=freqs,
        backend_flags=f_flag.astype(str),
        Mmat=Mmat,
        fitpars=np.asarray(fitpars, dtype=str),
        pos=host.pos,
        flag_pta=np.full(n, "NANOGrav", dtype="U16"),
        flag_f=f_flag.astype(str),
        flag_fe=fe.astype(str),
        flag_be=be.astype(str),
    )
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    np.savez_compressed(args.out, **out)
    sz = os.path.getsize(args.out) / 1e3
    print(f"wrote {args.out}: ntoa={n}, Mmat {Mmat.shape} (rank {rank}), "
          f"{len(fitpars)} fitpars, {sz:.0f} kB, "
          f"post-fit rms {resid_post.std()*1e6:.3f} us")


if __name__ == "__main__":
    main()
