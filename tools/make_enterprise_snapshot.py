"""Generate the committed enterprise-Pulsar attribute-surface snapshot.

The reference's canonical demo loads a *real* NANOGrav 9-yr pulsar through
``enterprise.Pulsar`` (tempo2 timing solution; ``clean_demo.ipynb`` cells
3-5) and the sampler consumes only the resulting attribute surface: full
design matrix ``Mmat``, post-fit ``residuals``, per-TOA flag arrays,
``pos``.  enterprise (and real NANOGrav data) are not present in this
environment, so this script *records* that attribute surface at full
structural fidelity from the shipped simulated corpus:

- dual-frequency observing (1440/820 MHz receiver pair) so dispersion
  columns are identifiable, as in any real NANOGrav dataset;
- ``Mmat`` widened from the leading-order partials to a NANOGrav-style
  tempo2 solution: DM + DMX piecewise-constant dispersion windows (one
  ``1/nu^2`` indicator column per ~60-day epoch window), per-backend
  JUMP offset columns, alongside spin/astrometry/parallax partials —
  the column structure enterprise hands the sampler for a 9-yr pulsar;
- ``residuals`` are *post-fit*: the injected realization minus its
  projection onto Mmat's column space (what tempo2's fit leaves);
- per-TOA flag arrays (``pta``, ``f``, ``fe``, ``be``) in the enterprise
  convention, exercising the adapter's array-flag handling.

The snapshot keeps ``from_enterprise`` testable hermetically:
``tests/test_enterprise_snapshot.py`` drives it through the adapter, the
model factory and both sampler backends with no enterprise install.

Usage: python tools/make_enterprise_snapshot.py [--psr J1713+0747]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

DAY = 86400.0
REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--psr", default="J1713+0747")
    ap.add_argument("--out", default="tests/data/enterprise_J1713+0747.npz")
    ap.add_argument("--dmx-days", type=float, default=60.0)
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu.data import load_pulsar
    from pulsar_timing_gibbsspec_tpu.data.design import design_matrix
    from pulsar_timing_gibbsspec_tpu.data.fourier import fourier_basis
    from pulsar_timing_gibbsspec_tpu.data.partim import parse_par, parse_tim
    from pulsar_timing_gibbsspec_tpu.data.simulate import inject_residuals

    par = parse_par(f"{REFDATA}/{args.psr}.par")
    tim = parse_tim(f"{REFDATA}/{args.psr}.tim")
    n = len(tim.mjds)
    mjd = tim.mjds

    # dual-frequency observing (DMX needs the frequency lever arm, as any
    # real NANOGrav dataset provides) with the backend split DECOUPLED
    # from frequency — each backend observes both bands, so the JUMP
    # column is not a linear combination of offset + DMX (it would be if
    # frequency were a function of backend: within each window,
    # a + b_j/nu^2 can reproduce any backend indicator that is)
    freq_ix = np.arange(n) % 2
    freqs = np.where(freq_ix == 0, 1440.0, 820.0)
    tim.freqs[:] = freqs
    sys_ix = (np.floor(mjd / 30.0).astype(int) % 2)
    fe = np.where(freq_ix == 0, "L-wide", "Rcvr_800").astype(object)
    be = np.where(sys_ix == 0, "PUPPI", "GUPPI").astype(object)
    f_flag = np.array([f"{a}_{b}" for a, b in zip(fe, be)], dtype=object)

    # base leading-order partials at the new frequencies
    M0 = design_matrix(par, tim)
    base_labels = ["Offset"] + [f"TM_{k}" for k in range(1, M0.shape[1])]

    # DMX windows: piecewise-constant 1/nu^2 columns
    cols = [M0]
    fitpars = list(base_labels)
    nu2 = (1400.0 / freqs) ** 2
    edges = np.arange(mjd.min(), mjd.max() + args.dmx_days, args.dmx_days)
    for j in range(len(edges) - 1):
        in_win = (mjd >= edges[j]) & (mjd < edges[j + 1])
        if in_win.sum() == 0:
            continue
        cols.append((in_win * nu2)[:, None])
        fitpars.append(f"DMX_{j + 1:04d}")
    # JUMP between the two systems
    cols.append((sys_ix == 1).astype(float)[:, None])
    fitpars.append("JUMP1")
    Mmat = np.hstack(cols)

    # injected realization -> post-fit residuals against the FULL Mmat
    Tspan = float(np.ptp(mjd) * DAY)
    F, f = fourier_basis(mjd, 30, Tspan)
    resid_post, _ = inject_residuals(
        par.name, F, f, Tspan, tim.errs, Mmat,
        log10_A=np.log10(2e-15), gamma=13.0 / 3.0)

    # column-normalized rank check: the raw partials span ~18 decades, so
    # an unnormalized matrix_rank reads deceptively low
    Mn = Mmat / np.linalg.norm(Mmat, axis=0)
    rank = np.linalg.matrix_rank(Mn)
    if rank < Mmat.shape[1]:
        raise SystemExit(
            f"Mmat rank {rank} < {Mmat.shape[1]} columns — snapshot would "
            "carry a degenerate timing solution")

    host = load_pulsar(f"{REFDATA}/{args.psr}.par",
                       f"{REFDATA}/{args.psr}.tim")
    out = dict(
        name=np.str_(par.name),
        toas=mjd * DAY,
        toaerrs=tim.errs,
        residuals=resid_post,
        freqs=freqs,
        backend_flags=f_flag.astype(str),
        Mmat=Mmat,
        fitpars=np.asarray(fitpars, dtype=str),
        pos=host.pos,
        flag_pta=np.full(n, "NANOGrav", dtype="U16"),
        flag_f=f_flag.astype(str),
        flag_fe=fe.astype(str),
        flag_be=be.astype(str),
    )
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    np.savez_compressed(args.out, **out)
    sz = os.path.getsize(args.out) / 1e3
    print(f"wrote {args.out}: ntoa={n}, Mmat {Mmat.shape} (rank {rank}), "
          f"{len(fitpars)} fitpars, {sz:.0f} kB, "
          f"post-fit rms {resid_post.std()*1e6:.3f} us")


if __name__ == "__main__":
    main()
