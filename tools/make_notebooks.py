"""Author + execute the demo notebooks (the reference ships ``.ipynb``).

The reference's user layer is three notebooks — ``clean_demo.ipynb``,
``singlepulsar_sim_A2e-15_gamma4.333.ipynb``, ``pta_gibbs_freespec.ipynb``
— whose flows the ``examples/*.py`` scripts already reproduce.  This tool
emits the same demos in notebook form with executed outputs committed, so
a reference user lands on the artifact shape they expect.  Cells are
authored here (single source of truth), executed on CPU via nbclient, and
written to ``notebooks/``.

Usage: ``python tools/make_notebooks.py [--no-exec] [--only NAME]``
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PREAMBLE = '''\
import os, sys
# CPU-pinned for hermetic execution; delete this line on a TPU host and
# the same cells run on the accelerator unchanged.
if __name__ == "__main__":   # script bootstrap; no import side effects
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, "..")
import numpy as np
REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")
'''

CLEAN_DEMO = [
    ("md", """\
# Clean demo — single-pulsar free-spectrum Gibbs run

Notebook form of the reference's `clean_demo.ipynb` (cells 3-9): load a
pulsar, build the `model_general` free-spectrum model with varying
per-backend white noise, run the blocked Gibbs sampler, and summarize the
posterior.  The reference notebook loads a NANOGrav 9-yr pulsar it does
not ship; the 45-pulsar simulated corpus stands in (point `PTGIBBS_REFDATA`
elsewhere, or pass an enterprise attribute snapshot through
`load_enterprise_snapshot` — see `examples/clean_demo.py --npz`)."""),
    ("code", PREAMBLE),
    ("md", """\
**Load the pulsar** (reference cell 3: `Pulsar(par, tim)`), injecting a
GWB power law so the spectrum has known structure to recover."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu.data import load_pulsar

psr = load_pulsar(f"{REFDATA}/J1713+0747.par", f"{REFDATA}/J1713+0747.tim",
                  inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0,
                              nmodes=30))
print(psr.name, f"{len(psr.toas)} TOAs,",
      f"{psr.Mmat.shape[1]} timing-model columns")'''),
    ("md", """\
**Build the model** (reference cell 5): SVD-stabilized timing model,
varying per-backend EFAC/EQUAD white noise, 10-bin common free spectrum
— the exact `model_general` kwarg surface of the reference's
`model_definition.py:18-32`."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu import model_general

pta = model_general([psr], tm_svd=True, red_var=False, white_vary=True,
                    common_psd="spectrum", common_components=10)
for name in pta.param_names:
    print(name)'''),
    ("md", """\
**Run the blocked Gibbs sampler** (reference cells 7-9).  `backend="jax"`
is the compiled device path — identical code runs on TPU; the `numpy`
backend is the f64 oracle it is KS-tested against."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu import PulsarBlockGibbs

NITER = 1500
gibbs = PulsarBlockGibbs(pta, backend="jax", seed=0)
x0 = gibbs.initial_sample(np.random.default_rng(0))
chain = gibbs.sample(x0, outdir="./chains_clean_demo", niter=NITER)
chain.shape'''),
    ("md", "**Posterior summary** (reference cell 9's corner-plot data)."),
    ("code", '''\
burn = NITER // 5
print(f"{'parameter':<42s} {'median':>9s} {'16%':>9s} {'84%':>9s}")
for k, name in enumerate(gibbs.param_names):
    q16, q50, q84 = np.quantile(chain[burn:, k], [0.16, 0.5, 0.84])
    print(f"{name:<42s} {q50:9.3f} {q16:9.3f} {q84:9.3f}")'''),
    ("code", '''\
import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt
%matplotlib inline

from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex

idx = BlockIndex.build(pta.param_names)
fig, ax = plt.subplots(figsize=(8, 4))
ax.violinplot([chain[burn:, c] for c in idx.rho],
              positions=np.arange(len(idx.rho)), widths=0.8,
              showextrema=False)
ax.set_xlabel("frequency bin")
ax.set_ylabel(r"$\\log_{10}\\rho$")
ax.set_title("common free spectrum, 10 bins (injected A=2e-15, $\\\\gamma$=13/3)")
fig.tight_layout()'''),
]

SINGLEPULSAR_SIM = [
    ("md", """\
# Single-pulsar injection recovery — A=2e-15, $\\gamma$=13/3

Notebook form of the reference's
`singlepulsar_sim_A2e-15_gamma4.333.ipynb` (cells 7-16): inject a GWB
power law into a simulated pulsar, recover the 30-bin free spectrum with
the Gibbs sampler, and render the reference's headline violin plot
against the injected line (its cell 16)."""),
    ("code", PREAMBLE),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu.data import load_pulsar

LOG10_A, GAMMA, NMODES = np.log10(2e-15), 13.0 / 3.0, 30
psr = load_pulsar(f"{REFDATA}/J1713+0747.par", f"{REFDATA}/J1713+0747.tim",
                  inject=dict(log10_A=LOG10_A, gamma=GAMMA,
                              nmodes=NMODES, seed=42))
print(psr.name, len(psr.toas), "TOAs")'''),
    ("md", """\
**Model** (reference cell 7): constant EFAC=1 white noise, 30-bin common
spectrum, SVD timing model."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu import PulsarBlockGibbs, model_general

pta = model_general([psr], tm_svd=True, red_var=False, white_vary=False,
                    common_psd="spectrum", common_components=NMODES)
NITER = 2000
gibbs = PulsarBlockGibbs(pta, backend="jax", seed=1)
x0 = gibbs.initial_sample(np.random.default_rng(1))
chain = gibbs.sample(x0, outdir="./chains_injection", niter=NITER)
chain.shape'''),
    ("md", """\
**Injected line**: per-bin $\\log_{10}\\rho$ from the injected power law
(the notebook's overlay, cell 16)."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu.models.psd import powerlaw
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex

sig = next(s for s in pta.model(0).signals if "gw" in s.name)
f, df = sig.freqs[::2], sig._df[::2]
inj = 0.5 * np.log10(powerlaw(f, df, log10_A=LOG10_A, gamma=GAMMA))

idx = BlockIndex.build(pta.param_names)
burn = NITER // 5
qs = np.quantile(chain[burn:, idx.rho], [0.05, 0.95], axis=0)
within = np.mean((inj >= qs[0]) & (inj <= qs[1]))
print(f"injected power law inside the 90% band in {100*within:.0f}% of bins")'''),
    ("code", '''\
import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt
%matplotlib inline

fig, ax = plt.subplots(figsize=(10, 4.5))
ax.violinplot([chain[burn:, c] for c in idx.rho],
              positions=np.arange(len(idx.rho)), widths=0.8,
              showextrema=False)
ax.plot(np.arange(len(idx.rho)), inj, "k--", lw=1.5,
        label="injected A=2e-15, $\\\\gamma$=13/3")
ax.set_xlabel("frequency bin")
ax.set_ylabel(r"$\\log_{10}\\rho$")
ax.legend()
ax.set_title("30-bin free-spectrum recovery (violin = posterior per bin)")
fig.tight_layout()'''),
]

PTA_FREESPEC = [
    ("md", """\
# PTA free-spectrum validation — Gibbs vs MH autocorrelation

Notebook form of the reference's `pta_gibbs_freespec.ipynb`: a
multi-pulsar common-spectrum Gibbs run (its cells 10-30), then the
validation that is the method's selling point (cells 31-39) — the same
posterior sampled by (a) the blocked Gibbs sampler and (b) adaptive
random-walk MH on the b-marginalized likelihood (the role PTMCMCSampler
plays in the reference), compared on per-channel integrated
autocorrelation time.  The exact conditional $\\rho$ draw decorrelates in
O(1) sweeps; the random walk takes O(100) steps."""),
    ("code", PREAMBLE),
    ("md", """\
**Multi-pulsar CRN run** (reference cells 10-30): 8 pulsars, common
free spectrum, uncorrelated across pulsars (the reference sampler's
case; for sampled Hellings-Downs correlations — beyond the reference —
see `examples/hd_pta_demo.py`)."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu import model_general
from pulsar_timing_gibbsspec_tpu.data import load_directory
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex

psrs = load_directory(REFDATA,
                      inject=dict(log10_A=np.log10(2e-15),
                                  gamma=13.0 / 3.0))[:8]
pta = model_general(psrs, tm_svd=True, red_var=False, white_vary=False,
                    common_psd="spectrum", common_components=10)
NITER = 1000
pg = PTABlockGibbs(pta, backend="jax", seed=0)
x0 = pg.initial_sample(np.random.default_rng(0))
pchain = pg.sample(x0, outdir="./chains_pta_freespec", niter=NITER)
idx = BlockIndex.build(pta.param_names)
burn = NITER // 5
print(f"{'bin':>4s} {'median':>9s} {'16%':>9s} {'84%':>9s}")
for j, k in enumerate(idx.rho):
    q16, q50, q84 = np.quantile(pchain[burn:, k], [0.16, 0.5, 0.84])
    print(f"{j:4d} {q50:9.2f} {q16:9.2f} {q84:9.2f}")'''),
    ("md", """\
**The validation** (reference cells 31-39), on a single pulsar so the MH
chain is cheap: Gibbs and adaptive MH on the identical 10-bin
free-spectrum posterior."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu import PulsarBlockGibbs
from pulsar_timing_gibbsspec_tpu.data import load_pulsar
from pulsar_timing_gibbsspec_tpu.sampler.numpy_backend import NumpyGibbs

psr = load_pulsar(f"{REFDATA}/J1713+0747.par", f"{REFDATA}/J1713+0747.tim",
                  inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0,
                              nmodes=10))
pta1 = model_general([psr], tm_svd=True, red_var=False, white_vary=False,
                     common_psd="spectrum", common_components=10)
idx1 = BlockIndex.build(pta1.param_names)
x1 = pta1.initial_sample(np.random.default_rng(0))

G_ITERS = 1500
gibbs = PulsarBlockGibbs(pta1, backend="numpy", seed=3, progress=False)
gchain = gibbs.sample(x1, outdir="./chains_act_nb", niter=G_ITERS)
print("Gibbs done:", gchain.shape)'''),
    ("code", '''\
# the adaptive random-walk MH (2.38/sqrt(d) AM scaling — the reference's
# PTMCMC stand-in) lives in the example script; one source of truth
from examples.gibbs_vs_mh_act import adaptive_mh

M_ITERS = 12000
# lnlike_fullmarg seeds the oracle's Gram cache itself on first call
# (white noise is fixed here, so the cache stays valid throughout)
oracle = NumpyGibbs(pta1, seed=4)

def lnpost(x):
    lp = pta1.get_lnprior(x)
    if not np.isfinite(lp):
        return -np.inf
    # white noise is fixed (white_vary=False) so the cached Gram stays
    # valid across evaluations; only rho moves, and it enters through phi
    return oracle.lnlike_fullmarg(x) + lp

mchain, rate = adaptive_mh(lnpost, x1, M_ITERS, np.random.default_rng(5))
print(f"MH acceptance rate: {rate:.2f}")'''),
    ("md", """\
**Per-channel integrated autocorrelation times** (the reference's cell-39
plot as a table + ACF figure)."""),
    ("code", '''\
from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

gb, mb = gchain[G_ITERS // 5:], mchain[M_ITERS // 5:]
print(f"{'rho bin':>8s} {'Gibbs ACT':>10s} {'MH ACT':>10s} {'ratio':>7s}")
ratios = []
for j, k in enumerate(idx1.rho):
    ga, ma = integrated_act(gb[:, k]), integrated_act(mb[:, k])
    ratios.append(ma / ga)
    print(f"{j:8d} {ga:10.1f} {ma:10.1f} {ma/ga:7.1f}")
print(f"\\nmedian ACT ratio (MH/Gibbs): {np.median(ratios):.1f}x")'''),
    ("code", '''\
import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt
%matplotlib inline

def acf(x, nlag):
    x = x - x.mean()
    c = np.correlate(x, x, "full")[len(x) - 1:][:nlag]
    return c / c[0]

k = idx1.rho[3]
fig, ax = plt.subplots(figsize=(8, 4))
ax.plot(acf(gb[:, k], 120), label="blocked Gibbs (exact conditional)")
ax.plot(acf(mb[:, k], 120), label="adaptive random-walk MH")
ax.axhline(0, color="k", lw=0.5)
ax.set_xlabel("lag (iterations)")
ax.set_ylabel("autocorrelation")
ax.set_title(r"$\\rho_3$ chain autocorrelation — why blocked Gibbs")
ax.legend()
fig.tight_layout()'''),
]

NOTEBOOKS = {
    "clean_demo": CLEAN_DEMO,
    "singlepulsar_sim_A2e-15_gamma4.333": SINGLEPULSAR_SIM,
    "pta_gibbs_freespec": PTA_FREESPEC,
}


def build(cells):
    import nbformat

    nb = nbformat.v4.new_notebook()
    nb.metadata = {
        "kernelspec": {"display_name": "Python 3", "language": "python",
                       "name": "python3"},
        "language_info": {"name": "python"},
    }
    for i, (kind, src) in enumerate(cells):
        cell = (nbformat.v4.new_markdown_cell(src) if kind == "md"
                else nbformat.v4.new_code_cell(src))
        # nbformat's random cell ids would churn the diff on every
        # regeneration; deterministic ids keep the artifact stable
        cell["id"] = f"cell-{i}"
        nb.cells.append(cell)
    return nb


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--no-exec", action="store_true",
                    help="write unexecuted notebooks (fast; no outputs)")
    ap.add_argument("--only", default=None, choices=list(NOTEBOOKS),
                    help="one notebook name")
    args = ap.parse_args()

    import nbformat

    outdir = os.path.join(REPO, "notebooks")
    os.makedirs(outdir, exist_ok=True)
    names = [args.only] if args.only else list(NOTEBOOKS)
    for name in names:
        nb = build(NOTEBOOKS[name])
        path = os.path.join(outdir, f"{name}.ipynb")
        if not args.no_exec:
            from nbclient import NotebookClient

            print(f"executing {name} ...", flush=True)
            client = NotebookClient(
                nb, timeout=3600, kernel_name="python3",
                resources={"metadata": {"path": outdir}})
            client.execute()
        nbformat.write(nb, path)
        print(f"wrote {path}")


if __name__ == "__main__":
    sys.exit(main())
