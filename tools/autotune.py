"""Per-backend autotune sweep -> pinned defaults table (AUTOTUNE.json).

Two independent sweeps, both reusing existing machinery:

- **dispatch knobs** (chunk, megachunk, nchains): one
  ``profiling.dispatch_breakdown`` staging per grid point — the
  ``chunk_probe --amortize`` measurement — scored by the host-side
  dispatch tax amortized per sweep (the PR 12 metric the mega-chunk
  loop drives under 1 ms/sweep).
- **gram_seg_len**: the steady ``tnt_d_seg32`` Gram block timed per
  candidate segment length (the kernel_probe measurement), scored by
  block wall time.  Short segments exist for TPU HBM scratch reasons
  (contracts/crn_bench_c128.json); on CPU the sweep lands on one
  segment.

The winner per backend is written to ``AUTOTUNE.json`` at the repo
root::

    {"version": 1, "backends": {"cpu": {"best": {"chunk": ...,
     "megachunk": ..., "nchains": ..., "gram_seg_len": ...},
     "entries": [...]}}}

``config.autotune_defaults()`` reads the table and the driver consults
it — **opt-in** via ``PTGIBBS_AUTOTUNE=1`` — for ``chunk_size`` and
``megachunk`` defaults; ``gram_seg_len``/``nchains`` are advisory (the
segment length is part of the bitwise-resume class, so it never
changes silently under a tuned table).

Usage: python tools/autotune.py [--chunks 16,64] [--out AUTOTUNE.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")

_REPO_ROOT = Path(__file__).resolve().parents[1]


def _build_pta(npsr, ntoa):
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    return build_model(synthetic_pulsars(npsr, ntoa, 3, seed=0), 10)


def sweep_dispatch(pta, chunks, megas, nchains_list, adapt):
    """One dispatch_breakdown staging per (nchains, megachunk, chunk)
    grid point; rows of amortized host tax per sweep."""
    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import (
        JaxGibbsDriver)

    x0 = pta.initial_sample(np.random.default_rng(0))
    rows = []
    for C in nchains_list:
        for mega in megas:
            drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                                 white_adapt_iters=adapt,
                                 chunk_size=min(chunks), nchains=C,
                                 megachunk=mega)
            niter = adapt + 2 * min(chunks)
            cshape, bshape = drv.chain_shapes(niter)
            it = drv.run(x0, np.zeros(cshape), np.zeros(bshape), 0, niter)
            next(it)       # warmup + adaptation only
            for chunk in chunks:
                drv.chunk_size = chunk
                bd = profiling.dispatch_breakdown(drv, drv.x_cur)
                rows.append({
                    "nchains": C, "megachunk": mega, "chunk": chunk,
                    "dispatch_amortized_ms_per_sweep":
                        float(bd["dispatch_amortized_per_sweep"]),
                    "sweeps_per_dispatch":
                        int(bd["sweeps_per_dispatch"])})
                print(f"  C={C} mega={mega} chunk={chunk}: "
                      f"{rows[-1]['dispatch_amortized_ms_per_sweep']:.4f}"
                      " ms/sweep (host tax)")
    return rows


def sweep_seg_len(pta, seg_lens, ntoa, iters=10, warmup=2):
    """Steady f32 Gram block wall time per candidate segment length."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.profiling import _scan_time
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    cm = compile_pta(pta)
    x0 = jnp.asarray(pta.initial_sample(np.random.default_rng(0)),
                     cm.cdtype)
    N0 = cm.ndiag_fast(x0)
    C = 8
    rows = []
    for seg in seg_lens:
        seg_eff = seg or ntoa

        def body(x, b, key, _s=seg_eff):
            out = jax.vmap(
                lambda k: jb.tnt_d_seg32(
                    cm, N0 * (1.0 + 0.0 * x), seg_len=_s)[0]
            )(jr.split(key, C))
            return x + 0.0 * out.ravel()[0].astype(x.dtype), b

        t = _scan_time(body, jnp.zeros((), cm.dtype),
                       jnp.zeros((), cm.dtype), iters, warmup)
        rows.append({"gram_seg_len": seg_eff,
                     "gram_block_ms": float(t * 1e3)})
        print(f"  seg_len={seg_eff}: {t * 1e3:.3f} ms (steady gram)")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--npsr", type=int, default=8)
    ap.add_argument("--ntoa", type=int, default=360)
    ap.add_argument("--adapt", type=int, default=100)
    ap.add_argument("--chunks", default="16,64,256")
    ap.add_argument("--megas", default="1,4")
    ap.add_argument("--nchains-list", default="8")
    ap.add_argument("--seg-lens", default="96,180,0",
                    help="candidate gram_seg_len values; 0 = ntoa "
                         "(one segment)")
    ap.add_argument("--out", default=str(_REPO_ROOT / "AUTOTUNE.json"))
    args = ap.parse_args()

    import jax

    backend = jax.default_backend()
    chunks = [int(s) for s in args.chunks.split(",")]
    megas = [int(s) for s in args.megas.split(",")]
    nchains_list = [int(s) for s in args.nchains_list.split(",")]
    seg_lens = [int(s) for s in args.seg_lens.split(",")]

    print(f"autotune: backend={backend}")
    pta = _build_pta(args.npsr, args.ntoa)
    print("autotune: dispatch-knob sweep (chunk, megachunk, nchains)")
    disp = sweep_dispatch(pta, chunks, megas, nchains_list, args.adapt)
    print("autotune: gram_seg_len sweep")
    segs = sweep_seg_len(pta, seg_lens, args.ntoa)

    best_disp = min(disp,
                    key=lambda r: r["dispatch_amortized_ms_per_sweep"])
    best_seg = min(segs, key=lambda r: r["gram_block_ms"])
    best = {"chunk": best_disp["chunk"],
            "megachunk": best_disp["megachunk"],
            "nchains": best_disp["nchains"],
            "gram_seg_len": best_seg["gram_seg_len"]}

    out = Path(args.out)
    table = {"version": 1, "backends": {}}
    if out.exists():
        try:
            table = json.loads(out.read_text())
        except Exception:
            pass
    table.setdefault("backends", {})[backend] = {
        "best": best, "entries": disp + segs}
    out.write_text(json.dumps(table, indent=2, sort_keys=True) + "\n")
    print(f"autotune: best for {backend}: {best}")
    print(f"autotune: wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
