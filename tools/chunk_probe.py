"""Where does steady-loop wall time go beyond the profiled blocks?

Times, per steady chunk on the real device: the chunk dispatch call
(fn(...) return), the xs conversion, the b_flat conversion, aux build —
against the per-block sweep sums.  Usage: python tools/chunk_probe.py
[--nchains 32] [--chunk 100]

``--amortize`` switches to the dispatch-tax sweep (docs/PERFORMANCE.md
mega-chunk knobs): for each chunk size it stages one dispatch through
``profiling.dispatch_breakdown`` and tabulates the host-side overhead
(host_prep + enqueue + writeback) amortized per sweep — the quantity the
mega-chunk loop drives under 1 ms/sweep.  ``--mega N`` scans N
sub-chunks inside each dispatch, so the table directly shows how the
tax falls as one dispatch covers more sweeps.  Works on any backend;
on CPU shrink the geometry first (e.g. ``--npsr 8 --adapt 100
--sizes 16,64,256``).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def amortize(args):
    """chunk_size -> amortized dispatch-tax table (one row per size)."""
    import bench

    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    sizes = [int(s) for s in args.sizes.split(",")]
    import os
    if os.path.isdir(bench.REFDATA):
        pta = bench.build_pta(args.npsr)
    else:
        # no reference data (bare container / CI): the synthetic CRN
        # model from the contract entries keeps the tax measurable —
        # the host-side overhead barely depends on the model size
        from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
            build_model, synthetic_pulsars)

        print(f"# {bench.REFDATA} missing; synthetic "
              f"{args.npsr}-pulsar stand-in")
        pta = build_model(synthetic_pulsars(args.npsr, 100, 3, seed=0), 10)
    x0 = pta.initial_sample(np.random.default_rng(0))
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=args.adapt,
                         chunk_size=min(sizes), nchains=args.nchains,
                         megachunk=args.mega)
    niter = args.adapt + 2 * min(sizes)
    cshape, bshape = drv.chain_shapes(niter)
    it = drv.run(x0, np.zeros(cshape), np.zeros(bshape), 0, niter)
    next(it)   # warmup + adaptation; the steady loop is never entered
    print(f"# {args.npsr} psr x {drv.C} chains, megachunk={args.mega} "
          f"(host-side tax only; device compute excluded)")
    print(f"{'chunk':>6} {'sweeps/disp':>11} {'host_prep':>10} "
          f"{'enqueue':>8} {'writeback':>10} {'ms/sweep':>9}")
    for s in sizes:
        # chunk fns are cached per size, so one adapted driver serves
        # the whole sweep; the ctor's DE guard does not apply (the CRN
        # bench model has no powerlaw-red MH block)
        drv.chunk_size = s
        bd = profiling.dispatch_breakdown(drv, drv.x_cur)
        print(f"{s:>6} {int(bd['sweeps_per_dispatch']):>11} "
              f"{bd['host_prep']:>10.2f} {bd['enqueue']:>8.2f} "
              f"{bd['writeback']:>10.2f} "
              f"{bd['dispatch_amortized_per_sweep']:>9.4f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=32)
    ap.add_argument("--chunk", type=int, default=100)
    ap.add_argument("--nchunks", type=int, default=4)
    ap.add_argument("--amortize", action="store_true",
                    help="dispatch-tax sweep: chunk_size -> host overhead "
                    "amortized per sweep, one dispatch_breakdown staging "
                    "per size (see module docstring)")
    ap.add_argument("--sizes", default="64,256,1024,4096",
                    help="comma-separated chunk sizes for --amortize")
    ap.add_argument("--npsr", type=int, default=45,
                    help="pulsar count for --amortize (bench geometry)")
    ap.add_argument("--adapt", type=int, default=300,
                    help="white-adaptation iterations for --amortize")
    ap.add_argument("--mega", type=int, default=1,
                    help="megachunk depth for --amortize: sub-chunks "
                    "scanned inside each dispatch")
    ap.add_argument("--overlap", action="store_true",
                    help="mirror run()'s double-buffered loop instead of "
                    "the serial component timing: dispatch chunk i+1, then "
                    "convert chunk i — the per-chunk wall vs the serial "
                    "component sum measures how much transfer the tunnel "
                    "actually hides under device compute")
    args = ap.parse_args()
    if args.amortize:
        return amortize(args)

    import bench
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta = bench.build_pta(45)
    x0 = pta.initial_sample(np.random.default_rng(0))
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=300, chunk_size=args.chunk,
                         nchains=args.nchains)
    niter = 200 + args.chunk * (args.nchunks + 2)
    cshape, bshape = drv.chain_shapes(niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    it = drv.run(x0, chain, bchain, 0, niter)
    next(it)   # warmup + adaptation

    # manual steady chunks with fine timing (mirrors run()'s loop body)
    x = jnp.asarray(drv.x_cur, drv.cm.cdtype)
    b_dev = jnp.asarray(drv.b)
    ii = 220    # past warmup rows; absolute iteration index only keys RNG
    fn = drv._chunk_fn(args.chunk)
    if args.overlap:
        # prime the steady chunk fn (first call pays the XLA compile;
        # keeping it out of the timed loop)
        x, b_dev, xs, bs, _h = fn(x, b_dev, drv.key,
                                  jnp.asarray(ii, jnp.int32),
                                  drv._aux(chain, ii),
                                  jnp.asarray(args.chunk, jnp.int32))
        _ = np.asarray(x)[0, 0]
        ii += args.chunk
        pending = None
        t00 = time.time()
        for rep in range(args.nchunks + 1):
            t0 = time.time()
            aux = drv._aux(chain, ii)
            x, b_dev, xs, bs, _h = fn(x, b_dev, drv.key,
                                      jnp.asarray(ii, jnp.int32), aux,
                                      jnp.asarray(args.chunk, jnp.int32))
            t1 = time.time()
            if pending is not None:
                pxs, pbs = pending
                for arr in (pxs, pbs):
                    try:
                        arr.copy_to_host_async()
                    except (AttributeError, RuntimeError):
                        pass
                xs_h = np.asarray(pxs, dtype=np.float64)
                bs_h = np.asarray(pbs, np.float64)
                t2 = time.time()
                print(f"chunk {rep}: dispatch {1e3*(t1-t0):7.1f} ms | "
                      f"fetch prev {1e3*(t2-t1):7.1f} ms | wall "
                      f"{1e3*(t2-t0):7.1f} ms")
            pending = (xs, bs)
            ii += args.chunk
        # drain the final in-flight chunk so every dispatched sweep is
        # paid for inside the timed span
        _ = np.asarray(pending[0], np.float64)
        _ = np.asarray(pending[1], np.float64)
        steady = (time.time() - t00)
        per_sweep_ms = steady / (args.nchunks + 1) / args.chunk * 1e3
        print(f"overlapped wall: {per_sweep_ms:.1f} ms/sweep "
              f"(see serial mode for the per-component breakdown)")
        return

    for rep in range(args.nchunks):
        t0 = time.time()
        aux = drv._aux(chain, ii)
        t1 = time.time()
        x, b_dev, xs, bs, _h = fn(x, b_dev, drv.key,
                                  jnp.asarray(ii, jnp.int32), aux,
                                  jnp.asarray(args.chunk, jnp.int32))
        t2 = time.time()
        # block on the tiny carry first: this isolates pure device compute
        # from the record transfers below
        _ = np.asarray(x)[0, 0]
        t3 = time.time()
        xs_h = np.asarray(xs, dtype=np.float64)
        t4 = time.time()
        # run_chunk returns bs already flat+f32; mirror _writeback
        bs_h = np.asarray(bs, np.float64)
        t5 = time.time()
        print(f"chunk {rep}: aux {1e3*(t1-t0):7.1f} ms | dispatch "
              f"{1e3*(t2-t1):8.1f} ms | compute {1e3*(t3-t2):8.1f} ms | "
              f"xs->host {1e3*(t4-t3):7.1f} ms | b_flat {1e3*(t5-t4):7.1f} "
              f"ms | total {1e3*(t5-t0)/args.chunk:6.2f} ms/sweep")
        ii += args.chunk
    print(f"payloads: xs {xs.dtype} {xs.nbytes/1e6:.1f} MB | "
          f"bs {bs.dtype} {bs.nbytes/1e6:.1f} MB")


if __name__ == "__main__":
    main()
