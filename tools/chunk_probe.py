"""Where does steady-loop wall time go beyond the profiled blocks?

Times, per steady chunk on the real device: the chunk dispatch call
(fn(...) return), the xs conversion, the b_flat conversion, aux build —
against the per-block sweep sums.  Usage: python tools/chunk_probe.py
[--nchains 32] [--chunk 100]
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=32)
    ap.add_argument("--chunk", type=int, default=100)
    ap.add_argument("--nchunks", type=int, default=4)
    args = ap.parse_args()

    import bench
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta = bench.build_pta(45)
    x0 = pta.initial_sample(np.random.default_rng(0))
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=300, chunk_size=args.chunk,
                         nchains=args.nchains)
    niter = 200 + args.chunk * (args.nchunks + 2)
    cshape, bshape = drv.chain_shapes(niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    it = drv.run(x0, chain, bchain, 0, niter)
    next(it)   # warmup + adaptation

    # manual steady chunks with fine timing (mirrors run()'s loop body)
    x = jnp.asarray(drv.x_cur, drv.cm.cdtype)
    b_dev = jnp.asarray(drv.b)
    ii = 220    # past warmup rows; absolute iteration index only keys RNG
    fn = drv._chunk_fn(args.chunk)
    for rep in range(args.nchunks):
        t0 = time.time()
        aux = drv._aux(chain, ii)
        t1 = time.time()
        x, b_dev, xs, bs = fn(x, b_dev, drv.key,
                              jnp.asarray(ii, jnp.int32), aux,
                              jnp.asarray(args.chunk, jnp.int32))
        t2 = time.time()
        xs_h = np.asarray(xs, dtype=np.float64)
        t3 = time.time()
        # run_chunk returns bs already flat+f32; mirror _writeback
        bs_h = np.asarray(bs, np.float64)
        t4 = time.time()
        # force x/b to host too (dispatch may return before compute ends)
        _ = np.asarray(x)[0, 0]
        t5 = time.time()
        print(f"chunk {rep}: aux {1e3*(t1-t0):7.1f} ms | dispatch+compute "
              f"{1e3*(t2-t1):8.1f} ms | xs->host {1e3*(t3-t2):7.1f} ms | "
              f"b_flat {1e3*(t4-t3):7.1f} ms | sync {1e3*(t5-t4):7.1f} ms "
              f"| total {1e3*(t5-t0)/args.chunk:6.2f} ms/sweep")
        ii += args.chunk


if __name__ == "__main__":
    main()
