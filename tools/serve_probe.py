"""Smoke-drive the resident sampler service on synthetic datasets.

Submits ``--jobs`` heterogeneous synthetic analyses (different TOA
counts and noise seeds, identical structure) so they all snap into one
bucket, multiplexes them through a ``--slots``-wide compiled program,
and prints a JSON report: per-job states and first-sample latency, the
SLO gauges (``queue_depth``, ``warm_hit_rate``, ``compile_stalls``,
``tenant_evictions``, ``time_to_first_sample_ms``), steady-phase
retrace attribution, and the multiplexed aggregate throughput.

Exit is nonzero when any job fails, any steady-phase retrace is
unplanned, or the warm-hit rate is below ``(jobs - 1) / jobs`` (every
admission after the first must land on the cached program).

Usage: python tools/serve_probe.py [--jobs N] [--niter N] [--slots N]
       [--chunk N] [--quantum N] [--outdir DIR]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import time
from pathlib import Path

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=3,
                    help="concurrent synthetic analyses (default 3)")
    ap.add_argument("--niter", type=int, default=12)
    ap.add_argument("--slots", type=int, default=2,
                    help="batch rows of the compiled program")
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--quantum", type=int, default=2,
                    help="fair-share chunks before preemptive eviction")
    ap.add_argument("--n-psr", type=int, default=2)
    ap.add_argument("--nmodes", type=int, default=3)
    ap.add_argument("--outdir", default="/tmp/serve_probe")
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.serve import (
        BucketOverflow, BucketTable, SamplerService, probe_shape)

    base = Path(args.outdir)
    if base.exists():
        shutil.rmtree(base)

    # heterogeneous TOA counts, one structure -> one bucket of the ladder
    ptas = [build_model(
        synthetic_pulsars(args.n_psr, 24 + 6 * i, tm_cols=3, seed=i),
        args.nmodes) for i in range(args.jobs)]
    table = BucketTable.ladder(args.nmodes, pulsars=(args.n_psr,),
                               toas=(24 + 6 * args.jobs,),
                               basis=(probe_shape(ptas[0]).basis,))

    telemetry.reset()
    svc = SamplerService(base, table, slots=args.slots, chunk=args.chunk,
                         quantum=args.quantum)
    with recompile_counter() as rc:
        rc.phase("serve")
        try:
            jobs = [svc.submit(pta, args.niter, tenant_id=i)
                    for i, pta in enumerate(ptas)]
        except BucketOverflow as e:
            print(f"FAIL: {e}", file=sys.stderr)
            sys.exit(1)
        t0 = time.monotonic()
        report = svc.run()
        wall = time.monotonic() - t0

    total_rows = sum(j.it for j in jobs)
    report["aggregate_samples_per_s"] = total_rows / wall if wall else None
    report["wall_s"] = wall
    report["unplanned_serve_retraces"] = rc.unplanned("serve")
    report["gauges"] = telemetry.gauges()
    print(json.dumps(report, indent=2))

    ok = (all(j.state == "done" for j in jobs)
          and rc.unplanned("serve") == 0
          and report["warm_hit_rate"] >= (args.jobs - 1) / args.jobs)
    if not ok:
        print("FAIL: serving contract violated", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
