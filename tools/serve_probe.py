"""Smoke-drive the resident sampler service on synthetic datasets.

Submits ``--jobs`` heterogeneous synthetic analyses (different TOA
counts and noise seeds, identical structure) so they all snap into one
bucket, multiplexes them through a ``--slots``-wide compiled program,
and prints a JSON report: per-job states and first-sample latency, the
SLO gauges (``queue_depth``, ``warm_hit_rate``, ``compile_stalls``,
``tenant_evictions``, ``time_to_first_sample_ms``), steady-phase
retrace attribution, and the multiplexed aggregate throughput.

Exit is nonzero when any job fails, any steady-phase retrace is
unplanned, or the warm-hit rate is below ``(jobs - 1) / jobs`` (every
admission after the first must land on the cached program).

``--gateway`` drives the SAME contract through the network boundary
instead of the in-process API: submissions, result streams and the
warm-hit scrape all travel HTTP (``serve.gateway`` over
``serve.wire.HttpTransport``), so the probe proves the transport
frontend does not cost a single retrace or a warm miss.

``--multigroup`` submits ``--jobs`` analyses in EACH of two buckets and
requires both groups to reach a warm steady state concurrently on
their own placement slices: per-group ``warm_hit_rate`` ≥
``(jobs - 1) / jobs``, ``max_concurrent_groups`` ≥ 2 (no cross-group
drain waits), and the slice-labeled ``serve_slice_*`` fault-domain
gauges present in the Prometheus scrape.

Usage: python tools/serve_probe.py [--jobs N] [--niter N] [--slots N]
       [--chunk N] [--quantum N] [--outdir DIR] [--gateway]
       [--multigroup]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import time
from pathlib import Path

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def _gateway_probe(args):
    """Drive the probe's invariants through HTTP: submit via POST
    /v1/jobs, collect every row via cursor streams, scrape
    ``warm_hit_rate`` from /v1/metrics, and hold the same bar —
    all jobs done, zero unplanned retraces, every admission after the
    first a warm hit."""
    import urllib.request

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.serve import BucketTable, probe_shape
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import HttpTransport

    base = Path(args.outdir)
    if base.exists():
        shutil.rmtree(base)

    probe_pta = build_model(
        synthetic_pulsars(args.n_psr, 24, tm_cols=3, seed=0), args.nmodes)
    table = BucketTable.ladder(args.nmodes, pulsars=(args.n_psr,),
                               toas=(24 + 6 * args.jobs,),
                               basis=(probe_shape(probe_pta).basis,))

    def _req(method, path, body=None):
        data = None if body is None else json.dumps(body).encode()
        r = urllib.request.Request(f"{burl}{path}", data=data,
                                   method=method)
        with urllib.request.urlopen(r, timeout=120) as resp:
            return resp.read()

    telemetry.reset()
    rows_by_job, handles = {}, []
    with recompile_counter() as rc:
        rc.phase("serve")
        gw = Gateway(base, table,
                     svc_kw=dict(slots=args.slots, chunk=args.chunk,
                                 quantum=args.quantum),
                     stop_when_idle=True)
        tx = HttpTransport(gw)
        tx.start()
        host, port = tx.address
        burl = f"http://{host}:{port}"
        t0 = time.monotonic()
        # admission needs no scheduler: submit the whole batch first so
        # idle-stop cannot fire between two submissions
        for i in range(args.jobs):
            raw = _req("POST", "/v1/jobs", {
                "dedupe_key": f"probe{i}", "niter": args.niter,
                "payload": {"synthetic": {
                    "n_psr": args.n_psr, "ntoa": 24 + 6 * i,
                    "tm_cols": 3, "seed": i, "nmodes": args.nmodes}}})
            handles.append(json.loads(raw))
        gw.start()
        for h in handles:
            jid, cursor, state = h["job_id"], 0, None
            rows = rows_by_job.setdefault(jid, [])
            while True:
                raw = _req("GET", f"/v1/jobs/{jid}/stream"
                           f"?cursor={cursor}&wait=5")
                final = False
                for line in raw.splitlines():
                    if not line.strip():
                        continue
                    ev = json.loads(line)
                    rows.extend(ev.get("rows") or [])
                    cursor = max(cursor, int(ev.get("cursor", cursor)))
                    state = ev.get("state", state)
                    final = final or bool(ev.get("final"))
                if final:
                    break
            if state != "done":
                print(f"FAIL: {jid} ended {state!r} over HTTP",
                      file=sys.stderr)
                sys.exit(1)
        wall = time.monotonic() - t0
        scrape = _req("GET", "/v1/metrics").decode()
        gw.join(timeout=120)
        tx.stop()

    warm = None
    for line in scrape.splitlines():
        if line.startswith("ptgibbs_warm_hit_rate "):
            warm = float(line.split()[1])
    total_rows = sum(len(r) for r in rows_by_job.values())
    report = {
        "mode": "gateway",
        "jobs": {h["job_id"]: {"rows": len(rows_by_job[h["job_id"]]),
                               "tenant_id": h["tenant_id"]}
                 for h in handles},
        "warm_hit_rate": warm,
        "aggregate_samples_per_s": total_rows / wall if wall else None,
        "wall_s": wall,
        "unplanned_serve_retraces": rc.unplanned("serve"),
        "gateway": gw.report()["state"],
    }
    print(json.dumps(report, indent=2))

    ok = (all(len(rows_by_job[h["job_id"]]) == args.niter
              for h in handles)
          and rc.unplanned("serve") == 0
          and warm is not None
          and warm >= (args.jobs - 1) / args.jobs)
    if not ok:
        print("FAIL: serving contract violated through the gateway",
              file=sys.stderr)
        sys.exit(1)


def _multigroup_probe(args):
    """Drive TWO ``(bucket, signature)`` groups concurrently on their
    own placement slices and hold the placement contract: both groups
    reach a warm steady state (per-group ``warm_hit_rate`` ≥
    ``(jobs - 1) / jobs``), ≥2 groups were concurrently resident (no
    cross-group drain waits — pre-placement, a second bucket had to
    wait for the active group to drain), zero unplanned serve-phase
    retraces, and the slice-labeled ``serve_slice_*`` gauges flow
    through the Prometheus exposition."""
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.serve import (
        BucketTable, SamplerService, probe_shape)

    base = Path(args.outdir)
    if base.exists():
        shutil.rmtree(base)

    # two TOA rungs of one ladder: group A fills the first bucket,
    # group B sits strictly inside the second (past the first), so
    # route_pta keeps the groups on their own buckets
    toas_a = 24 + 6 * args.jobs
    ptas_a = [build_model(
        synthetic_pulsars(args.n_psr, 24 + 6 * i, tm_cols=3, seed=i),
        args.nmodes) for i in range(args.jobs)]
    ptas_b = [build_model(
        synthetic_pulsars(args.n_psr, toas_a + 2 + 6 * i, tm_cols=3,
                          seed=10 + i),
        args.nmodes) for i in range(args.jobs)]
    basis = probe_shape(ptas_a[0]).basis   # same structure, same basis
    table = BucketTable.ladder(
        args.nmodes, pulsars=(args.n_psr,),
        toas=(toas_a, toas_a + 2 + 6 * args.jobs),
        basis=(basis, basis))

    telemetry.reset()
    svc = SamplerService(
        base, table, chunk=args.chunk, quantum=args.quantum,
        placement=[{"slots": args.slots}, {"slots": args.slots}])
    with recompile_counter() as rc:
        rc.phase("serve")
        jobs = [svc.submit(pta, args.niter, tenant_id=i)
                for i, pta in enumerate(ptas_a + ptas_b)]
        t0 = time.monotonic()
        report = svc.run()
        wall = time.monotonic() - t0

    scrape = svc.prometheus()
    slice_series = sorted(
        line.split()[0] for line in scrape.splitlines()
        if line.startswith("ptgibbs_serve_slice_"))
    pl = report["placement"]
    total_rows = sum(j.it for j in jobs)
    report["aggregate_samples_per_s"] = total_rows / wall if wall else None
    report["wall_s"] = wall
    report["unplanned_serve_retraces"] = rc.unplanned("serve")
    report["slice_series"] = slice_series
    print(json.dumps(report, indent=2))

    bar = (args.jobs - 1) / args.jobs
    group_ok = (len(pl["groups"]) >= 2
                and all(g["warm_hit_rate"] >= bar
                        for g in pl["groups"].values()))
    wanted = {f'ptgibbs_serve_slice_{n}{{slice="{s}"}}'
              for n in ("residents", "chunks", "losses")
              for s in ("0", "1")}
    ok = (all(j.state == "done" for j in jobs)
          and rc.unplanned("serve") == 0
          and group_ok
          and pl["max_concurrent_groups"] >= 2
          and wanted.issubset(set(slice_series)))
    if not ok:
        print("FAIL: multigroup placement contract violated",
              file=sys.stderr)
        if not group_ok:
            print(f"  per-group warmth below {bar}: {pl['groups']}",
                  file=sys.stderr)
        if pl["max_concurrent_groups"] < 2:
            print("  groups were serialized (cross-group drain wait)",
                  file=sys.stderr)
        missing = wanted - set(slice_series)
        if missing:
            print(f"  slice series missing from the scrape: {missing}",
                  file=sys.stderr)
        sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=3,
                    help="concurrent synthetic analyses (default 3)")
    ap.add_argument("--niter", type=int, default=12)
    ap.add_argument("--slots", type=int, default=2,
                    help="batch rows of the compiled program")
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--quantum", type=int, default=2,
                    help="fair-share chunks before preemptive eviction")
    ap.add_argument("--n-psr", type=int, default=2)
    ap.add_argument("--nmodes", type=int, default=3)
    ap.add_argument("--outdir", default="/tmp/serve_probe")
    ap.add_argument("--gateway", action="store_true",
                    help="drive the same assertions through the HTTP "
                    "gateway instead of the in-process API")
    ap.add_argument("--multigroup", action="store_true",
                    help="drive --jobs analyses in EACH of two buckets "
                    "concurrently on two placement slices and assert "
                    "per-group warm steady state with no cross-group "
                    "drain waits")
    args = ap.parse_args()

    if args.gateway:
        return _gateway_probe(args)
    if args.multigroup:
        return _multigroup_probe(args)

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.serve import (
        BucketOverflow, BucketTable, SamplerService, probe_shape)

    base = Path(args.outdir)
    if base.exists():
        shutil.rmtree(base)

    # heterogeneous TOA counts, one structure -> one bucket of the ladder
    ptas = [build_model(
        synthetic_pulsars(args.n_psr, 24 + 6 * i, tm_cols=3, seed=i),
        args.nmodes) for i in range(args.jobs)]
    table = BucketTable.ladder(args.nmodes, pulsars=(args.n_psr,),
                               toas=(24 + 6 * args.jobs,),
                               basis=(probe_shape(ptas[0]).basis,))

    telemetry.reset()
    svc = SamplerService(base, table, slots=args.slots, chunk=args.chunk,
                         quantum=args.quantum)
    with recompile_counter() as rc:
        rc.phase("serve")
        try:
            jobs = [svc.submit(pta, args.niter, tenant_id=i)
                    for i, pta in enumerate(ptas)]
        except BucketOverflow as e:
            print(f"FAIL: {e}", file=sys.stderr)
            sys.exit(1)
        t0 = time.monotonic()
        report = svc.run()
        wall = time.monotonic() - t0

    total_rows = sum(j.it for j in jobs)
    report["aggregate_samples_per_s"] = total_rows / wall if wall else None
    report["wall_s"] = wall
    report["unplanned_serve_retraces"] = rc.unplanned("serve")
    report["gauges"] = telemetry.gauges()
    print(json.dumps(report, indent=2))

    ok = (all(j.state == "done" for j in jobs)
          and rc.unplanned("serve") == 0
          and report["warm_hit_rate"] >= (args.jobs - 1) / args.jobs)
    if not ok:
        print("FAIL: serving contract violated", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
