"""HD mixing measurement: ACT/ESS of the correlated-ORF channels under the
dense joint b-draw vs the sequential pulsar-wise conditional sweep.

VERDICT r3 weak-point 4: the HD bench reported throughput but no mixing
quality for the path where the sequential sweep (the only scalable one)
"mixes the cross-pulsar correlations over sweeps instead of within one".
This probe runs, on CPU (f64, deterministic, no tunnel noise):

  A. 3-pulsar toy (fits under HD_DENSE_MAX): dense joint draw vs forced
     sequential — per-channel ACT of the common rho_k, plus the sampled
     ORF weights under bin_orf for the weight channels.
  B. 45-pulsar real-size config, sequential (the only option): rho_k ACT.

Writes docs/HD_MIXING.md and prints a JSON line consumed by bench.py's
``hd.ess_per_sec`` computation (the measured ACTs let throughput be
converted to effective samples/sec).

Usage: python tools/hd_mixing_probe.py [--niter 4000] [--full]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def act_table(chain, cols, names, burn):
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    out = {}
    for k in cols:
        out[names[k]] = max(float(integrated_act(chain[burn:, k])), 1.0)
    return out


def run_chain(pta, x0, seed, niter, outdir, kernel="dense"):
    """kernel: "dense" (joint draw) | "freq" | "pulsar" (scalable paths,
    forced past HD_DENSE_MAX)."""
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    old, oldk = jb.HD_DENSE_MAX, jb.HD_SCALABLE_KERNEL
    try:
        if kernel != "dense":
            jb.HD_DENSE_MAX = 0
            jb.HD_SCALABLE_KERNEL = kernel
        g = PTABlockGibbs(pta, backend="jax", seed=seed, progress=False)
        return g.sample(x0, outdir=outdir, niter=niter)
    finally:
        jb.HD_DENSE_MAX, jb.HD_SCALABLE_KERNEL = old, oldk


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--niter", type=int, default=4000)
    ap.add_argument("--full", action="store_true",
                    help="also run the 45-pulsar sequential config")
    ap.add_argument("--full-niter", type=int, default=1500)
    ap.add_argument("--outdir", default="/tmp/hd_mixing")
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu.data import load_directory
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex

    psrs = load_directory(
        REFDATA, inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0))

    results = {}

    # ---- A: toy size where dense and sequential both run -----------------
    for orf in ("hd", "bin_orf"):
        pta = model_general(psrs[:3], tm_svd=True, red_var=False,
                            white_vary=False, common_psd="spectrum",
                            common_components=5, orf=orf)
        names = pta.param_names
        idx = BlockIndex.build(names)
        x0 = pta.initial_sample(np.random.default_rng(4))
        if len(idx.orf):
            x0[idx.orf] = 0.0
        cols = list(idx.rho) + list(idx.orf)
        burn = max(300, args.niter // 10)
        for seed, mode in enumerate(("dense", "freq", "pulsar")):
            chain = run_chain(pta, x0, 60 + seed, args.niter,
                              f"{args.outdir}/{orf}_{mode}", kernel=mode)
            assert np.all(np.isfinite(chain))
            results[f"toy3_{orf}_{mode}"] = act_table(
                chain, cols, names, burn)

    # ---- B: real size, sequential only -----------------------------------
    if args.full:
        pta = model_general(psrs, tm_svd=True, white_vary=True,
                            common_psd="spectrum", common_components=10,
                            red_var=True, red_psd="spectrum",
                            red_components=10, orf="hd")
        names = pta.param_names
        idx = BlockIndex.build(names)
        x0 = pta.initial_sample(np.random.default_rng(4))
        burn = max(200, args.full_niter // 10)
        for mode in ("freq", "pulsar"):
            chain = run_chain(pta, x0, 62, args.full_niter,
                              f"{args.outdir}/full45_{mode}", kernel=mode)
            assert np.all(np.isfinite(chain))
            results[f"full45_hd_{mode}"] = act_table(
                chain, list(idx.rho), names, burn)

    # ---- report ----------------------------------------------------------
    lines = [
        "# HD (correlated-ORF) mixing: dense joint vs sequential b-draw",
        "",
        "Per-channel Sokal integrated ACT (sweeps/effective sample; lower "
        "is better), measured on CPU f64 chains "
        f"(toy: 3 pulsars, {args.niter} sweeps; the size where the dense "
        "joint draw still compiles).  Two scalable kernels run past "
        "``HD_DENSE_MAX``: ``pulsar`` (production: the sequential "
        "pulsar-wise conditional sweep, random-scan order — it resolves "
        "the dominant gw <-> timing-model coupling within each pulsar "
        "draw) and ``freq`` (two-block sweep with per-frequency "
        "cross-pulsar joint draws, intrinsic-red columns folded into "
        "each frequency block; a K-length scan instead of P).",
        "",
    ]
    for orf in ("hd", "bin_orf"):
        dn = results[f"toy3_{orf}_dense"]
        fr = results[f"toy3_{orf}_freq"]
        sq = results[f"toy3_{orf}_pulsar"]
        lines += [f"## toy 3-pulsar, orf={orf}", "",
                  "| channel | dense ACT | freq ACT | pulsar ACT |"
                  " freq/dense | pulsar/dense |",
                  "|---|---|---|---|---|---|"]
        for name in dn:
            lines.append(
                f"| `{name}` | {dn[name]:.2f} | {fr[name]:.2f} "
                f"| {sq[name]:.2f} | {fr[name] / dn[name]:.2f} "
                f"| {sq[name] / dn[name]:.2f} |")
        medf = np.median([fr[n] / dn[n] for n in dn])
        medp = np.median([sq[n] / dn[n] for n in dn])
        lines += ["", f"median freq/dense ACT ratio: **{medf:.2f}**; "
                  f"median pulsar/dense ACT ratio: **{medp:.2f}**", ""]
        results[f"toy3_{orf}_freq_ratio_median"] = float(medf)
        results[f"toy3_{orf}_pulsar_ratio_median"] = float(medp)
    for mode in ("freq", "pulsar"):
        if f"full45_hd_{mode}" not in results:
            continue
        acts = list(results[f"full45_hd_{mode}"].values())
        lines += [f"## 45-pulsar, orf=hd, {mode} kernel (real size)",
                  "",
                  f"rho_k ACT over {len(acts)} bins: median "
                  f"{np.median(acts):.2f}, max {np.max(acts):.2f} "
                  f"({args.full_niter} sweeps)", ""]
        results[f"full45_rho_act_median_{mode}"] = float(np.median(acts))
        results[f"full45_rho_act_max_{mode}"] = float(np.max(acts))
    lines += [
        "Generated by `tools/hd_mixing_probe.py`.  bench.py divides the "
        "measured HD sweeps/sec by the median rho ACT to report "
        "`hd.ess_per_sec` (effective common-spectrum samples per second).",
        "",
    ]
    os.makedirs("docs", exist_ok=True)
    with open("docs/HD_MIXING.md", "w") as fh:
        fh.write("\n".join(lines))
    print(json.dumps({k: v for k, v in results.items()
                      if isinstance(v, float)}))
    print("wrote docs/HD_MIXING.md", file=sys.stderr)


if __name__ == "__main__":
    main()
