"""Quantify the pct=95 sub-chain sizing tradeoff (VERDICT r3 weak 5).

``JaxGibbsDriver._act_from_rec`` sizes the white/ECORR MH sub-chains by
the 95th percentile of the per-(chain, pulsar, parameter) adaptation
ACTs instead of the reference's max (``pulsar_gibbs.py:367-371``).  The
justification was argued, not measured: coordinates above the 95th
percentile get sub-chains shorter than their own ACT, so their chain-level
mixing (in sweeps) could inflate.  This probe measures it:

  1. run the 45-pulsar bench model's adaptation, capturing every
     coordinate's adaptation ACT and the sub-chain lengths pct=95 and
     pct=100 would choose;
  2. run a long post-adaptation chain and measure every white
     coordinate's *chain* ACT in sweeps;
  3. report chain-ACT statistics for the slow tail (adaptation ACT above
     the 95th percentile) vs the bulk, and the ESS each achieves over a
     realistic 10k-sweep run.

Writes docs/ACT_TAIL.md.  CPU (f64): mixing quality is
device-independent.  Usage: python tools/act_tail_probe.py [--niter 4000]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--niter", type=int, default=4000)
    ap.add_argument("--n-psr", type=int, default=45)
    args = ap.parse_args()

    import bench
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta = bench.build_pta(args.n_psr)
    names = pta.param_names
    idx = BlockIndex.build(names)
    x0 = pta.initial_sample(np.random.default_rng(0))

    # capture the adaptation ACTs the percentile rule sees
    captured = {}
    orig = JaxGibbsDriver._act_from_rec

    def spy(self, rec, nper, pct=95.0):
        rec_np = np.asarray(rec, dtype=np.float64)
        nper_np = np.asarray(nper)
        acts, labels = [], []
        for c in range(rec_np.shape[0]):
            burn = rec_np[c, min(100, rec_np.shape[1] // 2):]
            for p in range(self.cm.P_real):
                for w in range(int(nper_np[p])):
                    acts.append(integrated_act(burn[:, p, w]))
                    labels.append((c, p, w))
        key = "white" if "white" not in captured else "ecorr"
        captured[key] = (np.asarray(acts), labels)
        return orig(self, rec, nper, pct)

    JaxGibbsDriver._act_from_rec = spy
    try:
        drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                             white_adapt_iters=1000, chunk_size=100,
                             nchains=1)
        cshape, bshape = drv.chain_shapes(args.niter)
        chain = np.zeros(cshape)
        bchain = np.zeros(bshape)
        for _ in drv.run(x0, chain, bchain, 0, args.niter):
            pass
    finally:
        JaxGibbsDriver._act_from_rec = orig

    acts_ad, labels = captured["white"]
    nw95 = max(1, int(np.ceil(np.percentile(acts_ad, 95.0))))
    nw100 = max(1, int(np.ceil(acts_ad.max())))
    thr = np.percentile(acts_ad, 95.0)

    # map the (pulsar, param-within-pulsar) adaptation labels to chain
    # columns: white_par_ix[p, w] indexes x
    wpi = np.asarray(drv.cm.white_par_ix)
    col_of = {(p, w): int(wpi[p, w]) for (c, p, w) in labels}
    burn = max(200, args.niter // 10)
    rows = []
    for (c, p, w), a_ad in zip(labels, acts_ad):
        col = col_of[(p, w)]
        a_ch = integrated_act(chain[burn:, col])
        rows.append((names[col], a_ad, a_ch))

    a_ad = np.array([r[1] for r in rows])
    a_ch = np.array([r[2] for r in rows])
    tail = a_ad > thr
    bulk = ~tail

    def stats_of(v):
        return (f"median {np.median(v):.1f}, p90 "
                f"{np.percentile(v, 90):.1f}, max {v.max():.1f}")

    ess10k_tail = 10000.0 / max(np.max(a_ch[tail]) if tail.any() else 1.0,
                                1.0)
    lines = [
        "# Sub-chain sizing: percentile-ACT (pct=95) vs max-ACT",
        "",
        f"45-pulsar bench model, single chain, {args.niter} sweeps "
        f"(CPU f64).  Adaptation measured {len(a_ad)} white-noise "
        f"coordinates; pct=95 chooses a {nw95}-step sub-chain vs "
        f"{nw100} for the reference's max rule "
        "(`pulsar_gibbs.py:367-371`).",
        "",
        "| group | n | adaptation ACT | chain ACT (sweeps) |",
        "|---|---|---|---|",
        f"| bulk (<= p95) | {bulk.sum()} | {stats_of(a_ad[bulk])} | "
        f"{stats_of(a_ch[bulk])} |",
        f"| slow tail (> p95) | {tail.sum()} | {stats_of(a_ad[tail])} | "
        f"{stats_of(a_ch[tail])} |",
        "",
        f"The slow-tail coordinates' worst chain ACT is "
        f"{np.max(a_ch[tail]) if tail.any() else 0:.1f} sweeps — a "
        f"10k-sweep run still yields >= {ess10k_tail:.0f} effective "
        "samples for the slowest coordinate, at a sub-chain "
        f"{nw100 - nw95} steps shorter per sweep for every pulsar.",
        "",
        "Worst five slow-tail coordinates (adaptation ACT, chain ACT):",
        "",
    ]
    order = np.argsort(-a_ad)
    seen = 0
    for i in order:
        if not tail[i]:
            continue
        lines.append(f"- `{rows[i][0]}`: {a_ad[i]:.1f} -> "
                     f"{a_ch[i]:.1f} sweeps")
        seen += 1
        if seen >= 5:
            break
    lines += ["", "Generated by `tools/act_tail_probe.py`; cited from "
              "`JaxGibbsDriver._act_from_rec`.", ""]
    with open("docs/ACT_TAIL.md", "w") as fh:
        fh.write("\n".join(lines))
    print("\n".join(lines[:14]))
    print("wrote docs/ACT_TAIL.md", file=sys.stderr)


if __name__ == "__main__":
    main()
