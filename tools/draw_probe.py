"""Probe 2: where does the segmented b-draw's non-Gram cost sit, and how
well does a cheap proposal (segmented Gram + f32 ridge factor + refined
mean) accept?

Components timed at C chains on the real device:
  - phi(x) f64
  - Sigma build + Jacobi precond (f64 elementwise)
  - blocked_chol_inv f64
  - solves/matvecs (mean + sample)
  - f32 factor pipeline (native cholesky + triangular solves)
  - proposed production draw: segmented Gram -> f64 Sigma -> f32 ridge
    factor -> iteratively-refined mean -> sample + exact Hastings accept

Usage: python tools/draw_probe.py [--nchains 32] [--warm 200]
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")
from tools.gram_probe import tnt_d_nseg  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=32)
    ap.add_argument("--warm", type=int, default=200)
    ap.add_argument("--adapt", type=int, default=300)
    args = ap.parse_args()

    import bench
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.ops.linalg import (
        _batched_diag, blocked_chol_inv)
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta = bench.build_pta(45)
    x0 = pta.initial_sample(np.random.default_rng(0))
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=args.adapt, chunk_size=50,
                         nchains=args.nchains)
    C = drv.C
    cm = drv.cm
    cshape, bshape = drv.chain_shapes(args.warm)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    t0 = time.time()
    for _ in drv.run(x0, chain, bchain, 0, args.warm):
        pass
    print(f"# warmup {args.warm} iters in {time.time()-t0:.1f}s",
          file=sys.stderr)

    x = jnp.asarray(np.asarray(drv.x_cur, np.float64), cm.cdtype)
    b = jnp.asarray(drv.b)

    def t_body(single, label):
        def body(xx, bb, k):
            return jax.vmap(single)(xx, bb, jr.split(k, C))

        t = profiling._scan_time(body, x, b, 20, 3)
        print(f"{label:36s} {t*1e3:9.3f} ms  (C={C})")
        return t

    mark = 1e-30

    # keep the computed arrays live in the scan carry so XLA can't elide
    def ps(b1, *arrs):
        s = sum(jnp.sum(a).astype(b1.dtype) for a in arrs)
        return b1 + mark * s

    t_body(lambda x1, b1, k1: (x1, ps(b1, cm.phi(x1))), "phi(x) f64")

    def sigma_build(x1, b1, k1):
        N = cm.ndiag_fast(x1)
        TNT, d = tnt_d_nseg(cm, N, 8)
        phi = cm.phi(x1)
        Sig = TNT + _batched_diag(1.0 / phi)
        diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        A = Sig * dj[:, :, None] * dj[:, None, :]
        return x1, ps(b1, A, d)

    t_body(sigma_build, "gram_seg + Sigma build + precond")

    def with_chol(x1, b1, k1):
        N = cm.ndiag_fast(x1)
        TNT, d = tnt_d_nseg(cm, N, 8)
        phi = cm.phi(x1)
        Sig = TNT + _batched_diag(1.0 / phi)
        diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        A = Sig * dj[:, :, None] * dj[:, None, :]
        L, Li = blocked_chol_inv(A)
        return x1, ps(b1, Li)

    t_body(with_chol, "... + blocked_chol_inv f64")

    def full_seg_draw(x1, b1, k1):
        N = cm.ndiag_fast(x1)
        TNT, d = tnt_d_nseg(cm, N, 8)
        phi = cm.phi(x1)
        Sig = TNT + _batched_diag(1.0 / phi)
        diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        A = Sig * dj[:, :, None] * dj[:, None, :]
        L, Li = blocked_chol_inv(A)
        u = jnp.einsum("...ij,...j->...i", Li, dj * d)
        mean = dj * jnp.einsum("...ji,...j->...i", Li, u)
        z = jr.normal(k1, (cm.P, cm.Bmax), cm.cdtype)
        samp = mean + dj * jnp.einsum("...ji,...j->...i", Li, z)
        return x1, samp

    t_body(full_seg_draw, "... + solves (full seg draw)")

    # ---- the candidate production proposal ------------------------------
    from pulsar_timing_gibbsspec_tpu.ops.linalg import (
        precond_cholesky, precond_solve)

    RIDGE = 4e-6

    def draw_refined(x1, b1, u1, k1, nrefine=2):
        fdt = cm.dtype
        cdt = cm.cdtype
        k1a, k2a = jr.split(k1)
        N = cm.ndiag_fast(x1)
        TNT, d = tnt_d_nseg(cm, N, 8)                 # f64 values
        phi = cm.phi(x1)
        Sig = TNT + _batched_diag(1.0 / phi)         # f64
        diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)                    # f64
        A = (Sig * dj[:, :, None] * dj[:, None, :]).astype(fdt)
        L32 = jnp.linalg.cholesky(
            A + fdt(RIDGE) * jnp.eye(cm.Bmax, dtype=fdt))
        dj32 = dj.astype(fdt)

        def solve32(v):
            w = jax.scipy.linalg.solve_triangular(
                L32, (dj32 * v.astype(fdt)), lower=True)
            w = jax.scipy.linalg.solve_triangular(L32, w, lower=True,
                                                  trans=1)
            return dj32 * w

        m = solve32(d).astype(cdt)
        for _ in range(nrefine):
            r = d - jnp.einsum("...ij,...j->...i", Sig, m)
            m = m + solve32(r).astype(cdt)
        z = jr.normal(k1a, (cm.P, cm.Bmax), fdt)
        step = dj32 * jax.scipy.linalg.solve_triangular(
            L32, z, lower=True, trans=1)
        bp = m + step.astype(cdt)
        up = jb.b_matvec(cm, bp)
        lpi_new = jb._logpi_b_per(cm, x1, bp, up)
        lpi_old = jb._logpi_b_per(cm, x1, b1, u1)
        w_old = jnp.einsum("pji,pj->pi", L32,
                           ((b1 - m).astype(fdt) / dj32), precision="highest")
        logq_old = -0.5 * jnp.sum(w_old * w_old, axis=1).astype(cdt)
        logq_new = -0.5 * jnp.sum(z * z, axis=1).astype(cdt)
        logr = (lpi_new - lpi_old) + (logq_old - logq_new)
        ok = jnp.all(jnp.isfinite(bp.astype(fdt)), axis=1) & jnp.isfinite(
            logr)
        logu = jnp.log(jr.uniform(k2a, (cm.P,), cdt))
        acc = ok & (logr > logu)
        b_new = jnp.where(acc[:, None], bp, b1)
        u_new = jnp.where(acc[:, None], up, u1)
        return b_new, u_new, acc, logr

    def prod_draw(x1, b1, k1):
        u1 = jb.b_matvec(cm, b1)
        bn, un, acc, _ = draw_refined(x1, b1, u1, k1)
        return x1, bn

    t_body(prod_draw, "candidate refined-mean MH draw")

    def cur_mh(x1, b1, k1):
        u1 = jb.b_matvec(cm, b1)
        bn, un, acc = jb.draw_b_mh(cm, x1, b1, u1, k1)
        return x1, bn

    t_body(cur_mh, "current draw_b_mh (f32)")

    # acceptance of the candidate across chains
    @jax.jit
    def acc_of(x1, b1, k1):
        u1 = jb.b_matvec(cm, b1)
        _, _, acc, logr = draw_refined(x1, b1, u1, k1)
        return jnp.minimum(1.0, jnp.exp(logr))

    accs = []
    for ci in range(C):
        accs.append(np.asarray(acc_of(x[ci], b[ci], jr.PRNGKey(ci))))
    accs = np.concatenate(accs)
    print(f"refined-MH accept: mean={accs.mean():.6f} "
          f"min={accs.min():.6f} p1={np.percentile(accs, 1):.6f}")

    # acceptance of current f32 draw for comparison
    @jax.jit
    def acc_cur(x1, b1, k1):
        u1 = jb.b_matvec(cm, b1)
        _, _, acc = jb.draw_b_mh(cm, x1, b1, u1, k1)
        return acc

    accs2 = []
    for ci in range(C):
        accs2.append(np.asarray(acc_cur(x[ci], b[ci], jr.PRNGKey(ci))))
    accs2 = np.concatenate(accs2)
    print(f"current f32 draw accept-rate (binary, one step): "
          f"{accs2.mean():.4f}")


if __name__ == "__main__":
    main()
