"""Thin CLI wrapper: ``python tools/numcheck.py [opts]``.

Equivalent to ``python -m pulsar_timing_gibbsspec_tpu.analysis.numcheck``
— kept under tools/ so the precision-flow auditor is discoverable next
to the other probes.  Importing this module has no side effects.
"""


def main(argv=None) -> int:
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from pulsar_timing_gibbsspec_tpu.analysis.numcheck.__main__ import \
        main as _main
    return _main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
