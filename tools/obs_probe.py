"""Observability probe: instrumented short sample -> trace + scrape body.

Runs a short chunked sample of a synthetic CRN model with the streaming
diagnostic sketch enabled (``obs=True``), the trace layer recording the
full span taxonomy (docs/OBSERVABILITY.md), and the driver's
``transfer_guard`` armed — then writes the three artifacts the obs
stack promises:

- ``trace.json``    Chrome/Perfetto trace of the pipeline spans
  (``warmup.chunk``, ``chunk.host_prep``/``dispatch``/``d2h``/
  ``writeback``, ``profile.*``) — load in ``chrome://tracing`` or
  https://ui.perfetto.dev;
- ``metrics.jsonl`` the same spans streamed as structured events;
- ``prometheus.txt``  the Prometheus text-format scrape body of the
  telemetry registry, including the obs summary gauges.

Exit is nonzero when the instrumented steady loop violates its static
contract dynamically: any UNPLANNED retrace (the sketch must ride the
one compiled chunk program), any implicit host transfer inside a
dispatch (``transfer_guard`` raises — the summary slab is the only
sanctioned device->host surface beyond the record), a failed obs
summary, or non-finite diagnostics.

Usage: python tools/obs_probe.py [--niter N] [--nchains C] [--chunk N]
       [--n-psr P] [--nmodes K] [--lags L] [--outdir DIR]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--niter", type=int, default=120,
                    help="total recorded iterations (short by design)")
    ap.add_argument("--nchains", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=10)
    ap.add_argument("--n-psr", type=int, default=3)
    ap.add_argument("--nmodes", type=int, default=3)
    ap.add_argument("--lags", type=int, default=64,
                    help="one-pass ACF window of the device sketch")
    ap.add_argument("--outdir", default="/tmp/obs_probe")
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.obs import metrics, trace
    from pulsar_timing_gibbsspec_tpu.profiling import (
        dispatch_breakdown, recompile_counter)
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import (
        JaxGibbsDriver)

    out = Path(args.outdir)
    out.mkdir(parents=True, exist_ok=True)
    telemetry.reset()
    trace.enable(trace.jsonl_sink(out / "metrics.jsonl"))

    pta = build_model(
        synthetic_pulsars(args.n_psr, 40, tm_cols=3, seed=0), args.nmodes)
    x0 = pta.initial_sample(np.random.default_rng(0))
    # transfer_guard arms jax.transfer_guard("disallow") around every
    # steady dispatch: an instrumentation-added implicit host transfer
    # raises right here instead of silently eating the tunnel
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=20, chunk_size=args.chunk,
                         nchains=args.nchains, warmup_sweeps=20,
                         transfer_guard=True, obs={"lags": args.lags})
    cshape, bshape = drv.chain_shapes(args.niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)

    failures = []
    with recompile_counter() as rc:
        rc.phase("warmup")
        it = drv.run(x0, chain, bchain, 0, args.niter)
        try:
            next(it)                     # warmup + first compiles
            rc.phase("steady")
            for _ in it:
                pass
        except Exception as exc:         # noqa: BLE001 — report, then fail
            failures.append(f"instrumented run raised "
                            f"{type(exc).__name__}: {exc}")
    retraces = rc.unplanned("steady")
    if retraces:
        failures.append(f"{retraces} unplanned retrace(s) in the "
                        "instrumented steady loop")

    summary = None
    if not failures:
        try:
            s = drv.obs_summary()
            summary = {
                "n": s["n"],
                "act_rho_med": round(float(s["act_rho_med"]), 3),
                "ess_total": round(float(s["ess_total"]), 1),
                "rhat_max": (None if s.get("rhat_max") is None
                             else round(float(s["rhat_max"]), 4)),
                "window_saturated": bool(s.get("window_saturated")),
                "move_rate": {k: round(float(np.mean(v)), 4)
                              for k, v in s["move_rate"].items()},
            }
            if not np.isfinite(s["act_rho_med"]):
                failures.append("non-finite device ACT")
            telemetry.gauge("obs_act_rho_med", float(s["act_rho_med"]))
            telemetry.gauge("obs_ess_total", float(s["ess_total"]))
            if s.get("rhat_max") is not None:
                telemetry.gauge("obs_rhat_max", float(s["rhat_max"]))
        except Exception as exc:         # noqa: BLE001
            failures.append(f"obs summary failed: "
                            f"{type(exc).__name__}: {exc}")
        try:
            bd = dispatch_breakdown(drv, drv.x_cur)
            for stage, ms in bd.items():
                telemetry.gauge("chunk_stage_ms", ms, stage=stage)
        except Exception as exc:         # noqa: BLE001
            failures.append(f"dispatch breakdown failed: "
                            f"{type(exc).__name__}: {exc}")

    trace_path = trace.write_chrome(out / "trace.json")
    (out / "prometheus.txt").write_text(metrics.render_telemetry())
    trace.disable()

    spans = {}
    for ev in trace.events():
        if ev.get("ph") == "X":
            spans[ev["name"]] = spans.get(ev["name"], 0) + 1
    report = {
        "niter": args.niter, "nchains": args.nchains,
        "chunk": args.chunk,
        "unplanned_steady_retraces": retraces,
        "span_counts": spans,
        "obs_summary": summary,
        "artifacts": {"trace": trace_path,
                      "metrics": str(out / "metrics.jsonl"),
                      "prometheus": str(out / "prometheus.txt")},
        "failures": failures,
    }
    print(json.dumps(report, indent=2))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
