"""Component timing of the HD sequential b-draw at a given chain width,
via the scan-amortized timer (``profiling._scan_time``) that cancels the
~100 ms per-dispatch tunnel overhead: cumulative stages (gram+Sigma ->
+factor -> +precompute -> full draw) are timed separately and differenced,
plus the two-float factorization as the candidate replacement for the f64
blocked factor.  The breakdown behind the r5 restructure of
``draw_b_hd_sequential``.

Usage: python tools/hd_draw_probe.py [--nchains 32] [--inner 20]
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=32)
    ap.add_argument("--inner", type=int, default=20)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    import bench

    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.ops.linalg import (blocked_chol_inv,
                                                        tf_chol_factor)
    from pulsar_timing_gibbsspec_tpu.profiling import _scan_time
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    pta = bench.build_pta(45, orf="hd")
    x0 = pta.initial_sample(np.random.default_rng(0))
    ix = BlockIndex.build(pta.param_names)
    if len(ix.orf):
        x0[ix.orf] = 0.0
    cm = compile_pta(pta)
    C = args.nchains
    cdt = cm.cdtype
    B, P = cm.Bmax, cm.P
    x = jnp.tile(jnp.asarray(x0, cdt)[None], (C, 1))
    b = jnp.zeros((C, P, B), cdt)
    print(f"C={C} P={P} B={B} K={cm.K} cdtype={np.dtype(cdt).name}",
          file=sys.stderr)

    def sigma_of(x1):
        N = cm.ndiag_fast(x1)
        TNT, d = jb.tnt_d_seg(cm, N)
        phi = cm.phi(x1)
        pinv = 1.0 / phi
        rows_p = jnp.arange(P)[:, None]
        rho = 10.0 ** (2.0 * jnp.asarray(x1, cdt)[cm.rho_ix_x])
        Ginv = cm.orf_ginv_k(x1).astype(cdt)
        prior = jnp.diagonal(Ginv, axis1=1, axis2=2).T / rho
        pin = pinv.at[rows_p, jnp.asarray(cm.gw_sin_ix)].set(
            prior, mode="drop")
        pin = pin.at[rows_p, jnp.asarray(cm.gw_cos_ix)].set(
            prior, mode="drop")
        Sigma = TNT + pin[:, :, None] * jnp.eye(B, dtype=cdt)
        return Sigma, d

    def vm(single):
        def body(x, b, k):
            return jax.vmap(single)(x, b, jr.split(k, C))
        return body

    def t(name, single):
        ms = _scan_time(vm(single), x, b, args.inner, args.repeats) * 1e3
        print(f"{name:28s} {ms:9.2f} ms")
        return ms

    t("full draw", lambda x1, b1, k1: (
        x1, jb.draw_b_hd_sequential(cm, x1, b1, k1)))

    def s1(x1, b1, k1):
        Sigma, d = sigma_of(x1)
        return x1, b1 + 0.0 * (Sigma[:, :, 0] + d)

    t("s1 gram+Sigma", s1)

    def prec(Sigma):
        diag = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        return Sigma * dj[..., :, None] * dj[..., None, :], dj

    def s2(x1, b1, k1):
        Sigma, d = sigma_of(x1)
        A, dj = prec(Sigma)
        _, Li = blocked_chol_inv(A)
        return x1, b1 + 0.0 * Li[:, :, 0]

    t("s2 = s1 + f64 factor", s2)

    def s2tf(x1, b1, k1):
        Sigma, d = sigma_of(x1)
        A, dj = prec(Sigma)
        _, Li = tf_chol_factor(A)
        return x1, b1 + 0.0 * Li[:, :, 0]

    t("s2tf = s1 + tf factor", s2tf)

    def s3(x1, b1, k1):
        Sigma, d = sigma_of(x1)
        A, dj = prec(Sigma)
        _, Li = blocked_chol_inv(A)
        z = jr.normal(k1, (P, B), cdt)
        w = jnp.einsum("pij,pj->pi", Li, dj * d, precision="highest")
        base = dj * jnp.einsum("pji,pj->pi", Li, w + z, precision="highest")
        cols = jnp.concatenate([jnp.asarray(cm.gw_sin_ix),
                                jnp.asarray(cm.gw_cos_ix)], axis=1)
        ccl = jnp.clip(cols, 0, B - 1)
        djc = jnp.take_along_axis(dj, ccl, axis=1)
        Lic = jnp.take_along_axis(
            Li, jnp.broadcast_to(ccl[:, None, :], (P, B, ccl.shape[1])),
            axis=2) * djc[:, None, :]
        Corr = dj[:, :, None] * jnp.einsum("pji,pjm->pim", Li, Lic,
                                           precision="highest")
        return x1, b1 + 0.0 * (base[:, :, None] + Corr)[:, :, 0]

    t("s3 = s2 + base/Corr", s3)


if __name__ == "__main__":
    main()
