"""Per-block sweep profile without the full bench: warm up the 45-pulsar
CRN driver, then run profiling.profile_blocks at the requested chain width.

Usage: python tools/sweep_probe.py [--nchains 64] [--niter 250]
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=64)
    ap.add_argument("--niter", type=int, default=250)
    ap.add_argument("--adapt", type=int, default=300)
    args = ap.parse_args()

    import bench

    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta = bench.build_pta(45)
    x0 = pta.initial_sample(np.random.default_rng(0))
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=args.adapt, chunk_size=100,
                         nchains=args.nchains)
    cshape, bshape = drv.chain_shapes(args.niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    for _ in drv.run(x0, chain, bchain, 0, args.niter):
        pass
    times = profiling.profile_blocks(drv, drv.x_cur, repeats=3, inner=20)
    for k, v in sorted(times.items(), key=lambda kv: -kv[1]):
        print(f"  {k:<16s} {v*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
