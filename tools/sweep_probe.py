"""Per-block sweep profile without the full bench: warm up the 45-pulsar
driver, then run profiling.profile_blocks at the requested chain width.
``--orf hd`` profiles the correlated-ORF sweep (the sequential
cross-pulsar b-draw) instead of the CRN-only blocks — the entry point
behind the HD chain-width knee trace in docs/HD_MIXING.md.

Usage: python tools/sweep_probe.py [--nchains 64] [--niter 250]
                                   [--orf {crn,hd,...}]
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=64)
    ap.add_argument("--niter", type=int, default=250)
    ap.add_argument("--adapt", type=int, default=300)
    ap.add_argument("--orf", default="crn",
                    help="crn | hd | ... — hd profiles the sequential "
                    "cross-pulsar draw instead of the CRN-only blocks")
    args = ap.parse_args()

    import bench

    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta = bench.build_pta(45, orf=args.orf)
    x0 = pta.initial_sample(np.random.default_rng(0))
    if args.orf != "crn":
        from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
        ix = BlockIndex.build(pta.param_names)
        if len(ix.orf):
            x0[ix.orf] = 0.0
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=args.adapt, chunk_size=100,
                         nchains=args.nchains)
    cshape, bshape = drv.chain_shapes(args.niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    for _ in drv.run(x0, chain, bchain, 0, args.niter):
        pass
    report = profiling.profile_blocks(drv, drv.x_cur, repeats=3, inner=20)
    times = {k: v / 1e3 for k, v in report["per_block_ms"].items()}

    if args.orf == "crn":
        _crn_refresh_internals(drv, times)

    in_sweep = report["in_sweep"]
    for k, v in sorted(times.items(), key=lambda kv: -kv[1]):
        tag = "" if in_sweep.get(k, True) else "   [off-sweep]"
        print(f"  {k:<22s} {v*1e3:8.2f} ms{tag}")
    print(f"  {'sum(in-sweep)':<22s} {report['sum_blocks_ms']:8.2f} ms")
    print(f"  {'full_sweep':<22s} {report['full_sweep_ms']:8.2f} ms")
    print(f"  {'dispatch':<22s} {report['dispatch_ms']:8.2f} ms")


def _crn_refresh_internals(drv, times):
    # refresh internals: which of the segmented Gram / two-float factor /
    # log-density pieces carries draw_b_refresh's cost
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.ops.linalg import (_batched_diag,
                                                        jacobi_factor_mean,
                                                        tf_chol_factor)
    from pulsar_timing_gibbsspec_tpu.profiling import _scan_time
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb

    cm = drv.cm
    C = drv.C
    x = jnp.asarray(drv.x_cur, cm.cdtype)
    b = jnp.asarray(drv.b)

    def vm(single):
        def body(x, b, k):
            return jax.vmap(single)(x, b, jr.split(k, C))
        return body

    def seg1(x1, b1, k1):
        TNT, d = jb.tnt_d_seg(cm, cm.ndiag_fast(x1))
        return x1, b1 + 0.0 * TNT[:, : b1.shape[1], 0].astype(b1.dtype)

    def tf1(x1, b1, k1):
        TNT, d = jb.tnt_d_seg(cm, cm.ndiag_fast(x1))
        Sig = TNT + _batched_diag(1.0 / cm.phi(x1))
        L, Li, dj, mean = jacobi_factor_mean(
            Sig, d, factor=lambda A: tf_chol_factor(
                A, ridge=jb._PROP_RIDGE))
        return x1, b1 + 0.0 * mean.astype(b1.dtype)

    def lp1(x1, b1, k1):
        u1 = jb.b_matvec(cm, b1)
        lp = jb._logpi_b_per(cm, x1, b1, u1)
        return x1 + 0.0 * lp[0], b1

    times["refresh:tnt_d_seg"] = _scan_time(vm(seg1), x, b, 20, 3)
    times["refresh:seg+tf_factor"] = _scan_time(vm(tf1), x, b, 20, 3)
    times["refresh:logpi+matvec"] = _scan_time(vm(lp1), x, b, 20, 3)


if __name__ == "__main__":
    main()
