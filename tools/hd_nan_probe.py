"""Locate the first non-finite state of the scalable HD warmup at the
45-pulsar scale: step the warmup body one sweep at a time, checking
finiteness of (x, b) after each, then dissect the failing draw — which
block (non-GW draw / which frequency step) produced the first NaN and
what the local conditioning looked like.

Usage: [JAX_PLATFORMS=cpu] python tools/hd_nan_probe.py [--nchains 2]
       [--kernel freq|pulsar] [--nsweeps 60]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=2)
    ap.add_argument("--kernel", default="freq")
    ap.add_argument("--nsweeps", type=int, default=60)
    args = ap.parse_args()

    import bench

    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    jb.HD_SCALABLE_KERNEL = args.kernel
    pta = bench.build_pta(45, orf="hd")
    x0 = pta.initial_sample(np.random.default_rng(0))
    ix = BlockIndex.build(pta.param_names)
    if len(ix.orf):
        x0[ix.orf] = 0.0
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=300, nchains=args.nchains)
    cm = drv.cm
    C = drv.C
    body = drv._warmup_body()
    vbody = jax.jit(jax.vmap(body, in_axes=(0, 0, 0, None)))
    x = jnp.asarray(np.tile(np.asarray(x0)[None], (C, 1)), cm.cdtype)
    key = jr.key(7)
    b = jax.vmap(lambda k1: jb.draw_b_fn(cm, jnp.asarray(x0, cm.cdtype),
                                         k1))(jr.split(key, C))
    u = jax.vmap(lambda b1: jb.b_matvec(cm, b1))(b)
    aux = drv._aux()
    carry = (x, b, u)
    prev = None
    for t in range(args.nsweeps):
        kt = jr.fold_in(key, t)
        keys = jax.vmap(lambda c: jr.fold_in(kt, c))(jnp.arange(C))
        prev = tuple(np.asarray(v, np.float64) for v in carry[:2])
        carry, _ = vbody(carry, keys, aux, jnp.asarray(t, jnp.int32))
        xh = np.asarray(carry[0], np.float64)
        bh = np.asarray(carry[1], np.float64)
        okx, okb = np.isfinite(xh).all(), np.isfinite(bh).all()
        if not (okx and okb):
            print(f"first non-finite at sweep {t}: x ok={okx} b ok={okb}")
            bad = ~np.isfinite(bh)
            cc, pp, bbix = np.where(bad)
            print("bad b entries: chains", sorted(set(cc.tolist()))[:5],
                  "pulsars", sorted(set(pp.tolist()))[:10],
                  "cols", sorted(set(bbix.tolist()))[:20])
            # dissect: rerun just the b draw from the pre-sweep state
            xprev = jnp.asarray(prev[0], cm.cdtype)
            bprev = jnp.asarray(prev[1], cm.cdtype)
            # the warmup body draws b LAST with k[4]; reproduce per chain
            for c in range(C):
                k = jr.split(keys[c], 8)
                bnew = jb.draw_b_fn(cm, carry[0][c], k[4], bprev[c])
                fin = bool(np.isfinite(np.asarray(bnew)).all())
                if not fin:
                    _dissect(cm, carry[0][c], bprev[c], k[4])
                    break
            return
    print(f"all {args.nsweeps} sweeps finite at C={C} "
          f"kernel={args.kernel}")


def _dissect(cm, x, b, key):
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.ops.linalg import tf_chol_factor
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb

    print("dissecting failing draw...")
    cdt = cm.cdtype
    B, P, K = cm.Bmax, cm.P, cm.K
    N = cm.ndiag_fast(x)
    TNT, d = jb.tnt_d_seg(cm, N)
    phi = cm.phi(x)
    pinv = 1.0 / phi
    rho = 10.0 ** (2.0 * jnp.asarray(x, cdt)[cm.rho_ix_x])
    print("rho range:", float(rho.min()), float(rho.max()))
    Ginv = cm.orf_ginv_k(x).astype(cdt)
    gsin = jnp.asarray(cm.gw_sin_ix)
    gcos = jnp.asarray(cm.gw_cos_ix)
    cols = jnp.concatenate([gsin, gcos], axis=1)
    valid = ((cols >= 0) & (cols < B)).astype(cdt)
    ccl = jnp.clip(cols, 0, B - 1)
    rows_p = jnp.arange(P)[:, None]
    gwm = jnp.zeros((P, B), cdt).at[rows_p, ccl].max(valid)
    nm = 1.0 - gwm
    Sigma = TNT + (pinv * nm)[:, :, None] * jnp.eye(B, dtype=cdt)
    Sn = Sigma * nm[:, :, None] * nm[:, None, :] \
        + gwm[:, :, None] * jnp.eye(B, dtype=cdt)
    diag = jnp.diagonal(Sn, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sn * dj[:, :, None] * dj[:, None, :]
    _, Li = tf_chol_factor(A)
    print("block1 Li finite:", bool(np.isfinite(np.asarray(Li)).all()))
    evs = np.linalg.eigvalsh(np.asarray(A, np.float64))
    print("block1 A lambda_min per-pulsar min:", float(evs.min()))

    # per-frequency systems
    rsin = jnp.asarray(cm.red_sin_ix)
    rcos = jnp.asarray(cm.red_cos_ix)
    Kr = int(rsin.shape[1])
    m = 4 if Kr else 2
    for k in range(K):
        gc = [np.asarray(jnp.take(gsin, k, axis=1)),
              np.asarray(jnp.take(gcos, k, axis=1))]
        if m == 4:
            kr = min(k, Kr - 1)
            gc += [np.asarray(rsin[:, kr]), np.asarray(rcos[:, kr])]
        c4 = np.clip(np.stack(gc, 1), 0, B - 1)
        v4 = np.stack([(g >= 0) & (g < B) for g in gc], 1).astype(float)
        TNTh = np.asarray(TNT, np.float64)
        Tr = np.take_along_axis(TNTh, c4[:, :, None], axis=1) \
            * v4[:, :, None]
        T4 = np.take_along_axis(Tr, np.repeat(c4[:, None, :], m, 1),
                                axis=2) * v4[:, None, :]
        Dg = np.asarray(Ginv[k], np.float64) / float(rho[k])
        Q = np.zeros((m * P, m * P))
        pr = np.asarray(pinv, np.float64)
        for i in range(m):
            for j in range(m):
                blk = np.diag(T4[:, i, j])
                if i == j:
                    if i < 2:
                        vi = v4[:, i]
                        blk = blk + Dg * np.outer(vi, vi) \
                            + np.diag(1.0 - vi)
                    else:
                        pri = np.take_along_axis(pr, c4[:, i][:, None],
                                                 1)[:, 0]
                        blk = blk + np.diag(np.where(v4[:, i] > 0, pri,
                                                     1.0))
                Q[i * P:(i + 1) * P, j * P:(j + 1) * P] = blk
        qj = 1.0 / np.sqrt(np.diagonal(Q))
        Aq = Q * qj[:, None] * qj[None, :]
        ev = np.linalg.eigvalsh(Aq)
        _, Lq = tf_chol_factor(jnp.asarray(Aq, cdt))
        print(f"k={k}: lambda_min={ev.min():.3e} lambda_max={ev.max():.3e}"
              f" tf finite={bool(np.isfinite(np.asarray(Lq)).all())}")


if __name__ == "__main__":
    main()
