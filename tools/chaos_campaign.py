"""Seeded randomized chaos campaign for the multi-tenant serving tier.

The blast-radius contract under test (docs/RESILIENCE.md): tenant rows
of the multiplexed sweep are independent conditional chains, so ANY
injected misbehavior — a poisoned tenant, a mid-chunk crash, a lost
device — must stay confined to its victim while every other tenant's
chain remains **bitwise identical** to an uninterrupted solo run.

Each seed draws a randomized fault schedule (reproducible:
``default_rng([campaign_seed, seed])``) over a 4-tenant run through one
shared bucket:

- ``poison``       NaN-poison one tenant's chunk rows (the quarantine
                   drill; fires once, victim replays clean from its
                   verified checkpoint)
- ``evict``        tenant-targeted eviction at the victim's Nth chunk
- ``crash``        injected crash at the chunk seam (service retry)
- ``xla``          injected XlaRuntimeError at the seam (service retry)
- ``stall``        short injected sleep at the seam (latency, no error)
- ``device_loss``  injected DeviceLost → full evacuation through
                   verified checkpoints and re-admission
- ``storm``        a fifth, cold-shape tenant (second bucket) submitted
                   under admission control with a tight compile-storm
                   window (full campaign only — its compile is a
                   one-time cost across the whole campaign)

Two deterministic transport legs run after the seeds in EVERY mode:
the gateway kill/restart/reattach drill (``_gateway_drill``) and the
standing-model append/migration drill (``_append_drill``) — a kill at
any migration seam must recover to the parent or the child generation,
never a torn hybrid, with co-residents bitwise untouched.

A multigroup leg then runs in EVERY mode (including --quick): two
``(bucket, signature)`` groups resident concurrently on disjoint
placement slices, with every seeded fault (device loss, poison, crash,
storm) aimed at slice 0 only.  The co-resident group on slice 1 must
finish bitwise vs its solo baseline — the fault-domain claim of the
placement engine — with per-slice loss counters confirming the blast
radius never crossed the slice boundary, ≥2 groups concurrently
resident, and pre-warming under a storm capped so it never starves a
resident.  A final multigroup gateway drill kills the scheduler with
two groups journaled and requires the restarted incarnation to re-route
each group to its own slice and finish both bitwise with zero orphans.

Invariants checked after EVERY seed:

1. every job reaches ``done`` and its chain/bchain is bitwise equal to
   its solo baseline (co-resident isolation AND victim recovery);
2. quarantine latency ≤ 1 chunk: each poison that actually FIRED (read
   off the fault handle — churn/evacuation can reset a victim's chunk
   clock below a scheduled threshold, leaving the fault armed but
   inert) produces exactly one quarantine event for its victim —
   detection happened on the poisoned chunk itself, since a missed
   chunk would leak NaNs into the chain and break invariant 1;
3. zero unplanned steady retraces (``recompile_counter``): churn,
   quarantine and evacuation all reuse or deliberately rebuild
   programs — no silent jit cache misses;
4. gauge consistency: the ``quarantines`` counter matches the log, the
   ``evacuations`` counter matches the fired device losses, retries
   stay within budget, and the queue fully drained.

Baselines and compiled programs are shared across seeds (one
``ProgramCache``), so the marginal cost of a seed is dispatch, not XLA.

Usage: python tools/chaos_campaign.py [--seeds N] [--quick]
       [--campaign-seed N] [--outdir DIR] [--json]
Exit status 0 when every seed holds every invariant, 1 otherwise.
``--quick --seeds 5`` is the optional ci_lint layer (``--chaos``).
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
from pathlib import Path

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")

NITER = 12
TENANTS = ((24, 0), (28, 1), (32, 2), (36, 3))
STORM_TENANT = (44, 9)       # routes to the second (cold) bucket


def _models():
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    def mk(ntoa, seed):
        return build_model(
            synthetic_pulsars(2, ntoa, tm_cols=3, seed=seed), 3)

    return [mk(*t) for t in TENANTS], mk(*STORM_TENANT)


def _table():
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (BucketSpec,
                                                           BucketTable)

    return BucketTable([BucketSpec(2, 40, 24, 3),
                        BucketSpec(2, 48, 24, 3)])


def _service(root, cache, **kw):
    from pulsar_timing_gibbsspec_tpu.serve import SamplerService

    kw.setdefault("slots", 4)
    kw.setdefault("chunk", 4)
    kw.setdefault("quantum", 100)
    kw.setdefault("save_every", 1)
    return SamplerService(root, _table(), cache=cache, **kw)


def _solo_baselines(root, cache, ptas):
    """Uninterrupted single-tenant runs — the bitwise ground truth."""
    out = []
    for i, pta in enumerate(ptas):
        svc = _service(root / f"solo{i}", cache)
        job = svc.submit(pta, NITER, job_id=f"solo{i}", tenant_id=i)
        svc.run()
        if job.state != "done":
            raise RuntimeError(f"solo baseline {i} failed: {job.failure}")
        out.append((job.chain.copy(), job.bchain.copy()))
    return out


def _draw_schedule(rng, quick):
    """A reproducible fault schedule: (kind, kwargs) pairs, bounded so
    the service budgets (max_retries=2, quarantine_max=2, evac_max=2)
    are never exceeded by construction — the campaign tests isolation,
    not budget exhaustion (tests/test_quarantine.py covers that)."""
    kinds = ["poison", "evict", "crash", "xla", "stall"]
    if not quick:
        kinds += ["device_loss", "storm"]
    n = 1 if quick else int(rng.integers(1, 4))
    sched, retryable, lost, per_tenant_poison = [], 0, 0, {}
    for _ in range(n):
        kind = str(rng.choice(kinds))
        if kind in ("crash", "xla", "stall") and retryable >= 2:
            kind = "evict"
        if kind == "device_loss" and lost >= 1:
            kind = "poison"
        tenant = int(rng.integers(0, len(TENANTS)))
        at = int(rng.integers(1, 3))
        if kind == "poison":
            if per_tenant_poison.get(tenant, 0) >= 2:
                kind = "evict"
            else:
                per_tenant_poison[tenant] = \
                    per_tenant_poison.get(tenant, 0) + 1
        if kind in ("crash", "xla", "stall"):
            retryable += 1
        if kind == "device_loss":
            lost += 1
        sched.append((kind, {"tenant": tenant, "at": at}))
    return sched


def _arm(sched):
    """Arm the schedule; returns the live fault handles (parallel to
    ``sched``, None for kinds with no registry entry).  The handles
    outlive ``faults.clear()`` — their ``fired`` counters are how the
    invariants distinguish a fault that actually fired from one whose
    trigger never came up (e.g. a poison whose victim-clock threshold
    became unreachable after an evacuation reset ``chunks_resident``)."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    handles = []
    for kind, kw in sched:
        if kind == "poison":
            handles.append(faults.inject(
                "poison_rows", tenant=kw["tenant"],
                at_row=kw["at"], times=1))
        elif kind == "evict":
            handles.append(faults.inject(
                "tenant_evict", point="serve.chunk",
                tenant=kw["tenant"], at_row=kw["at"], times=1))
        elif kind == "crash":
            handles.append(faults.inject(
                "crash", point="serve.chunk", at_row=kw["at"] + 1,
                times=1))
        elif kind == "xla":
            handles.append(faults.inject(
                "xla_error", point="serve.chunk", at_row=kw["at"] + 1,
                times=1))
        elif kind == "stall":
            handles.append(faults.inject(
                "stall", point="serve.chunk", at_row=kw["at"] + 1,
                seconds=0.02, times=1))
        elif kind == "device_loss":
            handles.append(faults.inject(
                "device_loss", point="serve.chunk", at_row=kw["at"] + 1,
                times=1, devices=1))
        else:
            handles.append(None)      # storm: no registry entry
    return handles


def _run_seed(seed, args, root, cache, ptas, storm_pta, solos,
              storm_solo):
    """One seeded drill.  Returns (record, failure list)."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    rng = np.random.default_rng([args.campaign_seed, seed])
    sched = _draw_schedule(rng, args.quick)
    with_storm = any(k == "storm" for k, _ in sched)
    fails = []

    kw = {}
    if bool(rng.integers(0, 2)):
        # half the seeds run with per-tenant breakers on a short
        # cooldown: re-admission must still converge to bitwise
        kw["breaker"] = {"window": 4, "threshold": 1.0,
                        "min_events": 1, "cooldown_s": 0.01}
    if with_storm:
        kw["admission"] = {"max_queue": 16, "storm_compiles": 1,
                           "storm_window_s": 0.1}
    svc = _service(root / f"seed{seed}", cache, **kw)
    faults.clear()
    handles = _arm(sched)
    jobs = []
    try:
        with recompile_counter() as rc:
            rc.phase("steady")
            for i, pta in enumerate(ptas):
                jobs.append(svc.submit(pta, NITER, job_id=f"job{i}",
                                       tenant_id=i))
            if with_storm:
                jobs.append(svc.submit(storm_pta, NITER,
                                       job_id="storm",
                                       tenant_id=len(TENANTS)))
            report = svc.run()
    except Exception as exc:                      # noqa: BLE001
        faults.clear()
        return {"seed": seed, "schedule": sched,
                "error": repr(exc)}, [f"seed {seed}: run raised {exc!r}"]
    finally:
        faults.clear()

    # 1. completion + bitwise isolation/recovery for EVERY tenant
    refs = list(solos) + ([storm_solo] if with_storm else [])
    for i, job in enumerate(jobs):
        if job.state != "done":
            fails.append(f"seed {seed}: {job.job_id} state={job.state!r}"
                         f" ({job.failure})")
            continue
        ref_c, ref_b = refs[i]
        if not (np.array_equal(job.chain, ref_c)
                and np.array_equal(job.bchain, ref_b)):
            fails.append(f"seed {seed}: {job.job_id} chain diverged "
                         "from its solo baseline (blast radius leaked)")

    # 2. each FIRED poison → exactly one quarantine of its victim.
    # Firing is read off the fault handles, not the schedule: a poison's
    # victim clock (chunks_resident) legitimately resets when churn or
    # an evacuation re-admits the victim, so a scheduled threshold can
    # become unreachable — an unfired poison is a no-op, not a missed
    # detection (invariant 1 still proves the chains stayed clean).
    fired_poison = [kw_ for (k, kw_), h in zip(sched, handles)
                    if k == "poison" and h is not None and h.fired]
    unfired = sum(1 for (k, _), h in zip(sched, handles)
                  if h is not None and not h.fired)
    qlog = report["quarantine_log"]
    if len(qlog) != len(fired_poison):
        fails.append(f"seed {seed}: {len(fired_poison)} poison(s) fired "
                     f"but {len(qlog)} quarantine(s) logged — detection "
                     "missed the poisoned chunk")
    victims = sorted(kw_["tenant"] for kw_ in fired_poison)
    logged = sorted(ev["tenant_id"] for ev in qlog)
    if victims != logged:
        fails.append(f"seed {seed}: quarantined tenants {logged} != "
                     f"poisoned tenants {victims}")

    # 3. no unplanned steady retraces
    unplanned = rc.unplanned("steady")
    if unplanned:
        fails.append(f"seed {seed}: {unplanned} unplanned steady "
                     "retrace(s)")

    # 4. counter/gauge consistency (device losses also counted as
    # actually fired, same reasoning as invariant 2)
    n_loss = sum(1 for (k, _), h in zip(sched, handles)
                 if k == "device_loss" and h is not None and h.fired)
    if report["quarantines"] != len(qlog):
        fails.append(f"seed {seed}: quarantines counter "
                     f"{report['quarantines']} != log {len(qlog)}")
    if report["evacuations"] != n_loss:
        fails.append(f"seed {seed}: evacuations {report['evacuations']} "
                     f"!= injected device losses {n_loss}")
    if report["service_retries"] > 2:
        fails.append(f"seed {seed}: retry budget exceeded "
                     f"({report['service_retries']})")
    if svc.queue:
        fails.append(f"seed {seed}: queue not drained "
                     f"({len(svc.queue)} left)")

    rec = {"seed": seed, "schedule": sched,
           "quarantines": report["quarantines"],
           "evacuations": report["evacuations"],
           "evictions": report["evictions"],
           "retries": report["service_retries"],
           "chunks": report["chunks"],
           "unplanned_retraces": unplanned,
           "unfired_faults": unfired,
           "ok": not fails}
    return rec, fails


def _http(method, url, body=None, headers=None, timeout=30):
    """Tiny stdlib client: (status, raw bytes)."""
    import urllib.error
    import urllib.request

    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=dict(headers or {}))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


def _poll_stream(base, job_id, cursor, dedupe, deadline_s=60.0):
    """Poll the cursor stream until the job is terminal; returns
    (rows, final_state, cursor).  This is the RECONNECTING client: each
    request stands alone, so it works identically across a gateway
    restart."""
    import time

    rows, state = [], None
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        st, raw = _http(
            "GET", f"{base}/v1/jobs/{job_id}/stream?cursor={cursor}"
            "&wait=2", headers={"x-ptgibbs-dedupe-key": dedupe})
        if st != 200:
            raise RuntimeError(f"stream HTTP {st}: {raw[:200]!r}")
        for line in raw.splitlines():
            if not line.strip():
                continue
            ev = json.loads(line)
            rows.extend(ev.get("rows") or [])
            cursor = max(cursor, int(ev.get("cursor", cursor)))
            state = ev.get("state", state)
            if ev.get("final"):
                return rows, state, cursor
    raise RuntimeError(f"stream did not reach a terminal state in "
                       f"{deadline_s}s (cursor {cursor}, state {state})")


def _gateway_drill(root, cache):
    """The transport leg: every serving-tier contract driven through
    the HTTP boundary under injected transport faults.

    Asserts, end to end: duplicate submissions (injected ``dup_submit``
    replay AND a real client retry) never double-admit; ``gateway_kill``
    mid-stream → restart → the client reattaches with its cursor and
    the assembled stream is BITWISE equal to the uninterrupted solo
    run; a reattach with the wrong dedupe credential refuses
    (``STREAM_CROSSING``); an expired client deadline drains through a
    verified checkpoint while co-residents finish untouched; a stalled
    live consumer is shed without blocking sampling; zero unplanned
    steady retraces; zero orphaned jobs in the final journal."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import (faults, integrity,
                                                     preemption, telemetry)
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import HttpTransport
    import time

    fails = []
    svc_kw = dict(slots=2, chunk=4, quantum=100, save_every=1,
                  cache=cache)
    payload = {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                             "seed": 0, "nmodes": 3}}

    # solo ground truth: the gateway assigns tenant 0 to its first
    # submission, and streams are pure in (service_seed, tenant_id,
    # iteration) — so an in-process solo run IS the bitwise reference
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    gniter = 4 * NITER
    solo_pta = build_model(synthetic_pulsars(2, 24, tm_cols=3, seed=0), 3)
    solo_svc = _service(root / "gwsolo", cache, slots=2)
    solo_job = solo_svc.submit(solo_pta, gniter, job_id="gwsolo",
                               tenant_id=0)
    solo_svc.run()
    if solo_job.state != "done":
        return [f"gateway: solo baseline failed ({solo_job.failure})"]
    solo_rows = np.asarray(solo_job.chain[:gniter], np.float64).copy()

    preemption.reset()
    faults.clear()
    shed0 = telemetry.get("shed_streams")
    gw = tx = None
    try:
        with recompile_counter() as rc:
            rc.phase("steady")
            gw = Gateway(root / "gw", _table(), svc_kw=svc_kw,
                         shed_lag=2, stop_when_idle=False).start()
            tx = HttpTransport(gw)
            tx.start()
            host, port = tx.address
            base = f"http://{host}:{port}"

            # -- idempotent submission under an injected duplicate
            faults.inject("dup_submit", point="wire.submit", times=1)
            st, raw = _http("POST", f"{base}/v1/jobs", body={
                "dedupe_key": "gwjob", "payload": payload,
                "niter": gniter})
            h1 = json.loads(raw)
            if st != 200:
                return [f"gateway: submit HTTP {st}: {raw[:200]!r}"]
            if not h1.get("replayed"):
                fails.append("gateway: injected dup_submit did not "
                             "resolve through the dedupe journal")
            # a real client retry (the lost-ACK path) — same handle
            st, raw = _http("POST", f"{base}/v1/jobs", body={
                "dedupe_key": "gwjob", "payload": payload,
                "niter": gniter})
            h2 = json.loads(raw)
            if h2.get("job_id") != h1.get("job_id") \
                    or not h2.get("replayed"):
                fails.append("gateway: client retry double-admitted "
                             f"({h1.get('job_id')} vs {h2.get('job_id')})")
            if len(gw.svc.jobs) != 1:
                fails.append(f"gateway: {len(gw.svc.jobs)} jobs admitted "
                             "for one dedupe key")
            jid = h1["job_id"]

            # -- kill the gateway mid-stream: arm the kill a couple of
            # scheduler steps out, then read a live prefix until the
            # stream dies under us (DRAINING final / rows so far)
            faults.inject("gateway_kill", point="gateway.step",
                          at_row=gw._steps + 2, times=1)
            rows = []
            cursor = 0
            st, raw = _http(
                "GET", f"{base}/v1/jobs/{jid}/stream?cursor=0&wait=5",
                headers={"x-ptgibbs-dedupe-key": "gwjob"})
            for line in raw.splitlines():
                if line.strip():
                    ev = json.loads(line)
                    rows.extend(ev.get("rows") or [])
                    cursor = max(cursor, int(ev.get("cursor", 0)))
            t0 = time.monotonic()
            while gw.alive() and time.monotonic() - t0 < 30:
                time.sleep(0.02)
            if gw.alive():
                fails.append("gateway: injected gateway_kill did not "
                             "stop the scheduler")
            tx.stop()

            # -- restart: journal reload, cursor reattach, finish
            gw2 = Gateway(root / "gw", _table(), svc_kw=svc_kw,
                          shed_lag=2, stop_when_idle=False).start()
            tx2 = HttpTransport(gw2)
            tx2.start()
            gw, tx = gw2, tx2
            host, port = tx2.address
            base = f"http://{host}:{port}"
            # stream-crossing refusal: wrong reattach credential
            st, raw = _http(
                "GET", f"{base}/v1/jobs/{jid}/stream?cursor={cursor}",
                headers={"x-ptgibbs-dedupe-key": "not-the-key"})
            if st != 409:
                fails.append("gateway: stream-crossing reattach was "
                             f"not refused (HTTP {st})")
            tail, state, cursor = _poll_stream(base, jid, cursor,
                                               "gwjob")
            rows.extend(tail)
            if state != "done":
                fails.append(f"gateway: job ended {state!r} after "
                             "restart, not done")
            got = np.asarray(rows, np.float64)
            if got.shape != solo_rows.shape \
                    or not np.array_equal(got, solo_rows):
                fails.append(
                    "gateway: reattached stream is not bitwise equal "
                    f"to the solo run (got {got.shape}, want "
                    f"{solo_rows.shape})")

            # -- deadline propagation: expires → verified-checkpoint
            # drain; the co-resident shed job below keeps sampling
            # niter is sized so the deadline reliably lands mid-run
            # (save_every=1 writes a verified checkpoint every chunk)
            st, raw = _http("POST", f"{base}/v1/jobs", body={
                "dedupe_key": "gwdl", "payload": payload,
                "niter": 20_000, "deadline_ms": 600})
            dl = json.loads(raw)
            if st != 200:
                fails.append(f"gateway: deadline submit HTTP {st}")

            # -- slow-client shedding on a live stream
            faults.inject("slow_client", point="wire.stream",
                          seconds=0.25, times=4)
            st, raw = _http("POST", f"{base}/v1/jobs", body={
                "dedupe_key": "gwshed", "payload": payload,
                "niter": 2 * NITER})
            sh = json.loads(raw)
            st, raw = _http(
                "GET", f"{base}/v1/jobs/{sh['job_id']}/stream"
                "?cursor=0&live=1",
                headers={"x-ptgibbs-dedupe-key": "gwshed"},
                timeout=60)
            evs = [json.loads(x) for x in raw.splitlines() if x.strip()]
            if not any(e.get("error") == "STREAM_SHED" for e in evs):
                fails.append("gateway: stalled live stream was not shed")
            if telemetry.get("shed_streams") <= shed0:
                fails.append("gateway: shed_streams counter did not move")
            # the shed client reattaches by cursor and still gets
            # every row
            cur = max(int(e.get("cursor", 0)) for e in evs)
            srows, sstate, _ = _poll_stream(base, sh["job_id"],
                                            0, "gwshed")
            if sstate != "done" or len(srows) != 2 * NITER:
                fails.append(f"gateway: shed job ended {sstate!r} with "
                             f"{len(srows)} rows")
            _ = cur

            # -- the expired job drained through a VERIFIED checkpoint
            t0 = time.monotonic()
            dstate = None
            while time.monotonic() - t0 < 30:
                st, raw = _http("GET", f"{base}/v1/jobs/{dl['job_id']}")
                dstate = json.loads(raw).get("state")
                if dstate == "expired":
                    break
                time.sleep(0.05)
            if dstate != "expired":
                fails.append(f"gateway: deadline job state {dstate!r}, "
                             "never expired")
            else:
                ent = gw.report()["entries"]["gwdl"]
                outdir = Path(ent["outdir"])
                if (outdir / "manifest.json").exists():
                    if not integrity.verify(outdir)["ok"]:
                        fails.append("gateway: expired job checkpoint "
                                     "fails verification")

            # -- zero orphans: every journal entry terminal, queue empty
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30 and not gw._all_settled():
                time.sleep(0.05)
            ents = gw.report()["entries"]
            bad = {k: e["state"] for k, e in ents.items()
                   if e["state"] not in ("done", "expired")}
            if bad:
                fails.append(f"gateway: orphaned journal entries {bad}")
            if gw.svc.queue:
                fails.append(f"gateway: queue not drained "
                             f"({len(gw.svc.queue)} left)")

            # teardown through the front door: the graceful-drain path
            # is part of the contract, so exercise it rather than
            # abandoning a daemon scheduler
            preemption.request_drain(reason="gateway_drill_teardown")
            gw.join(timeout=30)
            if gw.alive() or gw.state != "stopped":
                fails.append("gateway: graceful drain did not park the "
                             f"scheduler (state {gw.state!r})")
        unplanned = rc.unplanned("steady")
        if unplanned:
            fails.append(f"gateway: {unplanned} unplanned steady "
                         "retrace(s) across kill/restart")
    finally:
        faults.clear()
        preemption.reset()
        if tx is not None:
            tx.stop()
    return fails


def _append_drill(root, cache):
    """The standing-model leg: an append-TOAs migration driven through
    the gateway core, killed at EVERY migration seam in turn.

    Per seam (``migrate.pre_journal`` / ``post_journal`` / ``mid_repad``
    / ``pre_readmit``): a parent plus an untouched co-resident run to
    done, the append is killed at the seam (HTTP 500), the gateway
    drains gracefully, a fresh incarnation restarts from the journal,
    and the client's dedupe-keyed replay lands on the ORIGINAL child
    handle (or binds fresh when the kill preceded the journal write).
    Asserts: the child completes at generation 1; the retained-row
    prefix is **bitwise** the parent's chain through the re-bucketing;
    the co-resident is bitwise its solo baseline (blast radius); the
    parent entry is ``superseded``, every entry settled (zero orphaned
    journal entries); zero unplanned steady retraces.  Then the race
    and corruption legs: an append arriving during a drain refuses
    typed (503, nothing bound), and a severed lineage hash chain
    degrades resolution to the newest verified ancestor."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import (faults, lineage,
                                                     preemption)
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest
    import time

    fails = []
    svc_kw = dict(slots=2, chunk=4, quantum=100, save_every=1,
                  cache=cache)
    payload = {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                             "seed": 0, "nmodes": 3}}
    co_payload = {"synthetic": {"n_psr": 2, "ntoa": 28, "tm_cols": 3,
                                "seed": 1, "nmodes": 3}}
    append_spec = {"add": 20, "seed": 7}    # ntoa 24 -> 44: rebucket
    append_body = {"dedupe_key": "apd", "parent": "par",
                   "append": append_spec, "niter": 2 * NITER}

    def post(gw, path, body):
        resp = gw.handle(WireRequest("POST", path, {}, {},
                                     json.dumps(body).encode()))
        return resp.status, resp.body or {}

    def wait_entries(gw, want, deadline_s=60.0):
        """Poll the journal until each dedupe key reaches its state."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            ents = gw.report()["entries"]
            if all(ents.get(k, {}).get("state") == s
                   for k, s in want.items()):
                return ents
            time.sleep(0.02)
        return gw.report()["entries"]

    def shutdown(gw, tag):
        preemption.request_drain(reason=f"append_drill_{tag}")
        gw.join(timeout=30)
        preemption.reset()
        if gw.alive() or gw.state != "stopped":
            fails.append(f"append[{tag}]: graceful drain did not park "
                         f"the scheduler (state {gw.state!r})")

    # solo ground truth for the co-resident (the gateway assigns
    # tenant 1 to its second submission; streams are pure in
    # (service_seed, tenant_id, iteration))
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    co_pta = build_model(synthetic_pulsars(2, 28, tm_cols=3, seed=1), 3)
    co_svc = _service(root / "apsolo", cache, slots=2)
    co_job = co_svc.submit(co_pta, NITER, job_id="apsolo", tenant_id=1)
    co_svc.run()
    if co_job.state != "done":
        return [f"append: co-resident solo baseline failed "
                f"({co_job.failure})"]
    co_solo = co_job.chain.copy()

    SEAMS = ("migrate.pre_journal", "migrate.post_journal",
             "migrate.mid_repad", "migrate.pre_readmit")
    preemption.reset()
    faults.clear()
    child_out = parent_out = None
    try:
        with recompile_counter() as rc:
            rc.phase("steady")
            for i, seam in enumerate(SEAMS):
                r = root / f"ap{i}"
                gw = Gateway(r, _table(), svc_kw=svc_kw,
                             stop_when_idle=False).start()
                st, _ = post(gw, "/v1/jobs", {
                    "dedupe_key": "par", "payload": payload,
                    "niter": NITER})
                st2, _ = post(gw, "/v1/jobs", {
                    "dedupe_key": "co", "payload": co_payload,
                    "niter": NITER})
                if st != 200 or st2 != 200:
                    fails.append(f"append[{seam}]: submits HTTP "
                                 f"{st}/{st2}")
                    shutdown(gw, seam)
                    continue
                ents = wait_entries(gw, {"par": "done", "co": "done"})
                if ents.get("par", {}).get("state") != "done":
                    fails.append(f"append[{seam}]: parent never "
                                 "finished")
                    shutdown(gw, seam)
                    continue

                # kill at the seam: the append must die typed, binding
                # either nothing or a journaled forking intent — never
                # a torn child
                faults.inject("kill_mid_migration", point=seam, times=1)
                st, body = post(gw, "/v1/append", append_body)
                faults.clear()
                if st != 500:
                    fails.append(f"append[{seam}]: seam kill returned "
                                 f"HTTP {st}, expected 500")
                shutdown(gw, f"{seam}_kill")

                # fresh incarnation + the client's dedupe-keyed replay
                gw2 = Gateway(r, _table(), svc_kw=svc_kw,
                              stop_when_idle=False).start()
                st, body = post(gw2, "/v1/append", append_body)
                if st != 200:
                    fails.append(f"append[{seam}]: replay after "
                                 f"restart HTTP {st}: {body}")
                    shutdown(gw2, seam)
                    continue
                want_replay = seam != "migrate.pre_journal"
                if bool(body.get("replayed")) != want_replay:
                    fails.append(
                        f"append[{seam}]: replayed="
                        f"{body.get('replayed')} (a kill "
                        + ("after" if want_replay else "before")
                        + " the journal write must "
                        + ("replay the original handle"
                           if want_replay else "bind fresh"))
                if int(body.get("generation", -1)) != 1:
                    fails.append(f"append[{seam}]: child generation "
                                 f"{body.get('generation')}, not 1")
                ents = wait_entries(gw2, {"apd": "done"})
                if ents.get("apd", {}).get("state") != "done":
                    fails.append(f"append[{seam}]: child never "
                                 "finished after replay")
                if ents.get("par", {}).get("state") != "superseded":
                    fails.append(
                        f"append[{seam}]: parent state "
                        f"{ents.get('par', {}).get('state')!r}, "
                        "not superseded")
                orphans = {k: e["state"] for k, e in ents.items()
                           if e["state"] not in ("done", "superseded")}
                if orphans:
                    fails.append(f"append[{seam}]: orphaned journal "
                                 f"entries {orphans}")

                parent_out = Path(ents["par"]["outdir"])
                child_out = Path(ents["apd"]["outdir"])
                pchain = np.load(parent_out / "chain.npy")
                cchain = np.load(child_out / "chain.npy")
                if not np.array_equal(cchain[:NITER], pchain):
                    fails.append(f"append[{seam}]: retained prefix is "
                                 "not bitwise through the migration")
                co_chain = np.load(Path(ents["co"]["outdir"])
                                   / "chain.npy")
                if not np.array_equal(co_chain, co_solo):
                    fails.append(f"append[{seam}]: co-resident "
                                 "diverged from its solo baseline "
                                 "(migration blast radius leaked)")

                if seam == SEAMS[-1]:
                    # the drain race: an append that arrives after the
                    # drain began refuses typed, binding nothing
                    faults.inject("append_during_drain",
                                  point="gateway.append", times=1)
                    st, body = post(gw2, "/v1/append", {
                        "dedupe_key": "apd2", "parent": "apd",
                        "append": {"add": 4, "seed": 9},
                        "niter": 2 * NITER})
                    faults.clear()
                    if st != 503 or body.get("error") != "DRAINING":
                        fails.append(
                            f"append: append-during-drain got HTTP "
                            f"{st} {body.get('error')!r}, want "
                            "503 DRAINING")
                    if "apd2" in gw2.report()["entries"]:
                        fails.append("append: a refused drain-race "
                                     "append was journaled anyway")
                shutdown(gw2, seam)
        unplanned = rc.unplanned("steady")
        if unplanned:
            fails.append(f"append: {unplanned} unplanned steady "
                         "retrace(s) across the migration drills")
    finally:
        faults.clear()
        preemption.reset()

    # the corruption leg (pure on-disk): sever the child's lineage
    # hash chain — both manifests, so .bak cannot heal it — and the
    # resolver must degrade to the newest verified ancestor
    if child_out is not None and not fails:
        faults._corrupt_lineage(child_out)
        try:
            degraded, report = lineage.resolve_verified(child_out)
        except lineage.LineageError as exc:
            fails.append(f"append: corrupted lineage did not degrade "
                         f"to an ancestor ({exc})")
        else:
            if str(degraded) != str(parent_out):
                fails.append(
                    f"append: corrupted generation resolved to "
                    f"{degraded}, not the verified parent "
                    f"{parent_out}")
            if not (report and report[0]["ok"] is False
                    and report[-1]["ok"] is True):
                fails.append(f"append: degrade report malformed: "
                             f"{report}")
    return fails


MG_GROUP_A = ((24, 0), (28, 1))    # first bucket's group (slice 0)
MG_GROUP_B = ((44, 2), (46, 3))    # second bucket's group (slice 1)
MG_STORM = (52, 9)                 # third, cold bucket (storm/pre-warm)


def _mg_table():
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (BucketSpec,
                                                           BucketTable)

    return BucketTable([BucketSpec(2, 40, 24, 3),
                        BucketSpec(2, 48, 24, 3),
                        BucketSpec(2, 56, 24, 3)])


def _mg_service(root, cache, **kw):
    """Two-slice placement service (two slots each, unplaced — bitwise
    holds regardless of slot geometry, so solo baselines and seeded
    runs compare exactly)."""
    from pulsar_timing_gibbsspec_tpu.serve import SamplerService

    kw.setdefault("chunk", 4)
    kw.setdefault("quantum", 100)
    kw.setdefault("save_every", 1)
    kw.setdefault("placement", [{"slots": 2}, {"slots": 2}])
    return SamplerService(root, _mg_table(), cache=cache, **kw)


def _mg_models():
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    def mk(ntoa, seed):
        return build_model(
            synthetic_pulsars(2, ntoa, tm_cols=3, seed=seed), 3)

    return ([mk(*t) for t in MG_GROUP_A],
            [mk(*t) for t in MG_GROUP_B], mk(*MG_STORM))


def _mg_solos(root, cache, ptas_a, ptas_b, storm_pta):
    """Solo baselines in the SAME two-slice geometry (shares the
    slots=2 multiplexed programs with every seeded run)."""
    out = {}
    pairs = list(zip(ptas_a, MG_GROUP_A)) + list(zip(ptas_b, MG_GROUP_B))
    pairs.append((storm_pta, MG_STORM))
    for pta, (_, tenant) in pairs:
        svc = _mg_service(root / f"mgsolo{tenant}", cache)
        job = svc.submit(pta, NITER, job_id=f"mgsolo{tenant}",
                         tenant_id=tenant)
        svc.run()
        if job.state != "done":
            raise RuntimeError(
                f"multigroup solo baseline (tenant {tenant}) failed: "
                f"{job.failure}")
        out[tenant] = (job.chain.copy(), job.bchain.copy())
    return out


def _mg_schedule(rng, quick):
    """A seeded fault draw targeting SLICE 0 (group A) while group B is
    co-resident on slice 1.  Bounded like :func:`_draw_schedule`: one
    slice-targeted device loss max (replace budget), one poison per
    victim, retryable crashes within the service budget."""
    kinds = ["device_loss", "poison", "crash", "storm"]
    n = 1 if quick else int(rng.integers(1, 3))
    sched, lost, crashed, poisoned = [], 0, 0, set()
    for _ in range(n):
        kind = str(rng.choice(kinds))
        if kind == "device_loss" and lost >= 1:
            kind = "poison"
        if kind == "crash" and crashed >= 2:
            kind = "poison"
        tenant = int(rng.choice([t for _, t in MG_GROUP_A]))
        if kind == "poison" and tenant in poisoned:
            kind = "crash" if crashed < 2 else "storm"
        at = int(rng.integers(1, 3))
        if kind == "device_loss":
            lost += 1
        elif kind == "crash":
            crashed += 1
        elif kind == "poison":
            poisoned.add(tenant)
        sched.append((kind, {"tenant": tenant, "at": at}))
    return sched


def _mg_arm(sched):
    """Arm a multigroup schedule; device losses carry ``slice=0`` so
    only group A's fault domain evacuates."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    handles = []
    for kind, kw in sched:
        if kind == "device_loss":
            handles.append(faults.inject(
                "device_loss", point="serve.chunk",
                at_row=kw["at"] + 1, times=1, slice=0))
        elif kind == "poison":
            handles.append(faults.inject(
                "poison_rows", tenant=kw["tenant"],
                at_row=kw["at"], times=1))
        elif kind == "crash":
            handles.append(faults.inject(
                "crash", point="serve.chunk", at_row=kw["at"] + 1,
                times=1))
        else:
            handles.append(None)      # storm: no registry entry
    return handles


def _run_mg_seed(seed, args, root, cache, ptas_a, ptas_b, storm_pta,
                 solos):
    """One seeded multigroup drill: group A on slice 0, group B on
    slice 1, faults aimed at slice 0 only.  Invariants: every job done
    and bitwise vs its solo (group B's bitwise equality IS the
    fault-domain proof), zero unplanned steady retraces, counters
    consistent, ≥2 groups were concurrently resident, pre-warming under
    a storm never blocked a resident step (the storm tenant completes
    and the prewarm counter respects its cap)."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    rng = np.random.default_rng([args.campaign_seed, 1000 + seed])
    sched = _mg_schedule(rng, args.quick)
    with_storm = any(k == "storm" for k, _ in sched)
    fails = []

    kw = {}
    if with_storm:
        kw["admission"] = {"max_queue": 16, "storm_compiles": 1,
                           "storm_window_s": 0.1}
        kw["prewarm"] = 1
    svc = _mg_service(root / f"mg{seed}", cache, **kw)
    faults.clear()
    handles = _mg_arm(sched)
    jobs = []
    try:
        with recompile_counter() as rc:
            rc.phase("steady")
            # submission order pins the slice assignment: group A
            # claims slice 0, group B slice 1
            for pta, (_, tenant) in zip(ptas_a, MG_GROUP_A):
                jobs.append(svc.submit(pta, NITER,
                                       job_id=f"mga{tenant}",
                                       tenant_id=tenant))
            for pta, (_, tenant) in zip(ptas_b, MG_GROUP_B):
                jobs.append(svc.submit(pta, NITER,
                                       job_id=f"mgb{tenant}",
                                       tenant_id=tenant))
            if with_storm:
                jobs.append(svc.submit(storm_pta, NITER,
                                       job_id="mgstorm",
                                       tenant_id=MG_STORM[1]))
            report = svc.run()
    except Exception as exc:                      # noqa: BLE001
        faults.clear()
        return {"seed": seed, "leg": "multigroup", "schedule": sched,
                "error": repr(exc)}, \
            [f"mg seed {seed}: run raised {exc!r}"]
    finally:
        faults.clear()

    # completion + bitwise isolation for EVERY tenant; group B's
    # equality while slice 0 took the faults is the fault-domain claim
    for job in jobs:
        if job.state != "done":
            fails.append(f"mg seed {seed}: {job.job_id} "
                         f"state={job.state!r} ({job.failure})")
            continue
        ref_c, ref_b = solos[int(job.tenant_id)]
        if not (np.array_equal(job.chain, ref_c)
                and np.array_equal(job.bchain, ref_b)):
            fails.append(f"mg seed {seed}: {job.job_id} diverged from "
                         "its solo baseline (cross-slice blast radius)")

    fired_poison = [kw_ for (k, kw_), h in zip(sched, handles)
                    if k == "poison" and h is not None and h.fired]
    qlog = report["quarantine_log"]
    if len(qlog) != len(fired_poison):
        fails.append(f"mg seed {seed}: {len(fired_poison)} poison(s) "
                     f"fired but {len(qlog)} quarantine(s) logged")
    n_loss = sum(1 for (k, _), h in zip(sched, handles)
                 if k == "device_loss" and h is not None and h.fired)
    if report["evacuations"] != n_loss:
        fails.append(f"mg seed {seed}: evacuations "
                     f"{report['evacuations']} != injected slice "
                     f"losses {n_loss}")
    pl = report["placement"]
    losses0 = next(s["losses"] for s in pl["slices"] if s["slice"] == 0)
    losses1 = next(s["losses"] for s in pl["slices"] if s["slice"] == 1)
    if losses0 != n_loss or losses1 != 0:
        fails.append(f"mg seed {seed}: per-slice losses ({losses0}, "
                     f"{losses1}) != ({n_loss}, 0) — the loss was not "
                     "confined to its fault domain")
    if pl["max_concurrent_groups"] < 2:
        fails.append(f"mg seed {seed}: max_concurrent_groups "
                     f"{pl['max_concurrent_groups']} < 2 — groups were "
                     "serialized")
    if with_storm and pl["prewarms"] > 1:
        fails.append(f"mg seed {seed}: prewarms {pl['prewarms']} "
                     "exceeded the cap")
    unplanned = rc.unplanned("steady")
    if unplanned:
        fails.append(f"mg seed {seed}: {unplanned} unplanned steady "
                     "retrace(s)")
    if svc.queue:
        fails.append(f"mg seed {seed}: queue not drained "
                     f"({len(svc.queue)} left)")

    rec = {"seed": seed, "leg": "multigroup", "schedule": sched,
           "quarantines": report["quarantines"],
           "evacuations": report["evacuations"],
           "max_concurrent_groups": pl["max_concurrent_groups"],
           "prewarms": pl["prewarms"],
           "unplanned_retraces": unplanned, "ok": not fails}
    return rec, fails


def _mg_gateway_drill(root, cache):
    """Gateway restart with TWO groups journaled: both jobs (different
    buckets) sample concurrently on their own slices, the gateway is
    killed mid-run, and the restarted incarnation re-materializes both
    from the journal — each re-routed to its own group's slice (no
    'global active group' to misroute to), both finishing bitwise with
    zero orphaned journal entries and zero unplanned steady retraces."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import faults, preemption
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest
    import time

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    fails = []
    gniter = 4 * NITER
    svc_kw = dict(chunk=4, quantum=100, save_every=1, cache=cache,
                  placement=[{"slots": 2}, {"slots": 2}])
    pay_a = {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                           "seed": 0, "nmodes": 3}}
    pay_b = {"synthetic": {"n_psr": 2, "ntoa": 44, "tm_cols": 3,
                           "seed": 2, "nmodes": 3}}

    def post(gw, path, body):
        resp = gw.handle(WireRequest("POST", path, {}, {},
                                     json.dumps(body).encode()))
        return resp.status, resp.body or {}

    # solo ground truth (the gateway assigns tenants 0, 1 in
    # submission order; streams are pure in the tenant identity)
    solos = {}
    for tenant, (ntoa, dseed) in ((0, (24, 0)), (1, (44, 2))):
        pta = build_model(
            synthetic_pulsars(2, ntoa, tm_cols=3, seed=dseed), 3)
        svc = _mg_service(root / f"mggwsolo{tenant}", cache)
        job = svc.submit(pta, gniter, job_id=f"mggwsolo{tenant}",
                         tenant_id=tenant)
        svc.run()
        if job.state != "done":
            return [f"mg gateway: solo baseline {tenant} failed "
                    f"({job.failure})"]
        solos[tenant] = job.chain.copy()

    preemption.reset()
    faults.clear()
    try:
        with recompile_counter() as rc:
            rc.phase("steady")
            r = root / "mggw"
            gw = Gateway(r, _mg_table(), svc_kw=svc_kw,
                         stop_when_idle=False).start()
            st, ha = post(gw, "/v1/jobs", {
                "dedupe_key": "mga", "payload": pay_a, "niter": gniter})
            st2, hb = post(gw, "/v1/jobs", {
                "dedupe_key": "mgb", "payload": pay_b, "niter": gniter})
            if st != 200 or st2 != 200:
                fails.append(f"mg gateway: submits HTTP {st}/{st2}")
            # wait until BOTH groups are concurrently resident, then
            # kill the scheduler with no goodbye
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30:
                summ = gw.svc.placement_summary()
                if sum(1 for s in summ if s["residents"]) >= 2:
                    break
                time.sleep(0.02)
            else:
                fails.append("mg gateway: two groups never became "
                             "concurrently resident")
            faults.inject("gateway_kill", point="gateway.step",
                          at_row=gw._steps + 2, times=1)
            t0 = time.monotonic()
            while gw.alive() and time.monotonic() - t0 < 30:
                time.sleep(0.02)
            if gw.alive():
                fails.append("mg gateway: injected kill did not stop "
                             "the scheduler")

            # restart: both journaled groups re-materialize, each onto
            # its own slice
            gw2 = Gateway(r, _mg_table(), svc_kw=svc_kw,
                          stop_when_idle=False).start()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 60 and not gw2._all_settled():
                time.sleep(0.05)
            ents = gw2.report()["entries"]
            bad = {k: e["state"] for k, e in ents.items()
                   if e["state"] != "done"}
            if bad:
                fails.append(f"mg gateway: orphaned journal entries "
                             f"after restart: {bad}")
            groups = [s["group"] for s in gw2.report()["service"]
                      ["placement"]["slices"]]
            for key, tenant in (("mga", 0), ("mgb", 1)):
                ent = ents.get(key)
                if ent is None:
                    continue
                chain = np.load(Path(ent["outdir"]) / "chain.npy")
                if not np.array_equal(chain, solos[tenant]):
                    fails.append(f"mg gateway: {key} not bitwise vs "
                                 "its solo after the restart")
            _ = groups
            if gw2.svc.queue:
                fails.append(f"mg gateway: queue not drained "
                             f"({len(gw2.svc.queue)} left)")
            preemption.request_drain(reason="mg_gateway_teardown")
            gw2.join(timeout=30)
            if gw2.alive() or gw2.state != "stopped":
                fails.append("mg gateway: graceful drain did not park "
                             f"the scheduler (state {gw2.state!r})")
        unplanned = rc.unplanned("steady")
        if unplanned:
            fails.append(f"mg gateway: {unplanned} unplanned steady "
                         "retrace(s) across the restart")
    finally:
        faults.clear()
        preemption.reset()
    return fails


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="seeded chaos campaign over the serving tier")
    ap.add_argument("--seeds", type=int, default=25,
                    help="number of randomized fault schedules")
    ap.add_argument("--quick", action="store_true",
                    help="one fault per seed, no device-loss/storm "
                    "draws (the ci_lint --chaos layer)")
    ap.add_argument("--campaign-seed", type=int, default=0)
    ap.add_argument("--outdir", default=None,
                    help="work dir (default: a fresh temp dir)")
    ap.add_argument("--json", action="store_true",
                    help="print the full JSON report")
    args = ap.parse_args(argv)

    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache

    tmp = None
    if args.outdir is None:
        tmp = tempfile.mkdtemp(prefix="chaos_campaign_")
        root = Path(tmp)
    else:
        root = Path(args.outdir)
        root.mkdir(parents=True, exist_ok=True)

    cache = ProgramCache()
    ptas, storm_pta = _models()
    print(f"[campaign] building {len(ptas)} solo baselines "
          "(shared program cache) ...", flush=True)
    solos = _solo_baselines(root, cache, ptas)
    storm_solo = None
    if not args.quick:
        svc = _service(root / "solo_storm", cache)
        job = svc.submit(storm_pta, NITER, job_id="solo_storm",
                         tenant_id=len(TENANTS))
        svc.run()
        if job.state != "done":
            raise RuntimeError("storm-tenant baseline failed")
        storm_solo = (job.chain.copy(), job.bchain.copy())

    records, failures = [], []
    for seed in range(args.seeds):
        rec, fails = _run_seed(seed, args, root, cache, ptas, storm_pta,
                               solos, storm_solo)
        records.append(rec)
        failures.extend(fails)
        tag = "ok" if not fails else "FAIL"
        kinds = [k for k, _ in rec.get("schedule", [])]
        print(f"[campaign] seed {seed:3d} {tag:4s} faults={kinds}",
              flush=True)

    # the transport leg runs in every mode (the --quick invocation IS
    # the ci_lint --chaos layer, and the gateway contracts are exactly
    # what CI must hold)
    print("[campaign] gateway leg: kill/restart/reattach drill ...",
          flush=True)
    gw_fails = _gateway_drill(root, cache)
    failures.extend(gw_fails)
    records.append({"leg": "gateway", "failures": gw_fails})
    print(f"[campaign] gateway {'ok' if not gw_fails else 'FAIL'}",
          flush=True)

    # the standing-model leg also runs in every mode: a kill at ANY
    # migration seam must land on the parent or the child generation,
    # never a torn hybrid — exactly what CI must hold
    print("[campaign] append leg: seam-kill migration drill ...",
          flush=True)
    ap_fails = _append_drill(root, cache)
    failures.extend(ap_fails)
    records.append({"leg": "append", "failures": ap_fails})
    print(f"[campaign] append {'ok' if not ap_fails else 'FAIL'}",
          flush=True)

    # multigroup leg: faults aimed at one slice while a second group
    # is co-resident — the survivor's bitwise equality is the
    # fault-domain claim.  Runs in every mode (incl. --quick).
    mg_cache = ProgramCache()
    ptas_a, ptas_b, mg_storm_pta = _mg_models()
    print("[campaign] multigroup leg: building solo baselines ...",
          flush=True)
    mg_solos = _mg_solos(root, mg_cache, ptas_a, ptas_b, mg_storm_pta)
    for seed in range(args.seeds):
        rec, fails = _run_mg_seed(seed, args, root, mg_cache, ptas_a,
                                  ptas_b, mg_storm_pta, mg_solos)
        records.append(rec)
        failures.extend(fails)
        tag = "ok" if not fails else "FAIL"
        kinds = [k for k, _ in rec.get("schedule", [])]
        print(f"[campaign] mg seed {seed:3d} {tag:4s} faults={kinds}",
              flush=True)
    print("[campaign] multigroup gateway leg: two groups journaled, "
          "kill/restart ...", flush=True)
    mg_gw_fails = _mg_gateway_drill(root, mg_cache)
    failures.extend(mg_gw_fails)
    records.append({"leg": "mg_gateway", "failures": mg_gw_fails})
    print(f"[campaign] mg gateway "
          f"{'ok' if not mg_gw_fails else 'FAIL'}", flush=True)

    report = {"seeds": args.seeds, "quick": bool(args.quick),
              "campaign_seed": args.campaign_seed,
              "passed": args.seeds - len({f.split(':')[0]
                                          for f in failures}),
              "failures": failures, "records": records}
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    if failures:
        print(f"[campaign] {len(failures)} invariant failure(s):")
        for f in failures:
            print(f"  - {f}")
    else:
        print(f"[campaign] all {args.seeds} seeds held every invariant")
    if tmp is not None:
        shutil.rmtree(tmp, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
