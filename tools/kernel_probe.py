"""Measure the CRN steady block mix against the all-exact f64 blocks.

The kernel tier's perf claim, quantified on whatever backend is
present.  The production steady mix runs the f32 blocks —
``tnt_d_seg32`` segmented Gram + the fused ``chol_solve_sample``
factor chain — for ``exact_every - 1`` of every ``exact_every``
sweeps, and the near-exact two-float refresh blocks (``tnt_d_seg`` +
``factor="tf"``) for the remaining slot; the pre-PR 3 sweep ran the
f64 exact blocks (widening-f64 ``tnt_d`` + f64 Jacobi factor chain)
every sweep.  The probe times the three block chains vmapped over
chains and reports, for the Gram alone and for the full
Gram+factor+sample chain,

    mix rate    = exact_every / ((exact_every - 1) t_steady + t_refresh)
    exact rate  = 1 / t_exact
    speedup     = mix rate / exact rate

With ``--append`` the Gram-block speedup lands in PERF_LEDGER.jsonl as
``crn_steady_gram_mix_speedup_vs_exact`` — a gated metric
(``perfwatch --check``): a kernel or dispatch regression that erodes
the steady-path advantage fails the gate before it reaches hardware.

``--gram-seg-len`` pins the steady segment length for the run; the
default 0 means one segment (``seg_len = ntoa``) — the CPU autotune
optimum (tools/autotune.py), since only TPU HBM scratch motivates
short segments.  ``--tier pallas|xla|auto`` pins the kernel tier
(off-TPU, ``pallas`` runs the interpreter — correctness-true but slow;
timing runs should keep the resolved default).

Usage: python tools/kernel_probe.py [--nchains 8] [--append]
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=8)
    ap.add_argument("--n-psr", type=int, default=20)
    ap.add_argument("--ntoa", type=int, default=720)
    ap.add_argument("--tm-cols", type=int, default=5)
    ap.add_argument("--nmodes", type=int, default=10)
    ap.add_argument("--exact-every", type=int, default=16)
    ap.add_argument("--gram-seg-len", type=int, default=0,
                    help="steady Gram segment length; 0 = one segment "
                         "(the CPU autotune optimum)")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--tier", default=None,
                    choices=("pallas", "xla", "auto"))
    ap.add_argument("--append", action="store_true",
                    help="append the speedup record to PERF_LEDGER.jsonl")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.config import settings
    from pulsar_timing_gibbsspec_tpu.obs import perf
    from pulsar_timing_gibbsspec_tpu.ops import kernels
    from pulsar_timing_gibbsspec_tpu.ops.linalg import (
        _batched_diag, jacobi_factor_mean_prop)
    from pulsar_timing_gibbsspec_tpu.profiling import _scan_time
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    if args.tier:
        settings.kernel_tier = args.tier
    seg = args.gram_seg_len or args.ntoa

    psrs = synthetic_pulsars(args.n_psr, args.ntoa,
                             tm_cols=args.tm_cols, seed=0)
    pta = build_model(psrs, args.nmodes)
    cm = compile_pta(pta)
    C = args.nchains
    x0 = jnp.asarray(pta.initial_sample(np.random.default_rng(0)),
                     cm.cdtype)
    N0 = cm.ndiag_fast(x0)
    phi = cm.phi(x0)
    phi32 = cm.phi(x0, dtype=cm.dtype)
    eye32 = jnp.eye(cm.Bmax, dtype=cm.dtype)

    # every body threads the scan carry through N so nothing hoists out
    # of the timing loop, and vmaps the per-chain block over C chains
    def _timed(block):
        def body(x, b, key):
            out = jax.vmap(block, in_axes=(None, 0))(
                N0 * (1.0 + 0.0 * x), jr.split(key, C))
            return x + 0.0 * out.ravel()[0].astype(x.dtype), b
        x = jnp.zeros((), cm.dtype)
        b = jnp.zeros((), cm.dtype)
        return _scan_time(body, x, b, args.iters, args.warmup)

    # -- Gram blocks alone ------------------------------------------------
    def gram_steady(N, _k):
        return jb.tnt_d_seg32(cm, N, seg_len=seg)[0]

    def gram_refresh(N, _k):
        return jb.tnt_d_seg(cm, N, seg_len=seg)[0].astype(cm.dtype)

    def gram_exact(N, _k):
        return jb.tnt_d(cm, N)[0].astype(cm.dtype)

    # -- full Gram + factor + sample chains -------------------------------
    def chain_steady(N, k):
        TNT, d = jb.tnt_d_seg32(cm, N, seg_len=seg)
        Sig = TNT + (1.0 / phi32)[:, :, None] * eye32
        z = jr.normal(k, (cm.P, cm.Bmax), cm.dtype)
        return kernels.chol_solve_sample(Sig, d, z,
                                         ridge=jb._PROP_RIDGE)[4]

    def chain_refresh(N, k):
        TNT, d = jb.tnt_d_seg(cm, N, seg_len=seg)
        Sig = TNT + _batched_diag(1.0 / phi)
        z = jr.normal(k, (cm.P, cm.Bmax), cm.cdtype)
        return kernels.chol_solve_sample(
            Sig, d, z, ridge=jb._PROP_RIDGE,
            factor="tf")[4].astype(cm.dtype)

    def chain_exact(N, k):
        TNT, d = jb.tnt_d(cm, N)
        Sig = TNT + _batched_diag(1.0 / phi)
        z = jr.normal(k, (cm.P, cm.Bmax), cm.cdtype)
        return jacobi_factor_mean_prop(Sig, d, z)[4].astype(cm.dtype)

    E = args.exact_every
    dev = jax.devices()[0]
    tier = kernels.resolve_tier()
    print(f"backend={jax.default_backend()} device={dev.device_kind} "
          f"tier={tier} C={C} P={cm.P} B={cm.Bmax} ntoa={args.ntoa} "
          f"seg_len={seg}")

    speedups = {}
    for label, steady, refresh_, exact in (
            ("gram", gram_steady, gram_refresh, gram_exact),
            ("gram+chol+sample", chain_steady, chain_refresh,
             chain_exact)):
        t_s = _timed(steady)
        t_r = _timed(refresh_)
        t_e = _timed(exact)
        mix_rate = E / ((E - 1) * t_s + t_r)
        speedups[label] = mix_rate * t_e
        print(f"{label:18s} steady {t_s * 1e3:7.2f} ms  refresh "
              f"{t_r * 1e3:7.2f} ms  exact {t_e * 1e3:7.2f} ms  "
              f"mix {mix_rate * C:9.1f} blk/s  all-exact "
              f"{C / t_e:9.1f} blk/s  speedup {speedups[label]:5.2f}x")

    if args.append:
        rec = perf.make_ledger_record(
            {"metric": "crn_steady_gram_mix_speedup_vs_exact",
             "value": float(speedups["gram"]), "unit": "x",
             "nchains": C, "device_kind": dev.device_kind,
             "backend": jax.default_backend()},
            source="tools/kernel_probe.py", kind="probe",
            note=(f"kernel_tier={tier}; mix=({E - 1}*f32_seg32+"
                  f"tf_refresh)/{E} vs widen-f64 tnt_d; chain speedup "
                  f"{speedups['gram+chol+sample']:.2f}x; P={cm.P} "
                  f"ntoa={args.ntoa} nmodes={args.nmodes} "
                  f"seg_len={seg}"))
        path = perf.ledger_append(rec)
        print(f"appended to {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
