"""Ensemble-mixing probe: plain vs ensemble-on, same synthetic CRN.

Runs the chunked CRN sampler twice on one synthetic dataset — once as
the plain per-chain Gibbs sweep, once with the ensemble mixing engine
(ASIS interweaving + interchain stretch moves, and a tempering ladder
when ``--pt-ladder > 1``; sampler/ensemble.py) — and prints a small
table of the quantities the engine is supposed to move:

- median Sokal rho-ACT (sweep units) and the mixing-adjusted ESS/s of
  each leg, plus their ratio (the ISSUE-10 acceptance is >= 2x on the
  bench config);
- stretch acceptance per temperature rung and the adjacent-rung swap
  rates / final betas when tempering is on.

Exit is nonzero when the engine violates its contracts dynamically or
statically:

- any UNPLANNED retrace in either steady loop (both programs must be
  the one compiled chunk, ensemble stage included);
- a non-allowlisted chain-axis collective: the committed fast
  contracts are re-audited in a subprocess (``tools/jaxprcheck.py
  --fast`` covers ``crn_ensemble``'s isolate_axis allowlist — small
  (rho, hyper) payloads only — and ``crn_2d_mesh``'s ensemble-off
  clean-axis pin);
- non-finite chains or a zero stretch-acceptance leg (the
  detailed-balance guard that caught the bounds-shadowing bug).

Usage: python tools/ensemble_probe.py [--niter N] [--nchains C]
       [--chunk N] [--n-psr P] [--nmodes K] [--pt-ladder T] [--skip-audit]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def _run_leg(pta, args, ensemble, pt_ladder):
    """One measured leg: (sweeps/s, rho-ACT sweeps, ESS/s, retraces,
    ensemble summary or None)."""
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import (
        JaxGibbsDriver)

    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=20, chunk_size=args.chunk,
                         nchains=args.nchains, warmup_sweeps=20,
                         ensemble=ensemble, pt_ladder=pt_ladder)
    cshape, bshape = drv.chain_shapes(args.niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    x0 = pta.initial_sample(np.random.default_rng(0))
    with recompile_counter() as rc:
        rc.phase("warmup")
        it = drv.run(x0, chain, bchain, 0, args.niter)
        done = next(it)                  # warmup + first compiles
        rc.phase("steady")
        t0, r0 = time.time(), done
        for done in it:
            pass
        wall = time.time() - t0
    retraces = rc.unplanned("steady")
    rate = (done - r0) / max(wall, 1e-9)
    idx = BlockIndex.build(pta.param_names)
    T = max(1, int(pt_ladder)) if ensemble else 1
    cold = chain[:, ::T]                 # only beta=1 chains are samples
    burn = len(chain) // 4
    acts = [integrated_act(np.ascontiguousarray(cold[burn:, c, k]))
            for k in idx.rho for c in range(cold.shape[1])]
    act = float(np.median(acts)) if acts else 1.0
    ess = cold.shape[1] * rate / max(act, 1.0)
    finite = bool(np.isfinite(chain).all())
    return {"sweeps_per_sec": rate, "rho_act": act, "ess_per_sec": ess,
            "retraces": retraces, "finite": finite,
            "ensemble": drv.ensemble_summary() if ensemble else None}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--niter", type=int, default=240,
                    help="recorded iterations per leg (short by design)")
    ap.add_argument("--nchains", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=10)
    ap.add_argument("--n-psr", type=int, default=3)
    ap.add_argument("--nmodes", type=int, default=3)
    ap.add_argument("--pt-ladder", type=int, default=1,
                    help="tempering ladder depth of the ensemble leg")
    ap.add_argument("--skip-audit", action="store_true",
                    help="skip the static fast-contract re-audit")
    args = ap.parse_args()

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    pta = build_model(
        synthetic_pulsars(args.n_psr, 40, tm_cols=3, seed=0), args.nmodes)
    failures = []
    plain = _run_leg(pta, args, ensemble=False, pt_ladder=1)
    ens = _run_leg(pta, args, ensemble=True, pt_ladder=args.pt_ladder)

    for name, leg in (("plain", plain), ("ensemble", ens)):
        if leg["retraces"]:
            failures.append(f"{leg['retraces']} unplanned steady "
                            f"retrace(s) in the {name} leg")
        if not leg["finite"]:
            failures.append(f"non-finite chain values in the {name} leg")
    es = ens["ensemble"] or {}
    if es.get("stretch") and not any(
            a > 0 for a in es.get("stretch_accept", [])):
        failures.append("stretch move accepted nothing — detailed "
                        "balance or bounds are broken")

    # static chain-axis audit: the committed fast contracts include the
    # crn_ensemble allowlist (small (rho, hyper) payloads only) and the
    # ensemble-off clean-axis pin; a subprocess so the auditor's CPU
    # host-device bootstrap cannot disturb this process's backend
    audit_rc = None
    if not args.skip_audit:
        here = os.path.dirname(os.path.abspath(__file__))
        res = subprocess.run(
            [sys.executable, os.path.join(here, "jaxprcheck.py"),
             "--fast"], capture_output=True, text=True, timeout=1800)
        audit_rc = res.returncode
        if audit_rc != 0:
            failures.append(
                "fast contract audit failed (non-allowlisted chain-axis "
                "collective or drift): "
                + (res.stdout + res.stderr).strip()[-400:])

    rows = [("leg", "sweeps/s", "rho-ACT", "ESS/s"),
            ("plain", f"{plain['sweeps_per_sec']:.2f}",
             f"{plain['rho_act']:.2f}", f"{plain['ess_per_sec']:.1f}"),
            ("ensemble", f"{ens['sweeps_per_sec']:.2f}",
             f"{ens['rho_act']:.2f}", f"{ens['ess_per_sec']:.1f}")]
    for r in rows:
        print(f"{r[0]:>9} {r[1]:>9} {r[2]:>8} {r[3]:>8}", file=sys.stderr)
    if es:
        print(f"stretch_accept {es.get('stretch_accept')} "
              f"swap_rate {es.get('swap_rate')} "
              f"betas {es.get('betas')}", file=sys.stderr)

    report = {
        "niter": args.niter, "nchains": args.nchains,
        "pt_ladder": args.pt_ladder,
        "plain": {k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in plain.items() if k != "ensemble"},
        "ensemble": {k: (round(v, 3) if isinstance(v, float) else v)
                     for k, v in ens.items() if k != "ensemble"},
        "ensemble_config": es,
        "ess_ratio": round(ens["ess_per_sec"]
                           / max(plain["ess_per_sec"], 1e-9), 3),
        "fast_audit_rc": audit_rc,
        "failures": failures,
    }
    print(json.dumps(report, indent=2))
    if failures:
        print("FAIL: " + "; ".join(failures), file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
