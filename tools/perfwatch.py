"""perfwatch: the performance-trajectory gate over ``PERF_LEDGER.jsonl``.

The ledger is append-only — one JSON line per bench run (``bench.py``
appends automatically; schema in docs/OBSERVABILITY.md).  This tool
makes the trajectory machine-checked the way jaxlint/jaxprcheck make
style and contracts machine-checked:

``--check`` (the ci_lint layer; no device execution)
    1. Ledger gate: within each (kind, metric, device_kind, backend)
       group the newest record's rates must sit inside explicit noise
       bands of the best prior record (``obs.perf.check_ledger``) — a
       committed regression fails the gate; new metrics/groups pass.
       Rate fields gate on DROPS below the best prior; the dispatch-tax
       field ``dispatch_amortized_ms_per_sweep`` gates on GROWTH above
       the best (lowest) prior (``obs.perf.LOWER_IS_BETTER``).
    2. Static cost-model self-check: trace the CRN Gram einsum on the
       CPU backend and require the jaxpr-derived ``dot_general`` FLOPs
       to match ``profiling.flop_counts`` within 5% — the roofline
       attribution's ground-truth tie, exercised on HEAD's code.

``--backfill``
    Rebuild the initial ledger from the committed ``BENCH_r*.json`` /
    ``MULTICHIP_r*.json`` snapshots (refuses to clobber an existing
    ledger without ``--force``).

``--report``
    Human-readable trajectory table per metric group.

Usage::

    python tools/perfwatch.py --check [--ledger PATH] [--band f=0.35]
    python tools/perfwatch.py --backfill [--force]
    python tools/perfwatch.py --report
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[1]
if str(_REPO_ROOT) not in sys.path:        # direct script execution
    sys.path.insert(0, str(_REPO_ROOT))


def _bootstrap_cpu():
    """Pin the CPU backend before jax first imports — the gate must
    never touch a device."""
    os.environ["JAX_PLATFORMS"] = "cpu"


# ---------------------------------------------------------------------------
# backfill: committed snapshots -> initial ledger

#: fields restored from adjacent context where a snapshot's own JSON was
#: truncated (BENCH_r05 committed only the tail of its headline line;
#: the device is the same v5e host as r04 — noted on the record)
_BACKFILL_OVERRIDES = {
    "BENCH_r05": {"device_kind": "TPU v5 lite",
                  "note": "device_kind restored from the r04 context "
                          "(same session/host); r05 JSON is tail-only"},
}

_TAIL_FLOAT = {
    "ess_per_sec": r'"ess_per_sec":\s*([0-9.eE+-]+)',
    "rho_act_median": r'"rho_act_median":\s*([0-9.eE+-]+)',
    "record_every": r'"record_every":\s*([0-9]+)',
}
_TAIL_RATE = re.compile(
    r"#\s*jax:\s*([0-9.]+)\s*sweeps/s\s*x\s*([0-9]+)\s*chains")
_TAIL_TS = re.compile(r"(\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2})")


def _parse_bench_snapshot(path: Path) -> dict | None:
    """A ledger record from one committed BENCH_rNN.json wrapper
    (``{"n", "cmd", "rc", "tail", "parsed"}``) — ``parsed`` carries the
    headline dict when the capture was complete, else the tail text is
    mined for what it still holds."""
    from pulsar_timing_gibbsspec_tpu.obs import perf

    doc = json.loads(path.read_text())
    run = path.stem
    tail = doc.get("tail") or ""
    headline = dict(doc.get("parsed") or {})
    note = None
    if not headline:
        # tail-only snapshot: top-level headline keys appear verbatim
        # in the truncated JSON text; the stderr gate line has the rate
        for k, pat in _TAIL_FLOAT.items():
            m = re.search(pat, tail)
            if m:
                headline[k] = float(m.group(1))
        m = _TAIL_RATE.search(tail)
        if m:
            sweeps, nchains = float(m.group(1)), int(m.group(2))
            headline["sweeps_per_sec"] = sweeps
            headline["nchains"] = nchains
            headline["metric"] = "gibbs_samples_per_sec_45psr_pta"
            headline["value"] = sweeps * nchains
            headline["unit"] = "samples/s"
        note = "backfilled from tail text (truncated snapshot)"
    if not headline.get("metric"):
        return None
    over = _BACKFILL_OVERRIDES.get(run, {})
    headline.update({k: v for k, v in over.items() if k != "note"})
    note = over.get("note", note)
    ts = None
    m = _TAIL_TS.search(tail)
    if m:
        import datetime as dt

        ts = dt.datetime.strptime(
            m.group(1), "%Y-%m-%d %H:%M:%S").timestamp()
    return perf.make_ledger_record(headline, source=path.name, run=run,
                                   ts=ts, note=note)


def _parse_multichip_snapshot(path: Path) -> dict | None:
    from pulsar_timing_gibbsspec_tpu.obs import perf

    doc = json.loads(path.read_text())
    # the snapshot JSON carries no wall-clock of its own; the file's
    # mtime is the host-side capture time (ts must never be null — the
    # ledger's "when did this regress" question depends on it)
    ts = float(path.stat().st_mtime)
    rec = {"schema": perf.LEDGER_SCHEMA, "kind": "multichip",
           "source": path.name, "run": path.stem, "ts": ts,
           "ts_iso": perf._iso_ts(ts),
           "ok": bool(doc.get("ok")),
           "n_devices": doc.get("n_devices")}
    if doc.get("skipped"):
        rec["skipped"] = True
    if doc.get("mesh_axes"):
        rec["mesh_axes"] = doc["mesh_axes"]
    scaling = doc.get("scaling")
    if scaling:
        rec["scaling"] = scaling
    if doc.get("collectives_evidence"):
        rec["collectives_evidence"] = doc["collectives_evidence"]
    return rec


def backfill(ledger: Path, force: bool = False) -> int:
    """Rebuild the snapshot-derived records (BENCH_r*/MULTICHIP_r*)
    and MERGE: records from other producers (probes, autotune, CI)
    are preserved in their original order after the snapshot block,
    with a host-side ``ts``/``ts_iso`` stamped onto any that predate
    the no-null-ts rule."""
    from pulsar_timing_gibbsspec_tpu.obs import perf

    if ledger.exists() and not force:
        print(f"perfwatch: {ledger} exists; --force to rebuild",
              file=sys.stderr)
        return 1
    snapshot_sources = {
        p.name for pat in ("BENCH_r*.json", "MULTICHIP_r*.json")
        for p in _REPO_ROOT.glob(pat)}
    preserved = []
    for rec in perf.ledger_read(ledger) if ledger.exists() else []:
        if rec.get("source") in snapshot_sources:
            continue            # regenerated below from the snapshot
        if rec.get("ts") is None:
            rec = dict(rec, ts=time.time())
        if not rec.get("ts_iso"):
            rec = dict(rec, ts_iso=perf._iso_ts(rec["ts"]))
        preserved.append(rec)
    records = []
    for p in sorted(_REPO_ROOT.glob("BENCH_r*.json")):
        try:
            rec = _parse_bench_snapshot(p)
        except Exception as e:      # noqa: BLE001 — skip torn snapshots
            print(f"perfwatch: skipping {p.name}: {e}", file=sys.stderr)
            continue
        if rec:
            records.append(rec)
        else:
            print(f"perfwatch: {p.name} has no headline; skipped")
    for p in sorted(_REPO_ROOT.glob("MULTICHIP_r*.json")):
        try:
            rec = _parse_multichip_snapshot(p)
        except Exception as e:      # noqa: BLE001
            print(f"perfwatch: skipping {p.name}: {e}", file=sys.stderr)
            continue
        if rec:
            records.append(rec)
    records.extend(preserved)
    ledger.write_text(
        "".join(json.dumps(r, sort_keys=True) + "\n" for r in records))
    print(f"perfwatch: wrote {len(records)} record(s) to {ledger} "
          f"({len(preserved)} non-snapshot record(s) preserved)")
    return 0


# ---------------------------------------------------------------------------
# the gate


def _cost_selfcheck(tol: float = 0.05) -> list[str]:
    """Trace the CRN Gram einsum (tiny synthetic model, CPU backend,
    nothing executes) and compare the jaxpr-derived dot FLOPs with the
    analytic ``profiling.flop_counts`` terms."""
    _bootstrap_cpu()
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.cost import cost_of
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.profiling import flop_counts
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    cm = compile_pta(build_model(synthetic_pulsars(3, 40, tm_cols=3), 3))
    x0 = jnp.zeros((cm.nx,), cm.cdtype)

    def gram(x):
        N = cm.ndiag_fast(x)
        TN = cm.T / N[:, :, None]
        return jnp.einsum("pnb,pnc->pbc", TN, cm.T,
                          preferred_element_type=cm.dtype,
                          precision="highest")

    rep = cost_of(gram, (x0,))
    want = flop_counts(cm)["gram_einsum"]
    problems = []
    if want <= 0:
        problems.append("flop_counts returned a non-positive gram term")
    elif abs(rep.dot_flops - want) > tol * want:
        problems.append(
            f"static cost model drifted from flop_counts on the CRN "
            f"gram einsum: modeled {rep.dot_flops:.6g} dot-FLOPs vs "
            f"analytic {want:.6g} (tolerance {tol:.0%})")
    return problems


def check(ledger: Path, bands: dict | None = None,
          skip_selfcheck: bool = False) -> int:
    from pulsar_timing_gibbsspec_tpu.obs import perf

    # an absent or empty ledger is a fresh checkout / new backend, not a
    # regression: the trajectory gate has nothing to gate against, so it
    # passes with an actionable note (the cost-model self-check — which
    # needs no history — still runs below)
    records = perf.ledger_read(ledger) if ledger.exists() else []
    if not records:
        print(f"perfwatch: no ledger records for this backend in "
              f"{ledger} — nothing to gate yet; seed the trajectory "
              "with `python tools/perfwatch.py --backfill` (committed "
              "snapshots) or run tools/bench.py to append the first "
              "record")
    problems = perf.check_ledger(records, bands) if records else []
    if not skip_selfcheck:
        problems += _cost_selfcheck()
    if problems:
        for p in problems:
            print(f"perfwatch: REGRESSION: {p}", file=sys.stderr)
        print(f"perfwatch: FAILED ({len(problems)} problem(s) over "
              f"{len(records)} record(s))", file=sys.stderr)
        return 1
    print(f"perfwatch: OK ({len(records)} record(s), "
          f"{'ledger only' if skip_selfcheck else 'ledger + cost model'})")
    return 0


def report(ledger: Path) -> int:
    from pulsar_timing_gibbsspec_tpu.obs import perf

    records = perf.ledger_read(ledger)
    groups: dict = {}
    for rec in records:
        if rec.get("kind") == "multichip":
            key = ("multichip", None, None, None)
        else:
            key = (rec.get("kind"), rec.get("metric"),
                   rec.get("device_kind"), rec.get("backend"))
        groups.setdefault(key, []).append(rec)
    for key, recs in sorted(groups.items(), key=lambda kv: str(kv[0])):
        kind, metric, dev, backend = key
        head = metric or kind
        print(f"{head}  [{dev or '?'} / {backend or '?'}]")
        for r in recs:
            if kind == "multichip":
                print(f"  {r.get('run'):>10s}  ok={r.get('ok')}  "
                      f"ndev={r.get('n_devices')}")
                continue
            bits = [f"value={r['value']:.4g}" if "value" in r else ""]
            for f in ("sweeps_per_sec", "ess_per_sec", "mfu",
                      "dispatch_amortized_ms_per_sweep"):
                if f in r:
                    bits.append(f"{f}={r[f]:.4g}")
            sha = r.get("git_sha", "")
            print(f"  {r.get('run') or r.get('source', '?'):>10s}  "
                  f"{'  '.join(b for b in bits if b)}  {sha}")
    print(f"perfwatch: {len(records)} record(s), {len(groups)} group(s)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perfwatch",
        description="perf-ledger regression gate (static; no device)")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--backfill", action="store_true")
    ap.add_argument("--report", action="store_true")
    ap.add_argument("--force", action="store_true",
                    help="allow --backfill to overwrite the ledger")
    ap.add_argument("--ledger", default=None, metavar="PATH")
    ap.add_argument("--band", action="append", default=[],
                    metavar="FIELD=FRAC",
                    help="override a noise band, e.g. ess_per_sec=0.5")
    ap.add_argument("--no-selfcheck", action="store_true",
                    help="--check without the jax cost-model self-check")
    args = ap.parse_args(argv)

    ledger = Path(args.ledger) if args.ledger else (
        _REPO_ROOT / "PERF_LEDGER.jsonl")
    bands = {}
    for spec in args.band:
        field, _, frac = spec.partition("=")
        bands[field] = float(frac)

    if args.backfill:
        return backfill(ledger, force=args.force)
    if args.report:
        return report(ledger)
    if args.check:
        return check(ledger, bands or None,
                     skip_selfcheck=args.no_selfcheck)
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
