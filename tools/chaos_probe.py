"""End-to-end kill/resume/verify drill for the resilient runtime.

Runs the full recovery story on a synthetic single-pulsar PTA (no
reference data needed): an uninterrupted baseline run, then a supervised
run with a fault injected mid-stream (default: process "kill" between
the chain.npy and bchain.npy replaces — the torn-checkpoint window),
and asserts the recovered chain is bit-identical to the baseline.
Prints a JSON report with the telemetry counters and retry metadata.

Usage: python tools/chaos_probe.py [--fault kill|truncate|corrupt|nan|xla]
       [--niter 60] [--save-every 20] [--at-row 30]
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def build_pta():
    from pulsar_timing_gibbsspec_tpu.data.dataset import Pulsar
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general

    DAY = 86400.0
    rng = np.random.default_rng(11)
    n = 60
    span = 6.0 * 365.25 * DAY
    toas = np.sort(rng.uniform(0.0, span, n)) + 53000.0 * DAY
    errs = np.full(n, 5e-7)
    res = errs * rng.standard_normal(n)
    t = (toas - toas.mean()) / span
    M = np.column_stack([np.ones(n), t, t * t])
    psr = Pulsar(
        name="FAKE_CHAOS", toas=toas, toaerrs=errs, residuals=res,
        freqs=np.full(n, 1400.0),
        backend_flags=np.asarray(["sim"] * n, dtype=object),
        Mmat=M, fitpars=["offset", "F0", "F1"],
        flags={"pta": "NANOGrav"},
        pos=np.array([1.0, 0.0, 0.0]))
    return model_general([psr], red_var=False, white_vary=False,
                         common_psd="spectrum", common_components=4)


FAULTS = {
    # torn-checkpoint window: die after chain.npy is replaced but
    # before bchain.npy/adapt.npz/manifest.json are
    "kill": [dict(kind="crash", point="chainstore.between_replaces")],
    # damage a file of the just-completed checkpoint set, then die
    # before anything can rewrite it: resume must detect the bad
    # checksum and roll back to the .bak set
    "truncate": [dict(kind="truncate_file", path="chain.npy",
                      point="chainstore.post_save"),
                 dict(kind="crash", point="chainstore.post_save")],
    "corrupt": [dict(kind="corrupt_file", path="adapt.npz",
                     point="chainstore.post_save"),
                dict(kind="crash", point="chainstore.post_save")],
    # poison one recorded row: the sentinel must reject the chunk
    # before it reaches disk
    "nan": [dict(kind="nan_rows", point="sample.loop")],
    # transient device failure: retry with capped backoff
    "xla": [dict(kind="xla_error", point="sample.loop")],
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fault", choices=sorted(FAULTS), default="kill")
    ap.add_argument("--niter", type=int, default=60)
    ap.add_argument("--save-every", type=int, default=20)
    ap.add_argument("--at-row", type=int, default=None,
                    help="inject at the first seam with row >= AT_ROW "
                    "(default: niter // 2)")
    ap.add_argument("--outdir", default="/tmp/chaos_probe")
    args = ap.parse_args()
    at_row = args.niter // 2 if args.at_row is None else args.at_row

    import shutil
    from pathlib import Path

    from pulsar_timing_gibbsspec_tpu.runtime import (
        faults, supervisor, telemetry)
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    pta = build_pta()
    x0 = pta.initial_sample(np.random.default_rng(0))
    base = Path(args.outdir)
    if base.exists():
        shutil.rmtree(base)
    ref_dir, run_dir = base / "baseline", base / "supervised"

    def gibbs():
        return PTABlockGibbs(pta, backend="numpy", seed=7, progress=False)

    ref = gibbs().sample(x0, outdir=ref_dir, niter=args.niter,
                         save_every=args.save_every)

    telemetry.reset()
    faults.clear()
    for spec in FAULTS[args.fault]:
        faults.inject(at_row=at_row, times=1, **spec)
    try:
        chain, rep = supervisor.run_supervised(
            gibbs(), x0, run_dir, niter=args.niter,
            save_every=args.save_every, backoff_base=0.0, jitter=0.0)
    finally:
        faults.clear()

    bitwise = bool(np.array_equal(chain, ref))
    on_disk = bool(np.array_equal(np.load(run_dir / "chain.npy"),
                                  np.load(ref_dir / "chain.npy")))
    report = {
        "fault": args.fault,
        "at_row": at_row,
        "niter": args.niter,
        "bitwise_recovery": bitwise,
        "on_disk_bitwise": on_disk,
        "supervisor": rep.as_dict(),
        "counters": telemetry.snapshot(),
    }
    print(json.dumps(report, indent=2))
    if not (bitwise and on_disk):
        print("FAIL: recovered chain differs from baseline",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
