"""End-to-end kill/resume/verify drill for the resilient runtime.

Runs a full recovery story on a synthetic single-pulsar PTA (no
reference data needed), always against an uninterrupted baseline run,
and asserts the recovered chain is bit-identical to it.  Prints a JSON
report with the telemetry counters/gauges and retry metadata.

Scenarios (``--scenario``):

- ``fault`` (default): supervised run with a fault injected mid-stream
  (``--fault kill|truncate|corrupt|nan|xla``; default "kill" — death
  between the chain.npy and bchain.npy replaces, the torn-checkpoint
  window), recovered by the supervisor's retry/rollback machinery.
- ``preempt``: a SIGTERM-style drain request mid-run stops the loop at
  the next seam, flushes a verified checkpoint, and surfaces as the
  supervisor's resumable ``preempted`` status; a second incarnation
  resumes bit-identically.
- ``stall``: a wedged dispatch trips the watchdog's EMA deadline, the
  chunk is abandoned as the ``stall`` failure class, and the stall
  retry budget resumes the run bit-identically (jax backend).
- ``reshard``: a run checkpointed under an 8-device mesh resumes under
  ``--devices`` (default 2; a 2-d shape like ``2x4`` runs the 4-chain
  (chain, pulsar)-mesh variant) via ``integrity.reshard_restore`` and
  the extended chain is bitwise-identical to the uninterrupted
  baseline — the elasticity contract (jax backend, forces 8 virtual
  host devices).
- ``tenant_evict``: the serving drill — three heterogeneous jobs
  multiplexed through one bucket get churned by injected evictions and
  then the whole service is killed mid-multiplex; a fresh incarnation
  readmits every in-flight job from its own verified checkpoint dir and
  each finishes bit-identical to its uninterrupted solo baseline (jax
  backend).
- ``append``: the standing-model drill — a finished job's dataset
  grows past its bucket, the cross-bucket migration is killed at the
  re-pad seam (``kill_mid_migration``), a fresh incarnation re-forks
  idempotently from the parent's verified checkpoint, the retained-row
  prefix survives **bitwise** through the re-bucketing, and a
  corrupted lineage link (``corrupt_lineage``) degrades resolution to
  the newest verified ancestor (jax backend).

Usage: python tools/chaos_probe.py [--scenario fault|preempt|stall|reshard|tenant_evict|append]
       [--fault kill|truncate|corrupt|nan|xla] [--niter N]
       [--save-every N] [--at-row N] [--devices N] [--outdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
from pathlib import Path

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def build_pta():
    from pulsar_timing_gibbsspec_tpu.data.dataset import Pulsar
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general

    DAY = 86400.0
    rng = np.random.default_rng(11)
    n = 60
    span = 6.0 * 365.25 * DAY
    toas = np.sort(rng.uniform(0.0, span, n)) + 53000.0 * DAY
    errs = np.full(n, 5e-7)
    res = errs * rng.standard_normal(n)
    t = (toas - toas.mean()) / span
    M = np.column_stack([np.ones(n), t, t * t])
    psr = Pulsar(
        name="FAKE_CHAOS", toas=toas, toaerrs=errs, residuals=res,
        freqs=np.full(n, 1400.0),
        backend_flags=np.asarray(["sim"] * n, dtype=object),
        Mmat=M, fitpars=["offset", "F0", "F1"],
        flags={"pta": "NANOGrav"},
        pos=np.array([1.0, 0.0, 0.0]))
    return model_general([psr], red_var=False, white_vary=False,
                         common_psd="spectrum", common_components=4)


FAULTS = {
    # torn-checkpoint window: die after chain.npy is replaced but
    # before bchain.npy/adapt.npz/manifest.json are
    "kill": [dict(kind="crash", point="chainstore.between_replaces")],
    # damage a file of the just-completed checkpoint set, then die
    # before anything can rewrite it: resume must detect the bad
    # checksum and roll back to the .bak set
    "truncate": [dict(kind="truncate_file", path="chain.npy",
                      point="chainstore.post_save"),
                 dict(kind="crash", point="chainstore.post_save")],
    "corrupt": [dict(kind="corrupt_file", path="adapt.npz",
                     point="chainstore.post_save"),
                dict(kind="crash", point="chainstore.post_save")],
    # poison one recorded row: the sentinel must reject the chunk
    # before it reaches disk
    "nan": [dict(kind="nan_rows", point="sample.loop")],
    # transient device failure: retry with capped backoff
    "xla": [dict(kind="xla_error", point="sample.loop")],
}


def _fresh(base: Path) -> Path:
    if base.exists():
        shutil.rmtree(base)
    return base


def _parse_devices(s):
    """``--devices`` value: an int, or ``CxP`` -> a 2-tuple of ints."""
    if "x" in s.lower():
        c, p = s.lower().split("x")
        return (int(c), int(p))
    return int(s)


def scenario_fault(args, base):
    from pulsar_timing_gibbsspec_tpu.runtime import (
        faults, supervisor, telemetry)
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    pta = build_pta()
    x0 = pta.initial_sample(np.random.default_rng(0))
    ref_dir, run_dir = base / "baseline", base / "supervised"

    def gibbs():
        return PTABlockGibbs(pta, backend="numpy", seed=7, progress=False)

    ref = gibbs().sample(x0, outdir=ref_dir, niter=args.niter,
                         save_every=args.save_every)

    telemetry.reset()
    faults.clear()
    for spec in FAULTS[args.fault]:
        faults.inject(at_row=args.at_row, times=1, **spec)
    try:
        chain, rep = supervisor.run_supervised(
            gibbs(), x0, run_dir, niter=args.niter,
            save_every=args.save_every, backoff_base=0.0, jitter=0.0)
    finally:
        faults.clear()

    bitwise = bool(np.array_equal(chain, ref))
    on_disk = bool(np.array_equal(np.load(run_dir / "chain.npy"),
                                  np.load(ref_dir / "chain.npy")))
    return bitwise and on_disk, {
        "fault": args.fault,
        "bitwise_recovery": bitwise,
        "on_disk_bitwise": on_disk,
        "supervisor": rep.as_dict(),
    }


def scenario_preempt(args, base):
    """Drain-to-checkpoint, then a second incarnation resumes bitwise."""
    from pulsar_timing_gibbsspec_tpu.runtime import (
        faults, integrity, preemption, supervisor, telemetry)
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    pta = build_pta()
    x0 = pta.initial_sample(np.random.default_rng(0))
    ref_dir, run_dir = base / "baseline", base / "supervised"

    def gibbs():
        return PTABlockGibbs(pta, backend="numpy", seed=7, progress=False)

    ref = gibbs().sample(x0, outdir=ref_dir, niter=args.niter,
                         save_every=args.save_every)

    telemetry.reset()
    faults.clear()
    preemption.reset()
    faults.inject("sigterm_at_seam", point="sample.loop",
                  at_row=args.at_row, times=1, seconds=60.0)
    try:
        _, rep = supervisor.run_supervised(
            gibbs(), x0, run_dir, niter=args.niter,
            save_every=args.save_every, backoff_base=0.0, jitter=0.0)
    finally:
        faults.clear()
    v = integrity.verify(run_dir)

    # next incarnation: fresh process, drain flag gone
    preemption.reset()
    chain2, rep2 = supervisor.run_supervised(
        gibbs(), x0, run_dir, niter=args.niter,
        save_every=args.save_every, backoff_base=0.0, jitter=0.0)
    bitwise = bool(np.array_equal(chain2, ref))
    ok = (rep.status == "preempted" and v["ok"]
          and rep2.status == "completed" and bitwise)
    return ok, {
        "drain_status": rep.status,
        "drain_checkpoint": v,
        "drain_latency_ms": telemetry.get_gauge("drain_latency_ms"),
        "resume_status": rep2.status,
        "bitwise_recovery": bitwise,
        "supervisor": rep2.as_dict(),
    }


def scenario_stall(args, base):
    """Watchdog abort of a wedged dispatch, then bitwise stall-retry."""
    from pulsar_timing_gibbsspec_tpu.runtime import (
        faults, supervisor, telemetry)
    from pulsar_timing_gibbsspec_tpu.runtime.watchdog import DispatchWatchdog
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    pta = build_pta()
    x0 = pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=7, progress=False, warmup_sweeps=2,
              chunk_size=4)
    ref = PTABlockGibbs(pta, **kw).sample(
        x0, outdir=base / "baseline", niter=args.niter,
        save_every=args.save_every)

    telemetry.reset()
    faults.clear()
    faults.inject("stall", point="dispatch.chunk", at_row=args.at_row,
                  times=1, seconds=5.0, backend="jax")
    wd = DispatchWatchdog(k=4.0, floor_s=0.4, first_floor_s=120.0,
                          poll_s=0.02)
    try:
        chain, rep = supervisor.run_supervised(
            PTABlockGibbs(pta, watchdog=wd, **kw), x0,
            base / "supervised", niter=args.niter,
            save_every=args.save_every, backoff_base=0.0, jitter=0.0)
    finally:
        faults.clear()
    bitwise = bool(np.array_equal(chain, ref))
    ok = (bitwise and rep.status == "completed" and rep.stall_retries >= 1)
    return ok, {
        "bitwise_recovery": bitwise,
        "stall_retries": rep.stall_retries,
        "watchdog_stalls": telemetry.get("watchdog_stalls"),
        "watchdog_dumps": telemetry.get("watchdog_dumps"),
        "supervisor": rep.as_dict(),
    }


def scenario_reshard(args, base):
    """8-device checkpoint resumed on --devices, bitwise vs baseline.

    A 2-d ``--devices CxP`` (e.g. ``2x4``) flips the drill to the
    4-chain variant: the baseline and the partial run execute on a
    (2, 4) chains x pulsars mesh (padded width 4) and the checkpoint
    resumes on the requested axis shape — any ``C`` dividing the 4
    chains and ``P`` dividing the padded width of 4."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh
    from pulsar_timing_gibbsspec_tpu.runtime import integrity, telemetry
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    pta = build_pta()
    x0 = pta.initial_sample(np.random.default_rng(0))
    two_d = isinstance(args.devices, tuple)
    kw = dict(backend="jax", seed=7, progress=False, warmup_sweeps=2,
              chunk_size=4)
    if two_d:
        kw.update(nchains=4, pad_pulsars=4)
        src_shape = (2, 4)
    else:
        kw.update(pad_pulsars=8)
        src_shape = 8
    part = max(args.save_every, (args.niter // 2) // args.save_every
               * args.save_every)

    telemetry.reset()
    ref = PTABlockGibbs(pta, mesh=make_mesh(src_shape), **kw).sample(
        x0, outdir=base / "baseline", niter=args.niter,
        save_every=args.save_every)
    src = base / "resharded"
    PTABlockGibbs(pta, mesh=make_mesh(src_shape), **kw).sample(
        x0, outdir=src, niter=part, save_every=args.save_every)

    g = integrity.reshard_restore(src, pta, devices=args.devices,
                                  seed=7, progress=False,
                                  warmup_sweeps=2, chunk_size=4)
    chain = g.sample(x0, outdir=src, niter=args.niter, resume=True,
                     save_every=args.save_every)
    bitwise = bool(np.array_equal(chain, ref))
    info = integrity.read_layout(src)
    return bitwise, {
        "bitwise_recovery": bitwise,
        "checkpointed_rows": part,
        "devices_from": list(src_shape) if two_d else src_shape,
        "devices_to": list(args.devices) if two_d else args.devices,
        "layout": info["layout"],
        "shard_map": info["shard_map"],
    }


def scenario_tenant_evict(args, base):
    """Service killed mid-multiplex; every in-flight job resumes from
    its own verified checkpoint dir, bitwise vs solo baselines."""
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.runtime import (
        faults, integrity, telemetry)
    from pulsar_timing_gibbsspec_tpu.runtime.faults import InjectedCrash
    from pulsar_timing_gibbsspec_tpu.serve import (
        BucketSpec, BucketTable, SamplerService)

    ptas = [build_model(synthetic_pulsars(2, ntoa, tm_cols=3, seed=i), 3)
            for i, ntoa in enumerate((24, 30, 36))]
    table = BucketTable([BucketSpec(2, 40, 24, 3)])
    svc_kw = dict(slots=2, chunk=4, save_every=1)

    solos = []
    for i, pta in enumerate(ptas):
        svc = SamplerService(base / f"solo{i}", table, **svc_kw)
        job = svc.submit(pta, args.niter, job_id=f"job{i}", tenant_id=i)
        svc.run()
        solos.append(job.chain.copy())

    # churn residency with injected evictions, then kill the service
    # while >= 2 jobs are mid-flight (max_retries=0: the crash escapes)
    telemetry.reset()
    faults.clear()
    mux_root = base / "mux"
    faults.inject("tenant_evict", point="serve.chunk", at_row=2, times=2)
    faults.inject("crash", point="serve.chunk", at_row=args.at_row,
                  times=1)
    svc = SamplerService(mux_root, table, max_retries=0, **svc_kw)
    jobs = [svc.submit(pta, args.niter, job_id=f"job{i}", tenant_id=i)
            for i, pta in enumerate(ptas)]
    died = False
    try:
        svc.run()
    except InjectedCrash:
        died = True
    finally:
        faults.clear()
    evictions = int(telemetry.get_gauge("tenant_evictions") or 0)
    in_flight = [j.job_id for j in jobs if 0 < j.it < args.niter]
    rows_at_kill = {j.job_id: int(j.it) for j in jobs}
    checkpoints = {j.job_id: integrity.verify(mux_root / j.job_id)
                   for j in jobs if j.it > 0}

    # fresh incarnation: resubmit the same identities, run to done
    svc2 = SamplerService(mux_root, table, **svc_kw)
    jobs2 = [svc2.submit(pta, args.niter, job_id=f"job{i}", tenant_id=i)
             for i, pta in enumerate(ptas)]
    svc2.run()
    bitwise = {j.job_id: bool(np.array_equal(j.chain, solos[i])
                              and np.array_equal(
                                  np.load(mux_root / j.job_id / "chain.npy"),
                                  solos[i]))
               for i, j in enumerate(jobs2)}
    ok = (died and evictions >= 1 and len(in_flight) >= 2
          and all(v["ok"] for v in checkpoints.values())
          and all(j.state == "done" for j in jobs2)
          and all(bitwise.values()))
    return ok, {
        "service_died": died,
        "tenant_evictions": evictions,
        "in_flight_at_kill": in_flight,
        "checkpoints_verified": {k: v["ok"] for k, v in checkpoints.items()},
        "resumed_states": {j.job_id: j.state for j in jobs2},
        "bitwise_recovery": bitwise,
        "rows_at_kill": rows_at_kill,
    }


def scenario_append(args, base):
    """Append-TOAs migration killed at the re-pad seam: recovery must
    land on the parent (nothing torn), a re-fork must be idempotent,
    the retained prefix bitwise, and a severed lineage link must
    degrade to the newest verified ancestor."""
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.data import append_polynomial_toas
    from pulsar_timing_gibbsspec_tpu.runtime import (
        faults, lineage, telemetry)
    from pulsar_timing_gibbsspec_tpu.runtime.faults import InjectedCrash
    from pulsar_timing_gibbsspec_tpu.serve import (
        BucketSpec, BucketTable, SamplerService)

    psrs = synthetic_pulsars(2, 24, tm_cols=3, seed=0)
    pta = build_model(psrs, 3)
    grown = build_model(append_polynomial_toas(psrs, 24, seed=5), 3)
    # ntoa 24 -> 48 overflows the first bucket; the second grows BOTH
    # padded axes (TOAs and basis), so the re-pad zero-embed is real
    table = BucketTable([BucketSpec(2, 40, 24, 3),
                         BucketSpec(2, 64, 32, 3)])
    svc_kw = dict(slots=2, chunk=4, save_every=1)
    root = base / "svc"
    pdir, cdir = root / "parent", root / "child"

    telemetry.reset()
    faults.clear()
    svc = SamplerService(root, table, **svc_kw)
    parent = svc.submit(pta, args.niter, job_id="parent", tenant_id=0)
    svc.run()
    if parent.state != "done":
        return False, {"error": f"parent failed: {parent.failure}"}
    parent_rows = np.load(pdir / "chain.npy").copy()

    # kill mid-re-pad: the child dir must be ABSENT afterwards (the
    # fork stages + atomically renames), never a torn hybrid
    faults.inject("kill_mid_migration", point="migrate.mid_repad",
                  times=1)
    died = False
    try:
        svc.append_job(grown, 2 * args.niter, parent_id="parent",
                       job_id="child", outdir=cdir)
    except InjectedCrash:
        died = True
    finally:
        faults.clear()
    torn_free = not (cdir / "manifest.json").exists()

    # fresh incarnation knows only the parent's directory: re-append,
    # run the child generation to done
    svc2 = SamplerService(root, table, **svc_kw)
    child = svc2.append_job(grown, 2 * args.niter, parent_outdir=pdir,
                            job_id="child", outdir=cdir)
    svc2.run()
    prefix = bool(np.array_equal(np.load(cdir / "chain.npy")[:args.niter],
                                 parent_rows))
    ancestry = lineage.walk(cdir)
    resolved, _ = lineage.resolve_verified(cdir)

    # sever the hash chain (both manifests, so .bak cannot heal it):
    # resolution must degrade to the verified parent, with the report
    faults._corrupt_lineage(cdir)
    degraded, report = lineage.resolve_verified(cdir)
    ok = (died and torn_free and child.state == "done"
          and int(child.generation) == 1
          and tuple(child.bucket.as_tuple()) == (2, 64, 32, 3)
          and prefix and len(ancestry) == 2
          and str(resolved) == str(cdir) and str(degraded) == str(pdir))
    return ok, {
        "service_died": died,
        "torn_free_after_kill": torn_free,
        "child_state": child.state,
        "child_generation": int(child.generation),
        "child_bucket": list(child.bucket.as_tuple()),
        "prefix_bitwise": prefix,
        "ancestry_generations": [a["generation"] for a in ancestry],
        "resolved": str(resolved),
        "degraded_to": str(degraded),
        "degrade_report": [(r["generation"], r["ok"]) for r in report],
        "lineage_degrades": telemetry.get("lineage_degrades"),
        "migrations": telemetry.get("migrations"),
    }


SCENARIOS = {"fault": scenario_fault, "preempt": scenario_preempt,
             "stall": scenario_stall, "reshard": scenario_reshard,
             "tenant_evict": scenario_tenant_evict,
             "append": scenario_append}
#: jax-backed scenarios run chunked; small defaults keep them quick
_JAX_DEFAULTS = {"stall": (16, 4), "reshard": (16, 4),
                 "tenant_evict": (12, 4), "append": (12, 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", choices=sorted(SCENARIOS),
                    default="fault")
    ap.add_argument("--fault", choices=sorted(FAULTS), default="kill",
                    help="fault kind (scenario 'fault' only)")
    ap.add_argument("--niter", type=int, default=None,
                    help="default 60 (numpy scenarios) or 16 (jax)")
    ap.add_argument("--save-every", type=int, default=None,
                    help="default 20 (numpy scenarios) or 4 (jax)")
    ap.add_argument("--at-row", type=int, default=None,
                    help="inject at the first seam with row >= AT_ROW "
                    "(default: niter // 2 rounded into the steady loop)")
    ap.add_argument("--devices", type=_parse_devices, default=2,
                    help="resume device count (scenario 'reshard'): an "
                    "int for the 1-d pulsar mesh (must divide the padded "
                    "width of 8), or CHAINSxPULSARS (e.g. 2x4) for the "
                    "2-d 4-chain drill (C | 4 chains, P | padded width 4)")
    ap.add_argument("--outdir", default="/tmp/chaos_probe")
    args = ap.parse_args()
    dflt = _JAX_DEFAULTS.get(args.scenario, (60, 20))
    args.niter = dflt[0] if args.niter is None else args.niter
    args.save_every = dflt[1] if args.save_every is None else args.save_every
    if args.at_row is None:
        if args.scenario == "tenant_evict":
            # the serve.chunk seam counts CHUNKS, not rows: kill at the
            # 4th chunk, after the eviction churn but mid-multiplex
            args.at_row = 4
        else:
            # land past the warmup/compile chunks for the jax scenarios
            args.at_row = args.niter // 2 + (3 if args.scenario == "stall"
                                             else 0)

    if args.scenario == "reshard":
        # must precede the first jax import: the contract drill needs 8
        # virtual host devices to stand in for the 8-way mesh
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_"
                                   "device_count=8").strip()

    from pulsar_timing_gibbsspec_tpu.runtime import telemetry

    base = _fresh(Path(args.outdir))
    ok, detail = SCENARIOS[args.scenario](args, base)
    report = {
        "scenario": args.scenario,
        "at_row": args.at_row,
        "niter": args.niter,
        "ok": bool(ok),
        "counters": telemetry.snapshot(),
        "gauges": telemetry.gauges(),
    }
    report.update(detail)
    print(json.dumps(report, indent=2))
    if not ok:
        print(f"FAIL: scenario '{args.scenario}' contract violated",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
