"""The static gate, one command: ``python tools/ci_lint.py``.

Runs the three analysis layers in cost order and reports a combined
status — the same set the ``lint`` pytest marker covers:

1. ruff        — generic Python lint (pyflakes/pycodestyle/isort),
                 skipped with a note when not installed;
2. jaxlint     — AST-level JAX discipline (rules R1-R7), ratcheted
                 against ``jaxlint_baseline.json``;
3. racecheck   — static concurrency / signal-safety / use-after-donate
                 / state-machine audit of the runtime and serving
                 layers (pure AST, the checked modules are never
                 imported), ratcheted against
                 ``racecheck_baseline.json``;
4. numcheck    — precision-flow / reassociation / exact-body audit of
                 the fast numcheck contracts (N1-N5 over the traced
                 entry builders), ratcheted against
                 ``numcheck_baseline.json`` with justified-baseline
                 semantics;
5. jaxprcheck  — jaxpr/HLO contract audit of the fast (CPU-traceable)
                 contracts in ``contracts/``, ratcheted against
                 ``jaxprcheck_baseline.json``; also fails when a jit
                 entry builder has no pinned contract (coverage);
6. perfwatch   — the perf-ledger regression gate over
                 ``PERF_LEDGER.jsonl`` plus the static cost-model
                 self-check (CPU tracing only, no device execution).

With ``--chaos`` an optional seventh layer runs the quick seeded chaos
campaign (``tools/chaos_campaign.py --quick --seeds 5``) — the serving
tier's blast-radius invariants under randomized fault schedules.  It
executes real (CPU) sampling, so it is opt-in rather than part of the
static gate.

Each layer runs in its own interpreter (jaxprcheck must configure the
JAX platform before jax is first imported), so a crash in one cannot
mask another.  Exit status is 0 only when every layer passes.
Importing this module has no side effects.
"""


def main(argv=None) -> int:
    import os
    import shutil
    import subprocess
    import sys

    repo = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    extra = list(argv) if argv is not None else sys.argv[1:]
    chaos = "--chaos" in extra
    if chaos:
        extra = [a for a in extra if a != "--chaos"]

    layers = []
    exe = shutil.which("ruff")
    if exe is None:
        print("ci_lint: ruff not installed; skipping generic lint")
    else:
        layers.append(("ruff", [exe, "check", "."]))
    layers.append(("jaxlint",
                   [sys.executable, "-m",
                    "pulsar_timing_gibbsspec_tpu.analysis"]))
    layers.append(("racecheck",
                   [sys.executable, "-m",
                    "pulsar_timing_gibbsspec_tpu.analysis.racecheck"]))
    layers.append(("numcheck",
                   [sys.executable, "-m",
                    "pulsar_timing_gibbsspec_tpu.analysis.numcheck",
                    "--fast"]))
    layers.append(("jaxprcheck",
                   [sys.executable, "-m",
                    "pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck",
                    "--fast", *extra]))
    layers.append(("perfwatch",
                   [sys.executable,
                    os.path.join("tools", "perfwatch.py"), "--check"]))
    if chaos:
        layers.append(("chaos",
                       [sys.executable,
                        os.path.join("tools", "chaos_campaign.py"),
                        "--quick", "--seeds", "5"]))

    failed = []
    for name, cmd in layers:
        shown = [os.path.basename(cmd[0])] + cmd[1:]
        print(f"ci_lint: [{name}] {' '.join(shown)}")
        rc = subprocess.run(cmd, cwd=repo, check=False).returncode
        if rc != 0:
            failed.append(name)
    if failed:
        print(f"ci_lint: FAILED ({', '.join(failed)})", file=sys.stderr)
        return 1
    print(f"ci_lint: OK ({len(layers)} layer(s) clean)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
