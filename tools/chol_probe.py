"""A/B the b_mh proposal's factor path on the real device.

(a) XLA native ``jnp.linalg.cholesky`` + 3 ``solve_triangular`` (the
    current ``precond_cholesky``/``precond_solve``/``precond_sample``), vs
(b) matmul-scheduled ``blocked_chol_inv`` in f32 + explicit-inverse
    matvecs,

at the bench shape (C, P, B, B).  Decides whether the 13.5 ms ``b_mh``
block (75% of the steady sweep at C=64, tools/sweep_probe.py) is the
native small-batch factorization lowering.

Usage: python tools/chol_probe.py [--nchains 64] [--B 37]
       [--kernel pallas|xla]   # extra row: fused ops/kernels chain
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

if __name__ == "__main__":   # script bootstrap; no import side effects
    sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nchains", type=int, default=64)
    ap.add_argument("--P", type=int, default=45)
    ap.add_argument("--B", type=int, default=37)
    ap.add_argument("--kernel", choices=("pallas", "xla"), default=None,
                    help="also time the fused ops/kernels "
                         "chol_solve_sample at this tier (extra row in "
                         "the table; off-TPU 'pallas' interprets)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.ops.linalg import (blocked_chol_inv,
                                                        precond_cholesky,
                                                        precond_sample,
                                                        precond_solve)
    from pulsar_timing_gibbsspec_tpu.profiling import _scan_time

    C, P, B = args.nchains, args.P, args.B
    rng = np.random.default_rng(0)
    M = rng.standard_normal((C, P, B, B))
    A = np.einsum("cpij,cpkj->cpik", M, M) + 10.0 * np.eye(B)
    A = jnp.asarray(A, jnp.float32)
    d = jnp.asarray(rng.standard_normal((C, P, B)), jnp.float32)

    # _scan_time wants body(x, b, key) -> (x, b); thread the data through b
    def native(x, b, key):
        L, dj = precond_cholesky(A + x * jnp.eye(B, dtype=jnp.float32))
        mean = precond_solve(L, dj, d)
        z = jr.normal(key, d.shape, jnp.float32)
        s = precond_sample(L, dj, mean, z)
        return x + 0.0 * s[0, 0, 0], b

    def blocked(x, b, key):
        Ax = A + x * jnp.eye(B, dtype=jnp.float32)
        diag = jnp.diagonal(Ax, axis1=-2, axis2=-1)
        dj = 1.0 / jnp.sqrt(diag)
        An = Ax * dj[..., :, None] * dj[..., None, :]
        L, Li = blocked_chol_inv(An)
        w = jnp.einsum("...ij,...j->...i", Li, dj * d)
        mean = dj * jnp.einsum("...ji,...j->...i", Li, w)
        z = jr.normal(key, d.shape, jnp.float32)
        s = mean + dj * jnp.einsum("...ji,...j->...i", Li, z)
        return x + 0.0 * s[0, 0, 0], b

    x = jnp.zeros((), jnp.float32)
    b = jnp.zeros((), jnp.float32)
    t_native = _scan_time(native, x, b, 20, 3)
    t_blocked = _scan_time(blocked, x, b, 20, 3)
    print(f"native cholesky+solves: {t_native*1e3:7.2f} ms")
    print(f"blocked_chol_inv path:  {t_blocked*1e3:7.2f} ms")

    if args.kernel:
        # the production fused chain at the requested tier: Jacobi
        # precondition + factor + both solves + sample in one dispatch
        from pulsar_timing_gibbsspec_tpu.config import settings
        from pulsar_timing_gibbsspec_tpu.ops import kernels

        settings.kernel_tier = args.kernel

        def fused(x, b, key):
            Ax = A + x * jnp.eye(B, dtype=jnp.float32)
            z = jr.normal(key, d.shape, jnp.float32)
            outs = jax.vmap(
                lambda a, dd, zz: kernels.chol_solve_sample(a, dd, zz)
            )(Ax, d, z)
            return x + 0.0 * outs[4][0, 0, 0], b

        t_fused = _scan_time(fused, x, b, 20, 3)
        print(f"fused chol_solve_sample [{args.kernel}]:"
              f" {t_fused*1e3:7.2f} ms")

    # accuracy cross-check of the blocked f32 factor against native
    L, dj = precond_cholesky(A)
    mean_n = precond_solve(L, dj, d)
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    djb = 1.0 / jnp.sqrt(diag)
    An = A * djb[..., :, None] * djb[..., None, :]
    Lb, Lib = blocked_chol_inv(An)
    w = jnp.einsum("...ij,...j->...i", Lib, djb * d)
    mean_b = djb * jnp.einsum("...ji,...j->...i", Lib, w)
    rel = float(jnp.max(jnp.abs(mean_b - mean_n))
                / (jnp.max(jnp.abs(mean_n)) + 1e-30))
    print(f"max |mean_blocked - mean_native| / max|mean|: {rel:.2e}")


if __name__ == "__main__":
    main()
