"""The performance observatory (obs.perf + analysis.jaxprcheck.cost +
tools/perfwatch.py): streaming stage gauges, anomaly capture, the static
roofline cost model, and the perf-ledger regression gate.

The cost-model acceptance bound lives here: the jaxpr-derived
``dot_general`` FLOPs of the CRN Gram einsum must match the analytic
``profiling.flop_counts`` term within 5% — the tie that keeps the
roofline attribution honest.
"""

import gzip
import importlib.util
import json
import math
from pathlib import Path

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.config import settings

settings.apply()

from pulsar_timing_gibbsspec_tpu.obs import metrics, trace as otrace
from pulsar_timing_gibbsspec_tpu.obs.perf import (DEFAULT_BANDS,
                                                  FlightRecorder,
                                                  RingSeries,
                                                  StageAggregator,
                                                  check_ledger,
                                                  ledger_append,
                                                  ledger_read,
                                                  make_ledger_record,
                                                  merge_perfetto)
from pulsar_timing_gibbsspec_tpu.runtime import telemetry

_REPO = Path(__file__).resolve().parents[1]


def _load_perfwatch():
    spec = importlib.util.spec_from_file_location(
        "perfwatch", _REPO / "tools" / "perfwatch.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# RingSeries


def test_ring_series_bounds_and_stats():
    s = RingSeries(cap=8, ema_alpha=0.5)
    assert s.last() is None
    for v in range(20):
        s.append(float(v))
    assert len(s) == 8                    # window bounded by cap
    assert s.count == 20                  # total appended still counted
    assert s.last() == 19.0
    vals = np.sort(s.values())
    np.testing.assert_array_equal(vals, np.arange(12.0, 20.0))
    assert 12.0 <= s.percentile(50) <= 19.0
    # EMA folds online over ALL samples, not just the retained window
    ema = None
    for v in range(20):
        ema = v if ema is None else 0.5 * v + 0.5 * ema
    assert s.ema == pytest.approx(ema)


# ---------------------------------------------------------------------------
# the static cost model


def test_cost_model_dot_general_exact():
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.cost import cost_of

    a = jnp.zeros((2, 16, 5))
    b = jnp.zeros((2, 16, 7))

    def f(a, b):
        return jnp.einsum("pnb,pnc->pbc", a, b)

    rep = cost_of(f, (a, b))
    # 2 * batch(2) * m(5) * n(7) * k(16)
    assert rep.dot_flops == 2 * 2 * 5 * 7 * 16
    assert rep.flops >= rep.dot_flops


def test_cost_model_scan_multiplies_by_length():
    import jax
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.cost import cost_of

    w = jnp.zeros((4, 4))

    def body_fn(c, _):
        return w @ c, None

    def scanned(c):
        out, _ = jax.lax.scan(body_fn, c, None, length=10)
        return out

    def once(c):
        return w @ c

    rep_scan = cost_of(scanned, (jnp.zeros((4,)),))
    rep_once = cost_of(once, (jnp.zeros((4,)),))
    assert rep_scan.dot_flops == 10 * rep_once.dot_flops


def test_cost_model_cholesky_rule():
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.cost import cost_of

    n = 12
    a = jnp.eye(n)

    def f(a):
        return jnp.linalg.cholesky(a)

    rep = cost_of(f, (a,))
    assert rep.flops >= n ** 3 / 3.0
    assert rep.hbm_bytes > 0


def test_cost_model_matches_flop_counts_on_crn_gram():
    """The roofline acceptance bound: static model within 5% of the
    analytic FLOP count on the CRN Gram einsum."""
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.cost import cost_of
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.profiling import flop_counts
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    cm = compile_pta(build_model(synthetic_pulsars(3, 40, tm_cols=3), 3))
    x0 = jnp.zeros((cm.nx,), cm.cdtype)

    def gram(x):
        N = cm.ndiag_fast(x)
        TN = cm.T / N[:, :, None]
        return jnp.einsum("pnb,pnc->pbc", TN, cm.T,
                          preferred_element_type=cm.dtype,
                          precision="highest")

    rep = cost_of(gram, (x0,))
    want = flop_counts(cm)["gram_einsum"]
    assert want > 0
    assert abs(rep.dot_flops - want) <= 0.05 * want


def test_roofline_classification_and_mfu():
    from pulsar_timing_gibbsspec_tpu.profiling import roofline

    costs = {
        "fat_matmul": {"flops": 4.0e12, "hbm_bytes": 1.0e9},
        "streamer": {"flops": 1.0e9, "hbm_bytes": 1.0e9},
    }
    roof = roofline(costs, per_block_ms={"fat_matmul": 100.0},
                    peak_flops=1.0e14, peak_bw=1.0e12)
    assert roof["ridge_flop_per_byte"] == pytest.approx(100.0)
    blocks = roof["blocks"]
    assert blocks["fat_matmul"]["bound"] == "compute"
    assert blocks["streamer"]["bound"] == "bandwidth"
    # MFU: 4e12 flops in 0.1 s on a 1e14 peak = 0.4
    assert blocks["fat_matmul"]["mfu"] == pytest.approx(0.4, rel=1e-6)
    # no measured time for streamer: mfu/ms absent, static fields stay
    assert "mfu" not in blocks["streamer"]
    assert blocks["streamer"]["intensity"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# streaming stage telemetry


def test_stage_aggregator_folds_spans_to_gauges():
    telemetry.reset("dispatch_ms")
    agg = StageAggregator(job="tj").install()
    try:
        # observers activate the span seams even with tracing disabled
        with otrace.span("chunk.dispatch"):
            pass
        with otrace.span("chunk.writeback"):
            pass
        with otrace.span("not.a.stage"):
            pass
    finally:
        agg.uninstall()
    summ = agg.summary()
    assert set(summ) == {"enqueue", "writeback"}
    assert summ["enqueue"]["n"] == 1
    g = telemetry.get_gauge("dispatch_ms", job="tj", stage="enqueue",
                            stat="last")
    assert g is not None and g >= 0.0
    body = metrics.render_telemetry()
    assert 'ptgibbs_dispatch_ms{job="tj",stage="enqueue",stat="ema"}' in body
    telemetry.reset("dispatch_ms")
    # uninstalled: spans are the shared nullcontext again (zero cost)
    assert otrace.span("chunk.dispatch") is otrace.span("chunk.d2h")


def test_stage_aggregator_band_breach_triggers():
    class FakeRecorder:
        reasons = []

        def install(self):
            return self

        def uninstall(self):
            pass

        def trigger(self, reason):
            self.reasons.append(reason)

    telemetry.reset("stage_band_breaches")
    rec = FakeRecorder()
    agg = StageAggregator(job="tb", band_k=3.0, warm_n=4, recorder=rec)
    for _ in range(6):
        agg.observe("device", 10.0)
    assert telemetry.get("stage_band_breaches", stage="device",
                         job="tb") == 0
    agg.observe("device", 100.0)          # 10x the EMA: breach
    assert telemetry.get("stage_band_breaches", stage="device",
                         job="tb") == 1
    assert rec.reasons == ["band_breach:device"]
    telemetry.reset("stage_band_breaches")
    telemetry.reset("dispatch_ms")


# ---------------------------------------------------------------------------
# anomaly capture


def _fake_xla_trace(profile_dir, name="plugin.trace.json.gz"):
    d = Path(profile_dir) / "plugins" / "profile" / "run1"
    d.mkdir(parents=True, exist_ok=True)
    doc = {"traceEvents": [{"ph": "X", "name": "fusion.1", "ts": 0,
                            "dur": 5, "pid": 1, "tid": 1}]}
    with gzip.open(d / name, "wt") as fh:
        json.dump(doc, fh)


def test_flight_recorder_capture_and_budget(tmp_path):
    telemetry.reset("anomaly_captures")
    rec = FlightRecorder(tmp_path, window_chunks=2, max_captures=1,
                         profiler=False).install()
    try:
        assert not rec._armed
        otrace.instant("watchdog.soft", ema=1.0)      # the trigger
        assert rec._armed
        _fake_xla_trace(rec._profile_dir())
        with otrace.span("chunk.dispatch"):
            pass
        with otrace.span("chunk.dispatch"):
            pass                                       # window closes
        assert not rec._armed
        assert len(rec.captures) == 1
        doc = json.loads(Path(rec.captures[0]).read_text())
        names = [e.get("name") for e in doc["traceEvents"]]
        assert "fusion.1" in names                     # XLA side
        assert "chunk.dispatch" in names               # span side
        assert doc["metadata"]["reason"] == "watchdog.soft"
        # capture budget spent: further triggers are refused
        assert rec.trigger("again") is False
    finally:
        rec.uninstall()
    assert telemetry.get("anomaly_captures") == 1
    telemetry.reset("anomaly_captures")


def test_merge_perfetto_tolerates_missing_profile_dir(tmp_path):
    out = tmp_path / "m.trace.json"
    merge_perfetto(tmp_path / "nope", out,
                   extra_events=[{"ph": "i", "name": "solo", "ts": 1}],
                   meta={"reason": "t"})
    doc = json.loads(out.read_text())
    assert [e["name"] for e in doc["traceEvents"]] == ["solo"]


# ---------------------------------------------------------------------------
# the perf ledger


def _rec(value, metric="m", kind="bench", dev="cpu", backend="cpu",
         **extra):
    r = {"schema": 1, "kind": kind, "metric": metric, "value": value,
         "device_kind": dev, "backend": backend, "source": "t"}
    r.update(extra)
    return r


def test_ledger_record_roundtrip(tmp_path):
    headline = {
        "metric": "gibbs_samples_per_sec_45psr_pta", "value": 3998.0,
        "unit": "samples/s", "device_kind": "TPU v5 lite",
        "backend": "tpu", "sweeps_per_sec": 62.5, "nchains": 64,
        "ess_per_sec": 88.7,
        "roofline": {"blocks": {"gram32": {"gflops": 1.0, "mfu": 0.31,
                                           "intensity": 120.0,
                                           "bound": "compute"}}},
        "resilience": {"jaxprcheck": {"contracts": {"crn_cost": "ab12"}}},
        "raw": [1, 2, 3],                 # heavy field: must not land
    }
    rec = make_ledger_record(headline, source="test", run="r1", ts=5.0)
    assert rec["schema"] == 1
    assert rec["ts"] == 5.0
    assert rec["value"] == 3998.0
    assert "raw" not in rec
    # roofline condensed to attribution-only fields
    assert rec["roofline"]["gram32"] == {"mfu": 0.31, "intensity": 120.0,
                                         "bound": "compute"}
    assert rec["contract_hashes"] == {"crn_cost": "ab12"}
    path = tmp_path / "L.jsonl"
    ledger_append(rec, path)
    ledger_append(rec, path)
    path.open("a").write("{torn json\n")
    got = ledger_read(path)
    assert len(got) == 2                  # corrupt line skipped
    assert got[0] == got[1] == {k: v for k, v in rec.items()}


def test_ledger_records_always_carry_wallclock(tmp_path):
    """Regression for the ``"ts": null`` ledger rows: every producer
    path must stamp a real host-side epoch plus its ISO-8601 twin."""
    rec = make_ledger_record({"metric": "m", "value": 1.0}, source="t",
                             ts=5.0)
    assert rec["ts"] == 5.0
    assert rec["ts_iso"] == "1970-01-01T00:00:05Z"
    # no explicit ts: stamped at record-build time, never left null
    rec = make_ledger_record({"metric": "m", "value": 1.0}, source="t")
    assert isinstance(rec["ts"], float) and rec["ts"] > 0
    assert rec["ts_iso"].endswith("Z")
    # a legacy null-ts record is stamped at append time
    path = tmp_path / "L.jsonl"
    ledger_append(dict(_rec(2.0), ts=None), path)
    got = ledger_read(path)[0]
    assert isinstance(got["ts"], float) and got["ts"] > 0
    assert got["ts_iso"].endswith("Z")


def test_perfwatch_snapshot_records_carry_wallclock(tmp_path):
    """The MULTICHIP snapshot parser (the producer that used to emit
    ``"ts": null``) now stamps the snapshot file's mtime."""
    pw = _load_perfwatch()
    snap = tmp_path / "MULTICHIP_r1.json"
    snap.write_text(json.dumps({"ok": True, "n_devices": 2}))
    rec = pw._parse_multichip_snapshot(snap)
    assert rec["ts"] == pytest.approx(snap.stat().st_mtime)
    assert rec["ts_iso"].endswith("Z")


def test_check_ledger_within_band_passes():
    recs = [_rec(100.0), _rec(90.0)]      # -10% < 35% band
    assert check_ledger(recs) == []


def test_check_ledger_regression_fails():
    recs = [_rec(100.0, sweeps_per_sec=50.0),
            _rec(10.0, sweeps_per_sec=5.0)]
    problems = check_ledger(recs)
    assert len(problems) == 2             # value AND sweeps_per_sec
    assert any("value" in p for p in problems)


def test_check_ledger_tolerates_new_metrics_and_groups():
    recs = [_rec(100.0),
            _rec(1.0, metric="brand_new"),        # new group: no prior
            _rec(95.0, ess_per_sec=7.0)]          # new field: no prior
    assert check_ledger(recs) == []
    # different backend = different group: a CPU run never gates vs TPU
    recs = [_rec(4000.0, backend="tpu"), _rec(60.0, backend="cpu")]
    assert check_ledger(recs) == []


def test_check_ledger_multichip_only_newest_gates():
    ok = {"schema": 1, "kind": "multichip", "run": "r3", "ok": True}
    bad = {"schema": 1, "kind": "multichip", "run": "r1", "ok": False}
    assert check_ledger([bad, ok]) == []          # history tolerated
    problems = check_ledger([ok, dict(bad, run="r9")])
    assert len(problems) == 1 and "r9" in problems[0]


def test_check_ledger_band_override():
    recs = [_rec(100.0), _rec(80.0)]
    assert check_ledger(recs, {"value": 0.1}) != []
    assert check_ledger(recs, {"value": 0.25}) == []
    assert set(DEFAULT_BANDS) >= {"value", "sweeps_per_sec",
                                  "ess_per_sec"}


# ---------------------------------------------------------------------------
# perfwatch CLI


def test_perfwatch_check_cli(tmp_path):
    pw = _load_perfwatch()
    path = tmp_path / "L.jsonl"
    ledger_append(_rec(100.0), path)
    ledger_append(_rec(95.0), path)
    assert pw.main(["--check", "--ledger", str(path),
                    "--no-selfcheck"]) == 0
    ledger_append(_rec(5.0), path)                # injected regression
    assert pw.main(["--check", "--ledger", str(path),
                    "--no-selfcheck"]) == 1
    assert pw.main(["--report", "--ledger", str(path)]) == 0


def test_perfwatch_check_passes_on_missing_or_empty_ledger(
        tmp_path, capsys):
    # a fresh checkout / new backend has no trajectory to gate against:
    # the gate passes with an actionable note instead of failing CI
    pw = _load_perfwatch()
    assert pw.main(["--check", "--ledger", str(tmp_path / "no.jsonl"),
                    "--no-selfcheck"]) == 0
    out = capsys.readouterr().out
    assert "no ledger records for this backend" in out
    assert "--backfill" in out
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert pw.main(["--check", "--ledger", str(empty),
                    "--no-selfcheck"]) == 0
    assert "no ledger records" in capsys.readouterr().out


def test_perfwatch_backfill_refuses_clobber(tmp_path):
    pw = _load_perfwatch()
    path = tmp_path / "L.jsonl"
    path.write_text("{}\n")
    assert pw.backfill(path, force=False) == 1
    assert path.read_text() == "{}\n"             # untouched


@pytest.mark.lint
def test_perfwatch_gate_on_repo_ledger():
    """The ci_lint layer: HEAD's committed ledger + the live cost-model
    self-check must pass (CPU tracing only, no device execution)."""
    pw = _load_perfwatch()
    assert pw.main(["--check"]) == 0


# ---------------------------------------------------------------------------
# Prometheus rendering of the new gauges


def test_prometheus_nonfinite_gauge_spellings():
    body = metrics.render(
        gauges={"a": float("nan"), "b": float("inf"),
                "c": float("-inf")}, prefix="t")
    lines = body.splitlines()
    assert "t_a NaN" in lines
    assert "t_b +Inf" in lines
    assert "t_c -Inf" in lines
    for ln in lines:
        assert " nan" not in ln and " inf" not in ln


def test_prometheus_label_escaping_roundtrip():
    telemetry.reset("tperf_")
    telemetry.gauge("tperf_g", 1.0, path='a"b\\c')
    body = metrics.render_telemetry()
    assert 'path="a\\"b\\\\c"' in body
    telemetry.reset("tperf_")


def test_sweep_flops_matches_flop_counts_terms():
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.profiling import (flop_counts,
                                                       sweep_flops)
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    cm = compile_pta(build_model(synthetic_pulsars(2, 24, tm_cols=3), 2))
    fc = flop_counts(cm, nchains=3)
    fl = sweep_flops(cm, nchains=3)
    assert fl["tnt_einsum"] == fc["gram_einsum"] + fc["basis_matvec"]
    assert fl["cholesky"] == fc["cholesky"] + fc["tri_solves"]
    assert fl["total"] == fl["tnt_einsum"] + fl["cholesky"]
    assert all(v > 0 for v in fc.values())
    assert math.isfinite(fl["total"])
