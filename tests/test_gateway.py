"""Transport-frontend contracts (serve/gateway.py + serve/wire.py).

The fast tier pins everything that does not need a compiled sampler:
the typed error taxonomy and its exception mapping, bounded
body/name/deadline/cursor parsing (network input is hostile), the
stream-subscription state machine, and the gateway journal's
integrity story — checksum sidecar, ``.bak`` rollback, refusal on an
unverifiable journal or a service-seed mismatch.

The ``slow``-marked end-to-end test drives a real submission through
``Gateway.handle`` (no sockets — the transport-agnostic seam): dedupe
replay returns the original handle, a changed payload is a
``DEDUPE_MISMATCH``, the cursor stream round-trips every row bitwise
against the job's own chain, and an expired deadline drains through a
verified checkpoint.  The HTTP layer on top of the same core is
exercised by ``tools/serve_probe.py --gateway`` and the chaos
campaign's gateway leg (kill mid-stream / restart / reattach).
"""

import json

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.serve import wire
from pulsar_timing_gibbsspec_tpu.serve.wire import WireError

NITER = 12


# -- wire format ----------------------------------------------------------

def test_error_taxonomy_is_closed():
    """Every code maps to a real HTTP status; unknown codes refuse."""
    for code, status in wire.ERROR_STATUS.items():
        err = WireError(code, "msg")
        assert err.status == status
        assert err.body()["error"] == code
    with pytest.raises(ValueError, match="unknown wire error code"):
        WireError("NOT_A_CODE", "msg")
    err = WireError("CIRCUIT_OPEN", "msg", retry_after_s=1.23456)
    assert err.body()["retry_after_s"] == 1.235


def test_parse_body_bounds_and_shape():
    with pytest.raises(WireError) as ei:
        wire.parse_body(b"x" * 100, limit=99)
    assert ei.value.code == "PAYLOAD_TOO_LARGE"
    with pytest.raises(WireError) as ei:
        wire.parse_body(b"not json{")
    assert ei.value.code == "BAD_REQUEST"
    with pytest.raises(WireError) as ei:
        wire.parse_body(b"[1, 2]")
    assert ei.value.code == "BAD_REQUEST"
    assert wire.parse_body(b'{"a": 1}') == {"a": 1}


def test_require_name_refuses_hostile_identifiers():
    """Names become path components and Prometheus label values —
    traversal, control characters and over-length all refuse."""
    assert wire.require_name("job-1.A_b", "dedupe_key") == "job-1.A_b"
    for bad in ("", "a\nb", "../etc", ".hidden", "a" * 65, 7, None,
                'quo"te', "spa ce", "unié"):
        with pytest.raises(WireError) as ei:
            wire.require_name(bad, "dedupe_key")
        assert ei.value.code == "BAD_REQUEST"


def test_parse_deadline_precedence_and_validation():
    hdr = {wire.DEADLINE_HEADER: "1500"}
    assert wire.parse_deadline_ms(hdr) == 1.5
    # case-insensitive header lookup (HTTP normalizes arbitrarily)
    assert wire.parse_deadline_ms({"X-PTGibbs-Deadline-Ms": "500"}) == 0.5
    # body wins over header
    assert wire.parse_deadline_ms(hdr, {"deadline_ms": 250}) == 0.25
    assert wire.parse_deadline_ms({}, {}) is None
    for bad in ("soon", -5, 0):
        with pytest.raises(WireError) as ei:
            wire.parse_deadline_ms({}, {"deadline_ms": bad})
        assert ei.value.code == "DEADLINE_INVALID"


def test_parse_cursor_token_bounds():
    assert wire.parse_cursor("5", niter=10) == 5
    assert wire.parse_cursor(0) == 0
    for bad, niter in (("x", None), (-1, None), (11, 10)):
        with pytest.raises(WireError) as ei:
            wire.parse_cursor(bad, niter=niter)
        assert ei.value.code == "CURSOR_INVALID"


def test_payload_digest_is_canonical():
    a = wire.payload_digest({"b": 1, "a": [1, 2]})
    b = wire.payload_digest({"a": [1, 2], "b": 1})
    assert a == b
    assert a != wire.payload_digest({"a": [1, 2], "b": 2})


def test_classify_exception_maps_service_taxonomy():
    from pulsar_timing_gibbsspec_tpu.runtime.supervisor import (
        CircuitBreaker, CircuitOpen)

    # passthrough
    we = WireError("NOT_FOUND", "gone")
    assert wire.classify_exception(we) is we
    # backpressure (no breaker attached) vs tenant breaker cooldown
    assert wire.classify_exception(
        CircuitOpen("queue full", breaker=None)).code == "QUEUE_FULL"
    t = {"now": 0.0}
    br = CircuitBreaker(window=2, threshold=0.5, min_events=1,
                        cooldown_s=30.0, clock=lambda: t["now"])
    br.record_failure()
    assert br.state == "open"
    t["now"] = 12.0
    err = wire.classify_exception(CircuitOpen("tenant", breaker=br))
    assert err.code == "CIRCUIT_OPEN" and err.status == 429
    assert err.retry_after_s == pytest.approx(18.0)
    # anything unclassified is INTERNAL, body carries the repr
    err = wire.classify_exception(RuntimeError("boom"))
    assert err.code == "INTERNAL" and "boom" in err.body()["message"]


def test_bucket_overflow_maps_to_422():
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (
        BucketOverflow, BucketSpec, DatasetShape)

    exc = BucketOverflow(DatasetShape(2, 99, 24, 3),
                         BucketSpec(2, 48, 24, 3))
    err = wire.classify_exception(exc)
    assert err.code == "BUCKET_OVERFLOW" and err.status == 422


# -- stream subscription machine ------------------------------------------

def test_stream_sub_state_machine():
    from pulsar_timing_gibbsspec_tpu.serve.gateway import (
        STREAM_STATES, StreamSub)

    sub = StreamSub("j", 0)
    assert sub.state == "attached" and sub.state in STREAM_STATES
    sub.begin()
    assert sub.state == "streaming"
    sub.shed()
    assert sub.state == "shed"
    sub.close()                       # shed is terminal: close is a no-op
    assert sub.state == "shed"
    sub2 = StreamSub("j", 3)
    sub2.close()                      # never began: attached -> closed
    assert sub2.state == "closed"
    sub2.begin()                      # closed is terminal
    assert sub2.state == "closed"


# -- journal integrity ----------------------------------------------------

def _table():
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (BucketSpec,
                                                           BucketTable)

    return BucketTable([BucketSpec(2, 40, 24, 3)])


def _fake_done_entry(root, key="k0", job_id="g00000"):
    return {"job_id": job_id, "tenant_id": 0, "niter": 4,
            "payload": {"synthetic": {}}, "payload_sha256": "0" * 64,
            "outdir": str(root / "jobs" / job_id), "dedupe_key": key,
            "state": "done", "deadline_unix": None}


def test_journal_roundtrip_and_bak_rollback(tmp_path):
    """The journal survives its own corruption: primary fails the
    checksum -> the rotated ``.bak`` pair restores the binding; both
    generations bad -> typed refusal, never a silent fresh start."""
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.runtime.integrity import CheckpointError
    from pulsar_timing_gibbsspec_tpu.serve.gateway import (
        JOURNAL, JOURNAL_BAK, JOURNAL_SHA, Gateway)

    gw = Gateway(tmp_path / "gw", _table())
    with gw._cond:
        gw._entries["k0"] = _fake_done_entry(tmp_path / "gw")
        gw._write_journal()
        gw._write_journal()          # second write rotates the .bak pair
    assert (tmp_path / "gw" / JOURNAL_BAK).exists()

    # clean reload: binding survives, done entries are NOT readmitted
    gw2 = Gateway(tmp_path / "gw", _table())
    assert gw2._entries["k0"]["job_id"] == "g00000"
    assert gw2.svc.jobs == {}

    # corrupt the primary: the verified .bak generation takes over
    prim = tmp_path / "gw" / JOURNAL
    prim.write_bytes(prim.read_bytes()[:-7] + b"GARBAGE")
    before = telemetry.get("rollbacks")
    gw3 = Gateway(tmp_path / "gw", _table())
    assert gw3._entries["k0"]["job_id"] == "g00000"
    assert telemetry.get("rollbacks") == before + 1

    # both generations unverifiable: refuse loudly
    prim.write_bytes(b"{}")
    (tmp_path / "gw" / JOURNAL_SHA).write_text("f" * 64)
    (tmp_path / "gw" / JOURNAL_BAK).write_bytes(b"junk")
    with pytest.raises(CheckpointError, match="journal"):
        Gateway(tmp_path / "gw", _table())


def test_journal_refuses_service_seed_mismatch(tmp_path):
    """Tenant PRNG identity is (service_seed, tenant_id, iteration): a
    journal written under another seed must not route onto this
    service's streams."""
    from pulsar_timing_gibbsspec_tpu.runtime.integrity import CheckpointError
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway

    gw = Gateway(tmp_path / "gw", _table())
    with gw._cond:
        gw._entries["k0"] = _fake_done_entry(tmp_path / "gw")
        gw._write_journal()
    with pytest.raises(CheckpointError, match="service_seed"):
        Gateway(tmp_path / "gw", _table(),
                svc_kw={"service_seed": 7})


def test_submission_is_journaled_before_ack(tmp_path):
    """The dedupe binding must be durable BEFORE the client can see the
    ACK — a fresh gateway instance on the same root resolves the retry
    to the original handle without the first instance saying goodbye."""
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    body = json.dumps({
        "dedupe_key": "dk", "niter": 4,
        "payload": {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                                  "seed": 0, "nmodes": 3}}}).encode()
    req = WireRequest("POST", "/v1/jobs", {}, {}, body)
    gw = Gateway(tmp_path / "gw", _table())
    resp = gw.handle(req)
    assert resp.status == 200 and resp.body["replayed"] is False
    # no shutdown, no drain: the journal alone carries the binding
    gw2 = Gateway(tmp_path / "gw", _table())
    resp2 = gw2.handle(req)
    assert resp2.status == 200
    assert resp2.body["replayed"] is True
    assert resp2.body["job_id"] == resp.body["job_id"]
    assert resp2.body["tenant_id"] == resp.body["tenant_id"]
    # same key, different payload: typed refusal, no second job
    body2 = json.dumps({
        "dedupe_key": "dk", "niter": 4,
        "payload": {"synthetic": {"n_psr": 2, "ntoa": 30, "tm_cols": 3,
                                  "seed": 0, "nmodes": 3}}}).encode()
    resp3 = gw2.handle(WireRequest("POST", "/v1/jobs", {}, {}, body2))
    assert resp3.status == 409
    assert resp3.body["error"] == "DEDUPE_MISMATCH"
    assert len(gw2.svc.jobs) == 1


def test_stream_crossing_refused_after_restart(tmp_path):
    """A reattach credential that does not match the journaled dedupe
    binding refuses with STREAM_CROSSING (409) — on a RESTARTED
    gateway, where only the journal knows the binding."""
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import (DEDUPE_HEADER,
                                                        WireRequest)

    body = json.dumps({
        "dedupe_key": "mine", "niter": 4,
        "payload": {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                                  "seed": 0, "nmodes": 3}}}).encode()
    gw = Gateway(tmp_path / "gw", _table())
    jid = gw.handle(
        WireRequest("POST", "/v1/jobs", {}, {}, body)).body["job_id"]
    gw2 = Gateway(tmp_path / "gw", _table())
    resp = gw2.handle(WireRequest("GET", f"/v1/jobs/{jid}", {},
                                  {DEDUPE_HEADER: "not-mine"}))
    assert resp.status == 409
    assert resp.body["error"] == "STREAM_CROSSING"
    # the right credential (or none — status is not secret) passes
    assert gw2.handle(WireRequest("GET", f"/v1/jobs/{jid}", {},
                                  {DEDUPE_HEADER: "mine"})).status == 200


def test_quarantined_entries_stay_parked_across_restart(tmp_path):
    """A journal entry in terminal ``quarantined`` state must NOT be
    readmitted on restart (resuming a parked job is an operator
    ``force_requeue`` decision), and a journal still saying ``active``
    over a quarantine-marked manifest — the gateway died between the
    park and the journal sync — defers to the manifest instead of
    wedging every subsequent restart on the resume refusal."""
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway

    gw = Gateway(tmp_path / "gw", _table())
    parked = _fake_done_entry(tmp_path / "gw", key="kq", job_id="g00000")
    parked["state"] = "quarantined"
    stale = _fake_done_entry(tmp_path / "gw", key="ka", job_id="g00001")
    stale["state"] = "active"
    outdir = tmp_path / "gw" / "jobs" / "g00001"
    outdir.mkdir(parents=True)
    (outdir / "manifest.json").write_text(json.dumps(
        {"files": {}, "serve": {"state": "quarantined"}}))
    with gw._cond:
        gw._entries.update({"kq": parked, "ka": stale})
        gw._write_journal()

    gw2 = Gateway(tmp_path / "gw", _table())
    assert gw2.svc.jobs == {}                  # nothing readmitted
    assert gw2._entries["kq"]["state"] == "quarantined"
    assert gw2._entries["ka"]["state"] == "quarantined"
    # the manifest-derived correction is itself durable
    gw3 = Gateway(tmp_path / "gw", _table())
    assert gw3._entries["ka"]["state"] == "quarantined"


def test_scheduler_failure_stops_gateway_loudly(tmp_path):
    """An exception escaping the recovery ladder must never leave a
    dead scheduler behind a live listener: the gateway stops, records
    the cause, settles the journal (active work parks resumable), and
    refuses new work with a typed DRAINING."""
    from pulsar_timing_gibbsspec_tpu.runtime import preemption
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    preemption.reset()
    gw = Gateway(tmp_path / "gw", _table())
    with gw._cond:
        ent = _fake_done_entry(tmp_path / "gw")
        ent["state"] = "active"
        gw._entries["k0"] = ent
        gw._by_job[ent["job_id"]] = ent

    def boom(defer_backoff=False):  # noqa: ARG001
        raise RuntimeError("scheduler boom")

    gw.svc.step_supervised = boom
    gw.start()
    gw.join(timeout=30)
    assert not gw.alive()
    assert gw.state == "stopped"
    assert "scheduler boom" in gw.failure
    health = gw.handle(WireRequest("GET", "/v1/healthz", {}, {})).body
    assert health["state"] == "stopped"
    assert "scheduler boom" in health["failure"]
    body = json.dumps({
        "dedupe_key": "fresh", "niter": 4,
        "payload": {"synthetic": {}}}).encode()
    resp = gw.handle(WireRequest("POST", "/v1/jobs", {}, {}, body))
    assert resp.status == 503 and resp.body["error"] == "DRAINING"
    # the in-flight entry parked resumable — and durably so
    assert gw.report()["entries"]["k0"]["state"] == "drained"
    gw2 = Gateway(tmp_path / "gw", _table())
    assert gw2._entries["k0"]["state"] in ("active", "drained")


def test_oversize_body_closes_keepalive_connection(tmp_path):
    """A body over the cap on an HTTP/1.1 keep-alive connection must
    not leave its unread remainder on the socket to be parsed as the
    next request (connection desync / smuggling): the gateway answers
    413 and closes; a malformed Content-Length closes too."""
    import socket

    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import HttpTransport

    gw = Gateway(tmp_path / "gw", _table(), max_body=256)
    tr = HttpTransport(gw)
    tr.start()
    try:
        host, port = tr.address

        def _one_closed_exchange(head, body):
            with socket.create_connection((host, port), timeout=10) as sk:
                sk.settimeout(10)
                sk.sendall(head + body)
                got = b""
                while True:
                    chunk = sk.recv(65536)
                    if not chunk:
                        break          # server closed: no desync window
                    got += chunk
            return got

        smuggled = b"GET /v1/healthz HTTP/1.1\r\nHost: x\r\n\r\n"
        body = b"x" * 400 + smuggled
        head = (b"POST /v1/jobs HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(body))
        got = _one_closed_exchange(head, body)
        assert got.startswith(b"HTTP/1.1 413")
        # exactly one response: the smuggled tail was never parsed
        assert got.count(b"HTTP/1.1 ") == 1

        head = (b"POST /v1/jobs HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: zzz\r\n\r\n")
        got = _one_closed_exchange(head, smuggled)
        assert got.startswith(b"HTTP/1.1 400")
        assert got.count(b"HTTP/1.1 ") == 1
    finally:
        tr.stop()


def test_unknown_route_and_job(tmp_path):
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    gw = Gateway(tmp_path / "gw", _table())
    assert gw.handle(WireRequest("PUT", "/v1/jobs", {}, {})).status == 400
    resp = gw.handle(WireRequest("GET", "/v1/jobs/nope", {}, {}))
    assert resp.status == 404 and resp.body["error"] == "NOT_FOUND"
    assert gw.handle(
        WireRequest("GET", "/v1/healthz", {}, {})).body["state"] == "serving"


def test_submit_validation_through_handle(tmp_path):
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    gw = Gateway(tmp_path / "gw", _table(), max_body=256, max_niter=100)

    def _submit(doc):
        raw = json.dumps(doc).encode()
        return gw.handle(WireRequest("POST", "/v1/jobs", {}, {}, raw))

    ok = {"dedupe_key": "d1", "niter": 4,
          "payload": {"synthetic": {"n_psr": 2, "ntoa": 24,
                                    "tm_cols": 3, "seed": 0,
                                    "nmodes": 3}}}
    assert _submit({**ok, "dedupe_key": "no\nnewline"}).body["error"] \
        == "BAD_REQUEST"
    assert _submit({**ok, "niter": 0}).body["error"] == "BAD_REQUEST"
    assert _submit({**ok, "niter": 101}).body["error"] == "BAD_REQUEST"
    assert _submit({**ok, "payload": 3}).body["error"] == "BAD_REQUEST"
    big = {**ok, "payload": {"synthetic": {"pad": "x" * 400}}}
    assert _submit(big).body["error"] == "PAYLOAD_TOO_LARGE"
    hostile = {**ok, "dedupe_key": "d2",
               "payload": {"synthetic": {"ntoa": 10**9}}}
    assert _submit(hostile).body["error"] == "BAD_REQUEST"
    assert gw.svc.jobs == {}          # nothing hostile was admitted


# -- end-to-end through the transport seam (compiles a sampler) -----------

@pytest.mark.slow
def test_gateway_stream_bitwise_and_deadline_drain(tmp_path):
    """One resident gateway run, handle()-level: the cursor stream
    delivers every row bitwise (JSON float round-trip is exact),
    reattachment from a mid-stream cursor resumes exactly, and an
    expired deadline drains through a VERIFIED checkpoint."""
    from pulsar_timing_gibbsspec_tpu.runtime import integrity, preemption
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    import time

    preemption.reset()
    gw = Gateway(tmp_path / "gw", _table(),
                 svc_kw={"slots": 2, "chunk": 4, "quantum": 100,
                         "save_every": 1})
    payload = {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                             "seed": 0, "nmodes": 3}}
    h = gw.handle(WireRequest("POST", "/v1/jobs", {}, {}, json.dumps(
        {"dedupe_key": "main", "niter": NITER,
         "payload": payload}).encode())).body
    gw.start()
    try:
        resp = gw.handle(WireRequest(
            "GET", f"/v1/jobs/{h['job_id']}/stream",
            {"cursor": "0", "live": "1"}, {}))
        rows, cursors = [], []
        for line in resp.stream:
            ev = json.loads(line)
            rows.extend(ev.get("rows") or [])
            cursors.append(int(ev["cursor"]))
        assert len(rows) == NITER
        assert cursors == sorted(cursors)          # monotonic tokens
        job = gw.svc.jobs[h["job_id"]]
        assert np.array_equal(np.asarray(rows, np.float64),
                              np.asarray(job.chain[:NITER], np.float64))
        # reattach mid-stream: exactly the suffix, bitwise
        resp = gw.handle(WireRequest(
            "GET", f"/v1/jobs/{h['job_id']}/stream",
            {"cursor": "5", "wait": "5"}, {}))
        tail = []
        for line in resp.stream:
            tail.extend(json.loads(line).get("rows") or [])
        assert np.array_equal(np.asarray(tail, np.float64),
                              np.asarray(job.chain[5:NITER], np.float64))

        # the deadline job: submitted onto a WARM cache (so the
        # deadline cannot expire inside the one planned compile) and
        # sized to be nowhere near done when it lands
        hdl = gw.handle(WireRequest(
            "POST", "/v1/jobs", {}, {}, json.dumps(
                {"dedupe_key": "late", "niter": 50_000,
                 "deadline_ms": 1000, "payload": payload}).encode())).body
        deadline = time.monotonic() + 60
        st = None
        while time.monotonic() < deadline:
            st = gw.handle(WireRequest(
                "GET", f"/v1/jobs/{hdl['job_id']}", {}, {})).body
            if st["state"] == "expired":
                break
            time.sleep(0.05)
        assert st is not None and st["state"] == "expired"
        assert 0 < st["cursor"] < 50_000      # drained mid-run
        rep = integrity.verify(tmp_path / "gw" / "jobs" / hdl["job_id"])
        assert rep["ok"]
        # the verified prefix stays streamable after expiry
        resp = gw.handle(WireRequest(
            "GET", f"/v1/jobs/{hdl['job_id']}/stream",
            {"cursor": "0", "wait": "1"}, {}))
        got = []
        for line in resp.stream:
            got.extend(json.loads(line).get("rows") or [])
        assert len(got) >= 4                  # at least one saved chunk
    finally:
        preemption.request_drain(reason="test_teardown")
        gw.join(timeout=60)
        preemption.reset()


@pytest.mark.slow
def test_graceful_drain_parks_and_journals(tmp_path):
    """request_drain() stops admissions (typed DRAINING), drains the
    resident through the preemption path, and the journal marks the
    job drained — a successor readmits it."""
    from pulsar_timing_gibbsspec_tpu.runtime import preemption
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    preemption.reset()
    try:
        gw = Gateway(tmp_path / "gw", _table(),
                     svc_kw={"slots": 2, "chunk": 4, "quantum": 100,
                             "save_every": 1})
        payload = {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                                 "seed": 0, "nmodes": 3}}
        gw.handle(WireRequest("POST", "/v1/jobs", {}, {}, json.dumps(
            {"dedupe_key": "d", "niter": 50_000,
             "payload": payload}).encode()))
        gw.start()
        gw.handle(WireRequest("POST", "/v1/drain", {}, {}))
        gw.join(timeout=120)
        assert gw.state == "stopped"
        resp = gw.handle(WireRequest("POST", "/v1/jobs", {}, {},
                                     json.dumps({
                                         "dedupe_key": "d2", "niter": 4,
                                         "payload": payload}).encode()))
        assert resp.status == 503 and resp.body["error"] == "DRAINING"
        assert gw.report()["entries"]["d"]["state"] == "drained"
    finally:
        preemption.reset()
    # a successor on the same root readmits the drained job
    gw2 = Gateway(tmp_path / "gw", _table(),
                  svc_kw={"slots": 2, "chunk": 4, "quantum": 100,
                          "save_every": 1})
    assert gw2.report()["entries"]["d"]["state"] == "active"
    assert "g00000" in gw2.svc.jobs
