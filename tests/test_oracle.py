"""Statistical unit tests of the NumPy oracle sampler's conditional draws.

Each conditional is checked against its closed-form density (KS tests /
moment checks), then a short end-to-end run sanity-checks the sweep.  These
mirror SURVEY §4's prescription: unit tests for each conditional kernel
against closed-form oracles.
"""

import numpy as np
import pytest
import scipy.stats as st

from pulsar_timing_gibbsspec_tpu.models import model_general
from pulsar_timing_gibbsspec_tpu.sampler.numpy_backend import NumpyGibbs


@pytest.fixture(scope="module")
def freespec_gibbs(j1713):
    pta = model_general([j1713], red_var=False, white_vary=True,
                        common_psd="spectrum", common_components=30)
    return NumpyGibbs(pta, seed=1234)


def test_analytic_rho_draw_distribution(freespec_gibbs):
    """The no-IRN rho draw must follow p(rho) ~ rho^-2 exp(-tau/rho) on
    [rhomin, rhomax] (vHV2014; reference pulsar_gibbs.py:215-216)."""
    g = freespec_gibbs
    tau = 1e-13
    draws = []
    g.b = np.zeros_like(g.b)
    # plant tau via the first GW sin/cos pair for frequency 0; read back rho_0
    g.b[g.gwid[0]] = np.sqrt(tau)
    g.b[g.gwid[1]] = np.sqrt(tau)
    x = g.pta.initial_sample(np.random.default_rng(7))
    for _ in range(4000):
        x2 = g.update_rho(x)
        draws.append(10.0 ** (2 * x2[g.idx.rho[0]]))
    draws = np.asarray(draws)

    # closed-form CDF in u = tau/rho: truncated Exp
    a, bnd = tau / g.rhomax, tau / g.rhomin
    u = tau / draws
    cdf = lambda uu: (np.exp(-a) - np.exp(-uu)) / (np.exp(-a) - np.exp(-bnd))
    ks = st.kstest(u, cdf)
    assert ks.pvalue > 1e-3, ks


def test_grid_rho_draw_matches_analytic(j1713):
    """With vanishing intrinsic red noise the grid/Gumbel-max draw must
    reproduce the analytic draw's distribution (reference :228-234)."""
    pta = model_general([j1713], red_var=True, white_vary=False,
                        common_psd="spectrum", common_components=10,
                        red_components=10)
    g = NumpyGibbs(pta, seed=5)
    tau = 2e-13
    g.b = np.zeros_like(g.b)
    g.b[g.gwid[0]] = np.sqrt(2 * tau)      # tau = (b_s^2+b_c^2)/2
    x = pta.initial_sample(np.random.default_rng(3))
    # push intrinsic red noise to negligible amplitude
    x[pta.param_names.index("J1713+0747_red_noise_log10_A")] = -19.9
    x[pta.param_names.index("J1713+0747_red_noise_gamma")] = 1.0

    draws = np.array([10.0 ** (2 * g.update_rho(x)[g.idx.rho[0]])
                      for _ in range(4000)])
    a, bnd = tau / g.rhomax, tau / g.rhomin
    u = tau / draws
    cdf = lambda uu: (np.exp(-a) - np.exp(-uu)) / (np.exp(-a) - np.exp(-bnd))
    ks = st.kstest(u, cdf)
    # grid draw is discrete (1000 points) — KS vs continuous CDF has a floor;
    # accept modest p-values but reject gross mismatch
    assert ks.statistic < 0.05, ks


def test_b_draw_moments(freespec_gibbs):
    """b | x ~ N(Sigma^-1 d, Sigma^-1): check mean/cov over many draws."""
    g = freespec_gibbs
    x = g.pta.initial_sample(np.random.default_rng(11))
    params = g.map_params(x)
    Nvec = g.pta.get_ndiag(params)[0]
    phiinv = g.pta.get_phiinv(params)[0]
    T, y = g._T, g._y
    TNT = T.T @ (T / Nvec[:, None])
    d = T.T @ (y / Nvec)
    Sigma = TNT + np.diag(phiinv)
    mean_exact = np.linalg.solve(Sigma, d)
    cov_exact = np.linalg.inv(Sigma)

    draws = []
    for _ in range(600):
        g.invalidate_cache()
        draws.append(g.draw_b(x).copy())
    draws = np.asarray(draws)
    # standardized mean error per coordinate ~ N(0, 1/sqrt(n))
    sd = np.sqrt(np.diag(cov_exact))
    zerr = (draws.mean(axis=0) - mean_exact) / (sd / np.sqrt(len(draws)))
    assert np.percentile(np.abs(zerr), 95) < 3.5
    # variance ratio near 1
    ratio = draws.var(axis=0) / np.diag(cov_exact)
    assert 0.75 < np.median(ratio) < 1.3


def test_white_block_posterior(j1713):
    """EFAC posterior from the white MH block matches a direct grid posterior
    when b = 0 (then y|efac is exactly diagonal-Gaussian)."""
    pta = model_general([j1713], red_var=False, white_vary=True,
                        common_psd="spectrum", common_components=5)
    g = NumpyGibbs(pta, white_adapt_iters=800, seed=42)
    g.b = np.zeros_like(g.b)    # condition on zero GP contribution
    x = pta.initial_sample(np.random.default_rng(0))
    iefac = pta.param_names.index("J1713+0747_test_efac")
    iequad = pta.param_names.index("J1713+0747_test_log10_tnequad")
    x[iequad] = -8.4            # negligible equad

    x = g.update_white(x, adapt=True)
    chains = []
    for _ in range(3000):
        x = g.update_white(x)
        chains.append(x[iefac])
    chains = np.asarray(chains[500:])

    # direct 2-d grid posterior over (efac, log10_equad), then marginalize:
    # the MH chain explores the joint, so the comparison must too
    efgrid = np.linspace(0.6, 1.6, 160)
    eqgrid = np.linspace(-8.5, -5.0, 160)
    sig2 = j1713.toaerrs**2
    r2 = j1713.residuals**2
    ll = np.empty((len(efgrid), len(eqgrid)))
    for jj, eqv in enumerate(eqgrid):
        N = efgrid[:, None] ** 2 * sig2[None, :] + 10.0 ** (2 * eqv)
        ll[:, jj] = -0.5 * np.sum(np.log(N) + r2[None, :] / N, axis=1)
    post = np.exp(ll - ll.max())
    marg = np.trapezoid(post, eqgrid, axis=1)
    marg /= np.trapezoid(marg, efgrid)
    mean_exact = np.trapezoid(efgrid * marg, efgrid)
    sd_exact = np.sqrt(np.trapezoid((efgrid - mean_exact) ** 2 * marg, efgrid))

    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act
    neff = len(chains) / max(integrated_act(chains), 1.0)
    assert abs(chains.mean() - mean_exact) < 5 * sd_exact / np.sqrt(neff)
    assert 0.6 < chains.std() / sd_exact < 1.6


def test_sweep_end_to_end(freespec_gibbs):
    g = freespec_gibbs
    x = g.pta.initial_sample(np.random.default_rng(2))
    x = g.sweep(x, first=True)
    assert g.aclength_white >= 1
    for _ in range(20):
        x = g.sweep(x)
    assert np.all(np.isfinite(x))
    rho = x[g.idx.rho]
    assert np.all(rho >= -10.0) and np.all(rho <= -4.0)
    assert np.all(np.isfinite(g.b))
