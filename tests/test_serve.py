"""Serving-layer contracts (pulsar_timing_gibbsspec_tpu/serve/).

The load-bearing claims, each tested here end-to-end on tiny synthetic
datasets:

- routing snaps a dataset to the SMALLEST covering bucket and refuses
  anything larger with a typed :class:`BucketOverflow` carrying the
  nearest bucket (never a crash inside ``compile_pta``);
- heterogeneous datasets sharing one bucket share ONE compiled program
  (warm cache hits, zero unplanned steady-phase retraces across
  membership churn);
- a tenant's chain is bitwise identical whether it runs solo,
  multiplexed next to other tenants, in a different slot, or in a
  wider service — the vmap-row independence + CRN stream identity
  contract;
- admission/eviction, service crash, and preemption drain all recover
  every in-flight job bit-exactly from its own verified checkpoint
  directory (``integrity.load_resume``).
"""

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.serve.buckets import (
    BucketOverflow, BucketSpec, BucketTable, DatasetShape, probe_shape)

NITER = 12


def _mk(ntoa, seed, nmodes=3):
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    return build_model(synthetic_pulsars(2, ntoa, tm_cols=3, seed=seed),
                       nmodes)


_CACHE = None


def _service(root, table, **kw):
    """Fresh service sharing the module-wide program cache (the
    warm-restart path: a successor process reusing compiled programs)
    so the suite compiles each bucket/width once, not per service."""
    global _CACHE
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache, SamplerService

    if _CACHE is None:
        _CACHE = ProgramCache()
    kw.setdefault("cache", _CACHE)
    kw.setdefault("slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("quantum", 100)
    return SamplerService(root, table, **kw)


@pytest.fixture(scope="module")
def ptas3():
    """Three heterogeneous datasets (TOA counts 24/30/36, different
    noise realizations) with identical structure -> one bucket."""
    return [_mk(24, 0), _mk(30, 1), _mk(36, 2)]


@pytest.fixture(scope="module")
def table():
    return BucketTable([BucketSpec(2, 40, 24, 3)])


@pytest.fixture(scope="module")
def solo_chains(ptas3, table, tmp_path_factory):
    """Uninterrupted single-tenant baselines, one service each."""
    base = tmp_path_factory.mktemp("serve_solo")
    out = []
    for i, pta in enumerate(ptas3):
        svc = _service(base / f"s{i}", table)
        job = svc.submit(pta, NITER, job_id=f"job{i}", tenant_id=i)
        svc.run()
        assert job.state == "done"
        out.append((job.chain.copy(), job.bchain.copy()))
    return out


# -- routing ---------------------------------------------------------------

def test_route_smallest_cover():
    small, mid, big = (BucketSpec(2, 40, 24, 3), BucketSpec(4, 100, 30, 3),
                       BucketSpec(8, 1000, 60, 3))
    t = BucketTable([mid, small, big])
    assert t.route(DatasetShape(2, 30, 20, 3)) == small
    assert t.route(DatasetShape(3, 90, 28, 3)) == mid
    assert t.route(DatasetShape(8, 1000, 60, 3)) == big


def test_ladder_sorted_and_routes():
    t = BucketTable.ladder(3, pulsars=(2, 4), toas=(64, 256))
    costs = [b.cost() for b in t.buckets]
    assert costs == sorted(costs)
    assert t.route(DatasetShape(2, 100, 20, 3)).toas == 256


def test_overflow_typed_with_nearest():
    t = BucketTable([BucketSpec(2, 40, 24, 3)])
    with pytest.raises(BucketOverflow) as ei:
        t.route(DatasetShape(2, 41, 24, 3))
    e = ei.value
    assert isinstance(e, ValueError)          # typed, but catchable broadly
    assert e.nearest == BucketSpec(2, 40, 24, 3)
    assert e.shape.toas == 41
    assert "TOA=41" in str(e) and "(2, 40, 24, 3)" in str(e)


def test_overflow_prefers_same_mode_nearest():
    k3, k5 = BucketSpec(2, 40, 24, 3), BucketSpec(2, 80, 24, 5)
    t = BucketTable([k3, k5])
    with pytest.raises(BucketOverflow) as ei:
        t.route(DatasetShape(2, 50, 24, 3))   # K=3: only k3 is comparable
    assert ei.value.nearest == k3


def test_probe_shape_and_route_pta(ptas3, table):
    s = probe_shape(ptas3[2])
    assert (s.pulsars, s.toas, s.modes) == (2, 36, 3)
    assert s.basis <= 24
    assert table.route_pta(ptas3[2]) == table.buckets[0]


def test_compile_pad_validation(ptas3):
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    with pytest.raises(ValueError, match="pad_toas"):
        compile_pta(ptas3[0], pad_toas=8)
    with pytest.raises(ValueError, match="pad_basis"):
        compile_pta(ptas3[0], pad_basis=2)


def test_signature_mismatch_refuses_graft(ptas3):
    from pulsar_timing_gibbsspec_tpu.serve.engine import (
        SignatureMismatch, adopt_static, compile_bucket)

    a = compile_bucket(ptas3[0], BucketSpec(2, 40, 24, 3))
    b = compile_bucket(ptas3[0], BucketSpec(2, 48, 24, 3))
    with pytest.raises(SignatureMismatch):
        adopt_static(b, a)                    # Nmax differs: no sharing


# -- multiplexing ----------------------------------------------------------

def test_multiplex_bitwise_and_zero_retrace(ptas3, table, solo_chains,
                                            tmp_path):
    """>= 3 heterogeneous datasets through one bucket, 2 concurrent
    slots, forced fair-share churn: zero unplanned steady retraces and
    every chain bitwise equal to its solo baseline (memory AND disk)."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache

    # own cache: warm_hit_rate must reflect THIS service's admissions
    svc = _service(tmp_path / "mux", table, quantum=2,
                   cache=ProgramCache())
    with recompile_counter() as rc:
        rc.phase("steady")
        jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
                for i, p in enumerate(ptas3)]
        report = svc.run()
    assert rc.unplanned("steady") == 0
    assert report["evictions"] >= 1           # quantum=2 forced churn
    assert report["warm_hit_rate"] == pytest.approx(2.0 / 3.0)
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])
        np.testing.assert_array_equal(job.bchain, solo_chains[i][1])
        disk = np.load(tmp_path / "mux" / job.job_id / "chain.npy")
        np.testing.assert_array_equal(disk, solo_chains[i][0])
    gauges = telemetry.gauges()
    for name in ("queue_depth", "warm_hit_rate", "compile_stalls",
                 "tenant_evictions", "time_to_first_sample_ms"):
        assert name in gauges


def test_capacity_independence(ptas3, table, solo_chains, tmp_path):
    """A wider service (3 slots: different compiled program, different
    co-residents) produces bitwise-identical per-tenant chains."""
    svc = _service(tmp_path / "wide", table, slots=3)
    jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
            for i, p in enumerate(ptas3)]
    svc.run()
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])


def test_mesh_placed_service_deterministic_and_stream_preserving(
        ptas3, table, solo_chains, tmp_path):
    """On a 2-d (chain, pulsar) mesh the tenant axis rides the chain
    axis.  The placement contract is the one the class docstring makes:
    per-tenant PRNG streams are untouched and two mesh-placed runs are
    bitwise identical to each other; against the UNPLACED solo baseline
    the chains agree at the f64 reduction-order class (GSPMD regroups
    within-sweep reductions — ULP-level, measured ~2e-16 relative), not
    bitwise.  The report records the layout, and a slot width the
    chain axis cannot split is refused with the actionable message."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh
    from pulsar_timing_gibbsspec_tpu.serve import SamplerService

    mesh = make_mesh((2, 2))

    def run(root):
        svc = _service(tmp_path / root, table, mesh=mesh)    # slots=2
        jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
                for i, p in enumerate(ptas3)]
        return svc.run(), [j.chain.copy() for j in jobs], jobs

    report, chains, jobs = run("mesh_a")
    _, chains_b, _ = run("mesh_b")
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(chains[i], chains_b[i])
        scale = np.abs(solo_chains[i][0]).max()
        assert np.abs(chains[i] - solo_chains[i][0]).max() < 1e-12 * scale
    assert report["mesh"]["axes"] == [["chain", 2], ["pulsar", 2]]
    with pytest.raises(ValueError, match="multiple of 2"):
        SamplerService(tmp_path / "bad", table, slots=3, mesh=mesh)


# -- recovery --------------------------------------------------------------

def test_eviction_midrun_resume(ptas3, table, solo_chains, tmp_path):
    """A job checkpointed mid-run is loadable with the standalone
    ``integrity.load_resume`` and a fresh service incarnation readmits
    it bit-exactly."""
    from pulsar_timing_gibbsspec_tpu.runtime import integrity

    root = tmp_path / "resume"
    svc = _service(root, table, save_every=1)
    for i in range(2):
        svc.submit(ptas3[i], NITER, job_id=f"job{i}", tenant_id=i)
    assert svc.step()                         # one chunk: 4 rows each
    got = integrity.load_resume(root / "job0")
    assert got is not None
    chain, bchain, upto, adapt = got
    assert upto == 4
    np.testing.assert_array_equal(chain[:upto], solo_chains[0][0][:upto])
    assert int(adapt["tenant_id"]) == 0

    svc2 = _service(root, table)              # fresh process semantics
    jobs2 = [svc2.submit(ptas3[i], NITER, job_id=f"job{i}", tenant_id=i)
             for i in range(2)]
    svc2.run()
    for i, job in enumerate(jobs2):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])


def test_resume_refuses_stream_crossing(tmp_path):
    """A checkpoint written under one tenant stream must not seed a
    different tenant's chain — the PRNG identity is (seed, tenant)."""
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore
    from pulsar_timing_gibbsspec_tpu.serve.jobs import Job

    store = ChainStore(tmp_path / "jobX", ["p0", "p1"], ["b0"])
    store.save(np.ones((2, 2)), np.ones((2, 1)), 2,
               adapt_state={"x": np.ones(2), "b": np.ones((1, 1)),
                            "tenant_id": np.asarray(7, np.int64)})
    job = Job(job_id="jobX", pta=None, niter=4, tenant_id=3,
              outdir=str(tmp_path / "jobX"))
    job.chain = np.zeros((4, 2))
    job.bchain = np.zeros((4, 1))
    with pytest.raises(RuntimeError, match="stream-crossing"):
        job.try_resume()
    job.tenant_id = 7
    assert job.try_resume()
    assert job.it == 2 and job.chain[:2].all()


@pytest.mark.chaos
def test_tenant_evict_crash_recovery(ptas3, table, solo_chains, tmp_path):
    """Eviction churn + service death mid-multiplex: every in-flight
    job resumes from its own verified checkpoint dir, bitwise."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults, integrity
    from pulsar_timing_gibbsspec_tpu.runtime.faults import InjectedCrash

    root = tmp_path / "mux"
    faults.clear()
    faults.inject("tenant_evict", point="serve.chunk", at_row=2, times=1)
    faults.inject("crash", point="serve.chunk", at_row=3, times=1)
    svc = _service(root, table, max_retries=0)
    jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
            for i, p in enumerate(ptas3)]
    try:
        with pytest.raises(InjectedCrash):
            svc.run()
    finally:
        faults.clear()
    in_flight = [j for j in jobs if 0 < j.it < NITER]
    assert in_flight                          # the kill landed mid-run
    for job in jobs:
        if job.it > 0:
            assert integrity.verify(root / job.job_id)["ok"]

    svc2 = _service(root, table)
    jobs2 = [svc2.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
             for i, p in enumerate(ptas3)]
    svc2.run()
    for i, job in enumerate(jobs2):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])


@pytest.mark.chaos
def test_transient_device_error_retries(ptas3, table, solo_chains,
                                        tmp_path):
    """A transient device error at the chunk seam is classified
    retryable; residents revert to their checkpoints and the replay is
    bit-exact."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    faults.clear()
    faults.inject("xla_error", point="serve.chunk", at_row=2, times=1)
    svc = _service(tmp_path / "retry", table, save_every=1)
    jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
            for i, p in enumerate(ptas3[:2])]
    try:
        report = svc.run()
    finally:
        faults.clear()
    assert report["service_retries"] == 1
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])


def test_drain_preempted_per_job_checkpoints(ptas3, table, solo_chains,
                                             tmp_path):
    """A drain request checkpoints every resident to a verified set,
    raises ``Preempted``, and a fresh incarnation resumes bitwise."""
    from pulsar_timing_gibbsspec_tpu.runtime import integrity, preemption

    root = tmp_path / "drain"
    preemption.reset()
    try:
        svc = _service(root, table)
        jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
                for i, p in enumerate(ptas3)]
        assert svc.step()
        preemption.request_drain(reason="test")
        with pytest.raises(preemption.Preempted) as ei:
            svc.run()
        assert ei.value.verified
        for job in jobs:
            if job.it > 0:
                assert job.state == "queued"  # resumable, not failed
                assert integrity.verify(root / job.job_id)["ok"]
    finally:
        preemption.reset()
    svc2 = _service(root, table)
    jobs2 = [svc2.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
             for i, p in enumerate(ptas3)]
    svc2.run()
    for i, job in enumerate(jobs2):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])
