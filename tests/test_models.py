import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.models import model_general, Uniform, Constant
from pulsar_timing_gibbsspec_tpu.models.psd import powerlaw, free_spectrum, turnover, broken_powerlaw
from pulsar_timing_gibbsspec_tpu.models.orf import hd, orf_matrix
from pulsar_timing_gibbsspec_tpu.models.priors import LinearExp


def test_priors_basic():
    u = Uniform(-9, -4, name="rho", size=3)
    rng = np.random.default_rng(0)
    x = u.sample(rng)
    assert x.shape == (3,) and np.all((x >= -9) & (x <= -4))
    assert u.get_logpdf(params={"rho": np.array([-5.0, -5.0, -5.0])}) == pytest.approx(3 * -np.log(5))
    assert u.get_logpdf(params={"rho": np.array([-3.0, -5.0, -5.0])}) == -np.inf
    # reference-style repr bound parsing still possible (pulsar_gibbs.py:84-87)
    rep = str(u.params[0])
    lohi = rep.split("(")[1].split(")")[0].split(", ")
    assert float(lohi[0].split("=")[1]) == -9.0

    le = LinearExp(-18, -11, name="A")
    xs = le.sample(np.random.default_rng(1))
    assert -18 <= xs <= -11


def test_free_spectrum_and_powerlaw():
    f = np.repeat([1e-9, 2e-9, 3e-9], 2)
    df = 1e-9
    phi = free_spectrum(f, df, np.array([-6.0, -7.0, -8.0]))
    assert phi.shape == (6,)
    np.testing.assert_allclose(phi[0], 1e-12)
    np.testing.assert_allclose(phi[1], 1e-12)
    np.testing.assert_allclose(phi[4], 1e-16)

    pl = powerlaw(f, df, -14.0, 13.0 / 3.0)
    assert pl.shape == (6,)
    assert np.all(np.diff(pl[::2]) < 0)  # red spectrum decreasing
    # turnover reduces low-frequency power relative to pure powerlaw
    to = turnover(f, df, -14.0, 13.0 / 3.0, lf0=np.log10(2.5e-9))
    assert to[0] < pl[0]
    bp = broken_powerlaw(f, df, -14.0, 13.0 / 3.0)
    assert np.all(bp > 0)


def test_hd_orf():
    a = np.array([1.0, 0, 0])
    assert hd(a, a) == 1.0
    b = np.array([-1.0, 0, 0])   # antipodal: HD -> ~0.25... actually
    # standard HD at 180 deg: x=1, 1.5*1*log(1) - 0.25 + 0.5 = 0.25
    assert hd(a, b) == pytest.approx(0.25)
    c = np.array([0.0, 1.0, 0])  # 90 deg: x=0.5
    assert hd(a, c) == pytest.approx(1.5 * 0.5 * np.log(0.5) - 0.125 + 0.5)
    G = orf_matrix("hd", [a, b, c])
    assert G.shape == (3, 3) and np.allclose(np.diag(G), 1.0)


def test_model_general_freespec(j1713):
    pta = model_general([j1713], red_var=False, white_vary=True,
                        common_psd="spectrum", common_components=30)
    names = pta.param_names
    # 2 white + 30 rho
    assert len(names) == 32
    assert "J1713+0747_test_efac" in names
    assert "gw_crn_log10_rho_0" in names
    # white params come first (alphabetical: 'J' < 'g')
    assert names[0].startswith("J1713")

    x0 = pta.initial_sample(np.random.default_rng(42))
    assert x0.shape == (32,)

    T = pta.get_basis()[0]
    m = j1713.Mmat.shape[1]
    assert T.shape == (720, m + 60)

    params = pta.map_params(x0)
    phi = pta.get_phi(params)[0]
    assert phi.shape == (m + 60,)
    assert np.all(phi[:m] == 1e40)
    rho = params["gw_crn_log10_rho"]
    np.testing.assert_allclose(phi[m:m + 60], np.repeat(10 ** (2 * rho), 2))

    N = pta.get_ndiag(params)[0]
    efac = params["J1713+0747_test_efac"]
    equad = params["J1713+0747_test_log10_tnequad"]
    np.testing.assert_allclose(N, efac**2 * j1713.toaerrs**2 + 10 ** (2 * equad))

    phiinv, ld = pta.get_phiinv(params, logdet=True)[0]
    np.testing.assert_allclose(phiinv, 1 / phi)
    assert ld == pytest.approx(np.sum(np.log(phi)))

    # signals mapping exposes gw basis for index bookkeeping
    sl = pta.model(0).basis_slice("gw")
    assert sl == slice(m, m + 60)


def test_model_general_with_red(j1713):
    pta = model_general([j1713], red_var=True, red_components=30,
                        white_vary=False, common_psd="spectrum",
                        common_components=30)
    names = pta.param_names
    # no white (constants), 2 red hypers + 30 rho
    assert len(names) == 32
    assert "J1713+0747_red_noise_gamma" in names
    assert "J1713+0747_red_noise_log10_A" in names

    x0 = pta.initial_sample(np.random.default_rng(0))
    params = pta.map_params(x0)
    m = j1713.Mmat.shape[1]
    T = pta.get_basis()[0]
    # red and gw share the Fourier block
    assert T.shape == (720, m + 60)
    phi = pta.get_phi(params)[0]
    gw = np.repeat(10 ** (2 * params["gw_crn_log10_rho"]), 2)
    red_sig = pta.signals["J1713+0747_J1713+0747_red_noise"]
    expected = gw + red_sig.get_phi(params)
    np.testing.assert_allclose(phi[m:m + 60], expected, rtol=1e-12)

    # constant white noise: N = sigma^2
    N = pta.get_ndiag(params)[0]
    np.testing.assert_allclose(N, j1713.toaerrs**2, rtol=1e-12)


def test_model_general_powerlaw_common_fixed_gamma(psrs8):
    pta = model_general(psrs8, red_var=True, white_vary=False,
                        common_psd="powerlaw", gamma_common=13.0 / 3.0)
    names = pta.param_names
    assert "gw_crn_log10_A" in names
    assert "gw_crn_gamma" not in names          # fixed -> Constant, not sampled
    assert len(pta.pulsars) == 8
    # common params deduped across pulsars
    assert sum(1 for n in names if n == "gw_crn_log10_A") == 1


def test_model_general_rejects_unsupported(j1713):
    with pytest.raises(NotImplementedError):
        model_general([j1713], tm_var=True)
    with pytest.raises(NotImplementedError):
        model_general([j1713], use_dmdata=True)
    with pytest.raises(NotImplementedError):
        model_general([j1713], red_psd="tprocess_adapt")
    with pytest.raises(TypeError):
        model_general([j1713], not_a_kwarg=1)


def test_multi_orf(psrs8):
    pta = model_general(psrs8, red_var=False, white_vary=False,
                        common_psd="powerlaw", orf="crn,hd", orf_names="crn,hd")
    names = pta.param_names
    assert "gw_crn_log10_A" in names and "gw_hd_log10_A" in names
