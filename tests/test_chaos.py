"""Fault-injection chaos suite (docs/RESILIENCE.md acceptance).

Every injected fault — NaN'd chunk, fake XlaRuntimeError, kill between
the two os.replace calls in ChainStore.save, truncated chain.npy,
corrupted adapt.npz — must be detected, recovered via rollback/retry,
and the supervised run's final chain must be bit-identical to an
uninterrupted run with the same seed (numpy backend; the jax backend's
resume is bitwise too, so its case asserts exact equality as well).
All cases run on the tiny synthetic PTA, fast enough for tier-1.
"""

import json
import shutil

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.runtime import (faults, integrity,
                                                 preemption, run_supervised,
                                                 supervisor, telemetry)
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

pytestmark = pytest.mark.chaos

NITER = 60
SAVE = 20


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    telemetry.reset()
    preemption.reset()       # the drain flag is process-wide
    yield
    faults.clear()
    preemption.reset()


@pytest.fixture(scope="module")
def x0(synth_pta):
    return synth_pta.initial_sample(np.random.default_rng(0))


@pytest.fixture(scope="module")
def baseline(synth_pta, x0, tmp_path_factory):
    """Uninterrupted numpy run — the bit-identical recovery target."""
    g = PTABlockGibbs(synth_pta, backend="numpy", seed=1, progress=False)
    out = tmp_path_factory.mktemp("baseline")
    return g.sample(x0, outdir=out, niter=NITER, save_every=SAVE)


def _gibbs(pta):
    return PTABlockGibbs(pta, backend="numpy", seed=1, progress=False)


def _events(outdir):
    with open(outdir / "metrics.jsonl") as fh:
        return [json.loads(ln) for ln in fh]


def test_kill_between_replaces_recovers_bitwise(synth_pta, x0, baseline,
                                                tmp_path):
    """A crash in the torn-checkpoint window (chain.npy replaced,
    bchain.npy not yet): the next attempt detects the sha mismatch,
    rolls back to the .bak generation and replays bit-exactly."""
    faults.inject("crash", point="chainstore.between_replaces", at_row=40)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, baseline)
    assert rep.retries == 1
    assert rep.failures[0]["kind"] == "crash"
    assert telemetry.get("rollbacks") == 1
    assert telemetry.get("corrupt_checkpoints") == 1
    evs = [e.get("event") for e in _events(tmp_path)]
    assert "checkpoint_corrupt" in evs and "checkpoint_rollback" in evs


def test_truncated_chain_rolls_back_and_extends_bitwise(synth_pta, x0,
                                                        tmp_path):
    """Truncate chain.npy after a completed run, then extend it under
    supervision: verification fails, the .bak restores the previous
    checkpoint, and the extension replays to a chain bit-identical to
    one never damaged."""
    g = _gibbs(synth_pta)
    g.sample(x0, outdir=tmp_path, niter=NITER, save_every=SAVE)
    ref_dir = tmp_path.parent / (tmp_path.name + "_ref")
    shutil.copytree(tmp_path, ref_dir)
    with open(tmp_path / "chain.npy", "r+b") as fh:
        fh.truncate(fh.seek(0, 2) // 2)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, 100,
                                save_every=SAVE, sleep=lambda s: None)
    ref, _ = run_supervised(_gibbs(synth_pta), x0, ref_dir, 100,
                            save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, ref)
    assert telemetry.get("rollbacks") >= 1


def test_corrupted_adapt_rolls_back_and_extends_bitwise(synth_pta, x0,
                                                        tmp_path):
    g = _gibbs(synth_pta)
    g.sample(x0, outdir=tmp_path, niter=NITER, save_every=SAVE)
    ref_dir = tmp_path.parent / (tmp_path.name + "_refa")
    shutil.copytree(tmp_path, ref_dir)
    with open(tmp_path / "adapt.npz", "r+b") as fh:
        size = fh.seek(0, 2)
        fh.seek(size // 2)
        fh.write(b"\xde\xad\xbe\xef")
    chain, _ = run_supervised(_gibbs(synth_pta), x0, tmp_path, 100,
                              save_every=SAVE, sleep=lambda s: None)
    ref, _ = run_supervised(_gibbs(synth_pta), x0, ref_dir, 100,
                            save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, ref)
    assert telemetry.get("corrupt_checkpoints") >= 1


def test_corruption_without_backup_raises(synth_pta, x0, tmp_path):
    """No verified .bak to fall back to: the supervisor must give up
    loudly (CheckpointError), not loop or resume from garbage."""
    from pulsar_timing_gibbsspec_tpu.runtime import CheckpointError

    g = _gibbs(synth_pta)
    g.sample(x0, outdir=tmp_path, niter=20, save_every=30)  # one save
    for nm in tmp_path.glob("*.bak*"):
        nm.unlink()
    with open(tmp_path / "chain.npy", "r+b") as fh:
        fh.truncate(fh.seek(0, 2) // 2)
    with pytest.raises(CheckpointError, match="no verified .bak"):
        run_supervised(_gibbs(synth_pta), x0, tmp_path, 40,
                       save_every=SAVE, sleep=lambda s: None)


def test_nan_chunk_rewinds_and_recovers_bitwise(synth_pta, x0, baseline,
                                                tmp_path):
    """A transiently NaN'd stretch of recorded rows: the sentinel stops
    it before the checkpoint, the retry rewinds and replays clean."""
    faults.inject("nan_rows", at_row=45)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, baseline)
    assert rep.retries == 1 and rep.refolds == 0
    assert rep.failures[0]["kind"] == "divergence"
    divs = [e for e in _events(tmp_path) if e.get("event") == "divergence"]
    assert divs and divs[0]["row"] == 45 and divs[0]["what"] == "nonfinite"


def test_repeated_divergence_refolds_prng(synth_pta, x0, baseline,
                                          tmp_path):
    """The same divergence reproducing on the deterministic replay means
    rewind-and-replay cannot help: the supervisor refolds the checkpoint
    PRNG so the re-draw takes a fresh stream (and the final chain is, by
    design, NOT the baseline's past the refold point)."""
    faults.inject("nan_rows", at_row=45, times=2)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, sleep=lambda s: None)
    assert np.isfinite(chain).all()
    assert rep.retries == 2 and rep.refolds == 1
    assert np.array_equal(chain[:40], baseline[:40])     # pre-checkpoint
    assert not np.array_equal(chain[40:], baseline[40:])  # fresh stream
    assert any(e.get("event") == "prng_refold" for e in _events(tmp_path))


def test_fake_xla_error_backoff_and_bitwise_recovery(synth_pta, x0,
                                                     baseline, tmp_path):
    """Device-class failures retry under capped exponential backoff; the
    final flush bounds the loss so the retry resumes past the fault row
    and the result is bit-identical."""
    faults.inject("xla_error", point="sample.loop", at_row=30, times=3)
    delays = []
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, backoff_base=0.5,
                                backoff_cap=1.0, jitter=0.0,
                                sleep=delays.append)
    assert np.array_equal(chain, baseline)
    assert [f["kind"] for f in rep.failures] == ["device"] * 3
    assert delays == [0.5, 1.0, 1.0]          # doubling, then capped
    retries = [e for e in _events(tmp_path)
               if e.get("event") == "supervised_retry"]
    assert [r["backoff_s"] for r in retries] == [0.5, 1.0, 1.0]


def test_final_flush_bounds_loss_on_interrupt(synth_pta, x0, tmp_path):
    """A failure between checkpoints still persists every verified row
    (satellite: try/finally flush) — the fault fires at row 30, past the
    row-20 checkpoint, yet resume starts from row 30, not 20."""
    faults.inject("xla_error", point="sample.loop", at_row=30)
    g = _gibbs(synth_pta)
    with pytest.raises(faults.XlaRuntimeError):
        g.sample(x0, outdir=tmp_path, niter=NITER, save_every=SAVE)
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore

    store = ChainStore(tmp_path, g.param_names, g.b_param_names)
    got = store.load_resume()
    assert got is not None and got[2] == 30
    assert any(e.get("event") == "final_flush"
               for e in _events(tmp_path))


def test_jax_nan_chunk_recovers_bitwise(synth_pta, tmp_path):
    """Jax-backend case: injected NaN rows rewind to the checkpoint and
    replay; jax resume is bitwise (per-sweep keys are pure in the
    absolute iteration index), so recovery is exactly equal too."""
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
              chunk_size=4)
    base_dir = tmp_path / "base"
    base = PTABlockGibbs(synth_pta, **kw).sample(
        x0, outdir=base_dir, niter=16, save_every=4)
    faults.inject("nan_rows", at_row=10, backend="jax")
    g = PTABlockGibbs(synth_pta, **kw)
    chain, rep = run_supervised(g, x0, tmp_path / "chaos", 16,
                                save_every=4, sleep=lambda s: None)
    assert np.array_equal(chain, base)
    assert rep.retries == 1
    assert rep.failures[0]["kind"] == "divergence"


def test_jax_degrades_to_numpy_and_completes(synth_pta, tmp_path):
    """After degrade_after consecutive device failures the supervisor
    swaps in the numpy oracle, which adopts the jax checkpoint (same
    rows, fresh deterministic RNG) and finishes the run."""
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    g = PTABlockGibbs(synth_pta, backend="jax", seed=3, progress=False,
                      warmup_sweeps=2, chunk_size=4)
    faults.inject("xla_error", point="sample.loop", at_row=8, times=10,
                  backend="jax")
    chain, rep = run_supervised(g, x0, tmp_path, 16, save_every=4,
                                degrade_after=2, sleep=lambda s: None)
    assert rep.degradations == 1 and rep.backend == "numpy"
    assert telemetry.get("degradations") == 1
    assert chain.shape[0] == 16 and np.isfinite(chain).all()
    evs = [e for e in _events(tmp_path)
           if e.get("event") == "backend_degraded"]
    assert evs and evs[0]["to"] == "numpy"
    # the numpy continuation preserved the jax prefix on disk
    saved = np.load(tmp_path / "chain.npy")
    assert saved.shape == (16, chain.shape[1])
    assert np.isfinite(saved).all()


def test_supervisor_gives_up_after_max_retries(synth_pta, x0, tmp_path):
    faults.inject("xla_error", point="sample.loop", at_row=10, times=99)
    with pytest.raises(faults.XlaRuntimeError):
        run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                       save_every=SAVE, max_retries=2, allow_degrade=False,
                       sleep=lambda s: None)
    evs = [e.get("event") for e in _events(tmp_path)]
    assert "supervised_giving_up" in evs
    assert evs.count("supervised_failure") == 3       # 1 + 2 retries


def test_report_counters_match_telemetry(synth_pta, x0, tmp_path):
    faults.inject("crash", point="chainstore.between_replaces", at_row=40)
    _, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                            save_every=SAVE, sleep=lambda s: None)
    assert rep.attempts == 2
    assert telemetry.get("retries") == rep.retries == 1
    d = rep.as_dict()
    assert d["backend"] == "numpy" and len(d["failures"]) == 1


# -- preemption drain / watchdog / reshard elasticity (ISSUE 4) -------------

def test_sigterm_drains_to_verified_checkpoint_and_resumes_bitwise(
        synth_pta, x0, baseline, tmp_path):
    """A drain request mid-run (sigterm_at_seam — the same request_drain
    the real SIGTERM handler calls) stops the loop, flushes, verifies,
    and surfaces as the supervisor's resumable ``preempted`` status —
    never a failure; the next incarnation resumes bit-identically."""
    faults.inject("sigterm_at_seam", point="sample.loop", at_row=30,
                  seconds=60.0)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, sleep=lambda s: None)
    assert rep.status == "preempted"
    assert rep.attempts == 1 and rep.retries == 0 and not rep.failures
    assert telemetry.get("preempt_requests") == 1
    assert telemetry.get("preempt_drains") == 1
    assert telemetry.get_gauge("drain_latency_ms") is not None
    v = integrity.verify(tmp_path)
    assert v["ok"] and v["rows"] == 30
    assert np.array_equal(chain[:30], baseline[:30])
    evs = [e.get("event") for e in _events(tmp_path)]
    for want in ("drain_requested", "preempted_drain",
                 "supervised_preempted"):
        assert want in evs, want
    # next incarnation (fresh process: flag cleared) — bitwise resume
    preemption.reset()
    chain2, rep2 = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                  save_every=SAVE, sleep=lambda s: None)
    assert rep2.status == "completed"
    assert np.array_equal(chain2, baseline)


def test_kill_during_drain_rolls_back_to_backup(synth_pta, x0, baseline,
                                                tmp_path):
    """A concurrent kill tearing the drain's final flush (chain.npy
    damaged after the manifest was written): the drain path verifies,
    rolls back to the .bak generation, and still reports a VERIFIED —
    just earlier — checkpoint; the next incarnation extends bitwise."""
    faults.inject("sigterm_at_seam", point="sample.loop", at_row=30,
                  seconds=60.0)
    faults.inject("truncate_file", point="chainstore.post_save",
                  at_row=25, path="chain.npy")
    _, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                            save_every=SAVE, sleep=lambda s: None)
    assert rep.status == "preempted"
    assert telemetry.get("rollbacks") == 1
    v = integrity.verify(tmp_path)
    assert v["ok"] and v["rows"] == 20          # the pre-drain checkpoint
    drains = [e for e in _events(tmp_path)
              if e.get("event") == "preempted_drain"]
    assert drains and drains[0]["verified"] and drains[0]["rolled_back"]
    preemption.reset()
    faults.clear()
    chain2, rep2 = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                  save_every=SAVE, sleep=lambda s: None)
    assert rep2.status == "completed"
    assert np.array_equal(chain2, baseline)


def test_watchdog_stall_aborts_chunk_and_resumes_bitwise(synth_pta,
                                                         tmp_path):
    """An injected stall inside the dispatch seam blows the watchdog's
    EMA deadline: the chunk is abandoned as the ``stall`` class, the
    supervisor retries under the stall budget, and the resumed run is
    bit-identical to an unstalled one (the aborted chunk never reached
    the chain files)."""
    from pulsar_timing_gibbsspec_tpu.runtime.watchdog import DispatchWatchdog

    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
              chunk_size=4)
    base = PTABlockGibbs(synth_pta, **kw).sample(
        x0, outdir=tmp_path / "base", niter=16, save_every=4)
    faults.inject("stall", point="dispatch.chunk", at_row=11,
                  seconds=5.0, backend="jax")
    wd = DispatchWatchdog(k=4.0, floor_s=0.4, first_floor_s=120.0,
                          poll_s=0.02)
    g = PTABlockGibbs(synth_pta, watchdog=wd, **kw)
    chain, rep = run_supervised(g, x0, tmp_path / "chaos", 16,
                                save_every=4, sleep=lambda s: None)
    assert np.array_equal(chain, base)
    assert rep.status == "completed"
    assert rep.stall_retries == 1 and rep.retries == 0
    assert rep.failures[0]["kind"] == "stall"
    assert telemetry.get("watchdog_stalls") == 1
    assert telemetry.get("watchdog_dumps") == 1
    assert telemetry.get("stall_retries") == 1


def test_stall_budget_is_capped(synth_pta, x0, tmp_path):
    """A stall that never clears exhausts its OWN capped budget and
    re-raises — it must not spin on the general retry budget."""
    from pulsar_timing_gibbsspec_tpu.runtime.watchdog import DispatchStall

    class AlwaysStalls:
        backend_name = "jax"
        chain = None

        def sample(self, *a, **k):
            raise DispatchStall("wedged")

    with pytest.raises(DispatchStall):
        run_supervised(AlwaysStalls(), x0, tmp_path, NITER,
                       save_every=SAVE, stall_max_retries=2,
                       sleep=lambda s: None)
    evs = [e.get("event") for e in _events(tmp_path)]
    assert "supervised_giving_up" in evs
    assert telemetry.get("stall_retries") == 2


@pytest.fixture(scope="module")
def crn_mesh8(synth_pta, tmp_path_factory):
    """A CRN run checkpointed mid-stream under an 8-device mesh with
    pad_pulsars=8 (the logical padded width), plus the uninterrupted
    16-row target — shared by the reshard cases below."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh

    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
              chunk_size=4, pad_pulsars=8)
    root = tmp_path_factory.mktemp("crn_mesh8")
    base = PTABlockGibbs(synth_pta, mesh=make_mesh(8), **kw).sample(
        x0, outdir=root / "base", niter=16, save_every=4)
    PTABlockGibbs(synth_pta, mesh=make_mesh(8), **kw).sample(
        x0, outdir=root / "src", niter=8, save_every=4)
    return {"x0": x0, "base": base, "src": root / "src"}


def test_reshard_resume_crn_bitwise(synth_pta, crn_mesh8, tmp_path):
    """Elasticity contract, CRN case: a checkpoint written under 8
    devices resumes under 1, 2 and 4 via reshard_restore, and every
    resumed chain is bitwise-identical per logical chain to the
    uninterrupted 8-device run — the logical layout (padded width,
    chain/pulsar order, key folds) pins the stream; the shard map is
    only placement."""
    for d in (1, 2, 4):
        dst = tmp_path / f"dev{d}"
        shutil.copytree(crn_mesh8["src"], dst)
        g = integrity.reshard_restore(dst, synth_pta, devices=d,
                                      seed=3, progress=False,
                                      warmup_sweeps=2, chunk_size=4)
        chain = g.sample(crn_mesh8["x0"], outdir=dst, niter=16,
                         resume=True, save_every=4)
        assert np.array_equal(chain, crn_mesh8["base"]), f"devices={d}"
        info = integrity.read_layout(dst)
        assert info["layout"]["pad_pulsars"] == 8
        if d > 1:
            assert info["shard_map"]["devices"] == d
        else:
            assert info["shard_map"] is None


def test_reshard_back_up_to_eight(synth_pta, crn_mesh8, tmp_path):
    """8 -> 4 -> 8: scale down mid-run, then back up, still bitwise."""
    dst = tmp_path / "updown"
    shutil.copytree(crn_mesh8["src"], dst)
    g = integrity.reshard_restore(dst, synth_pta, devices=4, seed=3,
                                  progress=False, warmup_sweeps=2,
                                  chunk_size=4)
    g.sample(crn_mesh8["x0"], outdir=dst, niter=12, resume=True,
             save_every=4)
    g = integrity.reshard_restore(dst, synth_pta, devices=8, seed=3,
                                  progress=False, warmup_sweeps=2,
                                  chunk_size=4)
    chain = g.sample(crn_mesh8["x0"], outdir=dst, niter=16, resume=True,
                     save_every=4)
    assert np.array_equal(chain, crn_mesh8["base"])
    assert integrity.read_layout(dst)["shard_map"]["devices"] == 8


def test_device_count_change_fault_overrides_reshard(synth_pta,
                                                     crn_mesh8, tmp_path):
    """The device_count_change_on_resume fault stands in for the pool
    handing the next incarnation a different slice: reshard_restore
    consults it and builds the mesh for the injected count."""
    dst = tmp_path / "pool"
    shutil.copytree(crn_mesh8["src"], dst)
    faults.inject("device_count_change_on_resume", devices=2)
    g = integrity.reshard_restore(dst, synth_pta, devices=8, seed=3,
                                  progress=False, warmup_sweeps=2,
                                  chunk_size=4)
    assert g._backend._mesh.devices.size == 2
    chain = g.sample(crn_mesh8["x0"], outdir=dst, niter=16, resume=True,
                     save_every=4)
    assert np.array_equal(chain, crn_mesh8["base"])


def test_reshard_rejects_indivisible_device_count(synth_pta, crn_mesh8,
                                                  tmp_path):
    dst = tmp_path / "bad"
    shutil.copytree(crn_mesh8["src"], dst)
    with pytest.raises(integrity.CheckpointError, match="padded pulsar"):
        integrity.reshard_restore(dst, synth_pta, devices=3)


def test_reshard_resume_hd_statistical(synth_hd_pta, tmp_path):
    """HD (multi-pulsar) case: cross-pulsar all-reduce order may change
    with the device count, so the contract is prefix-bitwise (the
    checkpointed rows ARE the checkpointed rows) plus a distribution-
    level match of the continuation, not a bitwise one."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh

    x0 = synth_hd_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=5, progress=False, warmup_sweeps=2,
              chunk_size=4, pad_pulsars=4)
    base = PTABlockGibbs(synth_hd_pta, mesh=make_mesh(4), **kw).sample(
        x0, outdir=tmp_path / "base", niter=16, save_every=4)
    src = tmp_path / "src"
    PTABlockGibbs(synth_hd_pta, mesh=make_mesh(4), **kw).sample(
        x0, outdir=src, niter=8, save_every=4)
    g = integrity.reshard_restore(src, synth_hd_pta, devices=2, seed=5,
                                  progress=False, warmup_sweeps=2,
                                  chunk_size=4)
    chain = g.sample(x0, outdir=src, niter=16, resume=True, save_every=4)
    assert chain.shape == base.shape
    assert np.array_equal(chain[:8], base[:8])      # checkpointed prefix
    assert np.isfinite(chain).all()
    # KS-level: the continued stretches sample the same posterior; with
    # identical seeds and only reduction-order noise between them they
    # are numerically close row-by-row long before 8 rows decorrelate
    tail, btail = chain[8:], base[8:]
    span = base.max(axis=0) - base.min(axis=0) + 1e-12
    assert np.all(np.abs(tail - btail) / span < 0.5)


# -- 2-d (chain, pulsar) mesh elasticity (ISSUE 9) ---------------------------

@pytest.fixture(scope="module")
def crn_mesh2d(synth_pta, tmp_path_factory):
    """A 4-chain CRN run checkpointed mid-stream under the 2-d (2, 4)
    chains x pulsars mesh (pad_pulsars=4 logical width), plus the
    uninterrupted 24-row target — shared by the 2-d reshard and chaos
    cases below."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh

    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
              chunk_size=4, nchains=4, pad_pulsars=4)
    root = tmp_path_factory.mktemp("crn_mesh2d")
    base = PTABlockGibbs(synth_pta, mesh=make_mesh((2, 4)), **kw).sample(
        x0, outdir=root / "base", niter=24, save_every=4)
    PTABlockGibbs(synth_pta, mesh=make_mesh((2, 4)), **kw).sample(
        x0, outdir=root / "src", niter=8, save_every=4)
    return {"x0": x0, "base": base, "src": root / "src"}


def test_reshard_roundtrip_2d_bitwise(synth_pta, crn_mesh2d, tmp_path):
    """Elasticity on both axes: a checkpoint written under (2, 4)
    resumes under (1, 1), then (4, 2), then back to (2, 4), and the
    final chain is bitwise-identical per LOGICAL chain to the
    uninterrupted (2, 4) run — chains are independent processes keyed
    by logical index, the padded width and key folds pin the streams,
    and placement (either axis) never touches them."""
    dst = tmp_path / "trip"
    shutil.copytree(crn_mesh2d["src"], dst)
    chain = None
    for devs, upto in (((1, 1), 12), ((4, 2), 16), ((2, 4), 24)):
        g = integrity.reshard_restore(dst, synth_pta, devices=devs,
                                      seed=3, progress=False,
                                      warmup_sweeps=2, chunk_size=4)
        chain = g.sample(crn_mesh2d["x0"], outdir=dst, niter=upto,
                         resume=True, save_every=4)
    assert np.array_equal(chain, crn_mesh2d["base"])
    info = integrity.read_layout(dst)
    assert info["layout"]["nchains"] == 4
    assert info["shard_map"]["axes"] == [["chain", 2], ["pulsar", 4]]


def test_reshard_2d_rejects_indivisible_axes(synth_pta, crn_mesh2d,
                                             tmp_path):
    """Both divisibility gates, each naming its own knob: the chain
    count over the chain axis, the padded width over the pulsar axis."""
    dst = tmp_path / "bad"
    shutil.copytree(crn_mesh2d["src"], dst)
    with pytest.raises(integrity.CheckpointError, match="chain count"):
        integrity.reshard_restore(dst, synth_pta, devices=(3, 2))
    with pytest.raises(integrity.CheckpointError, match="pulsar-axis"):
        integrity.reshard_restore(dst, synth_pta, devices=(2, 3))


def test_chaos_kill_mid_run_2d_recovers_bitwise(synth_pta, crn_mesh2d,
                                                tmp_path):
    """The torn-checkpoint kill on the 2-d mesh: a crash between the
    two os.replace calls mid-run rolls back to the .bak generation and
    the supervised retry replays every chain bit-exactly — chain-
    sharded carries add no new recovery surface."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh

    kw = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
              chunk_size=4, nchains=4, pad_pulsars=4)
    faults.inject("crash", point="chainstore.between_replaces", at_row=16)
    g = PTABlockGibbs(synth_pta, mesh=make_mesh((2, 4)), **kw)
    chain, rep = run_supervised(g, crn_mesh2d["x0"], tmp_path, 24,
                                save_every=4, sleep=lambda s: None)
    assert np.array_equal(chain, crn_mesh2d["base"])
    assert rep.retries == 1
    assert telemetry.get("rollbacks") == 1
