"""Fault-injection chaos suite (docs/RESILIENCE.md acceptance).

Every injected fault — NaN'd chunk, fake XlaRuntimeError, kill between
the two os.replace calls in ChainStore.save, truncated chain.npy,
corrupted adapt.npz — must be detected, recovered via rollback/retry,
and the supervised run's final chain must be bit-identical to an
uninterrupted run with the same seed (numpy backend; the jax backend's
resume is bitwise too, so its case asserts exact equality as well).
All cases run on the tiny synthetic PTA, fast enough for tier-1.
"""

import json
import shutil

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.runtime import (faults, run_supervised,
                                                 supervisor, telemetry)
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

pytestmark = pytest.mark.chaos

NITER = 60
SAVE = 20


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    telemetry.reset()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def x0(synth_pta):
    return synth_pta.initial_sample(np.random.default_rng(0))


@pytest.fixture(scope="module")
def baseline(synth_pta, x0, tmp_path_factory):
    """Uninterrupted numpy run — the bit-identical recovery target."""
    g = PTABlockGibbs(synth_pta, backend="numpy", seed=1, progress=False)
    out = tmp_path_factory.mktemp("baseline")
    return g.sample(x0, outdir=out, niter=NITER, save_every=SAVE)


def _gibbs(pta):
    return PTABlockGibbs(pta, backend="numpy", seed=1, progress=False)


def _events(outdir):
    with open(outdir / "metrics.jsonl") as fh:
        return [json.loads(ln) for ln in fh]


def test_kill_between_replaces_recovers_bitwise(synth_pta, x0, baseline,
                                                tmp_path):
    """A crash in the torn-checkpoint window (chain.npy replaced,
    bchain.npy not yet): the next attempt detects the sha mismatch,
    rolls back to the .bak generation and replays bit-exactly."""
    faults.inject("crash", point="chainstore.between_replaces", at_row=40)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, baseline)
    assert rep.retries == 1
    assert rep.failures[0]["kind"] == "crash"
    assert telemetry.get("rollbacks") == 1
    assert telemetry.get("corrupt_checkpoints") == 1
    evs = [e.get("event") for e in _events(tmp_path)]
    assert "checkpoint_corrupt" in evs and "checkpoint_rollback" in evs


def test_truncated_chain_rolls_back_and_extends_bitwise(synth_pta, x0,
                                                        tmp_path):
    """Truncate chain.npy after a completed run, then extend it under
    supervision: verification fails, the .bak restores the previous
    checkpoint, and the extension replays to a chain bit-identical to
    one never damaged."""
    g = _gibbs(synth_pta)
    g.sample(x0, outdir=tmp_path, niter=NITER, save_every=SAVE)
    ref_dir = tmp_path.parent / (tmp_path.name + "_ref")
    shutil.copytree(tmp_path, ref_dir)
    with open(tmp_path / "chain.npy", "r+b") as fh:
        fh.truncate(fh.seek(0, 2) // 2)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, 100,
                                save_every=SAVE, sleep=lambda s: None)
    ref, _ = run_supervised(_gibbs(synth_pta), x0, ref_dir, 100,
                            save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, ref)
    assert telemetry.get("rollbacks") >= 1


def test_corrupted_adapt_rolls_back_and_extends_bitwise(synth_pta, x0,
                                                        tmp_path):
    g = _gibbs(synth_pta)
    g.sample(x0, outdir=tmp_path, niter=NITER, save_every=SAVE)
    ref_dir = tmp_path.parent / (tmp_path.name + "_refa")
    shutil.copytree(tmp_path, ref_dir)
    with open(tmp_path / "adapt.npz", "r+b") as fh:
        size = fh.seek(0, 2)
        fh.seek(size // 2)
        fh.write(b"\xde\xad\xbe\xef")
    chain, _ = run_supervised(_gibbs(synth_pta), x0, tmp_path, 100,
                              save_every=SAVE, sleep=lambda s: None)
    ref, _ = run_supervised(_gibbs(synth_pta), x0, ref_dir, 100,
                            save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, ref)
    assert telemetry.get("corrupt_checkpoints") >= 1


def test_corruption_without_backup_raises(synth_pta, x0, tmp_path):
    """No verified .bak to fall back to: the supervisor must give up
    loudly (CheckpointError), not loop or resume from garbage."""
    from pulsar_timing_gibbsspec_tpu.runtime import CheckpointError

    g = _gibbs(synth_pta)
    g.sample(x0, outdir=tmp_path, niter=20, save_every=30)  # one save
    for nm in tmp_path.glob("*.bak*"):
        nm.unlink()
    with open(tmp_path / "chain.npy", "r+b") as fh:
        fh.truncate(fh.seek(0, 2) // 2)
    with pytest.raises(CheckpointError, match="no verified .bak"):
        run_supervised(_gibbs(synth_pta), x0, tmp_path, 40,
                       save_every=SAVE, sleep=lambda s: None)


def test_nan_chunk_rewinds_and_recovers_bitwise(synth_pta, x0, baseline,
                                                tmp_path):
    """A transiently NaN'd stretch of recorded rows: the sentinel stops
    it before the checkpoint, the retry rewinds and replays clean."""
    faults.inject("nan_rows", at_row=45)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, sleep=lambda s: None)
    assert np.array_equal(chain, baseline)
    assert rep.retries == 1 and rep.refolds == 0
    assert rep.failures[0]["kind"] == "divergence"
    divs = [e for e in _events(tmp_path) if e.get("event") == "divergence"]
    assert divs and divs[0]["row"] == 45 and divs[0]["what"] == "nonfinite"


def test_repeated_divergence_refolds_prng(synth_pta, x0, baseline,
                                          tmp_path):
    """The same divergence reproducing on the deterministic replay means
    rewind-and-replay cannot help: the supervisor refolds the checkpoint
    PRNG so the re-draw takes a fresh stream (and the final chain is, by
    design, NOT the baseline's past the refold point)."""
    faults.inject("nan_rows", at_row=45, times=2)
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, sleep=lambda s: None)
    assert np.isfinite(chain).all()
    assert rep.retries == 2 and rep.refolds == 1
    assert np.array_equal(chain[:40], baseline[:40])     # pre-checkpoint
    assert not np.array_equal(chain[40:], baseline[40:])  # fresh stream
    assert any(e.get("event") == "prng_refold" for e in _events(tmp_path))


def test_fake_xla_error_backoff_and_bitwise_recovery(synth_pta, x0,
                                                     baseline, tmp_path):
    """Device-class failures retry under capped exponential backoff; the
    final flush bounds the loss so the retry resumes past the fault row
    and the result is bit-identical."""
    faults.inject("xla_error", point="sample.loop", at_row=30, times=3)
    delays = []
    chain, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                                save_every=SAVE, backoff_base=0.5,
                                backoff_cap=1.0, jitter=0.0,
                                sleep=delays.append)
    assert np.array_equal(chain, baseline)
    assert [f["kind"] for f in rep.failures] == ["device"] * 3
    assert delays == [0.5, 1.0, 1.0]          # doubling, then capped
    retries = [e for e in _events(tmp_path)
               if e.get("event") == "supervised_retry"]
    assert [r["backoff_s"] for r in retries] == [0.5, 1.0, 1.0]


def test_final_flush_bounds_loss_on_interrupt(synth_pta, x0, tmp_path):
    """A failure between checkpoints still persists every verified row
    (satellite: try/finally flush) — the fault fires at row 30, past the
    row-20 checkpoint, yet resume starts from row 30, not 20."""
    faults.inject("xla_error", point="sample.loop", at_row=30)
    g = _gibbs(synth_pta)
    with pytest.raises(faults.XlaRuntimeError):
        g.sample(x0, outdir=tmp_path, niter=NITER, save_every=SAVE)
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore

    store = ChainStore(tmp_path, g.param_names, g.b_param_names)
    got = store.load_resume()
    assert got is not None and got[2] == 30
    assert any(e.get("event") == "final_flush"
               for e in _events(tmp_path))


def test_jax_nan_chunk_recovers_bitwise(synth_pta, tmp_path):
    """Jax-backend case: injected NaN rows rewind to the checkpoint and
    replay; jax resume is bitwise (per-sweep keys are pure in the
    absolute iteration index), so recovery is exactly equal too."""
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
              chunk_size=4)
    base_dir = tmp_path / "base"
    base = PTABlockGibbs(synth_pta, **kw).sample(
        x0, outdir=base_dir, niter=16, save_every=4)
    faults.inject("nan_rows", at_row=10, backend="jax")
    g = PTABlockGibbs(synth_pta, **kw)
    chain, rep = run_supervised(g, x0, tmp_path / "chaos", 16,
                                save_every=4, sleep=lambda s: None)
    assert np.array_equal(chain, base)
    assert rep.retries == 1
    assert rep.failures[0]["kind"] == "divergence"


def test_jax_degrades_to_numpy_and_completes(synth_pta, tmp_path):
    """After degrade_after consecutive device failures the supervisor
    swaps in the numpy oracle, which adopts the jax checkpoint (same
    rows, fresh deterministic RNG) and finishes the run."""
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    g = PTABlockGibbs(synth_pta, backend="jax", seed=3, progress=False,
                      warmup_sweeps=2, chunk_size=4)
    faults.inject("xla_error", point="sample.loop", at_row=8, times=10,
                  backend="jax")
    chain, rep = run_supervised(g, x0, tmp_path, 16, save_every=4,
                                degrade_after=2, sleep=lambda s: None)
    assert rep.degradations == 1 and rep.backend == "numpy"
    assert telemetry.get("degradations") == 1
    assert chain.shape[0] == 16 and np.isfinite(chain).all()
    evs = [e for e in _events(tmp_path)
           if e.get("event") == "backend_degraded"]
    assert evs and evs[0]["to"] == "numpy"
    # the numpy continuation preserved the jax prefix on disk
    saved = np.load(tmp_path / "chain.npy")
    assert saved.shape == (16, chain.shape[1])
    assert np.isfinite(saved).all()


def test_supervisor_gives_up_after_max_retries(synth_pta, x0, tmp_path):
    faults.inject("xla_error", point="sample.loop", at_row=10, times=99)
    with pytest.raises(faults.XlaRuntimeError):
        run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                       save_every=SAVE, max_retries=2, allow_degrade=False,
                       sleep=lambda s: None)
    evs = [e.get("event") for e in _events(tmp_path)]
    assert "supervised_giving_up" in evs
    assert evs.count("supervised_failure") == 3       # 1 + 2 retries


def test_report_counters_match_telemetry(synth_pta, x0, tmp_path):
    faults.inject("crash", point="chainstore.between_replaces", at_row=40)
    _, rep = run_supervised(_gibbs(synth_pta), x0, tmp_path, NITER,
                            save_every=SAVE, sleep=lambda s: None)
    assert rep.attempts == 2
    assert telemetry.get("retries") == rep.retries == 1
    d = rep.as_dict()
    assert d["backend"] == "numpy" and len(d["failures"]) == 1
