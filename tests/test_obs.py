"""Observability stack: on-device sketches, convergence finalizers,
trace spans, metrics exposition — and the bitwise-identity guarantee of
the instrumented chunk (the obs acceptance contract).

The sketch math tests drive :mod:`obs.sketch` directly with synthetic
streams in uneven chunks (the driver's chunk grid must not matter);
the driver test runs the real compiled chunk twice, obs off and on,
and asserts byte-identical sampling outputs.
"""

import json

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.config import settings

# the sketch state is f64 by contract; x64 must be on before any traced
# op (normally settings.apply() runs at model-compile entry)
settings.apply()

from pulsar_timing_gibbsspec_tpu.obs import convergence, metrics, summary
from pulsar_timing_gibbsspec_tpu.obs.sketch import (SketchSpec, init_state,
                                                    state_bytes, update)
from pulsar_timing_gibbsspec_tpu.obs.summary import (RollingDiag, finalize,
                                                     moment_split_rhat)
from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act
from pulsar_timing_gibbsspec_tpu.runtime import telemetry


def _spec(D=3, cross=2, lags=16):
    return SketchSpec(
        channels=np.arange(D, dtype=np.int32),
        names=tuple(f"p{i}_gw_rho" for i in range(D)),
        cross_k=cross, lags=lags,
        groups=(("all", np.arange(D, dtype=np.int32)),))


def _stream(spec, xs, chunks):
    """Feed ``xs`` (n, C, D) through ``update`` on the given chunk grid,
    returning (host state, per-chunk cumulative moment snapshots)."""
    import jax.numpy as jnp

    st = init_state(spec, xs.shape[1])
    x0 = jnp.zeros(xs.shape[1:])
    snaps, row = [], 0
    for c in chunks:
        blk = jnp.asarray(xs[row:row + c])
        st = update(spec, st, x0, blk)
        x0 = blk[-1]
        row += c
        snaps.append((float(np.asarray(st["n"])),
                      np.asarray(st["mean"], np.float64),
                      np.asarray(st["m2"], np.float64)))
    assert row == len(xs)
    return {k: np.asarray(v) for k, v in st.items()}, snaps


def _ar1(rng, n, C, D, phi=0.7):
    x = np.zeros((n, C, D))
    e = rng.standard_normal((n, C, D)) * np.sqrt(1 - phi**2)
    for t in range(1, n):
        x[t] = phi * x[t - 1] + e[t]
    return x


# ---------------------------------------------------------------------------
# sketch math


def test_welford_and_cross_cov_match_numpy():
    rng = np.random.default_rng(0)
    spec = _spec(D=3, cross=3, lags=8)
    xs = 3.0 + 2.0 * rng.standard_normal((57, 2, 3))
    st, _ = _stream(spec, xs, chunks=(7, 13, 37))
    fin = finalize(spec, st)
    flat = np.moveaxis(xs, 0, -1)                     # (C, D, n)
    np.testing.assert_allclose(fin["mean"], flat.mean(-1), atol=1e-10)
    np.testing.assert_allclose(fin["var"], flat.var(-1, ddof=1),
                               rtol=1e-10)
    for c in range(2):
        want = np.cov(flat[c], ddof=1)                # (D, D)
        np.testing.assert_allclose(fin["cross_cov"][c], want, rtol=1e-8)


def test_state_bytes_matches_pytree():
    spec = _spec(D=5, cross=2, lags=32)
    st = init_state(spec, 3)
    got = sum(np.asarray(v).nbytes for v in st.values())
    assert got == state_bytes(spec, 3)


def test_device_act_matches_host_sokal_on_ar1():
    """The acceptance bound: one-pass device ACT within 10% of the host
    Sokal estimator on the same stream (AR(1), true tau ~ 5.67)."""
    rng = np.random.default_rng(1)
    phi = 0.7
    spec = _spec(D=1, cross=1, lags=64)
    xs = _ar1(rng, 4000, 2, 1, phi)
    st, _ = _stream(spec, xs, chunks=(250,) * 16)
    fin = finalize(spec, st)
    for c in range(2):
        host = integrated_act(xs[:, c, 0])
        dev = float(fin["act"][c, 0])
        assert abs(dev - host) / host < 0.10
    # and both near the analytic tau = (1+phi)/(1-phi)
    true_tau = (1 + phi) / (1 - phi)
    assert abs(float(np.median(fin["act"])) - true_tau) / true_tau < 0.25
    assert not fin["window_saturated"]
    assert fin["act_rho_med"] > 1.0
    assert fin["ess_total"] > 0


def test_move_rate_counts_changed_transitions():
    spec = _spec(D=2, cross=0, lags=4)
    # chain 0 moves every sweep, chain 1 is frozen
    xs = np.zeros((10, 2, 2))
    xs[:, 0, :] = np.arange(10)[:, None]
    st, _ = _stream(spec, xs, chunks=(4, 6))
    fin = finalize(spec, st)
    rate = fin["move_rate"]["all"]
    assert rate[0] > 0.85           # first transition from x0=0 counts
    assert rate[1] < 0.15


# ---------------------------------------------------------------------------
# convergence


def test_rank_split_rhat_iid_near_one_and_shifted_large():
    rng = np.random.default_rng(2)
    iid = rng.standard_normal((4, 600))
    assert convergence.rank_normalized_split_rhat(iid) < 1.05
    shifted = iid + np.arange(4)[:, None] * 3.0
    assert convergence.rank_normalized_split_rhat(shifted) > 1.5
    # the folded half catches scale (tail) drift the bulk half misses
    scaled = iid * (1.0 + 3.0 * np.arange(4))[:, None]
    assert convergence.rank_normalized_split_rhat(scaled) > 1.2


def test_ensemble_rhat_shapes():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((3, 400, 5))
    r = convergence.ensemble_rhat(x)
    assert r.shape == (5,)
    assert np.all(r < 1.05)


def test_moment_split_rhat_from_snapshots():
    rng = np.random.default_rng(4)
    spec = _spec(D=2, cross=0, lags=4)
    xs = rng.standard_normal((400, 3, 2))
    st, snaps = _stream(spec, xs, chunks=(50,) * 8)
    r = moment_split_rhat(snaps, st)
    assert r.shape == (2,)
    assert np.all(r < 1.05)
    # a level shift halfway through the stream must blow up R-hat
    xs2 = xs.copy()
    xs2[200:] += 5.0
    st2, snaps2 = _stream(spec, xs2, chunks=(50,) * 8)
    r2 = moment_split_rhat(snaps2, st2)
    assert np.all(r2 > 2.0)


def test_rolling_diag_gauges():
    rng = np.random.default_rng(5)
    d = RollingDiag(cap=256)
    rows = _ar1(rng, 300, 1, 3)[:, 0, :]
    for i in range(0, 300, 25):
        d.observe(rows[i:i + 25], now=float(i))
    assert d.row_rate() > 0
    assert d.act() >= 1.0
    assert d.ess_per_sec() > 0
    assert d.rhat_max() < 1.2
    assert 0.0 <= d.accept_rate() <= 1.0


# ---------------------------------------------------------------------------
# the driver acceptance: instrumentation must not touch sampling


def test_instrumented_chunk_bitwise_identical():
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import (
        JaxGibbsDriver)

    pta = build_model(synthetic_pulsars(2, 24, tm_cols=3, seed=0), 2)
    x0 = pta.initial_sample(np.random.default_rng(0))
    runs = {}
    for obs in (None, True):
        drv = JaxGibbsDriver(pta, seed=7, common_rho=True,
                             white_adapt_iters=6, chunk_size=8,
                             nchains=2, warmup_sweeps=6, obs=obs)
        cs, bs = drv.chain_shapes(30)
        chain, bchain = np.zeros(cs), np.zeros(bs)
        for _ in drv.run(x0, chain, bchain, 0, 30):
            pass
        runs[obs] = (chain, bchain, drv)
    assert runs[None][0].tobytes() == runs[True][0].tobytes()
    assert runs[None][1].tobytes() == runs[True][1].tobytes()
    s = runs[True][2].obs_summary()
    assert s["n"] > 0
    assert np.isfinite(s["act_rho_med"])
    with pytest.raises(RuntimeError):
        runs[None][2].obs_summary()


def test_stage_aggregator_bitwise_inert():
    """The PR 7 proof extended over the streaming stage telemetry: a
    run with the StageAggregator observing every chunk span produces
    byte-identical sampling outputs — the gauges are host-side folds of
    host-side timestamps, nothing enters the traced program."""
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.obs.perf import StageAggregator
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import (
        JaxGibbsDriver)

    pta = build_model(synthetic_pulsars(2, 24, tm_cols=3, seed=0), 2)
    x0 = pta.initial_sample(np.random.default_rng(0))
    telemetry.reset("dispatch_ms")
    runs = {}
    for watched in (False, True):
        agg = StageAggregator(job="bw").install() if watched else None
        try:
            drv = JaxGibbsDriver(pta, seed=7, common_rho=True,
                                 white_adapt_iters=6, chunk_size=8,
                                 nchains=2, warmup_sweeps=6)
            cs, bs = drv.chain_shapes(30)
            chain, bchain = np.zeros(cs), np.zeros(bs)
            for _ in drv.run(x0, chain, bchain, 0, 30):
                pass
        finally:
            if agg is not None:
                agg.uninstall()
        runs[watched] = (chain, bchain, agg)
    assert runs[False][0].tobytes() == runs[True][0].tobytes()
    assert runs[False][1].tobytes() == runs[True][1].tobytes()
    # and the observer actually saw the pipeline: per-stage series fed,
    # labeled gauges live in the registry
    summ = runs[True][2].summary()
    assert summ, "StageAggregator saw no pipeline spans"
    assert any(st in summ for st in ("enqueue", "device"))
    assert telemetry.get_gauge("dispatch_ms", job="bw",
                               stage=next(iter(summ)),
                               stat="ema") is not None
    telemetry.reset("dispatch_ms")


# ---------------------------------------------------------------------------
# trace layer


def test_trace_spans_nest_and_export(tmp_path):
    trace = __import__("pulsar_timing_gibbsspec_tpu.obs.trace",
                       fromlist=["trace"])
    sink_lines = []
    trace.enable(lambda ev: sink_lines.append(ev))
    try:
        with trace.span("outer", row=1):
            with trace.span("inner"):
                pass
        trace.instant("mark", x=2)
        evs = trace.events()
    finally:
        path = trace.write_chrome(tmp_path / "t.json")
        trace.disable()
    names = [e["name"] for e in evs]
    assert names == ["inner", "outer", "mark"]   # spans close inner-first
    outer = evs[1]
    inner = evs[0]
    assert outer["ph"] == "X" and inner["ph"] == "X"
    # containment: inner lies within outer on the same track
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    assert outer["args"] == {"row": 1}
    doc = json.loads((tmp_path / "t.json").read_text())
    assert path == str(tmp_path / "t.json")
    assert len(doc["traceEvents"]) == 3
    # the sink saw the same three events as structured lines
    assert [ev["name"] for ev in sink_lines] == names


def test_trace_disabled_is_free():
    from pulsar_timing_gibbsspec_tpu.obs import trace

    trace.disable()
    before = trace.events()             # disable keeps the buffer for
    a = trace.span("x")                 # late export; enable() clears it
    b = trace.span("y", k=1)
    assert a is b                       # one shared nullcontext
    with a:
        pass
    trace.instant("z")
    assert trace.events() == before     # nothing recorded while off


def test_trace_ring_bounded_and_dropped(monkeypatch):
    """The event buffer is a ring: a long run cannot grow host memory
    unboundedly; evictions are counted and flagged in the export."""
    from pulsar_timing_gibbsspec_tpu.obs import trace

    monkeypatch.setattr(trace, "MAX_EVENTS", 5)
    trace.enable()                      # ring is sized at enable()
    try:
        for i in range(12):
            trace.instant(f"e{i}")
        evs = trace.events()
        assert len(evs) == 5
        assert [e["name"] for e in evs] == [f"e{i}" for i in range(7, 12)]
        assert trace.dropped() == 7
        doc = trace.to_chrome()
        assert any(e["name"] == "trace.ring_dropped"
                   and e["args"]["dropped"] == 7
                   for e in doc["traceEvents"])
    finally:
        trace.disable()


def test_trace_jsonl_sink_flushes_on_disable(tmp_path):
    from pulsar_timing_gibbsspec_tpu.obs import trace

    path = tmp_path / "t.jsonl"
    trace.enable(trace.jsonl_sink(path))
    with trace.span("work", k=1):
        pass
    trace.instant("mark")
    trace.disable()                     # flush + close the sink handle
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [ev["name"] for ev in lines] == ["work", "mark"]
    # supervisor metrics.jsonl record shape: args splatted inline
    assert lines[0]["event"] == "trace_span" and lines[0]["k"] == 1
    assert lines[0]["ms"] >= 0.0
    assert lines[1]["event"] == "trace_instant"


def test_trace_observer_activates_seams_while_disabled():
    """An installed observer receives every finished event live even
    with buffering off — and removal restores the shared nullcontext."""
    from pulsar_timing_gibbsspec_tpu.obs import trace

    trace.disable()
    before = trace.events()             # buffer kept from prior enables
    seen = []
    trace.add_observer(seen.append)
    try:
        with trace.span("chunk.dispatch"):
            pass
        trace.instant("ping")
    finally:
        trace.remove_observer(seen.append)
    assert [e["name"] for e in seen] == ["chunk.dispatch", "ping"]
    assert trace.events() == before     # nothing buffered while off
    assert trace.span("a") is trace.span("b")   # nullcontext restored


# ---------------------------------------------------------------------------
# telemetry labels + metrics exposition


def test_telemetry_labels_and_scoped_reset():
    telemetry.reset("tobs_")
    telemetry.incr("tobs_hits", job="a")
    telemetry.incr("tobs_hits", 2, job="b")
    telemetry.incr("tobs_hits")
    telemetry.gauge("tobs_speed", 1.5, job="a")
    assert telemetry.get("tobs_hits", job="b") == 2
    assert telemetry.get("tobs_hits") == 1
    snap = telemetry.snapshot("tobs_")
    assert snap == {"tobs_hits": 1, 'tobs_hits{job="a"}': 1,
                    'tobs_hits{job="b"}': 2}
    assert telemetry.get_gauge("tobs_speed", job="a") == 1.5
    # scoped reset clears ONLY this namespace, labels included
    telemetry.incr("other_counter_tobs_test")
    telemetry.reset("tobs_")
    assert telemetry.snapshot("tobs_") == {}
    assert telemetry.get("other_counter_tobs_test") == 1
    telemetry.reset("other_counter_tobs_test")


def test_prometheus_render_format():
    body = metrics.render(
        counts={"hits": 3, 'hits{job="a b"}': 1},
        gauges={"speed": 1.5, 'depth{q="x\\"y"}': 2.0},
        prefix="t")
    lines = body.splitlines()
    assert "# TYPE t_hits_total counter" in lines
    assert "t_hits_total 3" in lines
    assert 't_hits_total{job="a b"} 1' in lines
    assert "# TYPE t_speed gauge" in lines
    assert "t_speed 1.5" in lines
    assert body.endswith("\n")
    # family header appears once even with several labeled series
    assert sum(1 for ln in lines
               if ln == "# TYPE t_hits_total counter") == 1


def test_prometheus_sanitize_and_split_key():
    assert metrics.sanitize("a-b.c") == "a_b_c"
    assert metrics.sanitize("9lives")[0] == "_"
    name, labels = metrics.split_key('m{a="1",b="x"}')
    assert name == "m" and labels == {"a": "1", "b": "x"}
    assert metrics.split_key("plain") == ("plain", {})


def test_render_telemetry_roundtrip():
    telemetry.reset("tobs2_")
    telemetry.gauge("tobs2_ess", 12.5, job="j1")
    body = metrics.render_telemetry()
    assert 'ptgibbs_tobs2_ess{job="j1"} 12.5' in body
    telemetry.reset("tobs2_")


def test_prometheus_hostile_label_values_roundtrip():
    """satellite (PR 17): tenant/job names now arrive over the network.
    A hostile label value — newlines, carriage returns, quotes,
    backslashes, UTF-8 — must neither split a sample line (forging
    metrics for a scraper) nor lose information: every sample stays on
    ONE line and ``split_key``/``_unescape`` recover the exact value."""
    hostiles = [
        'evil" 1\nforged_metric 999',          # line-splitting attempt
        "cr\rlf\n",                            # bare CR must escape too
        "back\\slash\\",                       # trailing backslash
        'quo"te"',
        "unicodé-页-🙂",
        "plain",
    ]
    telemetry.reset("tobs3_")
    for i, name in enumerate(hostiles):
        telemetry.gauge("tobs3_g", float(i), tenant=name)
    body = metrics.render_telemetry()
    telemetry.reset("tobs3_")

    # 1) no sample line was split: every non-comment line is exactly
    #    `name{labels} value`, and no forged family appears
    sample_lines = [ln for ln in body.splitlines()
                    if ln.startswith("ptgibbs_tobs3_g")]
    assert len(sample_lines) == len(hostiles)
    assert "forged_metric" not in {ln.split("{")[0].split(" ")[0]
                                   for ln in body.splitlines() if ln}
    # 2) lossless: parse each line back and recover the exact value
    got = {}
    for ln in sample_lines:
        key, val = ln.rsplit(" ", 1)
        _name, labels = metrics.split_key(key[len("ptgibbs_"):])
        got[labels["tenant"]] = float(val)
    assert got == {name: float(i) for i, name in enumerate(hostiles)}


def test_prometheus_escape_unescape_roundtrip_exhaustive():
    for s in ("", "\n", "\r", "\\", '"', "\\n", "a\\\nb", 'x"\r\\"',
              "\\\\\n\r"):
        assert metrics._unescape(metrics._escape(s)) == s
