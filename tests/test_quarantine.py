"""Blast-radius isolation for the serving tier.

The operational guarantee under test: tenant rows of the multiplexed
sweep are mathematically independent conditional chains, so ONE bad
tenant (poisoned upload, diverging chain, hot-looping failure) must
never perturb a co-resident's bits — and the service must degrade that
tenant gracefully (quarantine → capped replay budget → parked with an
operator marker) instead of failing the group.

Layers, cheapest first:

- ``chaos_quick`` unit tests: the per-row health vector
  (``runtime.sentinels.chunk_health``), the circuit-breaker state
  machine and admission controller (``runtime.supervisor``), the
  watchdog EMA geometry reset, and the per-tenant fault targeting
  (``runtime.faults``) — all sub-second, no compiled sampler.
- integration drills on tiny synthetic datasets: the 4-tenant poison
  drill (quarantine within ≤ 1 chunk, co-residents bitwise vs solo),
  budget exhaustion → terminal park + ``load_resume`` refusal without
  ``force_requeue``, breaker-gated re-admission, compile-storm
  deferral, and device-loss evacuation.

The randomized version of these drills is ``tools/chaos_campaign.py``.
"""

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.serve.buckets import BucketSpec, BucketTable

NITER = 12


def _mk(ntoa, seed, nmodes=3):
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    return build_model(synthetic_pulsars(2, ntoa, tm_cols=3, seed=seed),
                       nmodes)


_CACHE = None


def _service(root, table, **kw):
    """Fresh service sharing the module-wide program cache so the suite
    compiles each (bucket, slots) program once, not per test."""
    global _CACHE
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache, SamplerService

    if _CACHE is None:
        _CACHE = ProgramCache()
    kw.setdefault("cache", _CACHE)
    kw.setdefault("slots", 4)
    kw.setdefault("chunk", 4)
    kw.setdefault("quantum", 100)
    return SamplerService(root, table, **kw)


@pytest.fixture(scope="module")
def ptas4():
    """Four heterogeneous datasets (different TOA counts and noise
    realizations) with identical structure -> one bucket."""
    return [_mk(24, 0), _mk(28, 1), _mk(32, 2), _mk(36, 3)]


@pytest.fixture(scope="module")
def table():
    return BucketTable([BucketSpec(2, 40, 24, 3)])


@pytest.fixture(scope="module")
def solo_chains(ptas4, table, tmp_path_factory):
    """Uninterrupted single-tenant baselines (same 4-slot geometry the
    drills use — slot width never changes a tenant's stream, but solo
    services here keep the program cache to one compiled mux)."""
    base = tmp_path_factory.mktemp("quar_solo")
    out = []
    for i, pta in enumerate(ptas4):
        svc = _service(base / f"s{i}", table)
        job = svc.submit(pta, NITER, job_id=f"job{i}", tenant_id=i)
        svc.run()
        assert job.state == "done"
        out.append((job.chain.copy(), job.bchain.copy()))
    return out


# -- chaos_quick unit layer ------------------------------------------------

@pytest.mark.chaos_quick
def test_chunk_health_per_row_vector():
    """finite / move_frac / rho_ok are PER ROW: one poisoned row never
    dirties a neighbor's verdict."""
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.runtime.sentinels import chunk_health

    xs = jnp.zeros((3, 4, 5)).at[:, :, 0].set(
        np.arange(12.0).reshape(3, 4))
    bs = jnp.ones((3, 4, 2, 6))
    xs = xs.at[1, 2, 3].set(jnp.nan)
    h = chunk_health(xs, bs)
    np.testing.assert_array_equal(
        np.asarray(h["finite"]), [True, True, False, True])
    assert np.asarray(h["move_frac"]).shape == (4,)
    np.testing.assert_array_equal(np.asarray(h["rho_ok"]), [True] * 4)

    # rho out of [lo, hi] flags ONLY the offending row; 1-d and per-row
    # 2-d index forms agree
    xs2 = jnp.full((3, 4, 5), -4.0).at[2, 1, 2].set(9.0)
    h1 = chunk_health(xs2, bs, np.array([2, 3]), -9.0, 0.0)
    np.testing.assert_array_equal(
        np.asarray(h1["rho_ok"]), [True, False, True, True])
    ix2 = np.tile(np.array([2, 3]), (4, 1))
    h2 = chunk_health(xs2, bs, ix2, -9.0, 0.0)
    np.testing.assert_array_equal(np.asarray(h2["rho_ok"]),
                                  np.asarray(h1["rho_ok"]))


@pytest.mark.chaos_quick
def test_sentinel_monitor_rho_breach_warns_not_raises():
    from pulsar_timing_gibbsspec_tpu.runtime.sentinels import SentinelMonitor

    mon = SentinelMonitor()
    ev = mon.observe({"finite": np.array([True, True]),
                      "move_frac": np.array([0.5, 0.5]),
                      "rho_ok": np.array([True, False])}, it=10)
    assert any(e["event"] == "rho_bound_breach" and e["chains"] == [1]
               for e in ev)
    assert mon.last["rho_ok_frac"] == 0.5


@pytest.mark.chaos_quick
def test_circuit_breaker_state_machine():
    from pulsar_timing_gibbsspec_tpu.runtime.supervisor import (
        CircuitBreaker, CircuitOpen)

    t = {"now": 0.0}
    br = CircuitBreaker(window=4, threshold=0.5, min_events=2,
                        cooldown_s=10.0, clock=lambda: t["now"])
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"          # min_events not reached
    br.record_failure()
    assert br.state == "open" and br.opens == 1
    assert not br.allow() and not br.would_allow()
    with pytest.raises(CircuitOpen, match="circuit open"):
        br.check("tenant 7")
    t["now"] = 10.0                       # cooldown elapsed: half-open
    assert br.would_allow()
    assert br.allow()                     # claims the single probe
    assert br.state == "half_open" and not br.allow()
    br.record_failure()                   # probe failed: re-open
    assert br.state == "open" and br.opens == 2
    t["now"] = 20.0
    assert br.allow()
    br.record_success()                   # probe cleared: closed, reset
    assert br.state == "closed" and br.allow()
    assert br.snapshot()["failure_rate"] == 0.0


@pytest.mark.chaos_quick
def test_breaker_half_open_single_probe_under_concurrency():
    """Regression (PR 17): the half-open probe slot is claimed
    atomically.  Before the breaker grew its instance lock, concurrent
    ``allow()`` callers could interleave between reading ``_probing``
    and setting it — several callers would each 'win' the single probe
    and hammer a tenant the breaker had just tripped.  Gateway handler
    threads make this a real interleaving, not a theoretical one: N
    threads race ``allow()`` (with ``would_allow`` queries mixed in,
    which must never consume the slot) and exactly one may claim."""
    import threading

    from pulsar_timing_gibbsspec_tpu.runtime.supervisor import CircuitBreaker

    for _ in range(20):                   # many rounds to shake the race
        br = CircuitBreaker(window=4, threshold=0.5, min_events=2,
                            cooldown_s=0.0)
        br.record_failure()
        br.record_failure()
        assert br.state == "open"         # cooldown 0: probe eligible now
        n = 8
        barrier = threading.Barrier(n)
        wins = []

        def racer():
            barrier.wait()
            for _ in range(25):
                br.would_allow()          # queries must not claim
            wins.append(br.allow())

        threads = [threading.Thread(target=racer) for _ in range(n)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert sum(wins) == 1, \
            f"{sum(wins)} callers claimed the single half-open probe"
        assert br.state == "half_open"
        br.record_success()
        assert br.state == "closed"


@pytest.mark.chaos_quick
def test_admission_controller_backpressure_and_storm():
    from pulsar_timing_gibbsspec_tpu.runtime.supervisor import (
        AdmissionController, CircuitOpen)

    t = {"now": 0.0}
    ac = AdmissionController(max_queue=2, storm_compiles=2,
                             storm_window_s=5.0, clock=lambda: t["now"])
    ac.admit_submission(1)                # below the cap: fine
    with pytest.raises(CircuitOpen, match="backpressure"):
        ac.admit_submission(2)
    assert ac.rejections == 1
    assert not ac.storming()
    ac.note_compile()
    ac.note_compile()
    assert ac.storming()
    assert ac.defer_cold(False)           # cold shape held in the storm
    assert not ac.defer_cold(True)        # warm shapes always admit
    t["now"] = 6.0                        # window drained
    assert not ac.storming() and not ac.defer_cold(False)
    assert ac.snapshot()["deferrals"] == 1


@pytest.mark.chaos_quick
def test_watchdog_ema_resets_on_geometry_change():
    """A megachunk change across a resume must not seed the deadline
    from the old geometry's per-sweep EMA."""
    from pulsar_timing_gibbsspec_tpu.runtime.watchdog import DispatchWatchdog

    wd = DispatchWatchdog(k=4.0, floor_s=0.0, first_floor_s=1800.0)
    wd.observe(1.0, n=4)
    assert wd.ema == pytest.approx(0.25)
    wd.observe(1.0, n=4)                  # same geometry: EMA smooths
    assert wd.ema == pytest.approx(0.25)
    wd.observe(4.0, n=8)                  # geometry changed: fresh seed
    assert wd.ema == pytest.approx(0.5)
    # the guarded-call path resets too — the first post-change call
    # must fall back to first_floor_s, not 4*ema*n of the old geometry
    wd.observe(1.0, n=8)
    assert wd.call(lambda: 41 + 1, what="t", n=2) == 42
    assert wd.ema is None                 # reset; next observe re-seeds
    assert wd.deadline(2) == pytest.approx(1800.0)


@pytest.mark.chaos_quick
def test_tenant_targeted_evict_counts_victim_chunks():
    """satellite fix: ``at_row`` on a tenant-targeted evict counts the
    VICTIM's resident chunks, not the global chunk counter."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    faults.clear()
    faults.inject("tenant_evict", point="serve.chunk", tenant=2, at_row=3)
    try:
        # global chunk way past 3, victim held only 2 chunks: no fire
        assert faults.tenant_evict_request(
            row=99, job_rows={1: 99, 2: 2}) is False
        got = faults.tenant_evict_request(row=100, job_rows={1: 99, 2: 3})
        assert got == {2}
        # consumed: fires once
        assert faults.tenant_evict_request(
            row=101, job_rows={2: 9}) is False
        # untargeted faults keep the historical global-row semantics
        faults.inject("tenant_evict", point="serve.chunk", at_row=5)
        assert faults.tenant_evict_request(row=4, job_rows={}) is False
        assert faults.tenant_evict_request(row=5, job_rows={}) is True
    finally:
        faults.clear()


@pytest.mark.chaos_quick
def test_poison_tenant_rows_targets_one_row():
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    faults.clear()
    faults.inject("poison_rows", tenant=7, at_row=1)
    try:
        xs = np.zeros((2, 3, 4))
        bs = np.zeros((2, 3, 5))
        # victim not resident: nothing fires
        _, _, hit = faults.poison_tenant_rows(xs, bs, {1: 0}, {1: 5})
        assert hit == set()
        # resident but too early on ITS clock
        _, _, hit = faults.poison_tenant_rows(
            xs, bs, {7: 2, 1: 0}, {7: 0, 1: 9})
        assert hit == set() and np.isfinite(xs).all()
        # read-only inputs (np.asarray of a device array) are copied
        xs.flags.writeable = False
        xs2, bs2, hit = faults.poison_tenant_rows(
            xs, bs, {7: 2, 1: 0}, {7: 1, 1: 9})
        assert hit == {2}
        assert np.isnan(xs2[:, 2]).all() and np.isnan(bs2[:, 2]).all()
        assert np.isfinite(xs2[:, [0, 1]]).all()  # neighbors untouched
        assert np.isfinite(np.asarray(xs)).all()  # original view intact
    finally:
        faults.clear()


@pytest.mark.chaos_quick
def test_load_resume_refuses_quarantined_dir(tmp_path):
    """satellite: the quarantine marker in the manifest gates resume
    behind ``force_requeue`` — and the forced load is bitwise."""
    from pulsar_timing_gibbsspec_tpu.runtime import integrity
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore

    rows = np.arange(8.0).reshape(4, 2)
    brows = np.arange(4.0).reshape(4, 1)
    store = ChainStore(tmp_path / "jobQ", ["p0", "p1"], ["b0"])
    store.save(rows, brows, 4,
               adapt_state={"x": rows[-1], "b": brows[-1:],
                            "tenant_id": np.asarray(3, np.int64)},
               extra={"serve": {"job_id": "jobQ", "tenant_id": 3,
                                "state": "quarantined"}})
    with pytest.raises(integrity.CheckpointError, match="force.requeue"):
        integrity.load_resume(tmp_path / "jobQ")
    chain, bchain, upto, adapt = integrity.load_resume(
        tmp_path / "jobQ", force_requeue=True)
    assert upto == 4
    np.testing.assert_array_equal(chain[:4], rows)
    np.testing.assert_array_equal(bchain[:4], brows)
    assert int(adapt["tenant_id"]) == 3


@pytest.mark.chaos_quick
def test_chainstore_facade_path_also_refuses_quarantined(tmp_path):
    """Regression: ``ChainStore.load_resume`` (the facade /
    ``reshard_restore`` path) used to skip the quarantine check
    entirely — a parked job could be silently resumed through the side
    door ``integrity.load_resume`` refused.  Both paths now route
    through ``integrity.check_not_quarantined``."""
    from pulsar_timing_gibbsspec_tpu.runtime import integrity
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore

    rows = np.arange(8.0).reshape(4, 2)
    brows = np.arange(4.0).reshape(4, 1)
    store = ChainStore(tmp_path / "jobF", ["p0", "p1"], ["b0"])
    store.save(rows, brows, 4,
               extra={"serve": {"job_id": "jobF", "tenant_id": 1,
                                "state": "quarantined"}})
    with pytest.raises(integrity.CheckpointError, match="force_requeue"):
        store.load_resume()
    chain, bchain, upto, _ = store.load_resume(force_requeue=True)
    assert upto == 4
    np.testing.assert_array_equal(chain[:4], rows)
    # a NON-quarantined serve marker stays loadable without force
    store.save(rows, brows, 4,
               extra={"serve": {"job_id": "jobF", "tenant_id": 1,
                                "state": "done"}})
    assert store.load_resume()[2] == 4


# -- integration drills ----------------------------------------------------

@pytest.mark.chaos
def test_poison_tenant_drill_blast_radius(ptas4, table, solo_chains,
                                          tmp_path):
    """THE acceptance drill: nan-poison one tenant of a 4-tenant
    multiplexed run.  The victim quarantines within <= 1 chunk of the
    fault, every co-resident's chain is bitwise identical to its solo
    baseline, the victim itself completes bitwise after its verified-
    checkpoint replay, and the steady phase stays retrace-free."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    faults.clear()
    # victim = tenant 2, poisoned on the chunk where it has 2 resident
    # chunks behind it (global chunk 3 here: everyone admits at chunk 1)
    faults.inject("poison_rows", tenant=2, at_row=2, times=1)
    svc = _service(tmp_path / "drill", table, save_every=1)
    try:
        with recompile_counter() as rc:
            rc.phase("steady")
            jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
                    for i, p in enumerate(ptas4)]
            report = svc.run()
    finally:
        faults.clear()
    assert rc.unplanned("steady") == 0
    assert report["quarantines"] == 1
    (ev,) = report["quarantine_log"]
    assert ev["tenant_id"] == 2 and ev["count"] == 1
    # the fault fired at global chunk 3; quarantine landed on the SAME
    # chunk's writeback — latency 0, comfortably <= 1 chunk
    assert ev["chunk"] == 3
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])
        np.testing.assert_array_equal(job.bchain, solo_chains[i][1])
    assert jobs[2].quarantines == 1


@pytest.mark.chaos
def test_quarantine_budget_exhaustion_parks_terminally(
        ptas4, table, solo_chains, tmp_path):
    """A deterministically re-breaching tenant exhausts its quarantine
    budget and PARKS: terminal state ``quarantined``, marker in the
    manifest, resume gated behind force_requeue — co-residents
    unharmed."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults, integrity

    faults.clear()
    faults.inject("poison_rows", tenant=1, at_row=1, times=10)
    svc = _service(tmp_path / "park", table, save_every=1,
                   quarantine_max=1)
    try:
        jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
                for i, p in enumerate(ptas4[:2])]
        report = svc.run()
    finally:
        faults.clear()
    assert jobs[0].state == "done"
    np.testing.assert_array_equal(jobs[0].chain, solo_chains[0][0])
    assert jobs[1].state == "quarantined"
    assert "budget exhausted" in jobs[1].failure
    assert report["quarantines"] == 2
    # the parked directory refuses a blind resume, loads when forced,
    # and the forced rows are the victim's own verified (clean) prefix
    with pytest.raises(integrity.CheckpointError, match="force.requeue"):
        integrity.load_resume(tmp_path / "park" / "job1")
    chain, _, upto, _ = integrity.load_resume(
        tmp_path / "park" / "job1", force_requeue=True)
    assert upto == jobs[1].it > 0
    np.testing.assert_array_equal(chain[:upto], solo_chains[1][0][:upto])


@pytest.mark.chaos
def test_breaker_gates_readmission_and_submit(ptas4, table, solo_chains,
                                              tmp_path):
    """With per-tenant breakers on, a quarantined tenant waits out the
    cooldown (half-open probe readmits it) and a tenant with an open
    breaker is rejected at submit with the typed CircuitOpen."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults
    from pulsar_timing_gibbsspec_tpu.runtime.supervisor import CircuitOpen

    faults.clear()
    faults.inject("poison_rows", tenant=1, at_row=1, times=1)
    svc = _service(tmp_path / "brk", table, save_every=1,
                   breaker={"window": 4, "threshold": 1.0,
                            "min_events": 1, "cooldown_s": 0.05})
    try:
        jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
                for i, p in enumerate(ptas4[:2])]
        report = svc.run()
    finally:
        faults.clear()
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])
    br = report["breakers"][1]
    assert br["opens"] == 1 and br["state"] == "closed"

    # an open breaker rejects the tenant's NEXT submission, typed
    svc2 = _service(tmp_path / "brk2", table,
                    breaker={"window": 4, "threshold": 1.0,
                             "min_events": 1, "cooldown_s": 60.0})
    svc2._tenant_breaker(9, create=True).record_failure()
    with pytest.raises(CircuitOpen, match="tenant 9"):
        svc2.submit(ptas4[0], 4, tenant_id=9)


@pytest.mark.chaos
def test_breaker_probe_survives_group_mismatch(ptas4, solo_chains,
                                               tmp_path):
    """Regression (chaos campaign seed 24): while a tenant from ANOTHER
    bucket holds the active group, the quarantined tenant's breaker
    cooldown elapses — the admission scan must gate on the
    non-consuming ``would_allow`` so the half-open probe is only
    claimed when the job is actually admitted.  Consuming it on a
    group-key mismatch strands the breaker half-open (no outcome ever
    recorded against the probe) and starves the tenant forever."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache

    two = BucketTable([BucketSpec(2, 40, 24, 3), BucketSpec(2, 48, 24, 3)])
    tick = {"n": 0}

    def clock():
        tick["n"] += 1
        return 0.01 * tick["n"]

    faults.clear()
    faults.inject("poison_rows", tenant=0, at_row=1, times=1)
    svc = _service(tmp_path / "probe", two, cache=ProgramCache(),
                   save_every=1, clock=clock,
                   breaker={"window": 4, "threshold": 1.0,
                            "min_events": 1, "cooldown_s": 0.05})
    try:
        ja = svc.submit(ptas4[0], NITER, job_id="victim", tenant_id=0)
        # long enough (7 chunks) that the cooldown elapses while this
        # other-bucket tenant still holds the active group
        jb = svc.submit(_mk(44, 9), 28, job_id="other", tenant_id=1)
        # bounded step loop instead of run(): the pre-fix failure mode
        # is an infinite deferral, which must fail the test, not hang it
        for _ in range(200):
            if not svc.step() and not svc.queue:
                break
    finally:
        faults.clear()
    assert ja.state == "done" and jb.state == "done"
    np.testing.assert_array_equal(ja.chain, solo_chains[0][0])
    br = svc.report()["breakers"][0]
    assert br["opens"] == 1 and br["state"] == "closed"


@pytest.mark.chaos
def test_admission_storm_defers_cold_shapes(ptas4, tmp_path):
    """During a compile storm, new dataset shapes (cold buckets) are
    deferred so they cannot serialize warm tenants behind back-to-back
    compiles — and they admit once the storm window drains."""
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache

    two = BucketTable([BucketSpec(2, 40, 24, 3), BucketSpec(2, 48, 24, 3)])
    # counting clock: deterministic regardless of compile wall time —
    # the storm window "drains" after a fixed number of reads, so the
    # cold shape is deferred on the early scheduling rounds and admits
    # on a later one (never starved)
    tick = {"n": 0}

    def clock():
        tick["n"] += 1
        return 0.01 * tick["n"]

    svc = _service(tmp_path / "storm", two, cache=ProgramCache(),
                   clock=clock,
                   admission={"max_queue": 8, "storm_compiles": 1,
                              "storm_window_s": 0.5})
    ja = svc.submit(ptas4[0], NITER, job_id="warmish", tenant_id=0)
    jb = svc.submit(_mk(44, 9), NITER, job_id="coldshape", tenant_id=1)
    report = svc.run()
    assert ja.state == "done" and jb.state == "done"
    assert report["admission"]["deferrals"] >= 1


@pytest.mark.chaos
def test_admission_backpressure_rejects_submit(ptas4, table, tmp_path):
    from pulsar_timing_gibbsspec_tpu.runtime.supervisor import CircuitOpen

    svc = _service(tmp_path / "bp", table, admission={"max_queue": 2})
    svc.submit(ptas4[0], 4, tenant_id=0)
    svc.submit(ptas4[1], 4, tenant_id=1)
    with pytest.raises(CircuitOpen, match="backpressure"):
        svc.submit(ptas4[2], 4, tenant_id=2)


@pytest.mark.chaos
def test_device_loss_evacuation(ptas4, table, solo_chains, tmp_path):
    """Device loss mid-multiplex: residents drain through their own
    verified checkpoints, programs rebuild on the survivors, jobs
    re-admit and finish bitwise."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache

    faults.clear()
    faults.inject("device_loss", point="serve.chunk", at_row=2, times=1,
                  devices=1)
    # own cache: evacuation replaces it, the module cache must survive
    svc = _service(tmp_path / "evac", table, cache=ProgramCache(),
                   save_every=1)
    try:
        jobs = [svc.submit(p, NITER, job_id=f"job{i}", tenant_id=i)
                for i, p in enumerate(ptas4[:2])]
        report = svc.run()
    finally:
        faults.clear()
    assert report["evacuations"] == 1
    assert svc.mesh is None
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])
        np.testing.assert_array_equal(job.bchain, solo_chains[i][1])
