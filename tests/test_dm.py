"""DM-variation GP (chromatic nu^-2 Fourier process).

The reference's ``model_general`` accepts ``dm_var`` and builds the block
via enterprise's dm-noise machinery (``model_definition.py:19-31``); round
1 rejected the kwarg.  These tests pin the chromatic basis scaling, the
generic hyper conditional that samples the DM hypers alongside the red/
common block, and jax-vs-numpy statistical equivalence.
"""

import numpy as np
import pytest
from scipy import stats

from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs


@pytest.fixture(scope="module")
def dm_psr(j1713):
    """J1713 with artificial dual-band radio frequencies so the chromatic
    basis is distinguishable from the achromatic one."""
    import dataclasses

    rng = np.random.default_rng(0)
    freqs = np.where(rng.uniform(size=j1713.ntoa) < 0.5, 800.0, 1400.0)
    return dataclasses.replace(j1713, freqs=freqs)


def test_dm_basis_chromatic_scaling(dm_psr):
    pta = model_general([dm_psr], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, dm_var=True, dm_components=5)
    assert any("dm_gp" in n for n in pta.param_names)
    m = pta.model(0)
    dm_sig = next(s for s in m.signals if "dm_gp" in s.name)
    gw_sig = next(s for s in m.signals if "gw" in s.name)
    F_dm, F_gw = dm_sig.get_basis(), gw_sig.get_basis()
    scale = (1400.0 / dm_psr.freqs) ** 2
    np.testing.assert_allclose(F_dm, F_gw[:, :F_dm.shape[1]]
                               * scale[:, None], rtol=1e-12)
    # own columns, not shared with the Fourier block
    assert m._slices[dm_sig.name].start >= m._slices[gw_sig.name].stop


def test_dm_hypers_join_mh_block_and_compile(dm_psr):
    pta = model_general([dm_psr], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, dm_var=True, dm_components=5)
    idx = BlockIndex.build(pta.param_names)
    dm_cols = [k for k, n in enumerate(pta.param_names) if "dm_gp" in n]
    assert set(dm_cols) <= set(idx.red.tolist())
    cm = compile_pta(pta)
    # the compiled phi carries the DM contribution on its own columns
    x = pta.initial_sample(np.random.default_rng(1))
    ph = np.asarray(cm.phi(x))[0]
    ph_host = pta.get_phi(pta.map_params(x))[0]
    sel = ph_host < 1e20
    np.testing.assert_allclose(ph[:len(ph_host)][sel], ph_host[sel],
                               rtol=1e-4)
    # gp_mask covers exactly the Fourier + DM columns
    m = pta.model(0)
    gp_cols = np.zeros(len(ph_host))
    for s in m._fourier + m._chrom:
        sl = m._slices[s.name]
        gp_cols[sl] = 1.0
    np.testing.assert_array_equal(np.asarray(cm.gp_mask)[0][:len(ph_host)],
                                  gp_cols)


def test_dm_turnover_psd_builds_and_samples(dm_psr, tmp_path):
    """Chromatic GPs accept the full powerlaw-family PSD menu (reference
    dm_psd includes 'turnover'); extra shape hypers are fixed Constants."""
    pta = model_general([dm_psr], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, dm_var=True,
                        dm_psd="turnover", dm_components=5)
    assert any("dm_gp_log10_A" in n for n in pta.param_names)
    assert not any("lf0" in n for n in pta.param_names)   # Constant shape
    g = PulsarBlockGibbs(pta, backend="jax", seed=9, progress=False)
    c = g.sample(pta.initial_sample(np.random.default_rng(3)),
                 outdir=str(tmp_path / "t"), niter=80)
    assert np.all(np.isfinite(c))


def test_chrom_and_gequad_build_and_sample(dm_psr, tmp_path):
    """dm_chrom (nu^-4 scattering GP) and gequad (global EQUAD) reach the
    right blocks on both backends and produce matched finite chains."""
    pta = model_general([dm_psr], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5, dm_chrom=True,
                        dm_components=5, gequad=True)
    names = pta.param_names
    assert any("chrom_gp" in n for n in names)
    assert any("gequad" in n for n in names)
    m = pta.model(0)
    chrom_sig = next(s for s in m.signals if "chrom_gp" in s.name)
    gw_sig = next(s for s in m.signals if "gw" in s.name)
    scale = (1400.0 / dm_psr.freqs) ** 4
    np.testing.assert_allclose(
        chrom_sig.get_basis(),
        gw_sig.get_basis()[:, :chrom_sig.get_basis().shape[1]]
        * scale[:, None], rtol=1e-12)
    idx = BlockIndex.build(names)
    igeq = names.index("J1713+0747_log10_gequad")
    assert igeq in idx.white.tolist()       # gequad joins the white block
    # compiled ndiag includes the gequad term
    cm = compile_pta(pta)
    x = pta.initial_sample(np.random.default_rng(2))
    nd = np.asarray(cm.ndiag(x))[0]
    nd_host = pta.get_ndiag(pta.map_params(x))[0]
    np.testing.assert_allclose(nd[:len(nd_host)], nd_host, rtol=1e-5)
    # short end-to-end on both backends: finite, gequad chain moves
    for backend, seed in [("jax", 41), ("numpy", 42)]:
        g = PulsarBlockGibbs(pta, backend=backend, seed=seed, progress=False,
                             white_adapt_iters=150)
        c = g.sample(pta.initial_sample(np.random.default_rng(3)),
                     outdir=str(tmp_path / backend), niter=150)
        assert np.all(np.isfinite(c))
        assert np.std(c[30:, igeq]) > 1e-3


def test_dm_annual_marginalized(dm_psr, tmp_path):
    """dm_annual adds two nu^-2 sin/cos columns at 1/yr, marginalized
    like timing columns (no new sampled parameters)."""
    pta = model_general([dm_psr], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, dm_annual=True)
    base = model_general([dm_psr], tm_svd=True, red_var=False,
                         white_vary=False, common_psd="spectrum",
                         common_components=5)
    assert pta.param_names == base.param_names      # no new parameters
    m = pta.model(0)
    ann = next(s for s in m.signals if s.name == "dm_annual")
    T = ann.get_basis()
    assert T.shape == (dm_psr.ntoa, 2)
    w = 2 * np.pi / (365.25 * 86400.0)
    scale = (1400.0 / dm_psr.freqs) ** 2
    np.testing.assert_allclose(T[:, 0], np.sin(w * dm_psr.toas) * scale,
                               rtol=1e-12)
    # marginalized: infinite prior variance, counted in the basis width
    assert pta.get_phi(pta.map_params(pta.initial_sample(
        np.random.default_rng(0))))[0].shape[0] == \
        base.get_basis()[0].shape[1] + 2
    g = PulsarBlockGibbs(pta, backend="jax", seed=51, progress=False,
                         white_adapt_iters=100)
    c = g.sample(pta.initial_sample(np.random.default_rng(3)),
                 outdir=str(tmp_path / "ann"), niter=120)
    assert np.all(np.isfinite(c))


def test_hyper_conditional_matches_oracle_unequal_modes(j1713):
    """The red-hyper conditional must agree between backends even when
    red_components > common_components: the red-only tail frequencies
    carry N(0, irn) terms both targets must include (regression: the
    oracle used to truncate to the GW grid)."""
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_backend import NumpyGibbs

    pta = model_general([j1713], tm_svd=True, red_var=True,
                        red_psd="powerlaw", white_vary=False,
                        common_psd="spectrum", common_components=6)
    g = NumpyGibbs(pta, seed=0)
    rng = np.random.default_rng(8)
    x = pta.initial_sample(rng)
    g.draw_b(x)
    cm = compile_pta(pta)
    b = np.zeros((cm.P, cm.Bmax))
    b[0, :len(g.b)] = g.b
    b = jnp.asarray(b, cm.cdtype)
    idx = BlockIndex.build(pta.param_names)
    # MH acceptance differences of the two targets must agree
    q = np.array(x)
    q[idx.red[0]] += 0.3
    q[idx.red[1]] -= 0.4
    d_np = g.lnlike_red(q) - g.lnlike_red(x)
    d_jx = float(jb.lnlike_hyper_fn(cm, jnp.asarray(q, cm.cdtype), b)
                 - jb.lnlike_hyper_fn(cm, jnp.asarray(x, cm.cdtype), b))
    assert abs(d_jx - d_np) < 1e-6 * max(1.0, abs(d_np)), (d_jx, d_np)


def test_dm_jax_vs_numpy_ks(dm_psr, tmp_path):
    pta = model_general([dm_psr], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, dm_var=True, dm_components=5)
    x0 = pta.initial_sample(np.random.default_rng(23))
    chains = {}
    for backend, seed in [("jax", 31), ("numpy", 32)]:
        g = PulsarBlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=1500)
    burn, thin = 300, 5
    dm_cols = [k for k, n in enumerate(pta.param_names) if "dm_gp" in n]
    idx = BlockIndex.build(pta.param_names)
    # rho bins mix in O(1) sweeps: KS directly
    pvals = [stats.ks_2samp(chains["jax"][burn::thin, k],
                            chains["numpy"][burn::thin, k]).pvalue
             for k in idx.rho[:3]]
    assert min(pvals) > 1e-4, pvals
    # the unconstrained DM hypers mix slowly under the 20-step MH block
    # (ACT 30-120 here), so compare them with an ESS-aware z-test
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    for k in dm_cols:
        cj = chains["jax"][burn:, k]
        cn = chains["numpy"][burn:, k]
        assert np.std(cj) > 1e-3     # the block must actually move
        ess_j = len(cj) / max(integrated_act(cj), 1.0)
        ess_n = len(cn) / max(integrated_act(cn), 1.0)
        z = abs(cj.mean() - cn.mean()) / np.sqrt(
            cj.var() / ess_j + cn.var() / ess_n)
        assert z < 4.0, (pta.param_names[k], z)
