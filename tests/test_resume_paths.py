"""Resume-contract coverage the seed lacked: the record_every > 1
thinned-resume roundtrip and the nchains shape-mismatch refusal
(facade resume block + driver adapt-state check)."""

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

KW = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
          chunk_size=4)


@pytest.fixture(scope="module")
def x0(synth_pta):
    return synth_pta.initial_sample(np.random.default_rng(0))


def test_thinned_resume_roundtrip_bitwise(synth_pta, x0, tmp_path):
    """record_every=2: the thinned record's resume must reproduce the
    uninterrupted run exactly — recorded iterations are anchored to the
    absolute index (≡ it_base mod k), not the chunk/checkpoint grid."""
    niter = 20
    full_dir, split_dir = tmp_path / "full", tmp_path / "split"
    full = PTABlockGibbs(synth_pta, record_every=2, **KW).sample(
        x0, outdir=full_dir, niter=niter, save_every=8)
    PTABlockGibbs(synth_pta, record_every=2, **KW).sample(
        x0, outdir=split_dir, niter=12, save_every=8)
    resumed = PTABlockGibbs(synth_pta, record_every=2, **KW).sample(
        x0, outdir=split_dir, niter=niter, resume=True, save_every=8)
    assert resumed.shape == full.shape
    assert resumed.shape[0] < niter           # actually thinned
    assert np.array_equal(resumed, full)
    assert np.array_equal(np.load(split_dir / "chain.npy"),
                          np.load(full_dir / "chain.npy"))


def test_hd_joint_resume_roundtrip_bitwise(synth_hd_pta, tmp_path):
    """Correlated-ORF (HD) chunked sweep with the structured joint b-draw
    and its hoisted per-sweep factor cache active: a split run + resume
    must reproduce the uninterrupted run bit-for-bit — the cache is a
    pure function of (x, iteration), so chunk boundaries cannot move the
    sampled process (the same contract the CRN path already keeps)."""
    x0 = synth_hd_pta.initial_sample(np.random.default_rng(0))
    niter = 20
    full_dir, split_dir = tmp_path / "full", tmp_path / "split"
    full = PTABlockGibbs(synth_hd_pta, **KW).sample(
        x0, outdir=full_dir, niter=niter, save_every=8)
    PTABlockGibbs(synth_hd_pta, **KW).sample(
        x0, outdir=split_dir, niter=12, save_every=8)
    resumed = PTABlockGibbs(synth_hd_pta, **KW).sample(
        x0, outdir=split_dir, niter=niter, resume=True, save_every=8)
    assert resumed.shape == full.shape
    assert np.isfinite(full).all()
    assert np.array_equal(resumed, full)
    assert np.array_equal(np.load(split_dir / "bchain.npy"),
                          np.load(full_dir / "bchain.npy"))


def test_resume_nchains_mismatch_raises(synth_pta, x0, tmp_path):
    """Chain files written with nchains=2 must refuse a resume with
    nchains=1 (and vice versa) instead of silently reshaping."""
    PTABlockGibbs(synth_pta, nchains=2, **KW).sample(
        x0, outdir=tmp_path, niter=10, save_every=5)
    with pytest.raises(RuntimeError, match="cannot resume"):
        PTABlockGibbs(synth_pta, **KW).sample(
            x0, outdir=tmp_path, niter=12, resume=True, save_every=5)


def test_driver_adapt_state_nchains_mismatch_raises(synth_pta):
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import \
        JaxGibbsDriver

    drv = JaxGibbsDriver(synth_pta, seed=3, common_rho=True,
                         warmup_sweeps=2, chunk_size=4, nchains=1)
    donor = JaxGibbsDriver(synth_pta, seed=3, common_rho=True,
                           warmup_sweeps=2, chunk_size=4, nchains=2)
    niter = 10
    cshape, bshape = donor.chain_shapes(niter)
    chain, bchain = np.zeros(cshape), np.zeros(bshape)
    for _ in donor.run(x0_tiled(donor, synth_pta), chain, bchain, 0, niter):
        pass
    with pytest.raises(RuntimeError, match="nchains"):
        drv.load_adapt_state(donor.adapt_state())


def x0_tiled(drv, pta):
    return pta.initial_sample(np.random.default_rng(0))
