"""Structured correlated-ORF joint b-draw (ISSUE 3): the two-stage
batched-block + GW-Schur factorization must sample the SAME conditional
as the dense reference ``draw_b_joint`` — same key, same permuted
coordinate ordering, same Cholesky — and the compiled sweep must neither
retrace per sweep nor lose bitwise resume with the hoisted factor cache.
"""

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta


@pytest.fixture(scope="module")
def hd_cm_x(synth_hd_pta):
    import jax.numpy as jnp

    cm = compile_pta(synth_hd_pta)
    x0 = synth_hd_pta.initial_sample(np.random.default_rng(3))
    return cm, jnp.asarray(x0, cm.cdtype)


def _rel_diff(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.max(np.abs(a - b)) / max(1e-30, np.max(np.abs(a)))


def test_structured_matches_dense_same_key_f64(hd_cm_x):
    """Acceptance: the structured exact (f64) draw reproduces the dense
    ``draw_b_joint`` sample for the same key to 1e-8 — both factor the
    same permuted system, so Cholesky uniqueness makes the sample maps
    identical up to roundoff."""
    import jax.random as jr

    cm, x = hd_cm_x
    key = jr.PRNGKey(5)
    bd = jb.draw_b_joint(cm, x, key)
    bs = jb.draw_b_joint_structured(cm, x, key, exact=True)
    assert np.isfinite(np.asarray(bd)).all()
    assert _rel_diff(bd, bs) < 1e-8


def test_structured_matches_dense_block_grid_path(hd_cm_x, monkeypatch):
    """Same-key agreement with the per-(frequency, phase) block-grid
    Schur factorization forced (SCHUR_DENSE_MAX=0 disables the small-size
    dense flattening) — the layout the production widths take."""
    import jax.random as jr

    cm, x = hd_cm_x
    monkeypatch.setattr(jb, "SCHUR_DENSE_MAX", 0)
    key = jr.PRNGKey(6)
    bd = jb.draw_b_joint(cm, x, key)
    bs = jb.draw_b_joint_structured(cm, x, key, exact=True)
    assert _rel_diff(bd, bs) < 1e-8


def test_factor_cache_is_inert(hd_cm_x):
    """A draw through a precomputed joint_factor_cache must equal the
    self-factoring draw bit-for-bit — the sweep's hoisted cache cannot
    change the sampled process."""
    import jax.random as jr

    cm, x = hd_cm_x
    key = jr.PRNGKey(9)
    for exact in (True, False):
        f = jb.joint_factor_cache(cm, x, exact=exact)
        a = jb.draw_b_joint_structured(cm, x, key, exact=exact)
        b = jb.draw_b_joint_structured(cm, x, key, exact=exact, factors=f)
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_mixed_breakdown_keeps_previous_b(hd_cm_x):
    """The joint_mixed non-finite guard, pinned directly: when the
    two-float stage factors break down (any NaN in the candidate), the
    draw must return the PREVIOUS b untouched — skip the update, never
    poison the chain — and zeros when no previous b exists.  The finite
    path must still produce a fresh draw, not the carry."""
    import jax.numpy as jnp
    import jax.random as jr

    cm, x = hd_cm_x
    key = jr.PRNGKey(21)
    f = jb.joint_factor_cache(cm, x, exact=False, mixed=True)
    b_ok = jb.draw_b_joint_structured(cm, x, key, factors=f, mixed=True)
    assert np.isfinite(np.asarray(b_ok)).all()
    prev = jnp.full_like(b_ok, 0.5)
    got = jb.draw_b_joint_structured(cm, x, key, b=prev, factors=f,
                                     mixed=True)
    assert np.array_equal(np.asarray(got), np.asarray(b_ok))
    assert not np.array_equal(np.asarray(got), np.asarray(prev))
    # poison the stage-1 inverse factor: every candidate entry goes NaN
    f_bad = f._replace(Li1=f.Li1 * np.nan)
    kept = jb.draw_b_joint_structured(cm, x, key, b=prev, factors=f_bad,
                                      mixed=True)
    assert np.array_equal(np.asarray(kept), np.asarray(prev))
    # no previous b: the guard falls back to a zero update
    kept0 = jb.draw_b_joint_structured(cm, x, key, factors=f_bad,
                                       mixed=True)
    assert np.array_equal(np.asarray(kept0),
                          np.zeros_like(np.asarray(kept0)))


def test_mixed_draw_is_ks_level(hd_cm_x):
    """The two-float (f32 factor + one refinement step) steady draw
    carries the accepted O(n*eps_f32) error class: same-key samples land
    within ~1e-3 of the f64 draw pointwise, and batch moments over many
    keys agree — the KS-level statement at toy size."""
    import jax
    import jax.random as jr

    cm, x = hd_cm_x
    key = jr.PRNGKey(11)
    bd = np.asarray(jb.draw_b_joint_structured(cm, x, key, exact=True))
    bm = np.asarray(jb.draw_b_joint_structured(cm, x, key, exact=False,
                                               mixed=True))
    assert np.isfinite(bm).all()
    assert _rel_diff(bd, bm) < 1e-3

    keys = jr.split(jr.PRNGKey(12), 192)
    ex = np.asarray(jax.vmap(
        lambda k: jb.draw_b_joint_structured(cm, x, k, exact=True))(keys))
    mx = np.asarray(jax.vmap(
        lambda k: jb.draw_b_joint_structured(cm, x, k, exact=False,
                                             mixed=True))(keys))
    sd = ex.std(axis=0)
    live = sd > 0
    # same keys, so the mean difference is the deterministic kernel error
    # (O(1e-5) of scale), far inside the Monte-Carlo band
    dmean = np.abs(ex.mean(axis=0) - mx.mean(axis=0))[live]
    assert np.all(dmean < 0.05 * sd[live] + 1e-12)
    rstd = np.abs(mx.std(axis=0)[live] / sd[live] - 1.0)
    assert np.all(rstd < 0.05)


def test_dispatch_and_dense_cap_preserved(hd_cm_x, monkeypatch):
    """PTGIBBS_HD_KERNEL=pulsar|freq still routes past the joint kernel
    when the system exceeds HD_DENSE_MAX (the escape hatch contract), and
    the joint kernel is the default at every size."""
    import jax.random as jr

    cm, x = hd_cm_x
    assert jb._joint_kernel_active(cm)
    monkeypatch.setattr(jb, "HD_DENSE_MAX", 0)
    assert jb._joint_kernel_active(cm)          # "joint" ignores the cap
    monkeypatch.setattr(jb, "HD_SCALABLE_KERNEL", "pulsar")
    assert not jb._joint_kernel_active(cm)
    b = jb.draw_b_fn(cm, x, jr.PRNGKey(1), exact=True)
    assert np.isfinite(np.asarray(b)).all()


def test_no_retraces_across_steady_chunks(synth_hd_pta):
    """Tier-1 perf guard (ISSUE 3 satellite): the factor-cache hoist and
    the non-CRN body pair must not reintroduce per-sweep or per-chunk
    retracing — zero XLA compiles across the second and later steady
    chunks of the toy HD config."""
    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import \
        JaxGibbsDriver

    drv = JaxGibbsDriver(synth_hd_pta, seed=4, common_rho=True,
                         warmup_sweeps=2, chunk_size=4)
    x0 = synth_hd_pta.initial_sample(np.random.default_rng(0))
    niter = 14                      # warmup + >= 3 steady chunks
    cshape, bshape = drv.chain_shapes(niter)
    chain, bchain = np.zeros(cshape), np.zeros(bshape)
    it = drv.run(x0, chain, bchain, 0, niter)
    next(it)                        # warmup + adaptation + compiles
    with profiling.recompile_counter() as rc:
        first = True
        for _ in it:
            if first:
                # the first steady chunk compiles the sweep pair once
                rc.reset()
                first = False
    assert not rc.retraced, f"steady-loop retraces: {rc.events}"
    assert np.isfinite(chain).all()
