"""jaxlint analyzer: each rule fires on its fixture, each fixture is
silenced by its pragma, and the whole package carries zero violations
beyond the checked-in baseline (which can only ratchet down)."""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from pulsar_timing_gibbsspec_tpu.analysis import (
    analyze_paths, analyze_source, baseline_counts, load_baseline)

ROOT = Path(__file__).resolve().parents[1]
PKG = ROOT / "pulsar_timing_gibbsspec_tpu"


def rules_of(src):
    return [v.rule for v in analyze_source(textwrap.dedent(src))]


# ---------------------------------------------------------------------------
# R1: PRNG key reuse
# ---------------------------------------------------------------------------

def test_r1_fires_on_key_reuse():
    src = """
        import jax.random as jr
        def f(key):
            a = jr.normal(key)
            b = jr.uniform(key)
            return a + b
    """
    assert rules_of(src) == ["R1"]


def test_r1_suppressed_by_pragma():
    src = """
        import jax.random as jr
        def f(key):
            a = jr.normal(key)
            b = jr.uniform(key)  # jaxlint: disable=R1
            return a + b
    """
    assert rules_of(src) == []


def test_r1_clean_after_split_and_reassign():
    src = """
        import jax.random as jr
        def f(key):
            k1, k2 = jr.split(key)
            a = jr.normal(k1)
            key = jr.fold_in(key, 3)
            b = jr.uniform(key)
            return a + b + jr.normal(k2)
    """
    assert rules_of(src) == []


def test_r1_catches_reuse_across_loop_iterations():
    src = """
        import jax.random as jr
        def f(key, xs):
            out = 0.0
            for x in xs:
                out = out + jr.normal(key) * x
            return out
    """
    assert "R1" in rules_of(src)


def test_r1_exclusive_branches_do_not_fire():
    src = """
        import jax.random as jr
        def f(key, flag):
            if flag:
                return jr.normal(key)
            return jr.uniform(key)
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# R2: host NumPy inside traced code
# ---------------------------------------------------------------------------

def test_r2_fires_in_jitted_function():
    src = """
        import jax
        import numpy as np
        @jax.jit
        def f(x):
            return np.sin(x)
    """
    assert rules_of(src) == ["R2"]


def test_r2_fires_item_and_float():
    src = """
        import jax
        @jax.jit
        def f(x):
            return float(x) + x.item()
    """
    assert rules_of(src) == ["R2", "R2"]


def test_r2_suppressed_by_pragma():
    src = """
        import jax
        import numpy as np
        @jax.jit
        def f(x):
            return np.sin(x)  # jaxlint: disable=R2
    """
    assert rules_of(src) == []


def test_r2_constants_and_untraced_code_are_fine():
    src = """
        import numpy as np
        def host(x):
            return np.sin(x)          # not traced
        import jax
        @jax.jit
        def f(x):
            return x * np.float32(2.0 * np.pi)   # constant-folded
    """
    assert rules_of(src) == []


def test_r2_seen_through_wrapper_call_site_and_scan_body():
    src = """
        import jax
        import numpy as np
        def body(c, x):
            return c, np.log(x)
        def g(x):
            return np.abs(x)
        def run(xs):
            jax.lax.scan(body, 0.0, xs)
            return jax.jit(jax.vmap(g))(xs)
    """
    # the immediately-invoked jit wrapper is itself an R4
    assert sorted(rules_of(src)) == ["R2", "R2", "R4"]


def test_r2_transitive_same_module_call():
    src = """
        import jax
        import numpy as np
        def helper(x):
            return np.cumsum(x)
        @jax.jit
        def f(x):
            return helper(x)
    """
    assert rules_of(src) == ["R2"]


# ---------------------------------------------------------------------------
# R3: implicit dtype in device allocations
# ---------------------------------------------------------------------------

def test_r3_fires_without_dtype():
    src = """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return x + jnp.zeros(3) + jnp.asarray(x)
    """
    assert rules_of(src) == ["R3", "R3"]


def test_r3_suppressed_by_pragma():
    src = """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return x + jnp.zeros(3)  # jaxlint: disable=R3
    """
    assert rules_of(src) == []


def test_r3_explicit_dtype_positional_keyword_or_astype():
    src = """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            a = jnp.zeros(3, jnp.float32)
            b = jnp.ones(3, dtype=x.dtype)
            c = jnp.asarray(x).astype(jnp.float32)
            return a + b + c
    """
    assert rules_of(src) == []


def test_r3_arange_requires_explicit_dtype():
    # arange's result dtype flips int/float with its argument types —
    # the classic silent-precision leak the R3 extension closes
    src = """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return x + jnp.arange(3)
    """
    assert rules_of(src) == ["R3"]
    ok = """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            a = jnp.arange(3, dtype=jnp.int32)
            b = jnp.arange(0, 3, 1, jnp.float32)
            return x + a + b
    """
    assert rules_of(ok) == []


def test_r3_untraced_allocation_is_fine():
    src = """
        import jax.numpy as jnp
        def setup():
            return jnp.zeros(3)
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# R4: retrace hazards
# ---------------------------------------------------------------------------

def test_r4_fires_on_immediately_invoked_jit():
    src = """
        import jax
        def f(x):
            return jax.jit(lambda y: y + 1.0)(x)
    """
    assert rules_of(src) == ["R4"]


def test_r4_fires_on_scalar_into_jitted_callable():
    src = """
        import jax
        g = jax.jit(lambda x, n: x * n)
        def f(x):
            return g(x, 3)
    """
    assert rules_of(src) == ["R4"]


def test_r4_suppressed_by_pragma():
    src = """
        import jax
        def f(x):
            return jax.jit(lambda y: y + 1.0)(x)  # jaxlint: disable=R4
    """
    assert rules_of(src) == []


def test_r4_static_argnums_is_fine():
    src = """
        import jax
        g = jax.jit(lambda x, n: x * n, static_argnums=(1,))
        def f(x):
            return g(x, 3)
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# R5: tracer leaks via self-assignment
# ---------------------------------------------------------------------------

def test_r5_fires_on_self_assign_in_traced_body():
    src = """
        import jax
        class A:
            @jax.jit
            def f(self, x):
                self.cache = x
                return x
    """
    assert rules_of(src) == ["R5"]


def test_r5_suppressed_by_pragma():
    src = """
        import jax
        class A:
            @jax.jit
            def f(self, x):
                self.cache = x  # jaxlint: disable=R5
                return x
    """
    assert rules_of(src) == []


def test_r5_untraced_method_is_fine():
    src = """
        class A:
            def f(self, x):
                self.cache = x
                return x
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# R6: debug leftovers
# ---------------------------------------------------------------------------

def test_r6_fires_on_debug_print_and_breakpoint():
    src = """
        import jax
        def f(x):
            jax.debug.print("x={}", x)
            breakpoint()
            return x
    """
    assert rules_of(src) == ["R6", "R6"]


def test_r6_suppressed_by_pragma():
    src = """
        import jax
        def f(x):
            jax.debug.print("x={}", x)  # jaxlint: disable=R6
            return x
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# R7: host-sync leaks
# ---------------------------------------------------------------------------

def test_r7_fires_on_bool_and_int_of_traced_values():
    src = """
        import jax
        @jax.jit
        def f(x):
            if bool(x):
                return x
            return int(x) + x
    """
    assert rules_of(src) == ["R7", "R7"]


def test_r7_fires_on_implicit_bool_of_jnp_expression():
    src = """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                return x
            while jnp.all(x):
                x = x - 1
            assert jnp.isfinite(x)
            return not jnp.any(x)
    """
    assert rules_of(src) == ["R7", "R7", "R7", "R7"]


def test_r7_static_branching_and_constants_are_fine():
    src = """
        import jax
        @jax.jit
        def f(x, flag=None):
            if flag is None:
                flag = True
            n = int(3.5)
            return x * n
    """
    assert rules_of(src) == []


def test_r7_untraced_code_is_fine():
    src = """
        import numpy as np
        def host(x):
            if bool(x.any()):
                return int(x.sum())
            return 0
    """
    assert rules_of(src) == []


def test_r7_suppressed_by_pragma():
    src = """
        import jax
        @jax.jit
        def f(x):
            return int(x)  # jaxlint: disable=R7
    """
    assert rules_of(src) == []


def test_pragma_all_silences_everything():
    src = """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            jax.debug.print("x")  # jaxlint: disable=all
            return jnp.zeros(3)  # jaxlint: disable=all
    """
    assert rules_of(src) == []


# ---------------------------------------------------------------------------
# whole-package ratchet
# ---------------------------------------------------------------------------

def test_package_has_zero_non_baselined_violations():
    violations = analyze_paths([PKG])
    baseline = load_baseline(ROOT / "jaxlint_baseline.json")
    current = baseline_counts(violations, ROOT)
    # exact equality, not <=: when a baselined violation is fixed the
    # baseline file must ratchet down with it (--write-baseline)
    assert current == baseline, (
        "package violations diverged from jaxlint_baseline.json; new "
        "violations must be fixed, fixed ones must shrink the baseline "
        f"(current={current})")


def test_no_r1_r3_r6_anywhere_in_package():
    # the satellite fix pass cleared every R1/R3/R6; keep them at zero
    # outright (no baseline allowance)
    bad = [v for v in analyze_paths([PKG]) if v.rule in ("R1", "R3", "R6")]
    assert bad == [], "\n".join(str(v) for v in bad)


def test_tools_probes_are_side_effect_free():
    # the probes must parse and carry no module-level env/path mutation
    # outside the __main__ guard (satellite: importable without side
    # effects); jaxlint parsing also confirms they are analyzable
    import ast
    for f in sorted((ROOT / "tools").glob("*.py")):
        tree = ast.parse(f.read_text(), filename=str(f))
        for node in tree.body:     # module level statements only
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.If)):
                    break          # guarded / deferred bodies are fine
                assert not (isinstance(sub, ast.Call)
                            and ast.unparse(sub.func).endswith(
                                ("sys.path.insert",
                                 "os.environ.setdefault"))), \
                    f"{f.name}: module-level side effect {ast.unparse(sub)}"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _run_cli(*args, cwd=ROOT):
    env = dict(os.environ, PYTHONPATH=str(ROOT))
    return subprocess.run(
        [sys.executable, "-m", "pulsar_timing_gibbsspec_tpu.analysis",
         *args],
        cwd=cwd, env=env, capture_output=True, text=True)


def test_cli_exits_zero_on_package():
    r = _run_cli(str(PKG))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_cli_exits_nonzero_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax
        def f(x):
            jax.debug.print("x={}", x)
            return x
    """))
    r = _run_cli(str(bad))
    assert r.returncode == 1
    assert "R6" in r.stderr


def test_cli_write_baseline_then_clean(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return x + jnp.zeros(3)
    """))
    bl = tmp_path / "bl.json"
    r = _run_cli(str(bad), "--baseline", str(bl), "--write-baseline")
    assert r.returncode == 0
    assert json.loads(bl.read_text())["violations"]
    # baselined -> clean
    r2 = _run_cli(str(bad), "--baseline", str(bl))
    assert r2.returncode == 0, r2.stdout + r2.stderr
    # a NEW violation on top of the baseline still fails
    bad.write_text(bad.read_text() + textwrap.dedent("""
        @jax.jit
        def g(x):
            return x + jnp.ones(4)
    """))
    r3 = _run_cli(str(bad), "--baseline", str(bl))
    assert r3.returncode == 1


def test_cli_reports_stale_baseline(tmp_path):
    f = tmp_path / "probe.py"
    f.write_text(textwrap.dedent("""
        import jax
        def f(x):
            jax.debug.print("a", x)
            jax.debug.print("b", x)
            return x
    """))
    bl = tmp_path / "bl.json"
    r0 = _run_cli(str(f), "--baseline", str(bl), "--write-baseline")
    assert r0.returncode == 0
    # fix one of the two baselined violations -> count drops below baseline
    f.write_text(f.read_text().replace('jax.debug.print("b", x)\n', ""))
    r = _run_cli(str(f), "--baseline", str(bl))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stale" in r.stdout
    # a file OUTSIDE the analyzed set must not be reported stale
    other = tmp_path / "other.py"
    other.write_text("x = 1\n")
    r2 = _run_cli(str(other), "--baseline", str(bl))
    assert r2.returncode == 0
    assert "stale" not in r2.stdout


def test_tools_jaxlint_wrapper_importable():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "tools_jaxlint", ROOT / "tools" / "jaxlint.py")
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)        # no side effects on import
    assert callable(m.main)
