"""Correlated-GWB injection + recovery of inter-pulsar correlations.

The per-pulsar injector validates spectra; ``inject_correlated`` draws all
pulsars' Fourier coefficients jointly with per-frequency covariance
``phi_j G`` so the correlated-ORF samplers can be validated against a
known *correlation* truth — something the reference could only set up
through libstempo/toasim.
"""

import os

import numpy as np

from pulsar_timing_gibbsspec_tpu.data import load_directory
from pulsar_timing_gibbsspec_tpu.data.simulate import inject_correlated
from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.models.orf import orf_matrix
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

REFDATA = os.environ.get("PTGIBBS_REFDATA",
                         "/root/reference/simulated_data")


def test_injected_coefficient_covariance():
    """Across seeds, the empirical cross-pulsar correlation of the injected
    coefficients converges to the requested ORF matrix."""
    psrs = load_directory(REFDATA)[:3]
    draws = np.stack([
        inject_correlated(psrs, orf="hd", nmodes=4, seed=s)[1]
        for s in range(300)])                        # (S, P, 2K)
    G = orf_matrix("hd", [p.pos for p in psrs])
    flat = draws.transpose(1, 0, 2).reshape(3, -1)   # (P, S*2K)
    # normalize out the per-column phi scale: correlation, not covariance
    emp = np.corrcoef(flat)
    np.testing.assert_allclose(emp, G, atol=0.08)


def test_orf_likelihood_locates_quadrupole():
    """Freeze the gw coefficients at an HD-injected truth and scan the
    legendre quadrupole weight: the coefficient-conditional ORF
    likelihood must peak at positive theta_2 (HD is quadrupole-
    dominated), and at ~0 for an uncorrelated injection."""
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb

    psrs = load_directory(REFDATA)[:8]
    # 2K coefficient vectors inform the P-dim correlation; keep 2K >> P
    # or the frozen-b scan hits the degenerate-MLE spike at singular G
    # (the sampler itself is immune: prior-bounded theta, b redrawn)
    K = 10
    peaks = {}
    for orf_inj in ("hd", "crn"):
        inj, a = inject_correlated(psrs, orf=orf_inj, nmodes=K, seed=3,
                                   log10_A=np.log10(5e-14))
        pta = model_general(inj, tm_svd=True, red_var=False,
                            white_vary=False, common_psd="spectrum",
                            common_components=K, orf="legendre_orf",
                            leg_lmax=2)
        cm = compile_pta(pta)
        names = list(pta.param_names)
        # state: true per-bin power in rho, theta at 0; coefficients at
        # the injected truth
        x = np.zeros(cm.nx)
        rho_names = [n for n in names if "rho" in n]
        tau = 0.5 * (a[:, ::2] ** 2 + a[:, 1::2] ** 2).mean(axis=0)
        for k, nm in enumerate(sorted(rho_names)):
            x[names.index(nm)] = 0.5 * np.log10(tau[k])
        b = np.zeros((cm.P, cm.Bmax))
        np.put_along_axis(b, np.asarray(cm.gw_sin_ix), a[:, ::2], axis=1)
        np.put_along_axis(b, np.asarray(cm.gw_cos_ix), a[:, 1::2], axis=1)
        lnlike = jb.lnlike_orf_fn(cm, jnp.asarray(b, cm.cdtype))
        j2 = names.index("gw_legendre_orf_orfw_leg_2")
        grid = np.linspace(-0.45, 0.45, 61)
        vals = []
        for t in grid:
            q = x.copy()
            q[j2] = t
            vals.append(float(lnlike(jnp.asarray(q, cm.cdtype))))
        # grid points where G(theta) leaves the PD cone evaluate to NaN
        peaks[orf_inj] = grid[int(np.nanargmax(vals))]
    assert peaks["hd"] > 0.12, peaks
    assert abs(peaks["crn"]) < peaks["hd"] / 2, peaks


def test_end_to_end_correlation_recovery(tmp_path):
    """Sample a legendre-ORF model on strongly HD-correlated data: the
    posterior-mean correlation curve must carry the HD signature —
    positive at small separations, lower near 90 degrees."""
    from scipy.special import eval_legendre

    psrs = load_directory(REFDATA)[:8]
    inj, _ = inject_correlated(psrs, orf="hd", nmodes=4, seed=5,
                               log10_A=np.log10(5e-14))
    pta = model_general(inj, tm_svd=True, red_var=False, white_vary=False,
                        common_psd="spectrum", common_components=4,
                        orf="legendre_orf", leg_lmax=2)
    idx = BlockIndex.build(pta.param_names)
    g = PTABlockGibbs(pta, backend="jax", seed=6, progress=False)
    chain = g.sample(pta.initial_sample(np.random.default_rng(0)),
                     outdir=str(tmp_path / "rec"), niter=1200)
    assert np.all(np.isfinite(chain))
    th = chain[300:, idx.orf].mean(axis=0)           # (3,) legendre weights

    def curve(cosz):
        return sum(th[l] * eval_legendre(l, cosz) for l in range(3))

    # HD: +0.5 at zeta -> 0, ~-0.09 at 90 degrees
    assert curve(0.999) > curve(0.0) + 0.1, (th, curve(0.999), curve(0.0))
    assert curve(0.999) > 0.05, th