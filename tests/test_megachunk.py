"""Mega-chunk steady loop acceptance (ISSUE 12, docs/PERFORMANCE.md).

The device-resident mega-chunk dispatch (``megachunk`` sub-chunks scanned
inside ONE jitted function) must be a pure execution-grid change: every
recorded row, carry and health datum bitwise-identical to the legacy
one-chunk-per-dispatch loop, across resume seams, chunk-geometry changes,
thinning, the DE jump-history window and mid-run kills on a 2-d mesh.
The amortization itself is covered by the ``dispatch_amortized`` probe /
gauge / ledger plumbing tested at the bottom.
"""

import importlib.util
from pathlib import Path

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
    build_model, synthetic_pulsars)
from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import (PTABlockGibbs,
                                                       PulsarBlockGibbs)

_REPO = Path(__file__).resolve().parents[1]

# one compile-friendly geometry shared by the facade cases: small CRN
# free-spectrum model, 2 vmapped chains, warmup well clear of the seams
KW = dict(backend="jax", seed=9, progress=False, white_adapt_iters=20,
          chunk_size=10, nchains=2, warmup_sweeps=5)
NITER = 64


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", _REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tiny_pta():
    psrs = synthetic_pulsars(3, 40, tm_cols=3, seed=0)
    return build_model(psrs, 3)


@pytest.fixture(scope="module")
def x0(tiny_pta):
    return tiny_pta.initial_sample(np.random.default_rng(5))


@pytest.fixture(scope="module")
def legacy64(tiny_pta, x0, tmp_path_factory):
    """The uninterrupted legacy-grid run every mega case must equal."""
    out = tmp_path_factory.mktemp("legacy64")
    return PulsarBlockGibbs(tiny_pta, **KW).sample(
        x0, outdir=str(out), niter=NITER, save_every=20)


# ---------------------------------------------------------------------------
# dispatch-level identity


def test_mega_fn_bitwise_vs_legacy_chunks(tiny_pta):
    """One mega dispatch (3 sub-chunks of 2 sweeps) vs three legacy
    dispatches: record slabs, end carries and chunk health must agree —
    xs/bs/x/b bitwise, ``finite`` AND-reduced, ``move_frac`` averaged.
    Also the mid-mega truncation: ``n_keep=3`` lands the carry exactly
    where legacy 2-full-sweeps-plus-``n_keep=1`` lands it."""
    import jax
    import jax.numpy as jnp

    fn, args, drv = jb.sweep_chunk_entry(tiny_pta, 4, chunk=2, seed=0)
    rng = np.random.default_rng(0)
    x0 = jnp.tile(jnp.asarray(tiny_pta.initial_sample(rng), drv.cm.cdtype),
                  (drv.C, 1))
    b0 = jnp.zeros((drv.C, drv.cm.P, drv.cm.Bmax), drv.cm.cdtype)
    n, n_sub, key = 2, 3, drv.key

    legacy_fn = drv._chunk_fn(n, 0)
    x, b = x0, b0
    xs_all, bs_all, healths = [], [], []
    for j in range(n_sub):
        out = legacy_fn(x, b, key, jnp.asarray(j * n, jnp.int32),
                        drv._aux(), jnp.asarray(n, jnp.int32))
        x, b, xs, bs, health = out[:5]
        xs_all.append(np.asarray(xs))
        bs_all.append(np.asarray(bs))
        healths.append(jax.tree_util.tree_map(np.asarray, health))

    mega_fn = drv._mega_fn(n, n_sub, 0)
    aux = drv._aux_mega(None, None, n_sub)
    out = mega_fn(x0, b0, key, jnp.asarray(0, jnp.int32), aux,
                  jnp.asarray(n * n_sub, jnp.int32))
    xm, bm, xs_m, bs_m, health_m = out[:5]

    np.testing.assert_array_equal(np.concatenate(xs_all, axis=0),
                                  np.asarray(xs_m))
    np.testing.assert_array_equal(np.concatenate(bs_all, axis=0),
                                  np.asarray(bs_m))
    np.testing.assert_array_equal(np.asarray(x), np.asarray(xm))
    np.testing.assert_array_equal(np.asarray(b), np.asarray(bm))
    hm = jax.tree_util.tree_map(np.asarray, health_m)
    np.testing.assert_array_equal(
        hm["finite"], np.all([h["finite"] for h in healths], axis=0))
    assert np.allclose(hm["move_frac"],
                       np.mean([h["move_frac"] for h in healths], axis=0),
                       rtol=1e-6)

    # donation means fresh carries for the truncated replay
    x0 = jnp.tile(jnp.asarray(
        tiny_pta.initial_sample(np.random.default_rng(0)), drv.cm.cdtype),
        (drv.C, 1))
    b0 = jnp.zeros((drv.C, drv.cm.P, drv.cm.Bmax), drv.cm.cdtype)
    out2 = mega_fn(x0, b0, key, jnp.asarray(0, jnp.int32), aux,
                   jnp.asarray(3, jnp.int32))
    x = jnp.tile(jnp.asarray(
        tiny_pta.initial_sample(np.random.default_rng(0)), drv.cm.cdtype),
        (drv.C, 1))
    b = jnp.zeros((drv.C, drv.cm.P, drv.cm.Bmax), drv.cm.cdtype)
    ref = legacy_fn(x, b, key, jnp.asarray(0, jnp.int32), drv._aux(),
                    jnp.asarray(n, jnp.int32))
    ref = legacy_fn(ref[0], ref[1], key, jnp.asarray(n, jnp.int32),
                    drv._aux(), jnp.asarray(1, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out2[0]), np.asarray(ref[0]))


# ---------------------------------------------------------------------------
# run-level identity, resume seams, retrace contract


def test_megachunk_run_bitwise_vs_legacy(tiny_pta, x0, legacy64, tmp_path):
    mega = PulsarBlockGibbs(tiny_pta, megachunk=3, **KW).sample(
        x0, outdir=str(tmp_path), niter=NITER, save_every=20)
    assert np.all(np.isfinite(mega))
    np.testing.assert_array_equal(mega, legacy64)


def test_megachunk_resume_chunk_change_no_retrace(tiny_pta, x0, legacy64,
                                                  tmp_path):
    """The elastic seam: stop a (chunk 10, mega 3) run at row 40 and
    resume it as (chunk 8, mega 2).  Per-sweep keys are pure in the
    absolute iteration, so the chain stays bitwise-identical to the
    legacy grid; and the driver brackets the new geometry's cache-miss
    compile as planned, so the steady-phase retrace count stays zero."""
    from pulsar_timing_gibbsspec_tpu import profiling

    kw = {k: v for k, v in KW.items() if k != "chunk_size"}
    PulsarBlockGibbs(tiny_pta, chunk_size=10, megachunk=3, **kw).sample(
        x0, outdir=str(tmp_path), niter=40, save_every=20)
    with profiling.recompile_counter() as rc:
        rc.phase("steady")
        g = PulsarBlockGibbs(tiny_pta, chunk_size=8, megachunk=2, **kw)
        resumed = g.sample(x0, outdir=str(tmp_path), niter=NITER,
                           resume=True, save_every=20)
    assert rc.unplanned("steady") == 0
    np.testing.assert_array_equal(resumed, legacy64)


def test_megachunk_thinned_bitwise(tiny_pta, x0, tmp_path):
    """record_every thinning rides the mega grid unchanged: the slab is
    megachunk x the legacy chunk's thinned rows, nothing else."""
    kw = dict(KW, record_every=4, chunk_size=12)
    legacy = PulsarBlockGibbs(tiny_pta, **kw).sample(
        x0, outdir=str(tmp_path / "l"), niter=NITER, save_every=20)
    mega = PulsarBlockGibbs(tiny_pta, megachunk=2, **kw).sample(
        x0, outdir=str(tmp_path / "m"), niter=NITER, save_every=20)
    np.testing.assert_array_equal(mega, legacy)


def test_megachunk_de_history_bitwise_and_guard(tmp_path):
    """The DE jump reads a replay of rows ``DE_DELAY`` behind the head;
    a mega dispatch advances the head ``megachunk`` chunks per refresh
    opportunity, so the run must cross several refresh boundaries and
    stay bitwise — and the ctor must reject geometries whose lookback
    outruns the history window."""
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import (
        DE_DELAY, DE_HIST_LEN, DE_Q)

    psrs = synthetic_pulsars(2, 30, tm_cols=3, seed=1)
    pta = model_general(psrs, tm_svd=True, red_var=True,
                        red_psd="powerlaw", red_components=4,
                        white_vary=False, common_psd="spectrum",
                        common_components=4)
    x0 = pta.initial_sample(np.random.default_rng(8))
    kw = dict(backend="jax", seed=12, progress=False,
              white_adapt_iters=50, chunk_size=20)
    niter = DE_DELAY + DE_HIST_LEN + 2 * DE_Q - 60
    legacy = PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "l"), niter=niter, save_every=100)
    mega = PulsarBlockGibbs(pta, megachunk=3, **kw).sample(
        x0, outdir=str(tmp_path / "m"), niter=niter, save_every=100)
    assert np.all(np.isfinite(legacy))
    np.testing.assert_array_equal(mega, legacy)
    with pytest.raises(ValueError, match="outruns the DE history"):
        PulsarBlockGibbs(pta, megachunk=4, **kw)


def test_megachunk_chaos_kill_mid_run_2d_bitwise(synth_pta, tmp_path):
    """The torn-checkpoint kill mid-mega on the 2-d (2, 4) chains x
    pulsars mesh: the crash lands between the two os.replace calls at a
    row inside the mega cadence, the supervised retry rolls back to the
    .bak generation and replays — final chain bitwise-identical to an
    uninterrupted LEGACY-grid run (identity and recovery in one)."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh
    from pulsar_timing_gibbsspec_tpu.runtime import (faults, preemption,
                                                     run_supervised,
                                                     telemetry)

    faults.clear()
    telemetry.reset()
    preemption.reset()
    try:
        x0 = synth_pta.initial_sample(np.random.default_rng(0))
        kw = dict(backend="jax", seed=3, progress=False, warmup_sweeps=2,
                  chunk_size=4, nchains=4, pad_pulsars=4)
        base = PTABlockGibbs(synth_pta, mesh=make_mesh((2, 4)),
                             **kw).sample(x0, outdir=tmp_path / "base",
                                          niter=24, save_every=4)
        faults.inject("crash", point="chainstore.between_replaces",
                      at_row=16)
        g = PTABlockGibbs(synth_pta, mesh=make_mesh((2, 4)), megachunk=2,
                          **kw)
        chain, rep = run_supervised(g, x0, tmp_path / "chaos", 24,
                                    save_every=4, sleep=lambda s: None)
        np.testing.assert_array_equal(chain, base)
        assert rep.retries == 1
    finally:
        faults.clear()
        preemption.reset()


# ---------------------------------------------------------------------------
# the dispatch-tax instruments


def test_dispatch_breakdown_reports_amortized_tax(tiny_pta):
    """The profiling probe on a mega driver: stage keys plus the two
    amortization fields, with the per-sweep tax equal to the host-side
    stage sum divided by the sweeps one dispatch covers."""
    from pulsar_timing_gibbsspec_tpu import profiling

    fn, args, drv = jb.megachunk_sweep_chunk_entry(tiny_pta, 4, chunk=2,
                                                   megachunk=3)
    x = np.asarray(tiny_pta.initial_sample(np.random.default_rng(3)))
    x = np.tile(x, (drv.C, 1))
    bd = profiling.dispatch_breakdown(drv, x)
    assert bd["sweeps_per_dispatch"] == 6.0
    host = bd["host_prep"] + bd["enqueue"] + bd["writeback"]
    assert bd["dispatch_amortized_per_sweep"] == pytest.approx(host / 6.0)


def test_stage_aggregator_amortizes_dispatch_over_sweeps():
    """A ``chunk.dispatch`` span carrying ``n=`` (sweeps per dispatch)
    must yield the synthetic ``dispatch_amortized`` stage at 1/n the
    enqueue wall — the streaming view of the dispatch tax."""
    from pulsar_timing_gibbsspec_tpu.obs import trace as otrace
    from pulsar_timing_gibbsspec_tpu.obs.perf import StageAggregator
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry

    telemetry.reset("dispatch_ms")
    agg = StageAggregator(job="tm").install()
    try:
        with otrace.span("chunk.dispatch", it0=0, n=8):
            pass
    finally:
        agg.uninstall()
    summ = agg.summary()
    assert set(summ) == {"enqueue", "dispatch_amortized"}
    assert (summ["dispatch_amortized"]["last"]
            == pytest.approx(summ["enqueue"]["last"] / 8.0))
    g = telemetry.get_gauge("dispatch_ms", job="tm",
                            stage="dispatch_amortized", stat="last")
    assert g is not None and g >= 0.0
    telemetry.reset("dispatch_ms")


def test_check_ledger_dispatch_tax_is_lower_is_better():
    """The amortized-dispatch headline gates in the opposite direction
    from the rate fields: growth past (1 + band) x best prior fails,
    improvement and in-band noise pass, and a --band override changes
    the width but never the direction."""
    from pulsar_timing_gibbsspec_tpu.obs.perf import (DEFAULT_BANDS,
                                                      LOWER_IS_BETTER,
                                                      check_ledger)

    assert "dispatch_amortized_ms_per_sweep" in DEFAULT_BANDS
    assert "dispatch_amortized_ms_per_sweep" in LOWER_IS_BETTER

    def rec(tax):
        return {"schema": 1, "kind": "bench", "metric": "m", "value": 100.0,
                "device_kind": "cpu", "backend": "cpu", "source": "t",
                "dispatch_amortized_ms_per_sweep": tax}

    assert check_ledger([rec(1.0), rec(1.4)]) == []        # in band (50%)
    assert check_ledger([rec(1.0), rec(0.2)]) == []        # improvement
    problems = check_ledger([rec(1.0), rec(1.6)])
    assert len(problems) == 1 and "grew past" in problems[0]
    assert check_ledger(
        [rec(1.0), rec(1.4)],
        {"dispatch_amortized_ms_per_sweep": 0.1}) != []    # tighter band
    assert check_ledger(
        [rec(1.0), rec(1.6)],
        {"dispatch_amortized_ms_per_sweep": 0.7}) == []    # wider band


def test_watchdog_deadline_is_per_sweep():
    """Mega-chunk dispatches cover M sweeps: the EMA must normalize by
    ``n`` so a chunk-geometry change between resumes cannot mis-scale
    the stall deadline."""
    from pulsar_timing_gibbsspec_tpu.runtime.watchdog import DispatchWatchdog

    wd = DispatchWatchdog(k=2.0, floor_s=1.0, first_floor_s=123.0)
    assert wd.deadline(8) == 123.0                  # no EMA yet
    wd.observe(8.0, n=8)                            # 1 s per sweep
    assert wd.ema == pytest.approx(1.0)
    assert wd.deadline(4) == pytest.approx(8.0)     # k * ema * n
    assert wd.deadline(1) == pytest.approx(2.0)
    wd2 = DispatchWatchdog(k=2.0, floor_s=1.0, first_floor_s=123.0)
    wd2.observe(8.0)                                # legacy n=1 semantics
    assert wd2.ema == pytest.approx(8.0)


def test_trim_steady_drops_drain_and_partial_tail():
    """The bench rate windows: a partial trailing chunk (smaller
    iteration stride) and the final full chunk (its writeback has no
    next compute to hide under — the drain) are both trimmed before
    windowing, so every window measures the same steady process.  The
    numpy oracle's stride-1 marks keep their tail."""
    bench = _load_bench()
    t = 1.58
    marks = [(100 * i, t * i) for i in range(25)]        # steady chunks
    marks.append((2500, marks[-1][1] + 7.9))             # drain-priced
    marks.append((2540, marks[-1][1] + 0.7))             # partial chunk
    trimmed = bench._trim_steady(marks)
    assert len(trimmed) == 25 and trimmed[-1][0] == 2400
    rates = bench._window_rates(marks)
    assert len(rates) == bench.NWINDOWS
    assert np.allclose(rates, 100.0 / t, rtol=1e-9)
    # stride-1 marks (the oracle): no drain drop
    oracle = [(i, 0.5 * i) for i in range(20)]
    assert len(bench._trim_steady(oracle)) == 20
    # too short to judge: untouched
    assert len(bench._trim_steady([(0, 0.0), (4, 1.0)])) == 2


def test_jacobi_factor_mean_prop_matches_unfused():
    """The fused mean+proposal kernel is the refresh hot path: it must
    reproduce ``jacobi_factor_mean`` plus the separate square-root
    matvec bit-for-bit in f64 (same factor, same contraction order)."""
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.ops import linalg

    rng = np.random.default_rng(42)
    B = 6
    A = rng.standard_normal((3, B, B))
    Sig = jnp.asarray(A @ np.swapaxes(A, -1, -2) + 5.0 * np.eye(B))
    d = jnp.asarray(rng.standard_normal((3, B)))
    z = jnp.asarray(rng.standard_normal((3, B)))
    L, Li, dj, mean = linalg.jacobi_factor_mean(Sig, d)
    bp_ref = mean + dj * jnp.einsum("...ji,...j->...i", Li, z,
                                    precision="highest")
    Lf, Lif, djf, meanf, bpf = linalg.jacobi_factor_mean_prop(Sig, d, z)
    np.testing.assert_array_equal(np.asarray(L), np.asarray(Lf))
    np.testing.assert_array_equal(np.asarray(dj), np.asarray(djf))
    np.testing.assert_allclose(np.asarray(mean), np.asarray(meanf),
                               rtol=1e-13, atol=1e-13)
    np.testing.assert_allclose(np.asarray(bp_ref), np.asarray(bpf),
                               rtol=1e-13, atol=1e-13)
