"""``parallel.sharding.collective_report`` edge cases.

The census core lives in ``analysis.jaxprcheck.collectives`` (the C2
contract); ``collective_report`` delegates to it.  These tests cover
the paths the MULTICHIP dry-run does not: text-level parsing, the
no-mesh single-device trace (zero collectives, no crash), the gather
budget actually raising, and the HD joint-draw claim in the docstring
(its Schur-block gathers stay far below basis size).
"""

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.collectives import (
    census_from_hlo, check_gather_budget)
from pulsar_timing_gibbsspec_tpu.parallel.sharding import (
    chain_sharding, chain_submesh_size, collective_report, make_mesh,
    mesh_layout, pulsar_sharding, pulsar_submesh_size, replicated_sharding,
    shard_carry, shard_compiled, validate_chains)

_HLO = """\
ENTRY main {
  %p = f32[6,17]{1,0} parameter(0)
  %ag = f32[48,17]{1,0} all-gather(%p), dimensions={0}
  %ar0 = f32[17]{0} all-reduce(%x), to_apply=%add
  %ar1 = f32[] all-reduce-start(%y), to_apply=%add
  %ag2 = f32[8]{0} all-gather-start(%z), dimensions={0}
}
"""


def test_census_from_hlo_counts_and_operand_elems():
    c = census_from_hlo(_HLO)
    assert c["all-reduce"] == 2          # all-reduce + all-reduce-start
    assert c["all-gather"] == 2          # all-gather + all-gather-start
    # elems come from the defining line's first shape (the gathered
    # result): the 48x17 panel and the rank-1 start op
    assert c["gather_elems"] == [8, 816]


def test_census_from_hlo_empty_program():
    assert census_from_hlo("ENTRY main { ROOT %r = f32[] add(a, b) }") == \
        {"all-reduce": 0, "all-gather": 0, "gather_elems": []}


def test_check_gather_budget():
    c = census_from_hlo(_HLO)
    assert check_gather_budget(c, None) is None
    assert check_gather_budget(c, 816) is None
    msg = check_gather_budget(c, 800)
    assert msg is not None and "[816]" in msg


def test_collective_report_single_device_no_mesh():
    # the plain-jit path: no mesh, nothing sharded — the report must be
    # all-zero rather than erroring on a collective-free program
    def f(x):
        return (x * 2.0).sum()

    rep = collective_report(f, np.zeros((4, 3), np.float32))
    assert rep == {"all-reduce": 0, "all-gather": 0, "gather_elems": []}


def test_collective_report_gather_budget_raises():
    import jax

    mesh = make_mesh(8)
    x = jax.device_put(np.zeros((8, 64), np.float32),
                       pulsar_sharding(mesh, 2))

    # replicating a sharded operand forces one all-gather of the
    # per-device (1, 64) shard
    fn = jax.jit(lambda a: a * 2.0,
                 out_shardings=replicated_sharding(mesh))
    rep = collective_report(fn, x)
    assert rep["all-gather"] >= 1
    # the gathered result is at most the full (8, 64) array
    assert rep["gather_elems"] and max(rep["gather_elems"]) <= 512
    with pytest.raises(RuntimeError, match="budget"):
        collective_report(fn, x, max_gather_elems=1)


# ---------------------------------------------------------------------------
# 2-d (chain, pulsar) mesh


def test_make_mesh_2d_axes_and_layout():
    mesh = make_mesh((2, 4))
    assert mesh.axis_names == ("chain", "pulsar")
    assert mesh.devices.shape == (2, 4)
    assert chain_submesh_size(mesh) == 2
    assert pulsar_submesh_size(mesh) == 4
    lay = mesh_layout(mesh)
    assert lay["devices"] == 8
    assert lay["axis"] == "pulsar"           # back-compat readers
    assert lay["axes"] == [["chain", 2], ["pulsar", 4]]
    # the classic 1-d mesh: no chain axis, size-1 chain submesh
    m1 = make_mesh(8)
    assert chain_submesh_size(m1) == 1
    assert pulsar_submesh_size(m1) == 8
    assert mesh_layout(m1)["axes"] == [["pulsar", 8]]


def test_make_mesh_2d_validation():
    with pytest.raises(ValueError, match="n_chain_devs"):
        make_mesh((2, 4, 1))
    with pytest.raises(ValueError, match="n_chain_devs"):
        make_mesh((0, 4))
    with pytest.raises(RuntimeError, match="refusing"):
        make_mesh((4, 4))                    # 16 > the 8 host devices


def test_shard_carry_places_chain_leaves():
    import jax

    mesh = make_mesh((2, 4))
    C = 4
    tree = {"x": np.zeros((C, 7), np.float32),
            "b": np.zeros((C, 3, 5), np.float32),
            "scalar": np.float32(1.0),
            "not_chain": np.zeros((3, C), np.float32)}
    placed = shard_carry(mesh, jax.device_put(tree), C)
    assert placed["x"].sharding.is_equivalent_to(chain_sharding(mesh, 2), 2)
    assert placed["b"].sharding.is_equivalent_to(chain_sharding(mesh, 3), 3)
    # non-chain-leading arrays replicate
    assert placed["not_chain"].sharding.is_equivalent_to(
        replicated_sharding(mesh), 2)
    # a chain-less mesh is a no-op (GSPMD keeps deciding)
    same = shard_carry(make_mesh(4), tree, C)
    assert same is tree
    assert shard_carry(None, tree, C) is tree


def test_validate_chains_actionable_error():
    mesh = make_mesh((2, 4))
    validate_chains(mesh, 4)                 # divides: fine
    validate_chains(make_mesh(8), 3)         # no chain axis: anything goes
    with pytest.raises(ValueError, match="multiple of 2"):
        validate_chains(mesh, 3)


def test_shard_compiled_2d_pad_suggestion(synth_hd_pta):
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    cm = compile_pta(synth_hd_pta)           # P = 3: does not divide 4
    with pytest.raises(ValueError, match=r"pulsar submesh \(4 of 8"):
        shard_compiled(cm, make_mesh((2, 4)))
    with pytest.raises(ValueError, match="pad_pulsars=4"):
        shard_compiled(cm, make_mesh((2, 4)))
    # padded compile shards cleanly on the same mesh
    cm4 = compile_pta(synth_hd_pta, pad_pulsars=4)
    shard_compiled(cm4, make_mesh((2, 4)))


@pytest.mark.slow
def test_collective_report_hd_joint_draw_no_basis_gather(synth_hd_pta):
    """The docstring's claim about the structured correlated-ORF joint
    b-draw, measured: under pulsar-axis sharding its cross-device
    movement stays orders below a basis-sized (P*Nmax*Bmax) operand."""
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    cm = compile_pta(synth_hd_pta, pad_pulsars=4)
    cm = shard_compiled(cm, make_mesh(4))

    # the model rides as a jit argument: closure-captured arrays lower
    # as replicated constants and GSPMD drops their shardings
    def draw(cm_, x, key):
        return jb.draw_b_fn(cm_, x, key)

    x0 = np.asarray(synth_hd_pta.initial_sample(np.random.default_rng(0)),
                    cm.cdtype)
    basis = cm.P * cm.T.shape[1] * cm.Bmax
    rep = collective_report(draw, cm, x0, jr.key(0),
                            max_gather_elems=basis - 1)
    assert all(e < basis for e in rep["gather_elems"])
