"""Test configuration.

Forces JAX onto the CPU backend with 8 virtual devices so multi-device
sharding paths are exercised without TPU hardware (the strategy SURVEY.md §4
prescribes in place of the reference's absent multi-node test story).  Must
run before anything imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# The interpreter's sitecustomize imports jax at startup, which latches the
# JAX_PLATFORMS env var before this file runs; the config API still works
# because no backend has initialized yet.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


@pytest.fixture(scope="session")
def j1713():
    from pulsar_timing_gibbsspec_tpu.data import load_pulsar

    return load_pulsar(
        f"{REFDATA}/J1713+0747.par",
        f"{REFDATA}/J1713+0747.tim",
        inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0, nmodes=30),
    )


@pytest.fixture(scope="session")
def psrs8():
    from pathlib import Path

    from pulsar_timing_gibbsspec_tpu.data import load_directory

    names = sorted(p.stem for p in Path(REFDATA).glob("*.par"))[:8]
    return load_directory(
        REFDATA, names=set(names),
        inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0))
