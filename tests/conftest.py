"""Test configuration.

Forces JAX onto the CPU backend with 8 virtual devices so multi-device
sharding paths are exercised without TPU hardware (the strategy SURVEY.md §4
prescribes in place of the reference's absent multi-node test story).  Must
run before anything imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# The interpreter's sitecustomize imports jax at startup, which latches the
# JAX_PLATFORMS env var before this file runs; the config API still works
# because no backend has initialized yet.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


@pytest.fixture(scope="session")
def synth_pta():
    """Tiny synthetic single-pulsar PTA with a common free-spectrum
    block — no reference data needed (resilience/chaos tests)."""
    from pulsar_timing_gibbsspec_tpu.data.dataset import Pulsar
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general

    DAY = 86400.0
    rng = np.random.default_rng(11)
    n = 60
    span = 6.0 * 365.25 * DAY
    toas = np.sort(rng.uniform(0.0, span, n)) + 53000.0 * DAY
    errs = np.full(n, 5e-7)
    res = errs * rng.standard_normal(n)
    t = (toas - toas.mean()) / span
    M = np.column_stack([np.ones(n), t, t * t])
    psr = Pulsar(
        name="FAKE_CHAOS", toas=toas, toaerrs=errs, residuals=res,
        freqs=np.full(n, 1400.0),
        backend_flags=np.asarray(["sim"] * n, dtype=object),
        Mmat=M, fitpars=["offset", "F0", "F1"],
        flags={"pta": "NANOGrav"},
        pos=np.array([1.0, 0.0, 0.0]))
    return model_general([psr], red_var=False, white_vary=False,
                         common_psd="spectrum", common_components=4)


@pytest.fixture(scope="session")
def synth_hd_pta():
    """Small self-contained 3-pulsar PTA with a shared free-spectrum GW
    block under the Hellings-Downs ORF — the correlated-phi joint-b-draw
    path (tests/test_joint_structured.py, resume coverage) without
    reference data."""
    from pulsar_timing_gibbsspec_tpu.data.dataset import Pulsar
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general

    DAY = 86400.0
    rng = np.random.default_rng(7)
    psrs = []
    for ii in range(3):
        n = 72
        span = 8.0 * 365.25 * DAY
        toas = np.sort(rng.uniform(0.0, span, n)) + 53000.0 * DAY
        errs = np.full(n, 5e-7)
        t = (toas - toas.mean()) / span
        M = np.column_stack([np.ones(n), t, t * t])
        th = rng.uniform(0, np.pi)
        ph = rng.uniform(0, 2 * np.pi)
        psrs.append(Pulsar(
            name=f"FAKE_HD{ii:02d}", toas=toas, toaerrs=errs,
            residuals=errs * rng.standard_normal(n),
            freqs=np.full(n, 1400.0),
            backend_flags=np.asarray(["sim"] * n, dtype=object),
            Mmat=M, fitpars=["offset", "F0", "F1"],
            pos=np.array([np.sin(th) * np.cos(ph),
                          np.sin(th) * np.sin(ph), np.cos(th)])))
    return model_general(psrs, tm_svd=True, white_vary=True,
                         common_psd="spectrum", common_components=4,
                         red_var=True, red_psd="spectrum",
                         red_components=3, orf="hd")


@pytest.fixture(scope="session")
def j1713():
    from pulsar_timing_gibbsspec_tpu.data import load_pulsar

    return load_pulsar(
        f"{REFDATA}/J1713+0747.par",
        f"{REFDATA}/J1713+0747.tim",
        inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0, nmodes=30),
    )


@pytest.fixture(scope="session")
def psrs8():
    from pathlib import Path

    from pulsar_timing_gibbsspec_tpu.data import load_directory

    names = sorted(p.stem for p in Path(REFDATA).glob("*.par"))[:8]
    return load_directory(
        REFDATA, names=set(names),
        inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0))
