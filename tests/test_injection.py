"""Injection recovery — the reference's tier-2 validation (SURVEY §4):
simulated data with a known GWB, free-spectrum posterior compared against
the injection (``singlepulsar_sim_A2e-15_gamma4.333.ipynb`` cells 13-16).

Unlike the reference's by-eye violin plots, this compares the posterior
per-bin against the *realized* injected coefficient power (the injection
is deterministic, so the exact Fourier coefficients are reconstructable),
which removes realization scatter from the assertion.  Everything is
seed-pinned, so the thresholds are exact-reproducibility margins, not
statistical ones.
"""

import numpy as np

from pulsar_timing_gibbsspec_tpu.data import load_pulsar
from pulsar_timing_gibbsspec_tpu.data.fourier import fourier_basis
from pulsar_timing_gibbsspec_tpu.data.simulate import inject_residuals
from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs

REFDATA = "/root/reference/simulated_data"
INJ = dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0, nmodes=10, seed=42)


def test_free_spectrum_recovers_injection(tmp_path):
    psr = load_pulsar(f"{REFDATA}/J1713+0747.par",
                      f"{REFDATA}/J1713+0747.tim", inject=dict(INJ))

    # reconstruct the exact injected coefficients (deterministic seed)
    Tspan = psr.toas.max() - psr.toas.min()
    F, f = fourier_basis(psr.toas / 86400.0, INJ["nmodes"], Tspan)
    r, a = inject_residuals(psr.name, F, f, Tspan, psr.toaerrs, psr.Mmat,
                            log10_A=INJ["log10_A"], gamma=INJ["gamma"],
                            seed=INJ["seed"])
    np.testing.assert_allclose(r, psr.residuals)
    realized = 0.5 * np.log10(0.5 * (a[::2] ** 2 + a[1::2] ** 2))

    pta = model_general([psr], tm_svd=True, red_var=False, white_vary=False,
                        common_psd="spectrum", common_components=10)
    g = PulsarBlockGibbs(pta, backend="jax", seed=1, progress=False)
    chain = g.sample(pta.initial_sample(np.random.default_rng(0)),
                     outdir=str(tmp_path / "inj"), niter=1500)
    med = np.median(chain[300:], axis=0)

    # strong bins recover the realized power tightly (bin 0 excluded: the
    # lowest frequency is largely absorbed by the spindown fit — the
    # post-fit projection removes that power from the data by design)
    for k in (1, 2, 3):
        assert abs(med[k] - realized[k]) < 0.6, (k, med[k], realized[k])
    # across all bins but the projected one, typical agreement stays tight
    deltas = np.abs(med[1:10] - realized[1:10])
    assert np.median(deltas) < 0.5, deltas
    # weak high-frequency bins sit below the strong low-frequency signal
    assert np.all(med[4:10] < med[1] + 0.3), med
