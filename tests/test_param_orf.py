"""Parameterized ORFs (bin_orf / legendre_orf): sampled inter-pulsar
correlation weights.

The reference can construct these models through enterprise_extensions
(``model_definition.py:198-216``, ``orf='bin_orf'/'legendre_orf'`` with
``leg_lmax``) but its sampler handles no correlated model at all; here the
weights get an MH block on the coefficient-conditional correlated
likelihood and the b/rho machinery rebuilds G(theta) per state.
"""

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.models.orf import (BIN_ORF_EDGES,
                                                    orf_param_basis)
from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs


def _positions(n, seed=0):
    rng = np.random.default_rng(seed)
    v = rng.standard_normal((n, 3))
    return [x / np.linalg.norm(x) for x in v]


def test_bin_orf_basis_partitions_pairs():
    pos = _positions(8)
    B, labels = orf_param_basis("bin_orf", pos)
    assert B.shape == (len(BIN_ORF_EDGES) - 1, 8, 8)
    assert len(labels) == 7
    # every off-diagonal pair lands in exactly one bin; diagonals zero
    total = B.sum(axis=0)
    assert np.allclose(total, 1.0 - np.eye(8))
    assert np.allclose(np.diagonal(B, axis1=1, axis2=2), 0.0)


def test_legendre_basis_matches_scipy():
    from scipy.special import eval_legendre

    pos = _positions(6, seed=1)
    B, labels = orf_param_basis("legendre_orf", pos, leg_lmax=4)
    assert B.shape == (5, 6, 6) and labels == [f"leg_{l}" for l in range(5)]
    cosz = np.array([[np.dot(a, b) for b in pos] for a in pos])
    for l in range(5):
        expect = eval_legendre(l, np.clip(cosz, -1, 1)) * (1 - np.eye(6))
        np.testing.assert_allclose(B[l], expect, atol=1e-12)


def test_identity_at_zero_weights(psrs8):
    """G(0) = I: the compiled dynamic Ginv at theta=0 equals the CRN
    identity stack, so the correlated machinery degenerates exactly."""
    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=4, orf="legendre_orf", leg_lmax=1)
    cm = compile_pta(pta)
    assert cm.orf_B is not None
    x = np.zeros(cm.nx)
    Gi = np.asarray(cm.orf_ginv_k(x))
    assert Gi.shape == (cm.K, cm.P, cm.P)
    np.testing.assert_allclose(Gi, np.broadcast_to(np.eye(cm.P), Gi.shape),
                               atol=1e-12)


def test_non_pd_start_rejected(psrs8, tmp_path):
    pta = model_general(psrs8[:4], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=4, orf="bin_orf")
    idx = BlockIndex.build(pta.param_names)
    x0 = pta.initial_sample(np.random.default_rng(0))
    x0[idx.orf] = -0.99
    for backend in ("jax", "numpy"):
        g = PTABlockGibbs(pta, backend=backend, seed=1, progress=False)
        with pytest.raises(ValueError):
            g.sample(x0, outdir=str(tmp_path / backend), niter=10)


def test_param_orf_nchains(psrs8, tmp_path):
    """The ORF-weight MH block composes with the vmapped chains axis."""
    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=4, orf="legendre_orf", leg_lmax=1)
    idx = BlockIndex.build(pta.param_names)
    g = PTABlockGibbs(pta, backend="jax", seed=7, progress=False, nchains=3)
    chain = g.sample(pta.initial_sample(np.random.default_rng(1)),
                     outdir=str(tmp_path / "c3"), niter=120)
    assert chain.shape[1] == 3 and np.all(np.isfinite(chain))
    # chains evolve independently: their theta trajectories differ
    th = chain[60:, :, idx.orf]
    assert not np.allclose(th[:, 0], th[:, 1])


def test_param_orf_jax_vs_numpy_equivalence(psrs8, tmp_path):
    """Backend statistical equivalence on the sampled weights and the
    common spectrum (ESS-aware z-tests); theta starts at 0 (G = I)."""
    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=4, orf="legendre_orf", leg_lmax=1)
    idx = BlockIndex.build(pta.param_names)
    assert len(idx.orf) == 2
    x0 = pta.initial_sample(np.random.default_rng(2))
    # the factory pins the weights' init at 0 (G = I): a usable start
    # without hand-editing x0
    np.testing.assert_array_equal(x0[idx.orf], 0.0)
    chains = {}
    for backend, seed in [("jax", 3), ("numpy", 4)]:
        g = PTABlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2500)
    burn = 500
    for k in np.concatenate([idx.orf, idx.rho]):
        cj, cn = chains["jax"][burn:, k], chains["numpy"][burn:, k]
        assert np.all(np.isfinite(cj)) and np.all(np.isfinite(cn))
        ess_j = len(cj) / max(integrated_act(cj), 1.0)
        ess_n = len(cn) / max(integrated_act(cn), 1.0)
        z = abs(cj.mean() - cn.mean()) / np.sqrt(
            cj.var() / ess_j + cn.var() / ess_n)
        assert z < 4.5, (pta.param_names[k], z, ess_j, ess_n)
