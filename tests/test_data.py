import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.data import (
    design_matrix, fourier_basis, from_enterprise, get_tspan, load_directory,
    load_pulsar, parse_par, parse_tim,
)
from pulsar_timing_gibbsspec_tpu.data.simulate import inject_residuals, powerlaw_psd

REFDATA = "/root/reference/simulated_data"


def test_parse_par_j1713():
    par = parse_par(f"{REFDATA}/J1713+0747.par")
    assert par.name == "J1713+0747"
    assert par["F0"] == pytest.approx(218.811843786, rel=1e-9)
    assert "F0" in par.fitted and "F1" in par.fitted
    assert "PEPOCH" not in par.fitted        # no fit flag on epochs
    assert par.get("PB") == pytest.approx(67.825, rel=1e-3)


def test_parse_tim_j1713():
    tim = parse_tim(f"{REFDATA}/J1713+0747.tim")
    assert len(tim.mjds) == 720
    assert np.all(np.diff(tim.mjds) >= 0)
    assert tim.errs.min() > 1e-8 and tim.errs.max() < 1e-5   # ~0.1 us range
    assert tim.flags[0].get("f") == "test"


def test_design_matrix_full_rank():
    par = parse_par(f"{REFDATA}/J1713+0747.par")
    tim = parse_tim(f"{REFDATA}/J1713+0747.tim")
    M = design_matrix(par, tim)
    assert M.shape[0] == 720
    # at least offset + spin + astrometry terms
    assert M.shape[1] >= 7
    Mn = M / np.linalg.norm(M, axis=0)
    s = np.linalg.svd(Mn, compute_uv=False)
    assert s[-1] > 1e-10 * s[0]
    # quadratic spin-down partial must be in the span (F1 is fitted)
    t2 = ((tim.mjds - tim.mjds.mean()) * 86400.0) ** 2
    c, *_ = np.linalg.lstsq(M, t2, rcond=None)
    assert np.linalg.norm(t2 - M @ c) < 1e-8 * np.linalg.norm(t2)


def test_fourier_basis_interleaving():
    t = np.linspace(50000, 55000, 100)
    F, f = fourier_basis(t, nmodes=5, Tspan=5000 * 86400.0)
    assert F.shape == (100, 10)
    assert f[0] == f[1] == 1.0 / (5000 * 86400.0)
    # column 0 is sin, column 1 is cos of the same frequency
    arg = 2 * np.pi * t * 86400.0 * f[0]
    np.testing.assert_allclose(F[:, 0], np.sin(arg), atol=1e-12)
    np.testing.assert_allclose(F[:, 1], np.cos(arg), atol=1e-12)


def test_powerlaw_psd_scaling():
    f = np.array([1e-8, 2e-8])
    df = 1e-9
    p1 = powerlaw_psd(f, -14.0, 3.0, df)
    p2 = powerlaw_psd(f, -13.0, 3.0, df)
    np.testing.assert_allclose(p2 / p1, 100.0)      # A^2 scaling
    # steeper spectrum falls faster
    p3 = powerlaw_psd(f, -14.0, 5.0, df)
    assert p3[1] / p3[0] < p1[1] / p1[0]


def test_injection_deterministic_and_postfit(j1713):
    p2 = load_pulsar(
        f"{REFDATA}/J1713+0747.par", f"{REFDATA}/J1713+0747.tim",
        inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0, nmodes=30),
    )
    np.testing.assert_array_equal(j1713.residuals, p2.residuals)
    # post-fit: residuals orthogonal to the design matrix columns
    proj = j1713.Mmat.T @ j1713.residuals
    scale = np.linalg.norm(j1713.Mmat, axis=0) * np.linalg.norm(j1713.residuals)
    assert np.all(np.abs(proj) / scale < 1e-8)
    # red excess above the ~0.11us white level (post-fit projection absorbs
    # much of the lowest-frequency injected power, so the margin is modest)
    assert j1713.residuals.std() > 1.5 * j1713.toaerrs.mean()


def test_load_directory_and_tspan():
    psrs = load_directory(REFDATA, names={"J1713+0747", "B1855+09"})
    assert len(psrs) == 2
    ts = get_tspan(psrs)
    assert ts > 10 * 365.25 * 86400.0
    for p in psrs:
        assert p.ntoa == len(p.residuals) == len(p.toaerrs)
        assert p.backends() == ["test"]


class _FakeEnterprisePulsar:
    """Synthetic object exposing the enterprise Pulsar attribute surface
    (the reference's real-data loader, clean_demo.ipynb cells 3-5)."""

    def __init__(self, n=64, m=5, seed=7):
        rng = np.random.default_rng(seed)
        self.name = "J0000+0000"
        self.toas = np.sort(rng.uniform(0, 9.0 * 365.25 * 86400.0, n)) \
            + 53000.0 * 86400.0
        self.toaerrs = np.full(n, 5e-7)
        self.residuals = 1e-6 * rng.standard_normal(n)
        self.freqs = rng.choice([430.0, 1410.0], n)
        self.backend_flags = np.asarray(
            ["430_ASP" if f < 1000 else "L-wide_PUPPI" for f in self.freqs],
            dtype=object)
        self.Mmat = rng.standard_normal((n, m))
        self.fitpars = ["Offset", "F0", "F1", "RAJ", "DECJ"]
        # enterprise flags: per-TOA arrays keyed by flag name
        self.flags = {
            "pta": np.asarray(["NANOGrav"] * n, dtype=object),
            "fe": np.asarray(["430" if f < 1000 else "L-wide"
                              for f in self.freqs], dtype=object),
        }
        th, ph = 1.1, 2.2
        self.pos = np.array([np.sin(th) * np.cos(ph),
                             np.sin(th) * np.sin(ph), np.cos(th)])


def test_from_enterprise_adapter():
    epsr = _FakeEnterprisePulsar()
    p = from_enterprise(epsr)
    # full-fidelity passthrough: the enterprise design matrix and post-fit
    # residuals land untouched
    np.testing.assert_array_equal(p.Mmat, epsr.Mmat)
    np.testing.assert_array_equal(p.residuals, epsr.residuals)
    np.testing.assert_array_equal(p.toas, epsr.toas)
    np.testing.assert_array_equal(p.pos, epsr.pos)
    assert p.name == "J0000+0000"
    assert p.fitpars == epsr.fitpars
    assert p.backends() == ["430_ASP", "L-wide_PUPPI"]
    # 'pta' normalized to the scalar label the factory's ECORR gate reads
    assert p.flags["pta"] == "NANOGrav"
    # other flags stay per-TOA
    assert len(p.flags["fe"]) == p.ntoa

    # and the product is model-ready: factory + compile accept it, with the
    # NANOGrav flag enabling the ECORR branch under backend selection
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    pta = model_general([p], tm_svd=True, white_vary=True,
                        common_psd="spectrum", common_components=5,
                        select="backend")
    cm = compile_pta(pta)
    assert cm.P == 1
    assert any("ecorr" in nm for nm in pta.param_names)


def test_from_enterprise_rejects_mismatched_design_matrix():
    epsr = _FakeEnterprisePulsar()
    epsr.Mmat = epsr.Mmat[:-3]
    with pytest.raises(ValueError, match="does not match"):
        from_enterprise(epsr)
