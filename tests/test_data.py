import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.data import (
    design_matrix, fourier_basis, from_enterprise, get_tspan, load_directory,
    load_pulsar, parse_par, parse_tim,
)
from pulsar_timing_gibbsspec_tpu.data.simulate import inject_residuals, powerlaw_psd

REFDATA = "/root/reference/simulated_data"


def test_parse_par_j1713():
    par = parse_par(f"{REFDATA}/J1713+0747.par")
    assert par.name == "J1713+0747"
    assert par["F0"] == pytest.approx(218.811843786, rel=1e-9)
    assert "F0" in par.fitted and "F1" in par.fitted
    assert "PEPOCH" not in par.fitted        # no fit flag on epochs
    assert par.get("PB") == pytest.approx(67.825, rel=1e-3)


def test_parse_tim_j1713():
    tim = parse_tim(f"{REFDATA}/J1713+0747.tim")
    assert len(tim.mjds) == 720
    assert np.all(np.diff(tim.mjds) >= 0)
    assert tim.errs.min() > 1e-8 and tim.errs.max() < 1e-5   # ~0.1 us range
    assert tim.flags[0].get("f") == "test"


def test_design_matrix_full_rank():
    par = parse_par(f"{REFDATA}/J1713+0747.par")
    tim = parse_tim(f"{REFDATA}/J1713+0747.tim")
    M = design_matrix(par, tim)
    assert M.shape[0] == 720
    # at least offset + spin + astrometry terms
    assert M.shape[1] >= 7
    Mn = M / np.linalg.norm(M, axis=0)
    s = np.linalg.svd(Mn, compute_uv=False)
    assert s[-1] > 1e-10 * s[0]
    # quadratic spin-down partial must be in the span (F1 is fitted)
    t2 = ((tim.mjds - tim.mjds.mean()) * 86400.0) ** 2
    c, *_ = np.linalg.lstsq(M, t2, rcond=None)
    assert np.linalg.norm(t2 - M @ c) < 1e-8 * np.linalg.norm(t2)


def _write_nanograv_style(tmp_path):
    """Minimal real-format NANOGrav-style par/tim pair: DMX windows with
    DMXR1_/DMXR2_ bounds, flag- and MJD-form JUMPs, FD terms, dual-band
    dual-backend TOAs with -fe/-be flags."""
    par = tmp_path / "J0000+0000.par"
    par.write_text("\n".join([
        "PSRJ           J0000+0000",
        "RAJ            04:37:15.8 1",
        "DECJ           -47:15:09.1 1",
        "F0             173.6879 1 3e-12",
        "F1             -1.728e-15 1 1e-19",
        "PEPOCH         53700",
        "DM             2.64476 1",
        "DMX_0001       1.2e-3 1 1e-4",
        "DMXR1_0001     53000.0",
        "DMXR2_0001     53090.0",
        "DMX_0002       -0.8e-3 1 1e-4",
        "DMXR1_0002     53090.0",
        "DMXR2_0002     53180.0",
        "DMX_0003       0.1e-3 0 1e-4",          # unfitted: no column
        "DMXR1_0003     53180.0",
        "DMXR2_0003     53270.0",
        "FD1            1.0e-5 1",
        "FD2            -2.0e-6 1",
        "JUMP -be GUPPI 2.2e-6 0",               # unfitted: no column
        "JUMP -fe Rcvr_800 6.4e-6 1 1.2e-7",     # fitted + uncertainty
        "JUMP MJD 53100 53150 1.1e-6 1",
        "JUMP -fe L-wide 1",                     # offset "1", NO fit flag
    ]))
    rng = np.random.default_rng(3)
    mjds = np.sort(rng.uniform(53000, 53300, 240))
    lines = ["FORMAT 1"]
    # continuous in-band frequency spread, as real sub-banded NANOGrav
    # TOAs carry: on a few-point frequency grid DM (1/nu^2), FD1 (log nu),
    # FD2 (log^2 nu), the offset and any band-tied JUMP indicator are
    # exactly collinear — a real degeneracy _degenerate_keep would
    # (correctly) remove
    for i, m in enumerate(mjds):
        freq = (rng.uniform(1100.0, 1800.0) if i % 2 == 0
                else rng.uniform(700.0, 900.0))
        fe = "L-wide" if i % 2 == 0 else "Rcvr_800"
        be = "PUPPI" if (int(m / 30.0) % 2 == 0) else "GUPPI"
        lines.append(f"toa{i} {freq:.3f} {m:.12f} 1.5 ao "
                     f"-fe {fe} -be {be}")
    tim = tmp_path / "J0000+0000.tim"
    tim.write_text("\n".join(lines))
    return par, tim


def test_design_matrix_dmx_jump_fd(tmp_path):
    """A real-format NANOGrav par (DMX_/DMXR/JUMP/FD lines) must ingest
    with the same column structure tools/make_enterprise_snapshot.py
    hand-builds: windowed 1/nu^2 DMX columns, indicator JUMP columns,
    log-frequency FD columns — full rank alongside the base partials
    (r4 VERDICT missing #1: these previously ingested at reduced
    fidelity, silently)."""
    parf, timf = _write_nanograv_style(tmp_path)
    par = parse_par(parf)
    tim = parse_tim(timf)
    assert len(par.jumps) == 4
    assert "DMX_0001" in par.fitted and "DMX_0003" not in par.fitted

    M = design_matrix(par, tim)
    # base: offset, t, t^2, annual pair, DM = 6; + 2 DMX + 2 FD + 2 JUMP
    assert M.shape == (240, 12)
    Mn = M / np.linalg.norm(M, axis=0)
    s = np.linalg.svd(Mn, compute_uv=False)
    assert s[-1] > 1e-8 * s[0], "DMX/JUMP/FD columns must be independent"

    nu2 = (tim.freqs / 1400.0) ** 2
    # DMX column: 1/nu^2 inside its window, zero outside (fitted only)
    win1 = (tim.mjds >= 53000.0) & (tim.mjds <= 53090.0)
    dmx_expect = win1 / nu2
    assert any(np.allclose(M[:, j], dmx_expect) for j in range(M.shape[1]))
    win3 = (tim.mjds >= 53180.0) & (tim.mjds <= 53270.0)
    assert not any(np.allclose(M[:, j], win3 / nu2)
                   for j in range(M.shape[1]))
    # FD columns: log(nu/1GHz)^k
    lognu = np.log(tim.freqs / 1000.0)
    assert any(np.allclose(M[:, j], lognu) for j in range(M.shape[1]))
    assert any(np.allclose(M[:, j], lognu ** 2) for j in range(M.shape[1]))
    # JUMP columns: the fitted flag-form and MJD-form indicators, not the
    # unfitted -be one
    sel_fe = np.array([fl.get("fe") == "Rcvr_800" for fl in tim.flags],
                      float)
    assert any(np.allclose(M[:, j], sel_fe) for j in range(M.shape[1]))
    sel_mjd = ((tim.mjds >= 53100.0) & (tim.mjds <= 53150.0)).astype(float)
    assert any(np.allclose(M[:, j], sel_mjd) for j in range(M.shape[1]))
    sel_be = np.array([fl.get("be") == "GUPPI" for fl in tim.flags], float)
    assert not any(np.allclose(M[:, j], sel_be) for j in range(M.shape[1]))
    # 3-token jump whose OFFSET is literally "1" (no fit flag): no column
    sel_lw = np.array([fl.get("fe") == "L-wide" for fl in tim.flags], float)
    assert not any(np.allclose(M[:, j], sel_lw) for j in range(M.shape[1]))
    # labels number FITTED jumps: the unfitted -be GUPPI line comes first
    # in the par, so raw-index numbering would call these JUMP2/JUMP3
    _, labels = design_matrix(par, tim, return_labels=True)
    assert [l for l in labels if l.startswith("JUMP")] == ["JUMP1", "JUMP2"]

    # end-to-end: the pulsar loads and the full basis keeps rank
    psr = load_pulsar(parf, timf)
    assert psr.Mmat.shape == (240, 12)
    assert np.all(np.isfinite(psr.residuals))


def test_fourier_basis_interleaving():
    t = np.linspace(50000, 55000, 100)
    F, f = fourier_basis(t, nmodes=5, Tspan=5000 * 86400.0)
    assert F.shape == (100, 10)
    assert f[0] == f[1] == 1.0 / (5000 * 86400.0)
    # column 0 is sin, column 1 is cos of the same frequency
    arg = 2 * np.pi * t * 86400.0 * f[0]
    np.testing.assert_allclose(F[:, 0], np.sin(arg), atol=1e-12)
    np.testing.assert_allclose(F[:, 1], np.cos(arg), atol=1e-12)


def test_powerlaw_psd_scaling():
    f = np.array([1e-8, 2e-8])
    df = 1e-9
    p1 = powerlaw_psd(f, -14.0, 3.0, df)
    p2 = powerlaw_psd(f, -13.0, 3.0, df)
    np.testing.assert_allclose(p2 / p1, 100.0)      # A^2 scaling
    # steeper spectrum falls faster
    p3 = powerlaw_psd(f, -14.0, 5.0, df)
    assert p3[1] / p3[0] < p1[1] / p1[0]


def test_injection_deterministic_and_postfit(j1713):
    p2 = load_pulsar(
        f"{REFDATA}/J1713+0747.par", f"{REFDATA}/J1713+0747.tim",
        inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0, nmodes=30),
    )
    np.testing.assert_array_equal(j1713.residuals, p2.residuals)
    # post-fit: residuals orthogonal to the design matrix columns
    proj = j1713.Mmat.T @ j1713.residuals
    scale = np.linalg.norm(j1713.Mmat, axis=0) * np.linalg.norm(j1713.residuals)
    assert np.all(np.abs(proj) / scale < 1e-8)
    # red excess above the ~0.11us white level (post-fit projection absorbs
    # much of the lowest-frequency injected power, so the margin is modest)
    assert j1713.residuals.std() > 1.5 * j1713.toaerrs.mean()


def test_load_directory_and_tspan():
    psrs = load_directory(REFDATA, names={"J1713+0747", "B1855+09"})
    assert len(psrs) == 2
    ts = get_tspan(psrs)
    assert ts > 10 * 365.25 * 86400.0
    for p in psrs:
        assert p.ntoa == len(p.residuals) == len(p.toaerrs)
        assert p.backends() == ["test"]


class _FakeEnterprisePulsar:
    """Synthetic object exposing the enterprise Pulsar attribute surface
    (the reference's real-data loader, clean_demo.ipynb cells 3-5)."""

    def __init__(self, n=64, m=5, seed=7):
        rng = np.random.default_rng(seed)
        self.name = "J0000+0000"
        self.toas = np.sort(rng.uniform(0, 9.0 * 365.25 * 86400.0, n)) \
            + 53000.0 * 86400.0
        self.toaerrs = np.full(n, 5e-7)
        self.residuals = 1e-6 * rng.standard_normal(n)
        self.freqs = rng.choice([430.0, 1410.0], n)
        self.backend_flags = np.asarray(
            ["430_ASP" if f < 1000 else "L-wide_PUPPI" for f in self.freqs],
            dtype=object)
        self.Mmat = rng.standard_normal((n, m))
        self.fitpars = ["Offset", "F0", "F1", "RAJ", "DECJ"]
        # enterprise flags: per-TOA arrays keyed by flag name
        self.flags = {
            "pta": np.asarray(["NANOGrav"] * n, dtype=object),
            "fe": np.asarray(["430" if f < 1000 else "L-wide"
                              for f in self.freqs], dtype=object),
        }
        th, ph = 1.1, 2.2
        self.pos = np.array([np.sin(th) * np.cos(ph),
                             np.sin(th) * np.sin(ph), np.cos(th)])


def test_from_enterprise_adapter():
    epsr = _FakeEnterprisePulsar()
    p = from_enterprise(epsr)
    # full-fidelity passthrough: the enterprise design matrix and post-fit
    # residuals land untouched
    np.testing.assert_array_equal(p.Mmat, epsr.Mmat)
    np.testing.assert_array_equal(p.residuals, epsr.residuals)
    np.testing.assert_array_equal(p.toas, epsr.toas)
    np.testing.assert_array_equal(p.pos, epsr.pos)
    assert p.name == "J0000+0000"
    assert p.fitpars == epsr.fitpars
    assert p.backends() == ["430_ASP", "L-wide_PUPPI"]
    # 'pta' normalized to the scalar label the factory's ECORR gate reads
    assert p.flags["pta"] == "NANOGrav"
    # other flags stay per-TOA
    assert len(p.flags["fe"]) == p.ntoa

    # and the product is model-ready: factory + compile accept it, with the
    # NANOGrav flag enabling the ECORR branch under backend selection
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    pta = model_general([p], tm_svd=True, white_vary=True,
                        common_psd="spectrum", common_components=5,
                        select="backend")
    cm = compile_pta(pta)
    assert cm.P == 1
    assert any("ecorr" in nm for nm in pta.param_names)


def test_from_enterprise_rejects_mismatched_design_matrix():
    epsr = _FakeEnterprisePulsar()
    epsr.Mmat = epsr.Mmat[:-3]
    with pytest.raises(ValueError, match="does not match"):
        from_enterprise(epsr)
