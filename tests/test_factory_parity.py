"""model_general kwarg-surface parity: pshift/wgts, BayesEphem, red_select,
red_breakflat, infinitepower, freq_hd and the fixed-ORF menu, is_wideband.

The reference's ``model_general`` advertises these options
(``model_definition.py:36-170``); its committed body exercises only a
subset, and its samplers none of the correlated ones.  These tests pin
that the TPU framework both *builds* the advertised models and — where a
sampler block exists — samples them to finite, matched chains.
"""

import dataclasses

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.models.ephem import BayesEphemSignal
from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.models.orf import (orf_ginv_stack,
                                                    orf_matrix,
                                                    orf_matrix_per_freq)
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import (PTABlockGibbs,
                                                       PulsarBlockGibbs)

BASE = dict(tm_svd=True, common_psd="spectrum", common_components=5,
            red_var=False)


def test_pshift_deterministic_and_distinct(psrs8):
    """pshift randomizes the common-process Fourier phases per pulsar,
    deterministically for a fixed pseed (reference pshift/pseed kwargs)."""
    p1 = model_general(psrs8[:2], **BASE, pshift=True, pseed=7)
    p2 = model_general(psrs8[:2], **BASE, pshift=True, pseed=7)
    p3 = model_general(psrs8[:2], **BASE, pshift=True, pseed=8)
    p0 = model_general(psrs8[:2], **BASE)

    def gw_basis(pta, ii):
        m = pta.model(ii)
        s = next(s for s in m.signals if "gw" in s.name)
        return s.get_basis()

    np.testing.assert_array_equal(gw_basis(p1, 0), gw_basis(p2, 0))
    assert not np.allclose(gw_basis(p1, 0), gw_basis(p3, 0))
    assert not np.allclose(gw_basis(p1, 0), gw_basis(p0, 0))
    # distinct shifts per pulsar
    F0, F1 = gw_basis(p1, 0), gw_basis(p1, 1)
    assert F0.shape[1] == F1.shape[1]

    # the shift survives a wider red process donating the shared basis,
    # and red/GW stay share-consistent (same leading phases)
    kw = dict(tm_svd=True, common_psd="spectrum", common_components=5,
              red_var=True, red_psd="spectrum", red_components=10)
    ps_on = model_general(psrs8[:1], **kw, pshift=True, pseed=7)
    ps_off = model_general(psrs8[:1], **kw)
    m_on, m_off = ps_on.model(0), ps_off.model(0)
    Ton, Toff = m_on.get_basis(), m_off.get_basis()
    sl = m_on._slices[next(s.name for s in m_on.signals if "gw" in s.name)]
    assert not np.allclose(Ton[:, sl.start:sl.stop],
                           Toff[:, sl.start:sl.stop])
    gw_on = next(s for s in m_on.signals if "gw" in s.name)
    red_on = next(s for s in m_on.signals if "red" in s.name)
    np.testing.assert_allclose(gw_on.get_basis(),
                               red_on.get_basis()[:, :10])


def test_wgts_overrides_bin_widths(psrs8):
    w = np.full(5, 2e-9)
    pta = model_general(psrs8[:1], **BASE, wgts=w)
    s = next(s for s in pta.model(0).signals if "gw" in s.name)
    np.testing.assert_allclose(s._df, np.repeat(w**2, 2))


def test_bayesephem_basis_and_sampling(psrs8, tmp_path):
    """11 delay-partial columns with enterprise-matched prior variances,
    marginalized in the b-draw; the flagship config still samples to
    finite chains with the extra basis."""
    sig = BayesEphemSignal(psrs8[0].toas, psrs8[0].pos)
    T = sig.get_basis()
    assert T.shape == (psrs8[0].ntoa, 11)
    # sigma-scaled columns: unit prior variance each
    np.testing.assert_allclose(sig.get_phi({}), 1.0)
    # jupiter-mass column bounded by a_J * AU_SEC * sigma_IAU in delay
    assert np.all(np.abs(T[:, 1]) <= 5.21 * 499.1 * 1.55e-11)
    assert T[:, 1].std() > 0
    # every column is a sub-microsecond-scale delay partial at 1 sigma
    assert np.all(np.abs(T) < 5e-6)

    pta = model_general(psrs8[:1], **BASE, white_vary=True, bayesephem=True)
    x0 = pta.initial_sample(np.random.default_rng(0))
    g = PulsarBlockGibbs(pta, backend="jax", seed=3, progress=False)
    chain = g.sample(x0, outdir=str(tmp_path / "be"), niter=200)
    assert np.all(np.isfinite(chain))
    # the ephemeris coefficients are sampled in bchain (marginalized draw)
    m = pta.model(0)
    sl = m._slices["bayesephem"]
    bcols = g.bchain[50:, sl.start:sl.stop]
    assert np.all(np.isfinite(bcols)) and bcols.std() > 0


def test_be_type_validation(psrs8):
    with pytest.raises(ValueError):
        BayesEphemSignal(psrs8[0].toas, psrs8[0].pos, be_type="nope")
    for bt in ("orbel", "orbel-v2", "setIII", "setIII_1980"):
        model_general(psrs8[:1], **BASE, bayesephem=True, be_type=bt)


def test_red_select_band_split_samples(psrs8, tmp_path):
    """red_select='band' splits the intrinsic red process into masked
    per-band GPs whose hypers ride the adaptive MH block on both
    backends."""
    psr = dataclasses.replace(
        psrs8[0], freqs=np.where(
            np.random.default_rng(0).uniform(size=psrs8[0].ntoa) < 0.5,
            800.0, 1400.0))
    pta = model_general([psr], tm_svd=True, common_psd="spectrum",
                        common_components=5, red_var=True,
                        red_select="band")
    names = pta.param_names
    assert any("red_noise_low_log10_A" in n for n in names)
    assert any("red_noise_high_log10_A" in n for n in names)
    # masked bases are orthogonal across bands
    m = pta.model(0)
    lo = next(s for s in m.signals if "red_noise_low" in s.name)
    hi = next(s for s in m.signals if "red_noise_high" in s.name)
    assert np.allclose(lo.get_basis() * hi.get_basis(), 0.0)

    x0 = pta.initial_sample(np.random.default_rng(1))
    idx = BlockIndex.build(names)
    assert len(idx.red) >= 4          # 2 bands x (log10_A, gamma)
    for backend in ("numpy", "jax"):
        g = PulsarBlockGibbs(pta, backend=backend, seed=11, progress=False)
        chain = g.sample(x0, outdir=str(tmp_path / backend), niter=150)
        assert np.all(np.isfinite(chain))
        assert chain[50:, idx.red].std() > 0


def test_red_select_spectrum_rejected(psrs8):
    with pytest.raises(NotImplementedError):
        model_general(psrs8[:1], tm_svd=True, red_var=True,
                      red_psd="spectrum", red_select="band",
                      common_psd="spectrum", common_components=5)


def test_red_breakflat_psd(psrs8):
    """Device lnphi for powerlaw_breakflat matches the host PSD: flat
    above the break, powerlaw below."""
    from pulsar_timing_gibbsspec_tpu.models import psd as psdmod

    f = np.array([1e-9, 3e-9, 1e-8, 3e-8])
    df = np.full(4, 1e-9)
    host = psdmod.powerlaw_breakflat(f, df, -14.0, 4.0, np.log10(5e-9))
    plaw = psdmod.powerlaw(f, df, -14.0, 4.0)
    assert np.allclose(host[:2], plaw[:2])
    assert np.allclose(host[2:], psdmod.powerlaw(
        np.full(2, 5e-9), df[2:], -14.0, 4.0))

    pta = model_general(psrs8[:1], tm_svd=True, common_psd="spectrum",
                        common_components=5, red_var=True,
                        red_breakflat=True, red_breakflat_fq=5e-9)
    cm = compile_pta(pta)
    assert cm.red_kind == "powerlaw_breakflat"
    x = pta.initial_sample(np.random.default_rng(0))
    dev = np.asarray(cm.phi(x))
    hostphi = pta.get_phi(pta.map_params(x))[0]
    m = pta.model(0)
    sl = m._slices[f"{pta.pulsars[0]}_red_noise"]
    np.testing.assert_allclose(dev[0, sl.start:sl.stop],
                               hostphi[sl.start:sl.stop], rtol=1e-5)


def test_red_infinitepower_marginalizes(psrs8, tmp_path):
    pta = model_general(psrs8[:1], tm_svd=True, common_psd="spectrum",
                        common_components=5, red_var=True,
                        red_psd="infinitepower", red_components=5)
    cm = compile_pta(pta)
    assert cm.red_kind == "infinitepower"
    x = pta.initial_sample(np.random.default_rng(0))
    # red columns get the big marginalization variance on device and host
    dev = np.asarray(cm.phi(x))
    assert dev.max() >= 1e29
    g = PulsarBlockGibbs(pta, backend="jax", seed=5, progress=False)
    chain = g.sample(x, outdir=str(tmp_path / "ip"), niter=100)
    assert np.all(np.isfinite(chain))


def test_orf_menu_and_zero_diag():
    rng = np.random.default_rng(2)
    pos = [v / np.linalg.norm(v) for v in rng.standard_normal((6, 3))]
    for name in ("crn", "hd", "dipole", "monopole", "gw_monopole",
                 "gw_dipole", "st"):
        G = orf_matrix(name, pos)
        assert np.allclose(np.diag(G), 1.0)
        assert np.allclose(G, G.T)
    Z = orf_matrix("zero_diag_hd", pos)
    assert np.allclose(np.diag(Z), 0.0)
    with pytest.raises(NotImplementedError):
        orf_ginv_stack("zero_diag_hd", pos, 3)
    with pytest.raises(NotImplementedError):
        orf_matrix("bin_orf", pos)


def test_zero_diag_param_orf_builds(psrs8):
    """zero_diag_bin_orf / zero_diag_legendre_orf BUILD with the
    reference's fixed-common-amplitude branch (model_definition.py:202-205)
    — same sampled weight surface as their full counterparts — and only
    *sampling* loud-rejects (non-PD coefficient prior)."""
    pta = model_general(psrs8[:3], tm_svd=True, common_psd="powerlaw",
                        common_components=5, red_var=False,
                        orf="zero_diag_bin_orf", log10_A_common=-14.5)
    names = pta.param_names
    # the 7 angular-bin weights are sampled parameters
    assert sum("orfw_bin_" in n for n in names) == 7
    # the common amplitude is pinned (Constant), not sampled
    assert not any(n.endswith("gw_zero_diag_bin_orf_log10_A")
                   for n in names)
    x = pta.initial_sample(np.random.default_rng(0))
    assert np.all(np.isfinite(x))
    with pytest.raises(NotImplementedError, match="zero_diag"):
        compile_pta(pta)

    pta2 = model_general(psrs8[:3], tm_svd=True, common_psd="powerlaw",
                         common_components=5, red_var=False,
                         orf="zero_diag_legendre_orf", leg_lmax=3,
                         log10_A_common=-14.5)
    assert sum("orfw_leg_" in n for n in pta2.param_names) == 4
    with pytest.raises(NotImplementedError, match="zero_diag"):
        compile_pta(pta2)


def test_freq_hd_stack():
    rng = np.random.default_rng(3)
    pos = [v / np.linalg.norm(v) for v in rng.standard_normal((4, 3))]
    Gk = orf_matrix_per_freq("freq_hd", pos, 5, orf_ifreq=2)
    assert Gk.shape == (5, 4, 4)
    assert np.allclose(Gk[0], np.eye(4)) and np.allclose(Gk[1], np.eye(4))
    np.testing.assert_allclose(Gk[2], orf_matrix("hd", pos))


def test_freq_hd_sampling(psrs8, tmp_path):
    """freq_hd (CRN below bin orf_ifreq, HD above) runs end-to-end on
    both backends with matched means on the correlated bins."""
    pta = model_general(psrs8[:3], **BASE, orf="freq_hd", orf_ifreq=2)
    cm = compile_pta(pta)
    G = np.asarray(cm.orf_Ginv)
    assert G.shape[0] == cm.K
    assert np.allclose(G[0], np.eye(cm.P))
    assert not np.allclose(G[4], np.eye(cm.P))
    x0 = pta.initial_sample(np.random.default_rng(4))
    chains = {}
    for backend, seed in [("jax", 5), ("numpy", 6)]:
        g = PTABlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=1500)
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    idx = BlockIndex.build(pta.param_names)
    burn = 300
    for k in idx.rho:
        cj, cn = chains["jax"][burn:, k], chains["numpy"][burn:, k]
        assert np.all(np.isfinite(cj)) and np.all(np.isfinite(cn))
        ess_j = len(cj) / max(integrated_act(cj), 1.0)
        ess_n = len(cn) / max(integrated_act(cn), 1.0)
        z = abs(cj.mean() - cn.mean()) / np.sqrt(
            cj.var() / ess_j + cn.var() / ess_n)
        assert z < 4.0, (k, z, ess_j, ess_n)


def test_is_wideband_excludes_ecorr(psrs8):
    psr = dataclasses.replace(psrs8[0], flags={"pta": "NANOGrav"})
    with_ec = model_general([psr], **BASE, white_vary=True)
    without = model_general([psr], **BASE, white_vary=True,
                            is_wideband=True)
    assert any("ecorr" in n for n in with_ec.param_names)
    assert not any("ecorr" in n for n in without.param_names)
