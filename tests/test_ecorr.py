"""End-to-end ECORR validation.

The reference's ECORR Gibbs update is disabled with a "NEEDS TO BE FIXED"
note (``pulsar_gibbs.py:409-486``) and its simulated corpus carries no
NANOGrav pta flags, so the block is never even constructed there.  Here a
NANOGrav-flagged synthetic pulsar with epoched TOAs exercises the complete
path: model construction gates ECORR on the flag
(``model_definition.py:221-223`` behavior), the oracle block matches a
closed-form conditional posterior, and the device backend's chains
KS-match the oracle's.
"""

import numpy as np
import pytest
from scipy import stats

from pulsar_timing_gibbsspec_tpu.data.dataset import Pulsar
from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs
from pulsar_timing_gibbsspec_tpu.sampler.numpy_backend import NumpyGibbs

DAY = 86400.0


@pytest.fixture(scope="module")
def nanograv_psr():
    """Synthetic NANOGrav-flagged pulsar with clustered observing epochs
    (60 epochs x 6 TOAs) and a known injected ECORR: per-epoch fully
    correlated offsets of sd 10^-6.3 s on top of white measurement noise."""
    rng = np.random.default_rng(7)
    n_epochs, per_epoch = 60, 6
    span = 10.0 * 365.25 * DAY
    centers = np.sort(rng.uniform(0.0, span, n_epochs)) + 53000.0 * DAY
    toas = np.concatenate([
        c + rng.uniform(0, 0.2 * DAY, per_epoch) for c in centers])
    order = np.argsort(toas)
    toas = toas[order]
    epoch_of = np.repeat(np.arange(n_epochs), per_epoch)[order]
    n = len(toas)
    # heterogeneous uncertainties: with constant errs the EFAC/EQUAD pair
    # is perfectly degenerate (efac^2 sigma^2 + 10^2q constant along a
    # ridge) and backend marginals can legitimately settle at different
    # ridge ends; a spread in sigma identifies both parameters
    errs = rng.uniform(2e-7, 9e-7, n)
    log10_ecorr_true = -6.3
    epoch_offsets = 10.0 ** log10_ecorr_true * rng.standard_normal(n_epochs)
    res = errs * rng.standard_normal(n) + epoch_offsets[epoch_of]
    t = (toas - toas.mean()) / span
    M = np.column_stack([np.ones(n), t, t * t])
    return Pulsar(
        name="FAKE_NG", toas=toas, toaerrs=errs, residuals=res,
        freqs=np.full(n, 1400.0),
        backend_flags=np.asarray(["sim"] * n, dtype=object),
        Mmat=M, fitpars=["offset", "F0", "F1"],
        flags={"pta": "NANOGrav"},
        pos=np.array([1.0, 0.0, 0.0]))


def _model(psr, white_vary=True):
    return model_general([psr], tm_svd=True, red_var=False,
                         white_vary=white_vary, common_psd="spectrum",
                         common_components=5)


def test_ecorr_constructed_only_with_flag(nanograv_psr):
    pta = _model(nanograv_psr)
    assert any("ecorr" in n for n in pta.param_names)
    import dataclasses

    unflagged = dataclasses.replace(nanograv_psr, flags={"pta": ""})
    pta2 = _model(unflagged)
    assert not any("ecorr" in n for n in pta2.param_names)


def test_ecorr_block_closed_form(nanograv_psr):
    """Conditioned on fixed basis coefficients b_j ~ the ECORR columns,
    the log10_ecorr conditional is analytic:
    ``p(e | b) ~ exp(-J ln10 e - S 10^(-2e) / 2)`` with ``S = sum b_j^2``
    (uniform prior) — the oracle MH block must reproduce its moments."""
    pta = _model(nanograv_psr)
    g = NumpyGibbs(pta, white_adapt_iters=600, seed=11)
    rng = np.random.default_rng(3)
    x = pta.initial_sample(rng)
    iec = pta.param_names.index("FAKE_NG_sim_log10_ecorr")

    # fix b: zeros except known ECORR coefficients
    g.b = np.zeros_like(g.b)
    true_e = -6.3
    bvals = 10.0 ** true_e * rng.standard_normal(len(g.ecid))
    g.b[g.ecid] = bvals
    J, S = len(bvals), float(np.sum(bvals ** 2))

    x = g.update_ecorr(x, adapt=True)
    chain = []
    for _ in range(4000):
        x = g.update_ecorr(x)
        chain.append(x[iec])
    chain = np.asarray(chain[500:])

    egrid = np.linspace(-8.5, -5.0, 4000)
    logp = -J * np.log(10.0) * egrid - 0.5 * S * 10.0 ** (-2.0 * egrid)
    p = np.exp(logp - logp.max())
    p /= np.trapezoid(p, egrid)
    mean_exact = np.trapezoid(egrid * p, egrid)
    sd_exact = np.sqrt(np.trapezoid((egrid - mean_exact) ** 2 * p, egrid))

    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    neff = len(chain) / max(integrated_act(chain), 1.0)
    assert abs(chain.mean() - mean_exact) < 5 * sd_exact / np.sqrt(neff), (
        chain.mean(), mean_exact, sd_exact, neff)
    assert 0.7 < chain.std() / sd_exact < 1.4
    # and the posterior actually concentrates near the truth
    assert abs(mean_exact - true_e) < 0.2


def test_ecorr_jax_vs_numpy_ks(nanograv_psr, tmp_path):
    """Full-chain statistical equivalence with the ECORR block active on
    both backends — the coverage VERDICT r1 flagged as absent."""
    pta = _model(nanograv_psr)
    x0 = pta.initial_sample(np.random.default_rng(19))
    chains = {}
    for backend, seed in [("jax", 21), ("numpy", 22)]:
        g = PulsarBlockGibbs(pta, backend=backend, seed=seed, progress=False,
                             white_adapt_iters=600)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2600)
    burn, thin = 400, 10
    idx = BlockIndex.build(pta.param_names)
    cols = list(idx.ecorr) + list(idx.white) + list(idx.rho[:2])
    pvals = [stats.ks_2samp(chains["jax"][burn::thin, k],
                            chains["numpy"][burn::thin, k]).pvalue
             for k in cols]
    # the ECORR chain must mix, not freeze
    for k in idx.ecorr:
        assert np.std(chains["jax"][burn:, k]) > 1e-3
    assert min(pvals) > 1e-4, pvals
    assert np.median(pvals) > 0.05, pvals
    # posterior localizes near the injected ECORR on both backends
    for be in ("jax", "numpy"):
        med = np.median(chains[be][burn:, idx.ecorr[0]])
        assert abs(med - (-6.3)) < 0.35, (be, med)
