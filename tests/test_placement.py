"""Placement-engine contracts (serve/placement.py + the service's
per-slice scheduler).

The load-bearing claims, each tested end-to-end on tiny synthetics:

- slice geometry: an explicit layout carves disjoint chain-submesh
  slices; per-slice slot widths the chain sub-axis cannot split refuse
  with a typed :class:`PlacementError` naming the slice, the required
  multiple and the nearest legal slot count;
- TWO ``(bucket, signature)`` groups with different chain counts AND
  different slot widths sample CONCURRENTLY on their own slices —
  deterministic across incarnations, ULP-close to unplaced solos
  (GSPMD reduction regrouping, same class as the single-group mesh
  contract), with zero unplanned serve-phase retraces;
- a slice-attributed device loss evacuates and re-places ONLY the
  victim slice's group (survivors bitwise, not retraced); a second
  loss inside ``replace_window`` trips the capped re-place budget with
  a typed terminal report while co-resident groups keep sampling;
- split/merge rebalancing drains residents through verified
  checkpoints first and the drained jobs replay bit-exactly;
- predictive pre-warming compiles a queued-but-unplaceable bucket
  under its hard cap, so the group admits warm when a slice frees;
- the slice-labeled ``serve_slice_*`` gauges ride the Prometheus
  exposition with parseable (escaped) label values;
- a gateway restart with TWO groups journaled re-routes each group to
  its own slice and finishes both.
"""

import json

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.serve.buckets import BucketSpec, BucketTable
from pulsar_timing_gibbsspec_tpu.serve.placement import (PlacementEngine,
                                                         PlacementError)

NITER = 8


def _mk(ntoa, seed, nmodes=3):
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    return build_model(synthetic_pulsars(2, ntoa, tm_cols=3, seed=seed),
                       nmodes)


_CACHE = None


def _service(root, table, **kw):
    """Fresh service sharing the module-wide program cache so the
    suite compiles each (bucket, width) once, not per service."""
    global _CACHE
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache, SamplerService

    if _CACHE is None:
        _CACHE = ProgramCache()
    kw.setdefault("cache", _CACHE)
    kw.setdefault("chunk", 4)
    kw.setdefault("quantum", 100)
    kw.setdefault("save_every", 1)
    return SamplerService(root, table, **kw)


@pytest.fixture(scope="module")
def table2():
    return BucketTable([BucketSpec(2, 40, 24, 3),
                        BucketSpec(2, 48, 24, 3)])


@pytest.fixture(scope="module")
def group_ptas():
    """Group A (bucket 40): tenants 0-1.  Group B (bucket 48):
    tenants 2-4 — strictly past bucket 40 so smallest-cover routing
    keeps the groups apart."""
    return ([_mk(24, 0), _mk(30, 1)],
            [_mk(44, 2), _mk(46, 3), _mk(48, 4)])


@pytest.fixture(scope="module")
def solo_chains(group_ptas, table2, tmp_path_factory):
    """Uninterrupted solo baselines, tenant_id = index, in the
    two-slice UNPLACED geometry (unplaced runs are bitwise regardless
    of slot/placement geometry, so these are exact references for
    every unplaced drill below and ULP references for the mesh one)."""
    base = tmp_path_factory.mktemp("placement_solo")
    out = []
    for i, pta in enumerate(group_ptas[0] + group_ptas[1]):
        svc = _service(base / f"s{i}", table2,
                       placement=[{"slots": 2}, {"slots": 2}])
        job = svc.submit(pta, NITER, job_id=f"solo{i}", tenant_id=i)
        svc.run()
        assert job.state == "done"
        out.append((job.chain.copy(), job.bchain.copy()))
    return out


def _submit_groups(svc, group_ptas, niter=NITER, nb=None):
    """Group A first (claims slice 0), then group B (claims slice 1)."""
    ptas_a, ptas_b = group_ptas
    jobs = [svc.submit(p, niter, job_id=f"a{i}", tenant_id=i)
            for i, p in enumerate(ptas_a)]
    for i, p in enumerate(ptas_b[:nb] if nb else ptas_b):
        jobs.append(svc.submit(p, niter, job_id=f"b{i}",
                               tenant_id=len(ptas_a) + i))
    return jobs


# -- geometry and typed refusals -------------------------------------------

def test_engine_carves_disjoint_fault_domains():
    """An explicit layout carves consecutive chain spans into
    standalone submeshes sharing NO devices, validates per-slice, and
    refuses spans past the chain axis."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh

    mesh = make_mesh((4, 2))
    eng = PlacementEngine(mesh, layout=[{"slots": 2, "chains": 1},
                                        {"slots": 3, "chains": 3}])
    assert eng.total_slots == 5
    ids = [set(d.id for d in sl.mesh.devices.flat) for sl in eng.slices]
    assert ids[0] & ids[1] == set()
    assert len(ids[0] | ids[1]) == 8
    rows = [r["chain_rows"] for r in eng.report()]
    assert rows == [[0, 1], [1, 4]]
    with pytest.raises(PlacementError, match="exceeds the mesh"):
        PlacementEngine(mesh, layout=[{"slots": 3, "chains": 3},
                                      {"slots": 2, "chains": 2}])
    with pytest.raises(PlacementError, match="empty"):
        PlacementEngine(mesh, layout=[])
    # split/merge guardrails: unknown ids, non-adjacency
    with pytest.raises(PlacementError, match="unknown slice"):
        eng.split_slice(99)
    eng2 = PlacementEngine(None, layout=[{"slots": 2}, {"slots": 2},
                                         {"slots": 2}])
    a, _, c = eng2.slices
    with pytest.raises(PlacementError, match="not adjacent"):
        eng2.merge_slices(a.slice_id, c.slice_id)


def test_divisibility_refusal_is_typed(table2, tmp_path):
    """A per-slice slot width the slice's chain sub-axis cannot split
    refuses at the SERVICE boundary with the historical "multiple of N"
    message, and the typed error carries the slice, the required
    multiple and the nearest legal slot count (satellite: the old
    global slots-vs-mesh check misfired for per-group slices)."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh
    from pulsar_timing_gibbsspec_tpu.serve import SamplerService

    mesh = make_mesh((4, 2))
    with pytest.raises(PlacementError, match="multiple of 2") as ei:
        SamplerService(tmp_path / "bad", table2, mesh=mesh,
                       placement=[{"slots": 2, "chains": 2},
                                  {"slots": 3, "chains": 2}])
    assert ei.value.slice_id == 1
    assert ei.value.required_multiple == 2
    assert ei.value.nearest == 4
    assert isinstance(ei.value, ValueError)      # historical contract


# -- concurrent groups on mesh slices --------------------------------------

def test_two_groups_concurrent_on_mesh_slices(group_ptas, table2,
                                              solo_chains, tmp_path):
    """The acceptance drill: two groups with different buckets AND
    different chain counts (1 vs 3 chain rows) and slot widths (2 vs 3)
    resident CONCURRENTLY on disjoint slices of a (4, 2) mesh.  Two
    incarnations are bitwise identical; vs the unplaced solos the
    chains agree at the f64 reduction-order class (same bar as the
    single-group mesh contract); zero unplanned serve retraces."""
    from pulsar_timing_gibbsspec_tpu.parallel.sharding import make_mesh
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter

    mesh = make_mesh((4, 2))
    layout = [{"slots": 2, "chains": 1}, {"slots": 3, "chains": 3}]

    def run(root):
        svc = _service(tmp_path / root, table2, mesh=mesh,
                       placement=layout)
        jobs = _submit_groups(svc, group_ptas)
        report = svc.run()
        return report, jobs, [j.chain.copy() for j in jobs]

    with recompile_counter() as rc:
        rc.phase("serve")
        report, jobs, chains = run("mesh_a")
        _, _, chains_b = run("mesh_b")
    assert rc.unplanned("serve") == 0
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(chains[i], chains_b[i])
        scale = np.abs(solo_chains[i][0]).max()
        assert np.abs(chains[i] - solo_chains[i][0]).max() < 1e-12 * scale
    pl = report["placement"]
    assert pl["max_concurrent_groups"] >= 2
    assert [s["chains"] for s in pl["slices"]] == [1, 3]
    assert sorted(tuple(s["group"]) for s in pl["slices"]
                  if s["group"]) == []            # drained at the end
    assert all(s["chunks"] > 0 for s in pl["slices"])


# -- fault domains ---------------------------------------------------------

def test_slice_loss_evacuates_victim_only(group_ptas, table2,
                                          solo_chains, tmp_path):
    """A slice-attributed device loss re-places ONLY the victim
    slice's group: every job still finishes, every chain is bitwise vs
    its solo, the survivor slice records zero losses and nothing
    retraces (satellite: evacuate→placement reuse)."""
    from pulsar_timing_gibbsspec_tpu.profiling import recompile_counter
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    svc = _service(tmp_path / "loss", table2,
                   placement=[{"slots": 2}, {"slots": 2}])
    faults.clear()
    faults.inject("device_loss", point="serve.chunk", at_row=2, times=1,
                  slice=0)
    try:
        with recompile_counter() as rc:
            rc.phase("serve")
            jobs = _submit_groups(svc, group_ptas, nb=2)
            report = svc.run()
    finally:
        faults.clear()
    assert rc.unplanned("serve") == 0            # survivor not retraced
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])
        np.testing.assert_array_equal(job.bchain, solo_chains[i][1])
    assert report["evacuations"] == 1
    losses = {s["slice"]: s["losses"] for s in report["placement"]["slices"]}
    assert losses == {0: 1, 1: 0}


def test_replace_budget_trips_typed_terminal(group_ptas, table2,
                                             solo_chains, tmp_path):
    """A second loss on the same slice inside ``replace_window`` trips
    the capped re-place budget: the victim slice parks ``failed`` with
    a typed terminal report and its jobs park ``failed`` with verified
    checkpoints intact, while the co-resident group finishes bitwise."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults

    niter = 24
    base = tmp_path / "budget"
    refs = []
    for i, pta in enumerate(group_ptas[1][:2]):
        s = _service(base / f"solo{i}", table2,
                     placement=[{"slots": 2}, {"slots": 2}])
        j = s.submit(pta, niter, job_id=f"solo{i}", tenant_id=2 + i)
        s.run()
        refs.append(j.chain.copy())

    svc = _service(base / "svc", table2,
                   placement=[{"slots": 2}, {"slots": 2}],
                   clock=lambda: 0.0)            # losses never age out
    faults.clear()
    faults.inject("device_loss", point="serve.chunk", at_row=3, times=1,
                  slice=0)
    faults.inject("device_loss", point="serve.chunk", at_row=7, times=1,
                  slice=0)
    jobs = _submit_groups(svc, group_ptas, niter=niter, nb=2)
    try:
        with pytest.raises(PlacementError,
                           match="re-place budget exhausted") as ei:
            svc.run()
    finally:
        faults.clear()
    assert ei.value.slice_id == 0
    victims = jobs[:2]
    for job in victims:
        assert job.state == "failed"
        assert "re-place budget exhausted" in job.failure
    states = {s["slice"]: s["state"] for s in svc.report()["placement"]
              ["slices"]}
    assert states[0] == "failed"
    # the surviving fault domain picks up where the raise left it
    report = svc.run()
    for i, job in enumerate(jobs[2:]):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, refs[i])
    assert report["placement"]["slices"][1]["losses"] == 0


# -- rebalancing -----------------------------------------------------------

def test_split_merge_through_verified_checkpoints(group_ptas, table2,
                                                  solo_chains, tmp_path):
    """Mid-run split drains the residents through verified checkpoints
    BEFORE the geometry mutates; they re-admit onto the new slices and
    replay bit-exactly.  Merging the (empty) pair restores one slice."""
    svc = _service(tmp_path / "rebal", table2,
                   placement=[{"slots": 4}])
    jobs = _submit_groups(svc, group_ptas, nb=0)     # group A only
    assert svc.step()                # chunk 1 of 2: residents mid-run
    assert any(j is not None
               for j in svc._engine.slices[0].residents)
    parts = svc.split_slice(0)
    assert len(svc._engine.slices) == 2
    assert [p.slots for p in parts] == [2, 2]
    assert svc.slots == 4
    svc.run()
    for i, job in enumerate(jobs):
        assert job.state == "done"
        np.testing.assert_array_equal(job.chain, solo_chains[i][0])
    merged = svc.merge_slices(parts[0].slice_id, parts[1].slice_id)
    assert len(svc._engine.slices) == 1
    assert merged.slots == 4


# -- predictive pre-warming ------------------------------------------------

def test_prewarm_compiles_waiting_bucket_under_cap(group_ptas, table2,
                                                   tmp_path):
    """With every slot held by group A, a queued group-B job cannot
    place; the warmth gauges (cold cache → ``warm_hit_rate`` < 1) pick
    its bucket for a PLANNED pre-warm compile, so B admits with zero
    misses when the slice frees.  The hard cap holds (one outstanding
    prewarm bucket)."""
    from pulsar_timing_gibbsspec_tpu.serve import ProgramCache

    svc = _service(tmp_path / "prewarm", table2,
                   cache=ProgramCache(),         # cold on purpose
                   placement=[{"slots": 2}], prewarm=1)
    jobs = _submit_groups(svc, group_ptas, nb=1)
    report = svc.run()
    assert all(j.state == "done" for j in jobs)
    pl = report["placement"]
    assert pl["prewarms"] == 1
    bucket_b = str(tuple(BucketSpec(2, 48, 24, 3).as_tuple()))
    assert pl["groups"][bucket_b]["misses"] == 0
    assert pl["groups"][bucket_b]["warm_hit_rate"] == 1.0


# -- observability ---------------------------------------------------------

def test_slice_gauges_ride_prometheus_with_labels(group_ptas, table2,
                                                  tmp_path):
    """The per-slice fault-domain series are slice-labeled in the
    Prometheus exposition and parse cleanly back through
    ``metrics.split_key`` — the same escaped-label path the hostile
    tenant-name series travel (PR 17)."""
    from pulsar_timing_gibbsspec_tpu.obs import metrics
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry

    telemetry.reset()
    svc = _service(tmp_path / "gauges", table2,
                   placement=[{"slots": 2}, {"slots": 2}])
    jobs = _submit_groups(svc, group_ptas, nb=2)
    svc.run()
    assert all(j.state == "done" for j in jobs)
    body = svc.prometheus()
    seen = {}
    for line in body.splitlines():
        if not line.startswith("ptgibbs_serve_slice_"):
            continue
        name, labels = metrics.split_key(line.rsplit(" ", 1)[0]
                                         .removeprefix("ptgibbs_"))
        seen.setdefault(name, set()).add(labels["slice"])
    for fam in ("serve_slice_residents", "serve_slice_chunks",
                "serve_slice_losses"):
        assert seen[fam] == {"0", "1"}
    telemetry.reset()


# -- gateway restart with two groups journaled -----------------------------

def test_gateway_restart_readmits_two_groups_to_own_slices(table2,
                                                           tmp_path):
    """Satellite: the ``_readmit`` path under multi-group placement.
    Two journaled jobs of DIFFERENT buckets re-materialize on restart
    and route each to its own slice (routing is by group key — there
    is no global active group to misroute to), both finishing."""
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    svc_kw = dict(chunk=4, quantum=100, save_every=1,
                  placement=[{"slots": 2}, {"slots": 2}])
    gw = Gateway(tmp_path / "gw", table2, svc_kw=svc_kw)
    for key, ntoa, seed in (("ga", 24, 0), ("gb", 44, 2)):
        body = json.dumps({
            "dedupe_key": key, "niter": NITER,
            "payload": {"synthetic": {
                "n_psr": 2, "ntoa": ntoa, "tm_cols": 3, "seed": seed,
                "nmodes": 3}}}).encode()
        resp = gw.handle(WireRequest("POST", "/v1/jobs", {}, {}, body))
        assert resp.status == 200
    # never started: both entries journaled active — the restart sees
    # only the journal, exactly the crashed-scheduler window
    gw2 = Gateway(tmp_path / "gw", table2, svc_kw=svc_kw,
                  stop_when_idle=True)
    assert len(gw2.svc.jobs) == 2                # both re-materialized
    gw2.start()
    gw2.join(timeout=300)
    ents = gw2.report()["entries"]
    assert {e["state"] for e in ents.values()} == {"done"}
    pl = gw2.report()["service"]["placement"]
    assert pl["max_concurrent_groups"] >= 2
    assert len(pl["groups"]) == 2
