import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.models import model_general
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs, PTABlockGibbs


def test_single_pulsar_numpy_run_and_resume(j1713, tmp_path):
    pta = model_general([j1713], red_var=False, white_vary=True,
                        common_psd="spectrum", common_components=10)
    g = PulsarBlockGibbs(pta, backend="numpy", seed=99, progress=False,
                         white_adapt_iters=300)
    x0 = g.initial_sample(np.random.default_rng(1))
    out = tmp_path / "chains"
    g.sample(x0, outdir=out, niter=60, resume=False, save_every=20)

    chain = np.load(out / "chain.npy")
    bchain = np.load(out / "bchain.npy")
    assert chain.shape == (60, len(g.param_names))
    assert bchain.shape[1] == pta.get_basis()[0].shape[1]
    names = (out / "pars_chain.txt").read_text().split()
    assert names == g.param_names
    bnames = (out / "pars_bchain.txt").read_text().split()
    assert len(bnames) == bchain.shape[1]
    assert (out / "adapt.npz").exists()

    # resume continues without re-adaptation and extends the chain
    g2 = PulsarBlockGibbs(pta, backend="numpy", seed=7, progress=False,
                          white_adapt_iters=300)
    g2.sample(x0, outdir=out, niter=100, resume=True, save_every=20)
    chain2 = np.load(out / "chain.npy")
    assert chain2.shape[0] == 100
    np.testing.assert_array_equal(chain2[:60], chain)

    # resume without adaptation state must fail loudly, not re-adapt silently
    (out / "adapt.npz").unlink()
    g3 = PulsarBlockGibbs(pta, backend="numpy", seed=7, progress=False)
    with pytest.raises(RuntimeError, match="adapt.npz"):
        g3.sample(x0, outdir=out, niter=120, resume=True)


def test_resume_bitwise_equals_uninterrupted(j1713, tmp_path):
    """A run interrupted at 30/60 and resumed must reproduce the
    uninterrupted 60-sweep chain exactly (same RNG stream, same states) —
    the guarantee the reference loses by not checkpointing adaptation
    (SURVEY §5)."""
    pta = model_general([j1713], red_var=False, white_vary=True,
                        common_psd="spectrum", common_components=8)
    x0 = pta.initial_sample(np.random.default_rng(4))

    g_full = PulsarBlockGibbs(pta, backend="numpy", seed=77, progress=False,
                              white_adapt_iters=200)
    g_full.sample(x0, outdir=tmp_path / "full", niter=60, save_every=30)

    g_a = PulsarBlockGibbs(pta, backend="numpy", seed=77, progress=False,
                           white_adapt_iters=200)
    g_a.sample(x0, outdir=tmp_path / "split", niter=30, save_every=30)
    g_b = PulsarBlockGibbs(pta, backend="numpy", seed=123, progress=False,
                           white_adapt_iters=200)   # seed ignored on resume
    g_b.sample(x0, outdir=tmp_path / "split", niter=60, resume=True,
               save_every=30)

    np.testing.assert_array_equal(g_b.chain, g_full.chain)
    np.testing.assert_array_equal(g_b.bchain, g_full.bchain)


def test_pta_numpy_common_spectrum(psrs8, tmp_path):
    psrs = psrs8[:3]
    pta = model_general(psrs, red_var=False, white_vary=False,
                        common_psd="spectrum", common_components=8)
    g = PTABlockGibbs(pta, backend="numpy", seed=3, progress=False)
    x0 = g.initial_sample(np.random.default_rng(5))
    assert len(x0) == 8          # only the common rho vector
    g.sample(x0, outdir=tmp_path / "c", niter=40, resume=False, save_every=40)
    chain = g.chain
    assert chain.shape == (40, 8)
    assert np.all(np.isfinite(chain))
    # all draws inside the prior bounds
    assert chain[5:].min() >= -10.0 and chain.max() <= -4.0
    # b chains recorded for every pulsar
    assert g.bchain.shape[1] == sum(T.shape[1] for T in pta.get_basis())


def test_pta_common_rho_couples_pulsars(psrs8):
    """The common-rho conditional must depend on every pulsar's coefficients
    (the product/psum coupling, reference pta_gibbs.py:205)."""
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    psrs = psrs8[:2]
    pta = model_general(psrs, red_var=False, white_vary=False,
                        common_psd="spectrum", common_components=4)
    g = NumpyPTAGibbs(pta, seed=0)
    x = pta.initial_sample(np.random.default_rng(0))
    for ii in range(g.P):
        g.b[ii] = np.full_like(g.b[ii], 1e-7)

    draws_small = np.array([g.update_rho(x)[g.idx.rho] for _ in range(400)])
    # crank up pulsar 1's GW coefficients only -> rho posterior must move up
    g.b[1][g.gwid[1]] = 3e-6
    draws_big = np.array([g.update_rho(x)[g.idx.rho] for _ in range(400)])
    assert draws_big.mean() > draws_small.mean() + 0.2


def test_hdf5_export_roundtrip(j1713, tmp_path):
    """sample(hdf5=True) writes the la-forge-friendly chain.h5 the
    reference leaves as a TODO (pulsar_gibbs.py:707-708); contents match
    the canonical npy chains."""
    h5py = pytest.importorskip("h5py")
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs

    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5)
    g = PulsarBlockGibbs(pta, backend="numpy", seed=1, progress=False)
    chain = g.sample(pta.initial_sample(np.random.default_rng(0)),
                     outdir=str(tmp_path / "h5"), niter=40, hdf5=True)
    with h5py.File(tmp_path / "h5" / "chain.h5") as fh:
        np.testing.assert_array_equal(fh["chain"][...], chain)
        assert fh["bchain"].shape[0] == 40
        assert [s.decode() for s in fh["params"][...]] == pta.param_names
        assert fh.attrs["niter"] == 40
        assert fh.attrs["backend"] == "numpy"
