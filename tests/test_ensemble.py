"""Ensemble mixing engine (sampler/ensemble.py): correctness gates.

Tier-1 (fast) coverage: the stretch kernel's detailed balance on a
known 2-d Gaussian, the applicability/validation gates (HD models,
ladder/walker factorization, the multiplexed service boundary), and
the ensemble-off bitwise-identity contract — the default driver and an
explicit ``ensemble=False`` driver must produce byte-identical chains
(the stage is gated in Python, so the off program is HEAD's program;
contracts/crn_2d_mesh.json pins the same claim at the lowering level).

Slow-marked coverage (``-m slow``): KS/law parity of the ensemble-on
posterior against the plain sweep on the single-pulsar and 3-pulsar
CRN fixtures, tempering-ladder adaptation toward the ~23% swap target,
and bitwise resume with the ensemble carry on the 1-d and (2, 4)
meshes via ``runtime.integrity.reshard_restore``.
"""

import shutil

import numpy as np
import pytest
from scipy import stats

from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.ensemble import (
    EnsembleSpec, ensemble_applies, stretch_halves, validate_ensemble)
from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

NITER = 18


def _crn_pta(n_psr=3, ntoa=40, nmodes=3):
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    return build_model(synthetic_pulsars(n_psr, ntoa, tm_cols=3, seed=0),
                       nmodes)


def _run(pta, x0, niter=NITER, seed=7, nchains=2, chunk_size=6, **kw):
    drv = JaxGibbsDriver(pta, seed=seed, common_rho=True, nchains=nchains,
                         chunk_size=chunk_size, warmup_sweeps=4,
                         white_adapt_iters=4, **kw)
    cshape, bshape = drv.chain_shapes(niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    for _ in drv.run(x0, chain, bchain, 0, niter):
        pass
    return chain, drv


# ---------------------------------------------------------------------------
# stretch kernel: detailed balance on a known target
# ---------------------------------------------------------------------------

def test_stretch_detailed_balance_gaussian():
    """The Goodman-Weare stretch sweep must leave a 2-d standard
    Gaussian invariant: correct affine-invariance Jacobian z^(d-1),
    complementary-half pairing, and no PRNG reuse between the partner /
    z / accept draws.  The bug class this guards (a bounds or Jacobian
    error) collapses acceptance to ~0 or skews the variance far outside
    these bands."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    def logpdf(c, lo):
        return -0.5 * jnp.sum(c * c, axis=-1)

    W, G, d = 8, 2, 2
    key = jr.key(3)
    coords = jr.normal(jr.fold_in(key, 999), (W, G, d))

    @jax.jit
    def sweep(coords, k):
        return stretch_halves(logpdf, coords, k, a=2.0)

    nsweep, burn = 600, 100
    samples, acc = [], 0.0
    for t in range(nsweep):
        coords, na = sweep(coords, jr.fold_in(key, t))
        acc += float(jnp.sum(na))
        if t >= burn:
            samples.append(np.asarray(coords))
    rate = acc / (nsweep * W * G)
    s = np.concatenate(samples, 0).reshape(-1, d)
    assert 0.45 < rate < 0.90, rate
    assert np.all(np.abs(s.mean(0)) < 0.25), s.mean(0)
    assert np.all((s.var(0) > 0.70) & (s.var(0) < 1.30)), s.var(0)
    assert abs(np.cov(s.T)[0, 1]) < 0.30


# ---------------------------------------------------------------------------
# applicability / validation / service gates
# ---------------------------------------------------------------------------

def test_ensemble_gates_and_validation(synth_hd_pta):
    # factorization: ladder must tile the chain batch, walkers per rung
    # even >= 2
    validate_ensemble(EnsembleSpec(n_temps=2), 8)
    with pytest.raises(ValueError, match="not a multiple"):
        validate_ensemble(EnsembleSpec(n_temps=3), 8)
    with pytest.raises(ValueError, match="even number"):
        validate_ensemble(EnsembleSpec(n_temps=2), 6)
    with pytest.raises(ValueError, match="pt_ladder"):
        validate_ensemble(EnsembleSpec(n_temps=0), 8)

    # HD (correlated phi) is outside the engine's applicability class;
    # the driver must refuse rather than silently sample the wrong law
    with pytest.raises(ValueError, match="ensemble"):
        JaxGibbsDriver(synth_hd_pta, common_rho=True, nchains=4,
                       ensemble=True)

    # pt_ladder > 1 is an ensemble-stage feature
    with pytest.raises(ValueError, match="pt_ladder"):
        JaxGibbsDriver(_crn_pta(n_psr=1, ntoa=24), common_rho=True,
                       nchains=4, ensemble=False, pt_ladder=2)


def test_service_rejects_ensemble(tmp_path):
    """The multiplexed service vmaps the sweep over the TENANT axis —
    interchain moves would couple unrelated analyses, so the service
    boundary rejects the kwargs loudly."""
    from pulsar_timing_gibbsspec_tpu.serve import SamplerService
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (
        BucketSpec, BucketTable)

    table = BucketTable([BucketSpec(2, 40, 24, 3)])
    with pytest.raises(ValueError, match="multiplexed"):
        SamplerService(tmp_path / "srv", table, ensemble=True)
    with pytest.raises(ValueError, match="multiplexed"):
        SamplerService(tmp_path / "srv", table, pt_ladder=2)


# ---------------------------------------------------------------------------
# ensemble-off: bitwise-identical to the plain sweep
# ---------------------------------------------------------------------------

def test_ensemble_off_bitwise_identical(synth_pta):
    """Python-level gating: a driver built with the default settings and
    one with ``ensemble=False`` must run the SAME compiled program —
    byte-identical chains — while ``ensemble=True`` on the same seed
    must actually change the process (the toggle is live, not DCE'd
    along with the stage)."""
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    c_default, d_default = _run(synth_pta, x0)
    c_off, _ = _run(synth_pta, x0, ensemble=False)
    c_on, d_on = _run(synth_pta, x0, ensemble=True)

    assert np.all(np.isfinite(c_default))
    assert c_default.tobytes() == c_off.tobytes()
    assert c_default.tobytes() != c_on.tobytes()

    # off: no ensemble carry in checkpoints, no summary channel
    assert not [k for k in d_default.adapt_state() if k.startswith("ens_")]
    assert d_default.ensemble_summary() is None

    # on: counters carried and live
    es = d_on.ensemble_summary()
    assert es["stretch"] and es["stretch_accept"][0] > 0
    st = d_on.adapt_state()
    assert int(st["ens_pt_ladder"]) == 1 and "ens_lsp" in st


# ---------------------------------------------------------------------------
# slow: statistical parity, ladder adaptation, bitwise resume
# ---------------------------------------------------------------------------

def _assert_same_law(a, b, cols, zmax=5.0):
    """ESS-aware two-run equivalence on (niter, C, npar) chain stacks
    (thresholds as test_jax_backend's _assert_same_law, adapted to
    multi-chain pooling): z-test on the marginal mean with per-chain-
    ACT effective sample sizes; for columns whose chains mix
    (ACT < 10), a KS test on pooled samples thinned along ITERATIONS
    before pooling — thinning the interleaved pooled series instead
    hides each chain's autocorrelation and makes the KS
    anti-conservative."""
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    for k in cols:
        xa, xb = a[:, :, k], b[:, :, k]
        acts = [max(max(float(integrated_act(np.ascontiguousarray(
                    x[:, c]))) for c in range(x.shape[1])), 1.0)
                for x in (xa, xb)]
        ess = [x.size / t for x, t in zip((xa, xb), acts)]
        se = np.sqrt(xa.var() / ess[0] + xb.var() / ess[1])
        z = abs(xa.mean() - xb.mean()) / max(se, 1e-12)
        assert z < zmax, (k, z, acts)
        if max(acts) < 10:
            t = int(np.ceil(max(acts)))
            p = stats.ks_2samp(xa[::t].ravel(), xb[::t].ravel()).pvalue
            assert p > 1e-4, (k, p)


@pytest.mark.slow
def test_ks_parity_and_ladder_single_pulsar(synth_pta):
    """Ensemble-on (stretch + ASIS + pt_ladder=2) must sample the SAME
    rho posterior as the plain sweep — only the beta=1 rungs are
    samples — and the SA ladder must adapt the swap rate toward the
    ~23% target from its beta_ratio=0.55 start."""
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    niter, burn = 400, 100
    cp, _ = _run(synth_pta, x0, niter=niter, seed=5, nchains=8)
    ce, drv = _run(synth_pta, x0, niter=niter, seed=6, nchains=8,
                   ensemble=True, pt_ladder=2)
    assert np.all(np.isfinite(ce))

    idx = BlockIndex.build(synth_pta.param_names)
    cold = ce[:, ::2]                     # beta=1 chains only
    _assert_same_law(cp[burn:], cold[burn:], idx.rho)

    es = drv.ensemble_summary()
    assert 0.15 < es["swap_rate"][0] < 0.38, es
    betas = es["betas"]
    assert betas[0] == 1.0 and 0.0 < betas[1] < 0.45, betas
    assert all(x > 0 for x in es["stretch_accept"]), es
    assert es["sa_steps"] > niter // 2


@pytest.mark.slow
def test_ks_parity_crn(tmp_path):
    """Same-law check on the multi-pulsar CRN class the engine targets
    (the bench configuration's structure, scaled down)."""
    pta = _crn_pta()
    x0 = pta.initial_sample(np.random.default_rng(0))
    niter, burn = 300, 80
    cp, _ = _run(pta, x0, niter=niter, seed=3, nchains=8, chunk_size=10)
    ce, drv = _run(pta, x0, niter=niter, seed=4, nchains=8, chunk_size=10,
                   ensemble=True, pt_ladder=2)
    assert np.all(np.isfinite(ce))
    idx = BlockIndex.build(pta.param_names)
    _assert_same_law(cp[burn:], ce[burn:, ::2], idx.rho)
    es = drv.ensemble_summary()
    assert all(x > 0 for x in es["stretch_accept"]), es


@pytest.mark.slow
def test_ensemble_resume_bitwise_1d(synth_pta, tmp_path):
    """Bitwise resume with the ensemble carry (adaptive ladder +
    counters ride adapt_state as ens_* keys): split/resumed == full."""
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=9, progress=False, nchains=4,
              white_adapt_iters=4, chunk_size=4, warmup_sweeps=2,
              ensemble=True, pt_ladder=2)
    full = PTABlockGibbs(synth_pta, **kw).sample(
        x0, outdir=str(tmp_path / "full"), niter=16, save_every=4)
    PTABlockGibbs(synth_pta, **kw).sample(
        x0, outdir=str(tmp_path / "split"), niter=8, save_every=4)
    resumed = PTABlockGibbs(synth_pta, **kw).sample(
        x0, outdir=str(tmp_path / "split"), niter=16, resume=True,
        save_every=4)
    assert np.all(np.isfinite(full))
    np.testing.assert_array_equal(resumed, full)

    # ladder mismatch on resume is a hard error, not silent drift
    with pytest.raises(RuntimeError, match="pt_ladder"):
        PTABlockGibbs(synth_pta, **{**kw, "pt_ladder": 1,
                                    "nchains": 4}).sample(
            x0, outdir=str(tmp_path / "split"), niter=16, resume=True)


@pytest.mark.slow
def test_ensemble_resume_bitwise_mesh_2x4(synth_pta, tmp_path):
    """Bitwise resume of an ensemble run checkpointed under the 2-d
    (chains x pulsars) mesh, restored through reshard_restore on the
    same (2, 4) layout — tempering swaps and stretch pairing stay
    within device-local chain blocks, and the carried ens_state round-
    trips exactly."""
    from pulsar_timing_gibbsspec_tpu.parallel import make_mesh
    from pulsar_timing_gibbsspec_tpu.runtime import integrity
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=3, progress=False, nchains=8,
              white_adapt_iters=4, chunk_size=4, warmup_sweeps=2,
              pad_pulsars=4, ensemble=True, pt_ladder=2)
    base = PTABlockGibbs(synth_pta, mesh=make_mesh((2, 4)), **kw)
    full = base.sample(x0, outdir=str(tmp_path / "full"), niter=16,
                       save_every=4)

    src = tmp_path / "src"
    PTABlockGibbs(synth_pta, mesh=make_mesh((2, 4)), **kw).sample(
        x0, outdir=str(src), niter=8, save_every=4)
    dst = tmp_path / "dst"
    shutil.copytree(src, dst)
    # reshard_restore pins backend/pad/mesh from the manifest + devices
    rkw = {k: v for k, v in kw.items()
           if k not in ("backend", "pad_pulsars")}
    g = integrity.reshard_restore(str(dst), synth_pta, devices=(2, 4),
                                  **rkw)
    resumed = g.sample(x0, outdir=str(dst), niter=16, resume=True,
                       save_every=4)
    assert np.all(np.isfinite(full))
    np.testing.assert_array_equal(resumed, full)
