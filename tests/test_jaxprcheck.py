"""jaxprcheck: the jaxpr/HLO contract auditor.

Fast tests exercise the size model and each auditor on tiny traces
(pure CPU tracing, milliseconds).  The ``slow`` tests run the
committed bench-scale contracts — the acceptance surface: C=128 must
now PASS under the segmented exact Gram (its scratch pinned so a
revert to the monolithic contraction fails calibration), C=64 must
pass within the calibrated tolerance, the CRN sweep census must
reproduce the committed contract byte-identically, and the 2-d
(chain, pulsar) mesh must keep its chain axis collective-free — all
statically, with zero device execution.
"""

import json

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.walk import (
    LANE, iter_eqns, source_of, tile_padded_bytes, trace_jaxpr)
from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.hbm import (
    GiB, audit_hbm, check_budget)
from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.dtypes import (
    audit_dtypes, dot_census)
from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.keys import (
    audit_keys, check_policy)
from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.donation import (
    aliased_outputs, audit_donation, check_aliasing)
from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck import runner


# ---------------------------------------------------------------------------
# tile-pad size model (calibration pins)
# ---------------------------------------------------------------------------

def test_tile_pad_minor_to_lane():
    # (3, 38) f32: sublane 3->8, lane 38->128
    assert tile_padded_bytes((3, 38), np.float32) == 8 * 128 * 4
    # rank-1 pads the single axis to a lane
    assert tile_padded_bytes((5,), np.float32) == LANE * 4
    # 2-byte dtypes use 16 sublanes
    assert tile_padded_bytes((3, 1), np.dtype("bfloat16")) == 16 * 128 * 2


def test_gram_scratch_calibration_pin():
    """The r4 measurement, as arithmetic: 8 segments of the
    (C, P, Nmax, B1) = (128, 45, 720, 38) f32 operand tile-pad to
    15.82 GiB at a 3.37x pad ratio (README: 15.8 GB, 3.4x)."""
    per_seg = tile_padded_bytes((128, 45, 720, 38), np.float32)
    total = 8 * per_seg
    assert total == 16_986_931_200
    assert abs(total / GiB - 15.82) < 0.01
    raw = 8 * 128 * 45 * 720 * 38 * 4
    assert abs(total / raw - 3.37) < 0.01


# ---------------------------------------------------------------------------
# C1: HBM audit on a synthetic wide-accumulation trace
# ---------------------------------------------------------------------------

def _widening_dot_entry():
    import jax
    import jax.numpy as jnp

    # the repo enables x64 at model-compile entry (config.apply); these
    # unit traces never compile a model, so flip the one-way switch here
    jax.config.update("jax_enable_x64", True)

    def gram(a):
        # f32 operands, f64 accumulation: the exact-Gram pattern
        return jnp.einsum("ij,ik->jk", a, a,
                          preferred_element_type=jnp.float64)

    import jax
    x = jax.ShapeDtypeStruct((960, 64), jnp.float32)
    return gram, (x,)


def test_hbm_scratch_fires_on_widening_dot_and_names_source():
    fn, args = _widening_dot_entry()
    rep = audit_hbm(trace_jaxpr(fn, args))
    sc = rep.largest_scratch
    assert sc is not None
    # nseg = ceil(960 / 96) = 10 segments of the (960, 64) operand
    assert sc.shape[0] == 10
    assert sc.source[2] == "gram"
    msg = check_budget(rep, budget_bytes=1)
    assert msg is not None and "gram" in msg and "scratch" in msg


def test_hbm_no_scratch_without_widening():
    import jax
    import jax.numpy as jnp

    def plain(a):
        return a @ a.T

    rep = audit_hbm(trace_jaxpr(
        plain, (jax.ShapeDtypeStruct((16, 8), jnp.float32),)))
    assert rep.scratches == []
    assert check_budget(rep, 1 << 30) is None


# ---------------------------------------------------------------------------
# C3: dtype islands
# ---------------------------------------------------------------------------

def test_dtype_island_flags_stray_f64_dot():
    fn, args = _widening_dot_entry()
    closed = trace_jaxpr(fn, args)
    v, census = audit_dtypes(closed, exact_fns=())
    assert census == {"float64": 1}
    assert len(v) == 1 and "exact-island" in v[0] and "gram" in v[0]
    # declaring the island (by function or by file) silences it
    assert audit_dtypes(closed, exact_fns=("gram",))[0] == []
    assert audit_dtypes(closed, exact_fns=("test_jaxprcheck.py",))[0] == []


def test_dtype_highest_policy():
    import jax
    import jax.numpy as jnp

    def seg(a):
        return jnp.einsum("ij,ik->jk", a, a)        # default precision

    closed = trace_jaxpr(seg, (jax.ShapeDtypeStruct((8, 4), jnp.float32),))
    v, _ = audit_dtypes(closed, highest_fns=("seg",))
    assert len(v) == 1 and "HIGHEST" in v[0]

    def seg_hi(a):
        return jnp.einsum("ij,ik->jk", a, a, precision="highest")

    closed = trace_jaxpr(seg_hi, (jax.ShapeDtypeStruct((8, 4),
                                                       jnp.float32),))
    assert audit_dtypes(closed, highest_fns=("seg_hi",))[0] == []


# ---------------------------------------------------------------------------
# C4: key lineage
# ---------------------------------------------------------------------------

def _key_arg():
    import jax.random as jr

    return jr.key(0)


def test_keys_clean_fold_then_split():
    import jax.random as jr

    def f(key, t):
        k = jr.fold_in(jr.fold_in(key, t), 1)
        k1, k2 = jr.split(k)
        return jr.normal(k1) + jr.normal(k2)

    rep = audit_keys(trace_jaxpr(f, (_key_arg(), 3)))
    assert rep.violations == []
    assert rep.fold_depths_at_split == [2]
    assert check_policy(rep, {"fold_depths_at_split": [2],
                              "max_in_trace_roots": 0,
                              "allow_pre_split_consume": False}) == []


def test_keys_flags_reuse():
    import jax.random as jr

    def f(key):
        return jr.normal(key) + jr.uniform(key)  # jaxlint: disable=R1

    rep = audit_keys(trace_jaxpr(f, (_key_arg(),)))
    assert any("more than once" in v for v in rep.violations)


def test_keys_flags_wrong_fold_depth_and_in_trace_seed():
    import jax.random as jr

    def f(key):
        k1, _ = jr.split(key)               # split at fold depth 0
        fresh = jr.key(7)                   # in-trace root
        return jr.normal(k1) + jr.normal(fresh)

    rep = audit_keys(trace_jaxpr(f, (_key_arg(),)))
    assert rep.violations == []
    out = check_policy(rep, {"fold_depths_at_split": [2],
                             "max_in_trace_roots": 0})
    assert len(out) == 2
    assert any("fold-depth" in v for v in out)
    assert any("seeded inside the trace" in v for v in out)


def test_keys_cond_branches_do_not_double_count():
    import jax
    import jax.random as jr

    def f(key, flag):
        return jax.lax.cond(flag,
                            lambda k: jr.normal(k),
                            lambda k: jr.uniform(k), key)  # jaxlint: disable=R1

    rep = audit_keys(trace_jaxpr(f, (_key_arg(), True)))
    assert rep.violations == []


def test_keys_scan_constant_key_consumption_flagged():
    import jax
    import jax.random as jr
    import jax.numpy as jnp

    def bad(key):
        def body(c, t):
            # same key every iteration
            return c + jr.normal(key, dtype=jnp.float32), None

        out, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(4))
        return out

    rep = audit_keys(trace_jaxpr(bad, (_key_arg(),)))
    assert any("loop constant" in v for v in rep.violations)

    def good(key):
        def body(c, t):
            k = jr.fold_in(key, t)
            return c + jr.normal(k, dtype=jnp.float32), None

        out, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(4))
        return out

    rep = audit_keys(trace_jaxpr(good, (_key_arg(),)))
    assert rep.violations == []


# ---------------------------------------------------------------------------
# C5: donation
# ---------------------------------------------------------------------------

def test_donation_aliases_detected_and_budgeted():
    import jax
    import jax.numpy as jnp

    def step(x, b, s):
        return x * 2.0, b + 1.0, s.sum()

    args = (jax.ShapeDtypeStruct((8, 8), jnp.float32),
            jax.ShapeDtypeStruct((8, 8), jnp.float32),
            jax.ShapeDtypeStruct((8,), jnp.float32))
    aliased, text = audit_donation(step, args, (0, 1))
    assert len(aliased) == 2
    assert aliased_outputs(text) == aliased
    assert check_aliasing(aliased, 2) is None
    assert "aliased" in check_aliasing(aliased, 3)


# ---------------------------------------------------------------------------
# source attribution
# ---------------------------------------------------------------------------

def test_source_of_prefers_repo_frames():
    from pulsar_timing_gibbsspec_tpu.ops.linalg import _mm

    def f(a, b):
        return _mm(a, b)

    closed = trace_jaxpr(f, (np.ones((3, 4, 4), np.float32),
                             np.ones((3, 4, 4), np.float32)))
    dots = [e for e, _ in iter_eqns(closed.jaxpr)
            if e.primitive.name == "dot_general"]
    assert dots
    fname, _line, fn = source_of(dots[0])
    # the dot attributes to the repo's linalg helper, not to whatever
    # jax-internal frame sits below it
    assert "pulsar_timing_gibbsspec_tpu" in fname
    assert fn == "_mm"


# ---------------------------------------------------------------------------
# contracts end-to-end
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_fast_contract_subset_passes():
    """The CI surface: every contract marked fast audits clean."""
    contracts = runner.discover_contracts(fast_only=True)
    assert contracts, "no fast contracts committed"
    violations, facts = runner.run_contracts(contracts)
    assert violations == [], [str(v) for v in violations]
    q = facts["crn_quick"]
    assert q["keys"]["fold_depths_at_split"] == [2]
    assert q["donation"]["aliased_outputs"] == [0, 1]


def test_contract_hashes_cover_all_contracts():
    hashes = runner.contract_hashes()
    assert {"crn_quick", "crn_bench_c64", "crn_bench_c128",
            "crn_multichip", "crn_2d_mesh"} <= set(hashes)
    assert all(len(h) == 64 for h in hashes.values())


@pytest.mark.lint
def test_every_entry_builder_has_a_committed_contract():
    """Coverage gate: a jit entry builder in entries.py without a
    pinned contracts/*.json is a compiled program shipping unaudited."""
    assert runner.check_contract_coverage() == []


def test_contract_coverage_names_the_missing_entries(tmp_path):
    # a contracts dir pinning only the gram entry: every other builder
    # must surface as its own coverage violation
    (tmp_path / "only_gram.json").write_text(json.dumps(
        {"name": "only_gram", "entry": {"entry": "gram"}, "checks": []}))
    v = runner.check_contract_coverage(tmp_path)
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        _ENTRIES)

    missing = {x.path.split("/")[-1] for x in v}
    assert missing == set(_ENTRIES) - {"gram"}
    assert all(x.rule == "coverage" for x in v)


def test_discover_contracts_skips_entry_less_configs(tmp_path):
    # contracts/ also holds racecheck's config — an entry-less JSON
    # must not crash or pollute a full jaxprcheck run
    (tmp_path / "racecheckish.json").write_text(json.dumps(
        {"name": "not-a-contract", "paths": ["x"]}))
    (tmp_path / "real.json").write_text(json.dumps(
        {"name": "real", "entry": {"entry": "gram"}, "checks": []}))
    got = runner.discover_contracts(tmp_path)
    assert [c["name"] for c in got] == ["real"]


def test_runner_reports_broken_contract_as_error_violation():
    v, f = runner.run_contracts([{"name": "nope",
                                  "entry": {"entry": "no-such"},
                                  "checks": []}])
    assert len(v) == 1 and v[0].rule == "error"


def test_violation_surface_matches_baseline_ratchet():
    from pathlib import Path

    from pulsar_timing_gibbsspec_tpu.analysis.baseline import (
        baseline_counts)

    v = runner.Violation("contracts/x.json", "hbm", "boom")
    counts = baseline_counts([v], Path("/root/repo"))
    assert counts == {"contracts/x.json": {"hbm": 1}}


def test_bench_contract_c128_passes_via_segmented_gram():
    """Acceptance, inverted from the r4 era: the segmented exact Gram
    bounds the widening dot's contraction at one seg_len segment, so
    the C=128 config now fits — 270 MiB of per-segment scratch (one
    tile-padded segment operand, down from 2.11 GiB when tnt_d held the
    whole-model operand and 15.82 GiB in the monolithic r4 lowering),
    under the 15.75 GiB budget.  The scratch pin names the kernel
    tier's _segment_dot so a refactor that silently reverts to the
    monolithic contraction fails calibration before it OOMs hardware."""
    c = runner.load_contract(runner.CONTRACT_DIR / "crn_bench_c128.json")
    violations, facts = runner.run_contract(c)
    assert violations == [], [str(x) for x in violations]
    hbm = facts["hbm"]
    assert hbm["estimate_bytes"] <= 16_911_433_728      # under 15.75 GiB
    assert hbm["scratch"]["source_fn"] == "_segment_dot"
    assert hbm["scratch"]["bytes"] == 283_115_520       # 270 MiB


@pytest.mark.slow
def test_bench_contract_c64_passes_within_tolerance():
    c = runner.load_contract(runner.CONTRACT_DIR / "crn_bench_c64.json")
    violations, facts = runner.run_contract(c)
    assert violations == [], [str(x) for x in violations]
    assert facts["hbm"]["estimate_bytes"] <= 16_911_433_728


@pytest.mark.slow
def test_multichip_contract_census_byte_identical():
    c = runner.load_contract(runner.CONTRACT_DIR / "crn_multichip.json")
    violations, facts = runner.run_contract(c)
    assert violations == [], [str(x) for x in violations]
    want = c["checks"][0]["census"]
    got = facts["collectives"]["census"]
    assert json.dumps(got, sort_keys=True) == \
        json.dumps(want, sort_keys=True)


# ---------------------------------------------------------------------------
# chain-axis isolation (the 2-d mesh's zero-collective contract)
# ---------------------------------------------------------------------------

def test_collective_groups_decodes_all_spellings():
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.collectives import (
        collective_groups)

    hlo = (
        "  %a = f32[8] all-reduce(%x), replica_groups={{0,4},{1,5}}, "
        "to_apply=%add\n"
        "  %b = f32[16] all-gather(%y), replica_groups=[2,4]<=[8], "
        "dimensions={0}\n"
        "  %c = f32[8] all-gather(%q), replica_groups=[4,2]<=[2,4]T(1,0), "
        "dimensions={0}\n"
        "  %d = u32[4] collective-permute(%z), "
        "source_target_pairs={{0,1},{4,5}}\n"
        "  %e = f32[8] all-reduce(%w), replica_groups={}, to_apply=%add\n")
    got = collective_groups(hlo)
    assert got[0] == ("all-reduce", [[0, 4], [1, 5]])
    assert got[1] == ("all-gather", [[0, 1, 2, 3], [4, 5, 6, 7]])
    # iota with transpose: arange(8).reshape(2,4).T rows -> column groups
    assert got[2] == ("all-gather", [[0, 4], [1, 5], [2, 6], [3, 7]])
    assert got[3] == ("collective-permute", [[0, 1], [4, 5]])
    # bare replica_groups={} (all devices) stays undecoded -> fails the
    # isolation check loudly rather than passing silently
    assert got[4][1] is None or got[4][1] == []


def test_check_axis_isolation_flags_cross_row_traffic():
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.collectives import (
        check_axis_isolation)

    # rows of a (2, 4) mesh: {0..3} and {4..7} — clean
    clean = ("  %b = f32[16] all-gather(%y), replica_groups=[2,4]<=[8], "
             "dimensions={0}\n"
             "  %d = u32[4] collective-permute(%z), "
             "source_target_pairs={{0,1},{4,5}}\n")
    assert check_axis_isolation(clean, (2, 4), axis=0) == []
    # the same groups ARE the pulsar-axis traffic — axis 1 spans
    assert check_axis_isolation(clean, (2, 4), axis=1)
    # column groups, a cross-row permute, and an all-device reduce all
    # cross axis 0
    for bad in (
            "  %a = f32[8] all-reduce(%x), replica_groups={{0,4},{1,5}}, "
            "to_apply=%add\n",
            "  %d = u32[4] collective-permute(%z), "
            "source_target_pairs={{0,4}}\n",
            "  %e = f32[8] all-reduce(%w), replica_groups={}, "
            "to_apply=%add\n"):
        msgs = check_axis_isolation(bad, (2, 4), axis=0)
        assert msgs and "spans" in msgs[0]


def test_2d_mesh_contract_chain_axis_clean():
    """Acceptance: the vmapped-over-chains CRN sweep on a (2, 4) mesh
    emits ONLY pulsar-axis collectives — every replica group decodes
    to a single chain row — and its census matches the committed pin.
    (The census is the crn_multichip per-chain structure with the C=4
    batch riding the value gathers; byte-identity with the 1-d pin is
    structurally impossible, so the replica-group isolation check is
    the zero-chain-traffic criterion.)"""
    c = runner.load_contract(runner.CONTRACT_DIR / "crn_2d_mesh.json")
    violations, facts = runner.run_contract(c)
    assert violations == [], [str(x) for x in violations]
    iso = facts["collectives"]["isolate_axis"]
    assert iso == {"mesh": [2, 4], "axis": 0, "clean": True}
    assert facts["keys"]["n_folds"] == 0
