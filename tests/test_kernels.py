"""Kernel tier (ops/kernels): fused kernels vs their XLA reference twins.

The parity contract is BITWISE, not approximate: the Pallas kernel
bodies run the same traced math on the same whole-batch shapes as the
reference implementations, and the Gram grid-accumulator shares the
reference's sequential left-to-right segment reduce — so in interpret
mode on this CPU container the tiers must agree to the last bit in BOTH
f64 and f32.  Anything weaker (a per-tile kernel, a reassociated
reduce) shows up here as a 1-2 ULP drift long before it reaches
hardware.  On top of parity: dispatch/fallback rules, the mixed-
precision island map (f64/tf bodies never route to Mosaic on hardware),
same-key tier agreement of the full Metropolised b-draw, and the
zero-retrace contract with the tier enabled.
"""

import contextlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.random as jr

from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
    build_model, synthetic_pulsars)
from pulsar_timing_gibbsspec_tpu.config import settings
from pulsar_timing_gibbsspec_tpu.ops import kernels
from pulsar_timing_gibbsspec_tpu.ops.kernels import reference

# f64 parity cases need x64 before the first traced op (normally
# settings.apply() runs at model-compile entry)
settings.apply()

pytestmark = pytest.mark.pallas

needs_pallas = pytest.mark.skipif(
    not kernels.pallas_available(),
    reason="Pallas does not import in this environment")


@contextlib.contextmanager
def _tier(tier):
    prev = settings.kernel_tier
    settings.kernel_tier = tier
    try:
        yield
    finally:
        settings.kernel_tier = prev


def _spd_batch(P, B, dtype, seed=0):
    rng = np.random.default_rng(seed)
    M = rng.standard_normal((P, B, B))
    A = np.einsum("pij,pkj->pik", M, M) + B * np.eye(B)
    return jnp.asarray(A, dtype)


def _gram_operands(P, nseg, m, B1, seed=1):
    rng = np.random.default_rng(seed)
    TNa = jnp.asarray(rng.standard_normal((P, nseg, m, B1)), jnp.float32)
    Ta = jnp.asarray(rng.standard_normal((P, nseg, m, B1)), jnp.float32)
    return TNa, Ta


# ---------------------------------------------------------------------------
# dispatch: tier resolution, fallback, island map


def test_resolve_tier_rules():
    assert kernels.resolve_tier("xla") == "xla"
    # this container is CPU-only: auto must resolve to the XLA tier
    # (Mosaic is TPU-only; interpret mode is a testing story, not a
    # production auto choice)
    assert jax.default_backend() != "tpu"
    assert kernels.resolve_tier("auto") == "xla"
    expected = "pallas" if kernels.pallas_available() else "xla"
    assert kernels.resolve_tier("pallas") == expected
    with pytest.raises(ValueError, match="kernel tier"):
        kernels.resolve_tier("mosaic")
    # no explicit argument: settings.kernel_tier decides
    with _tier("xla"):
        assert kernels.resolve_tier() == "xla"
    with _tier("auto"):
        assert kernels.resolve_tier() == "xla"


def test_interpret_mode_off_tpu():
    assert kernels.interpret_mode() is (jax.default_backend() != "tpu")


def test_xla_tier_is_the_reference_lowering():
    """tier="xla" must be jacobi_factor_mean_prop verbatim — the kernel
    layer adds dispatch, never a different lowering."""
    from pulsar_timing_gibbsspec_tpu.ops.linalg import \
        jacobi_factor_mean_prop

    Sig = _spd_batch(4, 7, jnp.float32)
    rng = np.random.default_rng(2)
    d = jnp.asarray(rng.standard_normal((4, 7)), jnp.float32)
    z = jnp.asarray(rng.standard_normal((4, 7)), jnp.float32)
    got = kernels.chol_solve_sample(Sig, d, z, ridge=1e-6, tier="xla")
    want = jacobi_factor_mean_prop(Sig, d, z, ridge=1e-6)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


@needs_pallas
def test_tf_factor_never_routes_to_pallas():
    """factor="tf" carries emulated-f64 arithmetic — XLA-tier by design
    even under tier="pallas" on hardware; here (interpret mode) both
    paths must still produce the reference tf chain bitwise."""
    Sig = _spd_batch(3, 6, jnp.float64)
    rng = np.random.default_rng(3)
    d = jnp.asarray(rng.standard_normal((3, 6)), jnp.float64)
    z = jnp.asarray(rng.standard_normal((3, 6)), jnp.float64)
    got = kernels.chol_solve_sample(Sig, d, z, ridge=1e-6, factor="tf",
                                    tier="pallas")
    want = reference.chol_solve_sample_ref(Sig, d, z, ridge=1e-6,
                                           factor="tf")
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_chol_solve_sample_rejects_unknown_factor():
    Sig = _spd_batch(2, 4, jnp.float32)
    d = jnp.zeros((2, 4), jnp.float32)
    with pytest.raises(ValueError, match="factor"):
        kernels.chol_solve_sample(Sig, d, d, factor="qr", tier="xla")


# ---------------------------------------------------------------------------
# interpret-mode parity: bitwise in f64 AND f32, jitted both sides


@needs_pallas
@pytest.mark.parametrize("dtype", ["float64", "float32"])
def test_chol_solve_sample_parity_bitwise(dtype):
    dt = jnp.dtype(dtype)
    Sig = _spd_batch(5, 9, dt, seed=4)
    rng = np.random.default_rng(5)
    d = jnp.asarray(rng.standard_normal((5, 9)), dt)
    z = jnp.asarray(rng.standard_normal((5, 9)), dt)
    f_p = jax.jit(lambda S, dd, zz: kernels.chol_solve_sample(
        S, dd, zz, ridge=1e-6, tier="pallas"))
    f_x = jax.jit(lambda S, dd, zz: kernels.chol_solve_sample(
        S, dd, zz, ridge=1e-6, tier="xla"))
    for g, w in zip(f_p(Sig, d, z), f_x(Sig, d, z)):
        assert g.dtype == w.dtype == dt
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


@needs_pallas
@pytest.mark.parametrize("widen,out_dtype", [
    (True, "float64"),      # the exact tnt_d widening accumulate
    (False, "float64"),     # the tnt_d_seg refresh class
    (False, "float32"),     # the tnt_d_seg32 steady body
])
def test_gram_accumulate_parity_bitwise(widen, out_dtype):
    TNa, Ta = _gram_operands(3, 4, 8, 7)
    dt = jnp.dtype(out_dtype)
    f_p = jax.jit(lambda a, b: kernels.gram_accumulate(
        a, b, out_dtype=dt, widen=widen, tier="pallas"))
    f_x = jax.jit(lambda a, b: kernels.gram_accumulate(
        a, b, out_dtype=dt, widen=widen, tier="xla"))
    g, w = f_p(TNa, Ta), f_x(TNa, Ta)
    assert g.dtype == w.dtype == dt
    assert g.shape == (3, 7, 7)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


@needs_pallas
@pytest.mark.parametrize("dtype", ["float64", "float32"])
def test_vmap_parity(dtype):
    """vmap over the chain axis (the production shape: the grid gains a
    leading dim).  The factor/solve outputs stay bitwise; only the
    final sample injection ``mean + dj * Li^T z`` moves by one ULP —
    XLA lowers that einsum differently once it carries the extra batch
    dim, while the per-grid-step kernel body is shape-invariant."""
    dt = jnp.dtype(dtype)
    C = 3
    Sig = jnp.stack([_spd_batch(4, 6, dt, seed=10 + c)
                     for c in range(C)])
    rng = np.random.default_rng(6)
    d = jnp.asarray(rng.standard_normal((C, 4, 6)), dt)
    z = jnp.asarray(rng.standard_normal((C, 4, 6)), dt)
    f_p = jax.jit(jax.vmap(lambda S, dd, zz: kernels.chol_solve_sample(
        S, dd, zz, ridge=1e-6, tier="pallas")))
    f_x = jax.jit(jax.vmap(lambda S, dd, zz: kernels.chol_solve_sample(
        S, dd, zz, ridge=1e-6, tier="xla")))
    got, want = f_p(Sig, d, z), f_x(Sig, d, z)
    for g, w in zip(got[:4], want[:4]):       # L, Li, dj, mean: bitwise
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    bp_p, bp_x = np.asarray(got[4]), np.asarray(want[4])
    eps = np.finfo(dt.type).eps
    assert np.max(np.abs(bp_p - bp_x)) <= 2 * eps * np.abs(bp_x).max()

    TNa, Ta = _gram_operands(2, 3, 5, 4)
    TNa = jnp.stack([TNa, TNa * 0.5, TNa * 2.0])
    Ta = jnp.stack([Ta, Ta * 2.0, Ta * 0.5])
    g_p = jax.jit(jax.vmap(lambda a, b: kernels.gram_accumulate(
        a, b, out_dtype=jnp.float32, tier="pallas")))(TNa, Ta)
    g_x = jax.jit(jax.vmap(lambda a, b: kernels.gram_accumulate(
        a, b, out_dtype=jnp.float32, tier="xla")))(TNa, Ta)
    np.testing.assert_array_equal(np.asarray(g_p), np.asarray(g_x))


# ---------------------------------------------------------------------------
# numerics of the reference itself


def test_gram_accumulate_widen_is_exact():
    """The widening accumulate is the exact Gram: with integer-valued
    f32 operands every product and partial sum is exactly representable
    in f64, so the result equals the numpy oracle to the last bit
    REGARDLESS of contraction order — segmentation cannot move it."""
    rng = np.random.default_rng(8)
    TNa = jnp.asarray(rng.integers(-8, 9, (3, 4, 8, 7)), jnp.float32)
    Ta = jnp.asarray(rng.integers(-8, 9, (3, 4, 8, 7)), jnp.float32)
    got = kernels.gram_accumulate(TNa, Ta, out_dtype=jnp.float64,
                                  widen=True, tier="xla")
    want = np.einsum("pnb,pnc->pbc",
                     np.asarray(TNa, np.float64).reshape(3, 32, 7),
                     np.asarray(Ta, np.float64).reshape(3, 32, 7))
    np.testing.assert_array_equal(np.asarray(got), want)


def test_gram_accumulate_steady_error_class():
    """The f32 steady body stays within the documented
    ~sqrt(nseg * seg_len) * eps_f32 error class of the exact Gram."""
    TNa, Ta = _gram_operands(3, 4, 8, 7, seed=9)
    exact = np.asarray(kernels.gram_accumulate(
        TNa, Ta, out_dtype=jnp.float64, widen=True, tier="xla"))
    steady = np.asarray(kernels.gram_accumulate(
        TNa, Ta, out_dtype=jnp.float32, widen=False, tier="xla"))
    scale = np.abs(exact).max()
    assert np.max(np.abs(steady - exact)) < 64 * np.sqrt(32) * 1.2e-7 * scale


# ---------------------------------------------------------------------------
# the production consumer: same-key tier agreement of the b-draw


@pytest.fixture(scope="module")
def tiny_cm():
    from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta

    pta = build_model(synthetic_pulsars(3, 40, tm_cols=3, seed=0), 3)
    return pta, compile_pta(pta)


@needs_pallas
def test_draw_b_mh_tier_agreement_same_key(tiny_cm):
    """One Metropolised b-draw from the same state and key under each
    tier: the mixed f32-proposal/f64-accept path must agree to <= 1e-8
    (interpret parity makes it bitwise here; the bound is the
    acceptance criterion that also holds on hardware)."""
    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb

    pta, cm = tiny_cm
    x = jnp.asarray(pta.initial_sample(np.random.default_rng(1)),
                    cm.cdtype)
    b = jnp.zeros((cm.P, cm.Bmax), cm.cdtype)
    u = jb.b_matvec(cm, b)
    key = jr.PRNGKey(7)
    outs = {}
    for tier in ("pallas", "xla"):
        with _tier(tier):
            outs[tier] = jax.jit(
                lambda xx, bb, uu, kk: jb.draw_b_mh(cm, xx, bb, uu, kk)
            )(x, b, u, key)
    b_p, u_p, acc_p = outs["pallas"]
    b_x, u_x, acc_x = outs["xla"]
    np.testing.assert_array_equal(np.asarray(acc_p), np.asarray(acc_x))
    assert bool(np.asarray(acc_p).any())      # the draw actually moved
    assert np.max(np.abs(np.asarray(b_p) - np.asarray(b_x))) <= 1e-8
    assert np.max(np.abs(np.asarray(u_p) - np.asarray(u_x))) <= 1e-8


@needs_pallas
def test_steady_loop_zero_retrace_with_kernel_tier(tiny_cm):
    """Enabling the tier is a trace-time dispatch decision: the steady
    chunk loop reports zero unplanned retraces, exactly as with the
    XLA tier (the PR 12 retrace contract)."""
    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import \
        JaxGibbsDriver

    pta, _cm = tiny_cm
    with _tier("pallas"):
        drv = JaxGibbsDriver(pta, seed=3, common_rho=True,
                             warmup_sweeps=2, white_adapt_iters=4,
                             chunk_size=4, nchains=1)
        niter = 12
        x0 = pta.initial_sample(np.random.default_rng(0))
        cshape, bshape = drv.chain_shapes(niter)
        chain = np.zeros(cshape)
        bchain = np.zeros(bshape)
        with profiling.recompile_counter() as rc:
            rc.phase("warmup")
            it = drv.run(x0, chain, bchain, 0, niter)
            done = next(it)
            rc.phase("steady")
            for done in it:
                pass
        assert done == niter
        assert rc.unplanned("steady") == 0
        assert np.all(np.isfinite(chain))
