"""t-process red PSD: powerlaw scaled by per-frequency InvGamma alphas.

The reference advertises ``red_psd='tprocess'`` (``model_definition.py:
103-105``, via enterprise_extensions ``t_process``) but its committed body
never builds the block and its samplers have no alpha kernel; here the
alphas get an exact conjugate Gibbs draw on both backends.
"""

import numpy as np
import pytest
from scipy import stats

from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.models.priors import InvGamma
from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act
from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs


def _tp_pta(psrs, n=1, nbins=5):
    return model_general(psrs[:n], tm_svd=True, white_vary=False,
                         common_psd="spectrum", common_components=nbins,
                         red_var=True, red_psd="tprocess",
                         red_components=nbins)


def test_invgamma_prior():
    p = InvGamma(1.0, 1.0, name="a", size=3)
    rng = np.random.default_rng(0)
    s = np.array([p.sample(rng) for _ in range(4000)]).ravel()
    ks = stats.kstest(s, stats.invgamma(a=1.0, scale=1.0).cdf)
    assert ks.pvalue > 1e-3
    assert np.isfinite(p.get_logpdf(np.array([0.5, 1.0, 2.0])))
    assert p.get_logpdf(np.array([-1.0])) == -np.inf


def _frozen_draws(pta, cm, x, b, nsamp=800):
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb

    names = list(pta.param_names)
    ai = [i for i, n in enumerate(names) if "alphas" in n]
    return ai, np.array([
        np.asarray(jb.tprocess_alpha_update(cm, x, b, jr.key(s)))[ai]
        for s in range(nsamp)])


def _quantile_match(draws, dist, tol=0.1):
    """Compare empirical 25/50/75% quantiles of each column against the
    analytic distribution in log10 (robust to the grid discretization)."""
    for k in range(draws.shape[1]):
        for q in (0.25, 0.5, 0.75):
            emp = np.log10(np.quantile(draws[:, k], q))
            ana = np.log10(dist[k].ppf(q) if isinstance(dist, list)
                           else dist.ppf(q))
            assert abs(emp - ana) < tol, (k, q, emp, ana)


def test_alpha_conditional_limits(psrs8):
    """The alpha grid draw must target the correct conditional, checked in
    both analytic limits: with the common-process variance negligible it
    is the conjugate InvGamma(2, 1 + tau/plaw); with the common process
    dominating the shared columns the likelihood carries no alpha
    information and the draw must return the InvGamma(1, 1) prior.  (The
    round-2 review caught a conjugate-only kernel that ignored the shared
    common variance — the second limit pins that bug.)"""
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu.models import psd as psdmod

    pta = _tp_pta(psrs8)
    cm = compile_pta(pta)
    assert cm.red_kind == "tprocess"
    names = list(pta.param_names)
    rng = np.random.default_rng(1)
    x0 = pta.initial_sample(rng)
    b = jnp.asarray(rng.standard_normal((cm.P, cm.Bmax)) * 1e-7, cm.cdtype)
    rho_ix = [i for i, n in enumerate(names) if "gw" in n and "rho" in n]
    # pin the red hypers so tau/plaw stays well inside the alpha grid
    # (at prior corners like log10_A=-20 the conditional mass sits beyond
    # the grid top and is legitimately truncated)
    x0[names.index(next(n for n in names if "red" in n and "log10_A" in n))] \
        = -13.5
    x0[names.index(next(n for n in names if "red" in n and "gamma" in n))] \
        = 3.0

    # ---- limit 1: common process off the bottom of its prior ------------
    x = x0.copy()
    x[rho_ix] = -10.0                      # rho = 1e-20, << alpha*plaw
    x = jnp.asarray(x, cm.cdtype)
    ai, draws = _frozen_draws(pta, cm, x, b)
    params = pta.map_params(np.asarray(x))
    m = pta.model(0)
    sig = next(s for s in m.signals if "red" in s.name)
    sl = m._slices[sig.name]
    bb = np.asarray(b)[0, sl.start:sl.stop] ** 2
    tau = 0.5 * (bb[::2] + bb[1::2])
    plaw = psdmod.powerlaw(sig.freqs[::2], sig._df[::2],
                           params[sig.params[0].name],
                           params[sig.params[1].name])
    rate = 1.0 + tau / plaw
    _quantile_match(draws, [stats.invgamma(a=2.0, scale=r) for r in rate])

    # ---- limit 2: common process dominates -> draw returns the prior ----
    x = x0.copy()
    x[rho_ix] = -4.0                       # rho = 1e-8, >> alpha*plaw range
    x = jnp.asarray(x, cm.cdtype)
    ai, draws = _frozen_draws(pta, cm, x, b)
    _quantile_match(draws, stats.invgamma(a=1.0, scale=1.0))


def test_tprocess_jax_vs_numpy_equivalence(psrs8, tmp_path):
    """Backend statistical equivalence on log10(alpha), the red hypers and
    the common rho bins (ESS-aware z-tests)."""
    pta = _tp_pta(psrs8)
    x0 = pta.initial_sample(np.random.default_rng(2))
    chains = {}
    for backend, seed in [("jax", 3), ("numpy", 4)]:
        g = PulsarBlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2000)
    names = list(pta.param_names)
    burn = 400
    check = [i for i, n in enumerate(names)
             if "alphas" in n or "log10_A" in n or "gamma" in n
             or "rho" in n]
    for k in check:
        cj, cn = chains["jax"][burn:, k], chains["numpy"][burn:, k]
        if "alphas" in names[k]:
            cj, cn = np.log10(cj), np.log10(cn)   # heavy-tailed -> log
        ess_j = len(cj) / max(integrated_act(cj), 1.0)
        ess_n = len(cn) / max(integrated_act(cn), 1.0)
        z = abs(cj.mean() - cn.mean()) / np.sqrt(
            cj.var() / ess_j + cn.var() / ess_n)
        assert z < 4.5, (names[k], z, ess_j, ess_n)


def test_tprocess_adapt_rejected(psrs8):
    with pytest.raises(NotImplementedError):
        model_general(psrs8[:1], red_psd="tprocess_adapt")
