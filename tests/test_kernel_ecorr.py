"""Kernel-ECORR (``ecorrsample='kernel'``) validation.

The reference's kernel-ECORR update is dead code ("NEEDS TO BE FIXED",
``pulsar_gibbs.py:409-486``) and its sampler ctor hard-rejects kernel-ECORR
models (``:65-68``).  Here the kernel semantics work: the epoch blocks live
inside N via per-epoch Woodbury (``N = D + U c U^T`` with disjoint epoch
indicators), which is exactly what the basis representation marginalizes
to — so basis and kernel runs of the SAME model must agree in
distribution, and that equivalence is the strongest cross-check in this
file.
"""

import numpy as np
import pytest
from scipy import stats

from pulsar_timing_gibbsspec_tpu.data.dataset import Pulsar
from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs
from pulsar_timing_gibbsspec_tpu.sampler.numpy_backend import NumpyGibbs

DAY = 86400.0


@pytest.fixture(scope="module")
def ng_psr():
    """NANOGrav-flagged synthetic pulsar with clustered epochs and an
    injected per-epoch correlated offset (same design as test_ecorr)."""
    rng = np.random.default_rng(17)
    n_epochs, per_epoch = 50, 5
    span = 9.0 * 365.25 * DAY
    centers = np.sort(rng.uniform(0.0, span, n_epochs)) + 53000.0 * DAY
    toas = np.sort(np.concatenate([
        c + rng.uniform(0, 0.2 * DAY, per_epoch) for c in centers]))
    n = len(toas)
    errs = rng.uniform(2e-7, 9e-7, n)
    epoch_of = np.searchsorted(centers + 0.5 * DAY, toas)
    offsets = 10.0 ** -6.3 * rng.standard_normal(n_epochs)
    res = errs * rng.standard_normal(n) + offsets[np.clip(epoch_of, 0,
                                                          n_epochs - 1)]
    t = (toas - toas.mean()) / span
    M = np.column_stack([np.ones(n), t, t * t])
    return Pulsar(
        name="FAKE_KE", toas=toas, toaerrs=errs, residuals=res,
        freqs=np.full(n, 1400.0),
        backend_flags=np.asarray(["sim"] * n, dtype=object),
        Mmat=M, fitpars=["offset", "F0", "F1"],
        flags={"pta": "NANOGrav"},
        pos=np.array([1.0, 0.0, 0.0]))


def _model(psr):
    return model_general([psr], tm_svd=True, red_var=False,
                         white_vary=True, common_psd="spectrum",
                         common_components=5)


def test_kernel_lnlike_matches_dense_woodbury(ng_psr):
    """The oracle's per-epoch Woodbury white likelihood must equal the
    brute-force dense-N Gaussian log-density (up to the constant both
    drop)."""
    pta = _model(ng_psr)
    g = NumpyGibbs(pta, ecorrsample="kernel", seed=0)
    rng = np.random.default_rng(2)
    x = pta.initial_sample(rng)
    g.b = rng.standard_normal(g.nb_total) * 1e-7

    params = pta.map_params(x)
    Nvec = pta.get_ndiag(params)[0]
    U = g.ecorr_sig._U
    c = np.asarray(g.ecorr_sig.get_phi(params))   # per-epoch 10^(2 ecorr)
    Ndense = np.diag(Nvec) + (U * c[None, :]) @ U.T
    r = g._y - g._T @ g.b
    sign, logdet = np.linalg.slogdet(Ndense)
    assert sign > 0
    dense = -0.5 * (logdet + r @ np.linalg.solve(Ndense, r))
    np.testing.assert_allclose(g.lnlike_white(x), dense, rtol=1e-9)

    # and the corrected TNT/d match the dense ones
    TNT, d = g._tnt_d(params, Nvec)
    Ninv = np.linalg.inv(Ndense)
    np.testing.assert_allclose(TNT, g._T.T @ Ninv @ g._T, rtol=1e-8,
                               atol=1e-3)
    np.testing.assert_allclose(d, g._T.T @ (Ninv @ g._y), rtol=1e-8,
                               atol=1e-6)


def test_kernel_drops_ecorr_columns(ng_psr):
    """Kernel mode samples the same parameter space but no ECORR basis
    coefficients: the b layout shrinks by one column per epoch."""
    pta = _model(ng_psr)
    basis = PulsarBlockGibbs(pta, backend="jax", progress=False, seed=1)
    kern = PulsarBlockGibbs(pta, backend="jax", ecorrsample="kernel",
                            progress=False, seed=1)
    n_epochs = pta.model(0)._ecorr[0]._U.shape[1]
    assert basis._backend.nb_total - kern._backend.nb_total == n_epochs
    assert kern.param_names == basis.param_names
    # the chain-file name sidecars must match the column counts
    assert len(kern.b_param_names) == kern._backend.nb_total
    assert len(basis.b_param_names) == basis._backend.nb_total


def test_kernel_rejected_without_ecorr(ng_psr):
    import dataclasses

    unflagged = dataclasses.replace(ng_psr, flags={"pta": ""})
    pta = model_general([unflagged], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5)
    with pytest.raises((ValueError, NotImplementedError)):
        PulsarBlockGibbs(pta, backend="jax", ecorrsample="kernel",
                         progress=False)


def test_kernel_vs_basis_ks(ng_psr, tmp_path):
    """Basis and kernel execution of the SAME model are marginally
    identical over the shared parameters — the defining property of the
    kernel representation."""
    pta = _model(ng_psr)
    x0 = pta.initial_sample(np.random.default_rng(23))
    chains = {}
    for mode, es, seed in [("basis", None, 31), ("kernel", "kernel", 32)]:
        g = PulsarBlockGibbs(pta, backend="jax", ecorrsample=es, seed=seed,
                             progress=False, white_adapt_iters=600)
        chains[mode] = g.sample(x0, outdir=str(tmp_path / mode), niter=2600)
    burn, thin = 400, 10
    idx = BlockIndex.build(pta.param_names)
    cols = list(idx.ecorr) + list(idx.white) + list(idx.rho[:2])
    pvals = [stats.ks_2samp(chains["basis"][burn::thin, k],
                            chains["kernel"][burn::thin, k]).pvalue
             for k in cols]
    for k in idx.ecorr:
        assert np.std(chains["kernel"][burn:, k]) > 1e-3
    assert min(pvals) > 1e-4, pvals
    assert np.median(pvals) > 0.05, pvals
    for mode in chains:
        med = np.median(chains[mode][burn:, idx.ecorr[0]])
        assert abs(med - (-6.3)) < 0.35, (mode, med)


def test_kernel_jax_vs_numpy_ks(ng_psr, tmp_path):
    """Device vs f64-oracle equivalence in kernel mode."""
    pta = _model(ng_psr)
    x0 = pta.initial_sample(np.random.default_rng(29))
    chains = {}
    for backend, seed in [("jax", 41), ("numpy", 42)]:
        g = PulsarBlockGibbs(pta, backend=backend, ecorrsample="kernel",
                             seed=seed, progress=False,
                             white_adapt_iters=600)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2600)
    burn, thin = 400, 10
    idx = BlockIndex.build(pta.param_names)
    cols = list(idx.ecorr) + list(idx.white) + list(idx.rho[:2])
    pvals = [stats.ks_2samp(chains["jax"][burn::thin, k],
                            chains["numpy"][burn::thin, k]).pvalue
             for k in cols]
    assert min(pvals) > 1e-4, pvals
    assert np.median(pvals) > 0.05, pvals


def test_kernel_fullmarg_equals_basis(ng_psr):
    """The b-marginalized likelihood integrates out every coefficient, so
    basis and kernel representations of the same model must give the SAME
    value at the same hyperparameters — an exact (not statistical)
    equivalence check, on both oracles."""
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    pta = _model(ng_psr)
    rng = np.random.default_rng(5)
    for cls, kw in ((NumpyGibbs, {}), (NumpyPTAGibbs, {})):
        gb = cls(pta, seed=0, **kw)
        gk = cls(pta, ecorrsample="kernel", seed=0, **kw)
        for _ in range(4):
            x = pta.initial_sample(rng)
            vb, vk = gb.lnlike_fullmarg(x), gk.lnlike_fullmarg(x)
            gb.invalidate_cache()
            gk.invalidate_cache()
            np.testing.assert_allclose(vk, vb, rtol=1e-8, err_msg=cls.__name__)


def test_kernel_pta_oracle_sweeps(ng_psr):
    """The multi-pulsar oracle runs kernel mode end-to-end (two flagged
    pulsars sharing a common spectrum) and stays finite."""
    import dataclasses

    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    p2 = dataclasses.replace(ng_psr, name="FAKE_K2",
                             residuals=ng_psr.residuals[::-1].copy())
    pta = model_general([ng_psr, p2], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=4)
    g = NumpyPTAGibbs(pta, ecorrsample="kernel", seed=4,
                      white_adapt_iters=100)
    x = g.sweep(pta.initial_sample(np.random.default_rng(1)), first=True)
    for _ in range(5):
        x = g.sweep(x)
    assert np.all(np.isfinite(x))
    assert g.nb_total == sum(T.shape[1] for T in g._T)


def test_kernel_resume_bitwise(ng_psr, tmp_path):
    pta = _model(ng_psr)
    x0 = pta.initial_sample(np.random.default_rng(3))
    kw = dict(backend="jax", ecorrsample="kernel", seed=13, progress=False,
              white_adapt_iters=100, chunk_size=20)
    full = PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "full"), niter=100, save_every=20)
    PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "split"), niter=60, save_every=20)
    resumed = PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "split"), niter=100, resume=True,
        save_every=20)
    assert np.all(np.isfinite(full))
    np.testing.assert_array_equal(resumed, full)
