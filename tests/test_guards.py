"""Runtime guards (analysis.guards): a compiled sweep runs clean under
the transfer guard, the recompile counter sees compiles/retraces and
nothing on cache hits, and debug_nans toggles scoped."""

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.analysis import guards
from pulsar_timing_gibbsspec_tpu.data.dataset import Pulsar
from pulsar_timing_gibbsspec_tpu.models.factory import model_general

DAY = 86400.0


@pytest.fixture(scope="module")
def small_pta():
    """Tiny synthetic single-pulsar PTA (no reference data needed)."""
    rng = np.random.default_rng(11)
    n = 80
    span = 6.0 * 365.25 * DAY
    toas = np.sort(rng.uniform(0.0, span, n)) + 53000.0 * DAY
    errs = np.full(n, 5e-7)
    res = errs * rng.standard_normal(n)
    t = (toas - toas.mean()) / span
    M = np.column_stack([np.ones(n), t, t * t])
    psr = Pulsar(
        name="FAKE_GUARD", toas=toas, toaerrs=errs, residuals=res,
        freqs=np.full(n, 1400.0),
        backend_flags=np.asarray(["sim"] * n, dtype=object),
        Mmat=M, fitpars=["offset", "F0", "F1"],
        flags={"pta": "NANOGrav"},
        pos=np.array([1.0, 0.0, 0.0]))
    return model_general([psr], red_var=False, white_vary=False,
                         common_psd="spectrum", common_components=4)


def test_recompile_counter_counts_compiles_not_cache_hits():
    import jax
    import jax.numpy as jnp

    def f(x):
        return x * 2.0 + 1.0

    g = jax.jit(f)
    with guards.count_recompiles() as rc:
        g(jnp.zeros((3,), jnp.float32))
        first = rc.events
        assert first > 0, "compile not observed"
        rc.reset()
        g(jnp.ones((3,), jnp.float32))        # cache hit
        assert rc.events == 0
        g(jnp.zeros((5,), jnp.float32))       # new shape -> retrace
        assert rc.retraced
    # detached: further compiles are not charged
    n = rc.events
    jax.jit(lambda x: x - 1.0)(jnp.zeros(()))
    assert rc.events == n


def test_recompile_counter_phase_attribution():
    import jax
    import jax.numpy as jnp

    with guards.count_recompiles() as rc:
        rc.phase("warmup")
        jax.jit(lambda x: x * 3.0)(jnp.zeros((2,), jnp.float32))
        assert rc.per_phase["warmup"] > 0
        rc.phase("steady")
        assert rc.per_phase["steady"] == 0
        jax.jit(lambda x: x * 5.0)(jnp.zeros((2,), jnp.float32))
        assert rc.per_phase["steady"] > 0
        # warmup compiles cannot pollute the steady bucket
        assert rc.unplanned("warmup") == rc.per_phase["warmup"]


def test_recompile_counter_planned_window_not_charged_as_unplanned():
    import jax
    import jax.numpy as jnp

    with guards.count_recompiles() as rc:
        rc.phase("steady")
        # a legitimate cache-miss compile, bracketed the way the driver
        # brackets its chunk dispatch: planned, not a retrace
        with guards.planned_compile():
            jax.jit(lambda x: x * 7.0)(jnp.zeros((2,), jnp.float32))
        assert rc.per_phase["steady"] > 0
        assert rc.unplanned("steady") == 0
        # an unbracketed compile in the same phase IS a retrace
        jax.jit(lambda x: x * 11.0)(jnp.zeros((2,), jnp.float32))
        assert rc.unplanned("steady") > 0


def test_recompile_counter_reset_zeroes_phases():
    import jax
    import jax.numpy as jnp

    with guards.count_recompiles() as rc:
        rc.phase("a")
        jax.jit(lambda x: x * 13.0)(jnp.zeros((2,), jnp.float32))
        rc.reset()
        assert rc.events == 0 and rc.unplanned("a") == 0


def test_recompile_counter_exported_via_profiling():
    import jax
    import jax.numpy as jnp

    from pulsar_timing_gibbsspec_tpu import profiling

    with profiling.recompile_counter() as rc:
        jax.jit(lambda x: x + 3.0)(jnp.zeros((2,), jnp.float32))
    assert rc.events > 0


def test_no_transfers_blocks_implicit_transfer():
    import jax
    import jax.numpy as jnp

    g = jax.jit(lambda x: x + 1.0)
    host = np.zeros((4,), np.float32)
    dev = jnp.asarray(host)
    g(host)                       # warm up with the host-arg signature
    with guards.no_transfers():
        g(dev)                    # all-device: fine
        with pytest.raises(Exception, match="[Tt]ransfer"):
            g(host)               # implicit host->device: trips


def test_debug_nans_scoped():
    import jax

    prev = jax.config.jax_debug_nans
    with guards.debug_nans():
        assert jax.config.jax_debug_nans
    assert jax.config.jax_debug_nans == prev


@pytest.mark.parametrize("external_guard", [False, True])
def test_compiled_sweep_under_transfer_guard(small_pta, external_guard):
    """The steady chunk loop is transfer-clean: both the driver's own
    transfer_guard=True mode and an external no_transfers() around the
    steady yields run without tripping (acceptance criterion)."""
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import \
        JaxGibbsDriver

    drv = JaxGibbsDriver(small_pta, seed=3, common_rho=True,
                         warmup_sweeps=2, chunk_size=4, nchains=1,
                         transfer_guard=not external_guard)
    niter = 12
    x0 = small_pta.initial_sample(np.random.default_rng(0))
    cshape, bshape = drv.chain_shapes(niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    it = drv.run(x0, chain, bchain, 0, niter)
    done = next(it)               # warmup + compile: transfers expected
    if external_guard:
        with guards.no_transfers():
            for done in it:
                pass
    else:
        for done in it:
            pass
    assert done == niter
    assert np.all(np.isfinite(chain))


# ---------------------------------------------------------------------------
# settings validation: the segmented-Gram segment lengths
# ---------------------------------------------------------------------------

def test_settings_rejects_bad_gram_seg_lengths():
    from pulsar_timing_gibbsspec_tpu.config import Settings, SettingsError

    assert Settings(gram_seg_len=96).gram_seg_len == 96
    for bad in (0, -3, 1.5, "96", True, None):
        with pytest.raises(SettingsError):
            Settings(gram_seg_len=bad)
        with pytest.raises(SettingsError):
            Settings(gram_seg_len_exact=bad)


def test_settings_validates_gram_seg_env_overrides(monkeypatch):
    from pulsar_timing_gibbsspec_tpu.config import Settings, SettingsError

    monkeypatch.setenv("PTGIBBS_GRAM_SEG", "48")
    assert Settings().gram_seg_len == 48
    monkeypatch.setenv("PTGIBBS_GRAM_SEG", "0")
    with pytest.raises(SettingsError, match="positive"):
        Settings()
    monkeypatch.setenv("PTGIBBS_GRAM_SEG", "ninety-six")
    with pytest.raises(SettingsError, match="not an integer"):
        Settings()
    monkeypatch.delenv("PTGIBBS_GRAM_SEG")
    monkeypatch.setenv("PTGIBBS_GRAM_SEG_EXACT", "-1")
    with pytest.raises(SettingsError, match="positive"):
        Settings()


# ---------------------------------------------------------------------------
# settings validation: the kernel tier
# ---------------------------------------------------------------------------

def test_settings_rejects_bad_kernel_tier():
    from pulsar_timing_gibbsspec_tpu.config import Settings, SettingsError

    for ok in ("pallas", "xla", "auto"):
        assert Settings(kernel_tier=ok).kernel_tier == ok
    for bad in ("mosaic", "", "XLA!", 1, True, None):
        with pytest.raises(SettingsError, match="kernel_tier"):
            Settings(kernel_tier=bad)


def test_settings_validates_kernel_tier_env_override(monkeypatch):
    from pulsar_timing_gibbsspec_tpu.config import Settings, SettingsError

    assert Settings().kernel_tier == "auto"          # default
    monkeypatch.setenv("PTGIBBS_KERNEL_TIER", "pallas")
    assert Settings().kernel_tier == "pallas"
    monkeypatch.setenv("PTGIBBS_KERNEL_TIER", " XLA ")
    assert Settings().kernel_tier == "xla"           # normalized
    monkeypatch.setenv("PTGIBBS_KERNEL_TIER", "tpu")
    with pytest.raises(SettingsError, match="must be one of"):
        Settings()


def test_auto_tier_resolves_to_xla_off_tpu():
    """The dispatch resolution the default tier lands on in this CPU
    container — Mosaic is TPU-only, so "auto" must mean the reference
    lowering here, and an explicit "pallas" is honored only when the
    Pallas module imports (fallback, not failure)."""
    import jax

    from pulsar_timing_gibbsspec_tpu.ops import kernels

    assert jax.default_backend() != "tpu"
    assert kernels.resolve_tier("auto") == "xla"
    assert kernels.resolve_tier("pallas") in ("pallas", "xla")
