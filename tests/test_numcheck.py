"""numcheck: the static precision-flow / reassociation / exact-body
auditor (``analysis/numcheck``).

The mutation self-test is the core: seed the exact defects the tool
exists to catch — an ``.astype(jnp.float32)`` injected into a synthetic
Gram accumulation (N1), a deleted f64 exact-body pairing (N4) — and
prove the rules fire; then prove the disciplined twins stay quiet.
Plus the N2/N3 positive/negative fixtures, the N5 ledger drift check,
pragma suppression, the justified-baseline gate, and the committed
contracts themselves (lint-marked — those trace the real entries).
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

from pulsar_timing_gibbsspec_tpu.analysis.baseline import (
    check_justifications, load_justified_baseline)
from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.walk import trace_jaxpr
from pulsar_timing_gibbsspec_tpu.analysis.numcheck.ledger import (
    check_ledger, error_ledger)
from pulsar_timing_gibbsspec_tpu.analysis.numcheck.pairs import (
    check_pair, compare_signatures)
from pulsar_timing_gibbsspec_tpu.analysis.numcheck.rules import RULES
from pulsar_timing_gibbsspec_tpu.analysis.numcheck.runner import (
    _suppressed, analyze_traced, discover_contracts, pragma_rules)

ROOT = Path(__file__).resolve().parents[1]

F64 = jax.ShapeDtypeStruct


def rules_of(findings):
    return [r for r, _msg, _f, _ln in findings]


def tr(fn, *avals):
    return trace_jaxpr(fn, tuple(avals))


# ---------------------------------------------------------------------------
# mutation self-test: the seeded N1 defect
# ---------------------------------------------------------------------------

def _gram_mutant(vs):
    """A Gram accumulation with the classic silent-mixed-precision bug
    seeded: the f64 rows are narrowed to f32 *inside* the accumulation
    loop, and the narrowed Gram feeds a Cholesky."""
    def step(g, v):
        v32 = v.astype(jnp.float32)     # the seeded defect
        return g + jnp.outer(v32, v32), None
    g, _ = jax.lax.scan(step, jnp.zeros((8, 8), jnp.float32), vs)
    return jnp.linalg.cholesky(g + 100.0 * jnp.eye(8, dtype=jnp.float32))


def _gram_disciplined(vs):
    """The twin without the defect: accumulate in f64, narrow only the
    final factor (outside any accumulation path's upstream)."""
    def step(g, v):
        return g + jnp.outer(v, v), None
    g, _ = jax.lax.scan(step, jnp.zeros((8, 8), jnp.float64), vs)
    return jnp.linalg.cholesky(g + 100.0 * jnp.eye(8, dtype=jnp.float64))


@pytest.mark.lint
def test_n1_fires_on_astype_injected_into_gram_accumulation():
    closed = tr(_gram_mutant, F64((16, 8), jnp.float64))
    findings, rep = analyze_traced(closed)
    assert "N1" in rules_of(findings)
    n1 = [m for r, m, _f, _ln in findings if r == "N1"]
    assert any("cholesky" in m or "dot_general" in m for m in n1)
    # the census fingerprints the seeded narrow
    assert sum(rep.narrow_census().values()) == 1


def test_n1_quiet_when_the_narrow_is_a_declared_island():
    closed = tr(_gram_mutant, F64((16, 8), jnp.float64))
    findings, _ = analyze_traced(
        closed, {"islands": ["test_numcheck.py"]})
    assert "N1" not in rules_of(findings)


def test_n1_quiet_on_the_disciplined_twin():
    closed = tr(_gram_disciplined, F64((16, 8), jnp.float64))
    findings, rep = analyze_traced(closed)
    assert "N1" not in rules_of(findings)
    assert rep.narrow_census() == {}


def test_scan_carried_accumulation_is_an_n2_reduction():
    # the Gram loop's carry is an add-chain over its own input: a
    # reassociation-sensitive reduction of length = trip count
    closed = tr(_gram_disciplined, F64((16, 8), jnp.float64))
    _, rep = analyze_traced(closed)
    carries = [r for r in rep.reductions if r.kind == "scan_carry"]
    assert carries and carries[0].length == 16


# ---------------------------------------------------------------------------
# mutation self-test: the deleted exact-body pairing (N4)
# ---------------------------------------------------------------------------

class _FakeCM:
    nx, P, Bmax = 4, 2, 3
    dtype = np.dtype("float32")
    cdtype = np.dtype("float32")
    y = np.zeros(8, np.float32)
    has_ke = False


class _PairedDriver:
    """A driver honouring the PR 3 convention: both bodies exist and
    share one abstract signature."""

    exact_every = 16
    cm = _FakeCM()

    def _aux(self):
        # chain-stacked aux, axis 0 = chains (as drv._aux() returns it)
        return (np.zeros((4, 2), np.float32),)

    def _sweep_body(self, bdraw):
        def body(carry, key, aux, t, beta=None):
            x, b, u = carry
            return (x + aux[0].sum(), b, u)
        return body


class _UnpairedDriver(_PairedDriver):
    """The seeded defect: the f64 exact body was deleted."""

    def _sweep_body(self, bdraw):
        if bdraw == "exact":
            raise AttributeError("exact body deleted by mutation")
        return super()._sweep_body(bdraw)


class _DriftedDriver(_PairedDriver):
    """The subtler defect: the exact body's signature drifted, so the
    chunk's lax.cond could no longer alternate the pair."""

    def _sweep_body(self, bdraw):
        def body(carry, key, aux, t, beta=None):
            x, b, u = carry
            if bdraw == "exact":
                x = x.astype(jnp.float64)
            return (x + aux[0].sum(), b, u)
        return body


def test_n4_quiet_on_a_paired_driver():
    assert check_pair(_PairedDriver(), {"exact_every": 16}) == []


@pytest.mark.lint
def test_n4_fires_when_the_exact_body_is_deleted():
    f = check_pair(_UnpairedDriver(), {"exact_every": 16})
    assert rules_of(f) == ["N4"]
    assert "no registered f64 exact body" in f[0][1]


def test_n4_fires_on_signature_drift():
    f = check_pair(_DriftedDriver(), {"exact_every": 16})
    assert rules_of(f) == ["N4"]
    assert "signature mismatch" in f[0][1]


def test_n4_cadence_must_be_declared_and_match():
    f = check_pair(_PairedDriver(), {})
    assert rules_of(f) == ["N4"] and "no exact_every" in f[0][1]
    f = check_pair(_PairedDriver(), {"exact_every": 8})
    assert rules_of(f) == ["N4"] and "does not match" in f[0][1]


def test_n4_kernel_ecorr_runs_exact_only_no_pair_required():
    class KE(_UnpairedDriver):
        class cm(_FakeCM):
            has_ke = True
    assert check_pair(KE(), {"exact_every": 16}) == []


def test_compare_signatures_reports_arity_and_leaf_drift():
    assert compare_signatures([((4,), "f32")], [((4,), "f32")]) == []
    a = compare_signatures([((4,), "f32")], [((4,), "f32"), ((2,), "f32")])
    assert "arity" in a[0]
    m = compare_signatures([((4,), "float32")], [((4,), "float64")])
    assert "mismatch at leaf 0" in m[0]


# ---------------------------------------------------------------------------
# N2: unpinned reassociation
# ---------------------------------------------------------------------------

def _big_sum(x):
    return jnp.sum(x)


def test_n2_fires_without_a_declared_order():
    closed = tr(_big_sum, F64((64,), jnp.float32))
    findings, _ = analyze_traced(closed)
    assert "N2" in rules_of(findings)


def test_n2_quiet_with_a_pinned_order():
    closed = tr(_big_sum, F64((64,), jnp.float32))
    findings, _ = analyze_traced(closed, {"declared_orders": [
        {"fn": "test_numcheck.py",
         "order": "single fused reduce in trace order"}]})
    assert "N2" not in rules_of(findings)


def test_n2_an_empty_order_note_does_not_count():
    closed = tr(_big_sum, F64((64,), jnp.float32))
    findings, _ = analyze_traced(closed, {"declared_orders": [
        {"fn": "test_numcheck.py", "order": "  "}]})
    assert "N2" in rules_of(findings)


def test_small_reductions_are_below_the_n2_floor():
    closed = tr(_big_sum, F64((4,), jnp.float32))
    findings, rep = analyze_traced(closed)
    assert "N2" not in rules_of(findings) and rep.reductions == []


# ---------------------------------------------------------------------------
# N3: default-precision dots on once-f64 data
# ---------------------------------------------------------------------------

def _tainted_dot(a, b):
    return a.astype(jnp.float32) @ b


def test_n3_fires_on_default_precision_tainted_f32_dot():
    closed = tr(_tainted_dot,
                F64((8, 8), jnp.float64), F64((8, 8), jnp.float32))
    findings, _ = analyze_traced(
        closed, {"islands": ["test_numcheck.py"]})
    assert "N3" in rules_of(findings)


def test_n3_an_island_does_not_excuse_the_tf32_hazard():
    # islands excuse the *downcast* (N1), never the precision flag
    closed = tr(_tainted_dot,
                F64((8, 8), jnp.float64), F64((8, 8), jnp.float32))
    findings, _ = analyze_traced(
        closed, {"islands": ["test_numcheck.py"]})
    assert "N1" not in rules_of(findings)
    assert "N3" in rules_of(findings)


def test_n3_quiet_at_highest_precision():
    def f(a, b):
        return jax.lax.dot(a.astype(jnp.float32), b,
                           precision="highest")
    closed = tr(f, F64((8, 8), jnp.float64), F64((8, 8), jnp.float32))
    findings, _ = analyze_traced(closed, {"islands": ["test_numcheck.py"]})
    assert "N3" not in rules_of(findings)


def test_n3_quiet_on_never_f64_data():
    def f(a, b):
        return a @ b
    closed = tr(f, F64((8, 8), jnp.float32), F64((8, 8), jnp.float32))
    findings, _ = analyze_traced(closed)
    assert "N3" not in rules_of(findings)


# ---------------------------------------------------------------------------
# census pin
# ---------------------------------------------------------------------------

def test_census_rule_flags_topology_drift():
    closed = tr(_gram_mutant, F64((16, 8), jnp.float64))
    _, rep = analyze_traced(closed)
    pin = rep.narrow_census()
    findings, _ = analyze_traced(
        closed, {"islands": ["test_numcheck.py"],
                 "narrow_census": pin,
                 "declared_orders": [{"fn": "test_numcheck.py",
                                      "order": "trace order"}]})
    assert findings == []
    drifted, _ = analyze_traced(
        closed, {"islands": ["test_numcheck.py"], "narrow_census": {}})
    assert "census" in rules_of(drifted)


# ---------------------------------------------------------------------------
# N5: the error ledger
# ---------------------------------------------------------------------------

def test_error_ledger_reports_chains_and_ulp_bounds():
    closed = tr(_gram_disciplined, F64((16, 8), jnp.float64))
    led = error_ledger(closed)
    assert "float64" in led["max_ulp_rel"]
    eps64 = float(np.finfo(np.float64).eps)
    # the Cholesky chain (n=8) dominates the 8-wide outer products
    assert led["max_ulp_rel"]["float64"] >= 8 * eps64
    assert any(b["block"].startswith("test_numcheck.py")
               for b in led["blocks"])


def test_n5_drift_unpinned_and_vanished_dtypes():
    led = {"max_ulp_rel": {"float32": 1.2e-4}}
    ok = {"ledger": {"max_ulp_rel": {"float32": 1.2e-4}}}
    assert check_ledger(led, ok) == []
    within = {"ledger": {"max_ulp_rel": {"float32": 1.0e-4}}}
    assert check_ledger(led, within) == []          # inside ±25%
    drift = {"ledger": {"max_ulp_rel": {"float32": 0.5e-4}}}
    assert rules_of(check_ledger(led, drift)) == ["N5"]
    unpinned = {"ledger": {"max_ulp_rel": {}}}
    assert "does not pin" in check_ledger(led, unpinned)[0][1]
    vanished = {"ledger": {"max_ulp_rel": {"float32": 1.2e-4,
                                           "float64": 1e-15}}}
    assert any("no longer has" in m
               for _r, m, _f, _ln in check_ledger(led, vanished))
    assert check_ledger(led, {}) == []              # no pin, no rule


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------

def test_pragma_parsing():
    assert pragma_rules("x = 1  # numcheck: disable=N1,N3") == {"N1", "N3"}
    assert pragma_rules("y = 2  # numcheck: disable=all") == {"ALL"}
    assert pragma_rules("z = 3  # no pragma here") == set()


def test_pragma_suppresses_by_source_line(tmp_path):
    src = tmp_path / "s.py"
    src.write_text("a = 1\nb = 2  # numcheck: disable=N2\n")
    assert _suppressed("N2", str(src), 2)
    assert not _suppressed("N1", str(src), 2)
    assert not _suppressed("N2", str(src), 1)
    assert not _suppressed("N2", None, None)


# ---------------------------------------------------------------------------
# the committed contracts and the justified-baseline gate
# ---------------------------------------------------------------------------

def test_committed_contracts_are_discovered_and_tagged():
    names = {c["name"] for c in discover_contracts()}
    assert {"numerics_crn", "numerics_hd_joint"} <= names
    fast = {c["name"] for c in discover_contracts(fast_only=True)}
    assert {"numerics_crn", "numerics_hd_joint"} <= fast


def test_jaxprcheck_discovery_skips_numcheck_contracts():
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.runner import (
        discover_contracts as jp_discover)
    names = {c["name"] for c in jp_discover()}
    assert not names & {"numerics_crn", "numerics_hd_joint"}


def test_committed_baseline_is_fully_justified():
    data = load_justified_baseline(ROOT / "numcheck_baseline.json")
    assert check_justifications(data) == []


def test_todo_stub_is_not_a_justification():
    data = {"violations": {"m.py": {"N1": 1}},
            "justifications": {"m.py [N1]": "TODO: fill in"}}
    assert check_justifications(data) == [("m.py", "N1")]
    data["justifications"]["m.py [N1]"] = "two-float kernel by design"
    assert check_justifications(data) == []


def test_rule_table_is_closed():
    assert set(RULES) == {"N1", "N2", "N3", "N4", "N5"}


# ---------------------------------------------------------------------------
# CLI / wrappers (lint tier: these trace the real entry builders)
# ---------------------------------------------------------------------------

def _run_cli(*args, cwd=ROOT):
    env = dict(os.environ, PYTHONPATH=str(ROOT))
    return subprocess.run(
        [sys.executable, "-m",
         "pulsar_timing_gibbsspec_tpu.analysis.numcheck", *args],
        cwd=cwd, env=env, capture_output=True, text=True)


@pytest.mark.lint
def test_cli_head_contracts_audit_clean(tmp_path):
    led = tmp_path / "ledger.json"
    r = _run_cli("--fast", "--ledger", str(led))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
    ledgers = json.loads(led.read_text())
    assert set(ledgers) == {"numerics_crn", "numerics_hd_joint"}
    for l in ledgers.values():
        assert l["max_ulp_rel"]


def test_cli_exits_2_without_contracts(tmp_path):
    r = _run_cli("--contracts", str(tmp_path))
    assert r.returncode == 2
    assert "no contracts" in r.stderr


def test_cli_fails_on_unjustified_baseline(tmp_path):
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({
        "violations": {"contracts/numerics_crn.json": {"N1": 1}},
        "justifications": {}}))
    # a bogus entry keeps this test off the (slow) tracing path: the
    # contract errors out as an `error` violation, the justification
    # gate still runs
    empty = tmp_path / "contracts"
    empty.mkdir()
    c = textwrap.dedent("""\
        {"name": "noop", "tool": "numcheck", "fast": true,
         "entry": {"entry": "does_not_exist"}}
    """)
    (empty / "noop.json").write_text(c)
    r = _run_cli("--contracts", str(empty), "--baseline", str(bl))
    assert r.returncode == 1
    assert "without justification" in r.stdout


def test_tools_numcheck_wrapper_importable():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "tools_numcheck", ROOT / "tools" / "numcheck.py")
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)          # no side effects on import
    assert callable(m.main)
