"""Standing-model lifecycle: checkpoint lineage, append-TOAs migration.

The fast tier pins everything that does not need a compiled sampler:
the lineage hash chain (fork / walk / verify / degrade-to-ancestor),
the typed layout refusal naming the FIRST mismatched pulsar, the
migration planner's refusals and the ``BucketOverflow`` hint, the
``MigrationTicket`` state machine (audited by racecheck), the
journal's per-entry ``schema_version`` refusal, and the ``/v1/append``
wire validation (hostile input binds nothing).

The ``slow``-marked tests compile samplers: the facade fork under
``record_every`` thinning stays bitwise; a service-level in-bucket
append keeps the retained prefix bitwise; a cross-bucket migration's
continuation is statistically indistinguishable from a cold run on the
grown dataset (KS gate, same threshold as the backend-parity gates);
the gateway append replays idempotently across a seam kill and a
restart; and ``tools/chaos_probe.py --scenario append`` holds its
contract end to end.
"""

import json
import types

import numpy as np
import pytest

NITER = 12


def _chainstore(outdir, rows=6, nx=3, nb=4, extra=None, seed=0):
    """A minimal verified checkpoint set (no sampler needed)."""
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore

    rng = np.random.default_rng(seed)
    store = ChainStore(outdir, [f"p{i}" for i in range(nx)],
                       [f"b{i}" for i in range(nb)])
    chain = rng.standard_normal((rows, nx))
    bchain = rng.standard_normal((rows, nb))
    adapt = {"x": chain[-1], "b": bchain[-1].reshape(2, 2),
             "tenant_id": np.asarray(0, np.int64)}
    store.save(chain, bchain, rows, adapt_state=adapt, extra=extra or {})
    return chain, bchain


# -- lineage hash chain ---------------------------------------------------

def test_fork_walk_verify_and_degrade_to_ancestor(tmp_path):
    """fork_generation chains the child to the parent's manifest hash;
    a severed link degrades resolution to the newest verified ancestor;
    a fully broken chain refuses typed with the per-generation report."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults, lineage

    parent, child = tmp_path / "gen0", tmp_path / "gen1"
    _chainstore(parent, extra={"layout": {"pulsars": ["A", "B"]}})
    man = lineage.fork_generation(parent, child,
                                  dataset_sha256="d" * 64,
                                  bucket=(2, 40, 24, 3))
    lin = man["lineage"]
    assert lin["generation"] == 1 and lin["parent_dir"] == str(parent)
    assert lin["dataset_sha256"] == "d" * 64
    assert lineage.verify_generation(child)["ok"]
    # the parent's extras ride along (nothing silently dropped)
    assert man["layout"]["pulsars"] == ["A", "B"]

    # idempotent: a second fork from the same parent state is a no-op
    man2 = lineage.fork_generation(parent, child)
    assert man2["lineage"]["parent_manifest_sha256"] == \
        lin["parent_manifest_sha256"]

    ancestry = lineage.walk(child)
    assert [a["generation"] for a in ancestry] == [1, 0]
    resolved, report = lineage.resolve_verified(child)
    assert str(resolved) == str(child) and report[0]["ok"]

    # sever the hash chain (both manifests, so .bak cannot heal it):
    # resolution degrades to the verified parent, typed report attached
    faults._corrupt_lineage(child)
    degraded, report = lineage.resolve_verified(child)
    assert str(degraded) == str(parent)
    assert [(r["generation"], r["ok"]) for r in report] == \
        [(1, False), (0, True)]
    assert "hash chain broken" in report[0]["why"]

    # break the ancestor too: LineageError carries the walk report
    for name in ("manifest.json", "manifest.bak.json"):
        p = parent / name
        if p.exists():
            p.write_text("{broken")
    with pytest.raises(lineage.LineageError) as ei:
        lineage.resolve_verified(child)
    assert len(ei.value.report) == 2
    assert not any(r["ok"] for r in ei.value.report)


def test_fork_tolerates_pruned_ancestor(tmp_path):
    """A deleted parent directory is a pruned ancestor: the child's
    linkage still verifies (the chain is only as long as what's kept)."""
    import shutil

    from pulsar_timing_gibbsspec_tpu.runtime import lineage

    parent, child = tmp_path / "gen0", tmp_path / "gen1"
    _chainstore(parent)
    lineage.fork_generation(parent, child)
    shutil.rmtree(parent)
    rep = lineage.verify_generation(child)
    assert rep["ok"] and rep["generation"] == 1
    resolved, _ = lineage.resolve_verified(child)
    assert str(resolved) == str(child)


# -- typed layout refusal (S1) --------------------------------------------

def test_layout_mismatch_names_first_mismatched_pulsar(tmp_path):
    from pulsar_timing_gibbsspec_tpu.runtime.integrity import (
        LayoutMismatch, check_layout_pulsars)

    # first mismatch wins, by index and by name
    with pytest.raises(LayoutMismatch) as ei:
        check_layout_pulsars(tmp_path, ["A", "B", "C"], ["A", "X", "C"])
    err = ei.value
    assert (err.index, err.expected, err.got) == (1, "B", "X")
    assert "pulsar order mismatch at index 1" in str(err)
    assert "'B'" in str(err) and "'X'" in str(err)

    # a strict-prefix PTA refuses at the boundary
    with pytest.raises(LayoutMismatch) as ei:
        check_layout_pulsars(tmp_path, ["A", "B"], ["A"])
    assert ei.value.index == 1 and ei.value.got == "<none>"

    # equal layouts and layout-less checkpoints pass
    check_layout_pulsars(tmp_path, ["A", "B"], ["A", "B"])
    check_layout_pulsars(tmp_path, [], ["A", "B"])


def test_load_resume_refuses_layout_disagreement(tmp_path):
    from pulsar_timing_gibbsspec_tpu.runtime import integrity

    _chainstore(tmp_path / "ck",
                extra={"layout": {"pulsars": ["PSR0", "PSR1"]}})
    pta = types.SimpleNamespace(pulsars=["PSR0", "OTHER"])
    with pytest.raises(integrity.LayoutMismatch) as ei:
        integrity.load_resume(tmp_path / "ck", pta=pta)
    assert (ei.value.index, ei.value.expected, ei.value.got) == \
        (1, "PSR1", "OTHER")
    # matching layout loads fine
    got = integrity.load_resume(
        tmp_path / "ck", pta=types.SimpleNamespace(
            pulsars=["PSR0", "PSR1"]))
    assert got is not None and got[2] == 6


# -- migration planner + overflow hint (S2) -------------------------------

def test_bucket_overflow_hint_names_covering_bucket():
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (
        BucketOverflow, BucketSpec, BucketTable, DatasetShape,
        next_covering)

    table = BucketTable([BucketSpec(2, 40, 24, 3)])
    shape = DatasetShape(2, 99, 24, 3)
    with pytest.raises(BucketOverflow) as ei:
        table.route(shape)
    exc = ei.value
    assert "migration hint" in str(exc)
    hint = exc.hint
    assert hint.covers(shape)
    assert str(hint.as_tuple()) in str(exc)
    # axis-doubling from the nearest base, modes copied exactly
    assert next_covering(shape, base=BucketSpec(2, 40, 24, 3)).modes == 3


def test_plan_migration_kinds_and_typed_refusals():
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (
        BucketSpec, BucketTable, DatasetShape, plan_migration)

    table = BucketTable([BucketSpec(2, 40, 24, 3),
                         BucketSpec(2, 64, 32, 3)])
    parent = table.buckets[0]
    grown_in = DatasetShape(2, 38, 24, 3)
    grown_out = DatasetShape(2, 50, 24, 3)

    plan = plan_migration(table, parent, grown_in)
    assert plan.in_place and plan.child_bucket is parent

    plan = plan_migration(table, parent, grown_out)
    assert not plan.in_place
    assert plan.child_bucket.as_tuple() == (2, 64, 32, 3)

    # parameter-space changes are NOT migrations: typed refusals
    with pytest.raises(ValueError, match="mode count"):
        plan_migration(table, parent, DatasetShape(2, 38, 24, 4))
    with pytest.raises(ValueError, match="pulsar"):
        plan_migration(table, parent, DatasetShape(3, 38, 24, 3))


# -- migration state machine (racecheck M1-M3) ----------------------------

def test_migration_ticket_state_machine():
    from pulsar_timing_gibbsspec_tpu.serve.jobs import (
        MIGRATION_STATES, MigrationTicket)

    t = MigrationTicket("j")
    assert t.state == "planned" and t.state in MIGRATION_STATES
    t.journaled()
    assert t.state == "journaled"
    t.forked()
    assert t.state == "forked"
    t.journaled()                       # illegal: forked stays forked
    assert t.state == "forked"
    t.readmitted()
    assert t.state == "readmitted"
    t.abort()                           # readmitted is final
    assert t.state == "readmitted"

    t2 = MigrationTicket("k")
    t2.forked()                         # service path: no journal leg
    assert t2.state == "forked"
    t2.abort()
    assert t2.state == "aborted"
    t2.readmitted()                     # aborted is final
    assert t2.state == "aborted"


# -- journal entry schema_version (S3) ------------------------------------

def _table():
    from pulsar_timing_gibbsspec_tpu.serve.buckets import (BucketSpec,
                                                           BucketTable)

    return BucketTable([BucketSpec(2, 40, 24, 3),
                        BucketSpec(2, 64, 32, 3)])


def test_journal_refuses_unknown_entry_schema(tmp_path):
    from pulsar_timing_gibbsspec_tpu.runtime.integrity import CheckpointError
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway

    gw = Gateway(tmp_path / "gw", _table())
    with gw._cond:
        gw._entries["k0"] = {
            "job_id": "g00000", "tenant_id": 0, "niter": 4,
            "payload": {"synthetic": {}}, "payload_sha256": "0" * 64,
            "outdir": str(tmp_path / "gw" / "jobs" / "g00000"),
            "dedupe_key": "k0", "state": "done",
            "deadline_unix": None, "schema_version": 99}
        gw._write_journal()
    with pytest.raises(CheckpointError) as ei:
        Gateway(tmp_path / "gw", _table())
    msg = str(ei.value)
    assert "schema_version" in msg and "99" in msg and "k0" in msg

    # a version-1 entry (and a version-less pre-field entry) both load
    with gw._cond:
        gw._entries["k0"]["schema_version"] = 1
        gw._entries["k1"] = dict(gw._entries["k0"], dedupe_key="k1",
                                 job_id="g00001")
        del gw._entries["k1"]["schema_version"]
        gw._write_journal()
    gw2 = Gateway(tmp_path / "gw", _table())
    assert set(gw2._entries) == {"k0", "k1"}


# -- /v1/append wire validation (fast: every path refuses pre-build) ------

def test_append_wire_validation_binds_nothing(tmp_path):
    from pulsar_timing_gibbsspec_tpu.runtime import faults
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    gw = Gateway(tmp_path / "gw", _table())

    def post(doc):
        return gw.handle(WireRequest(
            "POST", "/v1/append", {}, {}, json.dumps(doc).encode()))

    ok = {"dedupe_key": "apd", "parent": "par",
          "append": {"add": 8, "seed": 1}, "niter": NITER}
    assert post({**ok, "append": 7}).body["error"] == "BAD_REQUEST"
    assert post({**ok, "niter": 0}).body["error"] == "BAD_REQUEST"
    resp = post(ok)                       # unknown parent dedupe key
    assert resp.status == 404 and resp.body["error"] == "NOT_FOUND"

    # the drain race refuses typed BEFORE touching the journal
    faults.clear()
    faults.inject("append_during_drain", point="gateway.append", times=1)
    try:
        resp = post(ok)
    finally:
        faults.clear()
    assert resp.status == 503 and resp.body["error"] == "DRAINING"
    assert gw._entries == {} and gw.svc.jobs == {}


# -- compiled tiers -------------------------------------------------------

def _synth(n_psr=2, ntoa=24, seed=0, nmodes=3):
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)

    psrs = synthetic_pulsars(n_psr, ntoa, tm_cols=3, seed=seed)
    return psrs, build_model(psrs, nmodes)


@pytest.mark.slow
def test_facade_fork_record_every_prefix_bitwise(tmp_path):
    """An in-bucket fork of a thinned run (``record_every=2``) copies
    the adapt carries bitwise — the resumed child continues exactly the
    stream an uninterrupted run would have produced (S4)."""
    from pulsar_timing_gibbsspec_tpu.runtime import lineage
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    _, pta = _synth(ntoa=20)
    x0 = pta.initial_sample(np.random.default_rng(0))
    kw = dict(backend="jax", seed=7, progress=False, warmup_sweeps=2,
              chunk_size=4, record_every=2)

    ref = PTABlockGibbs(pta, **kw).sample(
        x0, outdir=tmp_path / "ref", niter=32, save_every=4)
    PTABlockGibbs(pta, **kw).sample(
        x0, outdir=tmp_path / "gen0", niter=16, save_every=4)

    lineage.fork_generation(tmp_path / "gen0", tmp_path / "gen1")
    chain = PTABlockGibbs(pta, **kw).sample(
        x0, outdir=tmp_path / "gen1", niter=32, resume=True,
        save_every=4)
    assert np.array_equal(chain, ref)

    # the fork carried record_every: a mismatched resume still refuses
    bad = PTABlockGibbs(pta, **{**kw, "record_every": 1})
    with pytest.raises(Exception, match="record_every"):
        bad.sample(x0, outdir=tmp_path / "gen1", niter=32, resume=True,
                   save_every=4)


@pytest.mark.slow
def test_service_inplace_append_bitwise_prefix(tmp_path):
    """A grown dataset that still fits the parent's bucket resumes in
    place: retained rows bitwise, child re-keyed to generation 1, and
    the whole append is idempotent at the service layer."""
    from pulsar_timing_gibbsspec_tpu.serve import (BucketSpec,
                                                   BucketTable,
                                                   SamplerService)

    psrs, pta = _synth()
    grown = _grown_model(psrs, add=8)                 # ntoa 32 <= 40
    table = BucketTable([BucketSpec(2, 40, 24, 3)])
    svc = SamplerService(tmp_path, table, slots=2, chunk=4, save_every=1)
    parent = svc.submit(pta, NITER, job_id="parent", tenant_id=0)
    svc.run()
    assert parent.state == "done"

    child = svc.append_job(grown, 2 * NITER, parent_id="parent",
                           job_id="child", outdir=tmp_path / "child")
    assert child.generation == 1
    assert svc.append_job(grown, 2 * NITER, parent_id="parent",
                          job_id="child",
                          outdir=tmp_path / "child") is child
    svc.run()
    assert child.state == "done"
    assert np.array_equal(child.chain[:NITER], parent.chain[:NITER])
    assert np.array_equal(
        np.load(tmp_path / "child" / "chain.npy")[:NITER],
        np.load(tmp_path / "parent" / "chain.npy"))
    # past the prefix the child's stream is generation-keyed: it must
    # NOT continue the parent's generation-0 stream
    solo = SamplerService(tmp_path / "solo",
                          BucketTable([BucketSpec(2, 40, 24, 3)]),
                          slots=2, chunk=4, save_every=1)
    cold = solo.submit(grown, 2 * NITER, job_id="cold", tenant_id=0)
    solo.run()
    assert not np.array_equal(child.chain[NITER:], cold.chain[NITER:])


def _grown_model(psrs, add):
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model)
    from pulsar_timing_gibbsspec_tpu.data import append_polynomial_toas

    return build_model(append_polynomial_toas(psrs, add, seed=5), 3)


@pytest.mark.slow
def test_cross_bucket_append_ks_vs_cold(tmp_path):
    """A re-bucketed warm start samples the same posterior as a cold
    run on the grown dataset: retained prefix bitwise through the
    re-pad, continuation KS-indistinguishable (p > 1e-4 per column,
    the backend-parity threshold and burn/thin discipline of
    ``test_jax_vs_numpy_posterior_ks``).  Gated on the conjugate
    ``log10_rho`` columns — the EFAC/EQUAD random walks need the
    white-vary gate's much larger sample budget to KS-compare even two
    independent COLD runs."""
    from scipy import stats

    from pulsar_timing_gibbsspec_tpu.serve import (BucketSpec,
                                                   BucketTable,
                                                   SamplerService)

    psrs, pta = _synth()
    grown = _grown_model(psrs, add=24)                # ntoa 48 > 40
    table = BucketTable([BucketSpec(2, 40, 24, 3),
                         BucketSpec(2, 64, 32, 3)])
    niter, total, burn, thin = 400, 2000, 600, 5
    svc = SamplerService(tmp_path, table, slots=2, chunk=16,
                         save_every=5)
    parent = svc.submit(pta, niter, job_id="parent", tenant_id=0)
    svc.run()
    assert parent.state == "done"

    child = svc.append_job(grown, total, parent_id="parent",
                           job_id="child", outdir=tmp_path / "child")
    svc.run()
    assert child.state == "done"
    assert tuple(child.bucket.as_tuple()) == (2, 64, 32, 3)
    assert np.array_equal(child.chain[:niter], parent.chain[:niter])

    cold = svc.submit(grown, total, job_id="cold", tenant_id=7)
    svc.run()
    assert cold.state == "done"

    cols = [k for k, name in enumerate(grown.param_names)
            if "log10_rho" in name]
    assert len(cols) >= 6                 # per-pulsar red + common rho
    warm = np.asarray(child.chain[burn:total:thin], np.float64)
    ref = np.asarray(cold.chain[burn:total:thin], np.float64)
    pvals = [stats.ks_2samp(warm[:, k], ref[:, k]).pvalue
             for k in cols]
    assert min(pvals) > 1e-4, pvals
    assert np.median(pvals) > 0.05, pvals


@pytest.mark.slow
def test_gateway_append_replay_and_seam_kill(tmp_path):
    """/v1/append through ``Gateway.handle``: idempotent replay, the
    parent superseded (409 on a second append), a changed replay is a
    DEDUPE_MISMATCH, and a kill at the re-pad seam recovers through a
    restart + replay onto the ORIGINAL handle — never a torn child."""
    from pulsar_timing_gibbsspec_tpu.runtime import faults
    from pulsar_timing_gibbsspec_tpu.serve.gateway import Gateway
    from pulsar_timing_gibbsspec_tpu.serve.wire import WireRequest

    payload = {"synthetic": {"n_psr": 2, "ntoa": 24, "tm_cols": 3,
                             "seed": 0, "nmodes": 3}}
    apd = {"dedupe_key": "apd", "parent": "par",
           "append": {"add": 20, "seed": 7}, "niter": 2 * NITER}

    def post(gw, path, doc):
        return gw.handle(WireRequest("POST", path, {}, {},
                                     json.dumps(doc).encode()))

    gw = Gateway(tmp_path / "gw", _table(),
                 svc_kw={"slots": 2, "chunk": 4, "save_every": 1})
    h = post(gw, "/v1/jobs", {"dedupe_key": "par", "payload": payload,
                              "niter": NITER}).body
    gw.svc.run()

    # seam kill: the append dies typed, the child dir is never torn
    faults.clear()
    faults.inject("kill_mid_migration", point="migrate.mid_repad",
                  times=1)
    try:
        resp = post(gw, "/v1/append", apd)
    finally:
        faults.clear()
    assert resp.status == 500
    ents = gw.report()["entries"]
    assert ents["apd"]["state"] == "forking"
    assert not (tmp_path / "gw" / "jobs" / ents["apd"]["job_id"]
                / "manifest.json").exists()

    # restart: the journaled forking intent re-materializes, and the
    # client's replay resolves to the ORIGINAL new-generation handle
    gw2 = Gateway(tmp_path / "gw", _table(),
                  svc_kw={"slots": 2, "chunk": 4, "save_every": 1})
    resp = post(gw2, "/v1/append", apd)
    assert resp.status == 200 and resp.body["replayed"]
    assert resp.body["job_id"] == ents["apd"]["job_id"]
    assert resp.body["generation"] == 1
    assert resp.body["parent_job_id"] == h["job_id"]
    gw2.svc.run()

    st = gw2.handle(WireRequest(
        "GET", f"/v1/jobs/{resp.body['job_id']}", {}, {})).body
    assert st["state"] == "done"
    ents = gw2.report()["entries"]
    assert ents["par"]["state"] == "superseded"
    assert ents["par"]["superseded_by"] == resp.body["job_id"]
    # retained prefix bitwise across kill + restart + re-bucket
    pdir = tmp_path / "gw" / "jobs" / h["job_id"]
    cdir = tmp_path / "gw" / "jobs" / resp.body["job_id"]
    assert np.array_equal(np.load(cdir / "chain.npy")[:NITER],
                          np.load(pdir / "chain.npy"))

    # the superseded parent refuses further appends, typed
    resp = post(gw2, "/v1/append", {**apd, "dedupe_key": "apd2"})
    assert resp.status == 409 and resp.body["error"] == "SUPERSEDED"
    # a replayed key with a different body is a DEDUPE_MISMATCH
    resp = post(gw2, "/v1/append", {**apd, "niter": 3 * NITER})
    assert resp.status == 409 and resp.body["error"] == "DEDUPE_MISMATCH"


@pytest.mark.slow
def test_chaos_probe_append_scenario(tmp_path):
    """The packaged drill holds its contract (S4): kill at the re-pad
    seam, idempotent re-fork, bitwise prefix, degrade-to-ancestor."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "chaos_probe", Path(__file__).resolve().parents[1]
        / "tools" / "chaos_probe.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    args = types.SimpleNamespace(niter=NITER, save_every=4, at_row=6)
    ok, detail = mod.scenario_append(args, tmp_path / "probe")
    assert ok, detail
    assert detail["prefix_bitwise"] and detail["torn_free_after_kill"]
    assert detail["degrade_report"] == [(1, False), (0, True)]
