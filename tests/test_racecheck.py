"""racecheck: the static concurrency / signal-safety / buffer-lifetime
auditor (``analysis/racecheck``).

Per-pass positive AND negative fixtures (every rule has a violation it
detects and a disciplined twin it stays quiet on), the PR 13
``serve/engine.make_mux`` donation regression, pragma suppression, the
justified-baseline gate, and the whole-repo ratchet — all pure AST,
no jax import, milliseconds.
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from pulsar_timing_gibbsspec_tpu.analysis.baseline import baseline_counts
from pulsar_timing_gibbsspec_tpu.analysis.racecheck import (
    RULES, analyze_repo, analyze_sources, check_justifications,
    load_baseline_file, load_config)

ROOT = Path(__file__).resolve().parents[1]


def rules_of(findings):
    return [f.rule for f in findings]


def run(src, config=None, path="m.py"):
    return analyze_sources({path: textwrap.dedent(src)}, config)


# ---------------------------------------------------------------------------
# L1: unguarded shared writes
# ---------------------------------------------------------------------------

L1_BASE = """
    import threading
    _lock = threading.Lock()
    _reg: dict = {}
"""


def test_l1_flags_unguarded_subscript_write():
    f = run(L1_BASE + """
    def hit(k):
        _reg[k] = 1
    """)
    assert rules_of(f) == ["L1"] and "_reg" in f[0].msg


def test_l1_flags_unguarded_mutator_call_and_global_rebind():
    f = run(L1_BASE + """
    _flag = False
    def wipe():
        global _flag
        _reg.clear()
        _flag = True
    """)
    assert rules_of(f) == ["L1", "L1"]


def test_l1_quiet_under_the_lock():
    assert run(L1_BASE + """
    def hit(k):
        with _lock:
            _reg[k] = 1
    """) == []


def test_l1_local_shadow_is_not_shared_state():
    # a parameter / local / nested def named like the global shadows it
    assert run(L1_BASE + """
    def fine(_reg):
        _reg["x"] = 1
    def also_fine():
        _reg = {}
        _reg["x"] = 1
    """) == []


def test_l1_unguarded_read_is_out_of_scope():
    # GIL-atomic reference loads are the documented fast path
    assert run(L1_BASE + """
    def peek(k):
        return _reg.get(k)
    """) == []


def test_pragma_suppresses_a_finding():
    f = run(L1_BASE + """
    def hit(k):
        _reg[k] = 1  # racecheck: disable=L1
    """)
    assert f == []


# ---------------------------------------------------------------------------
# L2: lock ordering
# ---------------------------------------------------------------------------

L2_BASE = """
    import threading
    _a = threading.Lock()
    _b = threading.Lock()
"""


def test_l2_flags_opposite_acquisition_orders():
    f = run(L2_BASE + """
    def f():
        with _a:
            with _b:
                pass
    def g():
        with _b:
            with _a:
                pass
    """)
    assert "L2" in rules_of(f)
    assert any("cycle" in x.msg for x in f)


def test_l2_quiet_on_consistent_order():
    assert run(L2_BASE + """
    def f():
        with _a:
            with _b:
                pass
    def g():
        with _a:
            with _b:
                pass
    """) == []


def test_l2_flags_self_reacquire_through_a_call():
    f = run(L2_BASE + """
    def helper():
        with _a:
            pass
    def f():
        with _a:
            helper()
    """)
    assert rules_of(f) == ["L2"] and "re-acquire" in f[0].msg


def test_l2_rlock_reentry_is_safe():
    assert run("""
    import threading
    _a = threading.RLock()
    def helper():
        with _a:
            pass
    def f():
        with _a:
            helper()
    """) == []


# ---------------------------------------------------------------------------
# S1: signal-handler safety
# ---------------------------------------------------------------------------

def test_s1_flags_handler_calling_jax_numpy_and_taking_a_lock():
    f = run("""
    import signal
    import threading
    import jax.numpy as jnp
    _lock = threading.Lock()
    def _handler(signum, frame):
        with _lock:
            pass
        jnp.zeros(3)
    def install():
        signal.signal(signal.SIGTERM, _handler)
    """)
    assert sorted(rules_of(f)) == ["S1", "S1"]
    assert any("jnp" in x.msg or "jax" in x.msg for x in f)


def test_s1_follows_the_call_graph_with_a_path():
    f = run("""
    import signal
    import time
    def _handler(signum, frame):
        helper()
    def helper():
        time.sleep(1)
    def install():
        signal.signal(signal.SIGINT, _handler)
    """)
    assert rules_of(f) == ["S1"]
    assert "_handler -> " in f[0].msg and "helper" in f[0].msg


def test_s1_rlock_and_allowlisted_calls_are_clean():
    src = """
    import signal
    import threading
    import time
    _lock = threading.RLock()
    def _handler(signum, frame):
        with _lock:
            pass
        time.monotonic()
    def install():
        signal.signal(signal.SIGTERM, _handler)
    """
    assert run(src) == []


def test_s1_config_allowlist_is_the_escape_hatch():
    src = """
    import signal
    import time
    def _handler(signum, frame):
        time.sleep(0)
    def install():
        signal.signal(signal.SIGTERM, _handler)
    """
    assert rules_of(run(src)) == ["S1"]
    cfg = {"signal": {"allow_calls": ["time.sleep"], "ban_calls": ["jax."]}}
    assert run(src, cfg) == []


# ---------------------------------------------------------------------------
# C6: use-after-donate
# ---------------------------------------------------------------------------

def test_c6_flags_read_of_donated_name():
    f = run("""
    import jax
    def go(step, x, b):
        mux = jax.jit(step, donate_argnums=(1, 2))
        y = mux(0, x, b)
        return x + y
    """)
    assert rules_of(f) == ["C6"] and "'x'" in f[0].msg


def test_c6_rebinding_from_outputs_is_the_fix():
    assert run("""
    import jax
    def go(step, x, b):
        mux = jax.jit(step, donate_argnums=(1, 2))
        x, b = mux(0, x, b)
        return x + b
    """) == []


def test_c6_copy_before_the_call_is_clean():
    assert run("""
    import jax
    import numpy as np
    def go(step, x, b):
        mux = jax.jit(step, donate_argnums=(1,))
        kept = np.array(x)
        y = mux(x, b)
        return kept + y
    """) == []


def test_c6_branch_join_keeps_the_name_dead():
    f = run("""
    import jax
    def go(step, x, cold):
        mux = jax.jit(step, donate_argnums=(0,))
        if cold:
            y = mux(x)
        else:
            y = x
        return x + y
    """)
    assert rules_of(f) == ["C6"]


def test_c6_regression_pr13_make_mux_donation_pattern():
    """The PR 13 bug, reduced: ``serve/engine.make_mux`` returns a
    donating jit; the scheduler called it and then touched the stale
    ``b`` carry (host heap corruption on the CPU backend).  The factory
    return must make the binding a donating callable and the stale read
    must be flagged."""
    f = run("""
    import jax
    import numpy as np

    def mux_body(chunk):
        def mux(cm_stack, x, b, tkeys, it0):
            return x, b, x, b, x
        return mux

    def make_mux(chunk):
        if jax.default_backend() == "cpu":
            return jax.jit(mux_body(chunk))
        return jax.jit(mux_body(chunk), donate_argnums=(1, 2))

    def dispatch(stack, x, b, tkeys, it0):
        mux = make_mux(2)
        X, B, xs, bs, health = mux(stack, x, b, tkeys, it0)
        return np.asarray(b)
    """)
    assert rules_of(f) == ["C6"]
    assert "'b'" in f[0].msg and "donated" in f[0].msg


def test_c6_pr13_fix_pattern_is_clean():
    # the shipped fix: carries re-bound from the call's outputs
    assert run("""
    import jax
    import numpy as np

    def make_mux(chunk):
        return jax.jit(lambda s, x, b: (x, b), donate_argnums=(1, 2))

    def dispatch(stack, x, b):
        mux = make_mux(2)
        x, b = mux(stack, x, b)
        return np.asarray(b)
    """) == []


# ---------------------------------------------------------------------------
# M: state-machine exhaustiveness
# ---------------------------------------------------------------------------

def m_cfg(**over):
    cfg = {"name": "m", "files": ["m.py"], "setter": "set_state",
           "states": ["a", "b", "c"], "initial": ["a"],
           "transitions": [["a", "b"], ["b", "c"]]}
    cfg.update(over)
    return {"machines": [cfg]}


def test_m1_unknown_state_literal():
    f = run("""
    def go(job):
        job.set_state("z")
    """, m_cfg())
    assert "M1" in rules_of(f)


def test_m2_declared_but_unreachable_state():
    f = run("""
    def go(job):
        job.set_state("b")
        job.set_state("c")
    """, m_cfg(states=["a", "b", "c", "paused"]))
    assert [x.rule for x in f if x.rule == "M2"] == ["M2"]
    assert "paused" in [x for x in f if x.rule == "M2"][0].msg


def test_m3_consecutive_pair_must_be_declared():
    clean = run("""
    def go(job):
        job.set_state("b")
        job.set_state("c")
    """, m_cfg())
    assert clean == []
    f = run("""
    def go(job):
        job.set_state("c")
        job.set_state("b")
    """, m_cfg())
    assert [x.rule for x in f] == ["M3"]
    assert "'c' -> 'b'" in f[0].msg


def test_m3_guard_inference_from_if_state_eq():
    # the fixtures leave some declared states unset on purpose, so
    # compare the M3 surface alone (M2 is covered above)
    f = run("""
    def go(job):
        if job.state == "a":
            job.set_state("c")
    """, m_cfg())
    assert [x.rule for x in f if x.rule == "M3"] == ["M3"]
    clean = run("""
    def go(job):
        if job.state == "a":
            job.set_state("b")
    """, m_cfg())
    assert [x for x in clean if x.rule == "M3"] == []


def test_m3_terminating_branch_drops_out_of_the_join():
    # the serve._quarantine shape: both arms assign, the first returns —
    # no cross-arm edge may be fabricated
    f = run("""
    def go(job):
        if job.bad():
            job.set_state("b")
            return
        job.set_state("b")
    """, m_cfg())
    assert [x for x in f if x.rule == "M3"] == []


def test_m3_loop_target_rebinding_is_not_an_edge():
    # the serve._drain shape: per-iteration job, b -> c inside, no
    # c -> b edge across iterations
    assert run("""
    def go(jobs):
        for job in jobs:
            job.set_state("b")
            job.set_state("c")
    """, m_cfg()) == []


def test_m_attr_machine_with_class_restriction():
    cfg = {"machines": [{
        "name": "breaker", "files": ["m.py"], "attr": "state",
        "class": "Breaker", "states": ["closed", "open"],
        "initial": ["closed"],
        "transitions": [["closed", "open"], ["open", "closed"]]}]}
    f = run("""
    class Breaker:
        def trip(self):
            if self.state == "closed":
                self.state = "open"
        def reset(self):
            self.state = "closed"
    class Other:
        def set(self):
            self.state = "weird"
    """, cfg)
    assert f == []      # Other.state is not the breaker's machine


def test_m1_states_const_must_match_the_table():
    cfg = m_cfg()
    cfg["machines"][0]["states_const"] = {"file": "m.py",
                                          "name": "STATES"}
    f = run("""
    STATES = ("a", "b", "c", "d")
    def go(job):
        job.set_state("b")
        job.set_state("c")
    """, cfg)
    assert "M1" in rules_of(f)
    assert "STATES" in [x for x in f if x.rule == "M1"][0].msg


# ---------------------------------------------------------------------------
# repo gate: committed config, baseline, justifications
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_repo_findings_match_the_committed_baseline():
    findings, _ = analyze_repo()
    data = load_baseline_file(ROOT / "racecheck_baseline.json")
    current = baseline_counts(findings, ROOT)
    # exact equality: new findings must be fixed (or justified into the
    # baseline), fixed ones must ratchet the baseline down
    assert current == data["violations"], (
        "racecheck findings diverged from racecheck_baseline.json "
        f"(current={current})")


@pytest.mark.lint
def test_every_baselined_pair_is_justified():
    data = load_baseline_file(ROOT / "racecheck_baseline.json")
    assert check_justifications(data) == []


@pytest.mark.lint
def test_repo_is_clean_outside_the_baselined_rule():
    # S1/C6/L2/M* carry no baseline allowance at all: the runtime's
    # signal path, donation protocol, lock graph and state machines
    # audit clean outright
    findings, _ = analyze_repo()
    hard = [f for f in findings if f.rule != "L1"]
    assert hard == [], "\n".join(str(f) for f in hard)


def test_committed_config_declares_the_serving_machines():
    cfg = load_config()
    names = {m["name"] for m in cfg["machines"]}
    assert {"job", "breaker"} <= names
    job = next(m for m in cfg["machines"] if m["name"] == "job")
    assert ["warming", "sampling"] in job["transitions"]
    assert ["draining", "queued"] in job["transitions"]


def test_rule_table_is_closed():
    assert set(RULES) == {"L1", "L2", "S1", "C6", "M1", "M2", "M3"}


# ---------------------------------------------------------------------------
# CLI / wrappers
# ---------------------------------------------------------------------------

def _run_cli(*args, cwd=ROOT):
    env = dict(os.environ, PYTHONPATH=str(ROOT))
    return subprocess.run(
        [sys.executable, "-m",
         "pulsar_timing_gibbsspec_tpu.analysis.racecheck", *args],
        cwd=cwd, env=env, capture_output=True, text=True)


@pytest.mark.lint
def test_cli_exits_zero_on_head_with_committed_baseline():
    r = _run_cli()
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_cli_fails_on_unjustified_baseline(tmp_path):
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({
        "violations": {
            "pulsar_timing_gibbsspec_tpu/runtime/preemption.py":
                {"L1": 2}},
        "justifications": {}}))
    r = _run_cli("--baseline", str(bl))
    assert r.returncode == 1
    assert "without justification" in r.stdout


def test_cli_write_baseline_stubs_todo_justifications(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent("""
        import threading
        _lock = threading.Lock()
        _reg: dict = {}
        def hit(k):
            _reg[k] = 1
    """))
    bl = tmp_path / "bl.json"
    r = _run_cli(str(f), "--baseline", str(bl), "--write-baseline")
    assert r.returncode == 0
    data = json.loads(bl.read_text())
    assert list(data["violations"].values()) == [{"L1": 1}]
    just = list(data["justifications"].values())
    assert len(just) == 1 and just[0].startswith("TODO")
    # the stub is not a justification: the gate refuses it
    r2 = _run_cli(str(f), "--baseline", str(bl))
    assert r2.returncode == 1
    assert "without justification" in r2.stdout


def test_tools_racecheck_wrapper_importable():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "tools_racecheck", ROOT / "tools" / "racecheck.py")
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)        # no side effects on import
    assert callable(m.main)
