"""JAX device backend validation.

The reference's correctness oracle is cross-sampler statistical equivalence
(SURVEY §4); here that becomes (a) deterministic identity of every compiled
conditional against the host model / NumPy oracle at matched states, and
(b) thinned KS agreement of full posteriors between the jit-compiled device
path and the float64 NumPy oracle.
"""

import numpy as np
import pytest
from scipy import stats

from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.sampler import jax_backend as jb
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.compiled import compile_pta
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import (PTABlockGibbs,
                                                       PulsarBlockGibbs)


@pytest.fixture(scope="module")
def pta8(psrs8):
    return model_general(psrs8, tm_svd=True, red_var=True, red_psd="spectrum",
                         red_components=10, white_vary=True,
                         common_psd="spectrum", common_components=10)


# ---------------------------------------------------------------------------
# deterministic identities at matched states
# ---------------------------------------------------------------------------

def test_compiled_matches_host_model(pta8):
    cm = compile_pta(pta8)
    x = pta8.initial_sample(np.random.default_rng(0))
    params = pta8.map_params(x)
    nd = np.asarray(cm.ndiag(x))
    ph = np.asarray(cm.phi(x))
    for ii in range(len(pta8.pulsars)):
        nd_host = pta8.get_ndiag(params)[ii]
        np.testing.assert_allclose(nd[ii, :len(nd_host)], nd_host, rtol=1e-5)
        ph_host = pta8.get_phi(params)[ii]
        sel = ph_host < 1e20     # timing columns use the f32-safe big-phi cap
        np.testing.assert_allclose(ph[ii, :len(ph_host)][sel], ph_host[sel],
                                   rtol=1e-4)
    assert abs(float(cm.lnprior(x)) - pta8.get_lnprior(x)) < 1e-2


def test_conditionals_match_oracle_at_state(pta8):
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    g = NumpyPTAGibbs(pta8, seed=0)
    x = pta8.initial_sample(np.random.default_rng(7))
    g.draw_b(x)
    cm = compile_pta(pta8)
    b = np.zeros((cm.P, cm.Bmax), cm.cdtype)
    for ii, bb in enumerate(g.b):
        b[ii, :len(bb)] = bb

    # white-noise conditional log-likelihood and its MH deltas
    ll_np = g.lnlike_white(x)
    r2 = jb.residual_sq(cm, b)
    ll_jx = float(jb.lnlike_white_fn(cm, x, r2))
    assert abs(ll_jx - ll_np) < 1e-6 * abs(ll_np)
    rng = np.random.default_rng(3)
    ll_rel = jb.white_ll_rel(cm, x, r2)
    rel0 = np.asarray(ll_rel(x))
    for _ in range(5):
        q = x.copy()
        q[rng.choice(g.idx.white)] += 0.1 * rng.standard_normal()
        d_np = g.lnlike_white(q) - ll_np
        d_jx = float(jb.lnlike_white_fn(cm, q, r2)) - ll_jx
        assert abs(d_jx - d_np) < 1e-6 * max(1.0, abs(d_np))
        # the f32 block-relative form the MH scans consume must agree with
        # the absolute-likelihood difference (its sign error is the round-2
        # bug that drove every white chain to the prior floor)
        d_rel = float(np.sum(np.asarray(ll_rel(q)) - rel0))
        assert abs(d_rel - d_np) < 1e-3 * max(1.0, abs(d_np))

    # common-rho conditional log-PDF grid (sum over pulsars == reference's
    # per-pulsar PDF product, pta_gibbs.py:205)
    params = g.map_params(x)
    K = len(g.idx.rho)
    grid = 10.0 ** np.linspace(np.log10(g.rhomin), np.log10(g.rhomax), 1000)
    lp_np = np.zeros((K, len(grid)))
    for ii in range(g.P):
        lp_np += g._rho_log_pdf_grid(
            g._gw_tau(ii)[:K],
            np.asarray(g.red_sigs[ii].get_phi(params))[::2][:K], grid)
    tau = np.asarray(cm.gw_tau(b))
    other = np.asarray(cm.red_phi(x))
    logratio = (np.log(tau)[:, :, None]
                - np.logaddexp(np.log(other)[:, :, None],
                               np.log(grid)[None, None, :]))
    lp_jx = (logratio - np.exp(logratio)).sum(axis=0)
    near_peak = lp_np > lp_np.max(axis=1, keepdims=True) - 30.0
    assert np.max(np.abs((lp_jx - lp_np)[near_peak])) < 1e-6

    # b-draw conditional mean
    import scipy.linalg as sl

    from pulsar_timing_gibbsspec_tpu.ops.linalg import mvn_conditional_draw

    Nvecs = pta8.get_ndiag(params)
    phinv = pta8.get_phiinv(params, logdet=False)
    g.invalidate_cache()
    g._ensure_cache(Nvecs)
    N = cm.ndiag(x)
    TNT, d = jb.tnt_d(cm, N)
    _, mean = mvn_conditional_draw(np.asarray(TNT),
                                   1.0 / np.asarray(cm.phi(x)),
                                   np.asarray(d),
                                   np.zeros((cm.P, cm.Bmax), cm.cdtype))
    for ii in range(g.P):
        Sigma = g._TNT[ii] + np.diag(phinv[ii])
        mn = sl.cho_solve(sl.cho_factor(Sigma), g._d[ii])
        scale = np.abs(mn).max()
        np.testing.assert_allclose(np.asarray(mean)[ii, :len(mn)], mn,
                                   atol=5e-3 * scale, rtol=5e-3)


def test_lnlike_fullmarg_matches_oracle(pta8):
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    g = NumpyPTAGibbs(pta8, seed=0)
    x = pta8.initial_sample(np.random.default_rng(11))
    cm = compile_pta(pta8)
    g.invalidate_cache()
    ll_np = g.lnlike_fullmarg(x)
    N = cm.ndiag(x)
    TNT, d = jb.tnt_d(cm, N)
    ll_jx = float(jb.lnlike_fullmarg_fn(cm, x, TNT, d))
    # big-phi cap (1e30 vs 1e40) shifts logdet_phi by a constant:
    # ntm_cols * log(1e10) / 2 per pulsar — remove it before comparing
    ntm = sum(m._slices[s.name].stop - m._slices[s.name].start
              for m in [pta8.model(i) for i in range(g.P)]
              for s in m._timing)
    shift = 0.5 * ntm * np.log(1e10)
    assert abs((ll_jx - shift) - ll_np) < 2e-5 * abs(ll_np)
    # differences (what MH sees) are unaffected by the constant shift
    q = np.array(x)
    q[g.idx.red[0] if len(g.idx.red) else 0] += 0.05
    d_np = g.lnlike_fullmarg(q) - ll_np
    d_jx = float(jb.lnlike_fullmarg_fn(cm, q, TNT, d)) - ll_jx
    assert abs(d_jx - d_np) < 1e-3 * max(1.0, abs(d_np))


def test_tnt_d_segmented_parity(synth_hd_pta):
    """The parity class :func:`jb.tnt_d`'s docstring claims, measured:
    the segmented exact path is a pure f64 reassociation of the
    monolithic dot (same exact f32*f32 products, different partial-sum
    grouping), so (a) bitwise identity whenever nseg == 1, (b)
    agreement within a few ULP at the Jacobi scale ``sqrt(G_bb G_cc)``
    when nseg > 1, and (c) bitwise determinism across calls."""
    cm = compile_pta(synth_hd_pta)
    x = synth_hd_pta.initial_sample(np.random.default_rng(17))
    Nv = cm.ndiag(x)
    ntoa = cm.T.shape[1]
    eps = np.finfo(np.float64).eps

    # monolithic oracle: one segment spanning every TOA
    TNT_m, d_m = (np.asarray(a) for a in jb.tnt_d(cm, Nv, seg_len=ntoa))
    assert TNT_m.dtype == np.dtype(cm.cdtype)

    # (a) any seg_len >= ntoa is the same single-segment program
    TNT_1, d_1 = (np.asarray(a) for a in
                  jb.tnt_d(cm, Nv, seg_len=ntoa + 999))
    np.testing.assert_array_equal(TNT_1, TNT_m)
    np.testing.assert_array_equal(d_1, d_m)

    # (b) force several segments (72 TOAs / 18 -> nseg = 4) and compare
    # at the Jacobi scale; elementwise relative error is NOT the claim
    # (cancellation-heavy near-zero elements move more in their own
    # terms, as any reassociated f64 sum does)
    TNT_s, d_s = (np.asarray(a) for a in jb.tnt_d(cm, Nv, seg_len=18))
    diag = np.sqrt(np.einsum("pbb->pb", TNT_m))
    scale = np.maximum(diag[:, :, None] * diag[:, None, :],
                       np.finfo(np.float64).tiny)
    assert (np.abs(TNT_s - TNT_m) / scale).max() < 50 * eps
    yNy = np.sum(np.asarray(cm.y, np.float64) ** 2
                 / np.asarray(Nv, np.float64), axis=1)
    dscale = np.maximum(diag * np.sqrt(yNy)[:, None],
                        np.finfo(np.float64).tiny)
    assert (np.abs(d_s - d_m) / dscale).max() < 50 * eps

    # (c) the segmented program is deterministic, bitwise
    TNT_s2, d_s2 = (np.asarray(a) for a in jb.tnt_d(cm, Nv, seg_len=18))
    np.testing.assert_array_equal(TNT_s2, TNT_s)
    np.testing.assert_array_equal(d_s2, d_s)

    # the default path (settings.gram_seg_len_exact) stays in class
    TNT_d, d_d = (np.asarray(a) for a in jb.tnt_d(cm, Nv))
    assert (np.abs(TNT_d - TNT_m) / scale).max() < 50 * eps
    assert (np.abs(d_d - d_m) / dscale).max() < 50 * eps


# ---------------------------------------------------------------------------
# full-chain statistical equivalence (the BASELINE.json metric)
# ---------------------------------------------------------------------------

def test_jax_vs_numpy_posterior_ks(j1713, tmp_path):
    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=10)
    x0 = pta.initial_sample(np.random.default_rng(42))
    chains = {}
    for backend, seed in [("jax", 1), ("numpy", 2)]:
        g = PulsarBlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2000)
    burn, thin = 200, 5
    pvals = [stats.ks_2samp(chains["jax"][burn::thin, k],
                            chains["numpy"][burn::thin, k]).pvalue
             for k in range(10)]
    # Bonferroni-style: no bin catastrophically off (null-control chains
    # occasionally reach p ~ 1e-3 from residual autocorrelation)
    assert min(pvals) > 1e-4, pvals
    assert np.median(pvals) > 0.05, pvals


def test_jax_vs_numpy_white_vary_ks(j1713, tmp_path):
    """KS agreement of the white-noise (EFAC/EQUAD) and rho posteriors when
    the white block varies — the coverage that was missing when the round-1
    empirical covariance adaptation collapsed to frozen EFAC chains."""
    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=10)
    x0 = pta.initial_sample(np.random.default_rng(17))
    chains = {}
    for backend, seed in [("jax", 3), ("numpy", 4)]:
        g = PulsarBlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2000)
    burn, thin = 200, 5
    idx = BlockIndex.build(pta.param_names)
    cols = list(idx.white) + list(idx.rho[:4])
    pvals = [stats.ks_2samp(chains["jax"][burn::thin, k],
                            chains["numpy"][burn::thin, k]).pvalue
             for k in cols]
    # the white chains must actually mix: reject frozen pseudo-chains
    for k in idx.white:
        assert np.std(chains["jax"][burn:, k]) > 1e-3
    assert min(pvals) > 1e-4, pvals
    assert np.median(pvals) > 0.05, pvals


# ---------------------------------------------------------------------------
# resume: bitwise continuation of the stochastic process
# ---------------------------------------------------------------------------

def test_jax_resume_bitwise(j1713, tmp_path):
    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(5))
    kw = dict(backend="jax", seed=9, progress=False, white_adapt_iters=100,
              chunk_size=20)

    g_full = PulsarBlockGibbs(pta, **kw)
    full = g_full.sample(x0, outdir=str(tmp_path / "full"), niter=100,
                         save_every=20)

    g_a = PulsarBlockGibbs(pta, **kw)
    g_a.sample(x0, outdir=str(tmp_path / "split"), niter=60, save_every=20)
    g_b = PulsarBlockGibbs(pta, **kw)
    resumed = g_b.sample(x0, outdir=str(tmp_path / "split"), niter=100,
                         resume=True, save_every=20)

    # finiteness first: assert_array_equal treats NaN==NaN as equal, which
    # made this test pass vacuously on NaN-poisoned chains in round 1
    assert np.all(np.isfinite(full))
    np.testing.assert_array_equal(resumed, full)


def test_record_every_thins_rows_and_matches_full(j1713, tmp_path):
    """On-device record thinning must not change the sampled process:
    the record_every=4 chain must equal exactly the corresponding rows of
    the record_every=1 chain from the same seed (per-sweep keys are pure
    in the iteration index), and a split/resumed thinned run must equal
    the uninterrupted one bitwise — including the recorded-iteration SET,
    which is anchored to absolute iteration residue, not the chunk grid."""
    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(5))
    kw = dict(backend="jax", seed=9, progress=False, white_adapt_iters=100,
              chunk_size=20, nchains=2)
    full = PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "full"), niter=90, save_every=20)
    g_thin = PulsarBlockGibbs(pta, record_every=4, **kw)
    thin = g_thin.sample(x0, outdir=str(tmp_path / "thin"), niter=90,
                         save_every=20)

    # expected recorded iterations: thinned warmup rows, the post-warmup
    # carry row, then steady iterations ≡ it_base (mod k)
    drv = g_thin._backend
    W = min(drv.warmup_sweeps, 89)
    it0 = W + 1
    its = list(range(0, W, 4)) + [W] + [t for t in range(it0, 90)
                                        if (t - it0) % 4 == 0]
    assert np.all(np.isfinite(full))
    assert thin.shape == (len(its), 2, len(pta.param_names))
    np.testing.assert_array_equal(thin, full[np.asarray(its)])
    bfull = np.load(tmp_path / "full" / "bchain.npy")
    bthin = np.load(tmp_path / "thin" / "bchain.npy")
    np.testing.assert_array_equal(bthin, bfull[np.asarray(its)])

    # bitwise resume under thinning (same recorded set, same values)
    g_a = PulsarBlockGibbs(pta, record_every=4, **kw)
    g_a.sample(x0, outdir=str(tmp_path / "split"), niter=71, save_every=20)
    g_b = PulsarBlockGibbs(pta, record_every=4, **kw)
    resumed = g_b.sample(x0, outdir=str(tmp_path / "split"), niter=90,
                         resume=True, save_every=20)
    np.testing.assert_array_equal(resumed, thin)

    # resuming a thinned checkpoint at a different record_every would
    # silently misread the row cursor as an iteration counter: loud error
    g_c = PulsarBlockGibbs(pta, **kw)            # record_every=1 default
    with pytest.raises(RuntimeError, match="record_every"):
        g_c.sample(x0, outdir=str(tmp_path / "split"), niter=120,
                   resume=True, save_every=20)


def test_rho_collapsed_matches_default(j1713, tmp_path, monkeypatch):
    """The opt-in partially-collapsed rho draw (PTGIBBS_RHO_COLLAPSE;
    red amplitudes marginalized by quadrature + rho-first sweep order)
    must sample the same posterior as the default conditional scan —
    measured net-negative on throughput at the bench scale but kept as
    a correct kernel, so it stays covered."""
    pta = model_general([j1713], tm_svd=True, red_var=True,
                        red_psd="spectrum", red_components=5,
                        white_vary=False, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(6))
    g0 = PulsarBlockGibbs(pta, backend="jax", seed=71, progress=False)
    c0 = g0.sample(x0, outdir=str(tmp_path / "default"), niter=1500)
    monkeypatch.setattr(jb, "RHO_COLLAPSE", True)
    gc = PulsarBlockGibbs(pta, backend="jax", seed=72, progress=False)
    assert jb._rho_collapsed_applies(gc._backend.cm)
    cc = gc.sample(x0, outdir=str(tmp_path / "collapsed"), niter=1500)
    assert np.all(np.isfinite(cc))
    idx = BlockIndex.build(pta.param_names)
    burn = 300
    _assert_same_law(c0[burn:], cc[burn:],
                     list(idx.rho) + list(idx.red_rho[:5]))


def test_record_every_guards(j1713):
    """Loud rejects: non-divisor chunk, DE-history models, numpy backend
    (jax-only device-transfer options must not die as bare TypeErrors)."""
    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5)
    with pytest.raises(ValueError, match="must divide"):
        PulsarBlockGibbs(pta, backend="jax", record_every=3, chunk_size=20)
    with pytest.raises(ValueError, match="jax-backend option"):
        PulsarBlockGibbs(pta, backend="numpy", record_every=2)
    with pytest.raises(ValueError, match="jax-backend option"):
        PulsarBlockGibbs(pta, backend="numpy", record_precision="bf16")
    pta_de = model_general([j1713], tm_svd=True, red_var=True,
                           red_psd="powerlaw", red_components=5,
                           white_vary=False, common_psd="spectrum",
                           common_components=5)
    with pytest.raises(ValueError, match="record_every"):
        PulsarBlockGibbs(pta_de, backend="jax", record_every=2,
                         chunk_size=20)


def test_resume_bitwise_across_de_refresh(j1713, tmp_path):
    """Bitwise resume must hold across a DE-history refresh boundary
    (iteration DE_Q*m >= DE_DELAY + DE_HIST_LEN, first at 384): the
    refreshed buffers are rebuilt from chain rows, and the resumed run's
    chunk grid is shifted off the original — the per-iteration period
    select in the sweep body is what keeps the two runs identical."""
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import (
        DE_DELAY, DE_HIST_LEN, DE_Q)

    niter = DE_DELAY + DE_HIST_LEN + 2 * DE_Q - 60   # crosses m=3 and m=4
    pta = model_general([j1713], tm_svd=True, red_var=True,
                        red_psd="powerlaw", red_components=5,
                        white_vary=False, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(8))
    kw = dict(backend="jax", seed=12, progress=False, white_adapt_iters=50,
              chunk_size=50)
    full = PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "full"), niter=niter, save_every=50)
    # split just past the first refresh so the resumed run re-derives a
    # refreshed (non-seed) buffer from preloaded chain rows
    PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "split"), niter=3 * DE_Q + 20,
        save_every=50)
    resumed = PulsarBlockGibbs(pta, **kw).sample(
        x0, outdir=str(tmp_path / "split"), niter=niter, resume=True,
        save_every=50)
    assert np.all(np.isfinite(full))
    np.testing.assert_array_equal(resumed, full)


def test_resume_bitwise_hd_red_and_tprocess(psrs8, j1713, tmp_path):
    """Bitwise resume holds for the round-2 blocks too: the correlated-ORF
    sweep with intrinsic red (carried b enters the sequential conditional)
    and the t-process alpha draw (alphas live in x)."""
    cases = {
        "hdred": (PTABlockGibbs, model_general(
            psrs8[:3], tm_svd=True, red_var=True, red_psd="spectrum",
            red_components=4, white_vary=False, common_psd="spectrum",
            common_components=4, orf="hd")),
        "tproc": (PulsarBlockGibbs, model_general(
            [j1713], tm_svd=True, red_var=True, red_psd="tprocess",
            red_components=4, white_vary=True, common_psd="spectrum",
            common_components=4)),
        "paramorf": (PTABlockGibbs, model_general(
            psrs8[:3], tm_svd=True, red_var=False, white_vary=False,
            common_psd="spectrum", common_components=4,
            orf="legendre_orf", leg_lmax=1)),
    }
    for lab, (cls, pta) in cases.items():
        x0 = pta.initial_sample(np.random.default_rng(6))
        kw = dict(backend="jax", seed=10, progress=False,
                  white_adapt_iters=100, chunk_size=20)
        full = cls(pta, **kw).sample(
            x0, outdir=str(tmp_path / f"{lab}_full"), niter=100,
            save_every=20)
        cls(pta, **kw).sample(
            x0, outdir=str(tmp_path / f"{lab}_split"), niter=60,
            save_every=20)
        resumed = cls(pta, **kw).sample(
            x0, outdir=str(tmp_path / f"{lab}_split"), niter=100,
            resume=True, save_every=20)
        assert np.all(np.isfinite(full)), lab
        np.testing.assert_array_equal(resumed, full, err_msg=lab)


# ---------------------------------------------------------------------------
# reference-API kernel-selector flags: honored or loud, never ignored
# ---------------------------------------------------------------------------

def test_sampling_flags_validated(j1713, pta8):
    pta_fs = model_general([j1713], tm_svd=True, red_var=True,
                           red_psd="spectrum", red_components=5,
                           white_vary=False, common_psd="spectrum",
                           common_components=5)
    # auto + structurally-consistent explicit values pass
    PulsarBlockGibbs(pta_fs, backend="numpy", seed=0)
    PulsarBlockGibbs(pta_fs, backend="numpy", seed=0,
                     hypersample="conditional", redsample="conditional")
    # asking for kernels the structure does not provide raises loudly
    with pytest.raises(NotImplementedError):
        PulsarBlockGibbs(pta_fs, backend="numpy", seed=0, redsample="mh")
    with pytest.raises(NotImplementedError):
        PulsarBlockGibbs(pta_fs, backend="numpy", seed=0, hypersample="mh")
    with pytest.raises(NotImplementedError):
        PulsarBlockGibbs(pta_fs, backend="numpy", seed=0, ecorrsample="gibbs")
    pta_pl = model_general([j1713], tm_svd=True, red_var=True,
                           red_psd="powerlaw", white_vary=False,
                           common_psd="spectrum", common_components=5)
    PulsarBlockGibbs(pta_pl, backend="numpy", seed=0, redsample="mh")
    with pytest.raises(NotImplementedError):
        PulsarBlockGibbs(pta_pl, backend="numpy", seed=0,
                         redsample="conditional")
    # common_rho asserts a shared free-spectrum block exists
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    pta_nogw = model_general([j1713], tm_svd=True, red_var=True,
                             red_psd="spectrum", red_components=5,
                             white_vary=False)
    with pytest.raises(ValueError):
        JaxGibbsDriver(pta_nogw, seed=0, common_rho=True)


# ---------------------------------------------------------------------------
# multi-chain axis (nchains): every chain a valid posterior, resume exact
# ---------------------------------------------------------------------------

def test_nchains_ks_and_shapes(j1713, tmp_path):
    """nchains=K vmaps whole sweeps over a chains axis: chain files gain a
    chains axis, every chain is finite and KS-consistent with the single-
    chain run, and pooled samples match too (the throughput axis must not
    change the sampled law; SURVEY §7 hard part (a))."""
    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(11))
    g1 = PulsarBlockGibbs(pta, backend="jax", seed=21, progress=False,
                          white_adapt_iters=200)
    c1 = g1.sample(x0, outdir=str(tmp_path / "c1"), niter=1200)
    gk = PulsarBlockGibbs(pta, backend="jax", seed=22, progress=False,
                          white_adapt_iters=200, nchains=3)
    ck = gk.sample(x0, outdir=str(tmp_path / "ck"), niter=1200)
    npar = len(pta.param_names)
    assert c1.shape == (1200, npar)
    assert ck.shape == (1200, 3, npar)
    assert np.all(np.isfinite(ck))
    saved = np.load(tmp_path / "ck" / "chain.npy")
    assert saved.shape == (1200, 3, npar)

    burn, thin = 200, 5
    idx = BlockIndex.build(pta.param_names)
    cols = list(idx.rho[:3]) + list(idx.white[:2])
    ref = c1[burn::thin]
    for c in range(3):
        pv = [stats.ks_2samp(ck[burn::thin, c, k], ref[:, k]).pvalue
              for k in cols]
        assert min(pv) > 1e-4, (c, pv)
    pooled = ck[burn::thin].reshape(-1, npar)
    pv = [stats.ks_2samp(pooled[:, k], ref[:, k]).pvalue for k in cols]
    assert min(pv) > 1e-4, pv
    # chains are genuinely distinct stochastic processes
    assert np.std(ck[burn:, 0, cols[0]] - ck[burn:, 1, cols[0]]) > 0


def test_nchains_resume_bitwise(j1713, tmp_path):
    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(5))
    kw = dict(backend="jax", seed=31, progress=False, white_adapt_iters=100,
              chunk_size=20, nchains=2)
    g_full = PulsarBlockGibbs(pta, **kw)
    full = g_full.sample(x0, outdir=str(tmp_path / "full"), niter=100,
                         save_every=20)
    g_a = PulsarBlockGibbs(pta, **kw)
    g_a.sample(x0, outdir=str(tmp_path / "split"), niter=60, save_every=20)
    g_b = PulsarBlockGibbs(pta, **kw)
    resumed = g_b.sample(x0, outdir=str(tmp_path / "split"), niter=100,
                         resume=True, save_every=20)
    assert np.all(np.isfinite(full))
    np.testing.assert_array_equal(resumed, full)


# ---------------------------------------------------------------------------
# Hellings-Downs correlated common process (the extension the reference
# never finished: pta_gibbs.py:533 assumes phi block-diagonal)
# ---------------------------------------------------------------------------

def test_hd_identity_orf_matches_crn_conditional(psrs8):
    """At G = I the correlated-ORF rho conditional must equal the CRN
    product-of-per-pulsar-PDFs conditional: taut_k == sum_p tau_pk."""
    import jax.numpy as jnp
    import jax.random as jr

    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, orf="hd")
    cm = compile_pta(pta)
    # overwrite the ORF with the identity: conditional == CRN
    import dataclasses

    cmI = dataclasses.replace(
        cm, orf_Ginv=np.tile(np.eye(cm.P), (cm.K, 1, 1)))
    x = jnp.asarray(pta.initial_sample(np.random.default_rng(0)), cm.cdtype)
    b = jb.draw_b_fn(cmI, x, jr.key(0))
    tau = np.asarray(cmI.gw_tau(b))
    a_s = np.take_along_axis(np.asarray(b), np.asarray(cmI.gw_sin_ix), 1)
    a_c = np.take_along_axis(np.asarray(b), np.asarray(cmI.gw_cos_ix), 1)
    taut = 0.5 * (np.sum(a_s ** 2, axis=0) + np.sum(a_c ** 2, axis=0))
    np.testing.assert_allclose(taut, tau.sum(axis=0), rtol=1e-10)


def test_hd_oracle_vs_jax_equivalence(psrs8, tmp_path):
    """Small-PTA statistical equivalence of the HD path: the joint
    cross-pulsar b-draw + quadratic-form rho conditional must produce the
    same posterior on both backends (reference target:
    model_definition.py:198-216 builds these models; no reference sampler
    ever sampled them).

    Weakly-constrained rho bins mix slowly (the rho^-P funnel at the grid
    bottom measures ACT 30-90 here), so a raw KS test at this chain
    length is dominated by Monte-Carlo error; every bin gets an ESS-aware
    z-test on the marginal mean, and the fast-mixing bins additionally a
    KS test on ACT-thinned samples."""
    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, orf="hd")
    x0 = pta.initial_sample(np.random.default_rng(4))
    chains = {}
    for backend, seed in [("jax", 5), ("numpy", 6)]:
        g = PTABlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2500)
    burn = 300
    idx = BlockIndex.build(pta.param_names)
    _assert_same_law(chains["jax"][burn:], chains["numpy"][burn:],
                     idx.rho, zmax=4.0)


@pytest.mark.parametrize("kernel", ["freq", "pulsar"])
def test_hd_scalable_matches_dense(psrs8, tmp_path, monkeypatch, kernel):
    """Both scalable HD kernels (the two-block frequency-joint sweep and
    the production sequential pulsar-wise sweep — docs/HD_MIXING.md) must
    sample the same posterior as the dense joint draw: same model, dense
    vs forced-scalable, ESS-aware comparison."""
    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, orf="hd")
    x0 = pta.initial_sample(np.random.default_rng(4))
    g_dense = PTABlockGibbs(pta, backend="jax", seed=61, progress=False)
    c_dense = g_dense.sample(x0, outdir=str(tmp_path / "dense"), niter=2500)
    monkeypatch.setattr(jb, "HD_DENSE_MAX", 0)
    monkeypatch.setattr(jb, "HD_SCALABLE_KERNEL", kernel)
    g_seq = PTABlockGibbs(pta, backend="jax", seed=62, progress=False)
    c_seq = g_seq.sample(x0, outdir=str(tmp_path / "seq"), niter=2500)
    assert np.all(np.isfinite(c_seq))
    burn = 300
    idx = BlockIndex.build(pta.param_names)
    _assert_same_law(c_dense[burn:], c_seq[burn:], idx.rho, zmax=4.0)


def test_hd_with_intrinsic_red(psrs8, tmp_path):
    """Correlated common process + per-pulsar intrinsic red free spectrum —
    the combination the reference builds (red_var defaults True) but no
    reference sampler ever sampled.  The factory gives the correlated
    process its own basis columns (disjoint from red), so the joint prior
    is purely rho_k G there and per-pulsar diagonal on the red columns;
    backends must agree statistically on both blocks."""
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    pta = model_general(psrs8[:3], tm_svd=True, red_var=True,
                        red_psd="spectrum", red_components=4,
                        white_vary=False, common_psd="spectrum",
                        common_components=4, orf="hd")
    # disjoint layout: gw own columns, red on the shared grid
    m = pta.model(0)
    rsl = m._slices[f"{pta.pulsars[0]}_red_noise"]
    gsl = m._slices["gw_hd"]
    assert rsl.stop <= gsl.start or gsl.stop <= rsl.start
    cm = compile_pta(pta)
    assert cm.orf_name == "hd" and not cm.red_shares_gw

    x0 = pta.initial_sample(np.random.default_rng(4))
    chains = {}
    for backend, seed in [("jax", 5), ("numpy", 6)]:
        g = PTABlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=2000)
    idx = BlockIndex.build(pta.param_names)
    burn = 400
    for k in np.concatenate([idx.rho, idx.red_rho]):
        cj, cn = chains["jax"][burn:, k], chains["numpy"][burn:, k]
        assert np.all(np.isfinite(cj)) and np.all(np.isfinite(cn))
        ess_j = len(cj) / max(integrated_act(cj), 1.0)
        ess_n = len(cn) / max(integrated_act(cn), 1.0)
        z = abs(cj.mean() - cn.mean()) / np.sqrt(
            cj.var() / ess_j + cn.var() / ess_n)
        assert z < 4.5, (k, z, ess_j, ess_n)


def test_hd_with_powerlaw_red_builds(psrs8, tmp_path):
    """HD + powerlaw intrinsic red: hypers ride the adaptive MH block,
    coefficients the correlated b-draw; short run stays finite."""
    pta = model_general(psrs8[:3], tm_svd=True, red_var=True,
                        red_psd="powerlaw", red_components=4,
                        white_vary=False, common_psd="spectrum",
                        common_components=4, orf="hd")
    g = PTABlockGibbs(pta, backend="jax", seed=8, progress=False)
    c = g.sample(pta.initial_sample(np.random.default_rng(2)),
                 outdir=str(tmp_path / "plred"), niter=150)
    assert np.all(np.isfinite(c))


# ---------------------------------------------------------------------------
# sharded multi-pulsar path
# ---------------------------------------------------------------------------

def test_sharded_pta_sweep(pta8, tmp_path):
    import jax

    from pulsar_timing_gibbsspec_tpu.parallel import make_mesh

    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    mesh = make_mesh(8)
    g = PTABlockGibbs(pta8, backend="jax", seed=3, progress=False,
                      white_adapt_iters=100, mesh=mesh)
    x0 = pta8.initial_sample(np.random.default_rng(1))
    chain = g.sample(x0, outdir=str(tmp_path / "c"), niter=40)
    assert chain.shape == (40, len(pta8.param_names))
    assert np.all(np.isfinite(chain))
    # rho parameters moved (the common draw runs over the sharded axis)
    idx = BlockIndex.build(pta8.param_names)
    assert np.std(chain[1:, idx.rho[0]]) > 0


def test_sharded_hd_sweep(psrs8, tmp_path):
    """The correlated-ORF (HD) sweep also runs over a pulsar-sharded mesh:
    the sequential cross-pulsar conditional gathers other shards'
    coefficients, so GSPMD must insert the collectives and the chain must
    stay finite with moving rho draws."""
    import jax

    from pulsar_timing_gibbsspec_tpu.parallel import make_mesh

    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    pta = model_general(psrs8, tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=3, orf="hd")
    mesh = make_mesh(8)
    g = PTABlockGibbs(pta, backend="jax", seed=7, progress=False,
                      mesh=mesh, warmup_sweeps=5)
    x0 = pta.initial_sample(np.random.default_rng(2))
    chain = g.sample(x0, outdir=str(tmp_path / "hd"), niter=30)
    assert chain.shape == (30, len(pta.param_names))
    assert np.all(np.isfinite(chain))
    idx = BlockIndex.build(pta.param_names)
    assert np.std(chain[1:, idx.rho[0]]) > 0


@pytest.mark.parametrize("kernel", ["pulsar", "freq"])
def test_hd_exact_path_and_breakdown_guards(psrs8, monkeypatch, kernel):
    """The two-float breakdown robustness contract, both halves: (a) the
    exact=True draw (warmup/init, the r5 seed-dependent-NaN fix) must
    not touch tf_chol_factor at all; (b) with the two-float factor
    poisoned to NaN, the exact=False draw's guards must SKIP updates
    (finite chain, old coords kept) rather than poison the chain."""
    import jax.numpy as jnp
    import jax.random as jr

    import pulsar_timing_gibbsspec_tpu.ops.linalg as lin

    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5, orf="hd")
    cm = compile_pta(pta)
    assert cm.P * cm.Bmax > 0
    monkeypatch.setattr(jb, "HD_DENSE_MAX", 0)   # force the scalable path
    monkeypatch.setattr(jb, "HD_SCALABLE_KERNEL", kernel)
    x = jnp.asarray(pta.initial_sample(np.random.default_rng(1)), cm.cdtype)
    rng = np.random.default_rng(2)
    b0 = jnp.asarray(rng.standard_normal((cm.P, cm.Bmax)) * 1e-7, cm.cdtype)

    def poisoned(A, *a, **k):
        return jnp.full_like(A, jnp.nan), jnp.full_like(A, jnp.nan)

    monkeypatch.setattr(lin, "tf_chol_factor", poisoned)
    # (a) exact path never touches the poisoned factor
    b_exact = jb.draw_b_fn(cm, x, jr.key(3), b0, exact=True)
    assert np.all(np.isfinite(np.asarray(b_exact)))
    assert not np.allclose(np.asarray(b_exact), np.asarray(b0))
    # (b) tf path: every factor broken -> every update skipped, chain
    # stays finite and UNCHANGED (the guards' contract)
    b_tf = jb.draw_b_fn(cm, x, jr.key(3), b0, exact=False)
    assert np.all(np.isfinite(np.asarray(b_tf)))
    np.testing.assert_array_equal(np.asarray(b_tf), np.asarray(b0))


def test_sharded_vs_unsharded_ks_and_pad_inertness(psrs8, tmp_path):
    """Mesh + pad slots must not change the sampled LAW, not just stay
    finite (r4 VERDICT weak #4: the sharded tests proved liveness only,
    so a pad leak into the all-reduce would have passed CI).  Six real
    pulsars padded to an 8-device mesh vs the same model unsharded:
    (a) the common-rho conditional draw at a matched state must agree to
    grid resolution under the mesh (pad-slot inertness through the
    sharded reduction), (b) the rho posteriors must KS-match."""
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.parallel import make_mesh

    pta = model_general(psrs8[:6], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(3))
    mesh = make_mesh(8)
    gm = PTABlockGibbs(pta, backend="jax", seed=101, progress=False,
                       mesh=mesh, pad_pulsars=8)
    g0 = PTABlockGibbs(pta, backend="jax", seed=202, progress=False)

    # (a) deterministic: same state, same key, grid-resolution agreement
    # of the rho draw through the sharded, padded reduction
    cmm = gm._backend.cm
    cm0 = g0._backend.cm
    rng = np.random.default_rng(9)
    b0 = jnp.asarray(rng.standard_normal((cm0.P, cm0.Bmax)) * 1e-6,
                     cm0.cdtype)
    bp = jnp.zeros((cmm.P, cmm.Bmax), cmm.cdtype).at[:cm0.P].set(b0)
    x = jnp.asarray(x0, cm0.cdtype)
    key = jr.key(5)
    r0 = np.asarray(jb.rho_update(cm0, x, b0, key), np.float64)
    rm = np.asarray(jb.rho_update(cmm, jnp.asarray(x0, cmm.cdtype), bp,
                                  key), np.float64)
    idx = BlockIndex.build(pta.param_names)
    # identical up to one inverse-CDF grid cell (~0.006 in log10 rho;
    # the sharded all-reduce may reassociate the f64 sum)
    assert np.max(np.abs(r0[idx.rho] - rm[idx.rho])) < 0.02

    # (b) statistical: full posteriors match (different seeds)
    niter, burn = 1500, 300
    cm_chain = gm.sample(x0, outdir=str(tmp_path / "mesh"), niter=niter)
    c0 = g0.sample(x0, outdir=str(tmp_path / "nomesh"), niter=niter)
    assert np.all(np.isfinite(cm_chain)) and np.all(np.isfinite(c0))
    _assert_same_law(cm_chain[burn:], c0[burn:], idx.rho)


def _assert_same_law(a, b, cols, zmax=5.0):
    """Mixing-aware two-run equivalence: the weakly-constrained rho bins
    measure ACT up to ~140 sweeps here, so a raw KS on autocorrelated
    samples is wildly overconfident (two UNSHARDED runs of identical law
    measure p ~ 5e-3 at these lengths).  Every channel gets an ESS-aware
    z-test on the marginal mean; channels that actually mix (ACT < 10)
    additionally get a KS test on ACT-thinned samples.  Shared by the
    oracle/dense/sharded equivalence tests so the thresholds live in
    one place."""
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    for k in cols:
        xa, xb = a[:, k], b[:, k]
        acts = [max(float(integrated_act(np.ascontiguousarray(v))), 1.0)
                for v in (xa, xb)]
        se = np.sqrt(xa.var() * acts[0] / len(xa)
                     + xb.var() * acts[1] / len(xb))
        z = abs(xa.mean() - xb.mean()) / max(se, 1e-12)
        assert z < zmax, (k, z, acts)
        if max(acts) < 10:
            t = int(np.ceil(max(acts)))
            p = stats.ks_2samp(xa[::t], xb[::t]).pvalue
            assert p > 1e-4, (k, p)


def test_sharded_hd_vs_unsharded_ks(psrs8, tmp_path):
    """The correlated-ORF (HD) sequential sweep under a pulsar-sharded,
    padded mesh must sample the same rho posterior as the unsharded
    sweep — the cross-pulsar conditional gathers other shards' (and pad
    slots') coefficients, the highest-risk path for a sharding-induced
    statistical bug."""
    from pulsar_timing_gibbsspec_tpu.parallel import make_mesh

    pta = model_general(psrs8[:6], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=3, orf="hd")
    x0 = pta.initial_sample(np.random.default_rng(2))
    mesh = make_mesh(8)
    gm = PTABlockGibbs(pta, backend="jax", seed=11, progress=False,
                       mesh=mesh, pad_pulsars=8, warmup_sweeps=5)
    g0 = PTABlockGibbs(pta, backend="jax", seed=22, progress=False,
                       warmup_sweeps=5)
    niter, burn = 800, 200
    cmesh = gm.sample(x0, outdir=str(tmp_path / "mesh"), niter=niter)
    c0 = g0.sample(x0, outdir=str(tmp_path / "nomesh"), niter=niter)
    assert np.all(np.isfinite(cmesh)) and np.all(np.isfinite(c0))
    idx = BlockIndex.build(pta.param_names)
    _assert_same_law(cmesh[burn:], c0[burn:], idx.rho)


def test_make_mesh_raises_when_under_provisioned():
    """An under-provisioned mesh must fail loudly, never truncate: a
    truncated 1-device 'multi-device' dryrun exercises no sharding at all
    (the round-2 vacuous-pass failure mode)."""
    import jax
    import pytest

    from pulsar_timing_gibbsspec_tpu.parallel import make_mesh

    n = len(jax.devices())
    with pytest.raises(RuntimeError, match="refusing to build a truncated"):
        make_mesh(n + 1)
    # exact provisioning still works
    assert make_mesh(n).devices.size == n


def test_pad_pulsars_inert(psrs8):
    """Dummy mesh-padding pulsars must not change the common-rho logpdf."""
    pta = model_general(psrs8[:3], tm_svd=True, red_var=False,
                        white_vary=False, common_psd="spectrum",
                        common_components=5)
    x = pta.initial_sample(np.random.default_rng(0))
    cm3 = compile_pta(pta)
    cm4 = compile_pta(pta, pad_pulsars=4)
    import jax.numpy as jnp
    import jax.random as jr

    from pulsar_timing_gibbsspec_tpu.ops.linalg import mvn_conditional_draw

    key = jr.key(0)
    x = jnp.asarray(x, cm3.cdtype)
    # conditional b means agree on the real rows (PRNG shapes differ, so
    # compare the deterministic part)
    means = []
    for cm in (cm3, cm4):
        TNT, d = jb.tnt_d(cm, cm.ndiag(x))
        _, mean = mvn_conditional_draw(TNT, 1.0 / cm.phi(x), d,
                                       jnp.zeros((cm.P, cm.Bmax), cm.cdtype))
        means.append(np.asarray(mean))
    np.testing.assert_allclose(means[1][:3], means[0], rtol=1e-8)
    # identical b (padded with an inert row) -> identical common-rho draw
    b3 = jb.draw_b_fn(cm3, x, key)
    b4 = jnp.concatenate([b3, jnp.ones((1, cm4.Bmax), cm4.cdtype)])
    x3 = np.asarray(jb.rho_update(cm3, x, b3, key))
    x4 = np.asarray(jb.rho_update(cm4, x, b4, key))
    np.testing.assert_allclose(x3, x4, rtol=1e-7)


def test_hd_kernels_keep_pad_rows_inert(synth_hd_pta):
    """Both scalable HD b-draw kernels must leave pad-pulsar rows of b
    exactly as they came in: a pad row that churns (block 1 of the freq
    kernel used to draw noise into it) makes pad contents depend on the
    kernel choice and leaks kernel-dependent state into checkpoints
    (ADVICE r5)."""
    import jax.numpy as jnp
    import jax.random as jr

    pta = synth_hd_pta
    x = pta.initial_sample(np.random.default_rng(1))
    cm = compile_pta(pta, pad_pulsars=4)
    x = jnp.asarray(x, cm.cdtype)
    key = jr.key(7)
    b0 = jnp.asarray(jb.draw_b_fn(cm, x, key, exact=True))
    marker = 7.25          # exactly representable; survives bitwise
    b0 = b0.at[3].set(marker)
    for kern in (jb.draw_b_hd_freqblock, jb.draw_b_hd_sequential):
        b1 = np.asarray(kern(cm, x, b0, jr.key(11), exact=True))
        assert np.all(b1[3] == marker), kern.__name__
        assert np.all(np.isfinite(b1[:3])), kern.__name__


# ---------------------------------------------------------------------------
# driver entry points
# ---------------------------------------------------------------------------

def test_graft_entry_single_and_multichip():
    import importlib.util
    import sys

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["__graft_entry__"] = mod
    spec.loader.exec_module(mod)

    import jax

    fn, args = mod.entry()
    x1, b1 = jax.jit(fn)(*args)
    assert np.all(np.isfinite(np.asarray(x1)))
    mod.dryrun_multichip(8)


def test_draw_b_mh_acceptance_and_law(pta8):
    """The Metropolised b-draw must accept most proposals (the f32
    proposal is a near-perfect approximation of the conditional) and,
    composed with the periodic exact draw, reproduce the exact draw's law
    at a fixed state."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    cm = compile_pta(pta8)
    x = jnp.asarray(pta8.initial_sample(np.random.default_rng(2)),
                    cm.cdtype)
    b = jb.draw_b_fn(cm, x, jr.key(0))
    u = jb.b_matvec(cm, b)
    f = jax.jit(lambda b, u, k: jb.draw_b_mh(cm, x, b, u, k))
    accs = []
    for i in range(60):
        b, u, acc = f(b, u, jr.key(i + 1))
        accs.append(np.asarray(acc)[np.asarray(cm.psr_mask) > 0])
    rate = np.mean(accs)
    assert rate > 0.7, rate
    # law check: long alternating MH chain vs fresh exact draws, KS on a
    # few representative coefficients of pulsar 0
    chain, exact = [], []
    for i in range(400):
        b, u, _ = f(b, u, jr.key(1000 + i))
        if i % 8 == 0:      # periodic exact refresh, as the sweep body does
            b = jb.draw_b_fn(cm, x, jr.key(5000 + i))
            u = jb.b_matvec(cm, b)
        chain.append(np.asarray(b)[0, :6])
        exact.append(np.asarray(jb.draw_b_fn(cm, x, jr.key(9000 + i)))[0, :6])
    chain, exact = np.asarray(chain), np.asarray(exact)
    pv = [stats.ks_2samp(chain[::4, j], exact[:, j]).pvalue for j in range(6)]
    assert min(pv) > 1e-4, pv


def test_draw_b_conditional_accuracy(pta8):
    """The b-draw's conditional mean and (gw-column) variances must match
    the f64 oracle to ~1e-5 of the posterior sd at prior-typical states —
    the guard that rejected a faster whitened-basis f32 formulation whose
    near-degenerate directions were O(0.1 sigma) wrong."""
    import jax.numpy as jnp
    import scipy.linalg as sl

    from pulsar_timing_gibbsspec_tpu.ops.linalg import (_batched_diag,
                                                        precond_cholesky,
                                                        precond_solve)
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    cm = compile_pta(pta8)
    g = NumpyPTAGibbs(pta8, seed=0)
    for seed in (1, 42):
        x = jnp.asarray(pta8.initial_sample(np.random.default_rng(seed)),
                        np.float64)
        Sigma = jb.tnt_d(cm, cm.ndiag_fast(x))[0] + _batched_diag(
            1.0 / cm.phi(x))
        d = jb.tnt_d(cm, cm.ndiag_fast(x))[1]
        L, dj = precond_cholesky(Sigma)
        assert bool(jnp.all(jnp.isfinite(L)))
        mean = np.asarray(precond_solve(L, dj, d))
        params = g.map_params(np.asarray(x))
        g.invalidate_cache()
        g._ensure_cache(pta8.get_ndiag(params))
        pinv = pta8.get_phiinv(params, logdet=False)
        for ii in range(g.P):
            S = g._TNT[ii] + np.diag(pinv[ii])
            cf = sl.cho_factor(S)
            mn = sl.cho_solve(cf, g._d[ii])
            Cov = sl.cho_solve(cf, np.eye(S.shape[0]))
            sd = np.sqrt(np.diag(Cov))
            assert np.max(np.abs(mean[ii, :len(mn)] - mn) / sd) < 1e-4
            var_j = np.diag(np.linalg.inv(
                np.asarray(Sigma[ii], np.float64)))[:S.shape[0]]
            gwid = g.gwid[ii]
            assert np.max(np.abs(var_j[gwid] / np.diag(Cov)[gwid] - 1)) < 1e-4


# ---------------------------------------------------------------------------
# bf16 record option (transfer diet for bandwidth-starved device links)
# ---------------------------------------------------------------------------

def test_record_precision_bf16(j1713, tmp_path):
    """record_precision="bf16" rounds ONLY the record: the sampled process
    (here white MH + conditionals; no DE history in this model) is bitwise
    identical to the f32-record run, the recorded chain agrees with the
    f32 record to bf16 quantization, and resume stays bitwise within a
    bf16 run."""
    import ml_dtypes

    pta = model_general([j1713], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=5)
    x0 = pta.initial_sample(np.random.default_rng(5))
    kw = dict(backend="jax", seed=31, progress=False, white_adapt_iters=100,
              chunk_size=20, nchains=2)
    g32 = PulsarBlockGibbs(pta, **kw)
    c32 = g32.sample(x0, outdir=str(tmp_path / "f32"), niter=100)
    g16 = PulsarBlockGibbs(pta, record_precision="bf16", **kw)
    c16 = g16.sample(x0, outdir=str(tmp_path / "bf16"), niter=100)

    # the process itself is unchanged: final carries bitwise equal
    np.testing.assert_array_equal(g16._backend.x_cur, g32._backend.x_cur)
    # record agrees to bf16 quantization (exact equality would be broken
    # by f64->f32->bf16 double rounding on ~2^-16 of entries, so compare
    # against the bf16 rounding of the f32 record with 1-ulp slack)
    ref = np.asarray(c32, np.float32).astype(ml_dtypes.bfloat16)
    got = np.asarray(c16, np.float32).astype(ml_dtypes.bfloat16)
    close = np.isclose(got.astype(np.float64), ref.astype(np.float64),
                       rtol=2.0 ** -7, atol=1e-30)
    assert close.mean() > 0.9999, f"bf16 record disagrees: {1-close.mean():.2e}"

    # resume is bitwise within a bf16 run
    ga = PulsarBlockGibbs(pta, record_precision="bf16", **kw)
    ga.sample(x0, outdir=str(tmp_path / "split"), niter=60, save_every=20)
    gb = PulsarBlockGibbs(pta, record_precision="bf16", **kw)
    resumed = gb.sample(x0, outdir=str(tmp_path / "split"), niter=100,
                        resume=True, save_every=20)
    np.testing.assert_array_equal(resumed, c16)

    with pytest.raises(ValueError, match="record_precision"):
        PulsarBlockGibbs(pta, record_precision="f16", **kw)
