"""Unit tests for the resilience runtime (pulsar_timing_gibbsspec_tpu.
runtime): telemetry counters, fault arming semantics, checkpoint
manifest/verify/rotate/rollback, sentinel monitor, failure taxonomy,
backoff schedule, and the ChainStore satellite fixes (hdf5 tmp cleanup,
non-tty progress)."""

import json

import numpy as np
import pytest

from pulsar_timing_gibbsspec_tpu.runtime import (faults, integrity,
                                                 sentinels, supervisor,
                                                 telemetry)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---- telemetry -------------------------------------------------------------

def test_telemetry_counters():
    telemetry.reset()
    assert telemetry.get("retries") == 0
    telemetry.incr("retries")
    telemetry.incr("retries", 2)
    assert telemetry.get("retries") == 3
    snap = telemetry.snapshot()
    assert snap["retries"] == 3
    snap["retries"] = 99                      # snapshot is a copy
    assert telemetry.get("retries") == 3
    telemetry.reset()
    assert telemetry.snapshot() == {}


# ---- faults ----------------------------------------------------------------

def test_fault_fires_once_at_row():
    f = faults.inject("crash", point="p", at_row=10)
    faults.fire("p", row=5)                   # below threshold: no-op
    faults.fire("other", row=50)              # wrong seam: no-op
    with pytest.raises(faults.InjectedCrash):
        faults.fire("p", row=12)
    faults.fire("p", row=20)                  # consumed: no-op
    assert f.fired == 1


def test_fault_backend_filter_and_context_manager():
    with faults.injected("xla_error", point="p", at_row=0, backend="jax"):
        faults.fire("p", row=1, backend="numpy")     # filtered out
        with pytest.raises(faults.XlaRuntimeError):
            faults.fire("p", row=1, backend="jax")
    faults.fire("p", row=1, backend="jax")    # disarmed on exit


def test_mutate_rows_poisons_only_target_row():
    chain = np.zeros((10, 3))
    bchain = np.zeros((10, 4))
    faults.inject("nan_rows", at_row=6)
    faults.mutate_rows(chain, bchain, 0, 5)   # row 6 not in [0, 5)
    assert np.isfinite(chain).all()
    faults.mutate_rows(chain, bchain, 5, 8)
    assert np.isnan(chain[6]).all() and np.isnan(bchain[6]).all()
    assert np.isfinite(chain[:6]).all() and np.isfinite(chain[7:]).all()


def test_file_damage_kinds(tmp_path):
    p = tmp_path / "chain.npy"
    np.save(p, np.arange(100.0))
    size = p.stat().st_size
    faults.inject("truncate_file", point="s", at_row=0, path="chain.npy")
    faults.fire("s", row=1, outdir=tmp_path)
    assert p.stat().st_size < size
    np.save(p, np.arange(100.0))
    sha = integrity.file_sha256(p)
    faults.inject("corrupt_file", point="s", at_row=0, path="chain.npy")
    faults.fire("s", row=1, outdir=tmp_path)
    assert p.stat().st_size == size           # same size, different bytes
    assert integrity.file_sha256(p) != sha


# ---- integrity -------------------------------------------------------------

def _write_set(d, rows=5):
    np.save(d / "chain.npy", np.arange(rows * 3.0).reshape(rows, 3))
    np.save(d / "bchain.npy", np.ones((rows, 4)))
    np.savez(d / "adapt.npz", iter=np.int64(rows), rng=np.arange(6))
    return integrity.write_manifest(d, rows=rows)


def test_manifest_roundtrip_and_verify(tmp_path):
    man = _write_set(tmp_path)
    assert man["schema"] == integrity.SCHEMA_VERSION
    assert man["files"]["chain.npy"]["shape"] == [5, 3]
    assert man["files"]["chain.npy"]["dtype"] == "float64"
    rep = integrity.verify(tmp_path)
    assert rep["ok"] and rep["rows"] == 5


def test_verify_catches_truncation_and_corruption(tmp_path):
    _write_set(tmp_path)
    with open(tmp_path / "bchain.npy", "r+b") as fh:
        fh.truncate(40)
    rep = integrity.verify(tmp_path)
    assert not rep["ok"] and rep["bad"] == ["bchain.npy"]
    _write_set(tmp_path)
    with open(tmp_path / "chain.npy", "r+b") as fh:
        fh.seek(80)
        fh.write(b"\xff\xff\xff\xff")         # same size, flipped bytes
    rep = integrity.verify(tmp_path)
    assert not rep["ok"] and rep["bad"] == ["chain.npy"]


def test_unparseable_manifest_fails_verification(tmp_path):
    _write_set(tmp_path)
    (tmp_path / "manifest.json").write_text("{not json")
    assert not integrity.verify(tmp_path)["ok"]


def test_rotate_and_rollback(tmp_path):
    telemetry.reset()
    _write_set(tmp_path, rows=5)
    assert integrity.rotate_backup(tmp_path)
    _write_set(tmp_path, rows=8)              # new generation
    # damage the current set; the .bak generation must restore rows=5
    with open(tmp_path / "chain.npy", "r+b") as fh:
        fh.truncate(30)
    assert not integrity.verify(tmp_path)["ok"]
    assert integrity.rollback(tmp_path)
    rep = integrity.verify(tmp_path)
    assert rep["ok"] and rep["rows"] == 5
    assert len(np.load(tmp_path / "chain.npy")) == 5
    assert telemetry.get("rollbacks") == 1


def test_rotate_refuses_unverified_set(tmp_path):
    _write_set(tmp_path, rows=5)
    assert integrity.rotate_backup(tmp_path)
    _write_set(tmp_path, rows=8)
    with open(tmp_path / "chain.npy", "r+b") as fh:
        fh.truncate(30)
    # the torn current set must NOT overwrite the good backup
    assert not integrity.rotate_backup(tmp_path)
    assert integrity.verify(tmp_path, integrity.read_manifest(
        tmp_path, integrity.MANIFEST_BAK), suffix=".bak")["ok"]


def test_rollback_without_backup_fails(tmp_path):
    _write_set(tmp_path)
    assert not integrity.rollback(tmp_path)


# ---- sentinels -------------------------------------------------------------

def test_check_rows_names_first_bad_row():
    chain = np.zeros((10, 3))
    bchain = np.zeros((10, 2))
    sentinels.check_rows(chain, bchain, 0, 10)       # clean: no raise
    chain[7, 1] = np.nan
    with pytest.raises(sentinels.ChainDivergence) as ei:
        sentinels.check_rows(chain, bchain, 5, 10)
    assert ei.value.row == 7 and ei.value.what == "nonfinite"
    sentinels.check_rows(chain, bchain, 0, 7)        # before the bad row


def test_monitor_collapse_warns_stuck_raises():
    mon = sentinels.SentinelMonitor(collapse_frac=0.1, stuck_chunks=2)
    ok = {"finite": np.array([True]), "move_frac": np.array([0.5])}
    low = {"finite": np.array([True]), "move_frac": np.array([0.01])}
    dead = {"finite": np.array([True]), "move_frac": np.array([0.0])}
    assert mon.observe(ok, 10) == []
    ev = mon.observe(low, 20)
    assert ev and ev[0]["event"] == "mh_acceptance_collapse"
    assert mon.observe(dead, 30) == []               # streak 1: tolerated
    with pytest.raises(sentinels.ChainDivergence) as ei:
        mon.observe(dead, 40)                        # streak 2: wedged
    assert ei.value.what == "stuck_chain"
    mon.reset_run()
    assert mon.observe(dead, 50) == []               # streak reset


def test_refold_changes_numpy_rng_stream(tmp_path):
    rng = np.random.default_rng(7)
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import (rng_state_pack,
                                                            rng_state_unpack)

    np.savez(tmp_path / "adapt.npz", iter=np.int64(3),
             rng_state=rng_state_pack(rng))
    before = np.load(tmp_path / "adapt.npz")["rng_state"]
    assert sentinels.refold_checkpoint_key(tmp_path, salt=1)
    after = np.load(tmp_path / "adapt.npz")["rng_state"]
    assert not np.array_equal(before, after)
    # deterministic: same salt from the same state -> same refold
    r2 = np.random.default_rng()
    rng_state_unpack(r2, after)
    assert sentinels.refold_checkpoint_key(tmp_path, salt=9)
    # a second refold with a different salt moves the state again
    assert not np.array_equal(
        after, np.load(tmp_path / "adapt.npz")["rng_state"])


def test_refold_jax_key_and_manifest_update(tmp_path):
    import jax.random as jr

    key = jr.key(0)
    np.savez(tmp_path / "adapt.npz", iter=np.int64(4),
             jax_key=np.asarray(jr.key_data(key)))
    integrity.write_manifest(tmp_path, rows=4)
    assert sentinels.refold_checkpoint_key(tmp_path, salt=2)
    after = np.load(tmp_path / "adapt.npz")["jax_key"]
    assert not np.array_equal(after, np.asarray(jr.key_data(key)))
    assert np.array_equal(after, np.asarray(jr.key_data(
        jr.fold_in(key, 2))))
    # the manifest tracks the rewritten adapt.npz
    assert integrity.verify(tmp_path)["ok"]


# ---- supervisor taxonomy + backoff ----------------------------------------

def test_classify_failure_table():
    cf = supervisor.classify_failure
    assert cf(faults.InjectedCrash("x")) == "crash"
    assert cf(integrity.CheckpointError("x")) == "corruption"
    assert cf(sentinels.ChainDivergence("x")) == "divergence"
    assert cf(FloatingPointError("NaN at iteration 5")) == "divergence"
    assert cf(faults.XlaRuntimeError("INTERNAL: boom")) == "device"
    assert cf(RuntimeError("RESOURCE EXHAUSTED: out of memory")) == "device"
    assert cf(ValueError("x0 has shape (3,)")) == "user"
    assert cf(RuntimeError("cannot resume - nchains mismatch")) == "user"
    assert cf(RuntimeError("Disallowed host-to-device transfer "
                           "(transfer guard)")) == "user"
    assert cf(OSError("disk full")) == "crash"


def test_backoff_capped_deterministic():
    d = [supervisor.backoff_delay(r, base=0.5, cap=4.0, jitter=0.0)
         for r in range(1, 7)]
    assert d == [0.5, 1.0, 2.0, 4.0, 4.0, 4.0]       # doubles, then caps
    a = supervisor.backoff_delay(3, jitter=0.25, seed=1)
    b = supervisor.backoff_delay(3, jitter=0.25, seed=1)
    assert a == b                                     # reproducible jitter
    assert supervisor.backoff_delay(3, jitter=0.25, seed=2) != a


def test_supervisor_reraises_user_bugs_immediately(synth_pta, tmp_path):
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    g = PTABlockGibbs(synth_pta, backend="numpy", seed=1, progress=False)
    calls = []
    with pytest.raises(ValueError, match="parameters"):
        supervisor.run_supervised(g, np.zeros(99), tmp_path, 10,
                                  sleep=calls.append)
    assert calls == []                                # no retry, no sleep


# ---- ChainStore satellites -------------------------------------------------

def test_export_hdf5_cleans_tmp_on_failure(tmp_path):
    h5py = pytest.importorskip("h5py")
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore

    store = ChainStore(tmp_path, ["a", "b"], ["c"])
    chain = np.zeros((4, 2))
    bchain = np.zeros((4, 1))
    with pytest.raises(Exception):
        # an attribute h5py cannot serialize fails the export mid-write
        store.export_hdf5(chain, bchain, 4,
                          extra_attrs={"bad": object()})
    assert not (tmp_path / "chain.h5.tmp").exists()
    # a later retry succeeds from a clean slate
    store.export_hdf5(chain, bchain, 4)
    assert (tmp_path / "chain.h5").exists()
    with h5py.File(tmp_path / "chain.h5") as fh:
        assert fh.attrs["niter"] == 4


def test_progress_plain_lines_when_not_tty(synth_pta, tmp_path, capsys):
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    g = PTABlockGibbs(synth_pta, backend="numpy", seed=1, progress=True)
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    g.sample(x0, outdir=tmp_path, niter=30, save_every=10)
    out = capsys.readouterr().out
    assert "\r" not in out                    # captured stdout is not a tty
    lines = [ln for ln in out.splitlines() if ln]
    assert len(lines) >= 3 and all("rows" in ln for ln in lines)


def test_torn_legacy_checkpoint_warns_and_logs(synth_pta, tmp_path):
    from pulsar_timing_gibbsspec_tpu.sampler.chains import ChainStore
    from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PTABlockGibbs

    g = PTABlockGibbs(synth_pta, backend="numpy", seed=1, progress=False)
    x0 = synth_pta.initial_sample(np.random.default_rng(0))
    g.sample(x0, outdir=tmp_path, niter=20, save_every=10)
    # simulate a legacy torn write: shorten bchain, drop the manifest
    b = np.load(tmp_path / "bchain.npy")
    np.save(tmp_path / "bchain.npy", b[:15])
    (tmp_path / "manifest.json").unlink()
    (tmp_path / "manifest.bak.json").unlink(missing_ok=True)
    store = ChainStore(tmp_path, g.param_names, g.b_param_names)
    with pytest.warns(RuntimeWarning, match="torn checkpoint"):
        got = store.load_resume()
    assert got is not None and got[2] == 15   # common prefix
    events = [json.loads(ln) for ln in open(tmp_path / "metrics.jsonl")]
    torn = [e for e in events if e.get("event") == "torn_checkpoint"]
    assert torn and torn[0]["file"] == "bchain.npy"
    assert torn[0]["chain_rows"] == 20 and torn[0]["bchain_rows"] == 15


# ---- preemption (drain state machine + signal handlers) --------------------

def test_drain_request_is_idempotent_and_first_wins():
    from pulsar_timing_gibbsspec_tpu.runtime import preemption

    preemption.reset()
    telemetry.reset()
    try:
        assert not preemption.drain_requested()
        assert preemption.deadline_remaining() == float("inf")
        assert not preemption.should_abandon(1e9)
        preemption.request_drain("maintenance", deadline_s=10.0)
        assert preemption.drain_requested()
        # a later request cannot extend the grace window
        preemption.request_drain("later", deadline_s=1e6)
        info = preemption.drain_info()
        assert info["reason"] == "maintenance"
        assert info["deadline_s"] == 10.0
        assert 0 < preemption.deadline_remaining() <= 10.0
        assert preemption.should_abandon(60.0)
        assert not preemption.should_abandon(0.0)
        assert telemetry.get("preempt_requests") == 1
        lat = preemption.mark_drained()
        assert lat >= 0.0
        assert telemetry.get("preempt_drains") == 1
        assert telemetry.get_gauge("drain_latency_ms") == pytest.approx(
            lat * 1000.0)
    finally:
        preemption.reset()
    assert not preemption.drain_requested()


def test_signal_handler_drains_then_escalates():
    import os
    import signal

    from pulsar_timing_gibbsspec_tpu.runtime import preemption

    preemption.reset()
    preemption.install(signals=(signal.SIGTERM,), deadline_s=5.0)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert preemption.drain_requested()
        assert preemption.drain_info()["reason"] == "SIGTERM"
        # the SECOND signal must not be swallowed: a wedged drain still
        # dies on an operator's repeated kill
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGTERM)
    finally:
        preemption.uninstall()
        preemption.reset()


# ---- watchdog --------------------------------------------------------------

def test_watchdog_deadline_model():
    from pulsar_timing_gibbsspec_tpu.runtime.watchdog import DispatchWatchdog

    with pytest.raises(ValueError, match="exceed 1"):
        DispatchWatchdog(k=1.0)
    wd = DispatchWatchdog(k=4.0, floor_s=10.0, first_floor_s=300.0,
                          ema_alpha=0.5)
    assert wd.deadline() == 300.0          # no steady wall yet
    wd.observe(1.0)
    assert wd.deadline() == 10.0           # floored
    wd.observe(9.0)                        # ema -> 5.0
    assert wd.deadline() == pytest.approx(20.0)


def test_watchdog_passthrough_and_stall():
    import time as _t

    from pulsar_timing_gibbsspec_tpu.runtime.watchdog import (
        DispatchStall, DispatchWatchdog)

    telemetry.reset()
    events = []
    wd = DispatchWatchdog(k=2.0, floor_s=0.1, first_floor_s=0.15,
                          poll_s=0.01, on_event=lambda s, i: events.append(s))
    assert wd.call(lambda: "ok") == "ok"

    def boom():
        raise RuntimeError("from inside")

    with pytest.raises(RuntimeError, match="from inside"):
        wd.call(boom)
    with pytest.raises(DispatchStall, match="deadline"):
        wd.call(lambda: _t.sleep(2.0))
    assert events == ["soft", "dump", "stall"]
    assert telemetry.get("watchdog_stalls") == 1
    assert telemetry.get("watchdog_dumps") == 1
    assert telemetry.get("watchdog_soft") >= 1
    # the detached worker is replaced: the guard still serves new calls
    assert wd.call(lambda: 7) == 7


# ---- new fault kinds -------------------------------------------------------

def test_stall_and_sigterm_fault_kinds():
    import time as _t

    from pulsar_timing_gibbsspec_tpu.runtime import preemption

    preemption.reset()
    try:
        faults.inject("stall", point="dispatch.chunk", seconds=0.05)
        t0 = _t.monotonic()
        faults.fire("dispatch.chunk", row=0)
        assert _t.monotonic() - t0 >= 0.05
        faults.fire("dispatch.chunk", row=1)   # consumed: no second sleep
        faults.inject("sigterm_at_seam", point="sample.loop", seconds=3.0)
        faults.fire("sample.loop", row=5)
        assert preemption.drain_requested()
        assert preemption.drain_info()["deadline_s"] == 3.0
    finally:
        preemption.reset()


def test_device_count_override_consumes_one_firing():
    faults.inject("device_count_change_on_resume", devices=4)
    assert faults.device_count_override(8) == 4
    assert faults.device_count_override(8) == 8


# ---- layout manifest helpers ----------------------------------------------

def test_read_layout_roundtrip(tmp_path):
    np.save(tmp_path / "chain.npy", np.zeros((3, 2)))
    lay = {"facade": "PTABlockGibbs", "nchains": 2, "pad_pulsars": 8,
           "pulsars": ["A", "B"], "record_every": 1}
    shard = {"devices": 8, "axis": "pulsar", "platform": "cpu"}
    integrity.write_manifest(tmp_path, rows=3,
                             extra={"layout": lay, "shard_map": shard})
    info = integrity.read_layout(tmp_path)
    assert info == {"layout": lay, "shard_map": shard}
    # pre-layout manifests read as None (legacy checkpoints)
    integrity.write_manifest(tmp_path, rows=3)
    assert integrity.read_layout(tmp_path) is None


def test_refold_preserves_layout_sections(tmp_path):
    import jax.random as jr

    key = np.asarray(jr.key_data(jr.key(0)))
    np.savez(tmp_path / "adapt.npz", iter=np.int64(4), jax_key=key)
    np.save(tmp_path / "chain.npy", np.zeros((4, 2)))
    lay = {"facade": "PTABlockGibbs", "pad_pulsars": 8, "nchains": 1,
           "pulsars": ["A"], "record_every": 1}
    integrity.write_manifest(tmp_path, rows=4, extra={"layout": lay,
                                                      "shard_map": None})
    assert sentinels.refold_checkpoint_key(tmp_path, salt=1)
    info = integrity.read_layout(tmp_path)
    assert info is not None and info["layout"] == lay
