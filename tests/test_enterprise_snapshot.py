"""The real-data ingestion path, exercised hermetically.

The reference's canonical demo drives a *real* NANOGrav pulsar through
``enterprise.Pulsar`` into the sampler (``clean_demo.ipynb`` cells 3-5).
enterprise is not installed here, so the committed snapshot
``tests/data/enterprise_J1713+0747.npz`` records the enterprise attribute
surface at full structural fidelity (tempo2-style Mmat with DMX windows and
backend JUMPs, post-fit residuals, per-TOA flag arrays; see
``tools/make_enterprise_snapshot.py``), and these tests drive it through
``from_enterprise`` -> ``model_general`` -> both sampler backends — the
adapter is the code under test, not a stand-in loader.
"""

import os

import numpy as np
import pytest
from scipy import stats

from pulsar_timing_gibbsspec_tpu.data import load_enterprise_snapshot
from pulsar_timing_gibbsspec_tpu.models.factory import model_general
from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
from pulsar_timing_gibbsspec_tpu.sampler.gibbs import PulsarBlockGibbs

SNAP = os.path.join(os.path.dirname(__file__), "data",
                    "enterprise_J1713+0747.npz")


@pytest.fixture(scope="module")
def epsr():
    return load_enterprise_snapshot(SNAP)


def test_adapter_surface(epsr):
    """from_enterprise carries the recorded tempo2 solution at full
    fidelity: wide Mmat (incl. DMX + JUMP columns), post-fit residuals,
    array flags with a scalar 'pta' label."""
    assert epsr.name == "J1713+0747"
    n = epsr.ntoa
    assert epsr.Mmat.shape == (n, 105)
    assert any(f.startswith("DMX_") for f in epsr.fitpars)
    assert "JUMP1" in epsr.fitpars
    # the pta flag is normalized to a scalar label for the factory's
    # ECORR gate; other flags stay per-TOA arrays
    assert epsr.flags["pta"] == "NANOGrav"
    assert epsr.flags["fe"].shape == (n,)
    assert len(epsr.backends()) == 4          # 2 receivers x 2 backends
    # post-fit residuals: orthogonal to the fitted column space
    Mn = epsr.Mmat / np.linalg.norm(epsr.Mmat, axis=0)
    proj = np.abs(Mn.T @ epsr.residuals) / np.linalg.norm(epsr.residuals)
    assert proj.max() < 1e-6
    # full rank after column normalization
    assert np.linalg.matrix_rank(Mn) == 105


def test_snapshot_through_factory_and_samplers(epsr, tmp_path):
    """clean-demo model on the snapshot (reference cells 5-9): the wide
    enterprise Mmat is marginalized, NANOGrav pta flag gates ECORR, both
    backends sample to KS-matched posteriors."""
    pta = model_general([epsr], tm_svd=True, red_var=False,
                        white_vary=True, common_psd="spectrum",
                        common_components=10)
    # the NANOGrav flag added per-backend ECORR parameters
    assert any("ecorr" in p for p in pta.param_names)
    x0 = pta.initial_sample(np.random.default_rng(7))
    chains = {}
    for backend, seed in [("jax", 11), ("numpy", 12)]:
        g = PulsarBlockGibbs(pta, backend=backend, seed=seed, progress=False)
        chains[backend] = g.sample(x0, outdir=str(tmp_path / backend),
                                   niter=1200)
    burn = 200
    idx = BlockIndex.build(pta.param_names)
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    # rho channels mix fast: ACT-thinned KS.  The ECORR amplitudes ride a
    # 508-column coefficient block and measure ACT ~80-150 sweeps in BOTH
    # backends at this length (a handful of effective samples) — a raw KS
    # there is statistically invalid, so they get the ESS-aware z-test the
    # HD tests use.
    pvals = []
    for k in idx.rho:
        a, b = chains["jax"][burn:, k], chains["numpy"][burn:, k]
        ta = max(integrated_act(np.ascontiguousarray(a)), 1.0)
        tb = max(integrated_act(np.ascontiguousarray(b)), 1.0)
        thin = int(np.ceil(max(ta, tb)))
        pvals.append(stats.ks_2samp(a[::thin], b[::thin]).pvalue)
    assert min(pvals) > 1e-4, pvals
    assert np.median(pvals) > 0.05, pvals
    for k in idx.ecorr[:2]:
        a, b = chains["jax"][burn:, k], chains["numpy"][burn:, k]
        ess_a = len(a) / max(integrated_act(np.ascontiguousarray(a)), 1.0)
        ess_b = len(b) / max(integrated_act(np.ascontiguousarray(b)), 1.0)
        z = abs(a.mean() - b.mean()) / np.sqrt(
            a.var() / ess_a + b.var() / ess_b)
        assert z < 4.5, (pta.param_names[k], z, ess_a, ess_b)
        # and the chains actually move
        assert np.std(a) > 1e-3 and np.std(b) > 1e-3
