"""Benchmark: Gibbs posterior samples/sec on the full 45-pulsar simulated PTA.

Prints ONE JSON line ``{"metric", "value", "unit", "vs_baseline", ...}``.

The metric is steady-state (post-adaptation, post-compile) Gibbs posterior
samples per second — sweeps/sec times the number of vmapped chains — of the
JAX device backend on the 45-pulsar ``simulated_data`` array with varying
white noise, per-pulsar free-spectrum red noise and a common free-spectrum
GW process (the BASELINE.json north-star config).  Every chain is an
independent KS-validated Gibbs process (tests/test_jax_backend.py::
test_nchains_ks_and_shapes), so chains multiply posterior samples/sec the
same way the reference would by running N copies — except the TPU runs them
in one compiled program on one chip.  ``vs_baseline`` is the speedup over
the in-repo float64 NumPy oracle (reference semantics, single CPU, one
chain) measured on the same model in the same process; the north-star
target is >= 20x.

Measurement: the steady phase is split into five equal windows and the
per-window rates are reported (``rate_windows``); the headline uses the
*median* window so one tunnel hiccup can neither inflate nor sink the
number (the TPU tunnel shows ~3x run-to-run variance).  The artifact also
always carries ``mfu``, ``per_block_ms`` and ``device_kind`` so the perf
claim is auditable from the JSON alone, plus an ``hd`` sub-object
benchmarking the correlated-ORF (Hellings-Downs) sweep — the beyond-
reference path (reference ``pta_gibbs.py:533`` is CRN-only) — against the
NumPy HD oracle.

Usage: python bench.py [--quick] [--niter N] [--numpy-iters N]
                       [--nchains C] [--profile] [--orf {both,crn,hd}]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def build_pta(n_psr=45, nbins=10, orf="crn"):
    from pulsar_timing_gibbsspec_tpu.data import load_directory
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general

    psrs = load_directory(
        REFDATA, inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0))
    psrs = psrs[:n_psr]
    kw = {}
    if orf != "crn":
        kw["orf"] = orf
    return model_general(
        psrs, tm_svd=True, white_vary=True,
        common_psd="spectrum", common_components=nbins,
        red_var=True, red_psd="spectrum", red_components=nbins, **kw)


NWINDOWS = 5


def _trim_steady(marks, nwin=NWINDOWS):
    """Drop the trailing marks that cannot belong to a steady window:

    - a PARTIAL trailing chunk (iteration stride below the modal chunk
      stride) measures a different amount of work per mark than every
      other window member;
    - the FINAL chunk's device-to-host writeback has no following compute
      to overlap with (the double-buffered steady loop drains there), so
      its interval mixes transfer drain into the rate.  BENCH_r05's last
      window read 12.92 vs ~63 (crn) and 17.31 vs ~24 (hd) purely from
      this contamination.

    Only the *rate* computation trims; the raw marks stay complete in the
    JSON (``_raw_marks``) so the drain remains visible and re-derivable.
    The drain drop only applies to chunked marks (stride > 1) with enough
    marks left for ``nwin`` real windows — the numpy oracle's per-sweep
    marks have no writeback to drain."""
    marks = np.asarray(marks, dtype=np.float64)
    if len(marks) < 4:
        return marks
    strides = np.diff(marks[:, 0])
    modal = float(np.median(strides[:-1]))
    if strides[-1] < modal:
        marks = marks[:-1]
    if modal > 1 and len(marks) >= nwin + 2:
        marks = marks[:-1]
    return marks


def _window_rates(marks, nwin=NWINDOWS):
    """Per-window sweep rates from (iteration, time) marks split into
    ``nwin`` equal spans (median-of-windows absorbs tunnel hiccups; >=5
    windows so the median has real support).  Incomplete trailing work —
    a partial final chunk or the un-overlapped final writeback — is
    trimmed first (``_trim_steady``) so the last window measures the same
    steady process as the others."""
    marks = np.asarray(marks, dtype=np.float64)
    if len(marks) < 2:
        return []
    marks = _trim_steady(marks, nwin)
    if len(marks) < nwin + 1:
        its, ts = marks[-1, 0] - marks[0, 0], marks[-1, 1] - marks[0, 1]
        return [float(its / ts)] if ts > 0 else []
    cuts = np.linspace(0, len(marks) - 1, nwin + 1).astype(int)
    out = []
    for a, b in zip(cuts[:-1], cuts[1:]):
        dt = marks[b, 1] - marks[a, 1]
        if dt > 0:
            out.append(float((marks[b, 0] - marks[a, 0]) / dt))
    return out


def _raw_marks(marks):
    """Self-explaining raw totals: every cross-round number is
    re-derivable from (iteration, unix-time) mark pairs.  The per-chunk
    wall timeline (``chunk_wall_ms``) makes window spread attributable
    from the JSON alone — in particular the final chunk, whose
    device-to-host writeback has no following compute to overlap with
    (the double-buffered steady loop drains there), shows up as the
    last entry rather than as an unexplained last-window droop."""
    marks = np.asarray(marks, dtype=np.float64)
    if len(marks) < 2:
        return {}
    walls = np.diff(marks[:, 1]) * 1e3
    out = {
        "steady_sweeps": int(marks[-1, 0] - marks[0, 0]),
        "steady_wall_s": round(float(marks[-1, 1] - marks[0, 1]), 3),
        "marks": [[int(i), round(float(t), 3)] for i, t in marks],
        "chunk_wall_ms": [round(float(w), 1) for w in walls],
    }
    med = float(np.median(walls))
    if len(walls) >= 3 and med > 0:
        slow = [int(i) for i, w in enumerate(walls) if w > 1.5 * med]
        if slow:
            note = (f"chunks {slow} ran >1.5x the {med:.0f} ms median "
                    "(tunnel transfer stalls; the headline is the median "
                    "window, which absorbs them)")
            if len(walls) - 1 in slow:
                note += ("; the final chunk additionally drains the "
                         "double-buffered writeback with no following "
                         "compute to overlap")
            out["slow_chunk_note"] = note
    return out


def _parse_mesh(s):
    """``"CxP"`` -> 2-d (chain, pulsar) mesh shape tuple, ``"N"`` -> 1-d
    pulsar mesh size (chaos_probe.py --devices grammar)."""
    if isinstance(s, str) and "x" in s:
        c, p = s.lower().split("x", 1)
        return (int(c), int(p))
    return int(s)


def bench_jax(pta, x0, niter, adapt_iters, nchains, profile=False,
              record="f32", record_every=1, mesh_shape=None,
              ensemble=False, pt_ladder=1):
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    # >= ~8 post-compile chunk marks so the five windows are real
    chunk = max(10, min(100, niter // 8))
    if record_every > 1:
        chunk = max(record_every, chunk - chunk % record_every)
    mesh_kw = {}
    if mesh_shape is not None:
        from pulsar_timing_gibbsspec_tpu.parallel.sharding import (
            make_mesh, pulsar_submesh_size)

        mesh = make_mesh(mesh_shape)
        # the pulsar axis shards the padded width: round up so 45
        # pulsars land on any submesh (48 on 2x4); the chain submesh is
        # validated against C by the driver (actionable error, not a
        # GSPMD shape failure)
        p_sub = pulsar_submesh_size(mesh)
        n_psr = len(pta.pulsars)
        mesh_kw = dict(mesh=mesh,
                       pad_pulsars=-(-n_psr // p_sub) * p_sub)
    # streaming diagnostic sketch rides the chunk (obs/): device-side
    # ACT/ESS come off the bounded summary slab instead of the shipped
    # chains.  lags=256 comfortably covers the measured rho taus
    # (~45-50 sweeps; Sokal window ~5*tau)
    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=adapt_iters, chunk_size=chunk,
                         nchains=nchains, record_precision=record,
                         record_every=record_every, obs={"lags": 256},
                         ensemble=ensemble, pt_ladder=pt_ladder,
                         **mesh_kw)
    C = drv.C
    cshape, bshape = drv.chain_shapes(niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    from pulsar_timing_gibbsspec_tpu import profiling

    marks = []
    first = True
    with profiling.recompile_counter() as rc:
        # phase-scoped counting: warmup/adaptation compiles land in the
        # "warmup" bucket, and the driver brackets legitimate cache-miss
        # chunk compiles as planned, so the steady retrace count below
        # is unpolluted by either
        rc.phase("warmup")
        it = drv.run(x0, chain, bchain, 0, niter)
        done = next(it)        # warmup + adaptation + compilation
        for done in it:
            if first:
                # first chunk includes the sweep-kernel compile (still
                # "warmup"); the steady clock and phase start at its
                # writeback
                marks = [(done, time.time())]
                rc.phase("steady")
                first = False
            else:
                # each chunk writeback is an honest device sync
                marks.append((done, time.time()))
    # unplanned compiles observed in the steady loop — must be 0; any
    # retrace is a throughput regression BENCH_*.json should surface
    n_retraces = rc.unplanned("steady")
    # marks count recorded ROWS; one row is record_every sweeps in the
    # steady loop, so sweep rates scale back up by the thinning factor
    # (the raw marks are converted to sweep units too, so steady_sweeps
    # and the headline rate stay mutually re-derivable)
    windows = [w * record_every for w in _window_rates(marks)]
    assert windows, "benchmark too short to measure a steady window"
    assert np.all(np.isfinite(chain)), "non-finite chain values"
    steady = float(np.median(windows))
    raw = _raw_marks([(i * record_every, t) for i, t in marks])
    prof = None
    if profile:
        from pulsar_timing_gibbsspec_tpu import profiling

        times = profiling.profile_blocks(drv, drv.x_cur, repeats=3, inner=20)
        fl = profiling.sweep_flops(drv.cm, nchains=C)
        print(profiling.format_report(times, fl, steady), file=sys.stderr)
        prof = times
    try:
        obs_sum = drv.obs_summary()
    except Exception as exc:  # noqa: BLE001 — diagnostics never kill a bench
        print(f"# obs summary failed: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        obs_sum = None
    return steady, windows, C, drv, prof, raw, chain, n_retraces, obs_sum


def bench_numpy(gibbs, x0, niter, act_iters=0):
    """Timed oracle rate over ``niter`` sweeps, then (untimed) extra
    sweeps up to ``act_iters`` rows: a Sokal ACT is capped near len/3,
    and the 45-pulsar common-rho ACT measures ~45-50 sweeps on the
    device chains — an oracle ACT read off a 100-sweep chain would be
    silently floored, overstating vs_oracle_ess by ~8x."""
    x = gibbs.sweep(x0, first=True)  # adaptation, untimed
    marks = [(0, time.time())]
    rec = np.empty((max(niter, act_iters), len(x)), np.float64)
    for ii in range(niter):
        x = gibbs.sweep(x)
        rec[ii] = x
        marks.append((ii + 1, time.time()))
    for ii in range(niter, len(rec)):
        x = gibbs.sweep(x)
        rec[ii] = x
    windows = _window_rates(marks, nwin=3)
    return (float(np.median(windows)), windows,
            _raw_marks([marks[0], marks[-1]]), rec)


def _retry_transport(fn):
    """The tunneled TPU's remote-compile endpoint drops transiently
    ("read body: response body closed..."); retry with a fresh driver
    rather than failing the whole benchmark on a transport hiccup."""
    last = None
    for attempt in range(3):
        try:
            return fn()
        except Exception as exc:
            if "remote_compile" not in str(exc):
                raise
            last = exc
            print(f"# remote-compile transport dropped "
                  f"(attempt {attempt + 1}/3); retrying", file=sys.stderr)
            time.sleep(20)
    raise last


def _rho_act(chain, rho_cols, burn):
    """Median Sokal ACT of the common-spectrum channels (per chain when a
    chains axis is present), in units of recorded rows."""
    from pulsar_timing_gibbsspec_tpu.ops.acf import integrated_act

    chain = np.asarray(chain, np.float64)
    if chain.ndim == 2:
        chain = chain[:, None, :]
    acts = [integrated_act(np.ascontiguousarray(chain[burn:, c, k]))
            for k in rho_cols for c in range(chain.shape[1])]
    return float(np.median(acts)) if acts else 1.0


def _mesh_axes(mesh_shape):
    """Normalize a mesh spec to the headline's ``mesh_axes`` object.

    None (single-device vmap, no mesh) and a 1-d pulsar mesh both have a
    chain axis of 1; the artifact records physical axis sizes, so scaling
    claims name the axis they scaled (chains are embarrassingly parallel,
    the pulsar axis pays the common-rho all-reduce)."""
    if mesh_shape is None:
        return {"n_chain_devs": 1, "n_pulsar_devs": 1}
    if isinstance(mesh_shape, tuple):
        return {"n_chain_devs": mesh_shape[0],
                "n_pulsar_devs": mesh_shape[1]}
    return {"n_chain_devs": 1, "n_pulsar_devs": int(mesh_shape)}


def bench_config(orf, n_psr, niter, np_iters, adapt, nchains, profile,
                 record="f32", record_every=1, mesh_shape=None,
                 ensemble=False, pt_ladder=1):
    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    pta = build_pta(n_psr=n_psr, orf=orf)
    x0 = pta.initial_sample(np.random.default_rng(0))
    idx = BlockIndex.build(pta.param_names)
    if orf != "crn" and len(idx.orf):
        # parameterized/fixed correlated ORFs start at G = identity
        x0[idx.orf] = 0.0
    jax_rate, windows, C, drv, prof, raw, chain, n_retraces, obs_sum = \
        _retry_transport(
        lambda: bench_jax(pta, x0, niter, adapt, nchains, profile=profile,
                          record=record, record_every=record_every,
                          mesh_shape=mesh_shape, ensemble=ensemble,
                          pt_ladder=pt_ladder))
    g = NumpyPTAGibbs(pta, seed=2, white_adapt_iters=adapt)
    np_rate, np_windows, np_raw, np_chain = bench_numpy(
        g, np.asarray(x0, np.float64), np_iters,
        # >= 200 rows even for the short HD/quick legs: the Sokal window
        # needs ~5*tau rows, and the measured oracle taus reach ~27
        act_iters=max(4 * np_iters, 200))
    fl = profiling.sweep_flops(drv.cm, nchains=C)
    out = {
        "sweeps_per_sec": round(jax_rate, 2),
        "rate_windows": [round(w, 2) for w in windows],
        "nchains": C,
        "mesh_axes": _mesh_axes(mesh_shape),
        "record_every": record_every,
        "n_retraces": n_retraces,
        "numpy_sweeps_per_sec": round(np_rate, 3),
        "numpy_rate_windows": [round(w, 3) for w in np_windows],
        "vs_oracle": round(C * jax_rate / np_rate, 2),
        "mfu": round(fl["total"] * jax_rate / profiling.device_peak_flops(),
                     6),
        "raw": raw,
        "numpy_raw": np_raw,
    }
    if prof is not None:
        out["per_block_ms"] = {k: round(v, 3)
                               for k, v in prof["per_block_ms"].items()}
        # reconciliation companions (see profiling.profile_blocks): which
        # blocks are actually in THIS config's every-sweep body, their
        # subtotal, and the composed sweep they must reconcile with — so
        # per_block_ms can't silently mix off-sweep entries (the r05
        # b_draw=403.8-next-to-full_sweep=10.8 misread)
        out["per_block_in_sweep"] = prof["in_sweep"]
        out["sum_blocks_ms"] = round(prof["sum_blocks_ms"], 3)
        out["full_sweep_ms"] = round(prof["full_sweep_ms"], 3)
        out["dispatch_ms"] = round(prof["dispatch_ms"], 3)
        # where one REAL chunk's wall goes (profiling.dispatch_breakdown):
        # host-prep vs enqueue vs device wait vs record writeback — the
        # per-chunk complement of the bare jit-overhead dispatch_ms
        if prof.get("dispatch_breakdown_ms"):
            bd = prof["dispatch_breakdown_ms"]
            out["dispatch_breakdown_ms"] = {
                k: round(v, 3) for k, v in bd.items()}
            # the dispatch-tax headline the mega-chunk loop drives:
            # host-side overhead per dispatch amortized over the sweeps
            # one dispatch covers — gated lower-is-better in the perf
            # ledger (obs.perf.LOWER_IS_BETTER)
            if "dispatch_amortized_per_sweep" in bd:
                out["dispatch_amortized_ms_per_sweep"] = round(
                    bd["dispatch_amortized_per_sweep"], 4)
        # static roofline attribution (profiling.block_cost_model joined
        # with the measured per-block times): per-block FLOPs/HBM bytes,
        # arithmetic intensity, MFU and bound class — the artifact form
        # of the format_report roofline table, so "which block to fuse
        # next" is answerable from the committed JSON alone
        if prof.get("roofline"):
            out["roofline"] = prof["roofline"]
    # resilience counters (runtime.telemetry): retries/rollbacks/refolds
    # accumulated during this process plus the driver's last on-device
    # health reductions — a long bench that silently retried or rolled
    # back is a different claim than a clean one
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    # counters cover the drain/watchdog/stall taxonomy too
    # (preempt_requests/preempt_drains/drain_abandoned_chunks/
    # watchdog_soft/watchdog_dumps/watchdog_stalls/stall_retries);
    # gauges carry last-value measurements such as drain_latency_ms
    out["resilience"] = {"counters": telemetry.snapshot(),
                         "gauges": telemetry.gauges(),
                         "sentinel": getattr(drv, "health_last", None)}
    # which static contracts this build was proven against (jaxprcheck):
    # the hash set ties a bench artifact to the exact committed budgets;
    # the fast subset re-audits here so a bench run on a drifted program
    # records the failure in its own artifact
    try:
        from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.runner import (
            contract_hashes, discover_contracts, run_contracts)

        jv, _facts = run_contracts(discover_contracts(fast_only=True))
        out["resilience"]["jaxprcheck"] = {
            "contracts": contract_hashes(),
            "fast_audit_violations": [str(v) for v in jv],
        }
    except Exception as e:   # the audit must never take down a bench run
        out["resilience"]["jaxprcheck"] = {"error": f"{type(e).__name__}: {e}"}
    # throughput x mixing, BOTH configs (VERDICT r3: "throughput x unknown
    # ACT is not a samples/sec claim"; r4: CRN carried no ACT at all and
    # vs_oracle was throughput-only).  Median Sokal ACT of the rho_k
    # channels from this run's own chains, in recorded-row units, so
    # ess_per_sec = chains x rows/s / ACT_rows is thinning-invariant;
    # the oracle's own ACT makes vs_oracle_ess an honest ESS-based
    # comparison (the HD oracle's dense joint draw mixes ~1.49x better
    # per sweep than the sequential device sweep, docs/HD_MIXING.md —
    # a throughput-only ratio overstates the win by that factor).
    burn = min(len(chain) // 4, 200)
    # with a tempering ladder only every T-th chain samples at beta=1;
    # mixing and the ESS rate are measured on those chains alone
    T = max(1, int(pt_ladder))
    cold = chain if T == 1 else np.asarray(chain)[:, ::T]
    C_eff = C // T
    act_rows = _rho_act(cold, idx.rho, burn)
    # ACT is reported in SWEEP units everywhere (row-ACT x record_every)
    # so the headline and thinned legs are directly comparable; the ESS
    # rate C x sweeps/s / ACT_sweeps == C x rows/s / ACT_rows is the
    # same thinning-invariant number in equivalent form
    act_med = act_rows * record_every
    out["rho_act_median"] = round(act_med, 2)
    out["ess_per_sec"] = round(C_eff * jax_rate / max(act_med, 1.0), 1)
    oracle_act = _rho_act(np_chain, idx.rho, min(len(np_chain) // 4, 200))
    out["oracle_rho_act"] = round(oracle_act, 2)
    oracle_ess = np_rate / max(oracle_act, 1.0)
    out["oracle_ess_per_sec"] = round(oracle_ess, 2)
    out["vs_oracle_ess"] = round(out["ess_per_sec"] / oracle_ess, 2)
    if ensemble:
        # the mixing-engine config rides the artifact next to the rates
        # it is claimed to explain (stretch/ASIS acceptance, ladder)
        ens_sum = drv.ensemble_summary()
        if ens_sum is not None:
            out["ensemble"] = ens_sum
    # device-side mixing from the streaming sketch (obs/): rho-ACT in
    # SWEEP units straight off the bounded summary slab — no chain
    # transfer involved — plus a parity ratio against the host Sokal on
    # this run's own thinned chains (both sides are sweep units now;
    # the obs acceptance band is 10%, i.e. parity in [0.9, 1.1] modulo
    # the host burn window)
    if obs_sum is not None:
        act_dev = float(obs_sum["act_rho_med"])
        out["rho_act_device"] = round(act_dev, 2)
        out["ess_per_sec_device"] = round(
            C_eff * jax_rate / max(act_dev, 1.0), 1)
        out["act_parity_device_vs_host"] = (
            round(act_dev / act_med, 4) if act_med > 0 else None)
        if obs_sum.get("rhat_max") is not None:
            out["rhat_max_device"] = round(float(obs_sum["rhat_max"]), 4)
        if obs_sum.get("window_saturated"):
            out["obs_window_saturated"] = True
        # units-parity gate: host and device ESS rates are the SAME
        # quantity (chains x sweeps/s / ACT_sweeps) measured two ways,
        # so a relapse of the row-vs-sweep units bug shows up as a
        # multiple-of-record_every split between them.  Sokal-window
        # noise on short thinned chains is real, hence the loose band;
        # skipped when the sketch window saturated (its ACT is a floor,
        # not a measurement) or the run is too short to estimate
        if (T == 1 and not obs_sum.get("window_saturated")
                and len(cold) - burn >= 200):
            ratio = out["ess_per_sec"] / max(out["ess_per_sec_device"],
                                             1e-9)
            assert 1.0 / 3.0 <= ratio <= 3.0, (
                f"ess_per_sec {out['ess_per_sec']} vs "
                f"ess_per_sec_device {out['ess_per_sec_device']} "
                f"disagree by {ratio:.2f}x — row/sweep ACT units have "
                "diverged between the host and device estimators")
    return out


def thinned_probe(orf, n_psr, niter, adapt, nchains, record, k=4,
                  ensemble=False):
    """Jax-only measurement of a thinned-record run (no oracle rerun):
    steady sweep rate + this run's own mixing-adjusted ess_per_sec."""
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex

    pta = build_pta(n_psr=n_psr, orf=orf)
    x0 = pta.initial_sample(np.random.default_rng(0))
    idx = BlockIndex.build(pta.param_names)
    if orf != "crn" and len(idx.orf):
        x0[idx.orf] = 0.0
    rate, windows, C, drv, _, raw, chain, _, obs_sum = bench_jax(
        pta, x0, niter, adapt, nchains, profile=False, record=record,
        record_every=k, ensemble=ensemble)
    act_rows = _rho_act(chain, idx.rho, min(len(chain) // 4, 200))
    # row-ACT x k converts to SWEEP units — the r5 artifact reported the
    # thinned leg's ACT in raw row units (10.33 rows next to the
    # headline's 45 sweeps), which read as a 4x mixing win that was
    # pure thinning; ess_per_sec = C x sweeps/s / ACT_sweeps is the
    # identical number either way, the ACT label is what changed
    act = act_rows * k
    out = {
        "record_every": k,
        "sweeps_per_sec": round(rate, 2),
        "rate_windows": [round(w, 2) for w in windows],
        "nchains": C,
        "rho_act_median": round(act, 2),
        "ess_per_sec": round(C * rate / max(act, 1.0), 1),
        "raw": raw,
    }
    # the thinned leg is where the device sketch earns its keep: the
    # host ACT only sees every k-th row, the sketch saw every sweep
    if obs_sum is not None:
        act_dev = float(obs_sum["act_rho_med"])
        out["rho_act_device"] = round(act_dev, 2)
        out["ess_per_sec_device"] = round(C * rate / max(act_dev, 1.0), 1)
    return out


def bench_serve(quick=False, niter=None, slots=2, chunk=4):
    """Serving-mode benchmark: multiplexed aggregate samples/s and
    warm-start admission latency of the resident service, on synthetic
    datasets (standalone — no reference data needed).

    Two phases: a *cold* phase pays the bucket compile with two
    multiplexed tenants; a *warm* phase then admits two FRESH tenants
    (new PRNG streams, one on a dataset shape the bucket has never
    seen) onto the already-compiled program — its wall clock is the
    steady multiplexed throughput and its first-sample latencies are
    the warm-start SLO.  Any unplanned retrace in either phase is
    reported (and must be zero: contracts/serve_buckets.json)."""
    import shutil
    import tempfile

    from pulsar_timing_gibbsspec_tpu import profiling
    from pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck.entries import (
        build_model, synthetic_pulsars)
    from pulsar_timing_gibbsspec_tpu.runtime import telemetry
    from pulsar_timing_gibbsspec_tpu.serve import (
        BucketSpec, BucketTable, SamplerService)

    niter = niter or (16 if quick else 64)
    ptas = [build_model(synthetic_pulsars(2, 24 + 6 * i, tm_cols=3,
                                          seed=i), 3)
            for i in range(3)]
    table = BucketTable([BucketSpec(2, 48, 24, 3)])
    root = tempfile.mkdtemp(prefix="bench_serve_")
    telemetry.reset()
    try:
        svc = SamplerService(root, table, slots=slots, chunk=chunk,
                             quantum=10 ** 9)
        with profiling.recompile_counter() as rc:
            rc.phase("cold")
            cold = [svc.submit(ptas[i], niter, tenant_id=i)
                    for i in range(2)]
            t0 = time.time()
            svc.run()
            cold_wall = time.time() - t0
            rc.phase("warm")
            warm = [svc.submit(ptas[d], niter, tenant_id=t)
                    for d, t in ((2, 2), (0, 3))]
            t0 = time.time()
            svc.run()
            warm_wall = time.time() - t0
        rows = sum(j.it for j in warm)
        lat = [j.time_to_first_sample_ms() for j in warm]
        return {
            "niter": niter, "slots": slots, "chunk": chunk,
            "jobs": {j.job_id: j.state for j in cold + warm},
            "cold_wall_s": round(cold_wall, 3),
            "cold_samples_per_s": round(
                sum(j.it for j in cold) / cold_wall, 2),
            "aggregate_samples_per_s": round(rows / warm_wall, 2),
            "warm_start_latency_ms": round(min(lat), 2),
            "warm_start_latency_ms_worst": round(max(lat), 2),
            "warm_hit_rate": svc.cache.warm_hit_rate(),
            "unplanned_retraces": {
                "cold": rc.unplanned("cold"),
                "warm": rc.unplanned("warm")},
            "gauges": telemetry.gauges(),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def scaling_probe(axis, ndev, niter=96, nchains=8):
    """One per-axis scaling point: samples/s and ESS/s of the CRN sweep
    on a mesh that puts ``ndev`` devices on ``axis`` and 1 on the other.

    Self-contained (synthetic pulsars, no reference data) so the probe
    runs in the CPU host-platform-device-count subprocesses the parent
    ``--scaling`` mode spawns.  8 pulsars / nchains=8 divide every
    power-of-two submesh up to 8, so no point pays padding waste and the
    per-device work is identical across the row — the honest weak-scaling
    frame for an embarrassingly parallel chain axis."""
    from __graft_entry__ import _model, _synthetic_pulsars
    from pulsar_timing_gibbsspec_tpu.sampler.blocks import BlockIndex

    psrs = _synthetic_pulsars(8, ntoa=24, nmodes=3)
    pta = _model(psrs, nmodes=3)
    x0 = pta.initial_sample(np.random.default_rng(0))
    idx = BlockIndex.build(pta.param_names)
    shape = (ndev, 1) if axis == "chain" else (1, ndev)
    steady, windows, C, drv, _prof, _raw, chain, n_retraces, obs_sum = \
        bench_jax(pta, x0, niter, 64, nchains, profile=False,
                  mesh_shape=shape)
    burn = min(len(chain) // 4, 200)
    act = _rho_act(chain, idx.rho, burn)
    out = {
        "axis": axis, "n_devices": ndev,
        "mesh_axes": _mesh_axes(shape),
        "samples_per_sec": round(C * steady, 2),
        "sweeps_per_sec": round(steady, 2),
        "nchains": C,
        "n_retraces": n_retraces,
        "rho_act_median": round(act, 2),
        "ess_per_sec": round(C * steady / max(act, 1.0), 1),
    }
    # mixing-adjusted scaling straight off the device sketch, so the
    # table carries ESS/s from the same instrument the headline uses
    if obs_sum is not None:
        act_dev = float(obs_sum["act_rho_med"])
        out["rho_act_device"] = round(act_dev, 2)
        out["ess_per_sec_device"] = round(C * steady / max(act_dev, 1.0), 1)
    return out


def run_scaling(out_path, counts=(1, 2, 4, 8)):
    """Per-axis scaling table + 2-d collectives evidence -> MULTICHIP
    artifact.

    Each point re-executes this file with ``--scaling-probe axis:N`` in a
    fresh subprocess that pins ``JAX_PLATFORMS=cpu`` and forces an
    8-virtual-device host platform *before* importing jax (the proven
    tests/conftest.py / __graft_entry__ isolation recipe — this
    environment's sitecustomize registers a TPU plugin in every child, so
    the probe also re-pins via jax.config).  The 1-device point is shared
    between the two axis rows ((1,1) is the same program).  The artifact
    also records the 2-d dry-run's collectives census and chain-axis
    isolation verdict from ``__graft_entry__ --dryrun-inner CxP``."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))

    def _env():
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        for k in [k for k in env if k.startswith(("PALLAS_AXON", "AXON"))]:
            env.pop(k)
        return env

    def _probe(axis, ndev):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--scaling-probe", f"{axis}:{ndev}"]
        res = subprocess.run(cmd, env=_env(), capture_output=True,
                             text=True, timeout=1800, cwd=here)
        if res.returncode != 0:
            return {"axis": axis, "n_devices": ndev,
                    "error": (res.stderr or res.stdout)[-1500:]}
        line = next(l for l in res.stdout.splitlines()
                    if l.startswith("{"))
        return json.loads(line)

    table = {"chain": [], "pulsar": []}
    for ndev in counts:
        print(f"# scaling: chain axis x{ndev}", file=sys.stderr)
        point = _probe("chain", ndev)
        table["chain"].append(point)
        if ndev == 1:
            # (1,1) == the same single-device program; share the point
            table["pulsar"].append({**point, "axis": "pulsar"})
    for ndev in counts[1:]:
        print(f"# scaling: pulsar axis x{ndev}", file=sys.stderr)
        table["pulsar"].append(_probe("pulsar", ndev))

    # the 2-d dry-run's own evidence: census + zero-chain-axis verdict
    print("# scaling: 2x4 dry-run (collectives evidence)", file=sys.stderr)
    dry = subprocess.run(
        [sys.executable, os.path.join(here, "__graft_entry__.py"),
         "--dryrun-inner", "2x4"],
        env=_env(), capture_output=True, text=True, timeout=1800, cwd=here)
    collectives = [l for l in dry.stdout.splitlines()
                   if l.startswith(("collectives:", "chain-axis:"))]
    out = {
        "n_devices": 8,
        "mesh_axes": {"n_chain_devs": 2, "n_pulsar_devs": 4},
        "rc": dry.returncode,
        "ok": (dry.returncode == 0
               and all("error" not in p
                       for row in table.values() for p in row)),
        "skipped": False,
        "collectives_evidence": collectives,
        "scaling": table,
        "note": ("per-axis scaling of the CRN sweep on CPU virtual "
                 "devices (8 synthetic pulsars, C=8 chains, niter=96): "
                 "samples/s and ESS/s at 1/2/4/8 devices along each mesh "
                 "axis.  All virtual devices SHARE one host CPU, so rates "
                 "cannot increase with device count here; the signal is "
                 "the RELATIVE partitioning overhead — the chain axis "
                 "stays near-flat (no collectives, per-device dispatch "
                 "only) while the pulsar axis pays the common-rho "
                 "collectives and basis reslicing every sweep.  Absolute "
                 "multi-chip throughput needs real devices "
                 "(BENCH_r*.json carries the single-chip headline). "
                 "chain-axis isolation is verified statically by the "
                 "dry-run's replica-group decode and pinned by "
                 "contracts/crn_2d_mesh.json"),
    }
    with open(out_path, "w") as fh:
        json.dump(out, fh, indent=1)
        fh.write("\n")
    print(json.dumps(out))
    return out


def _ledger_append(headline, args, kind="bench"):
    """Append this run's condensed record to the perf ledger (obs.perf)
    unless --no-ledger.  Best-effort: a ledger-write failure must never
    turn a finished bench into a nonzero exit."""
    if getattr(args, "no_ledger", False):
        return
    try:
        from pulsar_timing_gibbsspec_tpu.obs import perf as operf

        path = args.ledger or operf.ledger_path()
        rec = operf.make_ledger_record(headline, source="bench.py",
                                       kind=kind)
        operf.ledger_append(rec, path)
        print(f"# ledger: appended {rec.get('metric') or kind} to {path}",
              file=sys.stderr)
    except Exception as e:                            # noqa: BLE001
        print(f"# ledger: append failed ({type(e).__name__}: {e})",
              file=sys.stderr)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="8 pulsars, fewer iterations (smoke test)")
    ap.add_argument("--serve", action="store_true",
                    help="serving-mode benchmark: multiplexed aggregate "
                    "samples/s + warm-start latency of the resident "
                    "service on synthetic data (no reference data "
                    "needed); prints its own JSON line and exits")
    ap.add_argument("--niter", type=int, default=None)
    ap.add_argument("--numpy-iters", type=int, default=None)
    ap.add_argument("--nchains", type=int, default=None)
    ap.add_argument("--orf", choices=["both", "crn", "hd"], default="both",
                    help="which sweep configs to benchmark")
    ap.add_argument("--no-profile", action="store_true",
                    help="skip the per-block profile (saves a few compiles)")
    ap.add_argument("--profile", action="store_true",
                    help="deprecated (profile is on by default); kept so "
                    "older invocations still parse")
    ap.add_argument("--record", choices=["f32", "bf16"], default="f32",
                    help="dtype of the recorded chain shipped device->host "
                    "(driver default f32; bf16 is the opt-in transfer diet "
                    "for bandwidth-starved links — the JSON labels the "
                    "mode so numbers are never silently mixed)")
    ap.add_argument("--record-every", type=int, default=1,
                    help="on-device record thinning stride for the headline "
                    "run (default 1 = reference parity: every sweep "
                    "recorded).  The k=4 CRN rate is always measured as "
                    "the thinned_k4 sub-object when this is 1")
    ap.add_argument("--ensemble", dest="ensemble", action="store_true",
                    default=True,
                    help="ensemble mixing engine for the CRN leg: ASIS "
                    "rho interweaving + interchain stretch moves on the "
                    "common-spectrum block (sampler/ensemble.py).  ON "
                    "by default — the headline ess_per_sec is an "
                    "ensemble-on number; --no-ensemble reverts to the "
                    "plain per-chain sweep (bitwise r5 behavior)")
    ap.add_argument("--no-ensemble", dest="ensemble", action="store_false",
                    help="disable the ensemble mixing engine")
    ap.add_argument("--pt-ladder", type=int, default=1,
                    help="parallel-tempering ladder depth for the CRN "
                    "leg (requires --ensemble; default 1 = no "
                    "tempering).  nchains must be a multiple; only the "
                    "beta=1 chains count toward ess_per_sec")
    ap.add_argument("--mesh", type=_parse_mesh, default=None,
                    help="device mesh for the headline run: 'CxP' places "
                    "chains over C devices and pulsars over P (e.g. 2x4), "
                    "a bare integer is the legacy 1-d pulsar mesh.  The "
                    "headline JSON records the shape as mesh_axes")
    ap.add_argument("--scaling", action="store_true",
                    help="per-axis scaling table instead of the headline "
                    "bench: samples/s + ESS/s at 1/2/4/8 devices along "
                    "the chain and pulsar axes (CPU virtual devices, "
                    "synthetic data), written to --scaling-out and "
                    "printed as one JSON line")
    ap.add_argument("--scaling-out", default=os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "MULTICHIP_r06.json"),
                    help="artifact path for --scaling")
    ap.add_argument("--scaling-probe", default=None, metavar="AXIS:N",
                    help=argparse.SUPPRESS)  # internal: one scaling point
    ap.add_argument("--no-ledger", action="store_true",
                    help="skip the PERF_LEDGER.jsonl append (the ledger "
                    "is append-only history gated by tools/perfwatch.py; "
                    "use this for throwaway experiments that should not "
                    "become a baseline)")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="append the run's ledger record to PATH instead "
                    "of the repo PERF_LEDGER.jsonl")
    args = ap.parse_args(argv)

    if args.scaling_probe:
        # inner probe: pin the platform before any backend initializes
        # (the parent already set JAX_PLATFORMS/XLA_FLAGS; sitecustomize
        # may have imported jax, so re-pin via the config API too)
        import jax

        jax.config.update("jax_platforms", "cpu")
        axis, ndev = args.scaling_probe.split(":")
        print(json.dumps(scaling_probe(axis, int(ndev))))
        return
    if args.scaling:
        run_scaling(args.scaling_out)
        return

    import jax

    if args.serve:
        serving = bench_serve(quick=args.quick, niter=args.niter)
        from pulsar_timing_gibbsspec_tpu.runtime import telemetry
        out = {
            "metric": "serve_aggregate_samples_per_sec",
            "value": serving["aggregate_samples_per_s"],
            "unit": "samples/s",
            "device_kind": jax.devices()[0].device_kind,
            "backend": jax.default_backend(),
            "serving": serving,
            "resilience": {"counters": telemetry.snapshot(),
                           "gauges": telemetry.gauges(),
                           "serving": serving},
        }
        print(json.dumps(out))
        _ledger_append(out, args, kind="serve")
        print(f"# serve: {serving['aggregate_samples_per_s']:.2f} "
              f"multiplexed samples/s ({serving['slots']} slots), "
              f"warm start {serving['warm_start_latency_ms']:.0f} ms, "
              f"unplanned retraces {serving['unplanned_retraces']}",
              file=sys.stderr)
        return
    n_psr = 8 if args.quick else 45
    niter = args.niter or (300 if args.quick else 1000)
    np_iters = args.numpy_iters or (20 if args.quick else 100)
    adapt = 300 if args.quick else 1000
    # default C: the throughput-optimal point measured on one v5e chip.
    # The old C=32 knee was NOT compute: tools/chunk_probe.py traced the
    # steady loop and found ~half the wall time was the (chunk, C, P,
    # Bmax) f64 b-record's device-to-host transfer over the ~18 MB/s
    # tunnel (42.6 MB/chunk at C=32), which scales linearly with C and
    # saturated the link while the chip idled.  After the transfer diet
    # (f32 records for both x and b, pad columns dropped on device) and
    # the compute work (two-float refresh replacing the 148.7 ms f64
    # exact draw; blocked matmul factorization replacing XLA's native
    # batched cholesky in the per-sweep draw, tools/chol_probe.py),
    # C=64 measures 56 sweeps/s = 3592 samples/s when the tunnel
    # cooperates — at that point the f32 record transfer is again the
    # binding constraint (~52 MB/chunk; --record bf16 halves it)
    nchains = args.nchains or (4 if args.quick else 64)
    profile = not args.no_profile

    crn = hd = None
    if args.orf in ("both", "crn"):
        crn = bench_config("crn", n_psr, niter, np_iters, adapt, nchains,
                           profile, record=args.record,
                           record_every=args.record_every,
                           mesh_shape=args.mesh, ensemble=args.ensemble,
                           pt_ladder=args.pt_ladder)
        if not args.quick and args.record_every == 1:
            # the record-transfer-bound demonstration (r4 weak #3): the
            # same config with the every-sweep record thinned on device to
            # every 4th (k ~ 2x the measured b-ACT median of ~2 sweeps,
            # docs/EXACT_EVERY.md) — the steady rate should move toward
            # the device-compute bound while ess_per_sec stays honest
            # (rows/s / ACT-on-rows)
            crn["thinned_k4"] = _retry_transport(
                lambda: thinned_probe("crn", n_psr, niter, adapt, nchains,
                                      args.record, k=4,
                                      ensemble=args.ensemble))
    if args.orf == "hd":
        # the sequential cross-pulsar conditional sweep is heavier per
        # sweep; fewer iterations and chains keep the wall-clock (and the
        # compiled program) in check.  r5 moved the r4 chain-width knee:
        # the f64 blocked factorization (81 of the 132 ms C=32 b-draw)
        # became the two-float MXU factor and the 45-step scan's
        # (C, B, B) matvecs were hoisted into pre-scan batched matmuls —
        # b-draw now 35 ms at C=32, 100 ms at C=64 (tools/sweep_probe.py
        # --orf hd, tools/hd_draw_probe.py; docs/HD_MIXING.md r5
        # section).  C=32 still maximizes samples/s (727 vs 564 at C=64:
        # per-chain cost still grows ~1.4x, two-float VMEM working
        # sets), so the default stays 32 — ~2.9x faster per sweep than
        # r4.  The CRN path, whose knee was the tunnel writeback, keeps
        # scaling to 64.
        # HD per-block profile rides this leg (the structured joint
        # b-draw is the block the ISSUE-3 acceptance reads here)
        hd = bench_config("hd", n_psr, max(100, niter // 4),
                          max(5, np_iters // 4), adapt,
                          nchains if args.nchains else min(nchains, 32),
                          profile=profile, record=args.record,
                          record_every=args.record_every,
                          mesh_shape=args.mesh)
    elif args.orf == "both":
        # own interpreter: the big correlated-ORF program has crashed the
        # tunneled TPU worker before, and a worker crash kills the whole
        # client — the headline CRN number must survive it
        import subprocess

        # honor an explicit --nchains verbatim; only the default is
        # capped for the heavier HD program (C=32 knee, see above)
        cmd = [sys.executable, os.path.abspath(__file__), "--orf", "hd",
               "--niter", str(niter), "--numpy-iters", str(np_iters),
               "--nchains", str(nchains if args.nchains
                                else min(nchains, 32)),
               "--record", args.record,
               "--record-every", str(args.record_every)]
        if args.mesh is not None:
            m = args.mesh
            cmd += ["--mesh", f"{m[0]}x{m[1]}" if isinstance(m, tuple)
                    else str(m)]
        if not profile:
            cmd.append("--no-profile")
        if args.quick:
            cmd.append("--quick")
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=3600)
            sys.stderr.write(res.stderr)
            line = next(l for l in res.stdout.splitlines()
                        if l.startswith("{"))
            hd = json.loads(line)["hd"]
        except Exception as exc:                      # noqa: BLE001
            hd = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    head = crn or hd
    # the headline is total posterior samples/sec of one chip (C vmapped
    # KS-validated chains) vs the single-chain single-CPU oracle — the
    # north-star framing; sweeps_per_sec/nchains expose the per-chain rate
    # so the two factors are always separable
    out = {
        "metric": f"gibbs_samples_per_sec_{n_psr}psr_pta",
        "value": round(head["nchains"] * head["sweeps_per_sec"], 2),
        "unit": "samples/s",
        "vs_baseline": head["vs_oracle"],
        "device_kind": jax.devices()[0].device_kind,
        # the backend name disambiguates ledger groups (perfwatch bands
        # compare within (metric, device_kind, backend) only): a CPU
        # smoke run must never gate against a TPU baseline
        "backend": jax.default_backend(),
        "record_precision": args.record,
        **{k: head[k] for k in ("sweeps_per_sec", "rate_windows", "nchains",
                                "mesh_axes",
                                "numpy_sweeps_per_sec",
                                "numpy_rate_windows", "mfu", "raw",
                                "numpy_raw", "record_every",
                                # mixing-adjusted companions (r5): this
                                # run's own rho-ACT/ESS rate and the
                                # oracle's, so vs_baseline always has an
                                # ESS-based reading next to it
                                "rho_act_median", "ess_per_sec",
                                "oracle_rho_act", "oracle_ess_per_sec",
                                "vs_oracle_ess",
                                # device-sketch companions (obs/): ACT/ESS
                                # off the summary slab, never the shipped
                                # chains, with the host-Sokal parity ratio
                                "rho_act_device", "ess_per_sec_device",
                                "act_parity_device_vs_host",
                                "rhat_max_device",
                                # the mixing-engine config (r6): which
                                # ensemble moves produced the headline
                                # ACT, their acceptance, and the ladder
                                "ensemble") if k in head},
    }
    if head.get("thinned_k4") is not None:
        out["thinned_k4"] = head["thinned_k4"]
    if crn is not None and "per_block_ms" in crn:
        for k in ("per_block_ms", "per_block_in_sweep", "sum_blocks_ms",
                  "full_sweep_ms", "dispatch_ms", "dispatch_breakdown_ms",
                  "dispatch_amortized_ms_per_sweep",
                  "roofline"):
            if k in crn:
                out[k] = crn[k]
    if hd is not None:
        out["hd"] = hd
    print(json.dumps(out))
    # ess_per_sec is a headline gating quantity (r6: the ensemble
    # mixing engine's acceptance bar is >= 2x the r5 ~90 ESS/s CRN
    # baseline), so the human-readable gate line carries it too
    ess = head.get("ess_per_sec")
    print(f"# jax: {head['sweeps_per_sec']:.2f} sweeps/s x "
          f"{head['nchains']} chains (windows {head['rate_windows']}); "
          + (f"ess_per_sec {ess:.1f} "
             f"(rho-ACT {head.get('rho_act_median')}); "
             if ess is not None else "")
          + f"numpy oracle: {head['numpy_sweeps_per_sec']:.2f} it/s "
          f"(single CPU, f64); target >= 20x", file=sys.stderr)
    _ledger_append(out, args)


if __name__ == "__main__":
    main()
