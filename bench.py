"""Benchmark: Gibbs posterior samples/sec on the full 45-pulsar simulated PTA.

Prints ONE JSON line ``{"metric", "value", "unit", "vs_baseline"}``.

The metric is steady-state (post-adaptation, post-compile) Gibbs posterior
samples per second — sweeps/sec times the number of vmapped chains — of the
JAX device backend on the 45-pulsar ``simulated_data`` array with varying
white noise, per-pulsar free-spectrum red noise and a common free-spectrum
GW process (the BASELINE.json north-star config).  Every chain is an
independent KS-validated Gibbs process (tests/test_jax_backend.py::
test_nchains_ks_and_shapes), so chains multiply posterior samples/sec the
same way the reference would by running N copies — except the TPU runs them
in one compiled program on one chip.  ``vs_baseline`` is the speedup over
the in-repo float64 NumPy oracle (reference semantics, single CPU, one
chain) measured on the same model in the same process; the north-star
target is >= 20x.

Usage: python bench.py [--quick] [--niter N] [--numpy-iters N]
                       [--nchains C] [--profile]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REFDATA = os.environ.get("PTGIBBS_REFDATA", "/root/reference/simulated_data")


def build_pta(n_psr=45, nbins=10):
    from pulsar_timing_gibbsspec_tpu.data import load_directory
    from pulsar_timing_gibbsspec_tpu.models.factory import model_general

    psrs = load_directory(
        REFDATA, inject=dict(log10_A=np.log10(2e-15), gamma=13.0 / 3.0))
    psrs = psrs[:n_psr]
    return model_general(
        psrs, tm_svd=True, white_vary=True,
        common_psd="spectrum", common_components=nbins,
        red_var=True, red_psd="spectrum", red_components=nbins)


def bench_jax(pta, x0, niter, adapt_iters, nchains, profile=False):
    from pulsar_timing_gibbsspec_tpu.sampler.jax_backend import JaxGibbsDriver

    drv = JaxGibbsDriver(pta, seed=1, common_rho=True,
                         white_adapt_iters=adapt_iters, chunk_size=100,
                         nchains=nchains)
    C = drv.C
    cshape, bshape = drv.chain_shapes(niter)
    chain = np.zeros(cshape)
    bchain = np.zeros(bshape)
    it = drv.run(x0, chain, bchain, 0, niter)
    next(it)                   # warmup + adaptation + compilation
    t0 = time.time()
    warm = next(it)            # first chunk: includes sweep-kernel compile
    t1 = time.time()
    done = warm
    for done in it:
        pass
    t2 = time.time()
    # the writeback of each chunk's chain rows is an honest device sync
    steady = (niter - warm) / (t2 - t1) if niter > warm else (
        (warm - 1) / (t1 - t0))
    assert np.all(np.isfinite(chain)), "non-finite chain values"
    if profile:
        from pulsar_timing_gibbsspec_tpu import profiling

        times = profiling.profile_blocks(drv, drv.x_cur)
        fl = profiling.sweep_flops(drv.cm, nchains=C)
        print(profiling.format_report(times, fl, steady), file=sys.stderr)
    return steady, C


def bench_numpy(pta, x0, niter, adapt_iters):
    from pulsar_timing_gibbsspec_tpu.sampler.numpy_pta import NumpyPTAGibbs

    g = NumpyPTAGibbs(pta, seed=2, white_adapt_iters=adapt_iters)
    x = g.sweep(x0, first=True)      # adaptation, untimed
    t0 = time.time()
    for _ in range(niter):
        x = g.sweep(x)
    return niter / (time.time() - t0)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="8 pulsars, fewer iterations (smoke test)")
    ap.add_argument("--niter", type=int, default=None)
    ap.add_argument("--numpy-iters", type=int, default=None)
    ap.add_argument("--nchains", type=int, default=None)
    ap.add_argument("--profile", action="store_true",
                    help="print a per-block sweep profile (extra compiles)")
    args = ap.parse_args(argv)

    n_psr = 8 if args.quick else 45
    niter = args.niter or (300 if args.quick else 1000)
    np_iters = args.numpy_iters or (20 if args.quick else 100)
    adapt = 300 if args.quick else 1000
    # default C: the throughput-optimal point measured on one v5e chip
    # (C-sweep with the Metropolised b-draw: 8 -> 344, 16 -> 466,
    # 32 -> 579, 48 -> 525 samples/s; the knee is ~32)
    nchains = args.nchains or (4 if args.quick else 32)

    pta = build_pta(n_psr=n_psr)
    x0 = pta.initial_sample(np.random.default_rng(0))

    # the tunneled TPU's remote-compile endpoint drops transiently
    # ("read body: response body closed..."); retry with a fresh driver
    # rather than failing the whole benchmark on a transport hiccup
    last = None
    for attempt in range(3):
        try:
            jax_rate, C = bench_jax(pta, x0, niter, adapt, nchains,
                                    profile=args.profile)
            break
        except Exception as exc:
            if "remote_compile" not in str(exc):
                raise
            last = exc
            print(f"# remote-compile transport dropped "
                  f"(attempt {attempt + 1}/3); retrying", file=sys.stderr)
            time.sleep(20)
    else:
        raise last
    np_rate = bench_numpy(pta, np.asarray(x0, np.float64), np_iters, adapt)

    # the headline is total posterior samples/sec of one chip (C vmapped
    # KS-validated chains) vs the single-chain single-CPU oracle — the
    # north-star framing; sweeps_per_sec/nchains expose the per-chain rate
    # so the two factors are always separable
    print(json.dumps({
        "metric": f"gibbs_samples_per_sec_{n_psr}psr_pta",
        "value": round(float(C * jax_rate), 2),
        "unit": "samples/s",
        "vs_baseline": round(float(C * jax_rate / np_rate), 2),
        "sweeps_per_sec": round(float(jax_rate), 2),
        "nchains": C,
        "numpy_sweeps_per_sec": round(float(np_rate), 2),
    }))
    print(f"# jax: {jax_rate:.2f} sweeps/s x {C} chains; "
          f"numpy oracle: {np_rate:.2f} it/s (single CPU, f64); "
          f"target >= 20x", file=sys.stderr)


if __name__ == "__main__":
    main()
