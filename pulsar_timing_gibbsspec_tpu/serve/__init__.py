"""Resident multi-tenant sampler service.

Turns the one-shot batch sampler into an always-on posterior engine
(ROADMAP open item 3): a small table of padded compiled shapes
(:mod:`.buckets`), a program cache that lands heterogeneous datasets on
one compiled sweep without retracing (:mod:`.engine`), per-request
state + checkpointing (:mod:`.jobs`), and a fair-share scheduler that
multiplexes independent analyses as extra batch rows of one compiled
program (:mod:`.service`).  Contracts and the gauge glossary live in
``docs/SERVING.md``; the static zero-retrace contract is
``contracts/serve_buckets.json``.
"""

from .buckets import BucketOverflow, BucketSpec, BucketTable, probe_shape
from .engine import ProgramCache, SignatureMismatch, model_signature
from .jobs import JOB_STATES, Job
from .service import SamplerService

__all__ = [
    "BucketOverflow", "BucketSpec", "BucketTable", "probe_shape",
    "ProgramCache", "SignatureMismatch", "model_signature",
    "JOB_STATES", "Job", "SamplerService",
]
