"""Resident multi-tenant sampler service.

Turns the one-shot batch sampler into an always-on posterior engine
(ROADMAP open item 3): a small table of padded compiled shapes
(:mod:`.buckets`), a program cache that lands heterogeneous datasets on
one compiled sweep without retracing (:mod:`.engine`), per-request
state + checkpointing (:mod:`.jobs`), and a fair-share scheduler that
multiplexes independent analyses as extra batch rows of one compiled
program (:mod:`.service`).  The network boundary sits in front of all
of it: the fault-tolerant transport frontend (:mod:`.gateway` behind
the :mod:`.wire` format/transports) adds idempotent submission,
deadline propagation, resumable cursor streams and graceful drain
without weakening any in-process contract.  Contracts and the gauge
glossary live in ``docs/SERVING.md``; the static zero-retrace contract
is ``contracts/serve_buckets.json``.

:mod:`.gateway`/:mod:`.wire` are imported lazily (via module
``__getattr__``) so the in-process service keeps its import cost and
the analysis tooling can audit the transport modules without loading
jax.
"""

from .buckets import BucketOverflow, BucketSpec, BucketTable, probe_shape
from .engine import ProgramCache, SignatureMismatch, model_signature
from .jobs import JOB_STATES, Job
from .service import SamplerService

_LAZY = {
    "Gateway": ("gateway", "Gateway"),
    "StreamSub": ("gateway", "StreamSub"),
    "HttpTransport": ("wire", "HttpTransport"),
    "WireError": ("wire", "WireError"),
    "WireRequest": ("wire", "WireRequest"),
    "WireResponse": ("wire", "WireResponse"),
}


def __getattr__(name):
    got = _LAZY.get(name)
    if got is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module("." + got[0], __name__), got[1])


__all__ = [
    "BucketOverflow", "BucketSpec", "BucketTable", "probe_shape",
    "ProgramCache", "SignatureMismatch", "model_signature",
    "JOB_STATES", "Job", "SamplerService",
    "Gateway", "StreamSub", "HttpTransport",
    "WireError", "WireRequest", "WireResponse",
]
