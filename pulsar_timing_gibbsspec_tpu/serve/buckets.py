"""Bucketed padding: snap any dataset to a small table of compiled shapes.

A compiled sweep is a function of the padded array geometry
``(P_pad, TOA_pad, B_pad, K)`` — pulsar axis, TOA axis, basis axis,
common-process frequency count.  Compiling per dataset means a cold
XLA compile per request; compiling per *bucket* means a handful of
programs total, each warmed once, with every request snapped up to the
smallest covering bucket.  The padding is exact, not approximate: pad
TOA rows carry ``y=0, T=0, sigma2=1`` with constant ``efac=1`` /
``equad=-40`` (unit Nvec, zero masked log-likelihood), pad basis
columns carry ``phi_base=1`` with ``basis_mask=0``, and pad pulsars are
fully inert (``sampler/compiled.py`` conventions) — so a dataset run in
a larger bucket samples the identical posterior.

The first three axes pad; ``K`` does not.  The frequency count is
structural (it sets the rho-block parameter count and the Fourier
basis), so a bucket only covers datasets with exactly its ``modes``.

Routing never over-pads silently and never reaches
``compile_pta``'s shape errors: a dataset beyond the largest covering
shape raises a typed :class:`BucketOverflow` carrying the nearest
bucket so the caller can renegotiate (split the dataset, or provision
a bigger table) instead of crashing mid-compile.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """One compiled-program shape: pad targets per axis + exact mode
    count.  Hashable (dict key of the program cache)."""

    pulsars: int    # padded pulsar-axis length (compile_pta pad_pulsars)
    toas: int       # padded TOA axis (compile_pta pad_toas -> Nmax)
    basis: int      # padded basis axis (compile_pta pad_basis -> Bmax)
    modes: int      # common-process frequency count K (exact match)

    def covers(self, shape: "DatasetShape") -> bool:
        return (self.pulsars >= shape.pulsars and self.toas >= shape.toas
                and self.basis >= shape.basis
                and self.modes == shape.modes)

    def cost(self) -> int:
        """Padded element count of the dominant (P, Nmax, Bmax) basis
        tensor — the 'smallest covering bucket' ordering."""
        return self.pulsars * self.toas * self.basis

    def as_tuple(self):
        return (self.pulsars, self.toas, self.basis, self.modes)


@dataclasses.dataclass(frozen=True)
class DatasetShape:
    """The routed quantities of one dataset (see :func:`probe_shape`)."""

    pulsars: int    # real pulsar count
    toas: int       # largest per-pulsar TOA count
    basis: int      # widest per-pulsar basis
    modes: int      # common free-spectrum frequency count


class BucketOverflow(ValueError):
    """No bucket covers the dataset.

    Carries the offending ``shape`` (:class:`DatasetShape`) and the
    ``nearest`` bucket — the largest-capacity bucket with the right
    mode count (or the largest overall when no bucket matches the mode
    count) — so callers can report exactly which axis overflowed and by
    how much instead of dying inside ``pad_pulsars``/``compile_pta``.
    """

    def __init__(self, shape: DatasetShape, nearest: BucketSpec | None):
        self.shape = shape
        self.nearest = nearest
        self.hint = next_covering(shape, base=nearest)
        near = (f"nearest bucket {nearest.as_tuple()}"
                if nearest is not None else "empty table")
        super().__init__(
            f"dataset shape (P={shape.pulsars}, TOA={shape.toas}, "
            f"B={shape.basis}, K={shape.modes}) exceeds every bucket; "
            f"{near}; migration hint: provision a covering bucket like "
            f"{self.hint.as_tuple()}")


def next_covering(shape: DatasetShape, base: BucketSpec | None = None
                  ) -> BucketSpec:
    """The planner's proposal for a bucket covering ``shape``: start
    from ``base`` (the nearest existing bucket, when any) and double
    each overflowing padded axis until it covers — the same doubling
    discipline as :meth:`BucketTable.ladder`, so provisioned buckets
    stay on the ladder instead of proliferating one-off shapes.  The
    mode count is structural and copied exactly."""
    p = int(base.pulsars) if base is not None else 1
    t = int(base.toas) if base is not None else 1
    b = int(base.basis) if base is not None else 1
    while p < shape.pulsars:
        p *= 2
    while t < shape.toas:
        t *= 2
    while b < shape.basis:
        b *= 2
    return BucketSpec(p, t, b, int(shape.modes))


@dataclasses.dataclass(frozen=True)
class MigrationPlan:
    """The migration planner's answer for a grown dataset (see
    :func:`plan_migration`).

    ``kind`` is ``"in_place"`` when the parent's bucket still covers
    the grown shape — the compiled program, padded widths, and hence
    the retained-row prefix are unchanged (bitwise contract) — or
    ``"rebucket"`` when the grown shape needs the next covering bucket
    and the checkpoint's padded-basis axes must be re-embedded
    (zero-padded) into the child bucket's geometry."""

    kind: str                   # "in_place" | "rebucket"
    parent_bucket: BucketSpec
    child_bucket: BucketSpec
    shape: DatasetShape

    @property
    def in_place(self) -> bool:
        return self.kind == "in_place"


def plan_migration(table: "BucketTable", parent_bucket: BucketSpec,
                   shape: DatasetShape) -> MigrationPlan:
    """Plan the bucket migration for a dataset grown to ``shape``
    while standing in ``parent_bucket``.

    In-place when the parent bucket still covers the grown shape
    (appends that stay under the padded TOA/basis headroom); otherwise
    routes the grown shape through ``table`` for the next covering
    bucket — raising the table's typed :class:`BucketOverflow` (hint
    attached) when nothing covers.  A mode-count change is structural
    (different parameter space), not a migration: typed refusal."""
    if shape.modes != parent_bucket.modes:
        raise ValueError(
            f"append cannot change the common-process mode count "
            f"(parent bucket K={parent_bucket.modes}, grown dataset "
            f"K={shape.modes}) — a mode change is a new model, not a "
            "migration; submit a fresh job")
    if shape.pulsars > parent_bucket.pulsars:
        # more REAL pulsars means more parameters: the chain prefix
        # would not even be the same vector.  Growing the pulsar set is
        # a new model; only the TOA/basis axes of existing pulsars may
        # grow under a migration.
        raise ValueError(
            f"append cannot add pulsars ({shape.pulsars} > parent "
            f"bucket's {parent_bucket.pulsars}) — the parameter space "
            "changes; submit a fresh job for the extended array")
    if parent_bucket.covers(shape):
        return MigrationPlan("in_place", parent_bucket, parent_bucket,
                             shape)
    child = table.route(shape)      # BucketOverflow propagates, typed
    return MigrationPlan("rebucket", parent_bucket, child, shape)


def probe_shape(pta) -> DatasetShape:
    """Measure the routed quantities of a host PTA model: real pulsar
    count, largest TOA count, widest basis, and the common
    free-spectrum frequency count (the rho-block size)."""
    from ..sampler.blocks import BlockIndex

    models = [pta.model(ii) for ii in range(len(pta.pulsars))]
    idx = BlockIndex.build(list(pta.param_names))
    return DatasetShape(
        pulsars=len(models),
        toas=max(m.pulsar.ntoa for m in models),
        basis=max(m.get_basis().shape[1] for m in models),
        modes=int(len(idx.rho)))


class BucketTable:
    """An ordered set of :class:`BucketSpec` shapes with smallest-cover
    routing."""

    def __init__(self, buckets):
        buckets = list(buckets)
        if not buckets:
            raise ValueError("BucketTable needs at least one bucket")
        self.buckets = sorted(buckets, key=BucketSpec.cost)

    @classmethod
    def ladder(cls, modes, pulsars=(8, 46), toas=(128, 1024),
               basis=None) -> "BucketTable":
        """A simple doubling ladder: the cross product of the given
        pulsar and TOA pads (basis defaults to a generous
        ``tm + 2*modes*2`` per TOA tier)."""
        if basis is None:
            basis = tuple(20 + 4 * int(modes) for _ in toas)
        out = []
        for p in pulsars:
            for t, b in zip(toas, basis):
                out.append(BucketSpec(int(p), int(t), int(b), int(modes)))
        return cls(out)

    def route(self, shape: DatasetShape) -> BucketSpec:
        """Smallest covering bucket, or raise :class:`BucketOverflow`
        (typed, with the nearest bucket attached)."""
        for b in self.buckets:          # sorted by cost: first hit wins
            if b.covers(shape):
                return b
        same_k = [b for b in self.buckets if b.modes == shape.modes]
        nearest = max(same_k or self.buckets, key=BucketSpec.cost)
        raise BucketOverflow(shape, nearest)

    def route_pta(self, pta) -> BucketSpec:
        return self.route(probe_shape(pta))
