"""Placement engine: concurrent resident groups on fault-domain slices.

The service historically held exactly ONE ``(bucket, signature)``
group resident at a time: a job routing to a different bucket waited
for a full group drain, so one hot tenant class head-of-line-blocked
every other model shape — and a single group was a single blast radius
spanning the whole mesh.  The blocked-Gibbs structure that makes
chains embarrassingly parallel (the 2-d ``(chain, pulsar)`` mesh
carries ZERO chain-axis collectives — measured, ``crn_2d_mesh``) makes
disjoint chain-submesh slices natural *fault domains*: programs on
different slices share no devices and no collectives, so a device
loss, quarantine storm or compile stall on one slice cannot perturb
another slice's bitwise streams.

This module owns the *geometry and lifecycle*; the
:class:`~.service.SamplerService` owns the jobs and drives it:

- :class:`Slice` — one fault domain: a contiguous span of chain-axis
  device rows carved into a standalone submesh
  (``parallel.sharding.chain_slice``), a fixed tenant-axis width
  (``slots``), and the per-slice scheduling state (residents, active
  group, stacked carries, warmed-program set).  ``slots`` must divide
  over the slice's chain rows — the quotient is the *chains sub-axis*:
  ``slots // chains`` tenant rows ride each chain device, so slices
  with different chain counts can coexist on one mesh.
- :class:`PlacementPlan` — the audited lifecycle of a slice
  (``planned → warming → resident → draining → migrating → failed``),
  declared in ``contracts/racecheck.json`` and M1–M3-checked: every
  transition below is a literal guarded assignment, so a new edge
  cannot land without a diff to the contract.
- :class:`PlacementEngine` — carves slices from a parent mesh
  (explicit layout or one whole-mesh slice), validates the chains
  sub-axis divisibility with typed refusals (:class:`PlacementError`
  naming the slice, the required multiple and the nearest legal slot
  count), splits/merges slices for rebalancing, and enforces the
  capped per-slice re-place budget (``replace_max`` losses within
  ``replace_window`` seconds) with deterministic per-slice backoff.

Pre-warming policy (driven by the service): the ``compile_stalls`` /
``warm_hit_rate`` gauges plus queue composition pick a queued cold
bucket that cannot be placed this step and compile it inside a
*planned* window while resident slices keep dispatching — hard-capped
(one compile per step, ``prewarm`` outstanding buckets) and suspended
during an admission-controller compile storm, so pre-warming can never
starve a resident group's step.
"""

from __future__ import annotations

#: audited slice lifecycle (contracts/racecheck.json machine
#: "placement"); module-level tuple so racecheck M1 pins it
PLACEMENT_STATES = ("planned", "warming", "resident", "draining",
                    "migrating", "failed")


class PlacementError(ValueError):
    """Typed placement refusal (a ``user``-class failure for the
    supervisor taxonomy: re-raised, never retried).  Carries the
    offending ``slice_id``, the ``required_multiple`` the slot count
    must satisfy (the slice's chain-device count) and the ``nearest``
    legal slot count when the refusal is a divisibility error."""

    def __init__(self, msg, *, slice_id=None, required_multiple=None,
                 nearest=None):
        super().__init__(msg)
        self.slice_id = slice_id
        self.required_multiple = required_multiple
        self.nearest = nearest


class PlacementPlan:
    """Audited slice lifecycle.  Transitions are guarded assignments
    (the racecheck M3 pattern): calling a method outside its legal
    source states is a silent no-op, so replayed/raced calls cannot
    fabricate an undeclared edge."""

    def __init__(self, slice_id):
        self.slice_id = int(slice_id)
        self.state = "planned"

    def warming(self):
        """A group starts admitting onto the slice (fresh placement)
        or re-placing after a device loss."""
        if self.state == "planned":
            self.state = "warming"
            return
        if self.state == "migrating":
            self.state = "warming"

    def resident(self):
        """First multiplexed chunk wrote back: the group is live."""
        if self.state == "warming":
            self.state = "resident"

    def draining(self):
        """The slice's group is leaving (drain/done/evict to empty)."""
        if self.state == "resident":
            self.state = "draining"

    def drained(self):
        """Empty again: the slice returns to the allocatable pool."""
        if self.state == "draining":
            self.state = "planned"

    def migrating(self):
        """Device loss / rebalance: the slice's jobs are being
        re-placed through their verified checkpoints."""
        if self.state == "resident":
            self.state = "migrating"
            return
        if self.state == "warming":
            self.state = "migrating"

    def fail(self):
        """Re-place budget exhausted: the slice parks terminally."""
        if self.state == "migrating":
            self.state = "failed"


class Slice:
    """One fault domain: geometry + the per-slice scheduling state the
    service mutates between chunks.  ``chains`` is the number of
    chain-axis device rows ([``chain_lo``, ``chain_lo + chains``) of
    the parent mesh); 0 means unplaced (no mesh — the chains sub-axis
    is trivially 1 and any slot count is legal)."""

    def __init__(self, slice_id, slots, chains=0, chain_lo=0, mesh=None):
        self.slice_id = int(slice_id)
        self.slots = int(slots)
        self.chains = int(chains)
        self.chain_lo = int(chain_lo)
        self.mesh = mesh                 # carved submesh (or None)
        self.plan = PlacementPlan(slice_id)
        # scheduling state (owned by SamplerService)
        self.residents = [None] * self.slots
        self.active = None               # (bucket, signature) group
        self.dirty = True
        self.stack = None
        self.X = self.B = self.K = None
        self.warmed = set()              # (chunk, group) combos compiled
        self.chunks = 0                  # dispatches on this slice
        # fault-domain bookkeeping
        self.losses = 0
        self.loss_times = []             # clock times within the window

    def live(self):
        return sum(1 for j in self.residents if j is not None)


def _validate_slice(sl, mesh_shape=None):
    """Chains sub-axis divisibility with the typed refusal the
    service's constructor surfaces (message keeps the historical
    "multiple of N" phrasing)."""
    from ..parallel.sharding import chain_submesh_size

    nc = chain_submesh_size(sl.mesh)
    if nc > 1 and sl.slots % nc:
        nearest = -(-sl.slots // nc) * nc
        where = (f"mesh {tuple(mesh_shape)}" if mesh_shape is not None
                 else "its submesh")
        raise PlacementError(
            f"slice {sl.slice_id}: slots={sl.slots} does not divide "
            f"over the slice's chain sub-axis ({nc} devices, {where}): "
            "the tenant axis is the chain axis on a 2-d serving mesh — "
            f"pass slots as a multiple of {nc} (e.g. slots={nearest}) "
            "or shrink the slice's chain span",
            slice_id=sl.slice_id, required_multiple=nc, nearest=nearest)


class PlacementEngine:
    """Carves, validates and rebalances the service's slices.

    ``layout=None`` keeps the historical single-group service: ONE
    slice spanning the whole mesh with all ``slots``.  An explicit
    layout (``[{"slots": 2, "chains": 2}, {"slots": 4, "chains": 2}]``)
    carves the chain axis into disjoint contiguous spans in order —
    groups with different chain counts coexist because each slice
    validates its own chains sub-axis.  On an unplaced service
    (``mesh=None``) the layout still creates independent slices (the
    chains sub-axis is trivially 1), so multi-group scheduling and the
    chaos drills run without devices."""

    def __init__(self, mesh, layout=None, slots=2, *, replace_max=1,
                 replace_window=30.0, clock=None):
        import time as _time

        from ..parallel.sharding import chain_slice, chain_submesh_size

        self.mesh = mesh
        self.replace_max = int(replace_max)
        self.replace_window = float(replace_window)
        self._clock = clock if clock is not None else _time.monotonic
        self._next_id = 0
        self.slices: list[Slice] = []
        nc = chain_submesh_size(mesh)
        shape = tuple(mesh.devices.shape) if mesh is not None else None
        if layout is None:
            sl = Slice(self._take_id(), int(slots),
                       chains=(nc if mesh is not None else 0),
                       chain_lo=0, mesh=mesh)
            _validate_slice(sl, shape)
            self.slices.append(sl)
            return
        specs = list(layout)
        if not specs:
            raise PlacementError("placement layout is empty")
        lo = 0
        for spec in specs:
            s = int(spec.get("slots", 2))
            c = int(spec.get("chains", 0) or 0)
            sub = None
            if mesh is not None and "chain" in mesh.axis_names and nc > 1:
                c = c or 1
                if lo + c > nc:
                    raise PlacementError(
                        f"slice {self._next_id}: chain span "
                        f"[{lo}, {lo + c}) exceeds the mesh's chain "
                        f"axis ({nc} rows, mesh {shape}) — shrink the "
                        "layout's chain counts or grow the mesh",
                        slice_id=self._next_id)
                sub = chain_slice(mesh, lo, lo + c)
            elif mesh is not None:
                c = 0
                sub = mesh      # 1-d mesh: no chain axis to carve
            else:
                c = 0
            sl = Slice(self._take_id(), s, chains=c, chain_lo=lo,
                       mesh=sub)
            _validate_slice(sl, shape)
            self.slices.append(sl)
            lo += c

    def _take_id(self):
        i, self._next_id = self._next_id, self._next_id + 1
        return i

    @property
    def total_slots(self):
        return sum(sl.slots for sl in self.slices)

    def slice_by_id(self, slice_id):
        for sl in self.slices:
            if sl.slice_id == int(slice_id):
                return sl
        return None

    # -- fault-domain budget -------------------------------------------------

    def note_loss(self, sl) -> int:
        """Record a device loss on ``sl``; returns the retry ordinal
        for the deterministic backoff, or raises the typed terminal
        :class:`PlacementError` when more than ``replace_max`` losses
        land within ``replace_window`` seconds."""
        now = self._clock()
        sl.losses += 1
        sl.loss_times = [t for t in sl.loss_times
                         if now - t < self.replace_window]
        sl.loss_times.append(now)
        if len(sl.loss_times) > self.replace_max:
            raise PlacementError(
                f"slice {sl.slice_id}: re-place budget exhausted "
                f"({len(sl.loss_times)} device losses within "
                f"{self.replace_window:g}s > replace_max="
                f"{self.replace_max}) — the slice parks failed and its "
                "jobs keep their verified checkpoints; resubmit after "
                "operator intervention", slice_id=sl.slice_id)
        return len(sl.loss_times)

    # -- rebalancing geometry ------------------------------------------------

    def split_slice(self, slice_id, *, slots=None, chains=None):
        """Split one (empty) slice into two; returns the new pair.
        Defaults halve both axes.  The service is responsible for
        draining the slice's residents through verified checkpoints
        BEFORE calling — geometry never mutates under a live group."""
        from ..parallel.sharding import chain_slice

        sl = self.slice_by_id(slice_id)
        if sl is None:
            raise PlacementError(f"split: unknown slice {slice_id}",
                                 slice_id=slice_id)
        if sl.live():
            raise PlacementError(
                f"split: slice {sl.slice_id} still holds "
                f"{sl.live()} resident job(s) — drain it first",
                slice_id=sl.slice_id)
        s1 = int(slots) if slots is not None else sl.slots // 2
        if not 0 < s1 < sl.slots:
            raise PlacementError(
                f"split: slice {sl.slice_id} slots={sl.slots} cannot "
                f"split at {s1}", slice_id=sl.slice_id)
        if sl.chains:
            c1 = int(chains) if chains is not None else sl.chains // 2
            if not 0 < c1 < sl.chains:
                raise PlacementError(
                    f"split: slice {sl.slice_id} chains={sl.chains} "
                    f"cannot split at {c1}", slice_id=sl.slice_id)
        else:
            c1 = 0
        idx = self.slices.index(sl)
        parts = []
        spans = [(sl.chain_lo, c1, s1),
                 (sl.chain_lo + c1, sl.chains - c1, sl.slots - s1)]
        shape = (tuple(self.mesh.devices.shape)
                 if self.mesh is not None else None)
        for lo, c, s in spans:
            sub = (chain_slice(self.mesh, lo, lo + c)
                   if c and self.mesh is not None else
                   (self.mesh if sl.mesh is self.mesh else None))
            part = Slice(self._take_id(), s, chains=c, chain_lo=lo,
                         mesh=sub)
            _validate_slice(part, shape)
            parts.append(part)
        self.slices[idx:idx + 1] = parts
        return tuple(parts)

    def merge_slices(self, a_id, b_id):
        """Merge two adjacent (empty) slices into one; returns it."""
        from ..parallel.sharding import chain_slice

        a, b = self.slice_by_id(a_id), self.slice_by_id(b_id)
        if a is None or b is None:
            raise PlacementError(
                f"merge: unknown slice in ({a_id}, {b_id})")
        ia, ib = self.slices.index(a), self.slices.index(b)
        if abs(ia - ib) != 1:
            raise PlacementError(
                f"merge: slices {a_id} and {b_id} are not adjacent",
                slice_id=a_id)
        for sl in (a, b):
            if sl.live():
                raise PlacementError(
                    f"merge: slice {sl.slice_id} still holds "
                    f"{sl.live()} resident job(s) — drain it first",
                    slice_id=sl.slice_id)
        lo = min(a.chain_lo, b.chain_lo)
        chains = a.chains + b.chains
        sub = (chain_slice(self.mesh, lo, lo + chains)
               if chains and self.mesh is not None else
               (self.mesh if a.mesh is self.mesh or b.mesh is self.mesh
                else None))
        merged = Slice(self._take_id(), a.slots + b.slots, chains=chains,
                       chain_lo=lo, mesh=sub)
        _validate_slice(merged, tuple(self.mesh.devices.shape)
                        if self.mesh is not None else None)
        i0 = min(ia, ib)
        self.slices[i0:i0 + 2] = [merged]
        return merged

    def recarve(self, mesh):
        """Re-derive slice submeshes after a global evacuation changed
        the parent mesh.  Single slice follows the mesh; a multi-slice
        layout re-carves the same chain spans when they still fit and
        degrades every slice to unplaced when they do not (streams are
        pure in the tenant identity, so placement changes never change
        bits)."""
        from ..parallel.sharding import chain_slice, chain_submesh_size

        self.mesh = mesh
        if len(self.slices) == 1:
            sl = self.slices[0]
            sl.mesh = mesh
            sl.chains = (chain_submesh_size(mesh)
                         if mesh is not None else 0)
            sl.chain_lo = 0
            return
        nc = chain_submesh_size(mesh)
        need = sum(sl.chains for sl in self.slices)
        if mesh is None or need == 0 or nc < need or \
                "chain" not in mesh.axis_names:
            for sl in self.slices:
                sl.mesh = None
                sl.chains = 0
            return
        lo = 0
        for sl in self.slices:
            sl.chain_lo = lo
            sl.mesh = chain_slice(mesh, lo, lo + sl.chains)
            lo += sl.chains

    # -- reporting -----------------------------------------------------------

    def report(self):
        out = []
        for sl in self.slices:
            group = None
            if sl.active is not None:
                try:
                    group = list(sl.active[0].as_tuple())
                except Exception:       # noqa: BLE001
                    group = str(sl.active[0])
            out.append({
                "slice": sl.slice_id,
                "state": sl.plan.state,
                "slots": sl.slots,
                "chains": int(sl.chains),
                "chain_rows": ([sl.chain_lo, sl.chain_lo + sl.chains]
                               if sl.chains else None),
                "residents": sl.live(),
                "group": group,
                "chunks": int(sl.chunks),
                "losses": int(sl.losses),
            })
        return out
