"""Program cache + batch-row multiplexed sweep engine.

Zero-retrace across datasets rests on two facts about the
``CompiledPTA`` pytree (``sampler/compiled.py``):

1. jit cache keys compare the static aux data by *box identity*
   (``_StaticBox.__hash__ = id``), so two CompiledPTA instances — even
   with equal static values — miss each other's cache entries;
2. everything trace-relevant that is NOT in the box is an array leaf,
   and the padding conventions make bucket-forced shapes exact.

So the cache keeps one *canonical* CompiledPTA per (bucket, model
signature) and grafts its box onto every later dataset compiled into
the same bucket (:func:`adopt_static`) — after verifying that every
static field a traced kernel can read (shapes, counts, kinds, prior
bounds, Gibbs block indices) is value-identical.  ``param_names`` may
differ (host-only labels); anything else differing is a
:class:`SignatureMismatch`, never a silent wrong-constant graft.

Multiplexing then stacks T grafted CompiledPTAs leaf-wise
(:func:`stack_cms`) and runs one jitted chunk that ``lax.scan``s sweeps
of ``jax.vmap(sharded_sweep_step)`` over the tenant axis — tenants ride
the vmap axis the way chains do, mathematically independent rows (vmap
introduces no cross-row ops), so a tenant's chain is bitwise identical
whether it runs solo or next to others, and admission/eviction between
chunks swaps leaf *data* under the same treedef + box → the jit cache
hits every time.

Per-tenant PRNG streams extend the repo policy
``fold_in(fold_in(base_key, iteration), chain)``: each tenant carries
its own base key (host-derived ``fold_in(service_key, tenant_id)``),
the chunk folds ``(iteration, 0)`` in-trace, and the step splits — the
stream is a pure function of (tenant key, absolute iteration), so row
placement, chunk grid, and co-residents are all bitwise-irrelevant.
"""

from __future__ import annotations

import numpy as np


class SignatureMismatch(ValueError):
    """Two CompiledPTAs cannot share a compiled program: a
    trace-relevant static field differs."""


#: static fields a traced kernel reads (directly or as baked constants);
#: value equality is required before a box graft.  ``param_names`` is
#: deliberately absent — host-only labels.
_GRAFT_EQ_FIELDS = (
    "P", "P_real", "Nmax", "Bmax", "nx", "K", "Kr", "widths",
    "gw_kind", "red_kind", "orf_name", "red_shares_gw",
    "rhomin", "rhomax", "red_rhomin", "red_rhomax",
)

#: optional array fields whose None-ness changes the pytree structure
_NONEABLE_FIELDS = ("orf_Ginv", "gp_mask", "red_f", "red_df", "orf_B",
                    "orf_par_ix", "ke_eid", "ke_par_ix")


def model_signature(cm) -> tuple:
    """Hashable trace-relevant identity of a CompiledPTA: two models
    with equal signatures (plus equal Gibbs block indices, verified at
    graft time) compile to the same program under one static box."""
    return (
        tuple((f, getattr(cm, f)) for f in _GRAFT_EQ_FIELDS),
        ("dtype", np.dtype(cm.dtype).name),
        ("cdtype", np.dtype(cm.cdtype).name),
        ("components", tuple(c.kind for c in cm.components)),
        ("none", tuple(getattr(cm, f) is None for f in _NONEABLE_FIELDS)),
    )


def group_key(bucket, cm) -> tuple:
    """Canonical ``(bucket, signature)`` group identity.  Jobs with
    equal group keys multiplex through ONE compiled program and may
    share a resident slot stack; the placement engine pins each group
    to at most one slice (its fault domain), so this key is also the
    routing key of :meth:`..service.SamplerService._admissions`."""
    return (bucket, model_signature(cm))


def adopt_static(cm, canon):
    """Graft ``canon``'s static box onto ``cm`` so the two share every
    jit cache entry.  Verifies the full trace-relevant static surface
    first; raises :class:`SignatureMismatch` on any difference."""
    sig, csig = model_signature(cm), model_signature(canon)
    if sig != csig:
        diff = [a for a, b in zip(sig, csig) if a != b]
        raise SignatureMismatch(
            f"cannot share a compiled program: {diff!r}")
    # Gibbs block positions are baked into traced gathers (mh_scan runs
    # over cm.idx.white as a constant) — value equality required even
    # though the names behind them differ per dataset
    for f in ("rho", "red", "red_rho", "white", "ecorr", "orf"):
        if not np.array_equal(getattr(cm.idx, f), getattr(canon.idx, f)):
            raise SignatureMismatch(
                f"Gibbs block index '{f}' differs between datasets "
                "with equal shape signatures")
    from jax import tree_util

    tree_util.tree_flatten(canon)       # memoize the canonical box
    cm.__dict__["_staticbox"] = canon.__dict__["_staticbox"]
    return cm


def compile_bucket(pta, bucket):
    """Compile ``pta`` at the bucket's padded geometry (exact by the
    pad-inertness conventions; see :mod:`.buckets`)."""
    from ..sampler.compiled import compile_pta

    return compile_pta(pta, pad_pulsars=int(bucket.pulsars),
                       pad_toas=int(bucket.toas),
                       pad_basis=int(bucket.basis))


def stack_cms(cms):
    """Stack T grafted CompiledPTAs into one batched pytree (leaves gain
    a leading tenant axis).  All members must share one treedef — i.e.
    one canonical box (:func:`adopt_static`) — or the stack raises
    :class:`SignatureMismatch` instead of silently retracing."""
    import jax.numpy as jnp
    from jax import tree_util

    flat0, treedef0 = tree_util.tree_flatten(cms[0])
    cols = [flat0]
    for cm in cms[1:]:
        flat, treedef = tree_util.tree_flatten(cm)
        if treedef != treedef0:
            raise SignatureMismatch(
                "stacked CompiledPTAs have different treedefs — "
                "adopt_static() was skipped or failed")
        for a, b in zip(flat0, flat):
            a, b = np.asarray(a), np.asarray(b)
            if a.shape != b.shape or a.dtype != b.dtype:
                raise SignatureMismatch(
                    f"stacked leaf aval mismatch: {a.shape}/{a.dtype} "
                    f"vs {b.shape}/{b.dtype}")
        cols.append(flat)
    leaves = [jnp.stack([c[i] for c in cols], axis=0)
              for i in range(len(flat0))]
    return tree_util.tree_unflatten(treedef0, leaves)


def mux_body(chunk: int):
    """The raw (unjitted) multiplexed chunk: ``lax.scan`` of
    ``vmap(sharded_sweep_step)`` over the tenant axis.

    ``mux(cm_stack, x, b, tkeys, it0) -> (x, b, xs, bs, health)`` with
    ``x (T, nx)``, ``b (T, P, Bmax)``, ``tkeys (T,)`` typed keys,
    ``it0 (T,) int32`` per-tenant absolute iteration of the chunk start
    (tenants admitted at different times run at different absolute
    iterations in the same chunk).  ``xs``/``bs`` record every sweep:
    ``(chunk, T, ...)``.  ``health`` is the per-tenant-row verdict of
    :func:`~..runtime.sentinels.chunk_health` — finite / move_frac /
    rho_ok, each ``(T,)`` — computed inside the jitted chunk so the
    blast-radius decision (quarantine ONE row, keep the others) rides
    the same dispatch as the recorded stacks instead of a host rescan.
    Exposed unjitted so jaxprcheck can trace the same program the
    service runs (``contracts/serve_buckets.json``).
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ..runtime.sentinels import chunk_health
    from ..sampler import jax_backend as jb

    n = int(chunk)

    def mux(cm_stack, x, b, tkeys, it0):
        def sweep(carry, s):
            x, b = carry
            keys = jax.vmap(
                lambda kk, i0: jr.fold_in(jr.fold_in(kk, i0 + s), 0)
            )(tkeys, it0)
            x, b = jax.vmap(jb.sharded_sweep_step)(cm_stack, x, b, keys)
            return (x, b), (x, b)

        (x, b), (xs, bs) = jax.lax.scan(
            sweep, (x, b), jnp.arange(n, dtype=jnp.int32))
        # per-row health: rho_ix_x is an array leaf (stacked (T, K) with
        # per-row columns) while the rho bounds are static-box floats —
        # graft verification already proved them identical across rows
        rho_ix = cm_stack.rho_ix_x
        if getattr(rho_ix, "size", 0):
            health = chunk_health(
                xs, bs, rho_ix,
                0.5 * float(np.log10(cm_stack.rhomin)),
                0.5 * float(np.log10(cm_stack.rhomax)))
        else:
            health = chunk_health(xs, bs)
        return x, b, xs, bs, health

    return mux


def make_mux(chunk: int):
    """The jitted :func:`mux_body`.  On non-CPU backends the (x, b)
    carries are donated — the scheduler threads them as device-resident
    carries between chunks and the old buffers are dead weight.  On the
    CPU backend donation is deliberately OFF: donating the carries of
    this program intermittently corrupts the heap inside the CPU
    runtime (observed as segfaults/aborts in the chunk dispatch or the
    following host writeback once the tenant axis is ≥ 4), and CPU
    donation saves nothing — the host has no HBM to economize."""
    import jax

    if jax.default_backend() == "cpu":
        return jax.jit(mux_body(chunk))
    return jax.jit(mux_body(chunk), donate_argnums=(1, 2))


def make_init():
    """Jitted fresh-tenant b-init: one conditional draw at the reserved
    iteration 0 (the recorded sweeps start at absolute iteration 1), so
    no sweep ever sees the degenerate ``b = 0`` state the drivers also
    avoid."""
    import jax

    from ..sampler import jax_backend as jb

    def init_b(cm, x, key):
        return jb.draw_b_fn(cm, x, key)

    return jax.jit(init_b)


class ProgramCache:
    """Canonical statics + jitted programs, keyed by (bucket, model
    signature).  ``hits``/``misses`` count admissions that found /
    created a canonical entry — the ``warm_hit_rate`` gauge."""

    def __init__(self):
        self._canon: dict = {}
        self._mux: dict = {}
        self._init = None
        self.hits = 0
        self.misses = 0

    def adopt(self, bucket, cm):
        """Register ``cm`` under its (bucket, signature); graft the
        canonical box when one exists.  Returns ``(cm, warm)`` where
        ``warm`` is True on a cache hit."""
        key = (bucket, model_signature(cm))
        canon = self._canon.get(key)
        if canon is None:
            self._canon[key] = cm
            self.misses += 1
            return cm, False
        adopt_static(cm, canon)
        self.hits += 1
        return cm, True

    def canonical(self, bucket, cm):
        """The canonical CompiledPTA sharing ``cm``'s program (used for
        inert filler rows in partially occupied stacks)."""
        return self._canon[(bucket, model_signature(cm))]

    def has_bucket(self, bucket) -> bool:
        """Whether any canonical program exists for ``bucket`` — the
        admission controller's warmth probe (bucket granularity: the
        signature needs a compile to learn, the bucket doesn't)."""
        return any(k[0] == bucket for k in self._canon)

    def mux(self, chunk: int):
        fn = self._mux.get(int(chunk))
        if fn is None:
            fn = self._mux[int(chunk)] = make_mux(chunk)
        return fn

    def init_fn(self):
        if self._init is None:
            self._init = make_init()
        return self._init

    def warm_hit_rate(self) -> float:
        tot = self.hits + self.misses
        return (self.hits / tot) if tot else 0.0
