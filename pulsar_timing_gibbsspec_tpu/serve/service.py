"""Fair-share scheduler: requests → batch rows of one compiled sweep.

The service owns resident *slots* grouped into placement **slices**
(:mod:`~.placement`), a FIFO queue, and the
:class:`~.engine.ProgramCache`.  Each slice is one fault domain: a
fixed tenant-axis width (the vmap width of its compiled program, fixed
at construction so occupancy changes never change shapes) pinned to a
contiguous span of chain-axis device rows.  By default there is ONE
slice spanning the whole mesh — the historical single-group service,
bit-for-bit.  With ``placement=[{"slots": ..., "chains": ...}, ...]``
several ``(bucket, signature)`` groups sample CONCURRENTLY on disjoint
chain-submesh slices (different chain counts coexist: each slice's
``slots`` divides over its own chain rows — the chains sub-axis).
Each :meth:`step` runs one multiplexed chunk per occupied slice, with
admission/eviction strictly *between* chunks:

- **admission** fills free slots from the queue head.  All residents
  of a slice share one (bucket, model-signature) program; a queued job
  routes to the slice already hosting its group, claims an empty slice
  otherwise, and only waits when every slice is busy with another
  group (its compile still happens once, at first consideration, and
  is cached) — no more whole-service head-of-line blocking behind one
  hot tenant class.
- **fair share** when the queue holds work for a slice, a resident
  that has held its slot for ``quantum`` chunks is checkpointed and
  requeued (``tenant_evictions`` gauge) — no request can starve the
  queue.
- **empty slots** carry an inert filler row (the bucket's canonical
  model with a fixed filler stream): rows are mathematically
  independent under vmap, so fillers cost compute but never touch a
  tenant's values, and the program never retraces for occupancy.
- **pre-warming** (``prewarm=N``) — predictive upgrade of the
  reactive compile-storm deferral: when the ``compile_stalls`` /
  ``warm_hit_rate`` gauges show cold compiles hurt and the queue holds
  a cold bucket that cannot be placed this step, its bucket compiles
  inside a *planned* window while residents keep dispatching — hard
  capped (one compile per step, N outstanding buckets) and suspended
  during an admission-controller compile storm, so pre-warming never
  starves a resident group's step.

Failure handling maps onto the supervisor taxonomy
(``runtime/supervisor.classify_failure``) with blast-radius isolation
as the organizing principle — tenant rows are independent conditional
chains under vmap, and slices share no devices and no collectives
(the chain axis is collective-free, measured in ``crn_2d_mesh``), so
one bad tenant must never perturb a neighbor's bits and one lost
slice must never perturb another slice's stream:

- **quarantine** — the jitted chunk returns a per-tenant-row health
  vector (finite / move_frac / rho_ok, ``runtime.sentinels``); a row
  breach QUARANTINES only that job: its poisoned rows never reach the
  host buffers, the slot swaps to an inert filler at the next chunk
  boundary, the job restarts from its own verified checkpoint under a
  capped per-job budget (``quarantine_max``), and every co-resident
  keeps running untouched (proven bitwise in tests/test_quarantine.py).
  Budget exhausted → the job parks terminally in state ``quarantined``
  with the marker in its manifest (``integrity.load_resume`` then
  refuses the directory without ``force_requeue``).
- **circuit breakers** — with ``breaker=`` configured, each tenant
  gets a failure-rate breaker (``runtime.supervisor.CircuitBreaker``):
  open tenants are rejected at :meth:`~SamplerService.submit` (typed
  :class:`~..runtime.supervisor.CircuitOpen`) and their quarantined
  jobs wait out the cooldown before the half-open probe re-admits.
- **admission control** — with ``admission=`` configured, submissions
  are gated on ``queue_depth`` backpressure and cold dataset shapes
  are DEFERRED during a compile storm
  (``runtime.supervisor.AdmissionController``, driven by the
  ``compile_stalls``/``queue_depth``/``time_to_first_sample_ms``
  gauges the service already publishes).
- **device loss** — ``faults.DeviceLost`` carrying a ``slice_id`` (on
  a multi-slice service) evacuates and re-places ONLY the lost
  slice's group (:meth:`evacuate_slice`): its jobs checkpoint their
  intact host rows and requeue at the head, only that slice's warmed
  programs and stacked carries drop — the shared
  :class:`~.engine.ProgramCache` and every survivor slice's programs
  stay untouched (survivors are provably not retraced), with
  deterministic per-slice backoff and a capped re-place budget
  (``replace_max`` losses within ``replace_window`` seconds → a typed
  terminal :class:`~.placement.PlacementError` and the slice parks
  ``failed``).  A loss without slice attribution evacuates the whole
  service (:meth:`evacuate`) exactly as before: programs rebuild on
  the surviving submesh and the jobs re-admit.
- **whole-step failures** — device/crash classes still retry the whole
  step with deterministic backoff after reverting every resident to
  its verified checkpoint; ``user`` errors re-raise immediately.  A
  preemption drain (``runtime/preemption``) checkpoints every resident
  to a verified set, marks the drain, and raises
  :class:`~..runtime.preemption.Preempted` (``EXIT_PREEMPTED=75``
  semantics preserved per job).

Rebalancing (:meth:`split_slice` / :meth:`merge_slices`) goes through
verified checkpoints with the never-a-torn-hybrid guarantee of the
standing-model migrations: every affected resident drains (checkpoint
+ ``integrity.verify``) BEFORE the geometry mutates, and the in-memory
layout is ephemeral — a restart sees only per-job checkpoints, never a
half-moved hybrid.

Chaos seams: ``faults.fire("serve.chunk", row=<global chunk>)`` runs
before every dispatch; ``faults.tenant_evict_request`` forces an
eviction (per-tenant targetable); ``faults.poison_tenant_rows`` NaN-
poisons one tenant's chunk rows; ``faults.inject("device_loss",
slice=<id>)`` targets one slice — the drills in
``tools/chaos_probe.py`` and the seeded campaign in
``tools/chaos_campaign.py`` (multi-group legs included).
"""

from __future__ import annotations

import time
from pathlib import Path

import numpy as np

from ..obs import trace as otrace
from ..runtime import faults, preemption, supervisor, telemetry
from .buckets import (BucketOverflow, BucketSpec, BucketTable,
                      plan_migration, probe_shape)
from .engine import ProgramCache, compile_bucket, stack_cms
from .jobs import Job, MigrationTicket, repad_checkpoint
from .placement import PlacementEngine, PlacementError

#: tenant index of the inert filler stream (far above any real tenant)
FILLER_TENANT = 0x7FFFFFFF

#: fold-salt for standing-model generations: a child generation's key
#: is ``fold_in(tenant_key, _GEN_SALT + generation)`` so generation g
#: of tenant t never collides with iteration folds (small ints) or
#: with another tenant's stream; generation 0 folds nothing (bitwise
#: backward compatibility with every pre-lineage checkpoint)
_GEN_SALT = 0x67656E


class SamplerService:
    """Resident multi-tenant sampler over per-slice device programs.

    ``slots`` is the tenant-axis width (compiled once per bucket);
    ``chunk`` the sweeps per dispatch; ``save_every`` the checkpoint
    cadence in chunks; ``quantum`` the fair-share slice in chunks.

    ``mesh`` (optional) places the service on a device mesh: on a 2-d
    ``(chain, pulsar)`` mesh the tenant axis IS the chain axis — each
    slice's ``slots`` must divide over its chain rows, the stacked
    per-tenant carries are committed with
    ``parallel.sharding.shard_carry`` on the slice's submesh (rows are
    mathematically independent under vmap, so tenant traffic never
    crosses the chain axis), and :meth:`report` records the layout.
    Placement never touches a tenant's PRNG stream and mesh-placed
    runs are deterministic (bitwise across incarnations on the same
    mesh, so checkpoint replay stays exact); against the UNPLACED
    service the values agree at the f64 reduction-order class — GSPMD
    regroups within-sweep reductions for the per-shard program — not
    bitwise (tests/test_serve.py).

    ``placement`` (optional) carves the mesh into concurrent fault-
    domain slices: a list of ``{"slots": s, "chains": c}`` specs,
    consumed in order from chain row 0 (``chains`` is ignored on an
    unplaced service — slices still schedule independently).  Omitted,
    the service keeps its historical shape: one slice, one resident
    group at a time, behavior identical to every prior release.
    ``prewarm`` enables predictive bucket pre-compilation (N
    outstanding buckets, hard-capped); ``replace_max`` /
    ``replace_window`` bound the per-slice device-loss re-place
    budget."""

    def __init__(self, root, table: BucketTable, *, slots=2, chunk=4,
                 save_every=1, quantum=8, service_seed=0, max_retries=2,
                 backoff_base=0.0, cache: ProgramCache | None = None,
                 mesh=None, ensemble=False, pt_ladder=1, perf=False,
                 quarantine_max=2, breaker=None, admission=None,
                 evac_max=2, placement=None, prewarm=0, replace_max=1,
                 replace_window=30.0, clock=time.monotonic):
        # the multiplexed chunk is vmap(sharded_sweep_step) over the
        # TENANT axis — rows are unrelated analyses, so any cross-chain
        # ensemble stage (stretch pairing, tempering swaps) would couple
        # tenants.  The kwargs exist only to reject the request loudly
        # at the service boundary instead of silently ignoring it.
        if ensemble or int(pt_ladder) > 1:
            raise ValueError(
                "ensemble moves / parallel tempering are not available "
                "in the multiplexed service: tenant rows share the "
                "chain axis and interchain moves would mix unrelated "
                "analyses.  Run ensemble sampling through the "
                "single-tenant driver (JaxGibbsDriver(ensemble=True))")
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.table = table
        self.mesh = mesh
        self._engine = PlacementEngine(
            mesh, layout=placement, slots=int(slots),
            replace_max=int(replace_max),
            replace_window=float(replace_window), clock=clock)
        self._slices = self._engine.slices
        self.slots = self._engine.total_slots
        self.chunk = int(chunk)
        self.save_every = max(1, int(save_every))
        self.quantum = max(1, int(quantum))
        self.service_seed = int(service_seed)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)

        # a caller-supplied cache lets a successor service (warm restart
        # in the same process) reuse the predecessor's compiled programs
        self.cache = ProgramCache() if cache is None else cache
        self.jobs: dict[str, Job] = {}
        self.queue: list[Job] = []
        self.global_chunk = 0
        self._fillers: dict = {}     # group-key -> (x, b) host filler state
        self._diags: dict = {}       # job_id -> (RollingDiag, channel idx)
        self._evictions = 0
        self._compile_stalls = 0
        self._next_tenant = 0
        self._retries = 0
        self._pending_backoff = 0.0

        # blast-radius isolation: per-job quarantine budget, per-tenant
        # circuit breakers, service-level admission control, and the
        # device-loss evacuation budget.  ``breaker``/``admission``
        # accept True (defaults) or a kwargs dict; ``clock`` is
        # injectable so breaker cooldowns are testable without sleeping
        self.quarantine_max = int(quarantine_max)
        self.evac_max = int(evac_max)
        self._clock = clock
        self._breaker_cfg = ({} if breaker is True else breaker)
        self._breakers: dict[int, supervisor.CircuitBreaker] = {}
        if admission is True:
            admission = {}
        self._admission = None if admission is None else \
            supervisor.AdmissionController(clock=clock, **admission)
        self._quarantines = 0
        self._evacuations = 0
        self._quarantine_log: list[dict] = []

        # predictive pre-warming: budget of outstanding pre-compiled
        # buckets (0 = off, the historical reactive-only behavior)
        self._prewarm_max = int(prewarm)
        self._prewarmed: set = set()
        self._prewarms = 0
        self._group_warmth: dict = {}   # bucket -> [hits, misses]
        self._max_groups = 0            # concurrency high-water mark

        # perf=True hangs the streaming stage aggregator off the trace
        # seams: every serve.prepare/dispatch/d2h/writeback span folds
        # into dispatch_ms{stage=...,job="svc"} gauges that prometheus()
        # scrapes live — no per-chunk work beyond the span it already
        # emits, and nothing traced (sampling stays bitwise identical)
        self._stage_agg = None
        if perf:
            from ..obs.perf import StageAggregator

            self._stage_agg = StageAggregator(job="svc").install()

    # -- residency views ----------------------------------------------------

    @property
    def residents(self):
        """Flat resident view across every slice (read-only snapshot —
        internal scheduling mutates the per-slice lists)."""
        out = []
        for sl in self._slices:
            out.extend(sl.residents)
        return out

    def placement_summary(self):
        """Compact per-slice residency (the gateway's healthz body)."""
        keep = ("slice", "state", "slots", "chains", "residents",
                "group")
        return [{k: ent[k] for k in keep}
                for ent in self._engine.report()]

    # -- request intake -----------------------------------------------------

    def submit(self, pta, niter, job_id=None, tenant_id=None,
               outdir=None, generation=0, lineage=None) -> Job:
        """Queue an analysis request.  ``tenant_id`` (with the service
        seed, and the ``generation`` counter for forked standing-model
        generations) IS the PRNG identity — pass the original values to
        readmit a job in a fresh process, or leave None for a new
        stream.

        Raises :class:`~..runtime.supervisor.CircuitOpen` when admission
        control rejects on queue-depth backpressure, or when the
        tenant's circuit breaker is open (a tenant whose uploads keep
        poisoning rows must wait out the cooldown)."""
        if self._admission is not None:
            self._admission.admit_submission(len(self.queue))
        if job_id is None:
            job_id = f"job{len(self.jobs):04d}"
        if job_id in self.jobs:
            raise ValueError(f"duplicate job_id {job_id!r}")
        if tenant_id is None:
            tenant_id = self._next_tenant
        br = self._breakers.get(int(tenant_id))
        if br is not None:
            br.check(f"tenant {int(tenant_id)}")
        self._next_tenant = max(self._next_tenant, int(tenant_id) + 1)
        if outdir is None:
            outdir = self.root / job_id
        job = Job(job_id=job_id, pta=pta, niter=int(niter),
                  tenant_id=int(tenant_id), outdir=str(outdir),
                  generation=int(generation),
                  lineage=dict(lineage) if lineage else None)
        self.jobs[job_id] = job
        self.queue.append(job)
        telemetry.gauge("queue_depth", float(len(self.queue)))
        return job

    # -- PRNG / state derivation -------------------------------------------

    def _service_key(self):
        import jax.random as jr

        return jr.key(self.service_seed)

    def _tenant_key(self, tenant_id, generation=0):
        """Tenant base key; a forked standing-model generation folds
        its counter (salted, so generation 1 never collides with a
        sibling tenant id) on top.  Generation 0 keeps the historical
        key exactly — every pre-lineage checkpoint replays bitwise."""
        import jax.random as jr

        k = jr.fold_in(self._service_key(), int(tenant_id))
        if int(generation):
            k = jr.fold_in(k, _GEN_SALT + int(generation))
        return k

    def _init_key(self, tenant_id, generation=0):
        """Reserved iteration-0 key for the fresh-tenant b draw."""
        import jax.random as jr

        return jr.fold_in(
            jr.fold_in(self._tenant_key(tenant_id, generation), 0), 0)

    def _x0(self, job) -> np.ndarray:
        """Deterministic per-(service_seed, tenant, generation) initial
        state — part of the stream identity, so solo and multiplexed
        runs agree."""
        seq = [self.service_seed, int(job.tenant_id)]
        if int(job.generation):
            seq.append(_GEN_SALT + int(job.generation))
        rng = np.random.default_rng(seq)
        return np.asarray(job.pta.initial_sample(rng), np.float64)

    # -- admission / eviction ----------------------------------------------

    def _route(self, job) -> bool:
        """Route only (cheap — no compile): sets ``job.bucket``.
        Returns False after marking the job failed on overflow."""
        if job.bucket is not None:
            return True
        try:
            job.bucket = self.table.route(probe_shape(job.pta))
        except BucketOverflow as exc:
            job.failure = f"overflow: {exc}"
            job.set_state("failed")
            return False
        return True

    def _prepare(self, job) -> bool:
        """Route + compile + graft (idempotent; cached on the job).
        Returns False after marking the job failed on a routing error."""
        if job.cm is not None:
            return True
        job.set_state("warming")
        if not self._route(job):
            return False
        from ..analysis import guards

        # staging a new dataset compiles small host->device programs;
        # mark them planned so retrace accounting only sees the sweep
        with guards.planned_compile(), \
                otrace.span("serve.prepare", job=job.job_id,
                            tenant=int(job.tenant_id)):
            cm = compile_bucket(job.pta, job.bucket)
            cm, warm = self.cache.adopt(job.bucket, cm)
        job.cm = cm
        g = self._group_warmth.setdefault(job.bucket, [0, 0])
        g[0 if warm else 1] += 1
        if not warm:
            self._compile_stalls += 1
            telemetry.gauge("compile_stalls", float(self._compile_stalls))
            if self._admission is not None:
                self._admission.note_compile()
        telemetry.gauge("warm_hit_rate", self.cache.warm_hit_rate())
        return True

    def _group_key(self, job):
        from .engine import group_key

        return group_key(job.bucket, job.cm)

    def _claimed_elsewhere(self, key, sl) -> bool:
        """True when another slice already hosts this group — a group
        is pinned to at most one slice, so its jobs queue there rather
        than splitting the group's program across fault domains."""
        return any(o is not sl and o.active == key
                   for o in self._slices)

    def _admit(self, job, sl, slot):
        import jax.numpy as jnp

        from ..analysis import guards

        job.set_state("warming")
        sl.plan.warming()
        cm = job.cm
        if job.chain is None:
            job.alloc(cm.nx, cm.P * cm.Bmax)
        if job.store is None:
            job.open_store()
            if not job.try_resume():
                job.x = self._x0(job)
                with guards.planned_compile():
                    b = self.cache.init_fn()(
                        cm, jnp.asarray(job.x, cm.cdtype),
                        self._init_key(job.tenant_id, job.generation))
                job.b = np.asarray(b, np.float64)
        job.chunks_resident = 0
        job.admitted_at = time.monotonic()
        job.slice_id = sl.slice_id
        sl.residents[slot] = job
        job.set_state("sampling")
        sl.dirty = True
        self._prewarmed.discard(job.bucket)

    def _evict(self, sl, slot, reason):
        job = sl.residents[slot]
        job.checkpoint()
        job.set_state("queued")
        sl.residents[slot] = None
        self.queue.append(job)
        self._evictions += 1
        telemetry.gauge("tenant_evictions", float(self._evictions))
        telemetry.gauge("queue_depth", float(len(self.queue)))
        sl.dirty = True

    def _tenant_breaker(self, tenant_id, create=False):
        """The tenant's circuit breaker (None when breakers are off)."""
        if self._breaker_cfg is None:
            return None
        br = self._breakers.get(int(tenant_id))
        if br is None and create:
            br = self._breakers[int(tenant_id)] = \
                supervisor.CircuitBreaker(clock=self._clock,
                                          **self._breaker_cfg)
        return br

    def _quarantine(self, sl, slot, why):
        """Blast-radius isolation for one poisoned row: drop the job
        from its slot (an inert filler swaps in at the restack — the
        next chunk boundary), discard the poisoned chunk (it never
        reached the host buffers), and restart the job from its own
        verified state — in-memory ``(x, b, it)`` still hold the last
        clean chunk's end, which the checkpoint here persists.  Every
        co-resident keeps running untouched: rows are independent under
        vmap and their writeback proceeds in the same loop.

        Within the ``quarantine_max`` budget the job requeues (state
        ``quarantined``; its breaker gates re-admission).  Budget
        exhausted → the job parks terminally with the quarantine marker
        in its manifest: a deterministic replay that breaches again
        will breach forever, and ``integrity.load_resume`` refuses the
        directory until an operator passes ``force_requeue``.
        """
        job = sl.residents[slot]
        job.quarantines += 1
        self._quarantines += 1
        telemetry.incr("sentinel_trips")
        telemetry.incr("quarantines")
        telemetry.gauge("quarantined_jobs", float(sum(
            1 for j in self.jobs.values() if j.state == "quarantined") + 1))
        self._quarantine_log.append({
            "job_id": job.job_id, "tenant_id": int(job.tenant_id),
            "chunk": int(self.global_chunk), "why": why,
            "count": int(job.quarantines)})
        br = self._tenant_breaker(job.tenant_id, create=True)
        if br is not None:
            br.record_failure()
        sl.residents[slot] = None
        sl.dirty = True
        otrace.instant("serve.quarantine", job=job.job_id,
                       tenant=int(job.tenant_id), why=why,
                       count=int(job.quarantines))
        if job.quarantines > self.quarantine_max:
            job.failure = (f"quarantined: {why} — budget exhausted "
                           f"({job.quarantines - 1} replays); "
                           "resume requires force_requeue")
            job.set_state("quarantined")
            job.checkpoint()    # manifest carries the quarantine marker
            return
        # verified checkpoint of the clean prefix, THEN the state flip:
        # the pending-requeue manifest must stay resumable by a fresh
        # incarnation without the operator override
        job.checkpoint()
        job.set_state("quarantined")
        self.queue.append(job)
        telemetry.gauge("queue_depth", float(len(self.queue)))

    def _admissions(self):
        """Fill free slots from the queue head, one (bucket, signature)
        group per slice.  A job routes to the slice hosting its group,
        claims an empty slice otherwise, and waits only when every
        slice is busy with another group.  A quarantined job waits for
        its tenant's breaker (half-open probe after the cooldown);
        during a compile storm, cold dataset shapes are deferred so a
        burst of novel buckets cannot serialize warm tenants behind
        back-to-back XLA compiles."""
        for sl in self._slices:
            if not any(sl.residents):
                # empty slice returns to the allocatable pool (guarded
                # no-ops outside resident→draining→planned)
                sl.plan.draining()
                sl.plan.drained()
                sl.active = None
        for sl in self._slices:
            if sl.plan.state == "failed":
                continue        # parked fault domain: never refills
            for slot in range(sl.slots):
                if sl.residents[slot] is not None:
                    continue
                take = None
                for job in self.queue:
                    if job.state == "quarantined":
                        # non-consuming gate: the half-open probe must
                        # only be claimed when the job is actually
                        # admitted — a group-key mismatch after allow()
                        # would strand the breaker half-open with its
                        # probe spent, starving the tenant forever
                        br = self._tenant_breaker(job.tenant_id)
                        if br is not None and not br.would_allow():
                            continue        # wait out the cooldown
                    if (self._admission is not None and job.cm is None):
                        if not self._route(job):
                            continue        # failed routing; skip
                        if self._admission.defer_cold(
                                self.cache.has_bucket(job.bucket)):
                            continue    # compile storm: hold cold shapes
                    if not self._prepare(job):
                        continue            # failed routing; skip
                    key = self._group_key(job)
                    if sl.active is None:
                        if self._claimed_elsewhere(key, sl):
                            continue        # queued for its own slice
                        sl.active = key
                    if key == sl.active:
                        take = job
                        break
                if take is None:
                    break
                if take.state == "quarantined":
                    br = self._tenant_breaker(take.tenant_id)
                    if br is not None and not br.allow():
                        break   # probe raced away; retry next round
                self.queue.remove(take)
                self.queue[:] = [j for j in self.queue
                                 if j.state != "failed"]
                telemetry.gauge("queue_depth", float(len(self.queue)))
                self._admit(take, sl, slot)
        # drop failed-routing jobs that never got picked
        self.queue[:] = [j for j in self.queue if j.state != "failed"]

    # -- predictive pre-warming --------------------------------------------

    def _job_waiting(self, job) -> bool:
        """True when the routed job cannot be placed this step: every
        slice is busy with another group and no matching slot is free.
        Pre-warming overlaps the compile with that wait instead of
        stalling the eventual admission."""
        for sl in self._slices:
            if sl.plan.state == "failed":
                continue
            if not any(sl.residents):
                return False        # an empty slice will take it
            if sl.active is not None and sl.active[0] == job.bucket \
                    and any(r is None for r in sl.residents):
                return False        # its group has a free slot
        return True

    def _prewarm(self):
        """Predictive bucket pre-compilation, driven by the gauges the
        service already publishes (``compile_stalls``,
        ``warm_hit_rate``) plus queue composition: pick the first
        queued cold bucket that must wait anyway and compile it inside
        a *planned* window.  Hard-capped so it can never starve a
        resident group: at most ONE compile per step, at most
        ``prewarm`` outstanding buckets, and fully suspended while the
        admission controller reports a compile storm."""
        if not self._prewarm_max or not self.queue:
            return
        if self._admission is not None and self._admission.storming():
            return      # storm: reactive deferral already shields us
        if len(self._prewarmed) >= self._prewarm_max:
            return
        if not (self._compile_stalls > 0
                or self.cache.warm_hit_rate() < 1.0):
            return      # no evidence cold compiles hurt: stay reactive
        from ..analysis import guards

        for job in list(self.queue):
            if job.cm is not None or job.state == "quarantined":
                continue
            if not self._route(job):
                continue
            if self.cache.has_bucket(job.bucket) or \
                    job.bucket in self._prewarmed:
                continue
            if not self._job_waiting(job):
                continue
            with guards.planned_compile(), \
                    otrace.span("serve.prewarm", job=job.job_id,
                                bucket=str(job.bucket.as_tuple())):
                cm = compile_bucket(job.pta, job.bucket)
                self.cache.adopt(job.bucket, cm)
            self._prewarmed.add(job.bucket)
            self._prewarms += 1
            telemetry.incr("serve_prewarms")
            telemetry.gauge("serve_prewarms", float(self._prewarms))
            if self._admission is not None:
                self._admission.note_compile()
            return      # hard cap: at most one prewarm compile per step

    # -- filler rows --------------------------------------------------------

    def _filler_state(self, key, canon):
        """Host (x, b) for the inert filler stream of one group
        (prior-midpoint state, reserved-iteration b draw)."""
        got = self._fillers.get(key)
        if got is not None:
            return got
        import jax.numpy as jnp

        from ..analysis import guards

        pa = np.asarray(canon.pa, np.float64)
        pb = np.asarray(canon.pb, np.float64)
        pk = np.asarray(canon.pkind, np.int64)
        # uniform/linexp: bound midpoint; normal: the mean (pa)
        x = np.where(pk == 1, pa, 0.5 * (pa + pb))
        with guards.planned_compile():
            b = self.cache.init_fn()(
                canon, jnp.asarray(x, canon.cdtype),
                self._init_key(FILLER_TENANT))
        got = (x, np.asarray(b, np.float64))
        self._fillers[key] = got
        return got

    # -- the multiplexed chunk ---------------------------------------------

    def _build_stack(self, sl):
        import jax.numpy as jnp

        live = [j for j in sl.residents if j is not None]
        canon = self.cache.canonical(live[0].bucket, live[0].cm)
        fx, fb = self._filler_state(sl.active, canon)
        cms, X, B, K = [], [], [], []
        for job in sl.residents:
            if job is not None:
                cms.append(job.cm)
                X.append(job.x)
                B.append(job.b)
                K.append(self._tenant_key(job.tenant_id,
                                          job.generation))
            else:
                cms.append(canon)
                X.append(fx)
                B.append(fb)
                K.append(self._tenant_key(FILLER_TENANT))
        cdtype = canon.cdtype
        sl.stack = stack_cms(cms)
        sl.X = jnp.asarray(np.stack(X), cdtype)
        sl.B = jnp.asarray(np.stack(B), cdtype)
        sl.K = jnp.stack(K)
        if sl.mesh is not None:
            from ..parallel.sharding import shard_carry

            sl.X, sl.B, sl.K = shard_carry(
                sl.mesh, (sl.X, sl.B, sl.K), sl.slots)
        sl.dirty = False

    def _it0(self, sl):
        import jax.numpy as jnp

        vals = [(j.it + 1) if j is not None else 1
                for j in sl.residents]
        return jnp.asarray(vals, jnp.int32)

    def _dispatch(self, sl):
        """One compiled multiplexed chunk on one slice; scatter rows to
        job buffers.  Slices share nothing on device — disjoint chain
        rows, zero chain-axis collectives — so per-slice dispatches
        never interact."""
        from ..analysis import guards

        if sl.dirty:
            # membership change: restacking compiles small staging
            # programs (jnp.stack per leaf) — planned, not a retrace
            with guards.planned_compile(), \
                    otrace.span("serve.restack", slice=sl.slice_id):
                self._build_stack(sl)
        mux = self.cache.mux(self.chunk)
        warm_key = (self.chunk, sl.active)
        if warm_key not in sl.warmed:
            with guards.planned_compile(), \
                    otrace.span("serve.compile_dispatch",
                                chunk=self.global_chunk,
                                slice=sl.slice_id):
                args = (sl.stack, sl.X, sl.B, sl.K, self._it0(sl))
                X, B, xs, bs, health = mux(*args)
            sl.warmed.add(warm_key)
        else:
            # the zero-retrace contract lives HERE: a steady chunk with
            # a warmed (chunk, group) must compile nothing
            with otrace.span("serve.dispatch", chunk=self.global_chunk,
                             slice=sl.slice_id):
                X, B, xs, bs, health = mux(sl.stack, sl.X, sl.B,
                                           sl.K, self._it0(sl))
        sl.X, sl.B = X, B
        sl.chunks += 1
        with otrace.span("serve.d2h", chunk=self.global_chunk):
            # OWNED host copies, not np.asarray views: on the CPU
            # backend a view aliases the XLA output buffer of a
            # donation-aliased program, and the runtime may reclaim it
            # while the writeback loop is still reading (intermittent
            # segfault under multi-bucket churn)
            np_xs = np.array(xs, np.float64)       # (chunk, T, nx)
            np_bs = np.array(bs, np.float64)       # (chunk, T, P, Bmax)
            h_fin = np.array(health["finite"])     # (T,) per-row verdict
            h_rho = np.array(health["rho_ok"])
        # chaos seam: NaN-poison one tenant's host rows (simulated
        # single-tenant divergence — the blast-radius drill trigger);
        # the maps are slice-local, so a fault targeting a tenant on
        # another slice stays armed until THAT slice dispatches
        live = {int(j.tenant_id): (s, j.chunks_resident)
                for s, j in enumerate(sl.residents) if j is not None}
        np_xs, np_bs, _poisoned = faults.poison_tenant_rows(
            np_xs, np_bs, {t: s for t, (s, _) in live.items()},
            {t: r for t, (_, r) in live.items()})
        now = time.monotonic()
        with otrace.span("serve.writeback", chunk=self.global_chunk):
            for slot, job in enumerate(sl.residents):
                if job is None:
                    continue
                rows = np_xs[:, slot]
                brows = np_bs[:, slot].reshape(self.chunk, -1)
                take = min(self.chunk, job.niter - job.it)
                # the device health vector covers the whole chunk row
                # (including sweeps past the job's tail); the host check
                # covers what would actually be recorded — either way
                # the breach stays confined to THIS row
                breach = None
                if not h_fin[slot]:
                    breach = "non-finite row (device health)"
                elif not h_rho[slot]:
                    breach = "rho-bound breach (device health)"
                elif not (np.isfinite(rows[:take]).all()
                          and np.isfinite(brows[:take]).all()):
                    breach = "non-finite chunk rows (host)"
                if breach is not None:
                    self._quarantine(sl, slot, breach)
                    continue
                job.chain[job.it:job.it + take] = rows[:take]
                job.bchain[job.it:job.it + take] = brows[:take]
                job.it += take
                job.x = rows[take - 1].copy()
                job.b = np_bs[take - 1, slot].copy()
                job.chunks_resident += 1
                if job.first_sample_at is None:
                    job.first_sample_at = now
                    telemetry.gauge("time_to_first_sample_ms",
                                    job.time_to_first_sample_ms())
                br = self._breakers.get(int(job.tenant_id))
                if br is not None:
                    br.record_success()
                self._observe_job(job, rows[:take], now)
        sl.plan.resident()

    def _observe_job(self, job, rows, now):
        """Feed the job's live diagnostics window and publish its SLO
        gauges (labeled per job/tenant so series never collide)."""
        got = self._diags.get(job.job_id)
        if got is None:
            from ..obs.sketch import make_sketch_spec
            from ..obs.summary import RollingDiag

            ch = np.asarray(make_sketch_spec(job.cm).channels)
            got = self._diags[job.job_id] = (RollingDiag(), ch)
        diag, ch = got
        diag.observe(rows[:, ch], now)
        lab = {"job": job.job_id, "tenant": str(int(job.tenant_id))}
        telemetry.gauge("serve_ess_per_sec", diag.ess_per_sec(), **lab)
        telemetry.gauge("serve_rhat_max", diag.rhat_max(), **lab)
        telemetry.gauge("serve_accept_rate", diag.accept_rate(), **lab)

    def _slice_gauges(self):
        """Per-slice residency/health series, slice-labeled so the
        Prometheus scrape separates fault domains."""
        for sl in self._slices:
            lab = {"slice": str(sl.slice_id)}
            telemetry.gauge("serve_slice_residents", float(sl.live()),
                            **lab)
            telemetry.gauge("serve_slice_chunks", float(sl.chunks),
                            **lab)
            telemetry.gauge("serve_slice_losses", float(sl.losses),
                            **lab)

    # -- drain / recovery ---------------------------------------------------

    def _drain(self):
        """Checkpoint every resident to a verified set and raise
        ``Preempted`` — each job resumes from its own directory."""
        from ..runtime import integrity

        rows = 0
        all_ok = True
        with otrace.span("serve.drain",
                         jobs=sum(1 for j in self.residents if j)):
            for sl in self._slices:
                for slot, job in enumerate(sl.residents):
                    if job is None:
                        continue
                    job.set_state("draining")
                    job.checkpoint()
                    res = integrity.verify(job.store.outdir)
                    if not res["ok"]:
                        all_ok = integrity.rollback(job.store.outdir) \
                            and all_ok
                    rows += job.it
                    job.set_state("queued")     # resumable, not failed
        preemption.mark_drained()
        raise preemption.Preempted(
            f"service drained {sum(1 for j in self.residents if j)} "
            f"job(s) to per-job checkpoints", rows=rows, verified=all_ok)

    def _revert_residents(self):
        """Roll every resident back to its last verified checkpoint
        (retry path: the replay from there is bit-exact)."""
        for sl in self._slices:
            for job in sl.residents:
                if job is None:
                    continue
                job.it = 0
                if not job.try_resume():
                    job.x = self._x0(job)
                    import jax.numpy as jnp

                    from ..analysis import guards

                    with guards.planned_compile():
                        b = self.cache.init_fn()(
                            job.cm, jnp.asarray(job.x, job.cm.cdtype),
                            self._init_key(job.tenant_id,
                                           job.generation))
                    job.b = np.asarray(b, np.float64)
            sl.dirty = True

    # -- scheduler loop -----------------------------------------------------

    def step(self) -> bool:
        """One scheduling round: seam, churn, admission, pre-warm, one
        chunk per occupied slice, checkpoints.  Returns False when
        there is nothing to run."""
        if preemption.drain_requested() and any(self.residents):
            self._drain()
        self.global_chunk += 1
        faults.fire("serve.chunk", row=self.global_chunk)
        evict_req = faults.tenant_evict_request(
            row=self.global_chunk,
            job_rows={int(j.tenant_id): j.chunks_resident
                      for j in self.residents if j is not None})
        if evict_req:
            evicted_any = False
            for sl in self._slices:
                for slot, job in enumerate(sl.residents):
                    if job is None:
                        continue
                    if evict_req is True:
                        # untargeted (historical): evict any one
                        if not evicted_any:
                            self._evict(sl, slot, "injected")
                            evicted_any = True
                    elif int(job.tenant_id) in evict_req:
                        self._evict(sl, slot, "injected")
        # fair share: the longest-resident tenant of a pressured slice
        # yields to a non-empty queue after its quantum (single-slice:
        # any queued work is pressure — the historical behavior)
        if self.queue:
            for sl in self._slices:
                held = [(j.chunks_resident, s)
                        for s, j in enumerate(sl.residents)
                        if j is not None]
                if not held:
                    continue
                if len(self._slices) > 1 and \
                        not self._slice_pressure(sl):
                    continue
                most, slot = max(held)
                if most >= self.quantum:
                    self._evict(sl, slot, "quantum")
        self._admissions()
        self._prewarm()
        groups = {sl.active for sl in self._slices
                  if sl.active is not None and any(sl.residents)}
        if len(groups) > self._max_groups:
            self._max_groups = len(groups)
        if not any(self.residents):
            return False
        for sl in self._slices:
            if any(sl.residents):
                self._dispatch(sl)
        for sl in self._slices:
            for slot, job in enumerate(sl.residents):
                if job is None:
                    continue
                if job.done:
                    job.checkpoint()
                    job.set_state("done")
                    sl.residents[slot] = None
                    sl.dirty = True
                elif job.chunks_resident % self.save_every == 0:
                    job.checkpoint()
        self._slice_gauges()
        telemetry.gauge("queue_depth", float(len(self.queue)))
        return True

    def _slice_pressure(self, sl) -> bool:
        """Fair-share pressure on one slice: a queued job that is
        unrouted (could land anywhere) or routed to this slice's
        group.  Without pressure, a multi-slice resident never yields
        its quantum to work that another slice will serve — the
        no-cross-group-drain-waits half of the placement contract."""
        for j in self.queue:
            if j.cm is None:
                return True
            if sl.active is not None and \
                    self._group_key(j) == sl.active:
                return True
        return False

    # -- device-loss fault domains ------------------------------------------

    def evacuate(self, devices=None, slice_id=None) -> None:
        """Device-loss recovery.  With ``slice_id`` on a multi-slice
        service, delegate to :meth:`evacuate_slice`: ONLY that fault
        domain's group re-places, every survivor slice keeps its warmed
        programs and its bitwise stream.  Otherwise (whole-service
        loss) drain every resident through its own verified checkpoint
        (the host row buffers are intact — the lost device only held
        carries and compiled programs), drop every device-resident
        artifact, rebuild on the surviving submesh and re-admit the
        drained jobs at the queue head.  The per-job analogue of the
        single-tenant ``integrity.reshard_restore`` path: streams are
        pure in (service_seed, tenant_id, iteration), so the
        re-admitted jobs replay bit-identically on the new topology."""
        if slice_id is not None and len(self._slices) > 1:
            sl = self._engine.slice_by_id(slice_id)
            if sl is None:
                raise PlacementError(
                    f"evacuate: unknown slice {slice_id}",
                    slice_id=slice_id)
            self.evacuate_slice(sl)
            return
        with otrace.span("serve.evacuate",
                         jobs=sum(1 for j in self.residents if j),
                         devices=devices):
            drained = []
            for sl in self._slices:
                for slot, job in enumerate(sl.residents):
                    if job is None:
                        continue
                    job.checkpoint()
                    job.set_state("queued")
                    job.cm = None      # recompile on the new topology
                    sl.residents[slot] = None
                    drained.append(job)
            self.queue[:0] = drained
            telemetry.gauge("queue_depth", float(len(self.queue)))
            # compiled programs, canonical statics and filler carries
            # are pinned to the lost topology: rebuild from scratch
            self.cache = ProgramCache()
            for job in self.jobs.values():
                job.cm = None
            for sl in self._slices:
                sl.warmed.clear()
                sl.stack = sl.X = sl.B = sl.K = None
                sl.active = None
                sl.dirty = True
            self._fillers.clear()
            if devices is None or int(devices) <= 1:
                self.mesh = None
            else:
                from ..parallel.sharding import (chain_submesh_size,
                                                 make_mesh)

                try:
                    mesh = make_mesh(int(devices))
                    nc = chain_submesh_size(mesh)
                    if nc > 1 and self.slots % nc:
                        mesh = None   # tenant axis no longer divides
                    self.mesh = mesh
                except Exception:
                    self.mesh = None  # survivors can't form a mesh
            self._engine.recarve(self.mesh)

    def evacuate_slice(self, sl) -> None:
        """Slice-scoped device-loss recovery: the lost slice's
        residents checkpoint their intact host rows and requeue at the
        head; only THIS slice's warmed programs and stacked carries
        drop.  The shared :class:`~.engine.ProgramCache`, the jobs'
        grafted programs and every other slice's state stay untouched —
        survivors keep dispatching their already-warm programs without
        a single retrace, and their streams (pure in the tenant
        identity) stay bitwise."""
        with otrace.span("serve.evacuate_slice", slice=sl.slice_id,
                         jobs=sl.live()):
            sl.plan.migrating()
            drained = []
            for slot, job in enumerate(sl.residents):
                if job is None:
                    continue
                job.checkpoint()
                job.set_state("queued")
                sl.residents[slot] = None
                drained.append(job)
            self.queue[:0] = drained
            telemetry.gauge("queue_depth", float(len(self.queue)))
            sl.warmed.clear()
            sl.stack = sl.X = sl.B = sl.K = None
            sl.active = None
            sl.dirty = True
            self._slice_gauges()

    def _slice_loss(self, sl, exc, defer_backoff) -> bool:
        """The supervised slice-loss path: budget check (typed terminal
        :class:`~.placement.PlacementError` when more than
        ``replace_max`` losses land within ``replace_window``),
        slice-scoped evacuation, deterministic per-slice backoff."""
        sl.plan.migrating()
        try:
            retry = self._engine.note_loss(sl)
        except PlacementError as perr:
            # budget exhausted: the slice parks failed, its jobs park
            # failed with verified checkpoints intact (resubmit after
            # operator intervention) — the typed terminal report
            sl.plan.fail()
            for slot, job in enumerate(sl.residents):
                if job is None:
                    continue
                job.checkpoint()
                job.failure = (f"slice {sl.slice_id} re-place budget "
                               f"exhausted: {exc}")
                job.set_state("failed")
                sl.residents[slot] = None
            sl.dirty = True
            self._slice_gauges()
            raise perr from exc
        self._evacuations += 1
        telemetry.incr("device_evacuations")
        self.evacuate_slice(sl)
        delay = supervisor.backoff_delay(
            retry, base=self.backoff_base, jitter=0.0,
            seed=self.service_seed + sl.slice_id)
        if defer_backoff:
            self._pending_backoff = float(delay)
        else:
            time.sleep(delay)
        return True

    # -- rebalancing ---------------------------------------------------------

    def _vacate_slice(self, sl, reason):
        """Drain one slice's residents through VERIFIED checkpoints and
        requeue them — the rebalance prerequisite.  Like the
        standing-model migrations there is never a torn hybrid: the
        geometry only mutates after every affected job is resumable
        from its own verified directory, and the in-memory layout is
        ephemeral (a restart sees only per-job checkpoints)."""
        from ..runtime import integrity

        with otrace.span("serve.vacate", slice=sl.slice_id,
                         reason=reason, jobs=sl.live()):
            for slot, job in enumerate(sl.residents):
                if job is None:
                    continue
                job.set_state("draining")
                job.checkpoint()
                res = integrity.verify(job.store.outdir)
                if not res["ok"]:
                    integrity.rollback(job.store.outdir)
                job.set_state("queued")
                sl.residents[slot] = None
                self.queue.append(job)
            sl.plan.draining()
            sl.plan.drained()
            sl.warmed.clear()
            sl.stack = sl.X = sl.B = sl.K = None
            sl.active = None
            sl.dirty = True
            telemetry.gauge("queue_depth", float(len(self.queue)))

    def split_slice(self, slice_id, *, slots=None, chains=None):
        """Rebalance: split one slice into two (load shifted toward
        more, smaller groups).  Residents drain through verified
        checkpoints FIRST, then the geometry mutates; the drained jobs
        re-admit onto the new slices and replay bit-exactly (streams
        are pure in the tenant identity).  Returns the new slices."""
        sl = self._engine.slice_by_id(slice_id)
        if sl is None:
            raise PlacementError(f"split: unknown slice {slice_id}",
                                 slice_id=slice_id)
        self._vacate_slice(sl, "split")
        parts = self._engine.split_slice(slice_id, slots=slots,
                                         chains=chains)
        self.slots = self._engine.total_slots
        return parts

    def merge_slices(self, a_id, b_id):
        """Rebalance: merge two adjacent slices (load shifted toward
        one wider group).  Same verified-checkpoint ordering as
        :meth:`split_slice`.  Returns the merged slice."""
        for sid in (a_id, b_id):
            sl = self._engine.slice_by_id(sid)
            if sl is None:
                raise PlacementError(f"merge: unknown slice {sid}",
                                     slice_id=sid)
            self._vacate_slice(sl, "merge")
        merged = self._engine.merge_slices(a_id, b_id)
        self.slots = self._engine.total_slots
        return merged

    def drain_job(self, job_id, reason="request") -> bool:
        """Per-request drain of ONE job through its verified
        checkpoint — the gateway's deadline-propagation path.  The job
        leaves its slot/queue position at this chunk boundary (the
        slot swaps to an inert filler at the next restack) and parks
        resumable in state ``queued``; every co-resident keeps running
        untouched, and nothing is ever hard-killed.  Returns True when
        the job was drained, False when it is unknown or already
        terminal."""
        from ..runtime import integrity

        job = self.jobs.get(job_id)
        if job is None or job.state in ("done", "failed"):
            return False
        if job.state == "quarantined" and job.failure:
            return False            # terminally parked: stays parked
        if job in self.queue:
            self.queue.remove(job)
            telemetry.gauge("queue_depth", float(len(self.queue)))
        for sl in self._slices:
            for slot, res in enumerate(sl.residents):
                if res is job:
                    sl.residents[slot] = None
                    sl.dirty = True
        if job.store is None:
            # never admitted: nothing on disk to verify, nothing held
            otrace.instant("serve.drain_job", job=job_id, reason=reason)
            return True
        with otrace.span("serve.drain_job", job=job_id, reason=reason):
            job.set_state("draining")
            job.checkpoint()
            res = integrity.verify(job.store.outdir)
            if not res["ok"]:
                integrity.rollback(job.store.outdir)
            job.set_state("queued")     # resumable, not failed
        return True

    def append_job(self, pta, niter, *, parent_id=None,
                   parent_outdir=None, job_id=None, outdir=None,
                   dataset_sha256=None, journaled=False) -> Job:
        """Standing-model append: supersede a parent job with a child
        generation warm-started from its verified checkpoint lineage.

        ``pta`` is the GROWN dataset's model (same pulsars, same mode
        count — only the TOA/basis axes may have grown; anything else
        is a typed refusal from the migration planner).  The fork
        source is the newest VERIFIED generation at or above the
        parent's checkpoint dir (``lineage.resolve_verified`` — a
        corrupted parent degrades to its newest verified ancestor), the
        child is re-keyed by generation so streams never cross, and the
        whole operation is idempotent: a replay finds the forked child
        on disk (or the already-registered job) and just returns it.

        A live parent is drained through its verified checkpoint first
        and parked dormant (the supersede pattern — it never re-enters
        the queue); terminal parents fork from whatever their directory
        holds.  ``journaled=True`` tells the migration ticket the
        caller (the gateway) made the forking intent durable before
        calling — the service-level path goes planned → forked
        directly.  On a multi-slice service the child routes by its
        GROUP like any admission: it lands on the slice hosting its
        (bucket, signature), or claims an empty slice — never "the
        active group" (there is no global one).  Raises
        :class:`~.buckets.BucketOverflow` (hint attached) when no
        bucket covers the grown shape, and
        :class:`~..runtime.lineage.LineageError` when no generation of
        the parent verifies.
        """
        from ..runtime import lineage

        parent = self.jobs.get(parent_id) if parent_id else None
        if parent_outdir is None:
            if parent is None:
                raise ValueError(
                    f"append_job: unknown parent job {parent_id!r} and "
                    "no parent_outdir given")
            parent_outdir = parent.outdir
        if job_id is None:
            job_id = f"job{len(self.jobs):04d}"
        existing = self.jobs.get(job_id)
        if existing is not None:
            return existing         # replayed append: one child job
        if parent is not None and parent.state not in ("done", "failed"):
            self.drain_job(parent_id, reason="superseded")
        src, lin_report = lineage.resolve_verified(parent_outdir)
        src_man = lineage.read_manifest(src)
        pserve = src_man.get("serve") or {}
        parent_gen = int((src_man.get("lineage") or {})
                         .get("generation", 0))
        generation = parent_gen + 1
        tenant_id = int(pserve.get("tenant_id",
                                   parent.tenant_id if parent else 0))
        if pserve.get("bucket"):
            pbucket = BucketSpec(*(int(v) for v in pserve["bucket"]))
        elif parent is not None and parent.bucket is not None:
            pbucket = parent.bucket
        else:
            raise lineage.LineageError(
                f"{src}: checkpoint records no bucket (serve section "
                "missing) — cannot plan a migration from it")
        retained = int(src_man.get("rows", 0))
        if int(niter) < retained:
            raise ValueError(
                f"append_job: child niter {int(niter)} is below the "
                f"parent's {retained} retained rows — the child "
                "continues the parent, it cannot un-record rows")
        shape = probe_shape(pta)
        plan = plan_migration(self.table, pbucket, shape)
        ticket = MigrationTicket(job_id, plan=plan)
        if journaled:
            ticket.journaled()
        if outdir is None:
            outdir = self.root / job_id
        try:
            transform = None
            if not plan.in_place:
                p_old, _, b_old, _ = plan.parent_bucket.as_tuple()
                p_new, _, b_new, _ = plan.child_bucket.as_tuple()

                def transform(stage, _man):
                    repad_checkpoint(stage, p_old, b_old, p_new, b_new)

            child_man = lineage.fork_generation(
                src, outdir,
                dataset_sha256=dataset_sha256,
                bucket=plan.child_bucket.as_tuple(),
                serve_extra={"serve": {
                    "job_id": job_id,
                    "tenant_id": tenant_id,
                    "niter": int(niter),
                    "bucket": list(plan.child_bucket.as_tuple()),
                    "state": "queued",
                    "generation": generation,
                    "pulsars": [str(p) for p in pta.pulsars],
                }},
                transform=transform,
                adapt_overrides={
                    "generation": np.asarray(generation, np.int64)})
            ticket.forked()
            faults.fire("migrate.pre_readmit", row=retained,
                        outdir=outdir)
            child = self.submit(pta, int(niter), job_id=job_id,
                                tenant_id=tenant_id, outdir=outdir,
                                generation=generation,
                                lineage=child_man.get("lineage"))
            child.bucket = plan.child_bucket
            ticket.readmitted()
        except Exception:
            ticket.abort()
            raise
        telemetry.incr("migrations")
        otrace.instant("serve.append_job", job=job_id,
                       parent=str(parent_id or parent_outdir),
                       generation=generation, kind=plan.kind,
                       retained=retained,
                       degraded=int(len(lin_report) > 1))
        return child

    def step_supervised(self, defer_backoff=False) -> bool:
        """One scheduling round under the recovery ladder: runs
        :meth:`step` and absorbs the retryable failure classes the
        supervisor taxonomy allows — a device loss attributed to one
        slice (multi-slice service) evacuates and re-places ONLY that
        fault domain under its capped budget; an unattributed loss
        evacuates the whole service onto the surviving submesh (up to
        ``evac_max``); device/crash/stall classes revert every resident
        to its verified checkpoint and back off deterministically (up
        to ``max_retries``).  ``user``/``unknown`` errors (including
        the typed :class:`~.placement.PlacementError` budget trip),
        exhausted budgets and ``Preempted`` re-raise.  Returns False
        when there was nothing to run — both :meth:`run` and the
        gateway scheduler thread are thin loops over this, so
        in-process and network-fronted serving share one recovery path.

        ``defer_backoff=True`` parks the retry delay in
        :meth:`take_backoff` instead of sleeping inline — the gateway
        steps under its handler-shared condition lock, and a backoff
        slept there would block every request for its duration."""
        try:
            return self.step()
        except preemption.Preempted:
            raise
        except faults.DeviceLost as exc:
            sid = getattr(exc, "slice_id", None)
            if sid is not None and len(self._slices) > 1:
                sl = self._engine.slice_by_id(sid)
                if sl is not None:
                    return self._slice_loss(sl, exc, defer_backoff)
            if self._evacuations >= self.evac_max:
                raise
            self._evacuations += 1
            telemetry.incr("device_evacuations")
            self.evacuate(exc.devices)
            return True
        except Exception as exc:                 # noqa: BLE001
            cls = supervisor.classify_failure(exc)
            if cls in ("user", "unknown") \
                    or self._retries >= self.max_retries:
                raise
            self._retries += 1
            telemetry.incr("retries")
            delay = supervisor.backoff_delay(
                self._retries, base=self.backoff_base, jitter=0.0,
                seed=self.service_seed)
            if defer_backoff:
                self._pending_backoff = float(delay)
            else:
                time.sleep(delay)
            self._revert_residents()
            return True

    def take_backoff(self) -> float:
        """Read-and-clear the deferred retry delay from the last
        ``step_supervised(defer_backoff=True)`` round (0.0 when none):
        the caller sleeps it outside whatever lock it steps under."""
        delay, self._pending_backoff = self._pending_backoff, 0.0
        return delay

    def run(self) -> dict:
        """Drive every submitted job to done/failed.  Retries
        retryable step failures (device/crash/stall classes) with
        deterministic backoff after reverting residents to their
        checkpoints; evacuates the lost slice (or the whole service)
        on device loss under the capped budgets; re-raises ``user``
        errors and ``Preempted``."""
        while True:
            worked = self.step_supervised()
            if not worked:
                if not self.queue:
                    break
                # every queued job is deferred (quarantine cooldown or
                # compile storm): idle briefly instead of hot-spinning
                # until a breaker's half-open probe comes due
                time.sleep(0.005)
        return self.report()

    def prometheus(self) -> str:
        """Prometheus text-format exposition of the process telemetry
        registry — counters (``_total``) and gauges, labels preserved,
        including the per-job ``serve_ess_per_sec`` /
        ``serve_rhat_max`` / ``serve_accept_rate`` SLO series and the
        slice-labeled ``serve_slice_*`` fault-domain series."""
        from ..obs import metrics

        return metrics.render_telemetry()

    def report(self) -> dict:
        jobs = {jid: {"state": j.state, "it": int(j.it),
                      "tenant_id": int(j.tenant_id),
                      "retries": int(j.retries),
                      "quarantines": int(j.quarantines),
                      "failure": j.failure,
                      "time_to_first_sample_ms":
                          j.time_to_first_sample_ms()}
                for jid, j in self.jobs.items()}
        from ..parallel.sharding import mesh_layout

        out = {
            "jobs": jobs,
            "chunks": int(self.global_chunk),
            "evictions": int(self._evictions),
            "compile_stalls": int(self._compile_stalls),
            "warm_hit_rate": self.cache.warm_hit_rate(),
            "service_retries": int(self._retries),
            "quarantines": int(self._quarantines),
            "quarantine_log": list(self._quarantine_log),
            "evacuations": int(self._evacuations),
            "breakers": {t: b.snapshot()
                         for t, b in self._breakers.items()},
            "admission": (None if self._admission is None
                          else self._admission.snapshot()),
            "mesh": mesh_layout(self.mesh),
            "placement": {
                "slices": self._engine.report(),
                "groups": {
                    str(tuple(b.as_tuple())): {
                        "hits": int(h), "misses": int(m),
                        "warm_hit_rate": (h / (h + m)) if (h + m)
                        else 0.0}
                    for b, (h, m) in self._group_warmth.items()},
                "max_concurrent_groups": int(self._max_groups),
                "prewarms": int(self._prewarms),
                "replace_max": int(self._engine.replace_max),
                "replace_window": float(self._engine.replace_window),
            },
            "gauges": telemetry.gauges(),
        }
        if self._stage_agg is not None:
            out["stage_summary"] = self._stage_agg.summary()
        return out

    def close(self) -> None:
        """Detach the service's trace observers (perf aggregator); the
        program cache and checkpoints stay for a warm successor."""
        if self._stage_agg is not None:
            self._stage_agg.uninstall()
            self._stage_agg = None
