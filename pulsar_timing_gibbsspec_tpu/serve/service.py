"""Fair-share scheduler: requests → batch rows of one compiled sweep.

The service owns T resident *slots* (the tenant/vmap axis width of the
compiled program, fixed at construction so occupancy changes never
change shapes), a FIFO queue, and the :class:`~.engine.ProgramCache`.
Each :meth:`step` runs one multiplexed chunk for the resident jobs,
with admission/eviction strictly *between* chunks:

- **admission** fills free slots from the queue head.  All residents
  must share one (bucket, model-signature) program; a queued job that
  routes elsewhere waits until the current group drains (its compile
  still happens once, at first consideration, and is cached).
- **fair share** when the queue is non-empty, a resident that has held
  its slot for ``quantum`` chunks is checkpointed and requeued
  (``tenant_evictions`` gauge) — no request can starve the queue.
- **empty slots** carry an inert filler row (the bucket's canonical
  model with a fixed filler stream): rows are mathematically
  independent under vmap, so fillers cost compute but never touch a
  tenant's values, and the program never retraces for occupancy.

Failure handling maps onto the supervisor taxonomy
(``runtime/supervisor.classify_failure``) with per-row blast-radius
isolation as the organizing principle — tenant rows are independent
conditional chains under vmap, so one bad tenant must never perturb a
neighbor's bits:

- **quarantine** — the jitted chunk returns a per-tenant-row health
  vector (finite / move_frac / rho_ok, ``runtime.sentinels``); a row
  breach QUARANTINES only that job: its poisoned rows never reach the
  host buffers, the slot swaps to an inert filler at the next chunk
  boundary, the job restarts from its own verified checkpoint under a
  capped per-job budget (``quarantine_max``), and every co-resident
  keeps running untouched (proven bitwise in tests/test_quarantine.py).
  Budget exhausted → the job parks terminally in state ``quarantined``
  with the marker in its manifest (``integrity.load_resume`` then
  refuses the directory without ``force_requeue``).
- **circuit breakers** — with ``breaker=`` configured, each tenant
  gets a failure-rate breaker (``runtime.supervisor.CircuitBreaker``):
  open tenants are rejected at :meth:`~SamplerService.submit` (typed
  :class:`~..runtime.supervisor.CircuitOpen`) and their quarantined
  jobs wait out the cooldown before the half-open probe re-admits.
- **admission control** — with ``admission=`` configured, submissions
  are gated on ``queue_depth`` backpressure and cold dataset shapes
  are DEFERRED during a compile storm
  (``runtime.supervisor.AdmissionController``, driven by the
  ``compile_stalls``/``queue_depth``/``time_to_first_sample_ms``
  gauges the service already publishes).
- **device loss** — ``faults.DeviceLost`` triggers
  :meth:`~SamplerService.evacuate`: every resident checkpoints its
  intact host rows, programs rebuild on the surviving submesh, and the
  jobs re-admit — same recovery shape as ``reshard_restore`` for the
  single-tenant driver, applied per job.
- **whole-step failures** — device/crash classes still retry the whole
  step with deterministic backoff after reverting every resident to
  its verified checkpoint; ``user`` errors re-raise immediately.  A
  preemption drain (``runtime/preemption``) checkpoints every resident
  to a verified set, marks the drain, and raises
  :class:`~..runtime.preemption.Preempted` (``EXIT_PREEMPTED=75``
  semantics preserved per job).

Chaos seams: ``faults.fire("serve.chunk", row=<global chunk>)`` runs
before every dispatch; ``faults.tenant_evict_request`` forces an
eviction (per-tenant targetable); ``faults.poison_tenant_rows`` NaN-
poisons one tenant's chunk rows — the drills in
``tools/chaos_probe.py`` and the seeded campaign in
``tools/chaos_campaign.py``.
"""

from __future__ import annotations

import time
from pathlib import Path

import numpy as np

from ..obs import trace as otrace
from ..runtime import faults, preemption, supervisor, telemetry
from .buckets import (BucketOverflow, BucketSpec, BucketTable,
                      plan_migration, probe_shape)
from .engine import ProgramCache, compile_bucket, stack_cms
from .jobs import Job, MigrationTicket, repad_checkpoint

#: tenant index of the inert filler stream (far above any real tenant)
FILLER_TENANT = 0x7FFFFFFF

#: fold-salt for standing-model generations: a child generation's key
#: is ``fold_in(tenant_key, _GEN_SALT + generation)`` so generation g
#: of tenant t never collides with iteration folds (small ints) or
#: with another tenant's stream; generation 0 folds nothing (bitwise
#: backward compatibility with every pre-lineage checkpoint)
_GEN_SALT = 0x67656E


class SamplerService:
    """Resident multi-tenant sampler over one device program.

    ``slots`` is the tenant-axis width (compiled once per bucket);
    ``chunk`` the sweeps per dispatch; ``save_every`` the checkpoint
    cadence in chunks; ``quantum`` the fair-share slice in chunks.

    ``mesh`` (optional) places the service on a device mesh: on a 2-d
    ``(chain, pulsar)`` mesh the tenant axis IS the chain axis —
    ``slots`` must divide over it, the stacked per-tenant carries are
    committed with ``parallel.sharding.shard_carry`` (rows are
    mathematically independent under vmap, so tenant traffic never
    crosses the chain axis), and :meth:`report` records the layout.
    Placement never touches a tenant's PRNG stream and mesh-placed
    runs are deterministic (bitwise across incarnations on the same
    mesh, so checkpoint replay stays exact); against the UNPLACED
    service the values agree at the f64 reduction-order class — GSPMD
    regroups within-sweep reductions for the per-shard program — not
    bitwise (tests/test_serve.py).
    """

    def __init__(self, root, table: BucketTable, *, slots=2, chunk=4,
                 save_every=1, quantum=8, service_seed=0, max_retries=2,
                 backoff_base=0.0, cache: ProgramCache | None = None,
                 mesh=None, ensemble=False, pt_ladder=1, perf=False,
                 quarantine_max=2, breaker=None, admission=None,
                 evac_max=2, clock=time.monotonic):
        # the multiplexed chunk is vmap(sharded_sweep_step) over the
        # TENANT axis — rows are unrelated analyses, so any cross-chain
        # ensemble stage (stretch pairing, tempering swaps) would couple
        # tenants.  The kwargs exist only to reject the request loudly
        # at the service boundary instead of silently ignoring it.
        if ensemble or int(pt_ladder) > 1:
            raise ValueError(
                "ensemble moves / parallel tempering are not available "
                "in the multiplexed service: tenant rows share the "
                "chain axis and interchain moves would mix unrelated "
                "analyses.  Run ensemble sampling through the "
                "single-tenant driver (JaxGibbsDriver(ensemble=True))")
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.table = table
        self.slots = int(slots)
        self.mesh = mesh
        if mesh is not None:
            from ..parallel.sharding import chain_submesh_size

            nc = chain_submesh_size(mesh)
            if nc > 1 and self.slots % nc:
                raise ValueError(
                    f"slots={self.slots} does not divide over the "
                    f"mesh's chain axis ({nc} devices, mesh "
                    f"{tuple(mesh.devices.shape)}): the tenant axis is "
                    "the chain axis on a 2-d serving mesh — pass slots "
                    f"as a multiple of {nc} (e.g. slots="
                    f"{-(-self.slots // nc) * nc}) or shrink the chain "
                    "axis with make_mesh((n_chain, n_pulsar))")
        self.chunk = int(chunk)
        self.save_every = max(1, int(save_every))
        self.quantum = max(1, int(quantum))
        self.service_seed = int(service_seed)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)

        # a caller-supplied cache lets a successor service (warm restart
        # in the same process) reuse the predecessor's compiled programs
        self.cache = ProgramCache() if cache is None else cache
        self.jobs: dict[str, Job] = {}
        self.queue: list[Job] = []
        self.residents: list[Job | None] = [None] * self.slots
        self.global_chunk = 0
        self._active = None          # (bucket, signature) of residents
        self._dirty = True           # membership changed since last stack
        self._stack = None
        self._X = self._B = self._K = None
        self._warmed: set = set()    # (chunk, active) combos already compiled
        self._fillers: dict = {}     # active-key -> (x, b) host filler state
        self._diags: dict = {}       # job_id -> (RollingDiag, channel idx)
        self._evictions = 0
        self._compile_stalls = 0
        self._next_tenant = 0
        self._retries = 0
        self._pending_backoff = 0.0

        # blast-radius isolation: per-job quarantine budget, per-tenant
        # circuit breakers, service-level admission control, and the
        # device-loss evacuation budget.  ``breaker``/``admission``
        # accept True (defaults) or a kwargs dict; ``clock`` is
        # injectable so breaker cooldowns are testable without sleeping
        self.quarantine_max = int(quarantine_max)
        self.evac_max = int(evac_max)
        self._clock = clock
        self._breaker_cfg = ({} if breaker is True else breaker)
        self._breakers: dict[int, supervisor.CircuitBreaker] = {}
        if admission is True:
            admission = {}
        self._admission = None if admission is None else \
            supervisor.AdmissionController(clock=clock, **admission)
        self._quarantines = 0
        self._evacuations = 0
        self._quarantine_log: list[dict] = []

        # perf=True hangs the streaming stage aggregator off the trace
        # seams: every serve.prepare/dispatch/d2h/writeback span folds
        # into dispatch_ms{stage=...,job="svc"} gauges that prometheus()
        # scrapes live — no per-chunk work beyond the span it already
        # emits, and nothing traced (sampling stays bitwise identical)
        self._stage_agg = None
        if perf:
            from ..obs.perf import StageAggregator

            self._stage_agg = StageAggregator(job="svc").install()

    # -- request intake -----------------------------------------------------

    def submit(self, pta, niter, job_id=None, tenant_id=None,
               outdir=None, generation=0, lineage=None) -> Job:
        """Queue an analysis request.  ``tenant_id`` (with the service
        seed, and the ``generation`` counter for forked standing-model
        generations) IS the PRNG identity — pass the original values to
        readmit a job in a fresh process, or leave None for a new
        stream.

        Raises :class:`~..runtime.supervisor.CircuitOpen` when admission
        control rejects on queue-depth backpressure, or when the
        tenant's circuit breaker is open (a tenant whose uploads keep
        poisoning rows must wait out the cooldown)."""
        if self._admission is not None:
            self._admission.admit_submission(len(self.queue))
        if job_id is None:
            job_id = f"job{len(self.jobs):04d}"
        if job_id in self.jobs:
            raise ValueError(f"duplicate job_id {job_id!r}")
        if tenant_id is None:
            tenant_id = self._next_tenant
        br = self._breakers.get(int(tenant_id))
        if br is not None:
            br.check(f"tenant {int(tenant_id)}")
        self._next_tenant = max(self._next_tenant, int(tenant_id) + 1)
        if outdir is None:
            outdir = self.root / job_id
        job = Job(job_id=job_id, pta=pta, niter=int(niter),
                  tenant_id=int(tenant_id), outdir=str(outdir),
                  generation=int(generation),
                  lineage=dict(lineage) if lineage else None)
        self.jobs[job_id] = job
        self.queue.append(job)
        telemetry.gauge("queue_depth", float(len(self.queue)))
        return job

    # -- PRNG / state derivation -------------------------------------------

    def _service_key(self):
        import jax.random as jr

        return jr.key(self.service_seed)

    def _tenant_key(self, tenant_id, generation=0):
        """Tenant base key; a forked standing-model generation folds
        its counter (salted, so generation 1 never collides with a
        sibling tenant id) on top.  Generation 0 keeps the historical
        key exactly — every pre-lineage checkpoint replays bitwise."""
        import jax.random as jr

        k = jr.fold_in(self._service_key(), int(tenant_id))
        if int(generation):
            k = jr.fold_in(k, _GEN_SALT + int(generation))
        return k

    def _init_key(self, tenant_id, generation=0):
        """Reserved iteration-0 key for the fresh-tenant b draw."""
        import jax.random as jr

        return jr.fold_in(
            jr.fold_in(self._tenant_key(tenant_id, generation), 0), 0)

    def _x0(self, job) -> np.ndarray:
        """Deterministic per-(service_seed, tenant, generation) initial
        state — part of the stream identity, so solo and multiplexed
        runs agree."""
        seq = [self.service_seed, int(job.tenant_id)]
        if int(job.generation):
            seq.append(_GEN_SALT + int(job.generation))
        rng = np.random.default_rng(seq)
        return np.asarray(job.pta.initial_sample(rng), np.float64)

    # -- admission / eviction ----------------------------------------------

    def _route(self, job) -> bool:
        """Route only (cheap — no compile): sets ``job.bucket``.
        Returns False after marking the job failed on overflow."""
        if job.bucket is not None:
            return True
        try:
            job.bucket = self.table.route(probe_shape(job.pta))
        except BucketOverflow as exc:
            job.failure = f"overflow: {exc}"
            job.set_state("failed")
            return False
        return True

    def _prepare(self, job) -> bool:
        """Route + compile + graft (idempotent; cached on the job).
        Returns False after marking the job failed on a routing error."""
        if job.cm is not None:
            return True
        job.set_state("warming")
        if not self._route(job):
            return False
        from ..analysis import guards

        # staging a new dataset compiles small host->device programs;
        # mark them planned so retrace accounting only sees the sweep
        with guards.planned_compile(), \
                otrace.span("serve.prepare", job=job.job_id,
                            tenant=int(job.tenant_id)):
            cm = compile_bucket(job.pta, job.bucket)
            cm, warm = self.cache.adopt(job.bucket, cm)
        job.cm = cm
        if not warm:
            self._compile_stalls += 1
            telemetry.gauge("compile_stalls", float(self._compile_stalls))
            if self._admission is not None:
                self._admission.note_compile()
        telemetry.gauge("warm_hit_rate", self.cache.warm_hit_rate())
        return True

    def _group_key(self, job):
        from .engine import model_signature

        return (job.bucket, model_signature(job.cm))

    def _admit(self, job, slot):
        import jax.numpy as jnp

        from ..analysis import guards

        job.set_state("warming")
        cm = job.cm
        if job.chain is None:
            job.alloc(cm.nx, cm.P * cm.Bmax)
        if job.store is None:
            job.open_store()
            if not job.try_resume():
                job.x = self._x0(job)
                with guards.planned_compile():
                    b = self.cache.init_fn()(
                        cm, jnp.asarray(job.x, cm.cdtype),
                        self._init_key(job.tenant_id, job.generation))
                job.b = np.asarray(b, np.float64)
        job.chunks_resident = 0
        job.admitted_at = time.monotonic()
        self.residents[slot] = job
        job.set_state("sampling")
        self._dirty = True

    def _evict(self, slot, reason):
        job = self.residents[slot]
        job.checkpoint()
        job.set_state("queued")
        self.residents[slot] = None
        self.queue.append(job)
        self._evictions += 1
        telemetry.gauge("tenant_evictions", float(self._evictions))
        telemetry.gauge("queue_depth", float(len(self.queue)))
        self._dirty = True

    def _tenant_breaker(self, tenant_id, create=False):
        """The tenant's circuit breaker (None when breakers are off)."""
        if self._breaker_cfg is None:
            return None
        br = self._breakers.get(int(tenant_id))
        if br is None and create:
            br = self._breakers[int(tenant_id)] = \
                supervisor.CircuitBreaker(clock=self._clock,
                                          **self._breaker_cfg)
        return br

    def _quarantine(self, slot, why):
        """Blast-radius isolation for one poisoned row: drop the job
        from its slot (an inert filler swaps in at the restack — the
        next chunk boundary), discard the poisoned chunk (it never
        reached the host buffers), and restart the job from its own
        verified state — in-memory ``(x, b, it)`` still hold the last
        clean chunk's end, which the checkpoint here persists.  Every
        co-resident keeps running untouched: rows are independent under
        vmap and their writeback proceeds in the same loop.

        Within the ``quarantine_max`` budget the job requeues (state
        ``quarantined``; its breaker gates re-admission).  Budget
        exhausted → the job parks terminally with the quarantine marker
        in its manifest: a deterministic replay that breaches again
        will breach forever, and ``integrity.load_resume`` refuses the
        directory until an operator passes ``force_requeue``.
        """
        job = self.residents[slot]
        job.quarantines += 1
        self._quarantines += 1
        telemetry.incr("sentinel_trips")
        telemetry.incr("quarantines")
        telemetry.gauge("quarantined_jobs", float(sum(
            1 for j in self.jobs.values() if j.state == "quarantined") + 1))
        self._quarantine_log.append({
            "job_id": job.job_id, "tenant_id": int(job.tenant_id),
            "chunk": int(self.global_chunk), "why": why,
            "count": int(job.quarantines)})
        br = self._tenant_breaker(job.tenant_id, create=True)
        if br is not None:
            br.record_failure()
        self.residents[slot] = None
        self._dirty = True
        otrace.instant("serve.quarantine", job=job.job_id,
                       tenant=int(job.tenant_id), why=why,
                       count=int(job.quarantines))
        if job.quarantines > self.quarantine_max:
            job.failure = (f"quarantined: {why} — budget exhausted "
                           f"({job.quarantines - 1} replays); "
                           "resume requires force_requeue")
            job.set_state("quarantined")
            job.checkpoint()    # manifest carries the quarantine marker
            return
        # verified checkpoint of the clean prefix, THEN the state flip:
        # the pending-requeue manifest must stay resumable by a fresh
        # incarnation without the operator override
        job.checkpoint()
        job.set_state("quarantined")
        self.queue.append(job)
        telemetry.gauge("queue_depth", float(len(self.queue)))

    def _admissions(self):
        """Fill free slots from the queue head, constrained to one
        (bucket, signature) group at a time.  A quarantined job waits
        for its tenant's breaker (half-open probe after the cooldown);
        during a compile storm, cold dataset shapes are deferred so a
        burst of novel buckets cannot serialize warm tenants behind
        back-to-back XLA compiles."""
        if not any(self.residents):
            self._active = None
        for slot in range(self.slots):
            if self.residents[slot] is not None:
                continue
            take = None
            for job in self.queue:
                if job.state == "quarantined":
                    # non-consuming gate: the half-open probe must only
                    # be claimed when the job is actually admitted — a
                    # group-key mismatch after allow() would strand the
                    # breaker half-open with its probe spent, starving
                    # the tenant forever
                    br = self._tenant_breaker(job.tenant_id)
                    if br is not None and not br.would_allow():
                        continue        # wait out the cooldown
                if (self._admission is not None and job.cm is None):
                    if not self._route(job):
                        continue        # failed routing; skip
                    if self._admission.defer_cold(
                            self.cache.has_bucket(job.bucket)):
                        continue        # compile storm: hold cold shapes
                if not self._prepare(job):
                    continue            # failed routing; skip
                key = self._group_key(job)
                if self._active is None:
                    self._active = key
                if key == self._active:
                    take = job
                    break
            if take is None:
                break
            if take.state == "quarantined":
                br = self._tenant_breaker(take.tenant_id)
                if br is not None and not br.allow():
                    break   # probe raced away; retry next round
            self.queue.remove(take)
            self.queue[:] = [j for j in self.queue
                             if j.state != "failed"]
            telemetry.gauge("queue_depth", float(len(self.queue)))
            self._admit(take, slot)
        # drop failed-routing jobs that never got picked
        self.queue[:] = [j for j in self.queue if j.state != "failed"]

    # -- filler rows --------------------------------------------------------

    def _filler_state(self, canon):
        """Host (x, b) for the inert filler stream of the active group
        (prior-midpoint state, reserved-iteration b draw)."""
        key = self._active
        got = self._fillers.get(key)
        if got is not None:
            return got
        import jax.numpy as jnp

        from ..analysis import guards

        pa = np.asarray(canon.pa, np.float64)
        pb = np.asarray(canon.pb, np.float64)
        pk = np.asarray(canon.pkind, np.int64)
        # uniform/linexp: bound midpoint; normal: the mean (pa)
        x = np.where(pk == 1, pa, 0.5 * (pa + pb))
        with guards.planned_compile():
            b = self.cache.init_fn()(
                canon, jnp.asarray(x, canon.cdtype),
                self._init_key(FILLER_TENANT))
        got = (x, np.asarray(b, np.float64))
        self._fillers[key] = got
        return got

    # -- the multiplexed chunk ---------------------------------------------

    def _build_stack(self):
        import jax.numpy as jnp

        live = [j for j in self.residents if j is not None]
        canon = self.cache.canonical(live[0].bucket, live[0].cm)
        fx, fb = self._filler_state(canon)
        cms, X, B, K = [], [], [], []
        for job in self.residents:
            if job is not None:
                cms.append(job.cm)
                X.append(job.x)
                B.append(job.b)
                K.append(self._tenant_key(job.tenant_id,
                                          job.generation))
            else:
                cms.append(canon)
                X.append(fx)
                B.append(fb)
                K.append(self._tenant_key(FILLER_TENANT))
        cdtype = canon.cdtype
        self._stack = stack_cms(cms)
        self._X = jnp.asarray(np.stack(X), cdtype)
        self._B = jnp.asarray(np.stack(B), cdtype)
        self._K = jnp.stack(K)
        if self.mesh is not None:
            from ..parallel.sharding import shard_carry

            self._X, self._B, self._K = shard_carry(
                self.mesh, (self._X, self._B, self._K), self.slots)
        self._dirty = False

    def _it0(self):
        import jax.numpy as jnp

        vals = [(j.it + 1) if j is not None else 1
                for j in self.residents]
        return jnp.asarray(vals, jnp.int32)

    def _dispatch(self):
        """One compiled multiplexed chunk; scatter rows to job buffers."""
        from ..analysis import guards

        if self._dirty:
            # membership change: restacking compiles small staging
            # programs (jnp.stack per leaf) — planned, not a retrace
            with guards.planned_compile(), otrace.span("serve.restack"):
                self._build_stack()
        mux = self.cache.mux(self.chunk)
        warm_key = (self.chunk, self._active)
        if warm_key not in self._warmed:
            with guards.planned_compile(), \
                    otrace.span("serve.compile_dispatch",
                                chunk=self.global_chunk):
                args = (self._stack, self._X, self._B, self._K,
                        self._it0())
                X, B, xs, bs, health = mux(*args)
            self._warmed.add(warm_key)
        else:
            # the zero-retrace contract lives HERE: a steady chunk with
            # a warmed (chunk, group) must compile nothing
            with otrace.span("serve.dispatch", chunk=self.global_chunk):
                X, B, xs, bs, health = mux(self._stack, self._X, self._B,
                                           self._K, self._it0())
        self._X, self._B = X, B
        with otrace.span("serve.d2h", chunk=self.global_chunk):
            # OWNED host copies, not np.asarray views: on the CPU
            # backend a view aliases the XLA output buffer of a
            # donation-aliased program, and the runtime may reclaim it
            # while the writeback loop is still reading (intermittent
            # segfault under multi-bucket churn)
            np_xs = np.array(xs, np.float64)       # (chunk, T, nx)
            np_bs = np.array(bs, np.float64)       # (chunk, T, P, Bmax)
            h_fin = np.array(health["finite"])     # (T,) per-row verdict
            h_rho = np.array(health["rho_ok"])
        # chaos seam: NaN-poison one tenant's host rows (simulated
        # single-tenant divergence — the blast-radius drill trigger)
        live = {int(j.tenant_id): (s, j.chunks_resident)
                for s, j in enumerate(self.residents) if j is not None}
        np_xs, np_bs, _poisoned = faults.poison_tenant_rows(
            np_xs, np_bs, {t: s for t, (s, _) in live.items()},
            {t: r for t, (_, r) in live.items()})
        now = time.monotonic()
        with otrace.span("serve.writeback", chunk=self.global_chunk):
            for slot, job in enumerate(self.residents):
                if job is None:
                    continue
                rows = np_xs[:, slot]
                brows = np_bs[:, slot].reshape(self.chunk, -1)
                take = min(self.chunk, job.niter - job.it)
                # the device health vector covers the whole chunk row
                # (including sweeps past the job's tail); the host check
                # covers what would actually be recorded — either way
                # the breach stays confined to THIS row
                breach = None
                if not h_fin[slot]:
                    breach = "non-finite row (device health)"
                elif not h_rho[slot]:
                    breach = "rho-bound breach (device health)"
                elif not (np.isfinite(rows[:take]).all()
                          and np.isfinite(brows[:take]).all()):
                    breach = "non-finite chunk rows (host)"
                if breach is not None:
                    self._quarantine(slot, breach)
                    continue
                job.chain[job.it:job.it + take] = rows[:take]
                job.bchain[job.it:job.it + take] = brows[:take]
                job.it += take
                job.x = rows[take - 1].copy()
                job.b = np_bs[take - 1, slot].copy()
                job.chunks_resident += 1
                if job.first_sample_at is None:
                    job.first_sample_at = now
                    telemetry.gauge("time_to_first_sample_ms",
                                    job.time_to_first_sample_ms())
                br = self._breakers.get(int(job.tenant_id))
                if br is not None:
                    br.record_success()
                self._observe_job(job, rows[:take], now)

    def _observe_job(self, job, rows, now):
        """Feed the job's live diagnostics window and publish its SLO
        gauges (labeled per job/tenant so series never collide)."""
        got = self._diags.get(job.job_id)
        if got is None:
            from ..obs.sketch import make_sketch_spec
            from ..obs.summary import RollingDiag

            ch = np.asarray(make_sketch_spec(job.cm).channels)
            got = self._diags[job.job_id] = (RollingDiag(), ch)
        diag, ch = got
        diag.observe(rows[:, ch], now)
        lab = {"job": job.job_id, "tenant": str(int(job.tenant_id))}
        telemetry.gauge("serve_ess_per_sec", diag.ess_per_sec(), **lab)
        telemetry.gauge("serve_rhat_max", diag.rhat_max(), **lab)
        telemetry.gauge("serve_accept_rate", diag.accept_rate(), **lab)

    # -- drain / recovery ---------------------------------------------------

    def _drain(self):
        """Checkpoint every resident to a verified set and raise
        ``Preempted`` — each job resumes from its own directory."""
        from ..runtime import integrity

        rows = 0
        all_ok = True
        with otrace.span("serve.drain",
                         jobs=sum(1 for j in self.residents if j)):
            for slot, job in enumerate(self.residents):
                if job is None:
                    continue
                job.set_state("draining")
                job.checkpoint()
                res = integrity.verify(job.store.outdir)
                if not res["ok"]:
                    all_ok = integrity.rollback(job.store.outdir) \
                        and all_ok
                rows += job.it
                job.set_state("queued")     # resumable, not failed
        preemption.mark_drained()
        raise preemption.Preempted(
            f"service drained {sum(1 for j in self.residents if j)} "
            f"job(s) to per-job checkpoints", rows=rows, verified=all_ok)

    def _revert_residents(self):
        """Roll every resident back to its last verified checkpoint
        (retry path: the replay from there is bit-exact)."""
        for slot, job in enumerate(self.residents):
            if job is None:
                continue
            job.it = 0
            if not job.try_resume():
                job.x = self._x0(job)
                import jax.numpy as jnp

                from ..analysis import guards

                with guards.planned_compile():
                    b = self.cache.init_fn()(
                        job.cm, jnp.asarray(job.x, job.cm.cdtype),
                        self._init_key(job.tenant_id, job.generation))
                job.b = np.asarray(b, np.float64)
        self._dirty = True

    # -- scheduler loop -----------------------------------------------------

    def step(self) -> bool:
        """One scheduling round: seam, churn, admission, one chunk,
        checkpoints.  Returns False when there is nothing to run."""
        if preemption.drain_requested() and any(self.residents):
            self._drain()
        self.global_chunk += 1
        faults.fire("serve.chunk", row=self.global_chunk)
        evict_req = faults.tenant_evict_request(
            row=self.global_chunk,
            job_rows={int(j.tenant_id): j.chunks_resident
                      for j in self.residents if j is not None})
        if evict_req:
            for slot, job in enumerate(self.residents):
                if job is None:
                    continue
                if evict_req is True:
                    # untargeted (historical): evict any one resident
                    self._evict(slot, "injected")
                    break
                if int(job.tenant_id) in evict_req:
                    self._evict(slot, "injected")
        # fair share: the longest-resident tenant yields to a non-empty
        # queue after its quantum
        if self.queue:
            held = [(j.chunks_resident, s)
                    for s, j in enumerate(self.residents) if j is not None]
            if held:
                most, slot = max(held)
                if most >= self.quantum:
                    self._evict(slot, "quantum")
        self._admissions()
        if not any(self.residents):
            return False
        self._dispatch()
        for slot, job in enumerate(self.residents):
            if job is None:
                continue
            if job.done:
                job.checkpoint()
                job.set_state("done")
                self.residents[slot] = None
                self._dirty = True
            elif job.chunks_resident % self.save_every == 0:
                job.checkpoint()
        telemetry.gauge("queue_depth", float(len(self.queue)))
        return True

    def evacuate(self, devices=None) -> None:
        """Device-loss recovery: drain every resident through its own
        verified checkpoint (the host row buffers are intact — the lost
        device only held carries and compiled programs), drop every
        device-resident artifact, rebuild on the surviving submesh and
        re-admit the drained jobs at the queue head.  The per-job
        analogue of the single-tenant ``integrity.reshard_restore``
        path: streams are pure in (service_seed, tenant_id, iteration),
        so the re-admitted jobs replay bit-identically on the new
        topology."""
        with otrace.span("serve.evacuate",
                         jobs=sum(1 for j in self.residents if j),
                         devices=devices):
            drained = []
            for slot, job in enumerate(self.residents):
                if job is None:
                    continue
                job.checkpoint()
                job.set_state("queued")
                job.cm = None          # recompile on the new topology
                self.residents[slot] = None
                drained.append(job)
            self.queue[:0] = drained
            telemetry.gauge("queue_depth", float(len(self.queue)))
            # compiled programs, canonical statics and filler carries
            # are pinned to the lost topology: rebuild from scratch
            self.cache = ProgramCache()
            for job in self.jobs.values():
                job.cm = None
            self._warmed.clear()
            self._fillers.clear()
            self._stack = self._X = self._B = self._K = None
            self._active = None
            self._dirty = True
            if devices is None or int(devices) <= 1:
                self.mesh = None
            else:
                from ..parallel.sharding import (chain_submesh_size,
                                                 make_mesh)

                try:
                    mesh = make_mesh(int(devices))
                    nc = chain_submesh_size(mesh)
                    if nc > 1 and self.slots % nc:
                        mesh = None   # tenant axis no longer divides
                    self.mesh = mesh
                except Exception:
                    self.mesh = None  # survivors can't form a mesh

    def drain_job(self, job_id, reason="request") -> bool:
        """Per-request drain of ONE job through its verified
        checkpoint — the gateway's deadline-propagation path.  The job
        leaves its slot/queue position at this chunk boundary (the
        slot swaps to an inert filler at the next restack) and parks
        resumable in state ``queued``; every co-resident keeps running
        untouched, and nothing is ever hard-killed.  Returns True when
        the job was drained, False when it is unknown or already
        terminal."""
        from ..runtime import integrity

        job = self.jobs.get(job_id)
        if job is None or job.state in ("done", "failed"):
            return False
        if job.state == "quarantined" and job.failure:
            return False            # terminally parked: stays parked
        if job in self.queue:
            self.queue.remove(job)
            telemetry.gauge("queue_depth", float(len(self.queue)))
        for slot, res in enumerate(self.residents):
            if res is job:
                self.residents[slot] = None
                self._dirty = True
        if job.store is None:
            # never admitted: nothing on disk to verify, nothing held
            otrace.instant("serve.drain_job", job=job_id, reason=reason)
            return True
        with otrace.span("serve.drain_job", job=job_id, reason=reason):
            job.set_state("draining")
            job.checkpoint()
            res = integrity.verify(job.store.outdir)
            if not res["ok"]:
                integrity.rollback(job.store.outdir)
            job.set_state("queued")     # resumable, not failed
        return True

    def append_job(self, pta, niter, *, parent_id=None,
                   parent_outdir=None, job_id=None, outdir=None,
                   dataset_sha256=None, journaled=False) -> Job:
        """Standing-model append: supersede a parent job with a child
        generation warm-started from its verified checkpoint lineage.

        ``pta`` is the GROWN dataset's model (same pulsars, same mode
        count — only the TOA/basis axes may have grown; anything else
        is a typed refusal from the migration planner).  The fork
        source is the newest VERIFIED generation at or above the
        parent's checkpoint dir (``lineage.resolve_verified`` — a
        corrupted parent degrades to its newest verified ancestor), the
        child is re-keyed by generation so streams never cross, and the
        whole operation is idempotent: a replay finds the forked child
        on disk (or the already-registered job) and just returns it.

        A live parent is drained through its verified checkpoint first
        and parked dormant (the supersede pattern — it never re-enters
        the queue); terminal parents fork from whatever their directory
        holds.  ``journaled=True`` tells the migration ticket the
        caller (the gateway) made the forking intent durable before
        calling — the service-level path goes planned → forked
        directly.  Raises :class:`~.buckets.BucketOverflow` (hint
        attached) when no bucket covers the grown shape, and
        :class:`~..runtime.lineage.LineageError` when no generation of
        the parent verifies.
        """
        from ..runtime import lineage

        parent = self.jobs.get(parent_id) if parent_id else None
        if parent_outdir is None:
            if parent is None:
                raise ValueError(
                    f"append_job: unknown parent job {parent_id!r} and "
                    "no parent_outdir given")
            parent_outdir = parent.outdir
        if job_id is None:
            job_id = f"job{len(self.jobs):04d}"
        existing = self.jobs.get(job_id)
        if existing is not None:
            return existing         # replayed append: one child job
        if parent is not None and parent.state not in ("done", "failed"):
            self.drain_job(parent_id, reason="superseded")
        src, lin_report = lineage.resolve_verified(parent_outdir)
        src_man = lineage.read_manifest(src)
        pserve = src_man.get("serve") or {}
        parent_gen = int((src_man.get("lineage") or {})
                         .get("generation", 0))
        generation = parent_gen + 1
        tenant_id = int(pserve.get("tenant_id",
                                   parent.tenant_id if parent else 0))
        if pserve.get("bucket"):
            pbucket = BucketSpec(*(int(v) for v in pserve["bucket"]))
        elif parent is not None and parent.bucket is not None:
            pbucket = parent.bucket
        else:
            raise lineage.LineageError(
                f"{src}: checkpoint records no bucket (serve section "
                "missing) — cannot plan a migration from it")
        retained = int(src_man.get("rows", 0))
        if int(niter) < retained:
            raise ValueError(
                f"append_job: child niter {int(niter)} is below the "
                f"parent's {retained} retained rows — the child "
                "continues the parent, it cannot un-record rows")
        shape = probe_shape(pta)
        plan = plan_migration(self.table, pbucket, shape)
        ticket = MigrationTicket(job_id, plan=plan)
        if journaled:
            ticket.journaled()
        if outdir is None:
            outdir = self.root / job_id
        try:
            transform = None
            if not plan.in_place:
                p_old, _, b_old, _ = plan.parent_bucket.as_tuple()
                p_new, _, b_new, _ = plan.child_bucket.as_tuple()

                def transform(stage, _man):
                    repad_checkpoint(stage, p_old, b_old, p_new, b_new)

            child_man = lineage.fork_generation(
                src, outdir,
                dataset_sha256=dataset_sha256,
                bucket=plan.child_bucket.as_tuple(),
                serve_extra={"serve": {
                    "job_id": job_id,
                    "tenant_id": tenant_id,
                    "niter": int(niter),
                    "bucket": list(plan.child_bucket.as_tuple()),
                    "state": "queued",
                    "generation": generation,
                    "pulsars": [str(p) for p in pta.pulsars],
                }},
                transform=transform,
                adapt_overrides={
                    "generation": np.asarray(generation, np.int64)})
            ticket.forked()
            faults.fire("migrate.pre_readmit", row=retained,
                        outdir=outdir)
            child = self.submit(pta, int(niter), job_id=job_id,
                                tenant_id=tenant_id, outdir=outdir,
                                generation=generation,
                                lineage=child_man.get("lineage"))
            child.bucket = plan.child_bucket
            ticket.readmitted()
        except Exception:
            ticket.abort()
            raise
        telemetry.incr("migrations")
        otrace.instant("serve.append_job", job=job_id,
                       parent=str(parent_id or parent_outdir),
                       generation=generation, kind=plan.kind,
                       retained=retained,
                       degraded=int(len(lin_report) > 1))
        return child

    def step_supervised(self, defer_backoff=False) -> bool:
        """One scheduling round under the recovery ladder: runs
        :meth:`step` and absorbs the retryable failure classes the
        supervisor taxonomy allows — device loss evacuates onto the
        surviving submesh (up to ``evac_max``), device/crash/stall
        classes revert every resident to its verified checkpoint and
        back off deterministically (up to ``max_retries``).  ``user``/
        ``unknown`` errors, exhausted budgets and ``Preempted`` re-
        raise.  Returns False when there was nothing to run — both
        :meth:`run` and the gateway scheduler thread are thin loops
        over this, so in-process and network-fronted serving share one
        recovery path.

        ``defer_backoff=True`` parks the retry delay in
        :meth:`take_backoff` instead of sleeping inline — the gateway
        steps under its handler-shared condition lock, and a backoff
        slept there would block every request for its duration."""
        try:
            return self.step()
        except preemption.Preempted:
            raise
        except faults.DeviceLost as exc:
            if self._evacuations >= self.evac_max:
                raise
            self._evacuations += 1
            telemetry.incr("device_evacuations")
            self.evacuate(exc.devices)
            return True
        except Exception as exc:                 # noqa: BLE001
            cls = supervisor.classify_failure(exc)
            if cls in ("user", "unknown") \
                    or self._retries >= self.max_retries:
                raise
            self._retries += 1
            telemetry.incr("retries")
            delay = supervisor.backoff_delay(
                self._retries, base=self.backoff_base, jitter=0.0,
                seed=self.service_seed)
            if defer_backoff:
                self._pending_backoff = float(delay)
            else:
                time.sleep(delay)
            self._revert_residents()
            return True

    def take_backoff(self) -> float:
        """Read-and-clear the deferred retry delay from the last
        ``step_supervised(defer_backoff=True)`` round (0.0 when none):
        the caller sleeps it outside whatever lock it steps under."""
        delay, self._pending_backoff = self._pending_backoff, 0.0
        return delay

    def run(self) -> dict:
        """Drive every submitted job to done/failed.  Retries
        retryable step failures (device/crash/stall classes) with
        deterministic backoff after reverting residents to their
        checkpoints; evacuates onto the surviving submesh on device
        loss (up to ``evac_max`` times); re-raises ``user`` errors and
        ``Preempted``."""
        while True:
            worked = self.step_supervised()
            if not worked:
                if not self.queue:
                    break
                # every queued job is deferred (quarantine cooldown or
                # compile storm): idle briefly instead of hot-spinning
                # until a breaker's half-open probe comes due
                time.sleep(0.005)
        return self.report()

    def prometheus(self) -> str:
        """Prometheus text-format exposition of the process telemetry
        registry — counters (``_total``) and gauges, labels preserved,
        including the per-job ``serve_ess_per_sec`` /
        ``serve_rhat_max`` / ``serve_accept_rate`` SLO series."""
        from ..obs import metrics

        return metrics.render_telemetry()

    def report(self) -> dict:
        jobs = {jid: {"state": j.state, "it": int(j.it),
                      "tenant_id": int(j.tenant_id),
                      "retries": int(j.retries),
                      "quarantines": int(j.quarantines),
                      "failure": j.failure,
                      "time_to_first_sample_ms":
                          j.time_to_first_sample_ms()}
                for jid, j in self.jobs.items()}
        from ..parallel.sharding import mesh_layout

        out = {
            "jobs": jobs,
            "chunks": int(self.global_chunk),
            "evictions": int(self._evictions),
            "compile_stalls": int(self._compile_stalls),
            "warm_hit_rate": self.cache.warm_hit_rate(),
            "service_retries": int(self._retries),
            "quarantines": int(self._quarantines),
            "quarantine_log": list(self._quarantine_log),
            "evacuations": int(self._evacuations),
            "breakers": {t: b.snapshot()
                         for t, b in self._breakers.items()},
            "admission": (None if self._admission is None
                          else self._admission.snapshot()),
            "mesh": mesh_layout(self.mesh),
            "gauges": telemetry.gauges(),
        }
        if self._stage_agg is not None:
            out["stage_summary"] = self._stage_agg.summary()
        return out

    def close(self) -> None:
        """Detach the service's trace observers (perf aggregator); the
        program cache and checkpoints stay for a warm successor."""
        if self._stage_agg is not None:
            self._stage_agg.uninstall()
            self._stage_agg = None
