"""Fault-tolerant job gateway: the network frontend of the service.

:class:`Gateway` puts a transport boundary in front of
:class:`~.service.SamplerService` without surrendering any contract
the runtime already guarantees.  Clients disconnect, retry, duplicate,
stall and lie about payload sizes; the gateway's job is to make all of
that boring:

- **Idempotent submission** — every submission carries a client-chosen
  ``dedupe_key``.  The first submission admits the job and records the
  ``dedupe_key -> (job_id, tenant_id, payload sha256)`` binding in the
  journal *before* the ACK leaves the building; a replay (client retry
  after a lost ACK — the ``conn_drop``/``dup_submit`` chaos kinds)
  returns the ORIGINAL handle instead of double-admitting
  (``dedupe_hits`` counter).  A replayed key with a different payload
  digest is a typed ``DEDUPE_MISMATCH``, never a second job.
- **Deadline propagation** — a per-request deadline
  (``X-PTGibbs-Deadline-Ms`` / ``deadline_ms``) rides into the
  scheduler loop: when it expires the job takes the existing
  per-request drain (``SamplerService.drain_job`` — verified
  checkpoint, slot freed at the chunk boundary, co-residents bitwise
  untouched; ``deadline_drains`` counter) and reports ``expired``.
  Its recorded prefix stays streamable and resumable.
- **Resumable result streams** — stream cursors ARE monotonic
  recorded-row counts, so the stream state lives in the client's
  cursor and the job's verified row buffer, not in per-connection
  server state: a disconnected client reattaches with its last cursor
  and resumes exactly where it left off — bitwise, across gateway
  restarts, because the rows come from the same deterministic chain.
  Live streams are bounded per client (``shed_lag`` rows): a consumer
  that falls further behind than the bound is SHED (typed
  ``STREAM_SHED`` final event, ``shed_streams`` counter) — the
  sampling loop never blocks on a slow socket.
- **Graceful drain** — SIGTERM (via ``runtime.preemption``; the
  gateway polls ``drain_requested`` like every other loop) stops
  admissions (typed ``DRAINING``), drains residents through the PR 4
  preemption path, persists the journal, and parks.  A restarted
  gateway reloads the journal (verified: checksum sidecar + ``.bak``
  rollback, the ``runtime/integrity`` manifest pattern), readmits
  unfinished jobs against their checkpoint dirs, and refuses
  stream-crossing reattachment (a reattach credential that does not
  match the journaled dedupe binding is a typed ``STREAM_CROSSING``).

Concurrency: transport handler threads and the scheduler thread share
ONE reentrant lock (``_cond``); handlers hold it only to read/adjust
bookkeeping, the scheduler holds it across a chunk step (submissions
during a dispatch queue briefly — admission is between chunks anyway).
Stream generators wait on the same condition, so a finished chunk
wakes every attached stream.  All state machines here
(``gateway``/``stream``) are declared in ``contracts/racecheck.json``
and audited by racecheck M1–M3 alongside L1/L2/S1/C6.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from pathlib import Path

import numpy as np

from ..obs import trace as otrace
from ..runtime import faults, preemption, telemetry
from . import wire
from .service import SamplerService
from .wire import WireError, WireRequest, WireResponse

JOURNAL = "gateway_journal.json"
JOURNAL_SHA = "gateway_journal.sha256"
JOURNAL_BAK = "gateway_journal.bak.json"
JOURNAL_BAK_SHA = "gateway_journal.bak.sha256"
JOURNAL_SCHEMA = 1
#: per-ENTRY schema: 1 = the PR 17 submission shape, 2 = lineage-
#: bearing append entries (parent_dedupe / parent_job_id / generation,
#: states "forking"/"superseded").  Entries carry their version
#: explicitly; a missing field reads as 1 (every pre-field journal is a
#: v1 journal).  Unknown versions are a TYPED refusal at load — a
#: future reader's entries must never be half-understood and resumed
#: wrong.
ENTRY_SCHEMA = 2
KNOWN_ENTRY_SCHEMAS = (1, 2)

#: gateway lifecycle (racecheck machine ``gateway``)
GATEWAY_STATES = ("serving", "draining", "stopped")
#: stream subscription lifecycle (racecheck machine ``stream``)
STREAM_STATES = ("attached", "streaming", "shed", "closed")

_JOB_ROUTE = re.compile(r"^/v1/jobs/([A-Za-z0-9._-]{1,64})$")
_STREAM_ROUTE = re.compile(r"^/v1/jobs/([A-Za-z0-9._-]{1,64})/stream$")


def synthetic_model_builder(payload: dict):
    """Default payload -> PTA builder: the bounded ``synthetic`` spec
    the probes and chaos drills upload.  Every field is range-checked —
    an upload names a model size, it does not get to pick one that
    compiles for an hour.  Deployments with real par/tim ingest pass
    their own builder; the gateway treats it as opaque."""
    spec = payload.get("synthetic")
    if not isinstance(spec, dict):
        raise WireError("BAD_REQUEST",
                        "payload must carry a 'synthetic' model spec")

    def _bounded(key, default, lo, hi):
        try:
            v = int(spec.get(key, default))
        except (TypeError, ValueError):
            raise WireError("BAD_REQUEST",
                            f"synthetic.{key} must be an int") from None
        if not lo <= v <= hi:
            raise WireError(
                "BAD_REQUEST",
                f"synthetic.{key}={v} outside [{lo}, {hi}]")
        return v

    n_psr = _bounded("n_psr", 2, 1, 8)
    ntoa = _bounded("ntoa", 24, 8, 512)
    tm_cols = _bounded("tm_cols", 3, 2, 8)
    seed = _bounded("seed", 0, 0, 2**31 - 1)
    nmodes = _bounded("nmodes", 3, 1, 16)
    from ..analysis.jaxprcheck.entries import build_model, synthetic_pulsars

    psrs = synthetic_pulsars(n_psr, ntoa, tm_cols=tm_cols, seed=seed)
    # accumulated /v1/append batches: the journal replays the whole
    # growth history so a restarted gateway rebuilds the grown model
    # from the payload alone.  Bounded like everything else an upload
    # names.
    appends = payload.get("appends") or []
    if not isinstance(appends, list) or len(appends) > 8:
        raise WireError("BAD_REQUEST",
                        "appends must be a list of at most 8 batches")
    if appends:
        from ..data.append import append_polynomial_toas

        for i, batch in enumerate(appends):
            if not isinstance(batch, dict):
                raise WireError("BAD_REQUEST",
                                f"appends[{i}] must be a JSON object")
            try:
                add = int(batch.get("add", 0))
                aseed = int(batch.get("seed", 0))
            except (TypeError, ValueError):
                raise WireError(
                    "BAD_REQUEST",
                    f"appends[{i}].add/.seed must be ints") from None
            if not 1 <= add <= 256:
                raise WireError(
                    "BAD_REQUEST",
                    f"appends[{i}].add={add} outside [1, 256]")
            if not 0 <= aseed <= 2**31 - 1:
                raise WireError(
                    "BAD_REQUEST",
                    f"appends[{i}].seed={aseed} outside range")
            psrs = append_polynomial_toas(psrs, add, seed=aseed)
        if max(p.ntoa for p in psrs) > 1024:
            raise WireError("BAD_REQUEST",
                            "grown dataset exceeds the 1024-TOA bound")
    return build_model(psrs, nmodes)


class StreamSub:
    """One attached result stream (bookkeeping only — the cursor is
    the client's; this object exists so live streams can be counted,
    bounded and shed)."""

    def __init__(self, job_id: str, cursor: int):
        self.job_id = job_id
        self.cursor = int(cursor)
        self.state = "attached"

    def begin(self) -> None:
        if self.state == "attached":
            self.state = "streaming"

    def shed(self) -> None:
        """The consumer fell past the lag bound: drop the stream, keep
        the sampler.  The client reattaches with its cursor."""
        if self.state == "streaming":
            self.state = "shed"

    def close(self) -> None:
        if self.state == "attached":
            self.state = "closed"
            return
        if self.state == "streaming":
            self.state = "closed"


class Gateway:
    """Transport-agnostic gateway core over one ``SamplerService``.

    ``handle(WireRequest) -> WireResponse`` is the whole surface a
    transport consumes (see :class:`~.wire.Transport`).  ``start()``
    spawns the scheduler thread; ``join()`` blocks until the gateway
    stops (drained, killed, or all work done and ``stop_when_idle``).
    """

    def __init__(self, root, table, *, model_builder=None, svc_kw=None,
                 max_body=wire.MAX_BODY_BYTES, max_niter=100_000,
                 shed_lag=256, stream_batch=64, stop_when_idle=False,
                 clock=time.monotonic):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_body = int(max_body)
        self.max_niter = int(max_niter)
        self.shed_lag = int(shed_lag)
        self.stream_batch = int(stream_batch)
        self.stop_when_idle = bool(stop_when_idle)
        self._clock = clock
        self._build = (synthetic_model_builder if model_builder is None
                       else model_builder)

        kw = dict(svc_kw or {})
        kw.setdefault("breaker", True)
        kw.setdefault("admission", True)
        self.svc = SamplerService(self.root / "svc", table, **kw)

        # one reentrant lock for every gateway/service mutation; the
        # condition wakes attached streams after each chunk writeback
        self._cond = threading.Condition(threading.RLock())
        # journal file I/O (two fsyncs + .bak rotation) happens under
        # its own lock so neither the scheduler condition nor handler
        # threads ever wait on disk; generation tags keep concurrent
        # writers ordered (lock order is always _cond -> _jlock)
        self._jlock = threading.Lock()
        # serializes append materializations (drain parent -> fork ->
        # readmit child): two racing replays of the same append must
        # resolve to ONE fork (lock order: _mlock -> _cond -> _jlock;
        # never taken while holding _cond)
        self._mlock = threading.Lock()
        self._journal_gen = 0
        self._journal_written = 0
        self.state = "serving"
        self.failure = None
        self._thread = None
        self._steps = 0
        self._requests = 0
        self._subs: set[StreamSub] = set()
        self._cold: dict[str, tuple] = {}   # job_id -> (rows, it) from disk

        # journal: dedupe_key -> entry; _by_job is the reverse route;
        # _unjournaled tracks bindings created but not yet durable so
        # a racing replay cannot ACK ahead of the journal write
        self._entries: dict[str, dict] = {}
        self._unjournaled: set[str] = set()
        self._next_seq = 0
        self._next_tenant = 0
        self._deadlines: dict[str, float] = {}   # job_id -> monotonic
        self._load_journal()
        self._by_job = {e["job_id"]: e for e in self._entries.values()}
        self._readmit()

    # -- journal (integrity pattern: tmp+fsync+rename, sha sidecar, .bak)

    def _journal_blob(self) -> bytes:
        doc = {"schema": JOURNAL_SCHEMA,
               "service_seed": int(self.svc.service_seed),
               "next_seq": int(self._next_seq),
               "next_tenant": int(self._next_tenant),
               "entries": self._entries}
        return json.dumps(doc, indent=1, sort_keys=True).encode("utf-8")

    def _write_atomic(self, name, blob: bytes) -> None:
        tmp = self.root / (name + ".tmp")
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.root / name)

    def _write_journal(self) -> None:
        """Persist the routing state: rotate the verified ``.bak`` pair
        first (a kill between the journal replace and the sidecar
        replace must leave a recoverable generation), then primary,
        then its checksum sidecar.

        Only the snapshot is taken under the gateway lock; the file
        I/O (two fsyncs plus the rotation's read-and-verify of the
        whole entry set) runs under the dedicated journal lock, so the
        scheduler and every other handler keep moving while a journal
        lands.  Each snapshot carries a generation tag: a writer that
        loses the disk race to a NEWER full snapshot skips — its
        mutation is already durable as part of what landed."""
        with self._cond:
            self._journal_gen += 1
            gen = self._journal_gen
            blob = self._journal_blob()
        with self._jlock:
            if gen <= self._journal_written:
                return
            prim, sha = self.root / JOURNAL, self.root / JOURNAL_SHA
            if prim.exists() and sha.exists():
                old = prim.read_bytes()
                if hashlib.sha256(old).hexdigest() == \
                        sha.read_text().strip():
                    self._write_atomic(JOURNAL_BAK, old)
                    self._write_atomic(JOURNAL_BAK_SHA,
                                       sha.read_bytes())
            self._write_atomic(JOURNAL, blob)
            self._write_atomic(JOURNAL_SHA,
                               hashlib.sha256(blob).hexdigest().encode())
            self._journal_written = gen

    def _verified_journal(self, name, sha_name):
        p, s = self.root / name, self.root / sha_name
        if not p.exists():
            return None
        blob = p.read_bytes()
        if not s.exists() or hashlib.sha256(blob).hexdigest() != \
                s.read_text().strip():
            return None
        try:
            doc = json.loads(blob)
        except ValueError:
            return None
        if not isinstance(doc, dict) or doc.get("schema") != JOURNAL_SCHEMA:
            return None
        return doc

    def _load_journal(self) -> None:
        from ..runtime.integrity import CheckpointError

        doc = self._verified_journal(JOURNAL, JOURNAL_SHA)
        if doc is None and (self.root / JOURNAL).exists():
            doc = self._verified_journal(JOURNAL_BAK, JOURNAL_BAK_SHA)
            if doc is None:
                raise CheckpointError(
                    f"{self.root / JOURNAL}: gateway journal fails its "
                    "checksum sidecar and no verified .bak generation "
                    "exists — refusing to serve with unverifiable "
                    "dedupe/routing state (delete the journal to start "
                    "a FRESH gateway that cannot resume old handles)")
            telemetry.incr("rollbacks")
        if doc is None:
            return
        if int(doc.get("service_seed", 0)) != int(self.svc.service_seed):
            raise CheckpointError(
                f"{self.root / JOURNAL}: journal was written under "
                f"service_seed {doc.get('service_seed')} but this "
                f"gateway runs seed {self.svc.service_seed} — tenant "
                "PRNG identities would cross streams; refuse")
        entries = dict(doc.get("entries", {}))
        for key, ent in entries.items():
            try:
                sv = int(ent.get("schema_version", 1))
            except (TypeError, ValueError):
                sv = -1
            if sv not in KNOWN_ENTRY_SCHEMAS:
                raise CheckpointError(
                    f"{self.root / JOURNAL}: journal entry {key!r} "
                    f"carries schema_version {ent.get('schema_version')!r}"
                    f" but this gateway understands only "
                    f"{list(KNOWN_ENTRY_SCHEMAS)} — refusing to resume "
                    "an entry written by a newer writer (half-understood "
                    "routing state could cross streams or drop lineage); "
                    "upgrade the gateway or serve this root with the "
                    "writer that produced it")
        self._entries = entries
        self._next_seq = int(doc.get("next_seq", len(self._entries)))
        self._next_tenant = int(doc.get("next_tenant", len(self._entries)))

    def _readmit(self) -> None:
        """Resubmit every unfinished journal entry against its own
        checkpoint dir (``Job.try_resume`` restores the verified
        prefix bitwise).  ``done`` entries stay cold — their rows
        stream from disk; ``expired`` entries stay drained (the
        client's deadline passed; re-running it is not our call);
        ``quarantined`` entries stay parked — their manifests carry
        the quarantine marker and resuming one is an operator decision
        (``force_requeue``), never a restart default.  A journal still
        saying ``active`` over a quarantine-marked manifest (the
        gateway died between the park and the journal sync) defers to
        the manifest, so one poisoned job can never wedge restarts."""
        from ..runtime.integrity import CheckpointError, \
            check_not_quarantined

        now = time.time()
        forking = []
        for ent in self._entries.values():
            if ent.get("state") in ("done", "expired", "failed",
                                    "quarantined", "superseded"):
                # superseded: a child generation replaced this job; its
                # verified rows stay streamable cold, it never reruns
                continue
            if ent.get("state") == "forking":
                # the gateway died between journaling the append intent
                # and promoting the child: re-materialize AFTER the
                # parent entry (below) is readmitted, so the fork finds
                # its parent job registered
                forking.append(ent)
                continue
            try:
                check_not_quarantined(ent["outdir"])
            except CheckpointError:
                ent["state"] = "quarantined"
                continue
            pta = self._build(ent["payload"])
            job = self.svc.submit(pta, int(ent["niter"]),
                                  job_id=ent["job_id"],
                                  tenant_id=int(ent["tenant_id"]),
                                  outdir=ent["outdir"],
                                  generation=int(ent.get("generation", 0)))
            ent["state"] = "active"
            dl = ent.get("deadline_unix")
            if dl is not None:
                self._deadlines[job.job_id] = \
                    self._clock() + max(0.0, float(dl) - now)
        for ent in forking:
            try:
                self._materialize_append(ent)
            except Exception as exc:             # noqa: BLE001
                # a migration that cannot complete on restart (table
                # changed, lineage unresolvable) must settle LOUDLY,
                # not park an orphan entry behind a live gateway
                ent["state"] = "failed"
                ent["failure"] = repr(exc)
                telemetry.incr("gateway_migration_failures")
                otrace.instant("gateway.migration_failure",
                               job=ent.get("job_id"), error=repr(exc))
        if self._entries:
            self._write_journal()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Gateway":
        self._thread = threading.Thread(target=self._scheduler,
                                        name="ptgibbs-gateway-sched",
                                        daemon=True)
        self._thread.start()
        return self

    def join(self, timeout=None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _scheduler(self) -> None:
        """The single service-driving loop: deadlines, one supervised
        chunk, journal sync, stream wakeup.  Exits by drain (SIGTERM /
        ``/v1/drain``), injected ``gateway_kill``, or idle completion
        when ``stop_when_idle``."""
        try:
            while True:
                if preemption.drain_requested():
                    self._graceful_drain()
                    return
                self._enforce_deadlines()
                self._steps += 1
                faults.fire("gateway.step", row=self._steps)
                try:
                    with self._cond:
                        busy = self.svc.step_supervised(
                            defer_backoff=True)
                        backoff = self.svc.take_backoff()
                        changed = self._sync_journal_states()
                        self._cond.notify_all()
                    if changed:
                        self._write_journal()
                    if backoff:
                        # the recovery ladder's deterministic backoff —
                        # slept here, NOT inside the locked step, so
                        # handlers keep answering while the service
                        # waits out a retry
                        time.sleep(backoff)
                except preemption.Preempted:
                    self._graceful_drain(residents_drained=True)
                    return
                if not busy:
                    if self.stop_when_idle and self._all_settled():
                        self._graceful_drain(idle=True)
                        return
                    time.sleep(0.002)
        except faults.InjectedCrash:
            # simulated SIGKILL mid-stream: no goodbye, no final journal
            # write — durability must already be on disk (it is: the
            # journal persists at every mutation, checkpoints at every
            # save_every chunk), which is exactly what the restart
            # drill asserts
            with self._cond:
                self.state = "stopped"
                self._cond.notify_all()
        except Exception as exc:                 # noqa: BLE001
            # anything the recovery ladder re-raises (exhausted retry
            # budget, user/unknown-class errors out of a hostile
            # payload, an unresumable checkpoint): the gateway must
            # FAIL LOUDLY, never park a dead scheduler behind a live
            # listener that keeps ACKing work nobody will run
            self._scheduler_failed(exc)

    def _scheduler_failed(self, exc) -> None:
        """Terminal scheduler failure: record the cause, settle the
        journal (jobs the service already failed/quarantined keep that
        verdict; everything else parks ``drained`` — resumable by a
        successor from its verified checkpoint), stop the gateway and
        wake every waiter, so handlers answer typed ``DRAINING`` and
        attached streams terminate instead of hanging forever."""
        telemetry.incr("gateway_scheduler_failures")
        otrace.instant("gateway.scheduler_failure", error=repr(exc))
        with self._cond:
            self.failure = repr(exc)
            self._sync_journal_states()
            for ent in self._entries.values():
                if ent.get("state") == "active":
                    ent["state"] = "drained"
            self.state = "stopped"
            self._cond.notify_all()
        try:
            self._write_journal()
        except Exception:                        # noqa: BLE001
            pass   # best effort: the listener is already refusing work

    def _all_settled(self) -> bool:
        """Every journaled job terminal — and at least one exists, so
        an idle-stopping gateway does not park before its first
        submission arrives."""
        with self._cond:
            return bool(self._entries) and all(
                e.get("state") in ("done", "expired", "failed",
                                   "quarantined", "superseded")
                for e in self._entries.values())

    def _graceful_drain(self, residents_drained=False, idle=False) -> None:
        """Stop admissions, drain residents through the preemption
        path, persist the journal, park.  Safe to reach twice."""
        with self._cond:
            if self.state == "serving":
                self.state = "draining"
            self._cond.notify_all()
        otrace.instant("gateway.drain", idle=idle)
        if not residents_drained and any(self.svc.residents):
            try:
                with self._cond:
                    # raises Preempted once residents are checkpointed
                    self.svc.step_supervised(defer_backoff=True)
            except preemption.Preempted:
                pass
            except Exception:                    # noqa: BLE001
                pass   # draining: best effort, journal still persists
        with self._cond:
            self._sync_journal_states()
            for ent in self._entries.values():
                if ent.get("state") == "active":
                    ent["state"] = "drained"
        self._write_journal()   # durable before the gateway parks
        with self._cond:
            if self.state == "draining":
                self.state = "stopped"
            self._cond.notify_all()

    def _sync_journal_states(self) -> bool:
        changed = False
        for ent in self._entries.values():
            if ent.get("state") not in ("active",):
                continue
            job = self.svc.jobs.get(ent["job_id"])
            if job is None:
                continue
            new = None
            if job.state == "done":
                new = "done"
            elif job.state == "failed":
                new = "failed"
            elif job.state == "quarantined" and job.failure:
                new = "quarantined"     # terminally parked, not cooldown
            if new is not None and ent.get("state") != new:
                ent["state"] = new
                changed = True
        return changed

    def _enforce_deadlines(self) -> None:
        """Expired client deadlines convert to the per-request drain:
        verified checkpoint, slot freed at the chunk boundary, every
        co-resident untouched — never a hard kill."""
        now = self._clock()
        with self._cond:
            due = [jid for jid, dl in self._deadlines.items() if now >= dl]
            for jid in due:
                del self._deadlines[jid]
                ent = self._by_job.get(jid)
                if ent is None or ent.get("state") != "active":
                    continue
                if self.svc.drain_job(jid, reason="deadline"):
                    ent["state"] = "expired"
                    telemetry.incr("deadline_drains")
                    otrace.instant("gateway.deadline_drain", job=jid)
            if due:
                self._cond.notify_all()
        if due:
            self._write_journal()

    # -- request handling ----------------------------------------------------

    def handle(self, req: WireRequest) -> WireResponse:
        """The transport-facing entry point (thread-safe)."""
        self._requests += 1
        fired = faults.transport_fault("wire.request", row=self._requests)
        with otrace.span("gateway.request", method=req.method,
                         route=req.path):
            try:
                resp = self._route(req)
            except WireError as err:
                resp = WireResponse.error(err)
            except Exception as exc:             # noqa: BLE001
                resp = WireResponse.error(wire.classify_exception(exc))
        telemetry.incr("gateway_requests", code=str(resp.status))
        if any(f.kind == "conn_drop" for f in fired):
            # the response is computed — and for a submission, already
            # journaled — but the client never sees it: the lost-ACK
            # window the dedupe contract exists for
            raise wire.ConnDropped(f"injected conn_drop on {req.path}")
        return resp

    def _route(self, req: WireRequest) -> WireResponse:
        path = req.path.rstrip("/") or "/"
        if req.method == "POST" and path == "/v1/jobs":
            return self._submit(req)
        if req.method == "POST" and path == "/v1/append":
            return self._append(req)
        if req.method == "POST" and path == "/v1/drain":
            preemption.request_drain(reason="gateway_api")
            return WireResponse(body={"draining": True})
        if req.method == "GET" and path == "/v1/metrics":
            return WireResponse(
                raw=self.svc.prometheus().encode("utf-8"),
                headers={"Content-Type":
                         "text/plain; version=0.0.4; charset=utf-8"})
        if req.method == "GET" and path == "/v1/healthz":
            with self._cond:
                body = {"state": self.state,
                        "failure": self.failure,
                        "jobs": len(self._entries),
                        "queue_depth": len(self.svc.queue),
                        "residents": sum(1 for j in self.svc.residents
                                         if j is not None),
                        "placement": self.svc.placement_summary()}
            return WireResponse(body=body)
        m = _JOB_ROUTE.match(path)
        if m and req.method == "GET":
            return self._status(m.group(1), req)
        m = _STREAM_ROUTE.match(path)
        if m and req.method == "GET":
            return self._stream(m.group(1), req)
        raise WireError("BAD_REQUEST",
                        f"no route for {req.method} {req.path}")

    # -- idempotent submission ----------------------------------------------

    def _submit(self, req: WireRequest) -> WireResponse:
        body = wire.parse_body(req.body, self.max_body)
        dedupe = wire.require_name(body.get("dedupe_key"), "dedupe_key")
        deadline_s = wire.parse_deadline_ms(req.headers, body)
        payload = body.get("payload")
        if not isinstance(payload, dict):
            raise WireError("BAD_REQUEST",
                            "payload must be a JSON object")
        try:
            niter = int(body.get("niter", 0))
        except (TypeError, ValueError):
            raise WireError("BAD_REQUEST", "niter must be an int") from None
        if not 1 <= niter <= self.max_niter:
            raise WireError("BAD_REQUEST",
                            f"niter must be in [1, {self.max_niter}]")
        digest = wire.payload_digest(payload)
        fired = faults.transport_fault("wire.submit", row=self._requests)
        resp = self._submit_once(dedupe, payload, digest, niter, deadline_s)
        for f in fired:
            if f.kind == "dup_submit":
                # the retry a real client sends after a lost ACK — must
                # resolve to the SAME handle via the journal binding
                resp = self._submit_once(dedupe, payload, digest, niter,
                                         deadline_s)
        return resp

    def _check_dedupe_locked(self, dedupe, digest, niter):
        """Replay resolution under the lock: the journaled entry bound
        to ``dedupe`` (byte-identical replays only), None when the key
        is fresh, typed refusals otherwise.  Callers hold ``_cond``."""
        if self.state != "serving":
            raise WireError(
                "DRAINING",
                f"gateway is {self.state}: not accepting work — "
                "resubmit to a serving instance (your dedupe key "
                "makes the retry safe)")
        ent = self._entries.get(dedupe)
        if ent is None:
            return None
        if ent["payload_sha256"] != digest \
                or int(ent["niter"]) != int(niter):
            raise WireError(
                "DEDUPE_MISMATCH",
                f"dedupe_key {dedupe!r} is bound to a different "
                "submission (payload digest or niter changed): "
                "replays must be byte-identical — pick a fresh "
                "key for new work")
        return ent

    def _ack(self, ent, dedupe, replayed) -> WireResponse:
        """The ACK leaves only AFTER the binding is durable.  A fresh
        binding always journals; a replay journals only when it raced
        the original submitter's write (the key is still pending) —
        otherwise the binding already survived at least one snapshot."""
        if not replayed or dedupe in self._unjournaled:
            self._write_journal()
            with self._cond:
                self._unjournaled.discard(dedupe)
        if replayed:
            telemetry.incr("dedupe_hits")
        with self._cond:
            return self._handle_body(ent, replayed=replayed)

    def _submit_once(self, dedupe, payload, digest, niter,
                     deadline_s) -> WireResponse:
        with self._cond:
            ent = self._check_dedupe_locked(dedupe, digest, niter)
        if ent is not None:
            return self._ack(ent, dedupe, replayed=True)
        # the model build (range-checked, but still array construction
        # the payload sizes) runs OUTSIDE the gateway lock: one slow
        # upload must not stall the scheduler or any other handler
        pta = self._build(payload)
        with self._cond:
            # re-check: another handler may have bound this key while
            # the build ran — the FIRST binding wins, ours is the replay
            ent = self._check_dedupe_locked(dedupe, digest, niter)
            if ent is None:
                job_id = f"g{self._next_seq:05d}"
                tenant_id = self._next_tenant
                outdir = self.root / "jobs" / job_id
                job = self.svc.submit(pta, niter, job_id=job_id,
                                      tenant_id=tenant_id, outdir=outdir)
                self._next_seq += 1
                self._next_tenant += 1
                ent = {"job_id": job.job_id, "tenant_id": int(tenant_id),
                       "niter": int(niter), "payload": payload,
                       "payload_sha256": digest, "outdir": str(outdir),
                       "dedupe_key": dedupe, "state": "active",
                       "schema_version": 1,
                       "deadline_unix": (None if deadline_s is None
                                         else time.time() + deadline_s)}
                self._entries[dedupe] = ent
                self._by_job[job.job_id] = ent
                self._unjournaled.add(dedupe)
                if deadline_s is not None:
                    self._deadlines[job.job_id] = \
                        self._clock() + deadline_s
                self._cond.notify_all()
                replayed = False
            else:
                replayed = True
        # the journal file I/O happens off the condition lock: handlers
        # and the scheduler keep moving while the fsyncs land
        return self._ack(ent, dedupe, replayed=replayed)

    # -- standing-model append (/v1/append) ---------------------------------

    def _append(self, req: WireRequest) -> WireResponse:
        """Append TOAs to a standing model: fork the parent job's
        verified checkpoint into a child generation on the grown
        dataset, supersede the parent, readmit the child warm.

        Same dedupe/journal contract as submission — the forking
        intent is journaled BEFORE any checkpoint work, the ACK leaves
        only after the binding is durable, and a replay (lost ACK,
        restart) resolves to the original child handle, re-running
        nothing: the fork itself is idempotent
        (``lineage.fork_generation`` recognizes a child already forked
        from this parent state).
        """
        body = wire.parse_body(req.body, self.max_body)
        dedupe = wire.require_name(body.get("dedupe_key"), "dedupe_key")
        parent_key = wire.require_name(body.get("parent"), "parent")
        deadline_s = wire.parse_deadline_ms(req.headers, body)
        spec = body.get("append")
        if not isinstance(spec, dict):
            raise WireError("BAD_REQUEST",
                            "append must be a JSON object (the grown-"
                            "TOAs spec, e.g. {'add': 16, 'seed': 1})")
        try:
            niter = int(body.get("niter", 0))
        except (TypeError, ValueError):
            raise WireError("BAD_REQUEST", "niter must be an int") from None
        if not 1 <= niter <= self.max_niter:
            raise WireError("BAD_REQUEST",
                            f"niter must be in [1, {self.max_niter}]")
        if faults.append_during_drain():
            # the injected race: the drain began before this append
            # could be journaled — refuse typed, bind nothing; the
            # dedupe key makes the client's retry safe elsewhere
            raise WireError(
                "DRAINING",
                "gateway began draining before this append was "
                "journaled — nothing was bound; retry against a "
                "serving instance (your dedupe key makes it safe)")
        with self._cond:
            parent_ent = self._entries.get(parent_key)
            if parent_ent is None:
                raise WireError(
                    "NOT_FOUND",
                    f"unknown parent submission {parent_key!r} — "
                    "'parent' is the parent's dedupe key")
            # the child payload = parent payload + this append batch:
            # the journal alone must reproduce the grown model on
            # restart, so appends accumulate in the payload itself
            child_payload = dict(parent_ent["payload"])
            child_payload["appends"] = \
                list(parent_ent["payload"].get("appends") or []) + [spec]
        digest = wire.payload_digest(child_payload)
        with self._cond:
            ent = self._check_dedupe_locked(dedupe, digest, niter)
        if ent is not None:
            return self._ack_append(ent, dedupe, replayed=True)
        with self._cond:
            pstate = parent_ent.get("state")
        if pstate == "superseded":
            raise WireError(
                "SUPERSEDED",
                f"parent {parent_key!r} was already superseded by "
                f"{parent_ent.get('superseded_by')!r} — append to the "
                "newest generation instead")
        if pstate in ("failed", "quarantined"):
            raise WireError(
                "BAD_REQUEST",
                f"parent {parent_key!r} is {pstate} — a {pstate} job "
                "cannot be grown; "
                + ("an operator must requeue it first"
                   if pstate == "quarantined" else
                   "submit the grown dataset as a fresh job"))
        # model build + bucket pre-flight OUTSIDE the lock (array
        # construction and routing are the slow part); overflow is a
        # typed 422 with the planner's migration hint attached, BEFORE
        # anything is journaled
        pta = self._build(child_payload)
        from .buckets import probe_shape

        self.svc.table.route(probe_shape(pta))
        faults.fire("migrate.pre_journal", row=self._requests)
        with self._cond:
            ent = self._check_dedupe_locked(dedupe, digest, niter)
            if ent is None:
                job_id = f"g{self._next_seq:05d}"
                self._next_seq += 1
                outdir = self.root / "jobs" / job_id
                ent = {"job_id": job_id,
                       "tenant_id": int(parent_ent["tenant_id"]),
                       "niter": int(niter), "payload": child_payload,
                       "payload_sha256": digest, "outdir": str(outdir),
                       "dedupe_key": dedupe, "state": "forking",
                       "schema_version": ENTRY_SCHEMA,
                       "parent_dedupe": parent_key,
                       "parent_job_id": parent_ent["job_id"],
                       "generation":
                           int(parent_ent.get("generation", 0)) + 1,
                       "deadline_unix": (None if deadline_s is None
                                         else time.time() + deadline_s)}
                self._entries[dedupe] = ent
                self._by_job[job_id] = ent
                self._unjournaled.add(dedupe)
                self._cond.notify_all()
                replayed = False
            else:
                replayed = True
        return self._ack_append(ent, dedupe, replayed=replayed, pta=pta,
                                deadline_s=deadline_s)

    def _ack_append(self, ent, dedupe, replayed, pta=None,
                    deadline_s=None) -> WireResponse:
        """Durable-then-materialize: the ``forking`` intent journals
        first (a kill after this point re-materializes from the
        journal), then the fork/readmit runs, then the settled states
        journal again and the ACK leaves."""
        if not replayed or dedupe in self._unjournaled:
            self._write_journal()
            with self._cond:
                self._unjournaled.discard(dedupe)
        if replayed:
            telemetry.incr("dedupe_hits")
        faults.fire("migrate.post_journal", row=self._requests,
                    outdir=ent["outdir"])
        self._materialize_append(ent, pta=pta)
        if deadline_s is not None:
            with self._cond:
                if ent.get("state") == "active":
                    self._deadlines[ent["job_id"]] = \
                        self._clock() + deadline_s
        with self._cond:
            it, state, _ = self._progress_locked(ent)
            return WireResponse(body={
                "job_id": ent["job_id"],
                "tenant_id": int(ent["tenant_id"]),
                "niter": int(ent["niter"]), "state": state,
                "generation": int(ent.get("generation", 0)),
                "parent_job_id": ent.get("parent_job_id"),
                "cursor": int(it), "replayed": bool(replayed)})

    def _materialize_append(self, ent, pta=None) -> None:
        """Drain the parent, fork the child generation, readmit it,
        flip the journal states (child ``forking -> active``, parent
        ``-> superseded``).  Idempotent and serialized under
        ``_mlock``: a replay or restart that finds the child already
        active returns without touching anything."""
        with self._mlock:
            with self._cond:
                if ent.get("state") != "forking":
                    return
                parent_ent = self._entries.get(ent["parent_dedupe"])
                parent_job_id = ent.get("parent_job_id")
            if pta is None:
                pta = self._build(ent["payload"])
            self.svc.append_job(
                pta, int(ent["niter"]),
                parent_id=parent_job_id,
                parent_outdir=(parent_ent or {}).get("outdir"),
                job_id=ent["job_id"], outdir=ent["outdir"],
                dataset_sha256=ent["payload_sha256"],
                journaled=True)
            with self._cond:
                ent["state"] = "active"
                if parent_ent is not None \
                        and parent_ent.get("state") not in \
                        ("failed", "quarantined"):
                    parent_ent["state"] = "superseded"
                    parent_ent["superseded_by"] = ent["job_id"]
                    self._deadlines.pop(parent_job_id, None)
                self._cond.notify_all()
            telemetry.incr("gateway_appends")
            otrace.instant("gateway.append", job=ent["job_id"],
                           parent=str(parent_job_id),
                           generation=int(ent.get("generation", 0)))
        self._write_journal()

    def _handle_body(self, ent, replayed) -> WireResponse:
        it, state, _ = self._progress_locked(ent)
        return WireResponse(body={
            "job_id": ent["job_id"], "tenant_id": int(ent["tenant_id"]),
            "niter": int(ent["niter"]), "state": state,
            "cursor": int(it), "replayed": bool(replayed)})

    # -- status / streams ----------------------------------------------------

    def _entry(self, job_id, req: WireRequest) -> dict:
        ent = self._by_job.get(job_id)
        if ent is None:
            raise WireError("NOT_FOUND", f"unknown job {job_id!r}")
        cred = req.headers.get(wire.DEDUPE_HEADER)
        if cred is not None and cred != ent["dedupe_key"]:
            raise WireError(
                "STREAM_CROSSING",
                f"reattach credential does not match the journaled "
                f"dedupe binding for {job_id!r} — refusing a "
                "stream-crossing reattachment")
        return ent

    def _cold_rows(self, ent):
        """Recorded rows of a job this incarnation never ran (done /
        expired before a restart): loaded once from the verified
        checkpoint.  ``force_requeue=True`` is a READ — streaming the
        verified clean prefix of a parked job is safe; re-running it is
        the decision that needs the operator."""
        jid = ent["job_id"]
        got = self._cold.get(jid)
        if got is None:
            from ..runtime import integrity

            loaded = integrity.load_resume(ent["outdir"],
                                           force_requeue=True)
            if loaded is None:
                got = (np.zeros((0, 0), np.float64), 0)
            else:
                chain, _bchain, upto, _adapt = loaded
                got = (np.asarray(chain[:upto], np.float64), int(upto))
            self._cold[jid] = got
        return got

    def _progress_locked(self, ent):
        """(it, state, job|None) under the lock.  The gateway overlay
        ('expired', terminal quarantine) wins over the raw job state."""
        job = self.svc.jobs.get(ent["job_id"])
        if ent.get("state") in ("expired", "superseded"):
            # gateway overlay wins: the underlying job may sit parked
            # "queued" (drained parent) but it will never run again
            it = int(job.it) if job is not None \
                else self._cold_rows(ent)[1]
            return it, str(ent["state"]), job
        if job is None:
            rows, it = self._cold_rows(ent)
            return it, str(ent.get("state", "unknown")), None
        state = job.state
        if state == "quarantined" and job.failure:
            state = "quarantined"       # terminally parked
        return int(job.it), state, job

    def _terminal(self, ent, state, job) -> bool:
        if state in ("done", "failed", "expired", "drained",
                     "superseded"):
            return True
        return state == "quarantined" and (job is None
                                           or job.failure is not None)

    def _rows_locked(self, ent, lo, hi) -> np.ndarray:
        job = self.svc.jobs.get(ent["job_id"])
        if job is not None and job.chain is not None:
            return np.array(job.chain[lo:hi], np.float64)
        rows, it = self._cold_rows(ent)
        return np.array(rows[lo:min(hi, it)], np.float64)

    def _diag_locked(self, ent) -> dict:
        lab = {"job": ent["job_id"], "tenant": str(int(ent["tenant_id"]))}
        out = {}
        for g in ("serve_ess_per_sec", "serve_rhat_max",
                  "serve_accept_rate"):
            v = telemetry.get_gauge(g, **lab)
            if v is not None:
                out[g] = v
        return out

    def _status(self, job_id, req: WireRequest) -> WireResponse:
        with self._cond:
            ent = self._entry(job_id, req)
            it, state, job = self._progress_locked(ent)
            body = {"job_id": job_id, "state": state, "cursor": int(it),
                    "niter": int(ent["niter"]),
                    "tenant_id": int(ent["tenant_id"]),
                    "diag": self._diag_locked(ent),
                    "deadline_pending": job_id in self._deadlines}
            if job is not None:
                body["failure"] = job.failure
                ttfs = job.time_to_first_sample_ms()
                if ttfs is not None:
                    body["time_to_first_sample_ms"] = ttfs
        return WireResponse(body=body)

    def _stream(self, job_id, req: WireRequest) -> WireResponse:
        with self._cond:
            ent = self._entry(job_id, req)
        cursor = wire.parse_cursor(req.query.get("cursor", 0),
                                   niter=ent["niter"])
        live = req.query.get("live", "") in ("1", "true", "yes")
        try:
            wait_s = float(req.query.get("wait", 0.0))
        except ValueError:
            raise WireError("BAD_REQUEST", "wait must be seconds") from None
        wait_s = min(max(wait_s, 0.0), 60.0)
        return WireResponse(
            stream=self._stream_iter(ent, cursor, live, wait_s))

    def _stream_iter(self, ent, cursor, live, wait_s):
        """NDJSON event generator.  Each line carries the NEXT cursor —
        acknowledging a line by advancing the client cursor is all the
        protocol there is, which is why reattachment is trivial.  In
        live mode the stream follows the job until terminal (or shed);
        otherwise it long-polls up to ``wait_s`` then returns whatever
        arrived."""
        sub = StreamSub(ent["job_id"], cursor)
        with self._cond:
            self._subs.add(sub)
            telemetry.gauge("gateway_streams", float(len(self._subs)))
        sub.begin()
        deadline = self._clock() + wait_s
        try:
            while True:
                fired = faults.transport_fault("wire.stream",
                                               row=sub.cursor)
                for f in fired:
                    if f.kind == "slow_client":
                        # the consumer stalls; rows keep landing.  The
                        # lag check below is what sheds it
                        time.sleep(f.seconds)
                    elif f.kind == "conn_drop":
                        raise wire.ConnDropped("injected mid-stream drop")
                with self._cond:
                    it, state, job = self._progress_locked(ent)
                    lag = it - sub.cursor
                    if live and lag > self.shed_lag:
                        sub.shed()
                        telemetry.incr("shed_streams")
                        otrace.instant("gateway.shed", job=sub.job_id,
                                       lag=int(lag))
                        err = WireError(
                            "STREAM_SHED",
                            f"stream lagged {lag} rows (> {self.shed_lag})"
                            " and was shed — reattach with your cursor")
                        yield (json.dumps(
                            {**err.body(), "cursor": int(sub.cursor),
                             "final": True},
                            sort_keys=True) + "\n").encode()
                        return
                    rows = (self._rows_locked(
                        ent, sub.cursor,
                        min(it, sub.cursor + self.stream_batch))
                        if lag > 0 else None)
                    terminal = self._terminal(ent, state, job) \
                        and it <= sub.cursor
                    stopped = self.state != "serving"
                    diag = self._diag_locked(ent)
                if rows is not None and len(rows):
                    nxt = sub.cursor + len(rows)
                    yield (json.dumps(
                        {"cursor": int(nxt), "state": state,
                         "rows": rows.tolist(), "diag": diag},
                        sort_keys=True) + "\n").encode()
                    sub.cursor = nxt
                    continue
                if terminal:
                    yield (json.dumps(
                        {"cursor": int(sub.cursor), "state": state,
                         "final": True, "diag": diag},
                        sort_keys=True) + "\n").encode()
                    return
                if stopped:
                    err = WireError("DRAINING",
                                    "gateway drained mid-stream — "
                                    "reattach to a serving instance "
                                    "with your cursor")
                    yield (json.dumps(
                        {**err.body(), "cursor": int(sub.cursor),
                         "final": True}, sort_keys=True) + "\n").encode()
                    return
                if not live and self._clock() >= deadline:
                    yield (json.dumps(
                        {"cursor": int(sub.cursor), "state": state,
                         "rows": []}, sort_keys=True) + "\n").encode()
                    return
                with self._cond:
                    self._cond.wait(0.05)
        finally:
            sub.close()
            with self._cond:
                self._subs.discard(sub)
                telemetry.gauge("gateway_streams", float(len(self._subs)))

    # -- reporting -----------------------------------------------------------

    def report(self) -> dict:
        with self._cond:
            return {
                "state": self.state,
                "failure": self.failure,
                "entries": {k: {kk: vv for kk, vv in e.items()
                                if kk != "payload"}
                            for k, e in self._entries.items()},
                "steps": int(self._steps),
                "requests": int(self._requests),
                "service": self.svc.report(),
            }
